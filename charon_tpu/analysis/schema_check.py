"""Codec wire-schema stability check: append-only, machine-enforced.

The binary codec's compatibility story (PR 7, docs/operations.md "Wire
format") rests on the wire-id tables being APPEND-ONLY: a type or enum
never loses its id, ids are never renumbered, and a registered
dataclass's field program only ever grows at the tail (new trailing
fields must be defaulted, so old frames still decode and old nodes
drop the unknown tail). Large committee-BLS deployments treat exactly
this — serialization-schema stability — as a hard compatibility
contract (arXiv:2302.00418): a silent renumber turns every
mixed-version cluster into a CodecError storm at the transport.

This checker snapshots the live registry (`_TYPE_WIRE_IDS` /
`_ENUM_WIRE_IDS` + per-type field programs + enum member values) and
compares it against the committed golden
`tests/testdata/wire_schema.json`:

  * removed / renumbered type or enum id ............ FAIL
  * reordered / removed / renamed existing field ..... FAIL
  * new REQUIRED field on an existing type ........... FAIL
    (old frames omit it; decode would reject them)
  * changed enum member value / removed member ....... FAIL
  * appended type, enum, defaulted field, member ..... OK (run with
    `--update` to re-bless the golden after review)

CLI: `python -m charon_tpu.analysis.schema_check [--update]` — wired
into `ci.sh analysis`. Imports only p2p/codec (jax-free).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

GOLDEN = (
    Path(__file__).resolve().parents[2]
    / "tests"
    / "testdata"
    / "wire_schema.json"
)


def current_snapshot() -> dict:
    from charon_tpu.p2p import codec

    types: dict[str, dict] = {}
    for name, wid in codec._TYPE_WIRE_IDS.items():
        sch = codec._SCHEMAS.get(name)
        if sch is None:
            # a wire id reserved for a type that never registered is
            # itself a schema bug — surface it as a snapshot entry the
            # compare step will flag against the golden
            types[name] = {"id": wid, "fields": None, "n_required": None}
            continue
        types[name] = {
            "id": wid,
            "fields": list(sch.field_names),
            "n_required": sch.n_required,
        }
    enums: dict[str, dict] = {}
    for name, wid in codec._ENUM_WIRE_IDS.items():
        cls = codec._ENUMS.get(name)
        enums[name] = {
            "id": wid,
            "members": (
                {m.name: int(m.value) for m in cls} if cls else None
            ),
        }
    return {"version": 1, "types": types, "enums": enums}


def compare(golden: dict, current: dict) -> list[str]:
    """Append-only violations of `current` against `golden`."""
    errors: list[str] = []
    g_types = golden.get("types", {})
    c_types = current.get("types", {})
    for name, g in g_types.items():
        c = c_types.get(name)
        if c is None:
            errors.append(f"type {name}: removed from the wire-id table")
            continue
        if c["id"] != g["id"]:
            errors.append(
                f"type {name}: wire id renumbered {g['id']} -> {c['id']}"
            )
        gf, cf = g.get("fields"), c.get("fields")
        if gf is None or cf is None:
            if gf != cf:
                errors.append(f"type {name}: registration state changed")
            continue
        if cf[: len(gf)] != gf:
            errors.append(
                f"type {name}: existing field program changed "
                f"(golden {gf} is not a prefix of {cf}) — fields are "
                "append-only"
            )
        elif len(cf) > len(gf) and c["n_required"] > g["n_required"]:
            errors.append(
                f"type {name}: appended field(s) {cf[len(gf):]} are "
                "REQUIRED (n_required {} -> {}) — old frames omit them "
                "and would be rejected; give them defaults".format(
                    g["n_required"], c["n_required"]
                )
            )
        elif c["n_required"] != g["n_required"] and len(cf) == len(gf):
            errors.append(
                f"type {name}: n_required changed "
                f"{g['n_required']} -> {c['n_required']} with no new "
                "fields — a required/default flip on an existing field"
            )
    # duplicate id check (current side)
    seen: dict[int, str] = {}
    for name, c in c_types.items():
        if c["id"] in seen:
            errors.append(
                f"type {name}: wire id {c['id']} collides with "
                f"{seen[c['id']]}"
            )
        seen[c["id"]] = name
    g_enums = golden.get("enums", {})
    c_enums = current.get("enums", {})
    seen_e: dict[int, str] = {}
    for name, c in c_enums.items():
        if c["id"] in seen_e:
            errors.append(
                f"enum {name}: wire id {c['id']} collides with "
                f"{seen_e[c['id']]}"
            )
        seen_e[c["id"]] = name
    for name, g in g_enums.items():
        c = c_enums.get(name)
        if c is None:
            errors.append(f"enum {name}: removed from the wire-id table")
            continue
        if c["id"] != g["id"]:
            errors.append(
                f"enum {name}: wire id renumbered {g['id']} -> {c['id']}"
            )
        gm, cm = g.get("members") or {}, c.get("members") or {}
        for member, val in gm.items():
            if member not in cm:
                errors.append(f"enum {name}.{member}: member removed")
            elif cm[member] != val:
                errors.append(
                    f"enum {name}.{member}: value changed "
                    f"{val} -> {cm[member]}"
                )
    return errors


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="charon_tpu.analysis.schema_check")
    ap.add_argument(
        "--update",
        action="store_true",
        help="re-bless the golden snapshot from the live registry "
        "(use after REVIEWING an append-only change)",
    )
    ap.add_argument("--golden", default=str(GOLDEN))
    args = ap.parse_args(argv)

    current = current_snapshot()
    golden_path = Path(args.golden)
    if args.update:
        golden_path.write_text(
            json.dumps(current, indent=1, sort_keys=True) + "\n"
        )
        print(f"wire schema golden updated: {golden_path}")
        return 0
    if not golden_path.exists():
        print(
            f"missing golden {golden_path}; run with --update to create",
            file=sys.stderr,
        )
        return 1
    golden = json.loads(golden_path.read_text())
    errors = compare(golden, current)
    for e in errors:
        print(f"wire-schema: {e}")
    if errors:
        print(
            f"{len(errors)} wire-schema violation(s) — the binary codec "
            "tables are an append-only compatibility contract "
            "(docs/operations.md 'Wire format')",
            file=sys.stderr,
        )
        return 1
    n = len(current["types"]) + len(current["enums"])
    print(f"wire schema stable: {n} ids match {golden_path.name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
