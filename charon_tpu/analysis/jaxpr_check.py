"""Device-graph static analyzer: jaxpr invariants + kernel golden manifest.

The half of the codebase that earns the speedups — the jitted device
graphs behind blsops and the mesh plane — had no analysis story: a stray
float promotion, an accidental host callback, or an off-bucket-ladder
shape silently re-introduces the regressions PRs 3-7 paid for, and
nothing in CI would notice until a (currently unavailable) TPU window
measured the damage. This module traces every registered kernel family
(ops/blsops kernel_families(), including the mesh program variants
registered by parallel.mesh.register_analysis_families() and the
ops/sswu.py / ops/decompress.py graphs those families wrap) with
`jax.make_jaxpr` on canonical bucket-ladder shapes under
JAX_PLATFORMS=cpu — tracing only, never executing — and enforces:

  * no host callbacks (pure_callback / io_callback / debug_callback /
    debug_print) inside hot kernels;
  * no floating-point dtypes anywhere — the limb engine is integer-only
    BY DESIGN, so a silent float32 promotion is a *correctness* bug
    (24-bit limb products don't round-trip through f32), not just perf;
  * no implicit convert_element_type widening of limb data beyond the
    geometry's declared limb dtype (uint32 -> uint64/int64 on the TPU
    geometry would silently fall back to XLA's slow emulated 64-bit
    path); index/iota values (int32/int64 scalars XLA mints for gathers)
    are exempt — only converts FROM the limb dtype count;
  * every traced input shape sits on blsops.bucket_lanes's ladder (an
    off-ladder shape means a caller bypassed the bucket discipline and
    the jit cache will grow per flush size).

Each family's primitive census (op counts, input/output avals, total
eqn count) is recorded in tests/testdata/kernel_manifest.json — the
device-graph twin of wire_schema.json. Any change that unfuses a fused
kernel, explodes a gather, or adds an unexpected transpose fails CI
with a named per-primitive diff; `--update` re-blesses deliberate
changes.

Cost model (1-core CI): the pairing-family graphs are 150k-400k eqns
and trace in 25-60 s EACH, so retracing everything per run would blow
the analysis tier's budget ~10x. A jaxpr is a pure function of the
graph-defining sources + the jax version, so the manifest records a
digest over charon_tpu/ops/*.py + charon_tpu/parallel/mesh.py: when
the digest matches, the heavy families cannot have drifted and only the
cheap `sentinel` families (seconds total; they cover both limb
geometries) are re-traced for live teeth. A digest mismatch — someone
actually edited kernel code — triggers the full retrace and census
compare. `--full` forces it; `ci.sh full` runs it.

Usage:
    python -m charon_tpu.analysis.jaxpr_check            # sentinel+digest
    python -m charon_tpu.analysis.jaxpr_check --full     # retrace all
    python -m charon_tpu.analysis.jaxpr_check --update   # re-bless
    python -m charon_tpu.analysis.jaxpr_check --list     # inventory
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from pathlib import Path

# Trace on CPU regardless of attached accelerators: the census must be
# identical on every host, and tracing never needs the device anyway.
# Only effective if jax has not initialized yet — when it has, the
# guard in gather_families() rejects non-CPU backends with a clear
# error instead of silently producing platform-dependent censuses
# (limb.default_fp_ctx() is geometry-per-platform).
if "jax" not in sys.modules:
    os.environ["JAX_PLATFORMS"] = "cpu"

_REPO = Path(__file__).resolve().parents[2]
MANIFEST_PATH = _REPO / "tests" / "testdata" / "kernel_manifest.json"

# Sources the traced graphs are a pure function of (plus jax version):
# editing anything here invalidates the digest fast path.
GRAPH_SOURCE_GLOBS = (
    ("charon_tpu/ops", "*.py"),
    ("charon_tpu/parallel", "mesh.py"),
)

HOST_CALLBACK_PRIMS = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "debug_print",
        "callback",
        "host_callback",
        "outside_call",
    }
)


# ---------------------------------------------------------------------------
# jaxpr walking + census
# ---------------------------------------------------------------------------


def _sub_jaxprs(value):
    """Yield every Jaxpr nested in an eqn param value (pjit/scan carry
    ClosedJaxpr, shard_map carries Jaxpr, cond carries tuples of them)."""
    stack = [value]
    while stack:
        v = stack.pop()
        if hasattr(v, "eqns"):
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            stack.extend(v)


def walk_eqns(jaxpr):
    """Depth-first over every equation, recursing through call/control
    primitives — the flattened device graph the checks run on."""
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            for v in eqn.params.values():
                stack.extend(_sub_jaxprs(v))


def _aval_str(aval) -> str:
    return f"{aval.dtype}[{','.join(str(d) for d in aval.shape)}]"


def census_of(closed_jaxpr, spec) -> dict:
    """Primitive census: the manifest record for one traced family."""
    prims: dict[str, int] = {}
    n_eqns = 0
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        n_eqns += 1
        prims[eqn.primitive.name] = prims.get(eqn.primitive.name, 0) + 1
    return {
        "lanes": spec.lanes,
        "multiple": spec.multiple,
        "ctx": spec.ctx.name,
        "dtype": str(spec.ctx.dtype),
        "eqns": n_eqns,
        "in_avals": [_aval_str(v.aval) for v in closed_jaxpr.jaxpr.invars],
        "out_avals": [_aval_str(v.aval) for v in closed_jaxpr.jaxpr.outvars],
        "prims": dict(sorted(prims.items())),
    }


# ---------------------------------------------------------------------------
# invariant checks
# ---------------------------------------------------------------------------


def check_jaxpr(name: str, closed_jaxpr, spec) -> list[str]:
    """The four device-graph invariants. Returns violation strings
    prefixed with the kernel family name — empty means clean."""
    import numpy as np

    from charon_tpu.ops import blsops

    out: list[str] = []
    limb_dtype = np.dtype(spec.ctx.np_dtype)

    callback_hits: dict[str, int] = {}
    float_hits: dict[str, int] = {}
    widen_hits: dict[str, int] = {}
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        pname = eqn.primitive.name
        if pname in HOST_CALLBACK_PRIMS:
            callback_hits[pname] = callback_hits.get(pname, 0) + 1
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                if np.issubdtype(aval.dtype, np.floating) or np.issubdtype(
                    aval.dtype, np.complexfloating
                ):
                    key = f"{pname}:{aval.dtype}"
                    float_hits[key] = float_hits.get(key, 0) + 1
        if pname == "convert_element_type":
            src = np.dtype(eqn.invars[0].aval.dtype)
            dst = np.dtype(eqn.params["new_dtype"])
            # widening of LIMB data past the declared geometry; index
            # dtypes (signed ints not equal to the limb dtype) and
            # bool masks are exempt
            if (
                src == limb_dtype
                and dst.kind in "iu"
                and dst.itemsize > limb_dtype.itemsize
            ):
                key = f"{src}->{dst}"
                widen_hits[key] = widen_hits.get(key, 0) + 1
    for pname, cnt in sorted(callback_hits.items()):
        out.append(
            f"{name}: host callback {pname} x{cnt} inside a hot kernel "
            "(device graphs must never re-enter the host)"
        )
    for key, cnt in sorted(float_hits.items()):
        out.append(
            f"{name}: floating-point aval {key} x{cnt} (the limb engine "
            "is integer-only by design — a float promotion is a "
            "correctness bug)"
        )
    for key, cnt in sorted(widen_hits.items()):
        out.append(
            f"{name}: convert_element_type {key} x{cnt} widens limb data "
            f"beyond the declared {limb_dtype} geometry"
        )

    # bucket-ladder shapes: declared lanes must be a ladder member, and
    # every array input's batch dim must sit on it
    if spec.lanes != blsops.bucket_lanes(spec.lanes, spec.multiple):
        out.append(
            f"{name}: canonical lanes {spec.lanes} off the bucket ladder "
            f"(bucket_lanes -> {blsops.bucket_lanes(spec.lanes, spec.multiple)})"
        )
    else:
        for i, v in enumerate(closed_jaxpr.jaxpr.invars):
            aval = getattr(v, "aval", None)
            if aval is None or not getattr(aval, "shape", ()):
                continue
            if aval.shape[0] != spec.lanes:
                out.append(
                    f"{name}: input {i} batch dim {aval.shape[0]} != "
                    f"declared ladder lanes {spec.lanes} "
                    f"({_aval_str(aval)})"
                )
    return out


def analyze_family(name: str, fam) -> tuple[dict, list[str]]:
    """Build the family's canonical TraceSpec and trace it (never
    executes). Returns (census, violations)."""
    import jax

    spec = fam.build()
    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    cens = census_of(closed, spec)
    cens["sentinel"] = bool(fam.sentinel)
    violations = check_jaxpr(name, closed, spec)
    del closed  # the big graphs are hundreds of MB — drop eagerly
    return cens, violations


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def graph_source_files(repo: Path = _REPO) -> list[Path]:
    files: list[Path] = []
    for rel, pattern in GRAPH_SOURCE_GLOBS:
        base = repo / rel
        files.extend(
            p
            for p in sorted(base.glob(pattern))
            if "__pycache__" not in p.parts
        )
    return files


def source_digest(repo: Path = _REPO) -> str:
    import jax

    h = hashlib.sha256()
    h.update(f"jax={jax.__version__}".encode())
    for p in graph_source_files(repo):
        h.update(p.relative_to(repo).as_posix().encode())
        h.update(b"\0")
        h.update(p.read_bytes())
        h.update(b"\0")
    return h.hexdigest()


def load_manifest(path: Path = MANIFEST_PATH) -> dict | None:
    if not path.exists():
        return None
    return json.loads(path.read_text())


def write_manifest(data: dict, path: Path = MANIFEST_PATH) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(data, indent=1, sort_keys=True) + "\n")


def diff_census(name: str, golden: dict, current: dict) -> list[str]:
    """Named per-primitive diff — the CI failure message IS the review
    artifact for a deliberate kernel change."""
    out: list[str] = []
    for field in ("lanes", "multiple", "ctx", "dtype", "eqns"):
        if golden.get(field) != current.get(field):
            out.append(
                f"{name}: {field} {golden.get(field)} -> {current.get(field)}"
            )
    for field in ("in_avals", "out_avals"):
        if golden.get(field) != current.get(field):
            out.append(
                f"{name}: {field} {golden.get(field)} -> "
                f"{current.get(field)}"
            )
    gp, cp = golden.get("prims", {}), current.get("prims", {})
    for prim in sorted(set(gp) | set(cp)):
        a, b = gp.get(prim, 0), cp.get(prim, 0)
        if a != b:
            out.append(f"{name}: prim {prim} {a} -> {b} ({b - a:+d})")
    return out


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def gather_families() -> dict:
    """The full registry: engine families (blsops import) + the mesh
    plane variants (registered here). Refuses to run against a non-CPU
    jax backend — the manifest censuses are blessed on CPU and
    limb.default_fp_ctx() is geometry-per-platform, so tracing
    elsewhere would diff against the wrong golden."""
    import jax

    backend = jax.default_backend()
    if backend != "cpu":
        raise RuntimeError(
            f"jaxpr_check must trace on CPU but jax already initialized "
            f"backend {backend!r} in this process — run it in a fresh "
            "process (JAX_PLATFORMS=cpu)"
        )
    from charon_tpu.ops import blsops
    from charon_tpu.parallel import mesh

    mesh.register_analysis_families()
    return blsops.kernel_families()


def run_check(
    families: dict,
    manifest: dict | None,
    *,
    full: bool = False,
    update: bool = False,
    only: list[str] | None = None,
    digest: str | None = None,
    progress=None,
) -> tuple[list[str], dict, int]:
    """Core engine shared by the CLI and the test batteries.

    Returns (failures, new_manifest_families, traced_count). `only`
    restricts tracing to the named families (no registry/golden
    completeness checks — the targeted test/debug mode)."""
    failures: list[str] = []
    traced: dict[str, dict] = {}

    if only is not None:
        unknown = set(only) - set(families)
        if unknown:
            raise KeyError(f"unknown kernel families: {sorted(unknown)}")
        to_trace = {n: families[n] for n in only}
    else:
        golden_fams = (manifest or {}).get("families", {})
        for name in sorted(set(golden_fams) - set(families)):
            # in update mode the rewritten manifest simply omits the
            # family — that IS the re-bless, not a violation
            if not update:
                failures.append(
                    f"{name}: in kernel_manifest.json but no longer "
                    "registered (removed kernel families must be "
                    "re-blessed with --update)"
                )
        for name in sorted(set(families) - set(golden_fams)):
            if not update:
                failures.append(
                    f"{name}: registered but missing from "
                    "kernel_manifest.json (bless new families with "
                    "--update)"
                )
        digest_ok = (
            manifest is not None
            and digest is not None
            and manifest.get("source_digest") == digest
            and manifest.get("jax_version") == _jax_version()
        )
        if full or update or not digest_ok:
            to_trace = dict(families)
        else:
            to_trace = {
                n: f for n, f in families.items() if f.sentinel
            }

    for name in sorted(to_trace):
        if progress:
            progress(name)
        cens, violations = analyze_family(name, to_trace[name])
        traced[name] = cens
        failures.extend(violations)
        golden = (manifest or {}).get("families", {}).get(name)
        if golden is not None and not update:
            failures.extend(diff_census(name, golden, cens))
    return failures, traced, len(traced)


def _jax_version() -> str:
    import jax

    return jax.__version__


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="charon_tpu.analysis.jaxpr_check",
        description="device-graph invariant checks + kernel golden manifest",
    )
    ap.add_argument(
        "--full",
        action="store_true",
        help="retrace every family (default: sentinels + source digest)",
    )
    ap.add_argument(
        "--update",
        action="store_true",
        help="retrace everything and re-bless the golden manifest",
    )
    ap.add_argument(
        "--family",
        action="append",
        default=None,
        help="trace only this family (repeatable; skips completeness checks)",
    )
    ap.add_argument(
        "--manifest",
        default=str(MANIFEST_PATH),
        help="golden manifest path (tests override)",
    )
    ap.add_argument(
        "--list", action="store_true", help="print the kernel inventory"
    )
    args = ap.parse_args(argv)

    if args.update and args.family:
        # run_check(only=...) skips golden diffs and main() never
        # writes a partial manifest — the combination would exit 0
        # having blessed NOTHING, which reads as success
        print(
            "--update re-blesses the WHOLE manifest and cannot be "
            "combined with --family",
            file=sys.stderr,
        )
        return 2

    families = gather_families()
    if args.list:
        for name, fam in sorted(families.items()):
            print(f"{'sentinel' if fam.sentinel else 'digest  '} {name}")
        return 0

    manifest_path = Path(args.manifest)
    manifest = load_manifest(manifest_path)
    digest = source_digest()
    if manifest is None and not args.update:
        print(
            f"no golden manifest at {manifest_path} — generate one with "
            "--update",
            file=sys.stderr,
        )
        return 1

    retracing_all = args.full or args.update or (
        manifest is not None
        and (
            manifest.get("source_digest") != digest
            or manifest.get("jax_version") != _jax_version()
        )
    )
    if retracing_all and not (args.full or args.update):
        print(
            "kernel sources (or jax) changed since the manifest was "
            "blessed — full retrace (25-60 s per pairing family on one "
            "core)",
            file=sys.stderr,
        )

    failures, traced, n = run_check(
        families,
        manifest,
        full=args.full,
        update=args.update,
        only=args.family,
        digest=digest,
        progress=lambda name: print(f"tracing {name}", file=sys.stderr),
    )
    for f in failures:
        print(f)

    if args.update and not args.family:
        if failures:
            print(
                "refusing to bless a manifest over live violations",
                file=sys.stderr,
            )
            return 1
        write_manifest(
            {
                "version": 1,
                "jax_version": _jax_version(),
                "source_digest": digest,
                "source_files": [
                    p.relative_to(_REPO).as_posix()
                    for p in graph_source_files()
                ],
                "families": traced,
            },
            manifest_path,
        )
        print(
            f"blessed {len(traced)} families into {manifest_path}",
            file=sys.stderr,
        )
        return 0

    if (
        not failures
        and retracing_all
        and manifest is not None
        and manifest.get("source_digest") != digest
    ):
        print(
            "censuses all match but the source digest is stale — run "
            "--update to restore the sentinel fast path",
            file=sys.stderr,
        )
    covered = len(families) if args.family is None else n
    print(
        f"{len(failures)} violation(s); {n} traced / {covered} families "
        f"covered",
        file=sys.stderr,
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
