"""jax-free-host: documented jax-less modules must not import jax.

Several host-side modules are load-bearing on jax-less hosts: the wire
codec and quarantine state machine (bench_wire.py runs them on the
jax-less CI image), app/metrics.py (scraped in every process, including
the promrated sidecar), testutil/chaos.py (the seeded fault plane must
inject into pure-host tests), the hostplane/wire benches themselves,
and this analysis package (the `ci.sh analysis` tier runs everywhere).
One careless module-scope `import jax` — or an innocent-looking
`from charon_tpu.core import X` whose import chain reaches jax —
breaks every one of those hosts at import time, and nothing catches it
until the jax-less CI leg runs.

The rule: for each module in the jax-free set (explicit list below,
plus any module whose docstring claims to be "jax-free"/"jax-less"),
no *unguarded module-scope* import may reach `jax`/`jaxlib`, directly
or transitively through charon_tpu-internal imports. Guarded imports
(inside `try/except ImportError` — the established "tolerates a
jax-less host" pattern, e.g. core/cryptoplane's optional ops import)
are soft edges and allowed; `if TYPE_CHECKING:` blocks are ignored.
Module-scope *calls* to module-level functions (the
`_register_core_types()` pattern in p2p/codec.py) count as module
scope: their imports execute at import time all the same.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

from charon_tpu.analysis.lint import (
    LintModule,
    Rule,
    Violation,
    scope_key,
)

_JAX_FREE_PREFIXES = ("charon_tpu/analysis/",)
_JAX_FREE_FILES = frozenset(
    {
        "charon_tpu/p2p/codec.py",
        "charon_tpu/p2p/quarantine.py",
        "charon_tpu/app/metrics.py",
        "charon_tpu/testutil/chaos.py",
        "bench_wire.py",
        "bench_hostplane.py",
    }
)
_DOC_MARK = re.compile(r"jax[- ](free|less)", re.IGNORECASE)


def _docstring_claims_jax_free(tree: ast.AST) -> bool:
    doc = ast.get_docstring(tree, clean=False) or ""
    return bool(_DOC_MARK.search(doc))


def _module_scope_imports(
    tree: ast.Module,
) -> list[tuple[str, int, bool]]:
    """(dotted_module, lineno, guarded) for every import that executes
    at module import time. `guarded` = inside a try whose handlers
    catch ImportError/ModuleNotFoundError/Exception (the module works
    without it). `if TYPE_CHECKING:` bodies never execute — skipped."""
    out: list[tuple[str, int, bool]] = []
    funcs = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }

    def _is_type_checking(test: ast.AST) -> bool:
        return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
            isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
        )

    def _catches_import_error(handlers) -> bool:
        names = set()
        for h in handlers:
            t = h.type
            if t is None:
                return True
            for n in t.elts if isinstance(t, ast.Tuple) else [t]:
                if isinstance(n, ast.Name):
                    names.add(n.id)
                elif isinstance(n, ast.Attribute):
                    names.add(n.attr)
        return bool(
            names & {"ImportError", "ModuleNotFoundError", "Exception",
                     "BaseException"}
        )

    def walk(stmts, guarded: bool) -> None:
        for st in stmts:
            if isinstance(st, ast.Import):
                for a in st.names:
                    out.append((a.name, st.lineno, guarded))
            elif isinstance(st, ast.ImportFrom):
                if st.level:  # relative import; repo uses absolute
                    continue
                if st.module:
                    for a in st.names:
                        # `from pkg import sub` may bind a submodule —
                        # record both candidates, resolver picks
                        out.append(
                            (f"{st.module}.{a.name}", st.lineno, guarded)
                        )
                        out.append((st.module, st.lineno, guarded))
            elif isinstance(st, ast.Try):
                g = guarded or _catches_import_error(st.handlers)
                walk(st.body, g)
                walk(st.orelse, guarded)
                walk(st.finalbody, guarded)
                for h in st.handlers:
                    walk(h.body, guarded)
            elif isinstance(st, ast.If):
                if _is_type_checking(st.test):
                    walk(st.orelse, guarded)
                    continue
                walk(st.body, guarded)
                walk(st.orelse, guarded)
            elif isinstance(st, (ast.With, ast.For, ast.While)):
                walk(st.body, guarded)
                walk(getattr(st, "orelse", []), guarded)
            elif (
                isinstance(st, ast.Expr)
                and isinstance(st.value, ast.Call)
                and isinstance(st.value.func, ast.Name)
                and st.value.func.id in funcs
            ):
                # module-scope call of a module-level function: its
                # imports execute at import time (codec's
                # _register_core_types pattern)
                fn = funcs[st.value.func.id]
                walk(fn.body, guarded)

    walk(tree.body, False)
    return out


class JaxFreeHost(Rule):
    name = "jax-free-host"
    description = (
        "documented jax-free modules must not reach jax via unguarded "
        "module-scope imports (directly or transitively)"
    )

    def __init__(self) -> None:
        self._cache: dict[Path, list[tuple[str, int, bool]] | None] = {}

    def applies(self, mod: LintModule) -> bool:
        key = scope_key(mod.relpath)
        return (
            key in _JAX_FREE_FILES
            or key.startswith(_JAX_FREE_PREFIXES)
            or _docstring_claims_jax_free(mod.tree)
        )

    # -- transitive resolution --------------------------------------------

    def _root(self, mod: LintModule) -> Path | None:
        if mod.path is None:
            return None
        key = scope_key(mod.relpath)
        p = mod.path.resolve()
        if key.startswith("charon_tpu/"):
            # strip the key's components off the real path
            for _ in key.split("/"):
                p = p.parent
            return p
        return p.parent

    def _imports_of(self, root: Path, dotted: str):
        """Module-scope imports of `dotted` (charon_tpu.*), or None when
        it doesn't resolve to a file (attr import / namespace miss)."""
        rel = dotted.split(".")
        for cand in (
            root.joinpath(*rel).with_suffix(".py"),
            root.joinpath(*rel, "__init__.py"),
        ):
            if cand.is_file():
                if cand not in self._cache:
                    try:
                        tree = ast.parse(
                            cand.read_text(encoding="utf-8")
                        )
                        self._cache[cand] = _module_scope_imports(tree)
                    except (OSError, SyntaxError):
                        self._cache[cand] = None
                return self._cache[cand], cand
        return None, None

    def _package_chain(self, dotted: str) -> list[str]:
        """Importing a.b.c executes a/__init__, a.b/__init__ too."""
        parts = dotted.split(".")
        return [".".join(parts[: i + 1]) for i in range(len(parts) - 1)]

    def check(self, mod: LintModule) -> Iterator[Violation]:
        root = self._root(mod)
        seen: set[str] = set()

        def reaches_jax(dotted: str, depth: int) -> list[str] | None:
            """Chain of modules from `dotted` to a jax import, or None."""
            if dotted.split(".")[0] in ("jax", "jaxlib"):
                return [dotted]
            if not dotted.startswith("charon_tpu") or root is None:
                return None
            if dotted in seen or depth > 12:
                return None
            seen.add(dotted)
            imports, _ = self._imports_of(root, dotted)
            if imports is None:
                return None
            for sub, _line, guarded in imports:
                if guarded:
                    continue
                chain = reaches_jax(sub, depth + 1)
                if chain is not None:
                    return [dotted] + chain
            return None

        flagged_lines: set[int] = set()
        for dotted, line, guarded in _module_scope_imports(mod.tree):
            if guarded or line in flagged_lines:
                continue
            if dotted.split(".")[0] in ("jax", "jaxlib"):
                flagged_lines.add(line)
                yield Violation(
                    self.name,
                    mod.relpath,
                    line,
                    "jax imported at module scope in a jax-free module "
                    "(guard it behind try/except ImportError or move it "
                    "into the function that needs it)",
                )
                continue
            if dotted.startswith("charon_tpu"):
                for hop in self._package_chain(dotted) + [dotted]:
                    chain = reaches_jax(hop, 0)
                    if chain is not None:
                        flagged_lines.add(line)
                        yield Violation(
                            self.name,
                            mod.relpath,
                            line,
                            "module-scope import chain reaches jax: "
                            + " -> ".join(chain),
                        )
                        break
