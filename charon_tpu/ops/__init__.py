"""JAX/TPU batched BLS12-381 engine — the framework's compute hot path.

Where the reference calls herumi's C++/asm BLS one signature at a time
(ref: tbls/herumi.go), this package executes *batches* of field/curve/pairing
operations as single XLA programs, sharded over a TPU mesh by
charon_tpu/parallel.

Layout:
  limb.py    generic multi-limb Montgomery modular arithmetic (24-bit limbs)
  fptower.py Fp2/Fp6/Fp12 tower with stacked (vectorized) multiplications
  curve.py   G1/G2 Jacobian point ops, batched scalar-mul, MSM
  pairing.py batched multi-pairing (projective Miller loop + final exp),
             mirroring charon_tpu/crypto/pairing_fast.py exactly
  blsops.py  the user-facing batched BLS operations

uint64 limb storage requires x64 mode; enable it on import, before any
array is created.
"""

import jax

jax.config.update("jax_enable_x64", True)
