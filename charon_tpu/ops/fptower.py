"""Batched BLS12-381 extension-field towers on the limb engine.

Mirrors charon_tpu/crypto/fields.py (the executable specification) with
Montgomery limb arrays in place of Python ints:

    Fp2  = Fp[u]  / (u^2 + 1)        tuple (c0, c1) of (..., n_limbs) arrays
    Fp6  = Fp2[v] / (v^3 - xi)       tuple of three Fp2, xi = 1 + u
    Fp12 = Fp6[w] / (w^2 - v)        tuple of two Fp6

All elements are JAX pytrees, so they flow through jit/scan/cond/vmap
unchanged. Every function takes the Fp ModCtx first so the same code runs
on the 24-bit/uint64 (CPU) and 12-bit/uint32 (TPU) limb geometries.

Multiplication counts (in Fp mont_muls): fp2_mul 3 (Karatsuba), fp2_sqr 2,
fp6_mul 18, fp12_mul 54, fp12_cyclotomic_sqr 18 (Granger–Scott).

Plays the role of herumi's field tower (ref: tbls/herumi.go:25-36 links the
C++/asm backend); the reference has no batched equivalent — this is the
TPU-first redesign.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from charon_tpu.crypto import fields as F
from charon_tpu.ops import limb
from charon_tpu.ops.limb import ModCtx

# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


def fp2_zero(ctx: ModCtx, batch_shape=()):
    return (limb.zeros(ctx, batch_shape), limb.zeros(ctx, batch_shape))


def fp2_one(ctx: ModCtx, batch_shape=()):
    return (limb.const(ctx, 1, batch_shape), limb.zeros(ctx, batch_shape))


def fp2_const(ctx: ModCtx, a, batch_shape=()):
    """Python-int pair (c0, c1) -> broadcast Montgomery constant."""
    return (
        limb.const(ctx, a[0], batch_shape),
        limb.const(ctx, a[1], batch_shape),
    )


def fp2_add(ctx, a, b):
    return (limb.add_mod(ctx, a[0], b[0]), limb.add_mod(ctx, a[1], b[1]))


def fp2_sub(ctx, a, b):
    return (limb.sub_mod(ctx, a[0], b[0]), limb.sub_mod(ctx, a[1], b[1]))


def fp2_neg(ctx, a):
    return (limb.neg_mod(ctx, a[0]), limb.neg_mod(ctx, a[1]))


def fp2_double(ctx, a):
    return (limb.double_mod(ctx, a[0]), limb.double_mod(ctx, a[1]))


def fp2_mul(ctx, a, b):
    """Karatsuba: 3 base muls.

    c0 = a0 b0 - a1 b1;  c1 = (a0+a1)(b0+b1) - a0 b0 - a1 b1.
    """
    v0 = limb.mont_mul(ctx, a[0], b[0])
    v1 = limb.mont_mul(ctx, a[1], b[1])
    s = limb.mont_mul(
        ctx,
        limb.add_mod(ctx, a[0], a[1]),
        limb.add_mod(ctx, b[0], b[1]),
    )
    return (
        limb.sub_mod(ctx, v0, v1),
        limb.sub_mod(ctx, limb.sub_mod(ctx, s, v0), v1),
    )


def fp2_sqr(ctx, a):
    """(a0+a1)(a0-a1) + 2 a0 a1 u — 2 base muls."""
    c0 = limb.mont_mul(
        ctx,
        limb.add_mod(ctx, a[0], a[1]),
        limb.sub_mod(ctx, a[0], a[1]),
    )
    c1 = limb.double_mod(ctx, limb.mont_mul(ctx, a[0], a[1]))
    return (c0, c1)


def fp2_mul_fp(ctx, a, s):
    """Multiply an Fp2 element by a (batched, Montgomery) Fp element."""
    return (limb.mont_mul(ctx, a[0], s), limb.mont_mul(ctx, a[1], s))


def fp2_small(ctx, a, k: int):
    """Multiply by a small static non-negative int via a double/add chain."""
    if k == 0:
        return fp2_zero(ctx, a[0].shape[:-1])
    acc = None
    add = a
    while k:
        if k & 1:
            acc = add if acc is None else fp2_add(ctx, acc, add)
        k >>= 1
        if k:
            add = fp2_double(ctx, add)
    return acc


def fp2_mul_xi(ctx, a):
    """Multiply by xi = 1 + u: (a0 - a1) + (a0 + a1) u."""
    return (limb.sub_mod(ctx, a[0], a[1]), limb.add_mod(ctx, a[0], a[1]))


def fp2_conj(ctx, a):
    return (a[0], limb.neg_mod(ctx, a[1]))


def fp2_inv(ctx, a):
    """Batched inverse: conj(a) / norm(a), norm inverted via Fermat.

    0 maps to 0 (inv_mod(0) == 0), which keeps identity-point lanes inert in
    batched curve code.
    """
    norm = limb.add_mod(
        ctx,
        limb.mont_sqr(ctx, a[0]),
        limb.mont_sqr(ctx, a[1]),
    )
    ninv = limb.inv_mod(ctx, norm)
    return (
        limb.mont_mul(ctx, a[0], ninv),
        limb.neg_mod(ctx, limb.mont_mul(ctx, a[1], ninv)),
    )


def fp2_is_zero(a):
    return jnp.logical_and(limb.is_zero(a[0]), limb.is_zero(a[1]))


def fp2_eq(a, b):
    return jnp.logical_and(
        jnp.all(a[0] == b[0], axis=-1), jnp.all(a[1] == b[1], axis=-1)
    )


def fp2_select(mask, a, b):
    return (limb.select(mask, a[0], b[0]), limb.select(mask, a[1], b[1]))


# ---------------------------------------------------------------------------
# Stacked multiplication engine
#
# XLA graph discipline: a pairing step contains hundreds of *independent*
# base-field multiplications. Emitting each as its own mont_mul subgraph
# made programs with ~100k HLO ops (30-minute CPU compiles). fp2_batch
# gathers every independent fp2 mul/sqr at one dependency level into a
# SINGLE stacked mont_mul (leading stack axis), cutting op count ~20x and
# giving XLA one big uniform kernel — exactly what the TPU wants.
# ---------------------------------------------------------------------------


def fp2_batch(ctx, ops):
    """Execute independent fp2 operations as one stacked base mul.

    ops: list of tuples —
      ("mul", a, b)    -> a * b          (3 base muls, Karatsuba)
      ("sqr", a)       -> a^2            (2 base muls)
      ("mul_fp", a, s) -> (a0*s, a1*s)   (2 base muls; s is an Fp element)

    All operands must share a batch shape. Returns the list of fp2 results
    in order.
    """
    xs, ys = [], []
    for op in ops:
        kind = op[0]
        if kind == "mul":
            _, a, b = op
            xs += [a[0], a[1], limb.add_mod(ctx, a[0], a[1])]
            ys += [b[0], b[1], limb.add_mod(ctx, b[0], b[1])]
        elif kind == "sqr":
            _, a = op
            xs += [limb.add_mod(ctx, a[0], a[1]), a[0]]
            ys += [limb.sub_mod(ctx, a[0], a[1]), a[1]]
        elif kind == "mul_fp":
            _, a, s = op
            xs += [a[0], a[1]]
            ys += [s, s]
        else:
            raise ValueError(kind)
    prods = limb.mont_mul(ctx, jnp.stack(xs), jnp.stack(ys))

    out = []
    i = 0
    for op in ops:
        kind = op[0]
        if kind == "mul":
            v0, v1, s = prods[i], prods[i + 1], prods[i + 2]
            i += 3
            out.append(
                (
                    limb.sub_mod(ctx, v0, v1),
                    limb.sub_mod(ctx, limb.sub_mod(ctx, s, v0), v1),
                )
            )
        elif kind == "sqr":
            c0, p = prods[i], prods[i + 1]
            i += 2
            out.append((c0, limb.double_mod(ctx, p)))
        else:  # mul_fp
            out.append((prods[i], prods[i + 1]))
            i += 2
    return out


def fp2_mul_many(ctx, pairs):
    return fp2_batch(ctx, [("mul", a, b) for a, b in pairs])


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------


def fp6_zero(ctx, batch_shape=()):
    return tuple(fp2_zero(ctx, batch_shape) for _ in range(3))


def fp6_one(ctx, batch_shape=()):
    return (
        fp2_one(ctx, batch_shape),
        fp2_zero(ctx, batch_shape),
        fp2_zero(ctx, batch_shape),
    )


def fp6_add(ctx, a, b):
    return tuple(fp2_add(ctx, x, y) for x, y in zip(a, b))


def fp6_sub(ctx, a, b):
    return tuple(fp2_sub(ctx, x, y) for x, y in zip(a, b))


def fp6_neg(ctx, a):
    return tuple(fp2_neg(ctx, x) for x in a)


# The 9 cross products one fp6 school-book multiply needs, as (i, j) index
# pairs into the two operands' coefficient triples.
_FP6_PRODS = ((0, 0), (1, 1), (2, 2), (1, 2), (2, 1), (0, 1), (1, 0), (0, 2), (2, 0))


def _fp6_combine(ctx, p):
    """Assemble an fp6 product from the 9 cross products (in _FP6_PRODS
    order): c0 = p00 + xi(p12 + p21); c1 = p01 + p10 + xi p22;
    c2 = p02 + p20 + p11."""
    p00, p11, p22, p12, p21, p01, p10, p02, p20 = p
    c0 = fp2_add(ctx, p00, fp2_mul_xi(ctx, fp2_add(ctx, p12, p21)))
    c1 = fp2_add(ctx, fp2_add(ctx, p01, p10), fp2_mul_xi(ctx, p22))
    c2 = fp2_add(ctx, fp2_add(ctx, p02, p20), p11)
    return (c0, c1, c2)


def fp6_mul(ctx, a, b):
    prods = fp2_mul_many(ctx, [(a[i], b[j]) for i, j in _FP6_PRODS])
    return _fp6_combine(ctx, prods)


def fp6_sqr(ctx, a):
    return fp6_mul(ctx, a, a)


def fp6_mul_by_v(ctx, a):
    """v * (a0 + a1 v + a2 v^2) = xi*a2 + a0 v + a1 v^2."""
    return (fp2_mul_xi(ctx, a[2]), a[0], a[1])


def fp6_inv(ctx, a):
    a0, a1, a2 = a
    t0 = fp2_sub(ctx, fp2_sqr(ctx, a0), fp2_mul_xi(ctx, fp2_mul(ctx, a1, a2)))
    t1 = fp2_sub(ctx, fp2_mul_xi(ctx, fp2_sqr(ctx, a2)), fp2_mul(ctx, a0, a1))
    t2 = fp2_sub(ctx, fp2_sqr(ctx, a1), fp2_mul(ctx, a0, a2))
    d = fp2_add(
        ctx,
        fp2_mul(ctx, a0, t0),
        fp2_mul_xi(
            ctx,
            fp2_add(ctx, fp2_mul(ctx, a2, t1), fp2_mul(ctx, a1, t2)),
        ),
    )
    dinv = fp2_inv(ctx, d)
    return (
        fp2_mul(ctx, t0, dinv),
        fp2_mul(ctx, t1, dinv),
        fp2_mul(ctx, t2, dinv),
    )


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def fp12_one(ctx, batch_shape=()):
    return (fp6_one(ctx, batch_shape), fp6_zero(ctx, batch_shape))


def fp12_mul(ctx, a, b):
    """Karatsuba over Fp6 with all 27 fp2 cross products in ONE stacked
    base mul: t0 = a0 b0, t1 = a1 b1, t2 = (a0+a1)(b0+b1);
    c0 = t0 + v t1, c1 = t2 - t0 - t1."""
    a0, a1 = a
    b0, b1 = b
    sa = fp6_add(ctx, a0, a1)
    sb = fp6_add(ctx, b0, b1)
    pairs = []
    for x, y in ((a0, b0), (a1, b1), (sa, sb)):
        pairs.extend((x[i], y[j]) for i, j in _FP6_PRODS)
    prods = fp2_mul_many(ctx, pairs)
    t0 = _fp6_combine(ctx, prods[0:9])
    t1 = _fp6_combine(ctx, prods[9:18])
    t2 = _fp6_combine(ctx, prods[18:27])
    c0 = fp6_add(ctx, t0, fp6_mul_by_v(ctx, t1))
    c1 = fp6_sub(ctx, fp6_sub(ctx, t2, t0), t1)
    return (c0, c1)


def fp12_sqr(ctx, a):
    """Generic square (the cyclotomic variant below is 3x cheaper but only
    valid after the easy part of the final exponentiation)."""
    return fp12_mul(ctx, a, a)


def fp12_conj(ctx, a):
    """f^(p^6): negates the w coefficient. Equals f^-1 for unitary f."""
    return (a[0], fp6_neg(ctx, a[1]))


def fp12_inv(ctx, a):
    a0, a1 = a
    d = fp6_sub(ctx, fp6_sqr(ctx, a0), fp6_mul_by_v(ctx, fp6_sqr(ctx, a1)))
    dinv = fp6_inv(ctx, d)
    return (fp6_mul(ctx, a0, dinv), fp6_neg(ctx, fp6_mul(ctx, a1, dinv)))


def fp12_select(mask, a, b):
    return tuple(
        tuple(
            fp2_select(mask, x, y)
            for x, y in zip(a6, b6)
        )
        for a6, b6 in zip(a, b)
    )


def fp12_is_one(ctx, a):
    """Batch mask: element == 1 (inputs in Montgomery form)."""
    one = limb.const(ctx, 1, a[0][0][0].shape[:-1])
    ok = jnp.all(a[0][0][0] == one, axis=-1)
    ok = jnp.logical_and(ok, limb.is_zero(a[0][0][1]))
    for c6 in (a[0][1], a[0][2], a[1][0], a[1][1], a[1][2]):
        ok = jnp.logical_and(ok, fp2_is_zero(c6))
    return ok


# Frobenius: gamma6 = xi^((p-1)/6); the (i, j) coefficient (of v^j w^i) is
# multiplied by gamma6^(2j+i) after Fp2 conjugation (ref spec:
# charon_tpu/crypto/fields.py fp12_frobenius).
@functools.lru_cache(maxsize=None)
def _gamma_pows() -> tuple:
    g = F.fp2_pow(F.XI, (F.P - 1) // 6)
    pows = [F.FP2_ONE]
    for _ in range(5):
        pows.append(F.fp2_mul(pows[-1], g))
    return tuple(pows)


def fp12_frobenius(ctx, a):
    pows = _gamma_pows()
    batch_shape = a[0][0][0].shape[:-1]
    ops = []
    for i in range(2):
        for j in range(3):
            k = 2 * j + i
            if k == 0:
                continue
            ops.append(
                (
                    "mul",
                    fp2_conj(ctx, a[i][j]),
                    fp2_const(ctx, pows[k], batch_shape),
                )
            )
    prods = iter(fp2_batch(ctx, ops))
    out6 = []
    for i in range(2):
        coeffs = []
        for j in range(3):
            if 2 * j + i == 0:
                coeffs.append(fp2_conj(ctx, a[i][j]))
            else:
                coeffs.append(next(prods))
        out6.append(tuple(coeffs))
    return tuple(out6)


def fp12_frobenius_n(ctx, a, n: int):
    for _ in range(n):
        a = fp12_frobenius(ctx, a)
    return a


def fp12_cyclotomic_sqr(ctx, a):
    """Granger–Scott squaring for unitary elements (post easy-part): 9 fp2
    squarings = 18 base muls vs 54 for a generic fp12_mul.

    With z = (c0, c1, c2) + (c3, c4, c5) w:
        t0..t5 as below, out = 3*t - 2*z (conjugate-flavored signs).
    """
    (c0, c1, c2), (c3, c4, c5) = a

    sq = fp2_batch(
        ctx,
        [
            ("sqr", c4),
            ("sqr", c0),
            ("sqr", fp2_add(ctx, c4, c0)),
            ("sqr", c2),
            ("sqr", c3),
            ("sqr", fp2_add(ctx, c2, c3)),
            ("sqr", c5),
            ("sqr", c1),
            ("sqr", fp2_add(ctx, c5, c1)),
        ],
    )
    t0, t1, t2, t3, t4, t5 = sq[0], sq[1], sq[3], sq[4], sq[6], sq[7]
    t6 = fp2_sub(ctx, sq[2], fp2_add(ctx, t0, t1))  # 2 c0 c4
    t7 = fp2_sub(ctx, sq[5], fp2_add(ctx, t2, t3))  # 2 c2 c3
    t8 = fp2_mul_xi(
        ctx, fp2_sub(ctx, sq[8], fp2_add(ctx, t4, t5))
    )  # 2 c1 c5 xi
    t0 = fp2_add(ctx, fp2_mul_xi(ctx, t0), t1)  # c0^2 + xi c4^2
    t2 = fp2_add(ctx, fp2_mul_xi(ctx, t2), t3)
    t4 = fp2_add(ctx, fp2_mul_xi(ctx, t4), t5)

    def out_c0(t, c):  # 3t - 2c
        return fp2_sub(ctx, fp2_small(ctx, t, 3), fp2_double(ctx, c))

    def out_c1(t, c):  # 3t + 2c
        return fp2_add(ctx, fp2_small(ctx, t, 3), fp2_double(ctx, c))

    return (
        (out_c0(t0, c0), out_c0(t2, c1), out_c0(t4, c2)),
        (out_c1(t8, c3), out_c1(t6, c4), out_c1(t7, c5)),
    )


# ---------------------------------------------------------------------------
# Host <-> device conversion helpers (tower elements <-> Python-int tuples)
# ---------------------------------------------------------------------------


def fp2_pack(ctx, values):
    """Iterable of Python Fp2 tuples -> batched device Fp2 (Montgomery)."""
    vals = list(values)
    return (
        jnp.asarray(limb.pack_mont_host(ctx, [v[0] for v in vals])),
        jnp.asarray(limb.pack_mont_host(ctx, [v[1] for v in vals])),
    )


def fp2_unpack(ctx, a) -> list:
    c0 = limb.unpack_mont_host(ctx, a[0])
    c1 = limb.unpack_mont_host(ctx, a[1])
    return list(zip(c0, c1))


def fp12_pack(ctx, values):
    """Iterable of Python Fp12 tower tuples -> batched device Fp12."""
    vals = list(values)
    return tuple(
        tuple(
            fp2_pack(ctx, [v[i][j] for v in vals])
            for j in range(3)
        )
        for i in range(2)
    )


def fp12_unpack(ctx, a) -> list:
    per_coeff = [
        [fp2_unpack(ctx, a[i][j]) for j in range(3)]
        for i in range(2)
    ]
    n = len(per_coeff[0][0])
    return [
        tuple(
            tuple(per_coeff[i][j][k] for j in range(3))
            for i in range(2)
        )
        for k in range(n)
    ]
