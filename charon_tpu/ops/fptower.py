"""Batched BLS12-381 extension-field towers on the limb engine.

Mirrors charon_tpu/crypto/fields.py (the executable specification) with
Montgomery limb arrays in place of Python ints:

    Fp2  = Fp[u]  / (u^2 + 1)        tuple (c0, c1) of (..., n_limbs) arrays
    Fp6  = Fp2[v] / (v^3 - xi)       tuple of three Fp2, xi = 1 + u
    Fp12 = Fp6[w] / (w^2 - v)        tuple of two Fp6

All elements are JAX pytrees, so they flow through jit/scan/cond/vmap
unchanged. Every function takes the Fp ModCtx first so the same code runs
on the 24-bit/uint64 (CPU) and 12-bit/uint32 (TPU) limb geometries.

Multiplication counts (in Fp mont_muls): fp2_mul 3 (Karatsuba), fp2_sqr 2,
fp6_mul 18, fp12_mul 54, fp12_cyclotomic_sqr 18 (Granger–Scott).

Plays the role of herumi's field tower (ref: tbls/herumi.go:25-36 links the
C++/asm backend); the reference has no batched equivalent — this is the
TPU-first redesign.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from charon_tpu.crypto import fields as F
from charon_tpu.ops import limb
from charon_tpu.ops.limb import ModCtx

# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


def fp2_zero(ctx: ModCtx, batch_shape=()):
    return (limb.zeros(ctx, batch_shape), limb.zeros(ctx, batch_shape))


def fp2_one(ctx: ModCtx, batch_shape=()):
    return (limb.const(ctx, 1, batch_shape), limb.zeros(ctx, batch_shape))


def fp2_const(ctx: ModCtx, a, batch_shape=()):
    """Python-int pair (c0, c1) -> broadcast Montgomery constant."""
    return (
        limb.const(ctx, a[0], batch_shape),
        limb.const(ctx, a[1], batch_shape),
    )


def fp2_add(ctx, a, b):
    r = limb.add_mod_many(ctx, [(a[0], b[0]), (a[1], b[1])])
    return (r[0], r[1])


def fp2_sub(ctx, a, b):
    r = limb.sub_mod_many(ctx, [(a[0], b[0]), (a[1], b[1])])
    return (r[0], r[1])


def fp2_neg(ctx, a):
    z = limb.zeros(ctx, a[0].shape[:-1])
    r = limb.sub_mod_many(ctx, [(z, a[0]), (z, a[1])])
    return (r[0], r[1])


def fp2_double(ctx, a):
    return fp2_add(ctx, a, a)


def fp2_mul(ctx, a, b):
    """Karatsuba, 3 base muls, as a one-op stacked batch:
    c0 = a0 b0 - a1 b1;  c1 = (a0+a1)(b0+b1) - a0 b0 - a1 b1."""
    return fp2_batch(ctx, [("mul", a, b)])[0]


def fp2_sqr(ctx, a):
    """(a0+a1)(a0-a1) + 2 a0 a1 u — 2 base muls."""
    return fp2_batch(ctx, [("sqr", a)])[0]


def fp2_mul_fp(ctx, a, s):
    """Multiply an Fp2 element by a (batched, Montgomery) Fp element."""
    return (limb.mont_mul(ctx, a[0], s), limb.mont_mul(ctx, a[1], s))


def fp2_small(ctx, a, k: int):
    """Multiply by a small static non-negative int via a double/add chain."""
    if k == 0:
        return fp2_zero(ctx, a[0].shape[:-1])
    acc = None
    add = a
    while k:
        if k & 1:
            acc = add if acc is None else fp2_add(ctx, acc, add)
        k >>= 1
        if k:
            add = fp2_double(ctx, add)
    return acc


def fp2_mul_xi(ctx, a):
    """Multiply by xi = 1 + u: (a0 - a1) + (a0 + a1) u."""
    ra, rs = limb.addsub_mod_many(
        ctx, [(a[0], a[1])], [(a[0], a[1])]
    )
    return (rs[0], ra[0])


def fp2_conj(ctx, a):
    return (a[0], limb.neg_mod(ctx, a[1]))


# -- stacked fp2 add/sub levels ---------------------------------------------
# Group independent fp2 additions/subtractions into ONE stacked limb
# normalize (see limb.add_mod_many): the tower's op count is dominated by
# carry-resolution subgraphs, so emitting one per dependency LEVEL rather
# than one per addition is the difference between compilable and
# intractable pairing programs.


def fp2_add_many(ctx, pairs):
    flat = []
    for a, b in pairs:
        flat += [(a[0], b[0]), (a[1], b[1])]
    res = limb.add_mod_many(ctx, flat)
    return [(res[2 * i], res[2 * i + 1]) for i in range(len(pairs))]


def fp2_sub_many(ctx, pairs):
    flat = []
    for a, b in pairs:
        flat += [(a[0], b[0]), (a[1], b[1])]
    res = limb.sub_mod_many(ctx, flat)
    return [(res[2 * i], res[2 * i + 1]) for i in range(len(pairs))]


def fp2_addsub_many(ctx, add_pairs, sub_pairs):
    """Independent fp2 adds + subs in one stacked normalize."""
    fa, fs = [], []
    for a, b in add_pairs:
        fa += [(a[0], b[0]), (a[1], b[1])]
    for a, b in sub_pairs:
        fs += [(a[0], b[0]), (a[1], b[1])]
    ra, rs = limb.addsub_mod_many(ctx, fa, fs)
    return (
        [(ra[2 * i], ra[2 * i + 1]) for i in range(len(add_pairs))],
        [(rs[2 * i], rs[2 * i + 1]) for i in range(len(sub_pairs))],
    )


def fp2_mul_xi_many(ctx, xs):
    """xi * x for xi = 1 + u: (x0 - x1, x0 + x1), stacked."""
    xs = list(xs)
    adds = [(x[0], x[1]) for x in xs]
    subs = [(x[0], x[1]) for x in xs]
    ra, rs = limb.addsub_mod_many(ctx, adds, subs)
    return [(rs[i], ra[i]) for i in range(len(xs))]


def fp2_inv(ctx, a):
    """Batched inverse: conj(a) / norm(a), norm inverted via Fermat.

    0 maps to 0 (inv_mod(0) == 0), which keeps identity-point lanes inert in
    batched curve code.
    """
    norm = limb.add_mod(
        ctx,
        limb.mont_sqr(ctx, a[0]),
        limb.mont_sqr(ctx, a[1]),
    )
    ninv = limb.inv_mod(ctx, norm)
    return (
        limb.mont_mul(ctx, a[0], ninv),
        limb.neg_mod(ctx, limb.mont_mul(ctx, a[1], ninv)),
    )


def fp2_is_zero(a):
    return jnp.logical_and(limb.is_zero(a[0]), limb.is_zero(a[1]))


def fp2_eq(a, b):
    return jnp.logical_and(
        jnp.all(a[0] == b[0], axis=-1), jnp.all(a[1] == b[1], axis=-1)
    )


def fp2_select(mask, a, b):
    return (limb.select(mask, a[0], b[0]), limb.select(mask, a[1], b[1]))


# ---------------------------------------------------------------------------
# Stacked multiplication engine
#
# XLA graph discipline: a pairing step contains hundreds of *independent*
# base-field multiplications. Emitting each as its own mont_mul subgraph
# made programs with ~100k HLO ops (30-minute CPU compiles). fp2_batch
# gathers every independent fp2 mul/sqr at one dependency level into a
# SINGLE stacked mont_mul (leading stack axis), cutting op count ~20x and
# giving XLA one big uniform kernel — exactly what the TPU wants.
# ---------------------------------------------------------------------------


def fp2_batch(ctx, ops):
    """Execute independent fp2 operations as one stacked base mul.

    ops: list of tuples —
      ("mul", a, b)    -> a * b          (3 base muls, Karatsuba)
      ("sqr", a)       -> a^2            (2 base muls)
      ("mul_fp", a, s) -> (a0*s, a1*s)   (2 base muls; s is an Fp element)

    All operands must share a batch shape. Returns the list of fp2 results
    in order.

    On the Pallas path the mul/sqr ops run as FUSED VMEM kernels
    (ops/pallas_mont.py fp2_mul_pallas/fp2_sqr_pallas): prep sums, the
    Montgomery multiplies, and the Karatsuba recombination never leave
    VMEM — the XLA path below round-trips HBM between each stacked
    normalize and the base multiply, which is where the engine was
    measured HBM-bound (PERF.md).
    """
    if _FP2_FUSION and limb._pallas_active(ctx):
        return _fp2_batch_pallas(ctx, ops)
    # prep level: every Karatsuba sum / squaring sum+difference in ONE
    # stacked normalize
    prep_adds, prep_subs = [], []
    for op in ops:
        if op[0] == "mul":
            _, a, b = op
            prep_adds += [(a[0], a[1]), (b[0], b[1])]
        elif op[0] == "sqr":
            _, a = op
            prep_adds.append((a[0], a[1]))
            prep_subs.append((a[0], a[1]))
        elif op[0] != "mul_fp":
            raise ValueError(op[0])
    ra, rs = limb.addsub_mod_many(ctx, prep_adds, prep_subs)
    ra, rs = iter(ra), iter(rs)

    xs, ys = [], []
    for op in ops:
        kind = op[0]
        if kind == "mul":
            _, a, b = op
            xs += [a[0], a[1], next(ra)]
            ys += [b[0], b[1], next(ra)]
        elif kind == "sqr":
            _, a = op
            xs += [next(ra), a[0]]
            ys += [next(rs), a[1]]
        else:  # mul_fp
            _, a, s = op
            xs += [a[0], a[1]]
            ys += [s, s]
    prods = limb.mont_mul(ctx, jnp.stack(xs), jnp.stack(ys))

    # post level A: v0+v1 per mul; post level B: the Karatsuba subs and
    # squaring doubles — two stacked normalizes for the whole batch
    a_adds = []
    i = 0
    for op in ops:
        if op[0] == "mul":
            a_adds.append((prods[i], prods[i + 1]))
            i += 3
        else:
            i += 2
    v01s = iter(limb.add_mod_many(ctx, a_adds) if a_adds else [])

    b_adds, b_subs = [], []
    i = 0
    for op in ops:
        if op[0] == "mul":
            v0, v1, s = prods[i], prods[i + 1], prods[i + 2]
            i += 3
            b_subs += [(v0, v1), (s, next(v01s))]
        elif op[0] == "sqr":
            b_adds.append((prods[i + 1], prods[i + 1]))  # double
            i += 2
        else:
            i += 2
    rb_add, rb_sub = limb.addsub_mod_many(ctx, b_adds, b_subs)
    rb_add, rb_sub = iter(rb_add), iter(rb_sub)

    out = []
    i = 0
    for op in ops:
        kind = op[0]
        if kind == "mul":
            out.append((next(rb_sub), next(rb_sub)))
            i += 3
        elif kind == "sqr":
            out.append((prods[i], next(rb_add)))
            i += 2
        else:  # mul_fp
            out.append((prods[i], prods[i + 1]))
            i += 2
    return out


# Fused-Fp2 escape hatch: disabling fusion keeps the (independently
# proven) mont_mul Pallas kernel active while the fp2 ops fall back to
# the stacked-XLA path — bench.py's degradation ladder uses this so a
# Mosaic regression in the fused kernels costs ~2x, not the ~10x of
# losing Pallas entirely. At startup the flag is owned by
# core/autotune.KernelConfig (the fp2_fusion tuner axis).
_FP2_FUSION = True


def set_fp2_fusion(mode: bool) -> None:
    global _FP2_FUSION
    _FP2_FUSION = mode


def _fp2_batch_pallas(ctx, ops):
    """fp2_batch on the fused kernels: stack same-kind ops along a new
    leading axis so each kernel family compiles once per shape."""
    from charon_tpu.ops import pallas_mont as PK

    out = [None] * len(ops)
    muls = [(i, op) for i, op in enumerate(ops) if op[0] == "mul"]
    sqrs = [(i, op) for i, op in enumerate(ops) if op[0] == "sqr"]
    mulfps = [(i, op) for i, op in enumerate(ops) if op[0] == "mul_fp"]
    if len(muls) + len(sqrs) + len(mulfps) != len(ops):
        raise ValueError("unknown fp2_batch op")

    if muls:
        sa0, sa1, sb0, sb1 = [], [], [], []
        for _, (_, a, b) in muls:
            x0, x1, y0, y1 = jnp.broadcast_arrays(a[0], a[1], b[0], b[1])
            sa0.append(x0), sa1.append(x1), sb0.append(y0), sb1.append(y1)
        c0, c1 = PK.fp2_mul_pallas(
            ctx,
            (jnp.stack(jnp.broadcast_arrays(*sa0)), jnp.stack(jnp.broadcast_arrays(*sa1))),
            (jnp.stack(jnp.broadcast_arrays(*sb0)), jnp.stack(jnp.broadcast_arrays(*sb1))),
        )
        for j, (i, _) in enumerate(muls):
            out[i] = (c0[j], c1[j])

    if sqrs:
        sa0 = jnp.stack(jnp.broadcast_arrays(*(op[1][0] for _, op in sqrs)))
        sa1 = jnp.stack(jnp.broadcast_arrays(*(op[1][1] for _, op in sqrs)))
        c0, c1 = PK.fp2_sqr_pallas(ctx, (sa0, sa1))
        for j, (i, _) in enumerate(sqrs):
            out[i] = (c0[j], c1[j])

    if mulfps:
        xs, ys = [], []
        for _, (_, a, s) in mulfps:
            xs += [a[0], a[1]]
            ys += [s, s]
        prods = limb.mont_mul(
            ctx, jnp.stack(jnp.broadcast_arrays(*xs)), jnp.stack(jnp.broadcast_arrays(*ys))
        )
        for j, (i, _) in enumerate(mulfps):
            out[i] = (prods[2 * j], prods[2 * j + 1])
    return out


def fp2_mul_many(ctx, pairs):
    return fp2_batch(ctx, [("mul", a, b) for a, b in pairs])


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------


def fp6_zero(ctx, batch_shape=()):
    return tuple(fp2_zero(ctx, batch_shape) for _ in range(3))


def fp6_one(ctx, batch_shape=()):
    return (
        fp2_one(ctx, batch_shape),
        fp2_zero(ctx, batch_shape),
        fp2_zero(ctx, batch_shape),
    )


def fp6_add(ctx, a, b):
    return tuple(fp2_add_many(ctx, list(zip(a, b))))


def fp6_sub(ctx, a, b):
    return tuple(fp2_sub_many(ctx, list(zip(a, b))))


def fp6_neg(ctx, a):
    z = limb.zeros(ctx, a[0][0].shape[:-1])
    r = limb.sub_mod_many(ctx, [(z, c) for x in a for c in x])
    return ((r[0], r[1]), (r[2], r[3]), (r[4], r[5]))


# The 9 cross products one fp6 school-book multiply needs, as (i, j) index
# pairs into the two operands' coefficient triples.
_FP6_PRODS = ((0, 0), (1, 1), (2, 2), (1, 2), (2, 1), (0, 1), (1, 0), (0, 2), (2, 0))


def _fp6_combine_many(ctx, prod_groups):
    """Assemble fp6 products from groups of 9 cross products (in
    _FP6_PRODS order): c0 = p00 + xi(p12 + p21); c1 = p01 + p10 + xi p22;
    c2 = p02 + p20 + p11 — all groups share two stacked add levels."""
    # level 1: the pairwise sums (p12+p21), (p01+p10), (p02+p20) and the
    # xi components of p22 for every group
    l1_adds = []
    for p00, p11, p22, p12, p21, p01, p10, p02, p20 in prod_groups:
        l1_adds += [(p12, p21), (p01, p10), (p02, p20)]
    l1 = iter(fp2_add_many(ctx, l1_adds))

    # level 2: xi of the (p12+p21) sums and of p22 (xi is itself one
    # add+sub level), then the final additions
    xi_in = []
    sums = []
    for g in prod_groups:
        s1221 = next(l1)
        s0110 = next(l1)
        s0220 = next(l1)
        xi_in += [s1221, g[2]]  # xi(p12+p21), xi(p22)
        sums.append((s0110, s0220))
    xis = iter(fp2_mul_xi_many(ctx, xi_in))

    l3_adds = []
    for g, (s0110, s0220) in zip(prod_groups, sums):
        xi1221 = next(xis)
        xi22 = next(xis)
        l3_adds += [(g[0], xi1221), (s0110, xi22), (s0220, g[1])]
    l3 = iter(fp2_add_many(ctx, l3_adds))
    return [tuple(next(l3) for _ in range(3)) for _ in prod_groups]


def _fp6_combine(ctx, p):
    return _fp6_combine_many(ctx, [p])[0]


def fp6_mul(ctx, a, b):
    prods = fp2_mul_many(ctx, [(a[i], b[j]) for i, j in _FP6_PRODS])
    return _fp6_combine(ctx, prods)


def fp6_sqr(ctx, a):
    return fp6_mul(ctx, a, a)


def fp6_mul_by_v(ctx, a):
    """v * (a0 + a1 v + a2 v^2) = xi*a2 + a0 v + a1 v^2."""
    return (fp2_mul_xi(ctx, a[2]), a[0], a[1])


def fp6_inv(ctx, a):
    a0, a1, a2 = a
    # all six products in one stacked batch, then one xi level, one sub
    # level, the d-assembly batch, and the final scaling batch
    sq0, sq1, sq2, m12, m01, m02 = fp2_batch(
        ctx,
        [
            ("sqr", a0),
            ("sqr", a1),
            ("sqr", a2),
            ("mul", a1, a2),
            ("mul", a0, a1),
            ("mul", a0, a2),
        ],
    )
    x12, xsq2 = fp2_mul_xi_many(ctx, [m12, sq2])
    t0, t1, t2 = fp2_sub_many(
        ctx, [(sq0, x12), (xsq2, m01), (sq1, m02)]
    )
    p0, p1, p2 = fp2_mul_many(ctx, [(a0, t0), (a2, t1), (a1, t2)])
    s12 = fp2_add(ctx, p1, p2)
    (xs12,) = fp2_mul_xi_many(ctx, [s12])
    d = fp2_add(ctx, p0, xs12)
    dinv = fp2_inv(ctx, d)
    r = fp2_mul_many(ctx, [(t0, dinv), (t1, dinv), (t2, dinv)])
    return (r[0], r[1], r[2])


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def fp12_one(ctx, batch_shape=()):
    return (fp6_one(ctx, batch_shape), fp6_zero(ctx, batch_shape))


def fp12_mul(ctx, a, b):
    """Karatsuba over Fp6 with all 27 fp2 cross products in ONE stacked
    base mul: t0 = a0 b0, t1 = a1 b1, t2 = (a0+a1)(b0+b1);
    c0 = t0 + v t1, c1 = t2 - t0 - t1. Every add/sub level is stacked."""
    a0, a1 = a
    b0, b1 = b
    sums = iter(
        fp2_add_many(
            ctx, list(zip(a0, a1)) + list(zip(b0, b1))
        )
    )
    sa = tuple(next(sums) for _ in range(3))
    sb = tuple(next(sums) for _ in range(3))
    pairs = []
    for x, y in ((a0, b0), (a1, b1), (sa, sb)):
        pairs.extend((x[i], y[j]) for i, j in _FP6_PRODS)
    prods = fp2_mul_many(ctx, pairs)
    t0, t1, t2 = _fp6_combine_many(
        ctx, [prods[0:9], prods[9:18], prods[18:27]]
    )
    # c0 = t0 + v t1 (3 adds after the xi twist in mul_by_v);
    # c1 = t2 - t0 - t1 (6 subs over two levels, folded to one via
    # d = t2 - t0 then d - t1)
    vt1 = fp6_mul_by_v(ctx, t1)
    adds = list(zip(t0, vt1))
    subs = list(zip(t2, t0))
    ra, rs = fp2_addsub_many(ctx, adds, subs)
    c0 = tuple(ra)
    c1 = tuple(fp2_sub_many(ctx, list(zip(rs, t1))))
    return (c0, c1)


def fp12_sqr(ctx, a):
    """Generic square (the cyclotomic variant below is 3x cheaper but only
    valid after the easy part of the final exponentiation)."""
    return fp12_mul(ctx, a, a)


def fp12_conj(ctx, a):
    """f^(p^6): negates the w coefficient. Equals f^-1 for unitary f."""
    return (a[0], fp6_neg(ctx, a[1]))


def fp12_inv(ctx, a):
    a0, a1 = a
    # both fp6 squarings share one 18-product batch and one combine
    prods = fp2_mul_many(
        ctx,
        [(a0[i], a0[j]) for i, j in _FP6_PRODS]
        + [(a1[i], a1[j]) for i, j in _FP6_PRODS],
    )
    s0, s1 = _fp6_combine_many(ctx, [prods[:9], prods[9:]])
    d = fp6_sub(ctx, s0, fp6_mul_by_v(ctx, s1))
    dinv = fp6_inv(ctx, d)
    # both output fp6 muls share one 18-product batch and one combine
    prods2 = fp2_mul_many(
        ctx,
        [(a0[i], dinv[j]) for i, j in _FP6_PRODS]
        + [(a1[i], dinv[j]) for i, j in _FP6_PRODS],
    )
    n0, n1 = _fp6_combine_many(ctx, [prods2[:9], prods2[9:]])
    return (n0, fp6_neg(ctx, n1))


def fp12_select(mask, a, b):
    return tuple(
        tuple(
            fp2_select(mask, x, y)
            for x, y in zip(a6, b6)
        )
        for a6, b6 in zip(a, b)
    )


def fp12_is_one(ctx, a):
    """Batch mask: element == 1 (inputs in Montgomery form)."""
    one = limb.const(ctx, 1, a[0][0][0].shape[:-1])
    ok = jnp.all(a[0][0][0] == one, axis=-1)
    ok = jnp.logical_and(ok, limb.is_zero(a[0][0][1]))
    for c6 in (a[0][1], a[0][2], a[1][0], a[1][1], a[1][2]):
        ok = jnp.logical_and(ok, fp2_is_zero(c6))
    return ok


# Frobenius: gamma6 = xi^((p-1)/6); the (i, j) coefficient (of v^j w^i) is
# multiplied by gamma6^(2j+i) after Fp2 conjugation (ref spec:
# charon_tpu/crypto/fields.py fp12_frobenius).
@functools.lru_cache(maxsize=None)
def _gamma_pows() -> tuple:
    g = F.fp2_pow(F.XI, (F.P - 1) // 6)
    pows = [F.FP2_ONE]
    for _ in range(5):
        pows.append(F.fp2_mul(pows[-1], g))
    return tuple(pows)


def fp12_frobenius(ctx, a):
    pows = _gamma_pows()
    batch_shape = a[0][0][0].shape[:-1]
    ops = []
    for i in range(2):
        for j in range(3):
            k = 2 * j + i
            if k == 0:
                continue
            ops.append(
                (
                    "mul",
                    fp2_conj(ctx, a[i][j]),
                    fp2_const(ctx, pows[k], batch_shape),
                )
            )
    prods = iter(fp2_batch(ctx, ops))
    out6 = []
    for i in range(2):
        coeffs = []
        for j in range(3):
            if 2 * j + i == 0:
                coeffs.append(fp2_conj(ctx, a[i][j]))
            else:
                coeffs.append(next(prods))
        out6.append(tuple(coeffs))
    return tuple(out6)


def fp12_frobenius_n(ctx, a, n: int):
    for _ in range(n):
        a = fp12_frobenius(ctx, a)
    return a


def fp12_cyclotomic_sqr(ctx, a):
    """Granger–Scott squaring for unitary elements (post easy-part): 9 fp2
    squarings = 18 base muls vs 54 for a generic fp12_mul.

    With z = (c0, c1, c2) + (c3, c4, c5) w:
        t0..t5 as below, out = 3*t - 2*z (conjugate-flavored signs).
    """
    (c0, c1, c2), (c3, c4, c5) = a

    s40, s23, s51 = fp2_add_many(ctx, [(c4, c0), (c2, c3), (c5, c1)])
    sq = fp2_batch(
        ctx,
        [
            ("sqr", c4),
            ("sqr", c0),
            ("sqr", s40),
            ("sqr", c2),
            ("sqr", c3),
            ("sqr", s23),
            ("sqr", c5),
            ("sqr", c1),
            ("sqr", s51),
        ],
    )
    t0, t1, t2, t3, t4, t5 = sq[0], sq[1], sq[3], sq[4], sq[6], sq[7]
    # pairwise sums + xi twists, stacked
    s01, s23b, s45 = fp2_add_many(ctx, [(t0, t1), (t2, t3), (t4, t5)])
    xt0, xt2, xt4 = fp2_mul_xi_many(ctx, [t0, t2, t4])
    adds, subs = fp2_addsub_many(
        ctx,
        [(xt0, t1), (xt2, t3), (xt4, t5)],  # xi t^2 + t'^2
        [(sq[2], s01), (sq[5], s23b), (sq[8], s45)],  # the 2ab terms
    )
    u0, u2, u4 = adds
    t6, t7, t8pre = subs
    (t8,) = fp2_mul_xi_many(ctx, [t8pre])

    # outputs 3t ± 2c over three stacked levels (double, triple, combine)
    ts = [u0, u2, u4, t8, t6, t7]
    cs = [c0, c1, c2, c3, c4, c5]
    doubles = fp2_add_many(
        ctx, [(t, t) for t in ts] + [(c, c) for c in cs]
    )
    t2s, c2s = doubles[:6], doubles[6:]
    t3s = fp2_add_many(ctx, list(zip(t2s, ts)))
    adds2, subs2 = fp2_addsub_many(
        ctx,
        list(zip(t3s[3:], c2s[3:])),  # c1 row: 3t + 2c
        list(zip(t3s[:3], c2s[:3])),  # c0 row: 3t - 2c
    )
    return (tuple(subs2), tuple(adds2))


# ---------------------------------------------------------------------------
# Host <-> device conversion helpers (tower elements <-> Python-int tuples)
# ---------------------------------------------------------------------------


def fp2_pack(ctx, values):
    """Iterable of Python Fp2 tuples -> batched device Fp2 (Montgomery)."""
    vals = list(values)
    return (
        jnp.asarray(limb.pack_mont_host(ctx, [v[0] for v in vals])),
        jnp.asarray(limb.pack_mont_host(ctx, [v[1] for v in vals])),
    )


def fp2_unpack(ctx, a) -> list:
    c0 = limb.unpack_mont_host(ctx, a[0])
    c1 = limb.unpack_mont_host(ctx, a[1])
    return list(zip(c0, c1))


def fp12_pack(ctx, values):
    """Iterable of Python Fp12 tower tuples -> batched device Fp12."""
    vals = list(values)
    return tuple(
        tuple(
            fp2_pack(ctx, [v[i][j] for v in vals])
            for j in range(3)
        )
        for i in range(2)
    )


def fp12_unpack(ctx, a) -> list:
    per_coeff = [
        [fp2_unpack(ctx, a[i][j]) for j in range(3)]
        for i in range(2)
    ]
    n = len(per_coeff[0][0])
    return [
        tuple(
            tuple(per_coeff[i][j][k] for j in range(3))
            for i in range(2)
        )
        for k in range(n)
    ]
