"""int8-MXU decomposition of 12-bit-limb Montgomery multiplication.

The separated-operand mont_mul (ops/limb.py:473) spends its FLOPs in
three limb convolutions. Two of them multiply by CONSTANT vectors —
t * ninv (mod R) and m * p — and a convolution by a constant is a matmul
against a fixed Toeplitz band matrix:

    conv(a, c)[k] = sum_i a[i] * c[k-i]  =  (a @ T_c)[k],
    T_c[i, k] = c[k-i]

which is exactly the shape the MXU consumes, provided the entries fit
its int8 x int8 -> int32 mode. A 12-bit limb splits into two 6-bit
pieces (v = v1*64 + v0, both < 64): 12 = 6 + 6 rather than the 4 + 8
split noted in ops/limb.py because the MXU multiplies SIGNED int8 — an
8-bit piece (0..255) would need offset correction terms, while 6-bit
pieces use the [0, 63] subrange directly. Each constant conv becomes
four int8 matmuls (a0/a1 against T0/T1) recombined with shifts:

    conv(a, c) = s00 + (s01 + s10) << 6 + s11 << 12

Headroom: n=32 column terms x 63^2 <= 127,008 per partial sum, and the
recombined column is < 2^30 — inside the uint32 accumulator range the
existing carry normalization (limb._normalize) is built for.

The data-dependent a*b product keeps the VPU band-einsum (both operands
vary per lane, so there is no constant matrix to hit the MXU with).

Cost model vs the pure-VPU path: see PERF.md "int8-MXU lever". This
module is interpret-mode/CPU-correct today (tests/test_limb_mxu.py
cross-checks bit-identity against mont_mul and the host bigint oracle);
enabling it on real TPU is a dispatch flag once measured.

ref analogue: none — the reference's herumi backend is scalar CPU
assembly (tbls/herumi.go); this decomposition exists only because the
target is a systolic array.
"""

from __future__ import annotations

import functools

import numpy as np
import jax.numpy as jnp
from jax import lax

from charon_tpu.ops import limb
from charon_tpu.ops.limb import ModCtx

_PIECE_BITS = 6
_PIECE_MASK = (1 << _PIECE_BITS) - 1


def _toeplitz_pieces(c: np.ndarray, n: int, out_cols: int):
    """Constant limb vector -> (T0, T1) int8 band matrices [n, out_cols]
    holding the low/high 6-bit pieces of c[k-i]."""
    T0 = np.zeros((n, out_cols), np.int8)
    T1 = np.zeros((n, out_cols), np.int8)
    for i in range(n):
        for k in range(out_cols):
            j = k - i
            if 0 <= j < n:
                v = int(c[j])
                T0[i, k] = v & _PIECE_MASK
                T1[i, k] = v >> _PIECE_BITS
    return T0, T1


@functools.lru_cache(maxsize=None)
def _ninv_toeplitz(ctx: ModCtx):
    """Low-conv (mod R) Toeplitz of -m^-1: out_cols = n."""
    return _toeplitz_pieces(ctx.ninv, ctx.n_limbs, ctx.n_limbs)


@functools.lru_cache(maxsize=None)
def _modulus_toeplitz(ctx: ModCtx):
    """Full-conv Toeplitz of the modulus: out_cols = 2n."""
    return _toeplitz_pieces(ctx.limbs, ctx.n_limbs, 2 * ctx.n_limbs)


def conv_const_mxu(a, T0, T1):
    """conv(a, c) for canonical-limb `a` and a constant c given as
    Toeplitz 6-bit piece matrices — four int8 matmuls on the MXU,
    recombined in uint32 accumulator range. The ONE copy of the
    piece-split/recombine math: the XLA-level mont_mul_mxu below and the
    fused Pallas kernel (ops/pallas_mont.py) both call it — T0/T1 may be
    numpy constants (XLA folds them) or VMEM ref loads. _PIECE_MASK is a
    Python int, so nothing here is a captured jnp constant (pallas_call
    rejects those)."""
    a = a.astype(jnp.int32)
    a0 = (a & _PIECE_MASK).astype(jnp.int8)
    a1 = (a >> _PIECE_BITS).astype(jnp.int8)

    def mm(x, T):
        return lax.dot_general(
            x,
            jnp.asarray(T),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

    s00 = mm(a0, T0)
    s01 = mm(a0, T1)
    s10 = mm(a1, T0)
    s11 = mm(a1, T1)
    return (
        s00.astype(jnp.uint32)
        + ((s01 + s10).astype(jnp.uint32) << _PIECE_BITS)
        + (s11.astype(jnp.uint32) << (2 * _PIECE_BITS))
    )


def mont_mul_mxu(ctx: ModCtx, a, b):
    """a * b * R^-1 mod m — same algorithm and tail as limb.mont_mul,
    with the two constant-operand convolutions lowered to int8 MXU
    matmuls (module docstring). Requires a 12-bit limb geometry."""
    if ctx.limb_bits != 12:
        raise ValueError("int8-MXU decomposition needs the 12-bit geometry")
    a, b = jnp.broadcast_arrays(a, b)
    n = ctx.n_limbs
    t = limb._conv_full(ctx, a, b)  # data-dependent: stays VPU
    t, _ = limb._normalize(ctx, t)
    m = conv_const_mxu(t[..., :n], *_ninv_toeplitz(ctx))
    m, _ = limb._normalize(ctx, m)  # mod R: top carry intentionally dropped
    s = t + conv_const_mxu(m, *_modulus_toeplitz(ctx))
    return limb._mont_tail(ctx, s)
