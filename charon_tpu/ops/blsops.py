"""User-facing batched BLS kernels: verify, threshold-aggregate, aggregate.

This is the device engine behind the tbls TPU implementation. Where the
reference recombines one signature at a time on the CPU
(ref: tbls/herumi.go:249-286 ThresholdAggregate — Lagrange interpolation at
the share indices; ref: tbls/herumi.go:288 Verify — one pairing per call),
these kernels take whole [num_validators, threshold] / [num_sigs] batches
and execute them as single XLA programs.

Kernel-shape discipline: public entry points pad the batch axis to the next
power of two and cache one compiled program per (kernel, padded-shape,
threshold) key, so steady-state slot processing never recompiles.

Identity encoding: affine (0, 0) lanes are group identities throughout
(safe on these curves since b != 0 means y = 0 never occurs).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from charon_tpu.ops import curve as C
from charon_tpu.ops import decompress as DEC
from charon_tpu.ops import fptower as T
from charon_tpu.ops import limb
from charon_tpu.ops import pairing as DP
from charon_tpu.ops import sswu as SSWU
from charon_tpu.ops.limb import ModCtx


def next_pow2(n: int) -> int:
    """Padded batch size: next power of two, minimum 4 — so every small
    call shares one compiled program (kernel-shape discipline)."""
    return max(4, 1 << max(0, (n - 1)).bit_length())


_next_pow2 = next_pow2  # internal alias (pre-bucketing name)


def bucket_lanes(n: int, multiple: int = 1) -> int:
    """THE shape-bucket ladder every batched entry point pads to:
    `multiple * pow2(ceil(n / multiple))`.

    `multiple` is the mesh shard count for sharded planes (the padded
    batch must split evenly over shards) and 1 for single-device
    engines, where this reduces to plain next_pow2 with its 4-lane
    floor. Sharded planes use a per-shard floor of 1 instead — the
    shard count is already their batch floor, so small slot workloads
    keep the cheap `multiple`-lane program. Using one ladder for
    BlsEngine AND the coalescer's sharded flushes keeps the jit cache
    bounded at O(log max_batch) compiled programs per kernel family —
    arbitrary flush sizes land on pre-declared bucket shapes instead of
    compiling per size (ISSUE 3: unify shape bucketing)."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    if multiple == 1:
        return next_pow2(n)
    per_shard = -(-n // multiple)
    return multiple * (1 << max(0, (per_shard - 1)).bit_length())


# Every jitted kernel this module builds registers here so tests (and
# operators via bench tooling) can measure COMPILED PROGRAM counts —
# the regression signal for unbounded jit-cache growth when a caller
# bypasses the bucket ladder.
_JIT_KERNELS: list = []


def _jit_kernel(fn):
    jitted = jax.jit(fn)
    _JIT_KERNELS.append(jitted)
    return jitted


def jit_cache_size() -> int:
    """Total compiled-program count across this module's live jitted
    kernels. Bounded by (kernel families) x (bucket-ladder shapes) —
    asserted in tests/test_hostplane.py across random-size flushes."""
    return sum(k._cache_size() for k in _JIT_KERNELS)


# ---------------------------------------------------------------------------
# Named kernel-family registry (ISSUE 11)
# ---------------------------------------------------------------------------
#
# _JIT_KERNELS above counts compiled programs but is anonymous — it can
# tell you HOW MANY programs exist, not WHICH. The named registry below
# is the machine-readable kernel inventory: every device-graph family
# registers a build closure that returns a traceable (fn, canonical
# args) pair on bucket-ladder shapes, so the static analyzer
# (charon_tpu/analysis/jaxpr_check.py) can jax.make_jaxpr each family
# WITHOUT executing it, and the future per-platform auto-tuner
# (ROADMAP item 3) can enumerate candidates. Registration is cheap
# (closures only); canonical inputs are built lazily at trace time.


@dataclasses.dataclass
class TraceSpec:
    """One traceable instantiation of a kernel family: the callable,
    canonical example args on a bucket-ladder shape, and the limb
    geometry the analyzer checks dtype invariants against."""

    fn: Callable
    args: tuple
    ctx: "ModCtx"
    lanes: int  # padded batch lanes (must sit on the bucket ladder)
    multiple: int = 1  # ladder multiple (mesh shard count; 1 = engine)


@dataclasses.dataclass(frozen=True)
class KernelFamily:
    """A registered device-graph family. `build()` -> TraceSpec.

    `sentinel` families are cheap to trace (~seconds) and are re-traced
    on EVERY `ci.sh analysis` run; non-sentinel families (the pairing
    graphs trace in 25-45 s each on one core) are covered by the
    manifest source digest and re-traced only when kernel sources
    change (jaxpr_check --full / --update)."""

    name: str
    build: Callable[[], TraceSpec]
    sentinel: bool = False


_KERNEL_FAMILIES: dict[str, KernelFamily] = {}


def register_kernel_family(
    name: str, build: Callable[[], TraceSpec], sentinel: bool = False
) -> None:
    if name in _KERNEL_FAMILIES:
        raise ValueError(f"kernel family {name!r} already registered")
    _KERNEL_FAMILIES[name] = KernelFamily(name, build, sentinel)


def kernel_families() -> dict[str, KernelFamily]:
    """Snapshot of the registry (engine families at import time; mesh
    plane variants after parallel.mesh.register_analysis_families())."""
    return dict(_KERNEL_FAMILIES)


def _register_engine_families() -> None:
    """Register this module's kernel families on canonical shapes.

    Canonical lanes = 4 (the ladder floor) keeps trace time minimal —
    the primitive census is shape-stable per family, so one ladder
    point pins the graph. Both limb geometries register for the cheap
    families: the uint32 (TPU) geometry is where a stray 64-bit
    widening or float promotion would actually hurt, so the sentinels
    cover it every run."""
    from charon_tpu.ops import curve as _C

    def _pts(ctx, n):
        from charon_tpu.crypto.g1g2 import G1_GEN, G2_GEN

        return (
            _C.g1_pack(ctx, [G1_GEN] * n),
            _C.g2_pack(ctx, [G2_GEN] * n),
            _C.g2_pack(ctx, [G2_GEN] * n),
        )

    def _grid(tree, t):
        return jax.tree_util.tree_map(
            lambda a: jnp.stack([a] * t, axis=1), tree
        )

    n = 4
    t = 3

    def _verify(ctx, fr_ctx):
        pk, msg, sig = _pts(ctx, n)
        return TraceSpec(_verify_kernel(ctx), (pk, msg, sig), ctx, n)

    def _verify_rlc(ctx, fr_ctx):
        pk, msg, sig = _pts(ctx, n)
        rand = jnp.asarray(limb.ctx_pack(fr_ctx, [1] * n))
        return TraceSpec(
            _verify_rlc_kernel(ctx, fr_ctx), (pk, msg, sig, rand), ctx, n
        )

    def _verify_grouped(ctx, fr_ctx):
        pk, msg, sig = _pts(ctx, n * n)
        gridify = lambda tree: jax.tree_util.tree_map(
            lambda a: a.reshape(n, n, *a.shape[1:]), tree
        )
        rand = jnp.asarray(
            np.asarray(limb.ctx_pack(fr_ctx, [1] * (n * n))).reshape(
                n, n, -1
            )
        )
        return TraceSpec(
            _verify_grouped_rlc_kernel(ctx, fr_ctx),
            (gridify(pk), _pts(ctx, n)[1], gridify(sig), rand),
            ctx,
            n,
        )

    def _thr_agg(ctx, fr_ctx):
        _, _, sig = _pts(ctx, n)
        idx = jnp.asarray(
            np.tile(np.arange(1, t + 1, dtype=np.int32), (n, 1))
        )
        return TraceSpec(
            _threshold_agg_kernel(ctx, fr_ctx, t),
            (_grid(sig, t), idx),
            ctx,
            n,
        )

    def _agg(ctx, fr_ctx):
        _, _, sig = _pts(ctx, n)
        return TraceSpec(_aggregate_kernel(ctx, t), (_grid(sig, t),), ctx, n)

    def _g1sum(ctx, fr_ctx):
        pk, _, _ = _pts(ctx, n)
        return TraceSpec(_g1_sum_kernel(ctx, t), (_grid(pk, t),), ctx, n)

    def _sub_g2(ctx, fr_ctx):
        _, _, sig = _pts(ctx, n)
        order = jnp.asarray(limb.ctx_pack(fr_ctx, [fr_ctx.modulus] * n))
        return TraceSpec(
            _subgroup_g2_kernel(ctx, fr_ctx), (sig, order), ctx, n
        )

    def _sub_g1(ctx, fr_ctx):
        pk, _, _ = _pts(ctx, n)
        order = jnp.asarray(limb.ctx_pack(fr_ctx, [fr_ctx.modulus] * n))
        return TraceSpec(
            _subgroup_g1_kernel(ctx, fr_ctx), (pk, order), ctx, n
        )

    def _dec_g2(ctx, fr_ctx):
        from charon_tpu.crypto.g1g2 import G2_GEN, g2_to_bytes

        parsed = [DEC.parse_g2_lane(g2_to_bytes(G2_GEN))] * n
        return TraceSpec(
            _decompress_g2_kernel(ctx, fr_ctx, True),
            DEC.pack_parsed_g2(ctx, parsed),
            ctx,
            n,
        )

    def _dec_g1(ctx, fr_ctx):
        from charon_tpu.crypto.g1g2 import G1_GEN, g1_to_bytes

        parsed = [DEC.parse_g1_lane(g1_to_bytes(G1_GEN))] * n
        return TraceSpec(
            _decompress_g1_kernel(ctx, fr_ctx, True),
            DEC.pack_parsed_g1(ctx, parsed),
            ctx,
            n,
        )

    def _h2c(ctx, fr_ctx):
        lanes = [SSWU.hash_to_field_lane(b"jaxpr-check", SSWU.DST_POP)] * n
        return TraceSpec(
            _hash_to_g2_kernel(ctx, fr_ctx),
            SSWU.pack_hashed(ctx, lanes),
            ctx,
            n,
        )

    def _g1_mul(ctx, fr_ctx):
        pk, _, _ = _pts(ctx, n)
        s = _C.fr_pack(fr_ctx, [1] * n)
        return TraceSpec(
            _g1_scalar_mul_kernel(ctx, fr_ctx), (pk, s), ctx, n
        )

    def _g2_mul(ctx, fr_ctx):
        _, _, sig = _pts(ctx, n)
        s = _C.fr_pack(fr_ctx, [1] * n)
        return TraceSpec(
            _g2_scalar_mul_kernel(ctx, fr_ctx), (sig, s), ctx, n
        )

    def _gen_mul(ctx, fr_ctx):
        s = _C.fr_pack(fr_ctx, [1] * n)
        return TraceSpec(
            _g1_gen_mul_kernel(ctx, fr_ctx, 255, 4), (s,), ctx, n
        )

    def _ceval(ctx, fr_ctx):
        pk, _, _ = _pts(ctx, n * t)
        grid = jax.tree_util.tree_map(
            lambda a: a.reshape(n, t, *a.shape[1:]), pk
        )
        xs = jnp.arange(1, n + 1, dtype=jnp.int32)
        return TraceSpec(
            _commitment_eval_kernel(ctx, fr_ctx, 1, t, 32), (grid, xs), ctx, n
        )

    def _g1msm(ctx, fr_ctx):
        pk, _, _ = _pts(ctx, n)
        s = jnp.asarray(limb.ctx_pack(fr_ctx, [1] * n))
        seg = jnp.zeros((n,), jnp.int32)
        return TraceSpec(
            _g1_msm_kernel(ctx, fr_ctx, 1, 255), (pk, s, seg), ctx, n
        )

    def _lag_at(ctx, fr_ctx):
        idx = jnp.asarray(
            np.tile(np.arange(1, t + 1, dtype=np.int32), (n, 1))
        )
        xs = jnp.arange(1, n + 1, dtype=jnp.int32)
        return TraceSpec(_lagrange_at_kernel(fr_ctx, t), (idx, xs), ctx, n)

    heavy = {
        "verify": _verify,
        "verify_rlc": _verify_rlc,
        "verify_grouped_rlc": _verify_grouped,
        "threshold_agg": _thr_agg,
        "hash_to_g2": _h2c,
        # ceremony families (ISSUE 20): fixed-base gather-adds, the
        # Straus/per-lane commitment evaluation, and the reshare
        # Pippenger MSM — curve-heavy graphs, digest-covered
        "g1_gen_mul": _gen_mul,
        "commitment_eval": _ceval,
        "g1_msm": _g1msm,
    }
    cheap = {
        "aggregate": _agg,
        "g1_sum": _g1sum,
        "subgroup_g2": _sub_g2,
        "subgroup_g1": _sub_g1,
        "decompress_g2": _dec_g2,
        "decompress_g1": _dec_g1,
        "g1_scalar_mul": _g1_mul,
        "g2_scalar_mul": _g2_mul,
        # pure-Fr Lagrange rows at arbitrary points (resharing): cheap
        # enough to sentinel-trace every analysis run
        "lagrange_at": _lag_at,
    }

    def _bind(builder):
        # default (CPU, 24-bit/uint64) geometry
        return lambda: builder(limb.default_fp_ctx(), limb.default_fr_ctx())

    def _bind32(builder):
        # TPU (12-bit/uint32) geometry — the widening check's real target
        return lambda: builder(limb.FP32, limb.FR32)

    for fname, builder in heavy.items():
        register_kernel_family(f"blsops/{fname}", _bind(builder))
    for fname, builder in cheap.items():
        register_kernel_family(
            f"blsops/{fname}", _bind(builder), sentinel=True
        )
    # uint32-geometry sentinels: cheap ladder kernels where an implicit
    # 64-bit promotion would silently wreck TPU throughput
    for fname in (
        "subgroup_g1",
        "g1_scalar_mul",
        "decompress_g1",
        "lagrange_at",
    ):
        register_kernel_family(
            f"blsops32/{fname}", _bind32(cheap[fname]), sentinel=True
        )


_register_engine_families()


# ---------------------------------------------------------------------------
# Device Lagrange coefficients at zero (Fr)
# ---------------------------------------------------------------------------


def _indices_to_fr(fr_ctx: ModCtx, idx):
    """int32 share indices (..., ) -> raw Fr limb arrays.

    Supports indices up to 2^(2*limb_bits) (far beyond any cluster size)."""
    idx = idx.astype(jnp.uint32)
    lo = (idx & np.uint32(fr_ctx.mask)).astype(fr_ctx.dtype)
    hi = (idx >> np.uint32(fr_ctx.limb_bits)).astype(fr_ctx.dtype)
    out = limb.zeros(fr_ctx, idx.shape)
    out = out.at[..., 0].set(lo)
    out = out.at[..., 1].set(hi)
    return out


def lagrange_coeffs_at_zero(fr_ctx: ModCtx, idx, t: int):
    """Batched Lagrange basis at x=0: idx is (..., t) int32 of distinct
    nonzero share indices; returns raw Fr limbs (..., t, n_limbs).

        coeff_j = prod_{m != j} x_m / (x_m - x_j)   (mod r)

    (spec: charon_tpu/crypto/shamir.py:45). t is static and small, so the
    j/m loops unroll; the inversions are one vectorized Fermat chain.
    """
    x_mont = limb.to_mont(fr_ctx, _indices_to_fr(fr_ctx, idx))  # (..., t, L)
    xs = [x_mont[..., j, :] for j in range(t)]
    nums, dens = [], []
    for j in range(t):
        num = None
        den = None
        for m in range(t):
            if m == j:
                continue
            num = xs[m] if num is None else limb.mont_mul(fr_ctx, num, xs[m])
            d = limb.sub_mod(fr_ctx, xs[m], xs[j])
            den = d if den is None else limb.mont_mul(fr_ctx, den, d)
        if num is None:  # t == 1
            num = limb.const(fr_ctx, 1, xs[j].shape[:-1])
            den = limb.const(fr_ctx, 1, xs[j].shape[:-1])
        nums.append(num)
        dens.append(den)
    num = jnp.stack(nums, axis=-2)  # (..., t, L)
    den = jnp.stack(dens, axis=-2)
    coeff = limb.mont_mul(fr_ctx, num, limb.inv_mod(fr_ctx, den))
    return limb.from_mont(fr_ctx, coeff)  # raw, for the bit schedule


def lagrange_coeffs_at(fr_ctx: ModCtx, idx, t: int, xs):
    """Batched Lagrange basis at ARBITRARY evaluation points — the
    resharing generalization of lagrange_coeffs_at_zero (ISSUE 20).

        coeff_j(x) = prod_{m != j} (x - x_m) / (x_j - x_m)   (mod r)

    idx is (..., t) int32 of distinct share indices; xs is (...,) int32
    evaluation points (one per batch lane). Returns raw Fr limbs
    (..., t, n_limbs). At x = 0 this reduces to the zero-point basis
    above (kept as separate code so the blessed duty-path graph is
    untouched)."""
    x_mont = limb.to_mont(fr_ctx, _indices_to_fr(fr_ctx, idx))  # (..., t, L)
    e_mont = limb.to_mont(fr_ctx, _indices_to_fr(fr_ctx, xs))  # (..., L)
    pts = [x_mont[..., j, :] for j in range(t)]
    nums, dens = [], []
    for j in range(t):
        num = None
        den = None
        for m in range(t):
            if m == j:
                continue
            nm = limb.sub_mod(fr_ctx, e_mont, pts[m])
            num = nm if num is None else limb.mont_mul(fr_ctx, num, nm)
            d = limb.sub_mod(fr_ctx, pts[j], pts[m])
            den = d if den is None else limb.mont_mul(fr_ctx, den, d)
        if num is None:  # t == 1
            num = limb.const(fr_ctx, 1, pts[j].shape[:-1])
            den = limb.const(fr_ctx, 1, pts[j].shape[:-1])
        nums.append(num)
        dens.append(den)
    num = jnp.stack(nums, axis=-2)  # (..., t, L)
    den = jnp.stack(dens, axis=-2)
    coeff = limb.mont_mul(fr_ctx, num, limb.inv_mod(fr_ctx, den))
    return limb.from_mont(fr_ctx, coeff)


def _mont_powers(fr_ctx: ModCtx, xs, t: int):
    """int32 evaluation points (...,) -> Montgomery-domain powers
    x^0..x^(t-1), shape (..., t, n_limbs). t is static and small, so the
    chain unrolls into t-1 mont_muls."""
    x = limb.to_mont(fr_ctx, _indices_to_fr(fr_ctx, xs))
    pows = [limb.const(fr_ctx, 1, x.shape[:-1])]
    for _ in range(1, t):
        pows.append(limb.mont_mul(fr_ctx, pows[-1], x))
    return jnp.stack(pows, axis=-2)


# ---------------------------------------------------------------------------
# Raw (already-packed) kernels — jit-compiled once per padded shape
# ---------------------------------------------------------------------------


def clear_kernel_caches() -> None:
    """Drop every cached jitted kernel so the next call RE-TRACES.

    The degradation ladders (bench.py, tbls/tpu_impl.py) flip trace-time
    routing flags (fptower.set_fp2_fusion, limb.set_pallas, limb.set_mxu,
    msm.set_msm); without this, the lru-cached jit wrappers — including
    _threshold_agg_kernel's Straus/per-lane routing — keep returning the
    already-compiled executable and the flag flip never takes effect."""
    import sys

    mod = sys.modules[__name__]
    for name in dir(mod):
        fn = getattr(mod, name)
        if callable(fn) and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    _JIT_KERNELS.clear()  # dropped with their lru entries — don't leak


def threshold_recombine(ctx: ModCtx, fr_ctx: ModCtx, t: int, sig_affine, idx):
    """(V, t) affine G2 share sigs + (V, t) int32 share indices -> [V]
    affine group signatures. THE threshold-recombination routine — the
    single place that decides Straus joint windowed mul (one shared
    doubling chain per validator, ops/msm.py) vs per-lane 255-bit
    double-and-add; both _threshold_agg_kernel and the sharded mesh
    plane (parallel/mesh.py) call it."""
    f = C.g2_ops(ctx)
    coeffs = lagrange_coeffs_at_zero(fr_ctx, idx, t)  # (V, t, L)
    proj = C.affine_to_point(f, sig_affine)
    from charon_tpu.ops import msm as MSM

    if MSM.msm_active():
        total = MSM.windowed_joint_mul(f, fr_ctx, proj, coeffs)
    else:
        scaled = C.point_scalar_mul(f, fr_ctx, proj, coeffs)
        total = C.point_sum(f, scaled, axis=-1)  # reduce the t axis
    return C.point_to_affine(f, total)


@functools.lru_cache(maxsize=None)
def _threshold_agg_kernel(ctx: ModCtx, fr_ctx: ModCtx, t: int):
    return _jit_kernel(
        lambda sig_affine, idx: threshold_recombine(
            ctx, fr_ctx, t, sig_affine, idx
        )
    )


@functools.lru_cache(maxsize=None)
def _verify_kernel(ctx: ModCtx):
    return _jit_kernel(functools.partial(DP.batched_verify, ctx))


@functools.lru_cache(maxsize=None)
def _verify_rlc_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    return _jit_kernel(functools.partial(DP.batched_verify_rlc, ctx, fr_ctx))


@functools.lru_cache(maxsize=None)
def _verify_grouped_rlc_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    return _jit_kernel(
        functools.partial(DP.batched_verify_grouped_rlc, ctx, fr_ctx)
    )


@functools.lru_cache(maxsize=None)
def _aggregate_kernel(ctx: ModCtx, k: int):
    """Sum k G2 points per lane (signature aggregation)."""
    f = C.g2_ops(ctx)

    def kernel(sig_affine):
        proj = C.affine_to_point(f, sig_affine)
        return C.point_to_affine(f, C.point_sum(f, proj, axis=-1))

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _g1_sum_kernel(ctx: ModCtx, k: int):
    f = C.g1_ops(ctx)

    def kernel(pk_affine):
        proj = C.affine_to_point(f, pk_affine)
        return C.point_to_affine(f, C.point_sum(f, proj, axis=-1))

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _subgroup_g2_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    f = C.g2_ops(ctx)

    def kernel(pts, order):
        proj = C.affine_to_point(f, pts)
        rp = C.point_scalar_mul(f, fr_ctx, proj, order)
        return C.point_is_identity(f, rp)

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _subgroup_g1_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    f = C.g1_ops(ctx)

    def kernel(pts, order):
        proj = C.affine_to_point(f, pts)
        rp = C.point_scalar_mul(f, fr_ctx, proj, order)
        return C.point_is_identity(f, rp)

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _decompress_g2_kernel(ctx: ModCtx, fr_ctx: ModCtx, subgroup: bool):
    """Compressed-G2 field work + (optionally) the psi subgroup check in
    ONE program — the decode stage of a flush no longer pays a separate
    subgroup_check_g2_batch dispatch (ISSUE 5)."""
    return _jit_kernel(
        lambda x0, x1, sign, inf, ok: DEC.decompress_g2_graph(
            ctx, fr_ctx, (x0, x1), sign, inf, ok, subgroup=subgroup
        )
    )


@functools.lru_cache(maxsize=None)
def _decompress_g1_kernel(ctx: ModCtx, fr_ctx: ModCtx, subgroup: bool):
    return _jit_kernel(
        lambda x0, sign, inf, ok: DEC.decompress_g1_graph(
            ctx, fr_ctx, x0, sign, inf, ok, subgroup=subgroup
        )
    )


@functools.lru_cache(maxsize=None)
def _hash_to_g2_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    """Device hash-to-curve tail (ISSUE 6): SSWU + 3-isogeny + psi
    cofactor clearing in ONE program — the host ships only the cheap
    SHA-256 hash_to_field outputs (ops/sswu.py)."""
    return _jit_kernel(
        lambda u00, u01, u10, u11, s0, s1: SSWU.hash_to_g2_graph(
            ctx, fr_ctx, (u00, u01), (u10, u11), s0, s1
        )
    )


@functools.lru_cache(maxsize=None)
def _g1_scalar_mul_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    f = C.g1_ops(ctx)

    def kernel(base_affine, scalars):
        proj = C.affine_to_point(f, base_affine)
        return C.point_to_affine(f, C.point_scalar_mul(f, fr_ctx, proj, scalars))

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _g2_scalar_mul_kernel(ctx: ModCtx, fr_ctx: ModCtx):
    f = C.g2_ops(ctx)

    def kernel(base_affine, scalars):
        proj = C.affine_to_point(f, base_affine)
        return C.point_to_affine(f, C.point_scalar_mul(f, fr_ctx, proj, scalars))

    return _jit_kernel(kernel)


# ---------------------------------------------------------------------------
# Ceremony kernels: DKG verification + key resharing (ISSUE 20)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _gen_table_g1(ctx: ModCtx, nbits: int, window: int):
    """Fixed-base window table for the G1 generator: packed affine
    multiples T[win][d] = d * 2^(window*win) * G, computed ONCE on the
    host (public constants). With the table baked into the graph the
    kernel needs zero doublings — one gathered add per window."""
    from charon_tpu.crypto.g1g2 import G1_GEN, g1_add

    n_win = -(-nbits // window)
    flat = []
    base = G1_GEN
    for _ in range(n_win):
        entry = None
        for _d in range(1 << window):
            flat.append(entry)
            entry = g1_add(entry, base)
        for _ in range(window):
            base = g1_add(base, base)
    packed = C.g1_pack(ctx, flat)
    return jax.tree_util.tree_map(
        lambda a: a.reshape(n_win, 1 << window, *a.shape[1:]), packed
    )


@functools.lru_cache(maxsize=None)
def _g1_gen_mul_kernel(ctx: ModCtx, fr_ctx: ModCtx, nbits: int, window: int):
    """Batched fixed-base scalar mul [k_i] G — the DKG share/PoK check
    LHS. Replaces the generic 255-double ladder with table gathers:
    ~nbits/window complete adds per lane, no doublings."""
    f = C.g1_ops(ctx)
    from charon_tpu.ops import msm as MSM

    table = _gen_table_g1(ctx, nbits, window)
    n_win = -(-nbits // window)

    def kernel(scalars):
        digits = MSM._digits(fr_ctx, scalars, nbits, window)  # (N, n_win)
        win = jnp.arange(n_win, dtype=jnp.int32)[None, :]
        sel = jax.tree_util.tree_map(lambda a: a[win, digits], table)
        proj = C.affine_to_point(f, sel)  # batch (N, n_win)
        # reduce the window axis with a lax.scan — ONE add body in the
        # compiled graph instead of n_win-1 unrolled point adds
        from jax import lax

        xs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 1, 0), proj)
        template = jax.tree_util.tree_leaves(proj)[0][:, 0]
        init = jax.tree_util.tree_map(
            lambda a: limb.match_vary(a, template),
            C.point_identity(f, (digits.shape[0],)),
        )
        acc, _ = lax.scan(
            lambda acc, p: (C.point_add(f, acc, p), None), init, xs
        )
        return C.point_to_affine(f, acc)

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _commitment_eval_kernel(
    ctx: ModCtx, fr_ctx: ModCtx, vecs: int, t: int, nbits: int
):
    """Per lane: sum over `vecs` commitment vectors of sum_k C_k x^k —
    the Feldman/FROST commitment-polynomial evaluation that dominates
    ceremony verification. The x^k powers are built in-graph from the
    public int32 evaluation point; routing between Straus joint
    windowed mul (one shared doubling chain over all vecs*t points per
    lane) and per-lane double-and-add is owned by
    core/autotune.KernelConfig via msm.set_ceremony_straus."""
    f = C.g1_ops(ctx)
    from charon_tpu.ops import msm as MSM

    def kernel(commit_affine, xs):
        # commit_affine: affine leaves (N, vecs*t, ...); xs: int32 (N,)
        pows = limb.from_mont(fr_ctx, _mont_powers(fr_ctx, xs, t))
        pows = jnp.tile(pows, (1, vecs, 1))  # (N, vecs*t, L)
        proj = C.affine_to_point(f, commit_affine)
        if MSM.ceremony_straus_active():
            total = MSM.windowed_joint_mul(
                f, fr_ctx, proj, pows, nbits=nbits, window=4
            )
        else:
            scaled = C.point_scalar_mul(f, fr_ctx, proj, pows, nbits=nbits)
            total = C.point_sum(f, scaled, axis=-1)
        return C.point_to_affine(f, total)

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _g1_msm_kernel(ctx: ModCtx, fr_ctx: ModCtx, n_segments: int, nbits: int):
    """Segmented G1 Pippenger MSM over full-width scalars — the reshare
    pubshare recombination sum_i lambda_i m^k D_ik. Window width is the
    autotuned ceremony axis (msm.ceremony_window)."""
    f = C.g1_ops(ctx)
    from charon_tpu.ops import msm as MSM

    def kernel(points_affine, scalars, segment_ids):
        proj = C.affine_to_point(f, points_affine)
        out = MSM.msm_segmented(
            f,
            fr_ctx,
            proj,
            scalars,
            segment_ids,
            n_segments,
            nbits=nbits,
            window=MSM.ceremony_window(),
        )
        return C.point_to_affine(f, out)

    return _jit_kernel(kernel)


@functools.lru_cache(maxsize=None)
def _lagrange_at_kernel(fr_ctx: ModCtx, t: int):
    """Batched Lagrange basis rows at arbitrary evaluation points (pure
    Fr — no curve ops)."""
    return _jit_kernel(
        lambda idx, xs: lagrange_coeffs_at(fr_ctx, idx, t, xs)
    )


# ---------------------------------------------------------------------------
# Host-facing batched operations (Python-int points in, results out)
# ---------------------------------------------------------------------------


class BlsEngine:
    """Batched BLS12-381 engine bound to a limb geometry.

    Host boundary: affine Python-int points in/out (the facade handles
    compressed-bytes conversion and caching). Every method pads its batch
    to a power of two so compiled kernels are reused across calls.
    """

    def __init__(self, ctx: ModCtx | None = None, fr_ctx: ModCtx | None = None):
        self.ctx = ctx or limb.default_fp_ctx()
        self.fr_ctx = fr_ctx or limb.default_fr_ctx()

    # -- verification -----------------------------------------------------

    def verify_batch(self, pks, msg_points, sigs) -> list[bool]:
        """Lane-wise: e(pk_i, H(m)_i) == e(G1, sig_i).

        pks: affine G1 (or None); msg_points: affine G2 hashed messages;
        sigs: affine G2 (or None). Identity-lane semantics are the caller's
        concern (the facade rejects infinite pubkeys up front).
        """
        n = len(pks)
        pad = _next_pow2(n)
        pk = C.g1_pack(self.ctx, list(pks) + [None] * (pad - n))
        msg = C.g2_pack(self.ctx, list(msg_points) + [None] * (pad - n))
        sig = C.g2_pack(self.ctx, list(sigs) + [None] * (pad - n))
        ok = _verify_kernel(self.ctx)(pk, msg, sig)
        return [bool(b) for b in np.asarray(ok)[:n]]

    def verify_batch_rlc(self, pks, msg_points, sigs, rng=None) -> bool:
        """Whole-batch verification by random linear combination (see
        ops/pairing.batched_verify_rlc): one shared final exponentiation,
        2^-64 soundness per call with fresh OS randomness. None lanes
        (identity points) contribute neutrally — the caller tracks their
        validity separately. Returns a single bool; on False the caller
        re-runs verify_batch for per-lane attribution."""
        import random as _random

        rng = rng or _random.SystemRandom()
        n = len(pks)
        pad = _next_pow2(n)
        pk = C.g1_pack(self.ctx, list(pks) + [None] * (pad - n))
        msg = C.g2_pack(self.ctx, list(msg_points) + [None] * (pad - n))
        sig = C.g2_pack(self.ctx, list(sigs) + [None] * (pad - n))
        rand = jnp.asarray(
            limb.ctx_pack(
                self.fr_ctx,
                [rng.randrange(1, 1 << 64) for _ in range(n)]
                + [0] * (pad - n),
            )
        )
        ok = _verify_rlc_kernel(self.ctx, self.fr_ctx)(pk, msg, sig, rand)
        return bool(ok)

    def verify_batch_grouped_rlc(self, groups, rng=None) -> bool:
        """Grouped whole-batch verification
        (ops/pairing.batched_verify_grouped_rlc): `groups` is a list of
        (msg_point, [(pk_point, sig_point), ...]) — one entry per
        DISTINCT message. The Miller stage runs one pair per group plus
        one aggregate pair; per-lane cost is two 64-bit scalar muls.
        Grid dims are padded to powers of two so compiled kernels are
        reused across calls (pad lanes: identity points + zero
        exponents, which contribute neutrally). Returns a single bool."""
        import random as _random

        rng = rng or _random.SystemRandom()
        m = _next_pow2(len(groups))
        k = _next_pow2(max(len(lanes) for _, lanes in groups))
        pk_flat: list = []
        sig_flat: list = []
        rand_ints: list = []
        msg_list: list = []
        for msg_pt, lanes in groups:
            msg_list.append(msg_pt)
            for pk_pt, sig_pt in lanes:
                pk_flat.append(pk_pt)
                sig_flat.append(sig_pt)
                rand_ints.append(rng.randrange(1, 1 << 64))
            pad = k - len(lanes)
            pk_flat.extend([None] * pad)
            sig_flat.extend([None] * pad)
            rand_ints.extend([0] * pad)
        for _ in range(m - len(groups)):  # identity pad groups
            msg_list.append(None)
            pk_flat.extend([None] * k)
            sig_flat.extend([None] * k)
            rand_ints.extend([0] * k)

        def grid(packed):
            return jax.tree_util.tree_map(
                lambda a: a.reshape(m, k, *a.shape[1:]), packed
            )

        pk = grid(C.g1_pack(self.ctx, pk_flat))
        sig = grid(C.g2_pack(self.ctx, sig_flat))
        msg = C.g2_pack(self.ctx, msg_list)
        rand = jnp.asarray(
            np.asarray(limb.ctx_pack(self.fr_ctx, rand_ints)).reshape(
                m, k, -1
            )
        )
        ok = _verify_grouped_rlc_kernel(self.ctx, self.fr_ctx)(
            pk, msg, sig, rand
        )
        return bool(ok)

    # -- threshold recombination -----------------------------------------

    def threshold_aggregate_batch(self, partials: list[dict]) -> list:
        """Each entry maps share index -> affine G2 partial signature; all
        entries must share the same threshold t = len(dict). Returns the
        recombined affine G2 group signature per entry
        (spec: crypto/shamir.py:68; ref: tbls/herumi.go:249)."""
        if not partials:
            return []
        t = len(partials[0])
        if any(len(p) != t for p in partials):
            raise ValueError("all entries must have the same threshold")
        v = len(partials)
        pad = _next_pow2(v)
        idx = np.ones((pad, t), np.int32)
        idx[:, :] = np.arange(1, t + 1, dtype=np.int32)  # benign pad rows
        flat_sigs = []
        for row, p in enumerate(partials):
            items = sorted(p.items())
            idx[row] = [i for i, _ in items]
            flat_sigs.extend(s for _, s in items)
        flat_sigs.extend([None] * ((pad - v) * t))
        sig = C.g2_pack(self.ctx, flat_sigs)
        sig = jax.tree_util.tree_map(
            lambda a: a.reshape(pad, t, *a.shape[1:]), sig
        )
        out = _threshold_agg_kernel(self.ctx, self.fr_ctx, t)(
            sig, jnp.asarray(idx)
        )
        return C.g2_unpack(self.ctx, out)[:v]

    # -- plain aggregation (point addition) ------------------------------

    def aggregate_sigs_batch(self, groups: list[list]) -> list:
        """Sum each group of affine G2 signatures (ref: tbls/herumi.go:225
        Aggregate). Groups are padded to a common length with identities."""
        if not groups:
            return []
        k = max(len(g) for g in groups)
        v = len(groups)
        pad = _next_pow2(v)
        flat = []
        for g in groups:
            flat.extend(g)
            flat.extend([None] * (k - len(g)))
        flat.extend([None] * ((pad - v) * k))
        sig = C.g2_pack(self.ctx, flat)
        sig = jax.tree_util.tree_map(
            lambda a: a.reshape(pad, k, *a.shape[1:]), sig
        )
        out = _aggregate_kernel(self.ctx, k)(sig)
        return C.g2_unpack(self.ctx, out)[:v]

    def aggregate_pks_batch(self, groups: list[list]) -> list:
        """Sum each group of affine G1 pubkeys (FastAggregateVerify input)."""
        if not groups:
            return []
        k = max(len(g) for g in groups)
        v = len(groups)
        pad = _next_pow2(v)
        flat = []
        for g in groups:
            flat.extend(g)
            flat.extend([None] * (k - len(g)))
        flat.extend([None] * ((pad - v) * k))
        pk = C.g1_pack(self.ctx, flat)
        pk = jax.tree_util.tree_map(
            lambda a: a.reshape(pad, k, *a.shape[1:]), pk
        )
        out = _g1_sum_kernel(self.ctx, k)(pk)
        return C.g1_unpack(self.ctx, out)[:v]

    # -- subgroup membership ---------------------------------------------

    def subgroup_check_g2_batch(self, points) -> list[bool]:
        """[r]P == identity for decompressed (on-curve) G2 points — the
        prime-order subgroup check eth2 mandates before pairing. None lanes
        (identities) pass. Batched 255-bit ladder, one device call."""
        n = len(points)
        if n == 0:
            return []
        pad = _next_pow2(n)
        pts = C.g2_pack(self.ctx, list(points) + [None] * (pad - n))
        # Raw (unreduced!) group order as the ladder schedule.
        order = jnp.asarray(
            limb.ctx_pack(self.fr_ctx, [self.fr_ctx.modulus] * pad)
        )
        mask = _subgroup_g2_kernel(self.ctx, self.fr_ctx)(pts, order)
        return [bool(b) for b in np.asarray(mask)[:n]]

    def subgroup_check_g1_batch(self, points) -> list[bool]:
        n = len(points)
        if n == 0:
            return []
        pad = _next_pow2(n)
        pts = C.g1_pack(self.ctx, list(points) + [None] * (pad - n))
        order = jnp.asarray(
            limb.ctx_pack(self.fr_ctx, [self.fr_ctx.modulus] * pad)
        )
        mask = _subgroup_g1_kernel(self.ctx, self.fr_ctx)(pts, order)
        return [bool(b) for b in np.asarray(mask)[:n]]

    # -- batched point decompression -------------------------------------

    def decompress_g2_batch(self, encoded, subgroup_check: bool = True):
        """Compressed 96-byte G2 lanes -> ([affine point | None],
        [valid]) with the field work (sqrt, sign, on-curve, psi subgroup
        check) batched on device. Accepts raw bytes or pre-parsed
        decompress.ParsedPoint lanes. Per-lane semantics, never raises:
        valid=True with point=None is a well-formed infinity; valid=False
        covers malformed flags, x >= p, non-residue x and (when
        `subgroup_check`) non-subgroup points."""
        parsed = [
            p if isinstance(p, DEC.ParsedPoint) else DEC.parse_g2_lane(p)
            for p in encoded
        ]
        n = len(parsed)
        if n == 0:
            return [], []
        pad = bucket_lanes(n)
        parsed = parsed + [parsed[0]] * (pad - n)
        arrays = DEC.pack_parsed_g2(self.ctx, parsed)
        aff, valid = _decompress_g2_kernel(
            self.ctx, self.fr_ctx, subgroup_check
        )(*arrays)
        pts = C.g2_unpack(self.ctx, aff)[:n]
        return pts, [bool(b) for b in np.asarray(valid)[:n]]

    def decompress_g1_batch(self, encoded, subgroup_check: bool = True):
        """Compressed 48-byte G1 lanes -> ([affine point | None],
        [valid]); see decompress_g2_batch for the mask contract."""
        parsed = [
            p if isinstance(p, DEC.ParsedPoint) else DEC.parse_g1_lane(p)
            for p in encoded
        ]
        n = len(parsed)
        if n == 0:
            return [], []
        pad = bucket_lanes(n)
        parsed = parsed + [parsed[0]] * (pad - n)
        arrays = DEC.pack_parsed_g1(self.ctx, parsed)
        aff, valid = _decompress_g1_kernel(
            self.ctx, self.fr_ctx, subgroup_check
        )(*arrays)
        pts = C.g1_unpack(self.ctx, aff)[:n]
        return pts, [bool(b) for b in np.asarray(valid)[:n]]

    # -- batched hash-to-curve -------------------------------------------

    def hash_to_g2_batch(self, msgs, dst: bytes = SSWU.DST_POP):
        """Messages (raw bytes, or pre-hashed sswu.HashedMsg lanes) ->
        ([affine G2 point], [valid]) with the field work (SSWU +
        isogeny + psi cofactor clearing) batched on device; the host
        pays only expand_message_xmd/hash_to_field (SHA-256). The bulk
        cache warm-up path (ISSUE 6): a restart replays its message
        set through here instead of per-point python hash_to_curve.
        valid is always True for real lanes — carried per-lane so a
        degraded batch masks instead of raising."""
        lanes = [
            m if isinstance(m, SSWU.HashedMsg) else SSWU.hash_to_field_lane(m, dst)
            for m in msgs
        ]
        n = len(lanes)
        if n == 0:
            return [], []
        pad = bucket_lanes(n)
        lanes = lanes + [lanes[0]] * (pad - n)
        arrays = SSWU.pack_hashed(self.ctx, lanes)
        aff, valid = _hash_to_g2_kernel(self.ctx, self.fr_ctx)(*arrays)
        pts = C.g2_unpack(self.ctx, aff)[:n]
        return pts, [bool(b) for b in np.asarray(valid)[:n]]

    # -- scalar multiplication (DKG / key derivation) --------------------

    def g1_scalar_mul_batch(self, bases, scalars: list[int]) -> list:
        """[k_i] P_i over G1 — the DKG verification workhorse
        (ref: dkg/frost.go public-share checks)."""
        n = len(bases)
        pad = _next_pow2(n)
        base = C.g1_pack(self.ctx, list(bases) + [None] * (pad - n))
        s = C.fr_pack(self.fr_ctx, list(scalars) + [0] * (pad - n))
        out = _g1_scalar_mul_kernel(self.ctx, self.fr_ctx)(base, s)
        return C.g1_unpack(self.ctx, out)[:n]

    def g2_scalar_mul_batch(self, bases, scalars: list[int]) -> list:
        n = len(bases)
        pad = _next_pow2(n)
        base = C.g2_pack(self.ctx, list(bases) + [None] * (pad - n))
        s = C.fr_pack(self.fr_ctx, list(scalars) + [0] * (pad - n))
        out = _g2_scalar_mul_kernel(self.ctx, self.fr_ctx)(base, s)
        return C.g2_unpack(self.ctx, out)[:n]

    # -- ceremony kernels (DKG verification + resharing, ISSUE 20) -------

    @staticmethod
    def _eval_nbits(t: int, xs) -> int:
        """Tight-but-bucketed bit schedule for x^k powers: the raw values
        are bounded by max(x)^(t-1), so small evaluation points (share
        indices) need nowhere near 255 bits. Bucketing to a short ladder
        keeps the compiled-variant count bounded."""
        mx = max((int(x) for x in xs), default=1)
        need = max(1, t - 1) * max(1, mx.bit_length()) + 1
        for cand in (32, 64, 128):
            if need <= cand:
                return cand
        return 255

    def g1_gen_mul_batch(self, scalars: list[int]) -> list:
        """[k_i] G over G1 via the fixed-base window table — the DKG
        share/PoK verification LHS (public derived points; the scalar
        inputs are shares the CALLER owns — they ride the device only as
        packed limbs and come back as public curve points)."""
        n = len(scalars)
        if n == 0:
            return []
        pad = bucket_lanes(n)
        s = C.fr_pack(self.fr_ctx, list(scalars) + [0] * (pad - n))
        out = _g1_gen_mul_kernel(self.ctx, self.fr_ctx, 255, 4)(s)
        return C.g1_unpack(self.ctx, out)[:n]

    def commitment_eval_batch(self, commit_rows, xs: list[int], t: int) -> list:
        """Evaluate commitment polynomials at public points, one lane per
        row: row i is a flat tuple of vecs*t affine G1 commitments (vecs
        concatenated degree-(t-1) vectors) and the result is
        sum_vec sum_k C_k * xs[i]^k. THE ceremony-verification bulk."""
        n = len(commit_rows)
        if n == 0:
            return []
        width = len(commit_rows[0])
        if width % t or any(len(r) != width for r in commit_rows):
            raise ValueError("commitment rows must share one vecs*t width")
        vecs = width // t
        pad = bucket_lanes(n)
        flat: list = []
        for row in commit_rows:
            flat.extend(row)
        flat.extend([None] * ((pad - n) * width))
        commits = C.g1_pack(self.ctx, flat)
        commits = jax.tree_util.tree_map(
            lambda a: a.reshape(pad, width, *a.shape[1:]), commits
        )
        xs_arr = jnp.asarray(
            np.asarray(list(xs) + [0] * (pad - n), np.int32)
        )
        nbits = self._eval_nbits(t, xs)
        out = _commitment_eval_kernel(self.ctx, self.fr_ctx, vecs, t, nbits)(
            commits, xs_arr
        )
        return C.g1_unpack(self.ctx, out)[:n]

    def g1_msm_batch(
        self, points, scalars: list[int], segment_ids: list[int], n_segments: int
    ) -> list:
        """Segmented multi-scalar multiplication over G1 with full-width
        scalars: out[s] = sum_{i: seg[i]==s} scalars[i] * points[i] — the
        reshare pubshare recombination shape (Pippenger)."""
        if n_segments <= 0:
            return []
        n = len(points)
        seg_pad = _next_pow2(n_segments)
        pad = bucket_lanes(max(n, 1))
        pts = C.g1_pack(self.ctx, list(points) + [None] * (pad - n))
        s = C.fr_pack(self.fr_ctx, list(scalars) + [0] * (pad - n))
        seg = jnp.asarray(
            np.asarray(list(segment_ids) + [0] * (pad - n), np.int32)
        )
        out = _g1_msm_kernel(self.ctx, self.fr_ctx, seg_pad, 255)(pts, s, seg)
        return C.g1_unpack(self.ctx, out)[:n_segments]

    def lagrange_coeffs_batch(
        self, idx_rows, xs: list[int]
    ) -> list[list[int]]:
        """Lagrange basis rows at arbitrary evaluation points: row i is a
        list of distinct share indices, xs[i] the evaluation point;
        returns the matching coefficient rows as Python ints (public
        values — functions of public indices only)."""
        n = len(idx_rows)
        if n == 0:
            return []
        t = len(idx_rows[0])
        if any(len(r) != t for r in idx_rows):
            raise ValueError("index rows must share one width")
        pad = bucket_lanes(n)
        benign = list(range(1, t + 1))
        idx = np.asarray(
            [list(r) for r in idx_rows] + [benign] * (pad - n), np.int32
        )
        xs_arr = jnp.asarray(
            np.asarray(list(xs) + [0] * (pad - n), np.int32)
        )
        out = _lagrange_at_kernel(self.fr_ctx, t)(jnp.asarray(idx), xs_arr)
        flat = limb.ctx_unpack(self.fr_ctx, np.asarray(out).reshape(pad * t, -1))
        return [flat[i * t : (i + 1) * t] for i in range(n)]


@functools.lru_cache(maxsize=None)
def default_engine() -> BlsEngine:
    return BlsEngine()
