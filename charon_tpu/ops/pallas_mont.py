"""Fused Montgomery multiplication as a Pallas TPU kernel.

The jnp/XLA path in ops/limb.py expresses each of mont_mul's three limb
convolutions as gather + broadcast-multiply + einsum, which materializes a
(batch, n_limbs, 2*n_limbs) band tensor in HBM per convolution — measured
HBM-bound on v5e (throughput flat in batch size). This kernel fuses the
WHOLE mont_mul (schoolbook product, Montgomery folding, parallel carry
normalization, conditional subtract) into one VMEM-resident program per
batch tile: HBM traffic drops to read a, read b, write out.

Geometry: the TPU limb layout (12-bit limbs in uint32, 32 limbs for Fp,
22 for Fr — ops/limb.py FP32/FR32). The kernel is generic over the
modulus via embedded per-ctx constants, mirrors limb.mont_mul's algorithm
step for step, and is validated against it by tests/test_pallas_mont.py
(interpret mode on CPU; bit-exact on device).

Replaces (batched, fused) the role of herumi's asm field multiply
(ref: tbls/herumi.go links the C++/asm backend one call at a time).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from charon_tpu.ops.limb import ModCtx, _r_minus_m, int_to_limbs


# batch rows per grid step — (8, 128) native tiles; 256 rows x 64 cols
# of u32 = 64 KiB per scratch-sized value, far under ~16 MiB VMEM.
TILE = 256


def _shift_pass(t, nbits: int, mask):
    """One elementwise carry pass over the limb axis (cols). Returns the
    new limbs and the (rows, 1) carry out of the top limb — the final
    normalize's overflow detection needs every dropped top carry, exactly
    like limb._normalize sums them."""
    width = t.shape[1]
    carry = t >> nbits
    shifted = jnp.concatenate(
        [jnp.zeros_like(carry[:, :1]), carry[:, : width - 1]], axis=1
    )
    return (t & mask) + shifted, carry[:, width - 1 : width]


def _kogge(t, nbits: int, mask, width: int):
    """Kogge-Stone resolve of limbs in [0, 2^(nbits+1)); returns
    (canonical_limbs, carry_out as (rows, 1) u32 in {0, 1}).

    Entirely bool-free: Mosaic mis-lowers i1 vector casts, so generate/
    propagate flags are u32 0/1 values — g comes straight from the top
    bit (inputs are < 2^(nbits+1)), p from an arithmetic carry trick
    (((t & mask) + 1) >> nbits == 1 iff the limb is all-ones), and the
    combine uses bitwise | and & which are exact on 0/1 values."""
    g = t >> nbits  # in {0, 1} for inputs < 2^(nbits+1)
    p = ((t & mask) + jnp.uint32(1)) >> nbits  # 1 iff limb == mask
    shift = 1
    while shift < width:
        g_prev = jnp.concatenate(
            [jnp.zeros_like(g[:, :shift]), g[:, : width - shift]], axis=1
        )
        p_prev = jnp.concatenate(
            [jnp.zeros_like(p[:, :shift]), p[:, : width - shift]], axis=1
        )
        g = g | (p & g_prev)
        p = p & p_prev
        shift *= 2
    c_in = jnp.concatenate(
        [jnp.zeros_like(g[:, :1]), g[:, : width - 1]], axis=1
    )
    out = (t + c_in) & mask
    return out, g[:, width - 1 : width]


def _normalize(t, nbits: int, mask, width: int):
    """Canonicalize; returns (limbs, total_carry_out as (rows, 1) u32)."""
    t, c1 = _shift_pass(t, nbits, mask)
    t, c2 = _shift_pass(t, nbits, mask)
    t, c3 = _shift_pass(t, nbits, mask)
    out, g_top = _kogge(t, nbits, mask, width)
    return out, c1 + c2 + c3 + g_top


def _conv_into(acc, a, b_row, n: int, out_cols: int):
    """acc[:, i+j] += a[:, i] * b_row[j] — unrolled over i; each partial
    product is statically padded into place (pure adds, no scatters —
    scatters would leave VMEM/registers)."""
    rows = a.shape[0]
    for i in range(n):
        width = min(n, out_cols - i)
        if width <= 0:
            break
        contrib = a[:, i : i + 1] * b_row[:, :width]
        parts = []
        if i:
            parts.append(jnp.zeros((rows, i), jnp.uint32))
        parts.append(contrib)
        if out_cols - i - width:
            parts.append(jnp.zeros((rows, out_cols - i - width), jnp.uint32))
        acc = acc + (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        )
    return acc


def _flag01(carry):
    """Collapse a small (<8) carry count to a 0/1 u32 flag — arithmetic
    select helper (no i1 vectors, no unsigned-min: both mis-lower in
    Mosaic)."""
    return (carry | (carry >> 1) | (carry >> 2)) & jnp.uint32(1)


@dataclass(frozen=True)
class _K:
    """Per-kernel constant bundle (everything the VMEM helpers need)."""

    n: int
    nbits: int
    mask: jnp.ndarray
    ninv: jnp.ndarray  # (1, n)
    p_row: jnp.ndarray  # (1, n)
    rm2n: jnp.ndarray  # (1, 2n): R - p in the high half
    rm_n: jnp.ndarray  # (1, n): R - p (R = 2^(nbits*n))
    one0: jnp.ndarray  # (1, n): one-hot limb 0


def _unpack_consts(ctx: ModCtx, consts_ref) -> _K:
    """consts_ref rows: 0 = ninv, 1 = p; 2..3 = R - p shifted into the
    high half (2n cols packed as two n-col rows; row 3 alone is the
    n-col R - p); 4 = one-hot limb 0."""
    return _K(
        n=ctx.n_limbs,
        nbits=ctx.limb_bits,
        mask=jnp.uint32((1 << ctx.limb_bits) - 1),
        ninv=consts_ref[0:1, :],
        p_row=consts_ref[1:2, :],
        rm2n=jnp.concatenate([consts_ref[2:3, :], consts_ref[3:4, :]], axis=1),
        rm_n=consts_ref[3:4, :],
        one0=consts_ref[4:5, :],
    )


def _conv_const_mxu(a, T0, T1):
    """conv(a, c) with the constant given as 6-bit Toeplitz pieces: the
    shared four-int8-matmul recombination (ops/limb_mxu.conv_const_mxu),
    here fed VMEM ref loads so the systolic array does the constant
    convolutions while the band intermediates never touch HBM."""
    from charon_tpu.ops.limb_mxu import conv_const_mxu

    return conv_const_mxu(a, T0, T1)


def _mont_core_mxu(k: _K, a, b, nT0, nT1, pT0, pT1):
    """_mont_core with the two constant-operand convolutions (t * ninv
    mod R and m * p) on the MXU. The data-dependent a * b product keeps
    the VPU unrolled conv — no constant matrix to feed the MXU with.
    Value ranges match the VPU path: every recombined column < 2^30
    (32 terms x 63^2 per 6-bit partial), inside what _normalize's three
    shift passes + Kogge resolve are built for."""
    rows = a.shape[0]
    n, nbits, mask = k.n, k.nbits, k.mask

    t = jnp.zeros((rows, 2 * n), jnp.uint32)
    t = _conv_into(t, a, b, n, 2 * n)
    t, _ = _normalize(t, nbits, mask, 2 * n)

    m = _conv_const_mxu(t[:, :n], nT0, nT1)
    m, _ = _normalize(m, nbits, mask, n)  # mod R: top carry dropped

    s = t + _conv_const_mxu(m, pT0, pT1)
    s2 = s + k.rm2n
    out1, _ = _normalize(s, nbits, mask, 2 * n)
    out2, carry2 = _normalize(s2, nbits, mask, 2 * n)
    flag = _flag01(carry2)
    hi1 = out1[:, n:]
    hi2 = out2[:, n:]
    return hi1 + (hi2 - hi1) * flag


def _mont_core(k: _K, a, b):
    """Full Montgomery multiply in VMEM: canonical n-limb result
    (mirrors limb.mont_mul's separated-operand algorithm step for step)."""
    rows = a.shape[0]
    n, nbits, mask = k.n, k.nbits, k.mask

    # 1. t = a * b over 2n columns
    t = jnp.zeros((rows, 2 * n), jnp.uint32)
    t = _conv_into(t, a, b, n, 2 * n)
    t, _ = _normalize(t, nbits, mask, 2 * n)

    # 2. m = (t mod R) * (-p^-1 mod R) mod R
    m = jnp.zeros((rows, n), jnp.uint32)
    m = _conv_into(m, t[:, :n], jnp.broadcast_to(k.ninv, (rows, n)), n, n)
    m, _ = _normalize(m, nbits, mask, n)

    # 3. s = t + m * p; final normalize fused with the conditional
    # subtract: lane2 adds (R - p) into the high columns, carry-out of
    # lane2 says hi >= p
    s = _conv_into(t, m, jnp.broadcast_to(k.p_row, (rows, n)), n, 2 * n)
    s2 = s + k.rm2n

    out1, _ = _normalize(s, nbits, mask, 2 * n)
    out2, carry2 = _normalize(s2, nbits, mask, 2 * n)
    flag = _flag01(carry2)
    hi1 = out1[:, n:]
    hi2 = out2[:, n:]
    return hi1 + (hi2 - hi1) * flag


def _mod_add(k: _K, x, y):
    """x + y mod p in VMEM (canonical inputs): raw lane + (R - p)
    adjustment lane, select on the adjusted lane's carry-out — the same
    trick as limb.addsub_mod_many."""
    s = x + y
    out1, _ = _normalize(s, k.nbits, k.mask, k.n)
    out2, c2 = _normalize(s + k.rm_n, k.nbits, k.mask, k.n)
    flag = _flag01(c2)
    return out1 + (out2 - out1) * flag


def _mod_sub(k: _K, x, y):
    """x - y mod p in VMEM: z = x + (R - 1 - y) + 1; carry-out of z says
    x >= y (take z), else take z + p."""
    z = x + (k.mask - y) + k.one0
    out1, c1 = _normalize(z, k.nbits, k.mask, k.n)
    out2, _ = _normalize(z + k.p_row, k.nbits, k.mask, k.n)
    flag = _flag01(c1)
    return out2 + (out1 - out2) * flag


def _fp2_mul_math(k: _K, mont, a0, a1, b0, b1):
    """Karatsuba Fp2 multiply on VMEM values: c0 = a0 b0 - a1 b1,
    c1 = (a0+a1)(b0+b1) - a0 b0 - a1 b1. `mont` is the Montgomery core
    (VPU or MXU-assisted)."""
    ta = _mod_add(k, a0, a1)
    tb = _mod_add(k, b0, b1)
    v0 = mont(a0, b0)
    v1 = mont(a1, b1)
    s = mont(ta, tb)
    return _mod_sub(k, v0, v1), _mod_sub(k, s, _mod_add(k, v0, v1))


def _fp2_sqr_math(k: _K, mont, a0, a1):
    """Fused Fp2 square: c0 = (a0+a1)(a0-a1), c1 = 2 a0 a1."""
    ta = _mod_add(k, a0, a1)
    ts = _mod_sub(k, a0, a1)
    c0 = mont(ta, ts)
    w = mont(a0, a1)
    return c0, _mod_add(k, w, w)


def _mont_kernel_body(ctx: ModCtx, a_ref, b_ref, consts_ref, out_ref):
    k = _unpack_consts(ctx, consts_ref)
    out_ref[:] = _mont_core(k, a_ref[:], b_ref[:])


def _mont_mxu_kernel_body(
    ctx: ModCtx, a_ref, b_ref, nT0, nT1, pT0, pT1, consts_ref, out_ref
):
    k = _unpack_consts(ctx, consts_ref)
    out_ref[:] = _mont_core_mxu(
        k, a_ref[:], b_ref[:], nT0[:], nT1[:], pT0[:], pT1[:]
    )


def _fp2_mul_kernel_body(
    ctx: ModCtx, a0_ref, a1_ref, b0_ref, b1_ref, consts_ref, c0_ref, c1_ref
):
    """Whole Karatsuba Fp2 multiply fused in VMEM: the prep sums, three
    Montgomery multiplies, and the recombination never touch HBM.

    This is the Miller loop's dominant op (~90% of pairing field work);
    the unfused path round-trips HBM between every stacked normalize and
    mont_mul (PERF.md 'Where the remaining gap is')."""
    k = _unpack_consts(ctx, consts_ref)
    mont = functools.partial(_mont_core, k)
    c0_ref[:], c1_ref[:] = _fp2_mul_math(
        k, mont, a0_ref[:], a1_ref[:], b0_ref[:], b1_ref[:]
    )


def _fp2_mul_mxu_kernel_body(
    ctx: ModCtx,
    a0_ref,
    a1_ref,
    b0_ref,
    b1_ref,
    nT0,
    nT1,
    pT0,
    pT1,
    consts_ref,
    c0_ref,
    c1_ref,
):
    """Fused Fp2 multiply with the constant convolutions of all three
    inner Montgomery multiplies on the MXU — the int8 pieces never leave
    VMEM (PERF.md int8-MXU lever, fold-into-Pallas step)."""
    k = _unpack_consts(ctx, consts_ref)
    mont = lambda x, y: _mont_core_mxu(  # noqa: E731
        k, x, y, nT0[:], nT1[:], pT0[:], pT1[:]
    )
    c0_ref[:], c1_ref[:] = _fp2_mul_math(
        k, mont, a0_ref[:], a1_ref[:], b0_ref[:], b1_ref[:]
    )


def _fp2_sqr_kernel_body(
    ctx: ModCtx, a0_ref, a1_ref, consts_ref, c0_ref, c1_ref
):
    """Fused Fp2 square — two Montgomery multiplies, all in VMEM."""
    k = _unpack_consts(ctx, consts_ref)
    mont = functools.partial(_mont_core, k)
    c0_ref[:], c1_ref[:] = _fp2_sqr_math(k, mont, a0_ref[:], a1_ref[:])


def _fp2_sqr_mxu_kernel_body(
    ctx: ModCtx, a0_ref, a1_ref, nT0, nT1, pT0, pT1, consts_ref, c0_ref, c1_ref
):
    k = _unpack_consts(ctx, consts_ref)
    mont = lambda x, y: _mont_core_mxu(  # noqa: E731
        k, x, y, nT0[:], nT1[:], pT0[:], pT1[:]
    )
    c0_ref[:], c1_ref[:] = _fp2_sqr_math(k, mont, a0_ref[:], a1_ref[:])


@functools.lru_cache(maxsize=None)
def _ctx_consts(ctx: ModCtx) -> np.ndarray:
    """(5, n) constant rows: ninv, p, (R-p) low half, (R-p) high half,
    one-hot limb 0 — rows 2..3 concatenate to the 2n-col adjustment lane
    (row 3 alone is the n-col R - p used by the mod-add helper)."""
    n = ctx.n_limbs
    out = np.zeros((5, n), np.uint32)
    out[0] = np.asarray(ctx.ninv, np.uint32)
    out[1] = np.asarray(ctx.limbs, np.uint32)
    rm2n = np.zeros(2 * n, np.uint32)
    rm2n[n:] = np.asarray(_r_minus_m(ctx), np.uint32)
    out[2] = rm2n[:n]
    out[3] = rm2n[n:]
    out[4, 0] = 1
    return out


@functools.lru_cache(maxsize=None)
def _toeplitz_consts(ctx: ModCtx):
    """int8 Toeplitz piece matrices for the two constant convolutions
    (shared geometry with ops/limb_mxu.py): (nT0, nT1) [n, n] for
    -m^-1 mod R, (pT0, pT1) [n, 2n] for the modulus."""
    from charon_tpu.ops.limb_mxu import _modulus_toeplitz, _ninv_toeplitz

    nT0, nT1 = _ninv_toeplitz(ctx)
    pT0, pT1 = _modulus_toeplitz(ctx)
    return nT0, nT1, pT0, pT1


def _mxu_usable(ctx: ModCtx) -> bool:
    return ctx.limb_bits == 12 and ctx.np_dtype is np.uint32


@functools.lru_cache(maxsize=None)
def _mont_call(ctx: ModCtx, interpret: bool, mxu: bool = False):
    """Gridless pallas_call over one (TILE, n_limbs) block. Batches
    larger than TILE run it under lax.map — Mosaic on this platform
    fails to legalize block index maps (i64 returns), and a device-side
    map over a fixed-shape kernel compiles the kernel exactly once
    anyway."""
    n = ctx.n_limbs
    body = _mont_mxu_kernel_body if mxu else _mont_kernel_body
    n_in = 7 if mxu else 3
    kernel = functools.partial(body, ctx)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((TILE, n), jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_in,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _fp2_call(ctx: ModCtx, kind: str, interpret: bool, mxu: bool = False):
    """Gridless pallas_call for the fused Fp2 kernels (same lax.map
    chunking strategy as the mont kernel)."""
    n = ctx.n_limbs
    out_shape = (
        jax.ShapeDtypeStruct((TILE, n), jnp.uint32),
        jax.ShapeDtypeStruct((TILE, n), jnp.uint32),
    )
    if kind == "mul":
        body = _fp2_mul_mxu_kernel_body if mxu else _fp2_mul_kernel_body
        n_in = 5 + (4 if mxu else 0)
    else:
        body = _fp2_sqr_mxu_kernel_body if mxu else _fp2_sqr_kernel_body
        n_in = 3 + (4 if mxu else 0)
    return pl.pallas_call(
        functools.partial(body, ctx),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_in,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )


def _resolve_mxu(ctx: ModCtx, mxu: bool | None) -> bool:
    """None = follow limb's MXU dispatch mode (limb.set_mxu, owned at
    startup by core/autotune.KernelConfig); True/False = forced for
    this call."""
    if mxu is None:
        from charon_tpu.ops import limb as _limb

        mxu = _limb._mxu_active(ctx)
    return bool(mxu) and _mxu_usable(ctx)


def _mxu_extras(ctx: ModCtx, mxu: bool) -> tuple:
    if not mxu:
        return ()
    return tuple(jnp.asarray(T) for T in _toeplitz_consts(ctx))


def _run_fp2(
    ctx: ModCtx, kind: str, operands, interpret: bool, mxu: bool | None
):
    """Flatten/pad a list of (..., n) operand arrays to TILE-row chunks
    and run the fused kernel; returns the two (..., n) outputs."""
    if ctx.np_dtype is not np.uint32:
        raise ValueError("pallas fp2 kernels require the uint32 limb geometry")
    mxu = _resolve_mxu(ctx, mxu)
    operands = jnp.broadcast_arrays(*operands)
    batch_shape = operands[0].shape[:-1]
    n = ctx.n_limbs
    flats = [o.reshape(-1, n) for o in operands]
    rows = flats[0].shape[0]
    padded = -(-rows // TILE) * TILE
    if padded != rows:
        flats = [jnp.pad(f, ((0, padded - rows), (0, 0))) for f in flats]
    extras = _mxu_extras(ctx, mxu)
    consts = jnp.asarray(_ctx_consts(ctx))
    call = _fp2_call(ctx, kind, interpret, mxu)
    if padded == TILE:
        c0, c1 = call(*flats, *extras, consts)
    else:
        chunks = padded // TILE
        c0, c1 = jax.lax.map(
            lambda xs: call(*xs, *extras, consts),
            tuple(f.reshape(chunks, TILE, n) for f in flats),
        )
        c0 = c0.reshape(padded, n)
        c1 = c1.reshape(padded, n)
    return (
        c0[:rows].reshape(*batch_shape, n),
        c1[:rows].reshape(*batch_shape, n),
    )


def fp2_mul_pallas(
    ctx: ModCtx, a, b, interpret: bool = False, mxu: bool | None = None
):
    """Fused Fp2 Karatsuba multiply: a, b are (c0, c1) tuples of reduced
    Montgomery limb arrays; returns the product tuple. Drop-in for
    ops/fptower.fp2_mul on the uint32 geometry."""
    return _run_fp2(ctx, "mul", (a[0], a[1], b[0], b[1]), interpret, mxu)


def fp2_sqr_pallas(
    ctx: ModCtx, a, interpret: bool = False, mxu: bool | None = None
):
    """Fused Fp2 square; drop-in for ops/fptower.fp2_sqr."""
    return _run_fp2(ctx, "sqr", (a[0], a[1]), interpret, mxu)


def mont_mul_pallas(
    ctx: ModCtx, a, b, interpret: bool = False, mxu: bool | None = None
):
    """Drop-in for limb.mont_mul on the uint32 geometry: reduced
    Montgomery-form inputs with arbitrary broadcastable batch dims."""
    if ctx.np_dtype is not np.uint32:
        raise ValueError("pallas mont_mul requires the uint32 limb geometry")
    mxu = _resolve_mxu(ctx, mxu)
    a, b = jnp.broadcast_arrays(a, b)
    batch_shape = a.shape[:-1]
    n = ctx.n_limbs
    flat_a = a.reshape(-1, n)
    flat_b = b.reshape(-1, n)
    rows = flat_a.shape[0]
    padded = -(-rows // TILE) * TILE
    if padded != rows:
        pad = ((0, padded - rows), (0, 0))
        flat_a = jnp.pad(flat_a, pad)
        flat_b = jnp.pad(flat_b, pad)
    extras = _mxu_extras(ctx, mxu)
    consts = jnp.asarray(_ctx_consts(ctx))
    call = _mont_call(ctx, interpret, mxu)
    if padded == TILE:
        out = call(flat_a, flat_b, *extras, consts)
    else:
        chunks = padded // TILE
        out = jax.lax.map(
            lambda ab: call(ab[0], ab[1], *extras, consts),
            (
                flat_a.reshape(chunks, TILE, n),
                flat_b.reshape(chunks, TILE, n),
            ),
        ).reshape(padded, n)
    return out[:rows].reshape(*batch_shape, n)
