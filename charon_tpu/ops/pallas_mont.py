"""Fused Montgomery multiplication as a Pallas TPU kernel.

The jnp/XLA path in ops/limb.py expresses each of mont_mul's three limb
convolutions as gather + broadcast-multiply + einsum, which materializes a
(batch, n_limbs, 2*n_limbs) band tensor in HBM per convolution — measured
HBM-bound on v5e (throughput flat in batch size). This kernel fuses the
WHOLE mont_mul (schoolbook product, Montgomery folding, parallel carry
normalization, conditional subtract) into one VMEM-resident program per
batch tile: HBM traffic drops to read a, read b, write out.

Geometry: the TPU limb layout (12-bit limbs in uint32, 32 limbs for Fp,
22 for Fr — ops/limb.py FP32/FR32). The kernel is generic over the
modulus via embedded per-ctx constants, mirrors limb.mont_mul's algorithm
step for step, and is validated against it by tests/test_pallas_mont.py
(interpret mode on CPU; bit-exact on device).

Replaces (batched, fused) the role of herumi's asm field multiply
(ref: tbls/herumi.go links the C++/asm backend one call at a time).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from charon_tpu.ops.limb import ModCtx, _r_minus_m, int_to_limbs

# batch rows per grid step — (8, 128) native tiles; 256 rows x 64 cols
# of u32 = 64 KiB per scratch-sized value, far under ~16 MiB VMEM.
TILE = 256


def _shift_pass(t, nbits: int, mask):
    """One elementwise carry pass over the limb axis (cols). Returns the
    new limbs and the (rows, 1) carry out of the top limb — the final
    normalize's overflow detection needs every dropped top carry, exactly
    like limb._normalize sums them."""
    width = t.shape[1]
    carry = t >> nbits
    shifted = jnp.concatenate(
        [jnp.zeros_like(carry[:, :1]), carry[:, : width - 1]], axis=1
    )
    return (t & mask) + shifted, carry[:, width - 1 : width]


def _kogge(t, nbits: int, mask, width: int):
    """Kogge-Stone resolve of limbs in [0, 2^(nbits+1)); returns
    (canonical_limbs, carry_out as (rows, 1) u32 in {0, 1}).

    Entirely bool-free: Mosaic mis-lowers i1 vector casts, so generate/
    propagate flags are u32 0/1 values — g comes straight from the top
    bit (inputs are < 2^(nbits+1)), p from an arithmetic carry trick
    (((t & mask) + 1) >> nbits == 1 iff the limb is all-ones), and the
    combine uses bitwise | and & which are exact on 0/1 values."""
    g = t >> nbits  # in {0, 1} for inputs < 2^(nbits+1)
    p = ((t & mask) + jnp.uint32(1)) >> nbits  # 1 iff limb == mask
    shift = 1
    while shift < width:
        g_prev = jnp.concatenate(
            [jnp.zeros_like(g[:, :shift]), g[:, : width - shift]], axis=1
        )
        p_prev = jnp.concatenate(
            [jnp.zeros_like(p[:, :shift]), p[:, : width - shift]], axis=1
        )
        g = g | (p & g_prev)
        p = p & p_prev
        shift *= 2
    c_in = jnp.concatenate(
        [jnp.zeros_like(g[:, :1]), g[:, : width - 1]], axis=1
    )
    out = (t + c_in) & mask
    return out, g[:, width - 1 : width]


def _normalize(t, nbits: int, mask, width: int):
    """Canonicalize; returns (limbs, total_carry_out as (rows, 1) u32)."""
    t, c1 = _shift_pass(t, nbits, mask)
    t, c2 = _shift_pass(t, nbits, mask)
    t, c3 = _shift_pass(t, nbits, mask)
    out, g_top = _kogge(t, nbits, mask, width)
    return out, c1 + c2 + c3 + g_top


def _conv_into(acc, a, b_row, n: int, out_cols: int):
    """acc[:, i+j] += a[:, i] * b_row[j] — unrolled over i; each partial
    product is statically padded into place (pure adds, no scatters —
    scatters would leave VMEM/registers)."""
    rows = a.shape[0]
    for i in range(n):
        width = min(n, out_cols - i)
        if width <= 0:
            break
        contrib = a[:, i : i + 1] * b_row[:, :width]
        parts = []
        if i:
            parts.append(jnp.zeros((rows, i), jnp.uint32))
        parts.append(contrib)
        if out_cols - i - width:
            parts.append(jnp.zeros((rows, out_cols - i - width), jnp.uint32))
        acc = acc + (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        )
    return acc


def _mont_kernel_body(
    ctx: ModCtx, a_ref, b_ref, consts_ref, out_ref
):
    """consts_ref rows: 0 = ninv, 1 = p (n cols); 2..3 = R - p shifted
    into the high half (2n cols packed as two n-col rows)."""
    n = ctx.n_limbs
    nbits = ctx.limb_bits
    mask = jnp.uint32((1 << nbits) - 1)
    a = a_ref[:]
    b = b_ref[:]
    rows = a.shape[0]
    ninv = consts_ref[0:1, :]
    p_row = consts_ref[1:2, :]
    rm = jnp.concatenate(
        [consts_ref[2:3, :], consts_ref[3:4, :]], axis=1
    )  # (1, 2n)

    # 1. t = a * b over 2n columns
    t = jnp.zeros((rows, 2 * n), jnp.uint32)
    t = _conv_into(t, a, b, n, 2 * n)
    t, _ = _normalize(t, nbits, mask, 2 * n)

    # 2. m = (t mod R) * (-p^-1 mod R) mod R
    m = jnp.zeros((rows, n), jnp.uint32)
    m = _conv_into(m, t[:, :n], jnp.broadcast_to(ninv, (rows, n)), n, n)
    m, _ = _normalize(m, nbits, mask, n)

    # 3. s = t + m * p; final normalize fused with the conditional
    # subtract: lane2 adds (R - p) into the high columns, carry-out of
    # lane2 says hi >= p (mirrors limb.mont_mul exactly)
    s = t
    s = _conv_into(s, m, jnp.broadcast_to(p_row, (rows, n)), n, 2 * n)
    s2 = s + rm

    out1, _ = _normalize(s, nbits, mask, 2 * n)
    out2, carry2 = _normalize(s2, nbits, mask, 2 * n)
    # arithmetic select (no i1 vectors, no unsigned-min — both mis-lower
    # in Mosaic): carry2 <= 4, collapse its bits to a 0/1 flag; uint32
    # wraparound in the difference cancels exactly when flag == 1
    flag = (carry2 | (carry2 >> 1) | (carry2 >> 2)) & jnp.uint32(1)
    hi1 = out1[:, n:]
    hi2 = out2[:, n:]
    out_ref[:] = hi1 + (hi2 - hi1) * flag


@functools.lru_cache(maxsize=None)
def _ctx_consts(ctx: ModCtx) -> np.ndarray:
    """(4, n) constant rows: ninv, p, (R-p) low half, (R-p) high half —
    where "(R-p) shifted into high columns" means rows 2..3 concatenate
    to the 2n-col adjustment lane."""
    n = ctx.n_limbs
    out = np.zeros((4, n), np.uint32)
    out[0] = np.asarray(ctx.ninv, np.uint32)
    out[1] = np.asarray(ctx.limbs, np.uint32)
    rm2n = np.zeros(2 * n, np.uint32)
    rm2n[n:] = np.asarray(_r_minus_m(ctx), np.uint32)
    out[2] = rm2n[:n]
    out[3] = rm2n[n:]
    return out


@functools.lru_cache(maxsize=None)
def _mont_call(ctx: ModCtx, interpret: bool):
    """Gridless pallas_call over one (TILE, n_limbs) block. Batches
    larger than TILE run it under lax.map — Mosaic on this platform
    fails to legalize block index maps (i64 returns), and a device-side
    map over a fixed-shape kernel compiles the kernel exactly once
    anyway."""
    n = ctx.n_limbs
    kernel = functools.partial(_mont_kernel_body, ctx)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((TILE, n), jnp.uint32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )


def mont_mul_pallas(ctx: ModCtx, a, b, interpret: bool = False):
    """Drop-in for limb.mont_mul on the uint32 geometry: reduced
    Montgomery-form inputs with arbitrary broadcastable batch dims."""
    if ctx.np_dtype is not np.uint32:
        raise ValueError("pallas mont_mul requires the uint32 limb geometry")
    a, b = jnp.broadcast_arrays(a, b)
    batch_shape = a.shape[:-1]
    n = ctx.n_limbs
    flat_a = a.reshape(-1, n)
    flat_b = b.reshape(-1, n)
    rows = flat_a.shape[0]
    padded = -(-rows // TILE) * TILE
    if padded != rows:
        pad = ((0, padded - rows), (0, 0))
        flat_a = jnp.pad(flat_a, pad)
        flat_b = jnp.pad(flat_b, pad)
    consts = jnp.asarray(_ctx_consts(ctx))
    call = _mont_call(ctx, interpret)
    if padded == TILE:
        out = call(flat_a, flat_b, consts)
    else:
        chunks = padded // TILE
        out = jax.lax.map(
            lambda ab: call(ab[0], ab[1], consts),
            (
                flat_a.reshape(chunks, TILE, n),
                flat_b.reshape(chunks, TILE, n),
            ),
        ).reshape(padded, n)
    return out[:rows].reshape(*batch_shape, n)
