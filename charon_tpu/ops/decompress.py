"""Batched G1/G2 point decompression on the limb engine (ISSUE 5).

The last pure-Python hot loop on the duty path was compressed-point
decode: `g1g2.g2_from_bytes` runs an Fp2 square root with Python bigints
(~ms per signature), and — with pubkeys and messages LRU-cached — the
always-fresh SIGNATURE decompression dominated the host cost of every
coalescer flush. This module splits decode the same way the rest of the
engine splits work (SURVEY §7):

  * HOST — `parse_g2_lane`/`parse_g1_lane`: flag-bit validation, infinity
    encoding checks, x < p range checks, bytes -> ints. Microseconds per
    lane, no field arithmetic, no jax import (bench_hostplane measures
    this side without a device).
  * DEVICE — `decompress_g2_graph`/`decompress_g1_graph`: the field work,
    batched over lanes inside whatever jitted program the caller builds
    (blsops kernels, the mesh plane's fused decode+verify programs):
      - y^2 = x^3 + b, then the square root by a FIXED-exponent chain:
        p^2 = 9 mod 16, so the candidate a^((p^2+7)/16) is off from a
        true root by one of the four 4th roots of unity; four cheap
        multiply+compare corrections recover the root or prove a is a
        non-residue (the on-curve check y^2 == x^3 + b and sqrt
        verification are the same comparison). G1 uses p = 3 mod 4 and
        a^((p+1)/4).
      - ZCash sign-bit selection (lexicographically-largest y).
      - G2 subgroup membership by the psi endomorphism: P is in G2 iff
        psi(P) == [x]P with x the (negative) BLS parameter — a 64-bit
        ladder instead of the 255-bit [r]P ladder (Scott 2021, "A note
        on group membership tests"; host oracle: g1g2.g2_psi). G1 uses
        the GLV twin: P is in G1 iff phi(P) == [lambda]P with
        phi(x, y) = (beta*x, y) — a 127-bit ladder (ISSUE 6: the [r]P
        ladder it replaced was the bulk-warm-up bottleneck; host
        oracle: g1g2.g1_in_subgroup_phi).

    Malformed encodings NEVER raise: every lane carries a validity bit
    from host parse through the device mask, so one forged signature in
    a flush fails per-lane instead of exploding the batch.

Host constants below are computed with charon_tpu/crypto/fields (pure
ints) so importing this module never touches jax — the graph functions
import the limb engine lazily.
"""

from __future__ import annotations

import dataclasses

from charon_tpu.crypto import fields as F

P = F.P

_COMPRESSED = 0x80
_INFINITY = 0x40
_LEX_LARGEST = 0x20

# -- fixed-exponent sqrt chains ---------------------------------------------
# p^2 = 9 mod 16: candidate c = a^((p^2+7)/16) satisfies c^2 = a * eta with
# eta^4 == 1; the correction factors r (r^2 = eta^-1) are the four values
# below. p = 3 mod 4 for the G1 chain.
SQRT_EXP_G2 = (P * P + 7) // 16
SQRT_EXP_G1 = (P + 1) // 4
_S1 = F.fp2_sqrt((P - 1, 0))  # sqrt(-1)
ROOTS_OF_UNITY = (
    F.FP2_ONE,
    _S1,
    F.fp2_sqrt(_S1),
    F.fp2_sqrt(F.fp2_neg(_S1)),
)
ROOTS_OF_UNITY_SQ = tuple(F.fp2_sqr(r) for r in ROOTS_OF_UNITY)

# -- endomorphism constants (single-sourced in the host oracle) -------------
# psi(x, y) = (cx * conj(x), cy * conj(y)); on G2 psi acts as
# multiplication by the BLS parameter x = -X_ABS (mod r). phi(x, y) =
# (beta*x, y) on G1 acts as multiplication by lambda = x^2 - 1. All
# constants are imported from the host oracle (g1g2.g2_psi / g1_phi,
# jax-free, import-time consistency asserts there) — one definition,
# so kernel and oracle cannot drift.
from charon_tpu.crypto.g1g2 import (  # noqa: E402
    G1_BETA,
    G1_LAMBDA,
    PSI_CX,
    PSI_CY,
)

X_ABS = F.X_ABS

_HALF = (P - 1) // 2  # lex-largest threshold


# ---------------------------------------------------------------------------
# Host parse (jax-free: bench_hostplane times this side standalone)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ParsedPoint:
    """One compressed lane after host parse. `ok` is the HOST verdict
    (flags / range / length); the device adds residue + subgroup bits.
    `raw` keeps the wire bytes so degradation rungs below the device
    (python decode) can re-serve the lane without replumbing."""

    raw: bytes
    x0: int  # real Fp component (the only one for G1)
    x1: int
    sign: bool  # lexicographically-largest-y flag
    infinity: bool
    ok: bool


def parse_g2_lane(data: bytes) -> ParsedPoint:
    """96-byte compressed G2 -> ParsedPoint. Never raises."""
    sign = infinity = False
    x0 = x1 = 0
    ok = len(data) == 96 and bool(data[0] & _COMPRESSED)
    if ok:
        flags = data[0]
        infinity = bool(flags & _INFINITY)
        sign = bool(flags & _LEX_LARGEST)
        if infinity:
            # spec: infinity is the flag byte alone, zero elsewhere
            ok = not (flags & 0x3F) and not any(data[1:])
            sign = False
        else:
            x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
            x0 = int.from_bytes(data[48:], "big")
            if x0 >= P or x1 >= P:
                ok = False
    if not ok:
        x0 = x1 = 0  # never ship unreduced limbs to the device
        sign = infinity = False
    return ParsedPoint(bytes(data), x0, x1, sign, infinity, ok)


def parse_g1_lane(data: bytes) -> ParsedPoint:
    """48-byte compressed G1 -> ParsedPoint (x1 unused)."""
    sign = infinity = False
    x0 = 0
    ok = len(data) == 48 and bool(data[0] & _COMPRESSED)
    if ok:
        flags = data[0]
        infinity = bool(flags & _INFINITY)
        sign = bool(flags & _LEX_LARGEST)
        if infinity:
            ok = not (flags & 0x3F) and not any(data[1:])
            sign = False
        else:
            x0 = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
            if x0 >= P:
                ok = False
    if not ok:
        x0 = 0
        sign = infinity = False
    return ParsedPoint(bytes(data), x0, 0, sign, infinity, ok)


def _parsed_raw_matrix(parsed, nbytes: int):
    """[ParsedPoint] -> (N, nbytes) uint8 matrix of the raw wire bytes
    with the 3 flag bits cleared, zero rows for lanes the host parse
    already failed (or flagged infinity) — mirrors parse_*_lane's
    x = 0 blanking without touching Python ints."""
    import numpy as np

    buf = bytearray(len(parsed) * nbytes)
    for i, p in enumerate(parsed):
        if p.ok and not p.infinity:
            buf[i * nbytes : (i + 1) * nbytes] = p.raw
    # frombuffer over the locally-owned bytearray is writable: the
    # flag-bit clear runs in place, zero extra copies
    arr = np.frombuffer(buf, np.uint8).reshape(len(parsed), nbytes)
    arr[:, 0] &= 0x1F
    return arr


def pack_parsed_g2(ctx, parsed):
    """[ParsedPoint] -> device inputs (x0, x1 raw limbs, sign, infinity,
    host_ok masks). The raw wire bytes convert to limb arrays in one
    vectorized `bytes_to_limbs_batch` pass per Fp component (ISSUE 7) —
    no per-lane Python bigints, no O(lanes*limbs) shift loop."""
    import jax.numpy as jnp
    import numpy as np

    from charon_tpu.ops import limb

    raw = _parsed_raw_matrix(parsed, 96)
    # big-endian wire layout: bytes [0:48) = x1 (flags cleared above),
    # bytes [48:96) = x0
    x1 = jnp.asarray(limb.ctx_bytes_to_limbs(ctx, raw[:, :48]))
    x0 = jnp.asarray(limb.ctx_bytes_to_limbs(ctx, raw[:, 48:]))
    sign = jnp.asarray(np.asarray([p.sign for p in parsed], bool))
    inf = jnp.asarray(np.asarray([p.infinity for p in parsed], bool))
    ok = jnp.asarray(np.asarray([p.ok for p in parsed], bool))
    return x0, x1, sign, inf, ok


def pack_parsed_g1(ctx, parsed):
    import jax.numpy as jnp
    import numpy as np

    from charon_tpu.ops import limb

    raw = _parsed_raw_matrix(parsed, 48)
    x0 = jnp.asarray(limb.ctx_bytes_to_limbs(ctx, raw))
    sign = jnp.asarray(np.asarray([p.sign for p in parsed], bool))
    inf = jnp.asarray(np.asarray([p.infinity for p in parsed], bool))
    ok = jnp.asarray(np.asarray([p.ok for p in parsed], bool))
    return x0, sign, inf, ok


# ---------------------------------------------------------------------------
# Device graph pieces (composable inside any jitted program)
# ---------------------------------------------------------------------------


def fp2_pow_const(ctx, a, exponent: int):
    """a^exponent in Fp2 (Montgomery in/out), square-and-multiply as a
    lax.scan over the STATIC exponent bits — the Fp2 twin of
    limb.mont_pow, used for the fixed sqrt chains."""
    import jax.numpy as jnp
    from jax import lax

    from charon_tpu.ops import fptower as T
    from charon_tpu.ops import limb

    if exponent == 0:
        return T.fp2_one(ctx, a[0].shape[:-1])
    bits = jnp.asarray(limb._exp_bits(exponent))

    def step(acc, bit):
        acc = T.fp2_sqr(ctx, acc)
        mul = T.fp2_mul(ctx, acc, a)
        out = (
            jnp.where(bit != 0, mul[0], acc[0]),
            jnp.where(bit != 0, mul[1], acc[1]),
        )
        return out, None

    acc, _ = lax.scan(step, a, bits[1:])  # leading 1 bit: start from a
    return acc


def _raw_gt_const(ctx, raw, const_limbs):
    """Per-lane raw-limb comparison raw > const (little-endian limbs):
    most-significant differing limb decides."""
    import jax.numpy as jnp

    c = jnp.asarray(const_limbs)
    gt = jnp.flip(raw > c, axis=-1)  # most significant first
    eq = jnp.flip(raw == c, axis=-1)
    # exclusive prefix-AND of eq: limb i decides only if all above agree
    pre = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones_like(eq[..., :1]), eq[..., :-1]], axis=-1
        ),
        axis=-1,
    ).astype(bool)
    return jnp.any(gt & pre, axis=-1)


def _half_limbs(ctx):
    from charon_tpu.ops import limb

    return limb.int_to_limbs(_HALF, ctx.n_limbs, ctx.limb_bits, ctx.np_dtype)


def fp2_is_lex_largest_graph(ctx, y):
    """Device mirror of fields.fp2_is_lex_largest on a Montgomery Fp2
    element: compare (c1, c0) lexicographically against -y."""
    import jax.numpy as jnp

    from charon_tpu.ops import limb

    y0r = limb.from_mont(ctx, y[0])
    y1r = limb.from_mont(ctx, y[1])
    half = _half_limbs(ctx)
    return jnp.where(
        limb.is_zero(y1r),
        _raw_gt_const(ctx, y0r, half),
        _raw_gt_const(ctx, y1r, half),
    )


def g2_psi_graph(ctx, affine):
    """psi(x, y) = (cx * conj(x), cy * conj(y)) on batched affine G2."""
    from charon_tpu.ops import fptower as T

    x, y = affine
    shape = x[0].shape[:-1]
    cx = T.fp2_const(ctx, PSI_CX, shape)
    cy = T.fp2_const(ctx, PSI_CY, shape)
    return (
        T.fp2_mul(ctx, T.fp2_conj(ctx, x), cx),
        T.fp2_mul(ctx, T.fp2_conj(ctx, y), cy),
    )


def g2_subgroup_psi_graph(ctx, fr_ctx, affine):
    """P in G2 iff psi(P) == [x]P, i.e. psi(P) + [|x|]P == identity (x is
    negative for BLS12-381). One 64-bit ladder — ~4x less point work than
    the [r]P check. Identity lanes ((0,0) affine) pass."""
    import jax.numpy as jnp

    from charon_tpu.ops import curve as C
    from charon_tpu.ops import limb

    f = C.g2_ops(ctx)
    proj = C.affine_to_point(f, affine)
    scal = jnp.asarray(
        limb.int_to_limbs(
            X_ABS, fr_ctx.n_limbs, fr_ctx.limb_bits, fr_ctx.np_dtype
        )
    )
    xp = C.point_scalar_mul(f, fr_ctx, proj, scal, nbits=X_ABS.bit_length())
    psi = C.affine_to_point(f, g2_psi_graph(ctx, affine))
    return C.point_is_identity(f, C.point_add(f, xp, psi))


def decompress_g2_graph(
    ctx, fr_ctx, x_raw, sign, infinity=None, host_ok=None, subgroup=True
):
    """Batched compressed-G2 field work: raw x limbs (pair of (..., L)
    arrays) + host parse masks -> ((x, y) Montgomery affine, valid).

    valid lanes: finite on-curve (subgroup-checked when `subgroup`)
    points, plus well-formed infinity lanes; both infinity and invalid
    lanes come out as the (0, 0) affine identity encoding."""
    import jax.numpy as jnp

    from charon_tpu.ops import fptower as T
    from charon_tpu.ops import limb

    shape = x_raw[0].shape[:-1]
    if infinity is None:
        infinity = jnp.zeros(shape, bool)
    if host_ok is None:
        host_ok = jnp.ones(shape, bool)
    x = (limb.to_mont(ctx, x_raw[0]), limb.to_mont(ctx, x_raw[1]))
    b = T.fp2_const(ctx, (4, 4), shape)  # 4(1 + u)
    a = T.fp2_add(ctx, T.fp2_mul(ctx, T.fp2_sqr(ctx, x), x), b)
    c = fp2_pow_const(ctx, a, SQRT_EXP_G2)
    c2 = T.fp2_sqr(ctx, c)
    y = T.fp2_zero(ctx, shape)
    ok_sqrt = jnp.zeros(shape, bool)
    # four-root correction; the match test doubles as the on-curve check
    for r, r2 in zip(ROOTS_OF_UNITY, ROOTS_OF_UNITY_SQ):
        match = T.fp2_eq(
            T.fp2_mul(ctx, c2, T.fp2_const(ctx, r2, shape)), a
        )
        cand = T.fp2_mul(ctx, c, T.fp2_const(ctx, r, shape))
        y = T.fp2_select(match & ~ok_sqrt, cand, y)
        ok_sqrt = ok_sqrt | match
    largest = fp2_is_lex_largest_graph(ctx, y)
    y = T.fp2_select(largest != sign, T.fp2_neg(ctx, y), y)
    valid = ok_sqrt & host_ok & ~infinity
    # blank non-valid lanes to the identity encoding BEFORE the subgroup
    # ladder so garbage x never feeds the point formulas
    zero = T.fp2_zero(ctx, shape)
    x = T.fp2_select(valid, x, zero)
    y = T.fp2_select(valid, y, zero)
    if subgroup:
        valid = valid & g2_subgroup_psi_graph(ctx, fr_ctx, (x, y))
        x = T.fp2_select(valid, x, zero)
        y = T.fp2_select(valid, y, zero)
    return (x, y), valid | (infinity & host_ok)


def g1_subgroup_phi_graph(ctx, fr_ctx, affine):
    """P in G1 iff phi(P) == [lambda]P (Scott 2021) with phi(x, y) =
    (beta*x, y) — a 127-bit ladder instead of the 255-bit [r]P one,
    the GLV twin of the psi G2 check (host oracle:
    g1g2.g1_in_subgroup_phi). Equality is checked by cross-multiplying
    against the projective [lambda]P, so no extra inversion. Identity-
    blanked lanes FAIL the compare (Y=1, y=0); callers AND this into a
    mask that is already False there, and infinity lanes are re-ORed
    after, so the verdict is unchanged."""
    import jax.numpy as jnp

    from charon_tpu.ops import curve as C
    from charon_tpu.ops import limb

    x, y = affine
    f = C.g1_ops(ctx)
    proj = C.affine_to_point(f, affine)
    scal = jnp.asarray(
        limb.int_to_limbs(
            G1_LAMBDA, fr_ctx.n_limbs, fr_ctx.limb_bits, fr_ctx.np_dtype
        )
    )
    lx, ly, lz = C.point_scalar_mul(
        f, fr_ctx, proj, scal, nbits=G1_LAMBDA.bit_length()
    )
    beta = limb.const(ctx, G1_BETA, x.shape[:-1])
    phi_x = limb.mont_mul(ctx, x, beta)
    same_x = jnp.all(limb.mont_mul(ctx, phi_x, lz) == lx, axis=-1)
    same_y = jnp.all(limb.mont_mul(ctx, y, lz) == ly, axis=-1)
    return same_x & same_y


def decompress_g1_graph(
    ctx, fr_ctx, x_raw, sign, infinity=None, host_ok=None, subgroup=True
):
    """Batched compressed-G1 field work (Fp chain, p = 3 mod 4). The
    subgroup check uses the GLV phi endomorphism (127-bit ladder) —
    the [r]P ladder it replaces was the bulk-warmup bottleneck for a
    1M-key cold start (ISSUE 6)."""
    import jax.numpy as jnp

    from charon_tpu.ops import limb

    shape = x_raw.shape[:-1]
    if infinity is None:
        infinity = jnp.zeros(shape, bool)
    if host_ok is None:
        host_ok = jnp.ones(shape, bool)
    x = limb.to_mont(ctx, x_raw)
    b = limb.const(ctx, 4, shape)
    a = limb.add_mod(
        ctx, limb.mont_mul(ctx, limb.mont_sqr(ctx, x), x), b
    )
    y = limb.mont_pow(ctx, a, SQRT_EXP_G1)
    ok_sqrt = jnp.all(limb.mont_sqr(ctx, y) == a, axis=-1)
    largest = _raw_gt_const(ctx, limb.from_mont(ctx, y), _half_limbs(ctx))
    y = limb.select(largest != sign, limb.neg_mod(ctx, y), y)
    valid = ok_sqrt & host_ok & ~infinity
    zero = limb.zeros(ctx, shape)
    x = limb.select(valid, x, zero)
    y = limb.select(valid, y, zero)
    if subgroup:
        valid = valid & g1_subgroup_phi_graph(ctx, fr_ctx, (x, y))
        x = limb.select(valid, x, zero)
        y = limb.select(valid, y, zero)
    return (x, y), valid | (infinity & host_ok)
