"""Batched G1/G2 point arithmetic with complete projective formulas.

TPU-first design choice: instead of the reference's branchy affine formulas
(it calls herumi one point at a time — ref: tbls/herumi.go:225-247
Aggregate), we use the *complete* homogeneous-projective addition and
doubling formulas of Renes–Costello–Batina 2015 (eprint 2015/1060,
algorithms 7 and 9 for a = 0). Complete formulas are branch-free: they are
correct for identity inputs, equal inputs, and inverses, so the whole batch
flows through identical straight-line code — exactly what XLA wants.

Points are (X, Y, Z) tuples of field elements; the identity is (0, 1, 0).
G1 uses Fp limbs directly, G2 uses fptower Fp2 pairs. Both share the same
code via a tiny field-ops vtable.

Curve constants: E1: y^2 = x^3 + 4, E2: y^2 = x^3 + 4(1+u), so
b3 = 12 for G1 and 12*(1+u) = 12*xi for G2.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax.numpy as jnp
from jax import lax

from charon_tpu.crypto import g1g2 as REF
from charon_tpu.crypto.fields import P
from charon_tpu.ops import fptower as T
from charon_tpu.ops import limb
from charon_tpu.ops.limb import ModCtx


@dataclasses.dataclass(frozen=True)
class FieldOps:
    """Vtable making point formulas generic over Fp (G1) and Fp2 (G2)."""

    name: str
    ctx: ModCtx
    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    double: Callable
    neg: Callable
    small: Callable  # (a, k: static int) -> k*a
    mul_b3: Callable  # multiply by 3*b
    inv: Callable
    is_zero: Callable
    select: Callable
    zero: Callable  # (batch_shape) -> 0
    one: Callable  # (batch_shape) -> 1
    batch_shape: Callable  # element -> batch shape tuple
    batch: Callable  # (ops list of ("mul",a,b)/("sqr",a)) -> results; one
    # stacked base mul per dependency level (see fptower.fp2_batch)


@functools.lru_cache(maxsize=None)
def g1_ops(ctx: ModCtx) -> FieldOps:
    return FieldOps(
        name="g1",
        ctx=ctx,
        add=functools.partial(limb.add_mod, ctx),
        sub=functools.partial(limb.sub_mod, ctx),
        mul=functools.partial(limb.mont_mul, ctx),
        sqr=functools.partial(limb.mont_sqr, ctx),
        double=functools.partial(limb.double_mod, ctx),
        neg=functools.partial(limb.neg_mod, ctx),
        small=lambda a, k: _small_fp(ctx, a, k),
        mul_b3=lambda a: _small_fp(ctx, a, 12),
        inv=functools.partial(limb.inv_mod, ctx),
        is_zero=limb.is_zero,
        select=limb.select,
        zero=lambda shape=(): limb.zeros(ctx, shape),
        one=lambda shape=(): limb.const(ctx, 1, shape),
        batch_shape=lambda a: a.shape[:-1],
        batch=functools.partial(_fp_batch, ctx),
    )


@functools.lru_cache(maxsize=None)
def g2_ops(ctx: ModCtx) -> FieldOps:
    return FieldOps(
        name="g2",
        ctx=ctx,
        add=functools.partial(T.fp2_add, ctx),
        sub=functools.partial(T.fp2_sub, ctx),
        mul=functools.partial(T.fp2_mul, ctx),
        sqr=functools.partial(T.fp2_sqr, ctx),
        double=functools.partial(T.fp2_double, ctx),
        neg=functools.partial(T.fp2_neg, ctx),
        small=functools.partial(T.fp2_small, ctx),
        mul_b3=lambda a: T.fp2_small(ctx, T.fp2_mul_xi(ctx, a), 12),
        inv=functools.partial(T.fp2_inv, ctx),
        is_zero=T.fp2_is_zero,
        select=T.fp2_select,
        zero=lambda shape=(): T.fp2_zero(ctx, shape),
        one=lambda shape=(): T.fp2_one(ctx, shape),
        batch_shape=lambda a: a[0].shape[:-1],
        batch=functools.partial(T.fp2_batch, ctx),
    )


def _fp_batch(ctx, ops):
    """Stacked base muls for the Fp (G1) field — mirrors fptower.fp2_batch."""
    xs, ys = [], []
    for op in ops:
        if op[0] == "mul":
            xs.append(op[1])
            ys.append(op[2])
        elif op[0] == "sqr":
            xs.append(op[1])
            ys.append(op[1])
        else:
            raise ValueError(op[0])
    prods = limb.mont_mul(ctx, jnp.stack(xs), jnp.stack(ys))
    return [prods[i] for i in range(len(ops))]


def _small_fp(ctx, a, k: int):
    if k == 0:
        return limb.zeros(ctx, a.shape[:-1])
    acc = None
    add = a
    while k:
        if k & 1:
            acc = add if acc is None else limb.add_mod(ctx, acc, add)
        k >>= 1
        if k:
            add = limb.double_mod(ctx, add)
    return acc


# ---------------------------------------------------------------------------
# Complete projective add / double (RCB15 algorithms 7 and 9, a = 0)
# ---------------------------------------------------------------------------


def point_identity(f: FieldOps, batch_shape=()):
    return (f.zero(batch_shape), f.one(batch_shape), f.zero(batch_shape))


def point_add(f: FieldOps, p, q):
    """Complete addition, RCB15 algorithm 7 (a=0). 12 field muls in two
    stacked levels."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0, t1, t2, a, b, c = f.batch(
        [
            ("mul", x1, x2),
            ("mul", y1, y2),
            ("mul", z1, z2),
            ("mul", f.add(x1, y1), f.add(x2, y2)),
            ("mul", f.add(y1, z1), f.add(y2, z2)),
            ("mul", f.add(x1, z1), f.add(x2, z2)),
        ]
    )
    t3 = f.sub(a, f.add(t0, t1))  # x1y2 + x2y1
    t4 = f.sub(b, f.add(t1, t2))  # y1z2 + y2z1
    y3 = f.sub(c, f.add(t0, t2))  # x1z2 + x2z1
    t0 = f.small(t0, 3)  # 3 x1x2
    t2 = f.mul_b3(t2)  # b3 z1z2
    z3 = f.add(t1, t2)
    t1 = f.sub(t1, t2)
    y3 = f.mul_b3(y3)  # b3 (x1z2 + x2z1)
    m1, m2, m3, m4, m5, m6 = f.batch(
        [
            ("mul", t3, t1),
            ("mul", t4, y3),
            ("mul", y3, t0),
            ("mul", t1, z3),
            ("mul", z3, t4),
            ("mul", t0, t3),
        ]
    )
    return (f.sub(m1, m2), f.add(m3, m4), f.add(m5, m6))


def point_double(f: FieldOps, p):
    """Complete doubling, RCB15 algorithm 9 (a=0). 6 muls + 2 squarings in
    two stacked levels."""
    x, y, z = p
    t0, t1, zz, xy = f.batch(
        [("sqr", y), ("mul", y, z), ("sqr", z), ("mul", x, y)]
    )
    z3c = f.small(t0, 8)
    t2 = f.mul_b3(zz)
    y3 = f.add(t0, t2)
    t0 = f.sub(t0, f.small(t2, 3))
    x3, z3, ty, xyt = f.batch(
        [
            ("mul", t2, z3c),
            ("mul", t1, z3c),
            ("mul", t0, y3),
            ("mul", xy, t0),
        ]
    )
    return (f.double(xyt), f.add(ty, x3), z3)


def point_neg(f: FieldOps, p):
    return (p[0], f.neg(p[1]), p[2])


def point_select(f: FieldOps, mask, p, q):
    return tuple(f.select(mask, a, b) for a, b in zip(p, q))


def point_is_identity(f: FieldOps, p):
    return f.is_zero(p[2])


def point_to_affine(f: FieldOps, p):
    """(X, Y, Z) -> (x, y) with the identity mapping to (0, 0).

    Batched Fermat inversion; Z = 0 lanes produce 0 (inv_mod(0) == 0)."""
    zinv = f.inv(p[2])
    return (f.mul(p[0], zinv), f.mul(p[1], zinv))


def affine_to_point(f: FieldOps, a):
    """(x, y) affine -> projective; (0, 0) is interpreted as the identity
    (safe: y = 0 never occurs on these curves since b != 0)."""
    x, y = a
    is_id = jnp.logical_and(f.is_zero(x), f.is_zero(y))
    shape = f.batch_shape(x)
    one = f.one(shape)
    zero = f.zero(shape)
    return (
        x,
        f.select(is_id, one, y),
        f.select(is_id, zero, one),
    )


# ---------------------------------------------------------------------------
# Batched scalar multiplication (dynamic per-element scalars)
# ---------------------------------------------------------------------------


def _scalar_bits_msb(fr_ctx: ModCtx, scalars, nbits: int):
    """Raw (non-Montgomery) Fr limb array (..., n_limbs) -> (nbits, ...)
    bit array, MSB first, as the scan schedule."""
    shifts = jnp.arange(fr_ctx.limb_bits, dtype=scalars.dtype)
    bits = (scalars[..., None] >> shifts) & fr_ctx.u(1)  # (..., n_limbs, lb)
    bits = bits.reshape(*scalars.shape[:-1], -1)[..., :nbits]  # little-endian
    bits = jnp.flip(bits, axis=-1)  # MSB first
    return jnp.moveaxis(bits, -1, 0)


def point_scalar_mul(f: FieldOps, fr_ctx: ModCtx, p, scalars, nbits: int = 255):
    """[k]P for batched projective points and per-element raw Fr scalars.

    Left-to-right double-and-add as a lax.scan over the bit schedule with a
    branch-free select — uniform work per step, fully vectorized over the
    batch. ~nbits * (1 dbl + 1 add) field ops.
    """
    bits = _scalar_bits_msb(fr_ctx, scalars, nbits)
    import jax

    template = p[0][0] if isinstance(p[0], tuple) else p[0]
    identity = jax.tree_util.tree_map(
        lambda a: limb.match_vary(a, template),
        point_identity(f, f.batch_shape(p[0])),
    )

    def step(acc, bit):
        acc = point_double(f, acc)
        added = point_add(f, acc, p)
        return point_select(f, bit != 0, added, acc), None

    acc, _ = lax.scan(step, identity, bits)
    return acc


def point_sum(f: FieldOps, p, axis: int = -1):
    """Reduce-add points over a (small, static) batch axis.

    Points are (X, Y, Z) field pytrees; `axis` indexes a batch axis of the
    underlying limb arrays (negative axes count from the last batch axis).
    Implemented as a sequential fold of complete adds — callers use this for
    the threshold axis (t <= ~7)."""

    def leaf_slices(leaf):
        # normalize axis to the batch axes (last dim is limbs)
        ax = axis if axis >= 0 else leaf.ndim - 1 + axis
        return [
            jnp.take(leaf, i, axis=ax) for i in range(leaf.shape[ax])
        ]

    import jax

    sliced = jax.tree_util.tree_map(leaf_slices, p)
    leaves, treedef = jax.tree_util.tree_flatten(sliced, is_leaf=lambda x: isinstance(x, list))
    n = len(leaves[0])
    terms = [
        jax.tree_util.tree_unflatten(treedef, [l[i] for l in leaves])
        for i in range(n)
    ]
    acc = terms[0]
    for t in terms[1:]:
        acc = point_add(f, acc, t)
    return acc


# ---------------------------------------------------------------------------
# Host <-> device packing (affine Python-int points, identity = None)
# ---------------------------------------------------------------------------


def g1_pack(ctx: ModCtx, points):
    """Iterable of affine G1 points ((x, y) ints or None) -> device affine
    pair of Montgomery limb arrays, identity encoded as (0, 0)."""
    xs, ys = [], []
    for pt in points:
        if pt is None:
            xs.append(0)
            ys.append(0)
        else:
            xs.append(pt[0])
            ys.append(pt[1])
    return (
        jnp.asarray(limb.pack_mont_host(ctx, xs)),
        jnp.asarray(limb.pack_mont_host(ctx, ys)),
    )


def g1_unpack(ctx: ModCtx, affine) -> list:
    xs = limb.unpack_mont_host(ctx, affine[0])
    ys = limb.unpack_mont_host(ctx, affine[1])
    return [None if x == 0 and y == 0 else (x, y) for x, y in zip(xs, ys)]


def g2_pack(ctx: ModCtx, points):
    """Iterable of affine G2 points (((x0,x1),(y0,y1)) or None) -> device
    affine pair of Fp2 elements."""
    xs, ys = [], []
    for pt in points:
        if pt is None:
            xs.append((0, 0))
            ys.append((0, 0))
        else:
            xs.append(pt[0])
            ys.append(pt[1])
    return (T.fp2_pack(ctx, xs), T.fp2_pack(ctx, ys))


def g2_unpack(ctx: ModCtx, affine) -> list:
    xs = T.fp2_unpack(ctx, affine[0])
    ys = T.fp2_unpack(ctx, affine[1])
    return [
        None if x == (0, 0) and y == (0, 0) else (x, y)
        for x, y in zip(xs, ys)
    ]


def fr_pack(ctx: ModCtx, scalars) -> jnp.ndarray:
    """Raw (non-Montgomery) scalar packing for the bit-schedule kernels."""
    return jnp.asarray(limb.ctx_pack(ctx, [s % ctx.modulus for s in scalars]))
