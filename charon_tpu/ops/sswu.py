"""Batched hash-to-curve (G2 SSWU) on the limb engine (ISSUE 6).

The last pure-Python bigint burst on the COLD path was message
hash-to-curve: a restart or validator-set rotation pays ~ms of host
field arithmetic per uncached message (SSWU + 3-isogeny + cofactor
clearing in crypto/h2c.py). This module splits it the same way
ops/decompress.py split point decompression (SURVEY §7):

  * HOST — `hash_to_field_lane`: expand_message_xmd + hash_to_field
    (RFC 9380 §5.2/§5.3.1, SHA-256 and byte slicing only, no field
    arithmetic, no jax import) -> two Fp2 elements per message plus
    their sgn0 bits (u is host-known, so the RFC sign of y is decided
    by a host bit instead of a device parity graph on u).
  * DEVICE — `hash_to_g2_graph`: the field work, batched over lanes:
      - simplified SWU onto E'' by a CONSTANT-TIME reformulation of
        RFC 9380 §6.6.2: one fixed-exponent chain gx1^((p^2+7)/16)
        (p^2 = 9 mod 16 — the same four-4th-roots-of-unity correction
        machinery as the decompression kernels) serves BOTH branches:
        the four candidates c*r decide the square case, and the
        non-square case's sqrt(gx2) = u^3 * Z^(3(p^2+7)/16) * c * r
        reuses c with a host-precomputed constant, so no second chain;
      - the 3-isogeny E'' -> E' (Horner over the RFC appendix E.3
        constants, both denominators inverted through ONE shared
        Fermat chain);
      - cofactor clearing by the psi-endomorphism split
        (Budroni–Pintore): h_eff*P = [x^2-x-1]P + [x-1]psi(P) +
        psi^2(2P) — two 64-bit ladders instead of the 1253-bit h_eff
        one. Host oracle: g1g2.g2_clear_cofactor_psi (asserted equal
        to the spec [h_eff]P ladder at import of crypto/h2c).

    Per-lane `ok` masks ride the whole graph (mathematically always
    True — SSWU is total — but carried so a malformed/padded lane can
    NEVER raise; the bulk warm-up path depends on that contract).

Endomorphism constants (PSI_CX/PSI_CY/PSI2_CX) are imported from the
host oracle in crypto/g1g2 — one definition, kernel and oracle cannot
drift (import-time asserts live there). Host constants below are pure
ints via crypto/fields, so importing this module never touches jax —
the graph functions import the limb engine lazily (bench_hostplane
times the host half without a device).
"""

from __future__ import annotations

import dataclasses

from charon_tpu.crypto import fields as F
from charon_tpu.crypto import h2c as H
from charon_tpu.crypto.g1g2 import PSI2_CX
from charon_tpu.ops.decompress import (
    ROOTS_OF_UNITY,
    ROOTS_OF_UNITY_SQ,
    SQRT_EXP_G2,
    fp2_pow_const,
    g2_psi_graph,
)

P = F.P
X_ABS = F.X_ABS

DST_POP = H.DST_POP

# -- host-precomputed SSWU constants (pure ints) ----------------------------
_A, _B, _Z = H.A_PRIME, H.B_PRIME, H.Z_SSWU
# generic-branch x1 = (-B/A) * (1 + 1/(Z u^2 + Z^2 u^4)); exceptional
# (denominator == 0) x1 = B / (Z A)
NEG_B_OVER_A = F.fp2_mul(F.fp2_neg(_B), F.fp2_inv(_A))
B_OVER_ZA = F.fp2_mul(_B, F.fp2_inv(F.fp2_mul(_Z, _A)))
# Z^(3(p^2+7)/16): with c = gx1^((p^2+7)/16) already computed for the
# square branch, sqrt(gx2) = sqrt(gx1 * (Z u^2)^3) = u^3 * C_Z3 * c
# up to a 4th root of unity — the non-square branch costs four
# multiply+compare corrections instead of a second 758-bit chain.
C_Z3 = F.fp2_pow(_Z, 3 * (P * P + 7) // 16)


# ---------------------------------------------------------------------------
# Host hashing (jax-free)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HashedMsg:
    """One message after host hash_to_field: the two Fp2 elements of
    the RO construction plus their sgn0 bits."""

    u0: tuple
    u1: tuple
    sgn0: bool
    sgn1: bool


def hash_to_field_lane(msg: bytes, dst: bytes = DST_POP) -> HashedMsg:
    """RFC 9380 hash_to_field for one message — SHA-256 + byte work
    only; the microseconds-per-lane host half of the device path."""
    u0, u1 = H.hash_to_field_fp2(msg, 2, dst)
    return HashedMsg(u0, u1, bool(F.fp2_sgn0(u0)), bool(F.fp2_sgn0(u1)))


def pack_hashed(ctx, lanes):
    """[HashedMsg] -> device inputs: four raw limb arrays (u0/u1 Fp2
    components) + two sgn0 bool arrays. Numpy/jnp packing only."""
    import jax.numpy as jnp
    import numpy as np

    from charon_tpu.ops import limb

    u00 = jnp.asarray(limb.ctx_pack(ctx, [l.u0[0] for l in lanes]))
    u01 = jnp.asarray(limb.ctx_pack(ctx, [l.u0[1] for l in lanes]))
    u10 = jnp.asarray(limb.ctx_pack(ctx, [l.u1[0] for l in lanes]))
    u11 = jnp.asarray(limb.ctx_pack(ctx, [l.u1[1] for l in lanes]))
    s0 = jnp.asarray(np.asarray([l.sgn0 for l in lanes], bool))
    s1 = jnp.asarray(np.asarray([l.sgn1 for l in lanes], bool))
    return u00, u01, u10, u11, s0, s1


# ---------------------------------------------------------------------------
# Device graph pieces (composable inside any jitted program)
# ---------------------------------------------------------------------------


def fp2_sgn0_graph(ctx, a):
    """RFC 9380 sgn0 for a Montgomery Fp2 element, as a device bool:
    sign_0 | (zero_0 & sign_1) on the raw (non-Montgomery) limbs.
    Limb 0 carries the low bits (little-endian, even limb width), so
    parity is bit 0 of limb 0."""
    import jax.numpy as jnp

    from charon_tpu.ops import limb

    a0r = limb.from_mont(ctx, a[0])
    a1r = limb.from_mont(ctx, a[1])
    sign_0 = (a0r[..., 0] & ctx.u(1)) != 0
    sign_1 = (a1r[..., 0] & ctx.u(1)) != 0
    return sign_0 | (limb.is_zero(a0r) & sign_1)


def sswu_graph(ctx, u, sgn_u):
    """Simplified SWU onto E'' (RFC 9380 §6.6.2), branch-free.

    u: Montgomery Fp2 (pair of (..., L) arrays); sgn_u: host sgn0(u)
    bools. Returns ((x, y) affine on E'', ok). `ok` is True whenever
    one of the eight sqrt candidates verified — always, for real field
    elements — and rides the caller's validity mask so a bad lane can
    never raise."""
    import jax.numpy as jnp

    from charon_tpu.ops import fptower as T

    shape = u[0].shape[:-1]
    u2 = T.fp2_sqr(ctx, u)
    tv1 = T.fp2_mul(ctx, u2, T.fp2_const(ctx, _Z, shape))  # Z u^2
    tv2 = T.fp2_sqr(ctx, tv1)
    den = T.fp2_add(ctx, tv1, tv2)
    den_zero = T.fp2_is_zero(den)
    # fp2_inv(0) == 0, so the generic expression is garbage-free on the
    # exceptional lanes and the select swaps in B/(Z A)
    x1 = T.fp2_mul(
        ctx,
        T.fp2_const(ctx, NEG_B_OVER_A, shape),
        T.fp2_add(ctx, T.fp2_one(ctx, shape), T.fp2_inv(ctx, den)),
    )
    x1 = T.fp2_select(den_zero, T.fp2_const(ctx, B_OVER_ZA, shape), x1)
    a_const = T.fp2_const(ctx, _A, shape)
    b_const = T.fp2_const(ctx, _B, shape)
    gx1 = T.fp2_add(
        ctx,
        T.fp2_mul(ctx, T.fp2_add(ctx, T.fp2_sqr(ctx, x1), a_const), x1),
        b_const,
    )
    # THE chain: c = gx1^((p^2+7)/16); everything else is corrections
    c = fp2_pow_const(ctx, gx1, SQRT_EXP_G2)
    c2 = T.fp2_sqr(ctx, c)
    y = T.fp2_zero(ctx, shape)
    ok1 = jnp.zeros(shape, bool)
    for r, r2 in zip(ROOTS_OF_UNITY, ROOTS_OF_UNITY_SQ):
        match = T.fp2_eq(
            T.fp2_mul(ctx, c2, T.fp2_const(ctx, r2, shape)), gx1
        )
        cand = T.fp2_mul(ctx, c, T.fp2_const(ctx, r, shape))
        y = T.fp2_select(match & ~ok1, cand, y)
        ok1 = ok1 | match
    # non-square branch: x2 = Z u^2 x1, gx2 = gx1 (Z u^2)^3, and
    # sqrt(gx2) = u^3 * C_Z3 * c up to the same four roots
    x2 = T.fp2_mul(ctx, tv1, x1)
    gx2 = T.fp2_mul(ctx, gx1, T.fp2_mul(ctx, tv1, tv2))
    u3 = T.fp2_mul(ctx, u2, u)
    base = T.fp2_mul(
        ctx, T.fp2_mul(ctx, u3, c), T.fp2_const(ctx, C_Z3, shape)
    )
    base2 = T.fp2_sqr(ctx, base)
    y2 = T.fp2_zero(ctx, shape)
    ok2 = jnp.zeros(shape, bool)
    for r, r2 in zip(ROOTS_OF_UNITY, ROOTS_OF_UNITY_SQ):
        match = T.fp2_eq(
            T.fp2_mul(ctx, base2, T.fp2_const(ctx, r2, shape)), gx2
        )
        cand = T.fp2_mul(ctx, base, T.fp2_const(ctx, r, shape))
        y2 = T.fp2_select(match & ~ok2, cand, y2)
        ok2 = ok2 | match
    x = T.fp2_select(ok1, x1, x2)
    y = T.fp2_select(ok1, y, y2)
    # RFC sign: sgn0(y) must equal sgn0(u)
    flip = fp2_sgn0_graph(ctx, y) != sgn_u
    y = T.fp2_select(flip, T.fp2_neg(ctx, y), y)
    return (x, y), ok1 | ok2


def iso_map_graph(ctx, pt):
    """3-isogeny E'' -> E' (RFC 9380 appendix E.3) on batched affine
    points. Both denominators share ONE Fermat inversion chain via the
    product trick: inv(xd) = inv(xd yd) yd, inv(yd) = inv(xd yd) xd."""
    from charon_tpu.ops import fptower as T

    x, y = pt
    shape = x[0].shape[:-1]

    def horner(coeffs):
        acc = T.fp2_const(ctx, coeffs[-1], shape)
        for k in reversed(coeffs[:-1]):
            acc = T.fp2_add(
                ctx, T.fp2_mul(ctx, acc, x), T.fp2_const(ctx, k, shape)
            )
        return acc

    x_num = horner(H._K["x_num"])
    x_den = horner(H._K["x_den"])
    y_num = horner(H._K["y_num"])
    y_den = horner(H._K["y_den"])
    d_inv = T.fp2_inv(ctx, T.fp2_mul(ctx, x_den, y_den))
    xo = T.fp2_mul(ctx, x_num, T.fp2_mul(ctx, d_inv, y_den))
    yo = T.fp2_mul(
        ctx, y, T.fp2_mul(ctx, y_num, T.fp2_mul(ctx, d_inv, x_den))
    )
    return (xo, yo)


def _g2_psi_proj(ctx, p):
    """psi on batched PROJECTIVE G2: conjugate all coordinates, scale
    X by cx and Y by cy (homogeneous, so Z just conjugates)."""
    from charon_tpu.ops import fptower as T

    x, y, z = p
    psi_aff = g2_psi_graph(ctx, (x, y))
    return (psi_aff[0], psi_aff[1], T.fp2_conj(ctx, z))


def _g2_psi2_proj(ctx, p):
    """psi^2 as its collapsed LINEAR form: (PSI2_CX * X, -Y, Z) — one
    Fp scale and a negation (constants single-sourced in g1g2)."""
    from charon_tpu.ops import fptower as T
    from charon_tpu.ops import limb

    x, y, z = p
    shape = x[0].shape[:-1]
    cx = limb.const(ctx, PSI2_CX, shape)
    return (T.fp2_mul_fp(ctx, x, cx), T.fp2_neg(ctx, y), z)


def _ladder_x(ctx, fr_ctx, f, p):
    """[x]P for the (negative) BLS parameter: a 64-bit |x| ladder plus
    a negation."""
    import jax.numpy as jnp

    from charon_tpu.ops import curve as C
    from charon_tpu.ops import limb

    scal = jnp.asarray(
        limb.int_to_limbs(
            X_ABS, fr_ctx.n_limbs, fr_ctx.limb_bits, fr_ctx.np_dtype
        )
    )
    return C.point_neg(
        f, C.point_scalar_mul(f, fr_ctx, p, scal, nbits=X_ABS.bit_length())
    )


def clear_cofactor_psi_graph(ctx, fr_ctx, proj):
    """Budroni–Pintore cofactor clearing on batched projective G2:
    [x^2-x-1]P + [x-1]psi(P) + psi^2(2P). Two 64-bit ladders + a
    handful of complete adds — vs 1253 doublings for the h_eff ladder.
    Oracle: g1g2.g2_clear_cofactor_psi."""
    from charon_tpu.ops import curve as C

    f = C.g2_ops(ctx)
    x_p = _ladder_x(ctx, fr_ctx, f, proj)  # [x]P
    psi_p = _g2_psi_proj(ctx, proj)
    s = C.point_add(f, x_p, psi_p)  # [x]P + psi(P)
    t = _ladder_x(ctx, fr_ctx, f, s)  # [x^2]P + [x]psi(P)
    t = C.point_add(f, t, C.point_neg(f, s))  # ... - [x]P - psi(P)
    t = C.point_add(f, t, C.point_neg(f, proj))  # ... - P
    two_p = C.point_double(f, proj)
    return C.point_add(f, t, _g2_psi2_proj(ctx, two_p))


def map_to_g2_graph(ctx, u, sgn_u):
    """SSWU + isogeny: one hash_to_field output -> affine E' point."""
    q, ok = sswu_graph(ctx, u, sgn_u)
    return iso_map_graph(ctx, q), ok


def hash_to_g2_graph(ctx, fr_ctx, u0_raw, u1_raw, sgn0, sgn1, host_ok=None):
    """Full device hash_to_curve tail: two raw-limb Fp2 elements (the
    host hash_to_field outputs, pairs of (..., L) arrays) + sgn0 bits
    -> ((x, y) Montgomery affine G2 in the r-subgroup, valid).

    Invalid/padded lanes (host_ok False, or the mathematically-
    impossible no-root case) come out as the (0, 0) affine identity
    encoding with valid False — never exceptions."""
    import jax.numpy as jnp

    from charon_tpu.ops import curve as C
    from charon_tpu.ops import fptower as T
    from charon_tpu.ops import limb

    shape = u0_raw[0].shape[:-1]
    if host_ok is None:
        host_ok = jnp.ones(shape, bool)
    u0 = (limb.to_mont(ctx, u0_raw[0]), limb.to_mont(ctx, u0_raw[1]))
    u1 = (limb.to_mont(ctx, u1_raw[0]), limb.to_mont(ctx, u1_raw[1]))
    q0, ok0 = map_to_g2_graph(ctx, u0, sgn0)
    q1, ok1 = map_to_g2_graph(ctx, u1, sgn1)
    f = C.g2_ops(ctx)
    p = C.point_add(
        f, C.affine_to_point(f, q0), C.affine_to_point(f, q1)
    )
    p = clear_cofactor_psi_graph(ctx, fr_ctx, p)
    x, y = C.point_to_affine(f, p)
    valid = ok0 & ok1 & host_ok
    zero = T.fp2_zero(ctx, shape)
    x = T.fp2_select(valid, x, zero)
    y = T.fp2_select(valid, y, zero)
    return (x, y), valid
