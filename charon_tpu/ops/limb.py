"""Batched multi-limb Montgomery arithmetic for big prime fields on TPU.

Representation: an element of Z/m is a little-endian vector of `n_limbs`
limbs of `limb_bits` bits stored as `dtype`, shape (..., n_limbs); leading
axes are batch axes. All public ops accept arbitrary broadcastable batch
shapes and keep values fully reduced (< m).

Two limb geometries are provided, selected per ModCtx:

  * 24-bit limbs in uint64 (CPU-friendly): products of two limbs are
    < 2^48, so a full 16-term schoolbook column plus Montgomery additions
    stays < 2^54 — far from uint64 overflow, which means NO carry
    normalization is needed inside the hot loops (one carry pass at the
    end of a multiply). 24 bits = 3 bytes, so host packing is a pure-numpy
    byte reshuffle.
  * 12-bit limbs in uint32 (TPU-friendly): TPUs have no native 64-bit
    integers (XLA emulates them slowly), so the TPU contexts use 12-bit
    limbs whose products fit 24 bits; a 32-term column plus Montgomery
    additions stays < 2^31 in uint32. The 12-bit width also splits into
    two 6-bit pieces that fit SIGNED int8 — the MXU decomposition of the
    constant-operand convolutions lives in ops/limb_mxu.py.

The no-mid-loop-carry invariant (see mont_mul) is asserted in make_ctx for
whatever geometry is requested.

Montgomery domain: R = 2^(limb_bits * n_limbs). `mont_mul(a, b) =
a*b*R^-1 mod m`. Values enter the domain with `to_mont` (device) and leave
with `from_mont`.

This file is generic over the modulus (instantiated for BLS12-381 Fp and Fr
at the bottom) and is the device-side counterpart of
charon_tpu/crypto/fields.py, which serves as its correctness oracle.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from charon_tpu.crypto.fields import P, R as FR_MOD

# Default geometry (kept as module constants for the host packing helpers).
LIMB_BITS = 24
LIMB_BYTES = 3
MASK = (1 << LIMB_BITS) - 1


@dataclasses.dataclass(frozen=True, eq=False)  # eq=False: identity hash, so
# module-singleton contexts work as lru_cache / static-argnum keys despite
# holding numpy arrays.
class ModCtx:
    """Everything the device needs to do arithmetic mod `modulus`."""

    name: str
    modulus: int
    n_limbs: int
    limb_bits: int
    np_dtype: type  # np.uint64 | np.uint32
    limbs: np.ndarray  # (n_limbs,) — the modulus
    pinv: int  # -modulus^-1 mod 2^limb_bits
    ninv: np.ndarray  # (n_limbs,) — -modulus^-1 mod 2^(limb_bits*n_limbs)
    r2: np.ndarray  # (n_limbs,) — R^2 mod m (to_mont multiplier)
    mont_one: np.ndarray  # (n_limbs,) — R mod m (1 in Montgomery form)

    @property
    def mask(self) -> int:
        return (1 << self.limb_bits) - 1

    @property
    def dtype(self):
        return jnp.dtype(self.np_dtype)

    @property
    def r_mont(self) -> int:
        return (1 << (self.limb_bits * self.n_limbs)) % self.modulus

    def u(self, x: int):
        """Python int -> dtype scalar constant."""
        return jnp.asarray(x, self.dtype)


def int_to_limbs(x: int, n_limbs: int, limb_bits: int = LIMB_BITS, np_dtype=np.uint64) -> np.ndarray:
    out = np.empty(n_limbs, np_dtype)
    mask = (1 << limb_bits) - 1
    for i in range(n_limbs):
        out[i] = (x >> (limb_bits * i)) & mask
    return out


def make_ctx(name: str, modulus: int, n_limbs: int, limb_bits: int = LIMB_BITS, np_dtype=np.uint64) -> ModCtx:
    if modulus.bit_length() > limb_bits * n_limbs - 2:
        raise ValueError("need >= 2 bits of headroom above the modulus")
    # No-mid-loop-carry invariant: a schoolbook column of n products plus n
    # Montgomery additions plus carries must fit the accumulator dtype.
    acc_bits = np.dtype(np_dtype).itemsize * 8
    worst = 2 * n_limbs * ((1 << limb_bits) - 1) ** 2 + (1 << acc_bits - 1) // (1 << limb_bits)
    if worst >= 1 << acc_bits:
        raise ValueError(f"limb geometry {limb_bits}b x {n_limbs} overflows {acc_bits}-bit accumulator")
    r = 1 << (limb_bits * n_limbs)
    return ModCtx(
        name=name,
        modulus=modulus,
        n_limbs=n_limbs,
        limb_bits=limb_bits,
        np_dtype=np_dtype,
        limbs=int_to_limbs(modulus, n_limbs, limb_bits, np_dtype),
        pinv=(-pow(modulus, -1, 1 << limb_bits)) % (1 << limb_bits),
        ninv=int_to_limbs(
            (-pow(modulus, -1, r)) % r, n_limbs, limb_bits, np_dtype
        ),
        r2=int_to_limbs(r * r % modulus, n_limbs, limb_bits, np_dtype),
        mont_one=int_to_limbs(r % modulus, n_limbs, limb_bits, np_dtype),
    )


# ---------------------------------------------------------------------------
# Host <-> device packing (pure numpy)
# ---------------------------------------------------------------------------


def bytes_to_limbs_batch(
    data,
    n_limbs: int,
    limb_bits: int = LIMB_BITS,
    np_dtype=np.uint64,
    item_bytes: int | None = None,
    byteorder: str = "big",
) -> np.ndarray:
    """Concatenated fixed-width byte strings -> (N, n_limbs) limb array
    in ONE vectorized numpy pass (ISSUE 7): no per-int Python loop, no
    Python bigints. `data` is bytes/bytearray/memoryview of N *
    item_bytes, or an already-shaped (N, item_bytes) uint8 array —
    which is how compressed wire signatures flow from the socket buffer
    to device-ready limb arrays without an int detour.

    `byteorder` is the byte order of each item ("big" = wire format for
    BLS field elements). Supported geometries: 24-bit limbs (3 bytes
    per limb) and 12-bit limbs in pairs (3 bytes per 2 limbs, n_limbs
    even) — the two engine geometries; anything else falls back to a
    per-item int path."""
    if isinstance(data, np.ndarray):
        raw = np.ascontiguousarray(data, dtype=np.uint8)
        if raw.ndim != 2:
            raise ValueError("ndarray input must be (N, item_bytes)")
        item_bytes = raw.shape[1]
    else:
        if item_bytes is None:
            raise ValueError("item_bytes required for flat byte input")
        raw = np.frombuffer(data, np.uint8)
        if item_bytes == 0 or raw.size % item_bytes:
            raise ValueError("byte length not a multiple of item_bytes")
        raw = raw.reshape(-1, item_bytes)
    total_bits = n_limbs * limb_bits
    if item_bytes * 8 > total_bits + 7:
        raise ValueError(
            f"{item_bytes}-byte items overflow {n_limbs}x{limb_bits}-bit limbs"
        )
    if byteorder == "big":
        raw = raw[:, ::-1]
    elif byteorder != "little":
        raise ValueError(f"bad byteorder {byteorder!r}")
    needed = (total_bits + 7) // 8
    if needed != item_bytes:
        pad = np.zeros((raw.shape[0], needed - item_bytes), np.uint8)
        raw = np.concatenate([raw, pad], axis=1)
    raw = np.ascontiguousarray(raw)
    if limb_bits == 24:
        b = raw.reshape(-1, n_limbs, 3).astype(np.uint64)
        out = b[..., 0] | (b[..., 1] << np.uint64(8)) | (b[..., 2] << np.uint64(16))
        return out.astype(np_dtype, copy=False)
    if limb_bits == 12 and n_limbs % 2 == 0:
        b = raw.reshape(-1, n_limbs // 2, 3).astype(np.uint32)
        lo = b[..., 0] | ((b[..., 1] & 0x0F) << np.uint32(8))
        hi = (b[..., 1] >> np.uint32(4)) | (b[..., 2] << np.uint32(4))
        out = np.empty((raw.shape[0], n_limbs), np.uint32)
        out[:, 0::2] = lo
        out[:, 1::2] = hi
        return out.astype(np_dtype, copy=False)
    # uncommon geometry: per-item int fallback (correct, not hot)
    vals = [
        int.from_bytes(raw[i].tobytes(), "little")
        for i in range(raw.shape[0])
    ]
    return pack(vals, n_limbs, limb_bits, np_dtype)


def ctx_bytes_to_limbs(
    ctx: ModCtx, data, item_bytes: int | None = None, byteorder: str = "big"
) -> np.ndarray:
    return bytes_to_limbs_batch(
        data, ctx.n_limbs, ctx.limb_bits, ctx.np_dtype, item_bytes, byteorder
    )


def pack(values, n_limbs: int, limb_bits: int = LIMB_BITS, np_dtype=np.uint64) -> np.ndarray:
    """List/iterable of ints -> (N, n_limbs) limb array."""
    vals = list(values)
    nbytes = (n_limbs * limb_bits + 7) // 8
    if limb_bits == 24 or (limb_bits == 12 and n_limbs % 2 == 0):
        # one int->bytes conversion per value, then the shared
        # vectorized byte->limb pass (the 12-bit geometry used to pay
        # an O(N * n_limbs) pure-Python shift loop here)
        buf = b"".join(int(v).to_bytes(nbytes, "little") for v in vals)
        return bytes_to_limbs_batch(
            buf, n_limbs, limb_bits, np_dtype,
            item_bytes=nbytes, byteorder="little",
        )
    mask = (1 << limb_bits) - 1
    out = np.empty((len(vals), n_limbs), np_dtype)
    for r, v in enumerate(vals):
        v = int(v)
        for i in range(n_limbs):
            out[r, i] = (v >> (limb_bits * i)) & mask
    return out


def unpack(arr, limb_bits: int = LIMB_BITS) -> list[int]:
    """(..., n_limbs) limb array -> flat list of ints (C-order batch)."""
    arr = np.asarray(arr).reshape(-1, np.shape(arr)[-1])
    out = []
    for row in arr:
        v = 0
        for i, limb in enumerate(row):
            v |= int(limb) << (limb_bits * i)
        out.append(v)
    return out


def ctx_pack(ctx: ModCtx, values) -> np.ndarray:
    return pack(values, ctx.n_limbs, ctx.limb_bits, ctx.np_dtype)


def ctx_unpack(ctx: ModCtx, arr) -> list[int]:
    return unpack(arr, ctx.limb_bits)


# ---------------------------------------------------------------------------
# Parallel carry machinery (TPU-first: no sequential lax.scan over limbs)
#
# Carry propagation is the classic adder-carry problem: ripple (a scan over
# the limb axis) serializes 32-64 tiny steps, which starves the TPU's
# vector units and bloats compile time. Instead:
#   * _shift_carries: split each limb v = a + 2^b c and re-add the carries
#     one position up — a purely elementwise pass that shrinks the excess
#     by `limb_bits` per application (3 passes take any accumulator-range
#     value down to < 2^(limb_bits+1));
#   * _kogge_resolve: the final {0,1}-carry resolution via a Kogge-Stone
#     (generate, propagate) associative scan — O(log n) parallel steps.
# ---------------------------------------------------------------------------


def _shift_carries(ctx: ModCtx, t):
    """One elementwise carry pass: limbs' excess moves one position up.
    Returns (limbs, carry_out_of_top_limb)."""
    mask = ctx.u(ctx.mask)
    carry = t >> ctx.limb_bits
    shifted = jnp.concatenate(
        [jnp.zeros_like(carry[..., :1]), carry[..., :-1]], axis=-1
    )
    return (t & mask) + shifted, carry[..., -1]


def _kogge_resolve(ctx: ModCtx, t):
    """Resolve limbs in [0, 2^(limb_bits+1)) to canonical form, returning
    (limbs, carry_out). Kogge-Stone over (generate, propagate)."""
    mask = ctx.u(ctx.mask)
    g = (t >> ctx.limb_bits).astype(jnp.bool_)  # generates a carry
    p = (t & mask) == mask  # propagates an incoming carry

    def op(a, b):
        # combine prefix a (lower limbs) then b (higher limbs)
        ga, pa = a
        gb, pb = b
        return jnp.logical_or(gb, jnp.logical_and(pb, ga)), jnp.logical_and(pa, pb)

    gi, _ = lax.associative_scan(op, (g, p), axis=-1)
    # exclusive carries: carry into limb i is the combined generate of [0, i)
    c_in = jnp.concatenate(
        [jnp.zeros_like(gi[..., :1]), gi[..., :-1]], axis=-1
    )
    out = (t + c_in.astype(ctx.dtype)) & mask
    return out, gi[..., -1].astype(ctx.dtype)


def _normalize(ctx: ModCtx, t, passes: int = 3):
    """Arbitrary accumulator-range limbs -> canonical form, (limbs, carry).
    `carry` is the total overflow out of the top limb (sum of the shift
    passes' dropped carries plus the final resolved carry) — callers doing
    mod-2^(bits*width) arithmetic ignore it. `passes` must take the input
    down to < 2^(limb_bits+1) before the Kogge resolution: 3 covers full
    accumulator range; 1 suffices for sums of a few canonical values."""
    cs = []
    for _ in range(passes):
        t, c = _shift_carries(ctx, t)
        cs.append(c)
    out, c_final = _kogge_resolve(ctx, t)
    return out, sum(cs) + c_final


def _carry_pass(ctx: ModCtx, a):
    """Normalize limbs, dropping the final carry (value must fit)."""
    out, _ = _normalize(ctx, a)
    return out


@functools.lru_cache(maxsize=None)
def _one_hot0(n_limbs: int, np_dtype) -> np.ndarray:
    out = np.zeros(n_limbs, np_dtype)
    out[0] = 1
    return out


@functools.lru_cache(maxsize=None)
def _r_minus_m(ctx: ModCtx) -> np.ndarray:
    """R - modulus as limbs (R = 2^(limb_bits*n))."""
    r = 1 << (ctx.limb_bits * ctx.n_limbs)
    return int_to_limbs(r - ctx.modulus, ctx.n_limbs, ctx.limb_bits, ctx.np_dtype)




# ---------------------------------------------------------------------------
# Modular add / sub / neg / select
#
# One stacked normalize per op: the raw result and its modulus-adjusted
# twin are normalized together on a leading stack axis, then selected by
# the twin's carry-out. Compared to normalize-then-conditionally-subtract
# (two sequential normalizes), this halves the op count of the single
# hottest subgraph in the whole engine — adds/subs outnumber multiplies
# ~4:1 in the tower/pairing code. Precondition (asserted in make_ctx):
# 2*modulus < R, so a+b never carries out of the top limb on its own.
# ---------------------------------------------------------------------------


def _add_many(ctx: ModCtx, pairs):
    """Batched modular adds: one stacked normalize for any number of
    independent (a, b) additions. Returns a list of canonical results."""
    if not pairs:
        return []
    rm = jnp.asarray(_r_minus_m(ctx))
    lanes = []
    for a, b in pairs:
        a, b = jnp.broadcast_arrays(a, b)
        s = a + b
        lanes.append(s)
        lanes.append(s + rm)  # == a + b + (R - p): carries out iff a+b >= p
    stacked = jnp.stack(jnp.broadcast_arrays(*lanes))
    out, carry = _normalize(ctx, stacked, passes=1)
    res = []
    for i in range(len(pairs)):
        raw, adj = out[2 * i], out[2 * i + 1]
        res.append(jnp.where((carry[2 * i + 1] == 1)[..., None], adj, raw))
    return res


def _sub_many(ctx: ModCtx, pairs):
    """Batched modular subs, one stacked normalize. For canonical a, b:
    lane1 = a - b + R (carries iff a >= b), lane2 = a - b + p + R."""
    if not pairs:
        return []
    mask = ctx.u(ctx.mask)
    one0 = jnp.asarray(_one_hot0(ctx.n_limbs, ctx.np_dtype))
    p = jnp.asarray(ctx.limbs)
    lanes = []
    for a, b in pairs:
        a, b = jnp.broadcast_arrays(a, b)
        z = a + (mask - b) + one0  # a - b + R limbwise (no borrows)
        lanes.append(z)
        lanes.append(z + p)
    stacked = jnp.stack(jnp.broadcast_arrays(*lanes))
    out, carry = _normalize(ctx, stacked, passes=1)
    res = []
    for i in range(len(pairs)):
        raw, adj = out[2 * i], out[2 * i + 1]
        # carry on the raw lane <=> a >= b <=> no +p needed
        res.append(jnp.where((carry[2 * i] == 1)[..., None], raw, adj))
    return res


def add_mod(ctx: ModCtx, a, b):
    return _add_many(ctx, [(a, b)])[0]


def sub_mod(ctx: ModCtx, a, b):
    return _sub_many(ctx, [(a, b)])[0]


def add_mod_many(ctx: ModCtx, pairs):
    """Independent modular adds sharing ONE stacked normalize. The tower
    code groups its adds by dependency level through this (and
    sub_mod_many) — the main lever that keeps pairing programs compilable:
    every emitted normalize is a Kogge-Stone subgraph, so op count scales
    with dependency depth, not with the number of additions."""
    return _add_many(ctx, list(pairs))


def sub_mod_many(ctx: ModCtx, pairs):
    return _sub_many(ctx, list(pairs))


def addsub_mod_many(ctx: ModCtx, add_pairs, sub_pairs):
    """Adds and subs together in ONE stacked normalize."""
    add_pairs, sub_pairs = list(add_pairs), list(sub_pairs)
    if not add_pairs and not sub_pairs:
        return [], []
    rm = jnp.asarray(_r_minus_m(ctx))
    mask = ctx.u(ctx.mask)
    one0 = jnp.asarray(_one_hot0(ctx.n_limbs, ctx.np_dtype))
    p = jnp.asarray(ctx.limbs)
    lanes = []
    for a, b in add_pairs:
        a, b = jnp.broadcast_arrays(a, b)
        s = a + b
        lanes += [s, s + rm]
    for a, b in sub_pairs:
        a, b = jnp.broadcast_arrays(a, b)
        z = a + (mask - b) + one0
        lanes += [z, z + p]
    out, carry = _normalize(ctx, jnp.stack(jnp.broadcast_arrays(*lanes)), passes=1)
    res_add, res_sub = [], []
    for i in range(len(add_pairs)):
        raw, adj = out[2 * i], out[2 * i + 1]
        res_add.append(jnp.where((carry[2 * i + 1] == 1)[..., None], adj, raw))
    off = 2 * len(add_pairs)
    for i in range(len(sub_pairs)):
        raw, adj = out[off + 2 * i], out[off + 2 * i + 1]
        res_sub.append(jnp.where((carry[off + 2 * i] == 1)[..., None], raw, adj))
    return res_add, res_sub


def neg_mod(ctx: ModCtx, a):
    return sub_mod(ctx, jnp.zeros_like(a), a)


def double_mod(ctx: ModCtx, a):
    return add_mod(ctx, a, a)


def triple_mod(ctx: ModCtx, a):
    return add_mod(ctx, double_mod(ctx, a), a)


def is_zero(a):
    """Boolean mask over batch dims: element == 0 (must be reduced)."""
    return jnp.all(a == 0, axis=-1)


def select(mask, a, b):
    """Elementwise: mask ? a : b, with mask over batch dims."""
    return jnp.where(mask[..., None], a, b)


def zeros(ctx: ModCtx, batch_shape=()):
    return jnp.zeros((*batch_shape, ctx.n_limbs), ctx.dtype)


def match_vary(arr, template):
    """Give a constant-built limb array the same shard_map varying axes
    as `template` (adds template * 0 — exact for unsigned limbs, folded
    away by XLA). lax.scan under shard_map requires carry init and carry
    output to agree on varying manual axes, so constant scan inits
    (fp12_one, identity points) must inherit the inputs' axes."""
    return arr + template * jnp.zeros((), template.dtype)


def const(ctx: ModCtx, value: int, batch_shape=()):
    """Montgomery-form constant broadcast to a batch shape."""
    limbs = int_to_limbs(
        value % ctx.modulus * ctx.r_mont % ctx.modulus,
        ctx.n_limbs,
        ctx.limb_bits,
        ctx.np_dtype,
    )
    return jnp.broadcast_to(jnp.asarray(limbs), (*batch_shape, ctx.n_limbs))


# ---------------------------------------------------------------------------
# Montgomery multiplication
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _band_index(n: int, out_cols: int):
    """idx[i, k] = k - i clipped to [0, n-1], valid[i, k] = 0 <= k-i < n.

    Used to express the schoolbook product as ONE gather + ONE contraction
    instead of n scatter-adds: t[..., k] = sum_i a_i * b_{k-i}. Keeping the
    hot multiply at ~3 ops (vs ~n dynamic-update-slices) is what makes the
    pairing kernel's scan body compilable in seconds instead of minutes on
    TPU (XLA optimization time scales with scan-body op count)."""
    idx = np.zeros((n, out_cols), np.int32)
    valid = np.zeros((n, out_cols), bool)
    for i in range(n):
        for k in range(out_cols):
            j = k - i
            if 0 <= j < n:
                idx[i, k] = j
                valid[i, k] = True
    return idx, valid


def _conv(ctx: ModCtx, a, b, out_cols: int):
    """Banded product t[..., k] = sum_{i+j=k} a_i * b_j over out_cols
    columns. Column sums stay within the accumulator headroom (asserted in
    make_ctx), so no mid-loop carries."""
    n = ctx.n_limbs
    idx, valid = _band_index(n, out_cols)
    # b_shift[..., i, k] = b[..., k-i] (zero outside the band)
    b_shift = jnp.where(
        jnp.asarray(valid), b[..., jnp.asarray(idx)], ctx.u(0)
    )
    # contraction over the limb axis i: (..., i) x (..., i, k) -> (..., k)
    return jnp.einsum("...i,...ik->...k", a, b_shift)


def _conv_full(ctx: ModCtx, a, b):
    """Schoolbook product into 2n columns."""
    return _conv(ctx, a, b, 2 * ctx.n_limbs)


def _conv_low(ctx: ModCtx, a, b):
    """Low n columns of the product (mod 2^(limb_bits*n))."""
    return _conv(ctx, a, b, ctx.n_limbs)


# Pallas kernel dispatch: None = auto (on for the uint32 geometry when
# the default backend is a real TPU), True/False = forced. The fused
# kernel keeps the whole multiply in VMEM — the jnp path's band-matrix
# intermediates make it HBM-bound (see ops/pallas_mont.py).
_PALLAS_MODE: bool | None = None


def set_pallas(mode: bool | None) -> None:
    global _PALLAS_MODE
    _PALLAS_MODE = mode


def _pallas_active(ctx: ModCtx) -> bool:
    if ctx.np_dtype is not np.uint32:
        return False
    if _PALLAS_MODE is not None:
        return _PALLAS_MODE
    return _is_tpu_backend()


# int8-MXU dispatch (ops/limb_mxu.py): opt-in until measured on real TPU
# (call set_mxu(True); bench.py exposes it as BENCH_MXU=1, and the
# startup tuner owns it via core/autotune.KernelConfig — the legacy
# CHARON_MXU_MONT env toggle folds in there as an explicit override, so
# this hot path no longer reads the environment). Takes precedence over
# the Pallas kernel when enabled so the two lowerings can be A/B'd from
# the same bench invocation.
_MXU_MODE: bool | None = None


def set_mxu(mode: bool | None) -> None:
    global _MXU_MODE
    _MXU_MODE = mode


def _mxu_active(ctx: ModCtx) -> bool:
    if ctx.limb_bits != 12:
        return False
    if _MXU_MODE is not None:
        return _MXU_MODE
    return False


def mont_mul(ctx: ModCtx, a, b):
    """a * b * R^-1 mod m for reduced Montgomery-form inputs.

    Separated-operand Montgomery (TPU-first — every step parallel over the
    limb axis, no sequential reduction rounds):

        t = a * b                      (conv, 2n columns)
        m = (t mod R) * (-m^-1 mod R)  (low conv, n columns)
        s = t + m * p                  (conv + add; s ≡ 0 mod R)
        result = s / R  (high half)    (< 2m, one conditional subtract)

    Three convolutions + parallel carry normalization replace the n-round
    scan: ~10x fewer XLA ops and no serialization on the limb axis.
    """
    if _mxu_active(ctx):
        # with Pallas also active, the Toeplitz matmuls are issued from
        # inside the fused kernel (int8 pieces stay in VMEM); Pallas-off
        # keeps the XLA-level lowering as the A/B reference
        if _pallas_active(ctx):
            from charon_tpu.ops.pallas_mont import mont_mul_pallas

            return mont_mul_pallas(ctx, a, b, mxu=True)
        from charon_tpu.ops.limb_mxu import mont_mul_mxu

        return mont_mul_mxu(ctx, a, b)
    if _pallas_active(ctx):
        from charon_tpu.ops.pallas_mont import mont_mul_pallas

        return mont_mul_pallas(ctx, a, b)
    a, b = jnp.broadcast_arrays(a, b)
    n = ctx.n_limbs
    t = _conv_full(ctx, a, b)
    t, _ = _normalize(ctx, t)
    m = _conv_low(ctx, t[..., :n], jnp.asarray(ctx.ninv))
    m, _ = _normalize(ctx, m)  # mod R: top carry intentionally dropped
    s = t + _conv_full(ctx, m, jnp.asarray(ctx.limbs))
    return _mont_tail(ctx, s)


def _mont_tail(ctx: ModCtx, s):
    """Shared Montgomery tail (also used by ops/limb_mxu): s ≡ 0 mod R in
    accumulator range -> canonical high half, with the final conditional
    subtract fused into the last normalize — lane2 adds (R - p) into the
    high columns, so its carry-out says hi >= p; one stacked normalize
    replaces normalize + cond_sub."""
    n = ctx.n_limbs
    rm_hi = jnp.zeros(2 * n, ctx.np_dtype).at[n:].set(
        jnp.asarray(_r_minus_m(ctx))
    )
    stacked = jnp.stack(jnp.broadcast_arrays(s, s + rm_hi))
    out, carry = _normalize(ctx, stacked)
    return jnp.where(
        (carry[1] == 1)[..., None], out[1, ..., n:], out[0, ..., n:]
    )


def mont_sqr(ctx: ModCtx, a):
    return mont_mul(ctx, a, a)


def to_mont(ctx: ModCtx, a):
    """Raw limbs (< m) -> Montgomery form, on device."""
    return mont_mul(ctx, a, jnp.asarray(ctx.r2))


def from_mont(ctx: ModCtx, a):
    """Montgomery form -> raw limbs, on device."""
    one = jnp.zeros_like(a).at[..., 0].set(ctx.u(1))
    return mont_mul(ctx, a, one)


# ---------------------------------------------------------------------------
# Exponentiation by a static exponent (lax.scan over its bits)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _exp_bits(exponent: int):
    """MSB-first bit array of a static exponent."""
    return np.array([int(c) for c in bin(exponent)[2:]], np.uint8)


def mont_pow(ctx: ModCtx, a, exponent: int):
    """a^exponent (Montgomery in, Montgomery out), square-and-multiply as a
    scan over the (static) exponent bits."""
    if exponent == 0:
        return jnp.broadcast_to(jnp.asarray(ctx.mont_one), a.shape)
    bits = jnp.asarray(_exp_bits(exponent))

    def step(acc, bit):
        acc = mont_sqr(ctx, acc)
        mul = mont_mul(ctx, acc, a)
        return jnp.where(bit != 0, mul, acc), None

    # First bit is the leading 1: start from a directly.
    acc, _ = lax.scan(step, a, bits[1:])
    return acc


def inv_mod(ctx: ModCtx, a):
    """a^-1 via Fermat (Montgomery in/out). 0 maps to 0."""
    return mont_pow(ctx, a, ctx.modulus - 2)


# ---------------------------------------------------------------------------
# Field contexts
# ---------------------------------------------------------------------------

# CPU-friendly geometry: 24-bit limbs in uint64.
#   Fp: 381 bits -> 16 x 24 = 384 bits (3 bits headroom)
#   Fr: 255 bits -> 11 x 24 = 264 bits
FP = make_ctx("fp", P, 16)
FR = make_ctx("fr", FR_MOD, 11)

# TPU-friendly geometry: 12-bit limbs in uint32 (TPUs lack native 64-bit
# integer units; uint64 ops are emulated and slow there).
#   Fp: 32 x 12 = 384 bits; Fr: 22 x 12 = 264 bits
FP32 = make_ctx("fp32", P, 32, limb_bits=12, np_dtype=np.uint32)
FR32 = make_ctx("fr32", FR_MOD, 22, limb_bits=12, np_dtype=np.uint32)


def _is_tpu_backend() -> bool:
    """True when the default device is a TPU — including TPUs exposed via
    alternative PJRT plugins whose platform name is not literally "tpu"
    (e.g. tunneled plugins reporting device_kind "TPU v5 lite")."""
    if jax.default_backend() == "tpu":
        return True
    try:
        d = jax.devices()[0]
        return "tpu" in f"{d.platform} {d.device_kind}".lower()
    except Exception:
        return False


def default_fp_ctx() -> ModCtx:
    """Pick the Fp context matching the default JAX backend."""
    return FP32 if _is_tpu_backend() else FP


def default_fr_ctx() -> ModCtx:
    return FR32 if _is_tpu_backend() else FR


def pack_mont_host(ctx: ModCtx, values) -> np.ndarray:
    """Host-side convenience: ints -> Montgomery limb array (host bigint
    conversion; prefer to_mont-on-device for large batches)."""
    r = ctx.r_mont
    return ctx_pack(ctx, (v % ctx.modulus * r % ctx.modulus for v in values))


def unpack_mont_host(ctx: ModCtx, arr) -> list[int]:
    rinv = pow(ctx.r_mont, -1, ctx.modulus)
    return [v * rinv % ctx.modulus for v in ctx_unpack(ctx, arr)]
