"""Batched multi-limb Montgomery arithmetic for big prime fields on TPU.

Representation: an element of Z/m is a little-endian vector of `n_limbs`
24-bit limbs stored as uint64, shape (..., n_limbs); leading axes are batch
axes. All public ops accept arbitrary broadcastable batch shapes and keep
values fully reduced (< m).

Why 24-bit limbs:
  * products of two limbs are < 2^48, so a full 16-term schoolbook column
    plus Montgomery additions stays < 2^54 — far from uint64 overflow,
    which means NO carry normalization is needed inside the hot loops
    (one carry pass at the end of a multiply);
  * 24 bits = 3 bytes, so host packing is a pure-numpy byte reshuffle;
  * 24 = 3 x 8 keeps a future Pallas int8-MXU decomposition aligned.

Montgomery domain: R = 2^(24 * n_limbs). `mont_mul(a, b) = a*b*R^-1 mod m`.
Values enter the domain with `to_mont` (device) and leave with `from_mont`.

This file is generic over the modulus (instantiated for BLS12-381 Fp and Fr
at the bottom) and is the device-side counterpart of
charon_tpu/crypto/fields.py, which serves as its correctness oracle.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from charon_tpu.crypto.fields import P, R as FR_MOD

LIMB_BITS = 24
LIMB_BYTES = 3
MASK = (1 << LIMB_BITS) - 1

_U64 = jnp.uint64


def _u(x):
    """Python int -> uint64 scalar constant."""
    return jnp.uint64(x)


@dataclasses.dataclass(frozen=True)
class ModCtx:
    """Everything the device needs to do arithmetic mod `modulus`."""

    name: str
    modulus: int
    n_limbs: int
    limbs: np.ndarray  # (n_limbs,) uint64 — the modulus
    pinv: int  # -modulus^-1 mod 2^24
    r2: np.ndarray  # (n_limbs,) — R^2 mod m (to_mont multiplier)
    mont_one: np.ndarray  # (n_limbs,) — R mod m (1 in Montgomery form)

    @property
    def r_mont(self) -> int:
        return (1 << (LIMB_BITS * self.n_limbs)) % self.modulus


def int_to_limbs(x: int, n_limbs: int) -> np.ndarray:
    out = np.empty(n_limbs, np.uint64)
    for i in range(n_limbs):
        out[i] = (x >> (LIMB_BITS * i)) & MASK
    return out


def make_ctx(name: str, modulus: int, n_limbs: int) -> ModCtx:
    if modulus.bit_length() > LIMB_BITS * n_limbs - 2:
        raise ValueError("need >= 2 bits of headroom above the modulus")
    r = 1 << (LIMB_BITS * n_limbs)
    return ModCtx(
        name=name,
        modulus=modulus,
        n_limbs=n_limbs,
        limbs=int_to_limbs(modulus, n_limbs),
        pinv=(-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS),
        r2=int_to_limbs(r * r % modulus, n_limbs),
        mont_one=int_to_limbs(r % modulus, n_limbs),
    )


# ---------------------------------------------------------------------------
# Host <-> device packing (pure numpy, byte-aligned thanks to 24-bit limbs)
# ---------------------------------------------------------------------------


def pack(values, n_limbs: int) -> np.ndarray:
    """List/iterable of ints -> (N, n_limbs) uint64 limb array."""
    vals = list(values)
    nbytes = n_limbs * LIMB_BYTES
    buf = b"".join(int(v).to_bytes(nbytes, "little") for v in vals)
    raw = np.frombuffer(buf, np.uint8).reshape(len(vals), n_limbs, LIMB_BYTES)
    raw = raw.astype(np.uint64)
    return raw[..., 0] | (raw[..., 1] << np.uint64(8)) | (raw[..., 2] << np.uint64(16))


def unpack(arr) -> list[int]:
    """(..., n_limbs) limb array -> flat list of ints (C-order batch)."""
    arr = np.asarray(arr, np.uint64).reshape(-1, np.shape(arr)[-1])
    out = []
    for row in arr:
        v = 0
        for i, limb in enumerate(row):
            v |= int(limb) << (LIMB_BITS * i)
        out.append(v)
    return out


# ---------------------------------------------------------------------------
# Carry / borrow scans along the limb axis
# ---------------------------------------------------------------------------


def _carry_pass(a):
    """Normalize limbs to < 2^24, propagating carries. Assumes the true
    value fits in n_limbs limbs (carry out of the top limb would be lost)."""
    xs = jnp.moveaxis(a, -1, 0)

    def step(c, x):
        x = x + c
        return x >> LIMB_BITS, x & _u(MASK)

    _, ys = lax.scan(step, jnp.zeros(a.shape[:-1], _U64), xs)
    return jnp.moveaxis(ys, 0, -1)


def _sub_borrow(a, b):
    """(a - b) mod 2^(24n) limbwise, plus the final borrow flag (1 if a<b).

    Inputs must be normalized (< 2^24 per limb)."""
    xs = jnp.moveaxis(jnp.stack([a, b], axis=0), -1, 0)  # (L, 2, ...)

    def step(borrow, x):
        d = x[0] + _u(1 << LIMB_BITS) - x[1] - borrow
        return _u(1) - (d >> LIMB_BITS), d & _u(MASK)

    borrow, ys = lax.scan(step, jnp.zeros(a.shape[:-1], _U64), xs)
    return jnp.moveaxis(ys, 0, -1), borrow


def _cond_sub(ctx: ModCtx, a):
    """a - m if a >= m else a, for normalized a < 2m."""
    p = jnp.asarray(ctx.limbs)
    d, borrow = _sub_borrow(a, jnp.broadcast_to(p, a.shape))
    return jnp.where((borrow == 0)[..., None], d, a)


# ---------------------------------------------------------------------------
# Modular add / sub / neg / select
# ---------------------------------------------------------------------------


def add_mod(ctx: ModCtx, a, b):
    return _cond_sub(ctx, _carry_pass(a + b))


def sub_mod(ctx: ModCtx, a, b):
    a, b = jnp.broadcast_arrays(a, b)
    d, borrow = _sub_borrow(a, b)
    p = jnp.asarray(ctx.limbs)
    d_plus_p = _carry_pass(d + p)  # wraps mod 2^(24n): == a - b + m
    return jnp.where((borrow == 1)[..., None], d_plus_p, d)


def neg_mod(ctx: ModCtx, a):
    return sub_mod(ctx, jnp.zeros_like(a), a)


def double_mod(ctx: ModCtx, a):
    return add_mod(ctx, a, a)


def triple_mod(ctx: ModCtx, a):
    return add_mod(ctx, double_mod(ctx, a), a)


def is_zero(a):
    """Boolean mask over batch dims: element == 0 (must be reduced)."""
    return jnp.all(a == 0, axis=-1)


def select(mask, a, b):
    """Elementwise: mask ? a : b, with mask over batch dims."""
    return jnp.where(mask[..., None], a, b)


def zeros(ctx: ModCtx, batch_shape=()):
    return jnp.zeros((*batch_shape, ctx.n_limbs), _U64)


def const(ctx: ModCtx, value: int, batch_shape=()):
    """Montgomery-form constant broadcast to a batch shape."""
    limbs = int_to_limbs(value * ctx.r_mont % ctx.modulus, ctx.n_limbs)
    return jnp.broadcast_to(jnp.asarray(limbs), (*batch_shape, ctx.n_limbs))


# ---------------------------------------------------------------------------
# Montgomery multiplication
# ---------------------------------------------------------------------------


def mont_mul(ctx: ModCtx, a, b):
    """a * b * R^-1 mod m for reduced Montgomery-form inputs.

    Schoolbook product into 2n columns (each < 2^53 — no mid-loop carries
    needed), then n word-reduction rounds as a scan, shifting one limb per
    round, then one carry pass and one conditional subtract.
    """
    a, b = jnp.broadcast_arrays(a, b)
    n = ctx.n_limbs
    outer = a[..., :, None] * b[..., None, :]  # (..., n, n)
    t = jnp.zeros(a.shape[:-1] + (2 * n,), _U64)
    for i in range(n):
        t = t.at[..., i : i + n].add(outer[..., i, :])

    p = jnp.asarray(ctx.limbs)
    pinv = _u(ctx.pinv)

    def round_(t, _):
        m = (t[..., 0] * pinv) & _u(MASK)
        t = t.at[..., :n].add(m[..., None] * p)
        carry = t[..., 0] >> LIMB_BITS
        t = jnp.concatenate([t[..., 1:], jnp.zeros_like(t[..., :1])], axis=-1)
        t = t.at[..., 0].add(carry)
        return t, None

    t, _ = lax.scan(round_, t, None, length=n)
    return _cond_sub(ctx, _carry_pass(t[..., :n]))


def mont_sqr(ctx: ModCtx, a):
    return mont_mul(ctx, a, a)


def to_mont(ctx: ModCtx, a):
    """Raw limbs (< m) -> Montgomery form, on device."""
    return mont_mul(ctx, a, jnp.asarray(ctx.r2))


def from_mont(ctx: ModCtx, a):
    """Montgomery form -> raw limbs, on device."""
    one = jnp.zeros_like(a).at[..., 0].set(_u(1))
    return mont_mul(ctx, a, one)


# ---------------------------------------------------------------------------
# Exponentiation by a static exponent (lax.scan over its bits)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _exp_bits(exponent: int):
    """MSB-first bit array of a static exponent."""
    return np.array([int(c) for c in bin(exponent)[2:]], np.uint8)


def mont_pow(ctx: ModCtx, a, exponent: int):
    """a^exponent (Montgomery in, Montgomery out), square-and-multiply as a
    scan over the (static) exponent bits."""
    if exponent == 0:
        return jnp.broadcast_to(jnp.asarray(ctx.mont_one), a.shape)
    bits = jnp.asarray(_exp_bits(exponent))

    def step(acc, bit):
        acc = mont_sqr(ctx, acc)
        mul = mont_mul(ctx, acc, a)
        return jnp.where(bit != 0, mul, acc), None

    # First bit is the leading 1: start from a directly.
    acc, _ = lax.scan(step, a, bits[1:])
    return acc


def inv_mod(ctx: ModCtx, a):
    """a^-1 via Fermat (Montgomery in/out). 0 maps to 0."""
    return mont_pow(ctx, a, ctx.modulus - 2)


# ---------------------------------------------------------------------------
# Field contexts
# ---------------------------------------------------------------------------

# Fp: 381 bits -> 16 x 24 = 384 bits (2 bits headroom? 384-381=3 ✓)
FP = make_ctx("fp", P, 16)
# Fr: 255 bits -> 11 x 24 = 264 bits
FR = make_ctx("fr", FR_MOD, 11)


def pack_mont_host(ctx: ModCtx, values) -> np.ndarray:
    """Host-side convenience: ints -> Montgomery limb array (host bigint
    conversion; prefer to_mont-on-device for large batches)."""
    r = ctx.r_mont
    return pack((v % ctx.modulus * r % ctx.modulus for v in values), ctx.n_limbs)


def unpack_mont_host(ctx: ModCtx, arr) -> list[int]:
    rinv = pow(ctx.r_mont, -1, ctx.modulus)
    return [v * rinv % ctx.modulus for v in unpack(arr)]
