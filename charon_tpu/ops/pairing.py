"""Batched optimal-ate pairing for BLS12-381 on the limb engine.

Device-side counterpart of charon_tpu/crypto/pairing_fast.py (the validated
scalar specification): projective Miller loop with unnormalized sparse
lines, and an x-chain final exponentiation computing f^(3h) via the BLS12
lattice identity — sound for every product-of-pairings == 1 check.

Batch semantics: every function maps over arbitrary leading batch axes. A
"pair" is (p, q) with p a batched affine G1 point (Fp limb pair) and q a
batched affine G2 point (Fp2 pair). Identity lanes (encoded affine (0, 0))
contribute the neutral line, so e(identity, q) == 1 per lane — matching the
aggregate-verify semantics the workflow needs.

Control flow is XLA-friendly: the Miller loop is a lax.scan over the static
64-bit BLS parameter schedule with lax.cond for the sparse add steps (only
6 of 63 bits are set), and the final exponentiation's x-chains are scans
with Granger–Scott cyclotomic squarings.

Replaces (batched) what the reference does one-signature-at-a-time through
herumi's pairing (ref: tbls/herumi.go:288 Verify, tbls/herumi.go:318
VerifyAggregate).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from charon_tpu.crypto.fields import P, X_ABS, X_IS_NEG
from charon_tpu.crypto import g1g2 as REF
from charon_tpu.ops import curve as C
from charon_tpu.ops import fptower as T
from charon_tpu.ops import limb
from charon_tpu.ops.limb import ModCtx

# Miller-loop schedule: bits of |x| below the leading one, MSB first.
X_BITS = np.array([int(b) for b in bin(X_ABS)[3:]], np.uint8)
# Full bit string of |x| (used by the cyclotomic x-powers).
X_BITS_FULL = np.array([int(b) for b in bin(X_ABS)[2:]], np.uint8)


# ---------------------------------------------------------------------------
# Sparse line multiplication: f * (l0 + l1 v w + l2 v^2 w)
# ---------------------------------------------------------------------------


def fp12_mul_sparse_line(ctx, f, l0, l1, l2):
    """18 fp2 muls vs 36 for a dense fp12 mul (spec: pairing_fast.py:79) —
    all independent, executed as ONE stacked base mul; the combine runs in
    three stacked add levels (sums, xi twists, final adds):
        c0 = (p0 + xi(p7+p8), p1 + xi(p3+p4), p2 + p5 + xi p6)
        c1 = (xi(p9+p10) + p15, p11 + xi p12 + p16, p13 + p14 + p17)
    """
    (a0, a1, a2), (b0, b1, b2) = f

    p = T.fp2_mul_many(
        ctx,
        [
            (a0, l0), (a1, l0), (a2, l0),          # t0
            (b1, l2), (b2, l1), (b0, l1), (b2, l2), (b0, l2), (b1, l1),  # t1
            (a1, l2), (a2, l1), (a0, l1), (a2, l2), (a0, l2), (a1, l1),  # a*L1
            (b0, l0), (b1, l0), (b2, l0),          # b*L0
        ],
    )
    s78, s34, s910, s25, s1116, s1314 = T.fp2_add_many(
        ctx,
        [
            (p[7], p[8]),
            (p[3], p[4]),
            (p[9], p[10]),
            (p[2], p[5]),
            (p[11], p[16]),
            (p[13], p[14]),
        ],
    )
    x78, x34, x6, x910, x12 = T.fp2_mul_xi_many(
        ctx, [s78, s34, p[6], s910, p[12]]
    )
    c = T.fp2_add_many(
        ctx,
        [
            (p[0], x78),
            (p[1], x34),
            (s25, x6),
            (x910, p[15]),
            (s1116, x12),
            (s1314, p[17]),
        ],
    )
    return ((c[0], c[1], c[2]), (c[3], c[4], c[5]))


# ---------------------------------------------------------------------------
# Projective Miller-loop steps (spec: pairing_fast.py:120,149)
# ---------------------------------------------------------------------------


def _dbl_step(ctx, t, xp, yp):
    """Double T and return the tangent line at P=(xp, yp) (batched Fp).

    Three stacked levels (spec: pairing_fast.py:120 — identical algebra)."""
    sub = functools.partial(T.fp2_sub, ctx)
    small = functools.partial(T.fp2_small, ctx)

    x, y, z = t
    xx, y2, s, xy = T.fp2_batch(
        ctx, [("sqr", x), ("sqr", y), ("mul", y, z), ("mul", x, y)]
    )
    w = small(xx, 3)

    w2, bb, ss, sz, y2z, wx, wz = T.fp2_batch(
        ctx,
        [
            ("sqr", w),
            ("mul", xy, s),
            ("sqr", s),
            ("mul", s, z),
            ("mul", y2, z),
            ("mul", w, x),
            ("mul", w, z),
        ],
    )
    h = sub(w2, small(bb, 8))

    two_yp = limb.double_mod(ctx, yp)
    hs, wb, y2ss, sss, l0raw, l2 = T.fp2_batch(
        ctx,
        [
            ("mul", h, s),
            ("mul", w, sub(small(bb, 4), h)),
            ("mul", y2, ss),
            ("mul", s, ss),
            ("mul_fp", sz, two_yp),
            ("mul_fp", wz, limb.neg_mod(ctx, xp)),
        ],
    )
    x3 = T.fp2_double(ctx, hs)
    y3 = sub(wb, small(y2ss, 8))
    z3 = small(sss, 8)
    l0 = T.fp2_mul_xi(ctx, l0raw)
    l1 = sub(wx, T.fp2_double(ctx, y2z))
    return (x3, y3, z3), (l0, l1, l2)


def _add_step(ctx, t, q, xp, yp):
    """Mixed add T + affine Q; chord line at P=(xp, yp). Four stacked
    levels (spec: pairing_fast.py:149 — identical algebra)."""
    sub = functools.partial(T.fp2_sub, ctx)
    add = functools.partial(T.fp2_add, ctx)

    x, y, z = t
    x2, y2 = q
    y2z, x2z = T.fp2_mul_many(ctx, [(y2, z), (x2, z)])
    theta = sub(y, y2z)
    lam = sub(x, x2z)

    lam2, theta2, tx2, ly2, l0raw, l2 = T.fp2_batch(
        ctx,
        [
            ("sqr", lam),
            ("sqr", theta),
            ("mul", theta, x2),
            ("mul", lam, y2),
            ("mul_fp", lam, yp),
            ("mul_fp", theta, limb.neg_mod(ctx, xp)),
        ],
    )
    l0 = T.fp2_mul_xi(ctx, l0raw)
    l1 = sub(tx2, ly2)

    lam3, theta2z, lam2x = T.fp2_mul_many(
        ctx, [(lam2, lam), (theta2, z), (lam2, x)]
    )
    ww = add(sub(theta2z, T.fp2_double(ctx, lam2x)), lam3)

    x3, tt, lam3y, z3 = T.fp2_batch(
        ctx,
        [
            ("mul", lam, ww),
            ("mul", theta, sub(lam2x, ww)),
            ("mul", lam3, y),
            ("mul", lam3, z),
        ],
    )
    y3 = sub(tt, lam3y)
    return (x3, y3, z3), (l0, l1, l2)


def _neutral_line(ctx, batch_shape):
    return (
        T.fp2_one(ctx, batch_shape),
        T.fp2_zero(ctx, batch_shape),
        T.fp2_zero(ctx, batch_shape),
    )


def _mask_line(ctx, dead_mask, line, batch_shape):
    """Force identity-member pairs to contribute the neutral line l = 1."""
    neutral = _neutral_line(ctx, batch_shape)
    return tuple(
        T.fp2_select(dead_mask, n, l) for n, l in zip(neutral, line)
    )


def miller_loop(ctx: ModCtx, pairs):
    """Product of Miller loops over a static list of batched (p, q) pairs.

    p: affine G1 (x, y) Fp limb arrays; q: affine G2 (x, y) Fp2 elements.
    Affine (0, 0) lanes are identities and contribute 1.

    Multiple pairs are STACKED onto one extra leading axis and run as
    independent per-lane Miller loops, combined with a single fp12 mul at
    the end (valid since the final exponentiation distributes over the
    product). This keeps the scan body at ONE doubling step + ONE sparse
    multiply regardless of len(pairs) — the body op count, not the
    iteration count, is what dominates XLA compile time.
    """
    if len(pairs) > 1:
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(jnp.broadcast_arrays(*xs)), *pairs
        )
        lanes = miller_loop(ctx, [stacked])
        f = jax.tree_util.tree_map(lambda a: a[0], lanes)
        for i in range(1, len(pairs)):
            f = T.fp12_mul(
                ctx, f, jax.tree_util.tree_map(lambda a: a[i], lanes)
            )
        return f

    ((p, q),) = pairs
    batch_shape = p[0].shape[:-1]
    dead = jnp.logical_and(limb.is_zero(p[0]), limb.is_zero(p[1])) | (
        jnp.logical_and(T.fp2_is_zero(q[0]), T.fp2_is_zero(q[1]))
    )

    # constant scan-carry inits inherit the inputs' shard_map varying
    # axes (see limb.match_vary)
    vary = functools.partial(limb.match_vary, template=q[0][0])
    t0 = (
        q[0],
        q[1],
        jax.tree_util.tree_map(vary, T.fp2_one(ctx, batch_shape)),
    )
    f0 = jax.tree_util.tree_map(vary, T.fp12_one(ctx, batch_shape))
    bits = jnp.asarray(X_BITS)

    def dbl(carry):
        f, t = carry
        t2, line = _dbl_step(ctx, t, p[0], p[1])
        line = _mask_line(ctx, dead, line, batch_shape)
        return fp12_mul_sparse_line(ctx, f, *line), t2

    def add(carry):
        f, t = carry
        t2, line = _add_step(ctx, t, q, p[0], p[1])
        line = _mask_line(ctx, dead, line, batch_shape)
        return fp12_mul_sparse_line(ctx, f, *line), t2

    def step(carry, bit):
        carry = dbl((T.fp12_sqr(ctx, carry[0]), carry[1]))
        carry = lax.cond(bit != 0, add, lambda c: c, carry)
        return carry, None

    # First schedule entry skips the squaring (f == 1 — squaring is a no-op,
    # so we just run the uniform step).
    (f, _), _ = lax.scan(step, (f0, t0), bits)
    if X_IS_NEG:
        f = T.fp12_conj(ctx, f)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation (spec: pairing_fast.py:211-244)
# ---------------------------------------------------------------------------


def _cyc_pow_u(ctx, f):
    """f^|x| in the cyclotomic subgroup: scan over the bits of |x| with
    Granger–Scott squarings and a selected multiply (6 of 64 bits set)."""
    bits = jnp.asarray(X_BITS_FULL[1:])  # leading 1: start from f

    def step(acc, bit):
        acc = T.fp12_cyclotomic_sqr(ctx, acc)
        mul = T.fp12_mul(ctx, acc, f)
        return jax.tree_util.tree_map(
            lambda m, a: jnp.where(bit != 0, m, a), mul, acc
        ), None

    acc, _ = lax.scan(step, f, bits)
    return acc


def _cyc_pow_x(ctx, f):
    out = _cyc_pow_u(ctx, f)
    return T.fp12_conj(ctx, out) if X_IS_NEG else out


def final_exp(ctx: ModCtx, f):
    """f^(3 * (p^12-1)/r): easy part, then the lattice-identity hard part."""
    # Easy part: f^((p^6-1)(p^2+1)) — lands in the cyclotomic subgroup.
    f = T.fp12_mul(ctx, T.fp12_conj(ctx, f), T.fp12_inv(ctx, f))
    m = T.fp12_mul(ctx, T.fp12_frobenius_n(ctx, f, 2), f)
    # Hard part: m^(3h) = m^((x-1)^2 (x+p) (x^2+p^2-1)) * m^3.
    a = T.fp12_mul(ctx, _cyc_pow_u(ctx, m), m)  # m^(u+1)
    a = T.fp12_mul(ctx, _cyc_pow_u(ctx, a), a)  # m^((x-1)^2)
    b = T.fp12_mul(ctx, _cyc_pow_x(ctx, a), T.fp12_frobenius(ctx, a))
    c = T.fp12_mul(
        ctx,
        T.fp12_mul(
            ctx,
            _cyc_pow_x(ctx, _cyc_pow_x(ctx, b)),
            T.fp12_frobenius_n(ctx, b, 2),
        ),
        T.fp12_conj(ctx, b),
    )
    m3 = T.fp12_mul(ctx, T.fp12_cyclotomic_sqr(ctx, m), m)
    return T.fp12_mul(ctx, c, m3)


def multi_pairing_check(ctx: ModCtx, pairs):
    """Batch mask: prod e(p_i, q_i) == 1 (computed as the cube — sound:
    GT has prime order r and gcd(3, r) = 1)."""
    f = miller_loop(ctx, pairs)
    e = final_exp(ctx, f)
    return T.fp12_is_one(ctx, e)


# ---------------------------------------------------------------------------
# BLS verification kernels (eth2 flavour: pubkeys G1, signatures/messages G2)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _neg_g1_gen_consts(ctx: ModCtx):
    x, y = REF.g1_neg(REF.G1_GEN)
    return (
        np.asarray(limb.pack_mont_host(ctx, [x])[0]),
        np.asarray(limb.pack_mont_host(ctx, [y])[0]),
    )


def neg_g1_gen(ctx: ModCtx, batch_shape=()):
    """-G1 generator broadcast to a batch shape (the fixed verify pair)."""
    x, y = _neg_g1_gen_consts(ctx)
    return (
        jnp.broadcast_to(jnp.asarray(x), (*batch_shape, ctx.n_limbs)),
        jnp.broadcast_to(jnp.asarray(y), (*batch_shape, ctx.n_limbs)),
    )


def batched_verify(ctx: ModCtx, pk, msg, sig):
    """Per-lane BLS verify: e(pk, H(m)) == e(G1, sig), i.e.
    e(pk, H(m)) * e(-G1, sig) == 1.

    pk: batched affine G1; msg: batched affine G2 (already hashed to the
    curve); sig: batched affine G2. Returns a bool mask over the batch.
    """
    batch_shape = pk[0].shape[:-1]
    return multi_pairing_check(
        ctx,
        [(pk, msg), (neg_g1_gen(ctx, batch_shape), sig)],
    )


def _fp12_prod_tree(ctx: ModCtx, f):
    """Product of a [N, ...] batch of Fp12 values over the leading axis in
    log2(N) stacked multiplies (N static; padded to a power of two with
    ones)."""

    n = jax.tree_util.tree_leaves(f)[0].shape[0]
    pow2 = 1 << (n - 1).bit_length()
    if pow2 != n:
        rest = jax.tree_util.tree_leaves(f)[0].shape[1:-1]
        ones = T.fp12_one(ctx, (pow2 - n, *rest))
        # inherit shard_map varying axes from a length-1 slice (the pad
        # block's leading dim differs from the source's)
        ones = jax.tree_util.tree_map(
            lambda o, ref: o + ref[:1] * jnp.zeros((), ref.dtype), ones, f
        )
        f = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate((a, b), axis=0), f, ones
        )
        n = pow2
    while n > 1:
        half = n // 2
        a = jax.tree_util.tree_map(lambda x: x[:half], f)
        b = jax.tree_util.tree_map(lambda x: x[half:], f)
        f = T.fp12_mul(ctx, a, b)
        n = half
    return jax.tree_util.tree_map(lambda x: x[0], f)


def _pad_pow2(C, f, pts, axis: int, n: int):
    """Pad a (possibly batched) point axis to the next power of two with
    identity points that inherit the source's shard_map varying axes."""
    pow2 = 1 << (n - 1).bit_length()
    if pow2 == n:
        return pts, n
    lead = jax.tree_util.tree_leaves(pts)[0].shape[:axis]
    ident = C.point_identity(f, (*lead, pow2 - n))

    def vary(o, ref):
        slicer = [slice(None)] * axis + [slice(0, 1)]
        return o + ref[tuple(slicer)] * jnp.zeros((), ref.dtype)

    ident = jax.tree_util.tree_map(vary, ident, pts)
    pts = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate((a, b), axis=axis), pts, ident
    )
    return pts, pow2


def _point_sum_tree(C, f, pts, n: int, axis: int = 0):
    """Log-depth pairwise sum of projective points over `axis` (any
    static size — padded to a power of two with identities; complete
    adds are identity-safe)."""
    pts, n = _pad_pow2(C, f, pts, axis, n)
    sl = lambda x, a, b: x[
        tuple([slice(None)] * axis + [slice(a, b)])
    ]
    while n > 1:
        half = n // 2
        a = jax.tree_util.tree_map(lambda x: sl(x, 0, half), pts)
        b = jax.tree_util.tree_map(lambda x: sl(x, half, None), pts)
        pts = C.point_add(f, a, b)
        n = half
    return jax.tree_util.tree_map(
        lambda x: x[tuple([slice(None)] * axis + [0])], pts
    )


def batched_verify_grouped_rlc(
    ctx: ModCtx, fr_ctx: ModCtx, pk, msg, sig, rand, nbits: int = 64
):
    """Grouped random-linear-combination batch verification:

        prod_m e( sum_{i in m} r_i * pk_i,  H(m) )  *  e(-G1, sum_i r_i * sig_i) == 1

    Layout: lanes pre-grouped by message on host — pk/sig/rand have shape
    [M, K] (M distinct messages, K lanes per group, padded with identity
    points + ZERO exponents), msg has shape [M].

    Per lane the pairing work collapses to one 64-bit G1 double-and-add
    and one 64-bit G2 double-and-add; the Miller stage runs over only
    M + 1 pairs and ONE final exponentiation — at production scale
    (thousands of partial signatures over a handful of duty roots per
    slot: every validator in a committee signs the same attestation
    data) this is ~10x fewer field ops per signature than the per-lane
    kernel, and the compiled program's Miller batch no longer grows with
    the signature count. Same 2^-nbits Schwartz-Zippel soundness as
    batched_verify_rlc (per-lane independent exponents bind each pk/sig
    pair); the construction consensus clients use for gossip batches.

    Returns a scalar bool (all-valid).
    """
    from charon_tpu.ops import curve as C

    g1f, g2f = C.g1_ops(ctx), C.g2_ops(ctx)
    m_groups, k = pk[0].shape[0], pk[0].shape[1]

    def flat2(t):
        return jax.tree_util.tree_map(
            lambda a: a.reshape(m_groups * k, *a.shape[2:]), t
        )

    rand_flat = rand.reshape(m_groups * k, -1)
    pk_proj = C.affine_to_point(g1f, flat2(pk))
    sig_proj = C.affine_to_point(g2f, flat2(sig))

    from charon_tpu.ops import msm as MSM

    if MSM.msm_active():
        # Pippenger bucket MSM shares the randomization work across
        # lanes: per-message G1 bucket sums in one segmented reduction,
        # the G2 aggregate as the single-segment case (~8x fewer
        # point-ops than per-lane double-and-add at nbits=64, w=8)
        seg = jnp.repeat(jnp.arange(m_groups, dtype=jnp.int32), k)
        buckets = MSM.msm_segmented(
            g1f, fr_ctx, pk_proj, rand_flat, seg, m_groups, nbits=nbits
        )
        s_total = MSM.msm(g2f, fr_ctx, sig_proj, rand_flat, nbits=nbits)
    else:
        # per-lane 64-bit scalar muls (zero exponents -> identity)
        pk_r = C.point_scalar_mul(g1f, fr_ctx, pk_proj, rand_flat, nbits=nbits)
        sig_r = C.point_scalar_mul(
            g2f, fr_ctx, sig_proj, rand_flat, nbits=nbits
        )

        # per-group sums over the K axis -> [M], then the G2 total over M
        def regroup(t, f):
            t = jax.tree_util.tree_map(
                lambda a: a.reshape(m_groups, k, *a.shape[1:]), t
            )
            return _point_sum_tree(C, f, t, k, axis=1)

        buckets = regroup(pk_r, g1f)  # [M] G1 projective
        sig_groups = regroup(sig_r, g2f)  # [M] G2 projective
        s_total = _point_sum_tree(C, g2f, sig_groups, m_groups)

    return grouped_rlc_check(ctx, buckets, msg, s_total)


def grouped_rlc_check(ctx: ModCtx, buckets, msgs, s_total):
    """The grouped-RLC verification equation's shared tail: per-group
    bucket pairs e(B_m, H_m) ++ ONE aggregate pair e(-G1, S), a single
    product tree and ONE final exponentiation; True iff the product is
    1. `buckets`: [M] projective G1 bucket sums; `msgs`: [M] affine G2
    message points; `s_total`: projective G2 aggregate. Soundness-
    critical — both batched_verify_grouped_rlc and the sharded mesh
    plane (parallel/mesh.py) verify through THIS function."""
    g1f, g2f = C.g1_ops(ctx), C.g2_ops(ctx)
    bucket_aff = C.point_to_affine(g1f, buckets)
    s_aff = C.point_to_affine(g2f, s_total)

    def append_lane(a, b):
        return jnp.concatenate((a, b[None, ...]), axis=0)

    neg_g = neg_g1_gen(ctx, ())
    pk_lanes = jax.tree_util.tree_map(append_lane, bucket_aff, neg_g)
    q_lanes = jax.tree_util.tree_map(append_lane, msgs, s_aff)
    f_lanes = miller_loop(ctx, [(pk_lanes, q_lanes)])  # [M+1] fp12
    f_tot = _fp12_prod_tree(ctx, f_lanes)
    e = final_exp(ctx, f_tot)
    return T.fp12_is_one(ctx, e)


def point_sum_tree(f, pts, n: int, axis: int = 0):
    """Public log-depth point reduction (pairwise complete adds)."""
    return _point_sum_tree(C, f, pts, n, axis=axis)


def batched_verify_rlc(
    ctx: ModCtx, fr_ctx: ModCtx, pk, msg, sig, rand, nbits: int = 64
):
    """Whole-batch BLS verification by random linear combination in GT:

        prod_i (e(pk_i, H(m_i)) * e(-G1, sig_i))^(r_i) == 1
      = prod_i e(pk_i^(r_i), H(m_i)) * e((-G1)^(r_i), sig_i) == 1

    with caller-supplied random nonzero `nbits`-bit exponents r_i (raw
    Fr limb array, shape [N, fr_limbs]). Lane i's verification value
    v_i = e(pk_i, H_i) * e(-G1, sig_i) is 1 iff the lane is valid, so a
    batch with any forged lane passes only with probability 2^-nbits
    over the verifier's randomness (Schwartz-Zippel in the exponent) —
    the standard batch-verification trick consensus clients use for
    gossip attestation batches. On False, re-run the per-lane
    `batched_verify` to attribute.

    Cost per lane vs batched_verify: the per-lane final exponentiation
    (the most expensive per-lane stage) is replaced by one stacked
    64-bit G1 double-and-add and a log2(N)-depth fp12 product tree with
    ONE shared final exponentiation. The Miller stage is byte-identical
    in structure (same stacked 2-pair scan), so the compiled program is
    no bigger than the per-lane kernel's.

    Returns a scalar bool (all-valid).
    """
    from charon_tpu.ops import curve as C

    g1f = C.g1_ops(ctx)

    # One stacked 64-bit scalar mul covers both G1 sides: [2, N] points
    # (pk_i and broadcast -G1), same exponent r_i for both rows.
    batch_shape = pk[0].shape[:-1]
    neg_g = neg_g1_gen(ctx, batch_shape)
    pts = jax.tree_util.tree_map(
        lambda a, b: jnp.stack(jnp.broadcast_arrays(a, b)), pk, neg_g
    )
    rand2 = jnp.stack(jnp.broadcast_arrays(rand, rand))
    scaled = C.point_scalar_mul(
        g1f, fr_ctx, C.affine_to_point(g1f, pts), rand2, nbits=nbits
    )
    aff = C.point_to_affine(g1f, scaled)
    pk_r = jax.tree_util.tree_map(lambda a: a[0], aff)
    negg_r = jax.tree_util.tree_map(lambda a: a[1], aff)

    f_lanes = miller_loop(ctx, [(pk_r, msg), (negg_r, sig)])  # [N] fp12
    f_tot = _fp12_prod_tree(ctx, f_lanes)
    e = final_exp(ctx, f_tot)
    return T.fp12_is_one(ctx, e)
