"""Batched multi-scalar multiplication: sorted-bucket Pippenger on device.

The grouped-RLC verify kernel's dominant stage is the per-lane
randomization: one 64-bit G1 and one 64-bit G2 scalar multiplication per
signature lane (`curve.point_scalar_mul` — ~64 doublings + 64 selected
additions each). At production batch sizes that stage is >99% of the
field arithmetic (the Miller/final-exp tail is fixed per distinct
message). Pippenger's bucket method shares that work ACROSS lanes: for
each w-bit window of the scalars, lanes with equal digits collapse into
one bucket sum, and the per-window bucket tables combine with
~2^w + w point-ops regardless of lane count. Total point-ops drop from
~2·nbits per lane to ~2·(nbits/w) per lane plus a fixed tail — ~8x
fewer at w = 8, nbits = 64.

TPU-first shape of the classic algorithm (GPU MSM implementations use
scatter-add into bucket memory; XLA wants batched dense ops instead):

  1. digits: [N, n_win] w-bit windows of the raw scalars;
  2. ONE flat element list over (window, lane) with composite sort key
     key = (window, segment, digit); `jnp.argsort` groups equal buckets
     into contiguous runs;
  3. a segmented inclusive scan (`lax.associative_scan` over the sorted
     points with a key-equality combine) reduces every run with
     complete-formula point adds — log-depth, fully batched, branch-free;
  4. the last element of each run is scattered into a dense
     [n_win, n_segments, 2^w] bucket table (unique targets — the scatter
     is deterministic); digit-0 buckets are dropped;
  5. the standard suffix-sum turns each window's buckets into
     sum_b b*B_b (one lax.scan, batched over windows x segments);
  6. Horner across windows: acc = 2^w*acc + W_win.

Complete projective formulas (curve.point_add) make every step total:
identity padding lanes, zero scalars, repeated points, and empty buckets
all flow through the same straight-line code — no data-dependent
branches anywhere, exactly what XLA needs (SURVEY.md design stance).

Segments: the grouped-RLC layout needs per-message G1 bucket sums, so
the kernel reduces into `segment_ids` partitions in the same sort (a
segment is just more key bits). The G2 aggregate is the n_segments = 1
case.

ref: core/sigagg/sigagg.go:84-122 is the reference's per-signature hot
path this batching replaces; the RLC construction itself is in
ops/pairing.py batched_verify_grouped_rlc.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from charon_tpu.ops import curve as C
from charon_tpu.ops import limb
from charon_tpu.ops.curve import FieldOps
from charon_tpu.ops.limb import ModCtx

_tree = jax.tree_util.tree_map


def _digits(fr_ctx: ModCtx, scalars, nbits: int, window: int):
    """Raw Fr limb array [..., n_limbs] -> [..., n_win] w-bit digits,
    little-endian windows (window 0 = least significant)."""
    shifts = jnp.arange(fr_ctx.limb_bits, dtype=scalars.dtype)
    bits = (scalars[..., None] >> shifts) & fr_ctx.u(1)
    bits = bits.reshape(*scalars.shape[:-1], -1)[..., :nbits]
    n_win = -(-nbits // window)
    pad = n_win * window - nbits
    if pad:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(*bits.shape[:-1], n_win, window).astype(jnp.int32)
    weights = (1 << jnp.arange(window, dtype=jnp.int32))[None, :]
    return jnp.sum(bits * weights, axis=-1)  # [..., n_win]


def msm_segmented(
    f: FieldOps,
    fr_ctx: ModCtx,
    points,
    scalars,
    segment_ids,
    n_segments: int,
    nbits: int = 64,
    window: int = 8,
):
    """sum_{i: segment_ids[i] == s} scalars[i] * points[i] for each s.

    points: projective pytree with leading batch axis [N]; scalars: raw
    (non-Montgomery) Fr limbs [N, n_limbs]; segment_ids: int32 [N] in
    [0, n_segments). Returns a projective pytree with batch [n_segments].
    """
    n = segment_ids.shape[0]
    n_win = -(-nbits // window)
    n_buckets = 1 << window

    digits = _digits(fr_ctx, scalars, nbits, window)  # [N, n_win]
    # flat element e = win * N + i (window-major so point index = e % N)
    win_idx = jnp.repeat(jnp.arange(n_win, dtype=jnp.int32), n)
    seg_flat = jnp.tile(segment_ids.astype(jnp.int32), n_win)
    digit_flat = digits.T.reshape(-1)  # [n_win * N]
    key = (win_idx * n_segments + seg_flat) * n_buckets + digit_flat

    order = jnp.argsort(key)
    key_sorted = key[order]
    pts_sorted = _tree(lambda a: a[order % n], points)

    def comb(a, b):
        pa, ka = a
        pb, kb = b
        return (
            C.point_select(f, ka == kb, C.point_add(f, pa, pb), pb),
            kb,
        )

    scanned, _ = lax.associative_scan(comb, (pts_sorted, key_sorted))

    # run tails -> dense bucket table (unique targets: deterministic set)
    table_size = n_win * n_segments * n_buckets
    last = jnp.concatenate(
        [key_sorted[1:] != key_sorted[:-1], jnp.array([True])]
    )
    target = jnp.where(last, key_sorted, table_size)  # non-tails -> trash

    identity_table = C.point_identity(f, (table_size + 1,))
    table = _tree(
        lambda init, v: init.at[target].set(v), identity_table, scanned
    )
    table = _tree(
        lambda a: a[:table_size].reshape(
            n_win, n_segments, n_buckets, *a.shape[1:]
        ),
        table,
    )
    # drop digit-0 buckets, reverse for the suffix scan (b = 2^w-1 .. 1)
    buckets = _tree(lambda a: jnp.flip(a[:, :, 1:], axis=2), table)

    def wstep(carry, bucket_b):  # bucket_b batched over [n_win, n_segments]
        running, acc = carry
        running = C.point_add(f, running, bucket_b)
        acc = C.point_add(f, acc, running)
        return (running, acc), None

    # scan carries must inherit the inputs' shard_map varying axes
    template = jax.tree_util.tree_leaves(buckets)[0][:, :, 0]
    init = (
        C.point_identity(f, (n_win, n_segments)),
        C.point_identity(f, (n_win, n_segments)),
    )
    init = _tree(lambda a: limb.match_vary(a, template), init)
    xs = _tree(lambda a: jnp.moveaxis(a, 2, 0), buckets)
    (_, windows), _ = lax.scan(wstep, init, xs)  # [n_win, n_segments]

    # Horner across windows, most significant first: acc = 2^w acc + W.
    # A lax.scan (not a Python unroll) keeps the compiled graph at ONE
    # w-double+add body regardless of window count — the unrolled form
    # added ~n_win*(w+1) point-op subgraphs to every MSM program and
    # dominated XLA compile time on the grouped kernels.
    acc = _tree(lambda a: a[n_win - 1], windows)

    def horner_step(carry, w_point):
        for _ in range(window):
            carry = C.point_double(f, carry)
        return C.point_add(f, carry, w_point), None

    if n_win > 1:
        xs = _tree(lambda a: jnp.flip(a[: n_win - 1], axis=0), windows)
        acc, _ = lax.scan(horner_step, acc, xs)
    return acc


def windowed_joint_mul(
    f: FieldOps,
    fr_ctx: ModCtx,
    points,
    scalars,
    nbits: int = 255,
    window: int = 4,
):
    """out[v] = sum_j scalars[v, j] * points[v, j] — the threshold-
    recombination shape: per validator, t share signatures scaled by
    255-bit Lagrange coefficients and summed.

    Pippenger needs many lanes per bucket; with only t (4..7) lanes per
    segment its bucket tables would be nearly empty, so this path uses
    the other classic batching — Straus/windowed joint multiplication:
    per-lane tables of the first 2^w multiples, then ONE shared
    doubling chain per validator with t table-gather adds per window.
    Point-ops per validator drop from t * 2 * nbits (per-lane
    double-and-add) to ~(nbits/w) * (w + t) — ~4x at t = 4, w = 4.

    points: projective pytree with batch (V, t); scalars raw Fr limbs
    (V, t, n_limbs). Returns a projective pytree with batch (V,).
    """
    digits = _digits(fr_ctx, scalars, nbits, window)  # (V, t, n_win)
    n_win = digits.shape[-1]
    t = digits.shape[-2]

    # per-lane multiple tables: T[d] = d * P, d in 0..2^w-1
    multiples = [C.point_identity(f, digits.shape[:-1]), points]
    for _ in range(2, 1 << window):
        multiples.append(C.point_add(f, multiples[-1], points))
    table = _tree(lambda *xs: jnp.stack(xs, axis=2), *multiples)
    # leaves: (V, t, 2^w, ...)

    template = jax.tree_util.tree_leaves(table)[0][:, 0, 0]
    init = _tree(
        lambda a: limb.match_vary(a, template),
        C.point_identity(f, digits.shape[:-2]),
    )

    def body(acc, digit_vt):  # digit_vt: (V, t), MSB window first
        for _ in range(window):
            acc = C.point_double(f, acc)
        for j in range(t):
            idx = digit_vt[:, j]
            pj = _tree(
                lambda a: jnp.take_along_axis(
                    a[:, j],
                    idx.reshape(idx.shape + (1,) * (a.ndim - 2)),
                    axis=1,
                ).squeeze(1),
                table,
            )
            acc = C.point_add(f, acc, pj)
        return acc, None

    xs = jnp.flip(jnp.moveaxis(digits, -1, 0), axis=0)  # MSB first
    acc, _ = lax.scan(body, init, xs)
    return acc


def msm(f: FieldOps, fr_ctx: ModCtx, points, scalars, nbits=64, window=8):
    """Single-segment convenience: sum_i scalars[i] * points[i]."""
    n = jax.tree_util.tree_leaves(points)[0].shape[0]
    seg = jnp.zeros((n,), jnp.int32)
    out = msm_segmented(
        f, fr_ctx, points, scalars, seg, 1, nbits=nbits, window=window
    )
    return _tree(lambda a: a[0], out)


_MSM_MODE: bool | None = None


def set_msm(mode: bool | None) -> None:
    """Force the grouped-RLC randomization stage onto (True) / off (False)
    the Pippenger kernel; None restores the default (on). Kernel choice
    is owned by core/autotune.KernelConfig at startup — the legacy
    CHARON_MSM env toggle is folded in there as an explicit override
    (autotune.env_overrides); this hot path no longer reads the
    environment."""
    global _MSM_MODE
    _MSM_MODE = mode


def msm_active() -> bool:
    if _MSM_MODE is not None:
        return _MSM_MODE
    return True


# --- ceremony-path routing (DKG / resharing, ISSUE 20) ---------------------
#
# The ceremony kernels (blsops commitment_eval / g1_msm) have their own
# routing flags, owned by core/autotune.KernelConfig exactly like the
# duty-path set_msm above: commitment evaluation picks Straus joint
# windowed mul vs per-lane double-and-add, and the reshare MSM picks its
# Pippenger window width. Both are trace-time flags — flips require
# blsops.clear_kernel_caches() (KernelConfig.apply does this).

_CEREMONY_STRAUS: bool | None = None
_CEREMONY_WINDOW: int | None = None


def set_ceremony_straus(mode: bool | None) -> None:
    """Commitment-polynomial evaluation: Straus joint windowed mul (True)
    vs per-lane double-and-add (False); None restores the default (on)."""
    global _CEREMONY_STRAUS
    _CEREMONY_STRAUS = mode


def ceremony_straus_active() -> bool:
    if _CEREMONY_STRAUS is not None:
        return _CEREMONY_STRAUS
    return True


def set_ceremony_window(window: int | None) -> None:
    """Pippenger window width for the ceremony MSM (reshare pubshare
    combination); None restores the default (8)."""
    global _CEREMONY_WINDOW
    if window is not None and not 1 <= window <= 16:
        raise ValueError(f"ceremony MSM window out of range: {window}")
    _CEREMONY_WINDOW = window


def ceremony_window() -> int:
    if _CEREMONY_WINDOW is not None:
        return _CEREMONY_WINDOW
    return 8
