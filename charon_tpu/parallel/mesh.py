"""Sharded slot-level crypto step over a device mesh.

Design (SURVEY.md §2.4, §7): the two parallelism axes of the reference —
validator-set batching (axis №1) and share-index t-of-n recombination
(axis №2) — map to array dimensions [V, t]. V is sharded over the mesh's
'shards' axis with shard_map; t stays local (the Lagrange reduction is a
t-term point fold). The only cross-device communication is a psum of the
per-shard validity counts — kilobyte-scale, riding ICI.

This is the "training step" analogue of the framework: one call per slot
processes every validator's partial signatures — verify each against its
pubshare, recombine to group signatures, verify the group signature — as a
single compiled SPMD program.
"""

from __future__ import annotations

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 top-level spelling
    _shard_map = jax.shard_map
except AttributeError:  # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

from charon_tpu.ops import blsops
from charon_tpu.ops import curve as C
from charon_tpu.ops import decompress as DEC
from charon_tpu.ops import fptower as T
from charon_tpu.ops import limb
from charon_tpu.ops import pairing as DP
from charon_tpu.ops import sswu as SSWU
from charon_tpu.ops.limb import ModCtx


def _dedupe_buckets(lanes, bucket_fn):
    """Keep one representative lane count per padded bucket shape."""
    seen, out = set(), []
    for n in lanes:
        b = bucket_fn(n)
        if b not in seen:
            seen.add(b)
            out.append(n)
    return out


def make_mesh(devices=None, axis: str = "shards") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def make_mesh_2d(
    n_hosts: int, devices=None, axes: tuple[str, str] = ("dcn", "ici")
) -> Mesh:
    """Multi-host mesh layout: leading axis across hosts (DCN), trailing
    axis across each host's chips (ICI).

    The slot plane's only collective is a scalar psum, which XLA lowers
    to an intra-host reduce over the minor (ICI) axis first and a single
    tiny cross-host reduce after — the validator batch axis is sharded
    over BOTH axes (flattened), so all bulk data stays device-local and
    nothing bulk ever crosses DCN (scaling-book recipe: shard so
    collectives ride ICI; DCN carries only scalars here).

    On real multi-host TPU the device list comes from
    `jax.distributed.initialize()` + `jax.devices()`; in tests the same
    layout is exercised by reshaping the 8-device virtual CPU mesh to
    (2 hosts x 4 chips)."""
    devices = devices if devices is not None else jax.devices()
    devices = np.asarray(devices)
    if devices.size % n_hosts:
        raise ValueError(
            f"{devices.size} devices do not split over {n_hosts} hosts"
        )
    return Mesh(devices.reshape(n_hosts, -1), axes)


class SlotCryptoPlane:
    """The per-slot batched crypto program, sharded over a mesh.

    Inputs per slot (leading axis V = #validators, sharded):
      pubshares  [V, t]  affine G1 — per-share public keys
      msg        [V]     affine G2 — per-validator signing roots (hashed)
      partials   [V, t]  affine G2 — per-share partial signatures
      group_pk   [V]     affine G1 — group public keys
      indices    [V, t]  int32     — share indices (1-based)

    Outputs:
      group_sig  [V]  affine G2 — recombined signatures (sharded)
      sig_ok     [V]  bool      — per-partial verify AND group verify
      total_ok   []   int32     — cluster-wide count of fully-valid lanes
                                  (psum over shards)
    """

    def __init__(self, mesh: Mesh, t: int, ctx: ModCtx | None = None, fr_ctx: ModCtx | None = None):
        self.mesh = mesh
        self.t = t
        self.ctx = ctx or limb.default_fp_ctx()
        self.fr_ctx = fr_ctx or limb.default_fr_ctx()
        # all mesh axes shard the validator batch dim together: on a
        # 2D (dcn, ici) mesh the flattened sharding keeps bulk data
        # device-local and the scalar psum is the only cross-axis op
        self.axis = tuple(mesh.axis_names)
        self._step = self._build()
        self._step_rlc = self._build_rlc()
        self._verify = self._build_verify()
        self._verify_rlc = self._build_verify_rlc()
        # decode-fused variants (ISSUE 5): signatures arrive as parsed
        # compressed lanes and the program decompresses them on device
        # before verifying — the coalescer's `decode_mode device` path.
        # Construction is free (jit compiles lazily on first call), so
        # planes that never see parsed flushes never compile these.
        self._verify_dec = self._build_verify_dec()
        self._verify_rlc_dec = self._build_verify_rlc_dec()
        self._step_dec = self._build_dec()
        self._step_rlc_dec = self._build_rlc_dec()
        # bulk warm-up programs (ISSUE 6): sharded hash-to-curve and G1
        # decompression for the cold-path cache warm — one compiled
        # program feeds thousands of point-cache entries per dispatch.
        self._h2c = self._build_h2c()
        self._g1dec = self._build_g1dec()
        # per-program timing hook (ISSUE 19): callable(family, seconds,
        # lanes), family names matching kernel_families ("mesh/verify_rlc"
        # ...). Fired from the host dispatch methods around each compiled
        # program INCLUDING its result sync, so the per-family times sum
        # to (approximately) the flush device_span — app/planeprof feeds
        # tpu_plane_kernel_seconds from it. None (the default) costs one
        # attribute check per dispatch.
        self.on_program = None

    def _timed(self, family: str, lanes: int, fn):
        """Run one compiled-program dispatch (with its sync) under the
        timing hook. Hook faults never fail the dispatch."""
        hook = self.on_program
        if hook is None:
            return fn()
        t0 = time.monotonic()
        try:
            return fn()
        finally:
            try:
                hook(f"mesh/{family}", time.monotonic() - t0, lanes)
            except Exception:  # noqa: BLE001 — observability stays off the duty path
                pass

    def _step_body(self, pubshares, msg, partials, group_pk, indices, live):
        """Per-shard recombine + per-lane attribution verify. Shared by
        the point-input program and the decode-fused one (which ANDs its
        decompression mask into `live` before calling)."""
        ctx, fr_ctx, t, axis = self.ctx, self.fr_ctx, self.t, self.axis
        # Threshold recombination first [Vl] — it has no data dependency
        # on the verifies, and doing it first lets BOTH verify tiers run
        # as ONE batched pairing program over Vl*(t+1) lanes (a single
        # Miller-loop/final-exp subgraph in the compiled module instead
        # of two, which halves the dominant XLA compile cost and keeps
        # the device busy with one large batch instead of two smaller
        # ones).
        group_sig = blsops.threshold_recombine(ctx, fr_ctx, t, partials, indices)

        # Verify lanes: [Vl, t] per-share partials ++ [Vl, 1] group sig,
        # flattened to one [Vl*(t+1)] batch.
        cat = lambda a, b: jnp.concatenate(
            (a, b[:, None, ...]), axis=1
        ).reshape(-1, *a.shape[2:])
        pk_all = jax.tree_util.tree_map(cat, pubshares, group_pk)
        sig_all = jax.tree_util.tree_map(cat, partials, group_sig)
        msg_rep = jax.tree_util.tree_map(
            lambda a: jnp.repeat(a, t + 1, axis=0), msg
        )
        ok_all = DP.batched_verify(ctx, pk_all, msg_rep, sig_all)
        ok = jnp.all(ok_all.reshape(-1, t + 1), axis=-1)
        # `live` masks padding lanes (V rounded up to the mesh size)
        # out of the cluster-wide count
        ok = jnp.logical_and(ok, live)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axis)
        return group_sig, ok, total

    def _build(self):
        axis = self.axis

        sharded = _shard_map(
            self._step_body,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P()),
        )
        return jax.jit(sharded)

    def _build_dec(self):
        """Attribution recombine on PARSED partials: decompress the
        [Vl, t] signature grid in-program, then the shared step body.
        Rows with any undecodable partial recombine as identities and
        fail via the decode mask folded into `live`."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local_step(ps, msg, px0, px1, psign, gpk, idx, live):
            partials, dec_ok = DEC.decompress_g2_graph(
                ctx, fr_ctx, (px0, px1), psign
            )
            row_ok = jnp.all(dec_ok, axis=1)
            return self._step_body(
                ps, msg, partials, gpk, idx, jnp.logical_and(live, row_ok)
            )

        sharded = _shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                P(axis), P(axis),
            ),
            out_specs=(P(axis), P(axis), P()),
        )
        return jax.jit(sharded)

    def _build_rlc(self):
        """The throughput path: identical recombination, but verification
        by random linear combination (ops/pairing.batched_verify_rlc
        design) — each shard product-trees its lanes' pairing values and
        runs ONE local final exponentiation (all shards in parallel), so
        the per-lane final-exp cost disappears. Returns (group_sig,
        all_ok) where all_ok is the cluster-wide AND (psum of per-shard
        failures == 0). Per-lane attribution on failure comes from the
        slower `step` (the reference pays per-signature herumi calls for
        every duty; here the common all-valid case costs one shared tail
        per shard — core/sigagg/sigagg.go:84-122)."""
        axis = self.axis

        sharded = _shard_map(
            self._step_rlc_body,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)
            ),
            out_specs=(P(axis), P()),
        )
        return jax.jit(sharded)

    def _step_rlc_body(self, pubshares, msg, partials, group_pk, indices, live, rand):
        ctx, fr_ctx, t, axis = self.ctx, self.fr_ctx, self.t, self.axis
        g2f = C.g2_ops(ctx)
        group_sig = blsops.threshold_recombine(ctx, fr_ctx, t, partials, indices)

        # INDEPENDENT exponent per verify lane ([Vl, t+1] from the
        # host): sharing one exponent across a validator's t+1 lanes
        # would let colluding operators craft partial-sig deltas whose
        # errors cancel deterministically inside the shared-exponent
        # product (the group-sig lane error is a public Lagrange
        # combination of the partial errors). Padding lanes carry
        # live=False: zero their exponent so their (possibly garbage)
        # pairing value contributes ^0 = 1.
        rand_live = jnp.where(live[:, None, None], rand, 0)
        cat_grid = lambda a, b: jnp.concatenate(
            (a, b[:, None, ...]), axis=1
        )
        pk_grid = jax.tree_util.tree_map(cat_grid, pubshares, group_pk)
        sig_grid = jax.tree_util.tree_map(cat_grid, partials, group_sig)

        from charon_tpu.ops import msm as MSM

        if MSM.msm_active():
            # Grouped RLC: a validator's t+1 lanes share its duty
            # message, so they collapse into ONE bucket pair
            # e(sum_j r_vj * pk_vj, H_v) — the Miller stage runs
            # Vl + 1 pairs instead of Vl * (t+1), a (t+1)x cut in
            # the dominant stage. Straus joint mul batches the
            # 64-bit randomization over the (Vl, t+1) grid; per-lane
            # exponents keep the independence property above (same
            # construction as pairing.batched_verify_grouped_rlc
            # with per-validator groups).
            g1f = C.g1_ops(ctx)
            buckets = MSM.windowed_joint_mul(
                g1f,
                fr_ctx,
                C.affine_to_point(g1f, pk_grid),
                rand_live,
                nbits=64,
            )
            sig_v = MSM.windowed_joint_mul(
                g2f,
                fr_ctx,
                C.affine_to_point(g2f, sig_grid),
                rand_live,
                nbits=64,
            )
            s_total = DP.point_sum_tree(g2f, sig_v, live.shape[0])
            ok = DP.grouped_rlc_check(ctx, buckets, msg, s_total)
        else:
            flat = lambda a: a.reshape(-1, *a.shape[2:])
            pk_all = jax.tree_util.tree_map(flat, pk_grid)
            sig_all = jax.tree_util.tree_map(flat, sig_grid)
            msg_rep = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, t + 1, axis=0), msg
            )
            ok = DP.batched_verify_rlc(
                ctx,
                fr_ctx,
                pk_all,
                msg_rep,
                sig_all,
                rand_live.reshape(-1, rand.shape[-1]),
            )
        bad = jax.lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis)
        return group_sig, bad == 0

    def _build_rlc_dec(self):
        """RLC recombine on PARSED partials: in-program decompression,
        rows with undecodable partials excluded from the shared product
        (exponent 0) and reported via the third output so the host can
        attribute per-lane results on the all-valid fast path."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local_step(ps, msg, px0, px1, psign, gpk, idx, live, rand):
            partials, dec_ok = DEC.decompress_g2_graph(
                ctx, fr_ctx, (px0, px1), psign
            )
            row_ok = jnp.logical_and(jnp.all(dec_ok, axis=1), live)
            group_sig, all_ok = self._step_rlc_body(
                ps, msg, partials, gpk, idx, row_ok, rand
            )
            return group_sig, all_ok, row_ok

        sharded = _shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                P(axis), P(axis), P(axis),
            ),
            out_specs=(P(axis), P(), P(axis)),
        )
        return jax.jit(sharded)

    def _build_verify_dec(self):
        """Per-lane attribution verify on PARSED signature lanes:
        decompress in-program (sqrt + sign + on-curve + psi subgroup
        check), then the pairing verify — one device dispatch for the
        whole decode+verify stage."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local(pk, msg, sx0, sx1, sign, live):
            sig, dec_ok = DEC.decompress_g2_graph(
                ctx, fr_ctx, (sx0, sx1), sign
            )
            ok = DP.batched_verify(ctx, pk, msg, sig)
            return jnp.logical_and(jnp.logical_and(ok, dec_ok), live)

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)
            ),
            out_specs=P(axis),
        )
        return jax.jit(sharded)

    def _build_verify_rlc_dec(self):
        """RLC verify on PARSED signature lanes. Undecodable lanes get
        exponent 0 (neutral in the shared product) and come back False
        in the per-lane mask output; all_ok therefore means 'every lane
        that DECODED verified' — the host resolves per-lane results as
        decode_mask on the fast path."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local(pk, msg, sx0, sx1, sign, live, rand):
            sig, dec_ok = DEC.decompress_g2_graph(
                ctx, fr_ctx, (sx0, sx1), sign
            )
            lane_ok = jnp.logical_and(dec_ok, live)
            rand_live = jnp.where(lane_ok[:, None], rand, 0)
            ok = DP.batched_verify_rlc(ctx, fr_ctx, pk, msg, sig, rand_live)
            bad = jax.lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis)
            return bad == 0, lane_ok

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                P(axis),
            ),
            out_specs=(P(), P(axis)),
        )
        return jax.jit(sharded)

    def _build_h2c(self):
        """Sharded device hash-to-curve tail: hash_to_field outputs in,
        cleared G2 points out (ops/sswu.hash_to_g2_graph). The bulk
        message-cache warm-up program."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local(u00, u01, u10, u11, s0, s1, live):
            aff, valid = SSWU.hash_to_g2_graph(
                ctx, fr_ctx, (u00, u01), (u10, u11), s0, s1
            )
            return aff, jnp.logical_and(valid, live)

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                P(axis),
            ),
            out_specs=(P(axis), P(axis)),
        )
        return jax.jit(sharded)

    def _build_g1dec(self):
        """Sharded batched G1 decompression (GLV subgroup check) — the
        bulk pubkey-cache warm-up program."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local(x0, sign, inf, ok, live):
            aff, valid = DEC.decompress_g1_graph(
                ctx, fr_ctx, x0, sign, inf, ok
            )
            return aff, jnp.logical_and(valid, live)

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
        )
        return jax.jit(sharded)

    def _build_verify(self):
        """Plain per-lane sharded verify: ok[N] — the attribution path
        (each lane pays its own final exponentiation; used only when the
        RLC fast path says the batch contains a failure)."""
        ctx, axis = self.ctx, self.axis

        def local(pk, msg, sig, live):
            ok = DP.batched_verify(ctx, pk, msg, sig)
            return jnp.logical_and(ok, live)

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=P(axis),
        )
        return jax.jit(sharded)

    def _build_verify_rlc(self):
        """Sharded whole-batch RLC verify: every shard product-trees its
        lanes under independent 64-bit exponents and runs ONE local final
        exponentiation; the cross-device op is a scalar psum of failure
        counts. Padding lanes (live=False) get exponent 0 so their
        pairing values contribute ^0 = 1."""
        ctx, fr_ctx, axis = self.ctx, self.fr_ctx, self.axis

        def local(pk, msg, sig, live, rand):
            rand = jnp.where(live[:, None], rand, 0)
            ok = DP.batched_verify_rlc(ctx, fr_ctx, pk, msg, sig, rand)
            bad = jax.lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis)
            return bad == 0

        sharded = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=P(),
        )
        return jax.jit(sharded)

    def step_rlc(self, pubshares, msg, partials, group_pk, indices, live, rand):
        """Fast path: (group_sig, all_ok). `rand` is a [V, t+1] raw Fr
        limb array of independent nonzero 64-bit exponents (host
        randomness, one per verify lane — see make_rand)."""
        return self._step_rlc(
            pubshares, msg, partials, group_pk, indices, live, rand
        )

    def make_rand(self, v: int, rng=None) -> jnp.ndarray:
        """[V_padded, t+1] independent nonzero 64-bit exponents packed as
        raw Fr limbs. Defaults to OS randomness (SystemRandom) — the
        2^-64 soundness bound assumes exponents unpredictable to the
        signers; pass a seeded Random only in tests."""
        import random as _random

        rng = rng or _random.SystemRandom()
        vp = self.bucket_lanes(v)
        return jnp.asarray(
            np.asarray(
                [
                    [
                        limb.int_to_limbs(
                            rng.randrange(1, 1 << 64),
                            self.fr_ctx.n_limbs,
                            self.fr_ctx.limb_bits,
                            self.fr_ctx.np_dtype,
                        )
                        for _ in range(self.t + 1)
                    ]
                    for _ in range(vp)
                ]
            )
        )

    # -- host-facing ------------------------------------------------------

    def shard_count(self) -> int:
        return self.mesh.devices.size

    def bucket_lanes(self, n: int) -> int:
        """Padded batch size for n lanes: the shared power-of-two bucket
        ladder (ops/blsops.bucket_lanes), kept divisible by the mesh so
        shard_map splits evenly. One ladder across BlsEngine and this
        plane bounds jit-cache growth to O(log max_batch) shapes."""
        return blsops.bucket_lanes(n, self.shard_count())

    def jit_cache_size(self) -> int:
        """Compiled-program count across this plane's programs (point
        AND decode-fused families) — the bucket-discipline regression
        signal (see blsops counterpart)."""
        return sum(
            prog._cache_size()
            for prog in (
                self._step,
                self._step_rlc,
                self._verify,
                self._verify_rlc,
                self._step_dec,
                self._step_rlc_dec,
                self._verify_dec,
                self._verify_rlc_dec,
                self._h2c,
                self._g1dec,
            )
        )

    # -- bulk warm-up host API (ISSUE 6) ----------------------------------

    def hash_to_g2_host(self, msgs, dst: bytes = SSWU.DST_POP):
        """Messages (raw bytes or sswu.HashedMsg lanes) -> ([affine G2
        point], [valid]) through the sharded device SSWU program; the
        host pays only SHA-256 hash_to_field. Bucket-padded like every
        other entry point, so warm-up chunks reuse compiled programs."""
        lanes = [
            m
            if isinstance(m, SSWU.HashedMsg)
            else SSWU.hash_to_field_lane(m, dst)
            for m in msgs
        ]
        n = len(lanes)
        if n == 0:
            return [], []
        pad = self.bucket_lanes(n) - n
        lanes = lanes + [lanes[0]] * pad
        arrays = SSWU.pack_hashed(self.ctx, lanes)
        live = jnp.asarray(np.arange(n + pad) < n)

        def run():
            aff, valid = self._h2c(*arrays, live)
            return (
                C.g2_unpack(self.ctx, aff)[:n],
                [bool(b) for b in np.asarray(valid)[:n]],
            )

        return self._timed("h2c", n, run)

    def decompress_g1_host(self, encoded):
        """Compressed 48-byte G1 lanes (or parsed lanes) -> ([affine
        point | None], [valid]) through the sharded decompression
        program — per-lane masks, never exceptions."""
        parsed = [
            p if isinstance(p, DEC.ParsedPoint) else DEC.parse_g1_lane(p)
            for p in encoded
        ]
        n = len(parsed)
        if n == 0:
            return [], []
        pad = self.bucket_lanes(n) - n
        parsed = parsed + [parsed[0]] * pad
        x0, sign, inf, ok = DEC.pack_parsed_g1(self.ctx, parsed)
        live = jnp.asarray(np.arange(n + pad) < n)

        def run():
            aff, valid = self._g1dec(x0, sign, inf, ok, live)
            return (
                C.g1_unpack(self.ctx, aff)[:n],
                [bool(b) for b in np.asarray(valid)[:n]],
            )

        return self._timed("g1dec", n, run)

    def pack_inputs(self, pubshares, msgs, partials, group_pks, indices):
        """Python-int affine points -> device arrays laid out [V, t]/[V].

        V is padded up to the power-of-two bucket ladder (bucket_lanes)
        by repeating lane 0; padding lanes carry live=False and are
        excluded from the psum total (and sliced off by step_host)."""
        v = len(msgs)
        t = self.t
        pad = self.bucket_lanes(v) - v
        if pad:
            pubshares = list(pubshares) + [pubshares[0]] * pad
            msgs = list(msgs) + [msgs[0]] * pad
            partials = list(partials) + [partials[0]] * pad
            group_pks = list(group_pks) + [group_pks[0]] * pad
            indices = list(indices) + [indices[0]] * pad
        vp = v + pad
        flat_ps = [p for row in pubshares for p in row]
        flat_sig = [s for row in partials for s in row]
        ps = C.g1_pack(self.ctx, flat_ps)
        ps = jax.tree_util.tree_map(lambda a: a.reshape(vp, t, -1), ps)
        sig = C.g2_pack(self.ctx, flat_sig)
        sig = jax.tree_util.tree_map(lambda a: a.reshape(vp, t, -1), sig)
        msg = C.g2_pack(self.ctx, msgs)
        gpk = C.g1_pack(self.ctx, group_pks)
        idx = jnp.asarray(np.asarray(indices, np.int32))
        live = jnp.asarray(np.arange(vp) < v)
        return ps, msg, sig, gpk, idx, live

    def step(self, pubshares, msg, partials, group_pk, indices, live):
        """Run one slot step on packed inputs. Returns (group_sig, ok,
        total_ok) device values."""
        return self._step(pubshares, msg, partials, group_pk, indices, live)

    def step_host(self, pubshares, msgs, partials, group_pks, indices):
        """Convenience host-level wrapper (pack, run, unpack)."""
        v = len(msgs)
        args = self.pack_inputs(pubshares, msgs, partials, group_pks, indices)
        group_sig, ok, total = self._step(*args)
        return (
            C.g2_unpack(self.ctx, group_sig)[:v],
            [bool(b) for b in np.asarray(ok)[:v]],
            int(total),
        )

    # -- coalescer-facing host API ----------------------------------------
    # (core/cryptoplane.SlotCoalescer talks to the plane exclusively
    # through recombine_host / verify_host so a counting fake can stand
    # in for the device in fast-tier tests)

    def pack_verify_inputs(self, pks, msgs, sigs):
        """Python-int affine points -> [N] device arrays + live mask,
        N padded up to the power-of-two bucket ladder by repeating
        lane 0."""
        n = len(pks)
        pad = self.bucket_lanes(n) - n
        if pad:
            pks = list(pks) + [pks[0]] * pad
            msgs = list(msgs) + [msgs[0]] * pad
            sigs = list(sigs) + [sigs[0]] * pad
        pk = C.g1_pack(self.ctx, pks)
        msg = C.g2_pack(self.ctx, msgs)
        sig = C.g2_pack(self.ctx, sigs)
        live = jnp.asarray(np.arange(n + pad) < n)
        return pk, msg, sig, live

    def make_lane_rand(self, n: int, rng=None) -> jnp.ndarray:
        """[N_padded] independent nonzero 64-bit exponents as raw Fr
        limbs (see make_rand for the randomness contract)."""
        import random as _random

        rng = rng or _random.SystemRandom()
        np_ = self.bucket_lanes(n)
        return jnp.asarray(
            np.asarray(
                [
                    limb.int_to_limbs(
                        rng.randrange(1, 1 << 64),
                        self.fr_ctx.n_limbs,
                        self.fr_ctx.limb_bits,
                        self.fr_ctx.np_dtype,
                    )
                    for _ in range(np_)
                ]
            )
        )

    def pack_verify_inputs_parsed(self, pks, msgs, parsed):
        """Decode-mode-device pack: pk/msg POINTS (host-cached decodes)
        plus PARSED compressed signature lanes
        (ops/decompress.ParsedPoint, host-valid and finite — the
        coalescer prefails the rest). Same bucket padding and trailing
        live mask as pack_verify_inputs."""
        n = len(pks)
        pad = self.bucket_lanes(n) - n
        if pad:
            pks = list(pks) + [pks[0]] * pad
            msgs = list(msgs) + [msgs[0]] * pad
            parsed = list(parsed) + [parsed[0]] * pad
        pk = C.g1_pack(self.ctx, pks)
        msg = C.g2_pack(self.ctx, msgs)
        sx0, sx1, sign, _inf, _ok = DEC.pack_parsed_g2(self.ctx, parsed)
        live = jnp.asarray(np.arange(n + pad) < n)
        return pk, msg, sx0, sx1, sign, live

    def verify_packed_parsed(self, arrays, rand, n: int) -> list[bool]:
        """Device stage for a parsed verify batch: decompression is fused
        into the verify program (no separate decode dispatch). Lanes that
        fail decompression on device come back False; the RLC fast path's
        per-lane answer is exactly the decode mask."""
        pk, msg, sx0, sx1, sign, live = arrays

        def fast():
            all_ok, lane_ok = self._verify_rlc_dec(
                pk, msg, sx0, sx1, sign, live, rand
            )
            return bool(all_ok), lane_ok

        all_ok, lane_ok = self._timed("verify_rlc_dec", n, fast)
        if all_ok:
            return [bool(b) for b in np.asarray(lane_ok)[:n]]
        ok = self._timed(
            "verify_dec",
            n,
            lambda: np.asarray(
                self._verify_dec(pk, msg, sx0, sx1, sign, live)
            ),
        )
        return [bool(b) for b in ok[:n]]

    def verify_packed(self, arrays, rand, n: int) -> list[bool]:
        """Device stage of verify_host on an already-packed batch — the
        coalescer's pipelined flush packs on its decode pool and calls
        this from the serialized device lane, so host packing of window
        k overlaps device execution of window k-1."""
        pk, msg, sig, live = arrays
        if self._timed(
            "verify_rlc",
            n,
            lambda: bool(self._verify_rlc(pk, msg, sig, live, rand)),
        ):
            return [True] * n
        ok = self._timed(
            "verify",
            n,
            lambda: np.asarray(self._verify(pk, msg, sig, live)),
        )
        return [bool(b) for b in ok[:n]]

    def verify_host(self, pks, msgs, sigs, rng=None) -> list[bool]:
        """Sharded batch verify of N independent (pk, msg, sig) lanes.
        RLC fast path first (one shared final-exp per shard); only a
        failing batch pays the per-lane attribution program."""
        n = len(pks)
        if n == 0:
            return []
        arrays = self.pack_verify_inputs(pks, msgs, sigs)
        rand = self.make_lane_rand(n, rng=rng)
        return self.verify_packed(arrays, rand, n)

    def pack_inputs_parsed(
        self, pubshares, msgs, parsed_partials, group_pks, indices
    ):
        """Decode-mode-device recombine pack: [V, t] PARSED partial
        signatures ride as raw limb grids; everything else is points as
        in pack_inputs."""
        v = len(msgs)
        t = self.t
        pad = self.bucket_lanes(v) - v
        if pad:
            pubshares = list(pubshares) + [pubshares[0]] * pad
            msgs = list(msgs) + [msgs[0]] * pad
            parsed_partials = (
                list(parsed_partials) + [parsed_partials[0]] * pad
            )
            group_pks = list(group_pks) + [group_pks[0]] * pad
            indices = list(indices) + [indices[0]] * pad
        vp = v + pad
        flat_ps = [p for row in pubshares for p in row]
        ps = C.g1_pack(self.ctx, flat_ps)
        ps = jax.tree_util.tree_map(lambda a: a.reshape(vp, t, -1), ps)
        flat_parsed = [p for row in parsed_partials for p in row]
        px0, px1, psign, _inf, _ok = DEC.pack_parsed_g2(
            self.ctx, flat_parsed
        )
        px0 = px0.reshape(vp, t, -1)
        px1 = px1.reshape(vp, t, -1)
        psign = psign.reshape(vp, t)
        msg = C.g2_pack(self.ctx, msgs)
        gpk = C.g1_pack(self.ctx, group_pks)
        idx = jnp.asarray(np.asarray(indices, np.int32))
        live = jnp.asarray(np.arange(vp) < v)
        return ps, msg, px0, px1, psign, gpk, idx, live

    def recombine_packed_parsed(self, args, rand, v: int):
        """Device stage for a parsed recombine batch. Rows with an
        undecodable partial recombine as identities (their group sig
        unpacks to None) and come back ok=False."""
        def fast():
            group_sig, all_ok, row_ok = self._step_rlc_dec(*args, rand)
            if not bool(all_ok):
                return None
            return (
                C.g2_unpack(self.ctx, group_sig)[:v],
                [bool(b) for b in np.asarray(row_ok)[:v]],
            )

        res = self._timed("step_rlc_dec", v, fast)
        if res is not None:
            return res

        def attrib():
            group_sig, ok, _total = self._step_dec(*args)
            return (
                C.g2_unpack(self.ctx, group_sig)[:v],
                [bool(b) for b in np.asarray(ok)[:v]],
            )

        return self._timed("step_dec", v, attrib)

    def recombine_packed(self, args, rand, v: int):
        """Device stage of recombine_host on an already-packed [V, t]
        batch (see verify_packed for the pipelining contract)."""
        def fast():
            group_sig, all_ok = self.step_rlc(*args, rand)
            if not bool(all_ok):
                return None
            return C.g2_unpack(self.ctx, group_sig)[:v], [True] * v

        res = self._timed("step_rlc", v, fast)
        if res is not None:
            return res

        def attrib():
            group_sig, ok, _total = self.step(*args)
            return (
                C.g2_unpack(self.ctx, group_sig)[:v],
                [bool(b) for b in np.asarray(ok)[:v]],
            )

        return self._timed("step", v, attrib)

    def recombine_host(
        self, pubshares, msgs, partials, group_pks, indices, rng=None
    ):
        """Recombine + verify [V, t] threshold workloads in one sharded
        program: returns ([V] group signature points, [V] ok flags).
        RLC fast path first; a failing batch re-runs the per-lane step
        for attribution."""
        v = len(msgs)
        if v == 0:
            return [], []
        args = self.pack_inputs(pubshares, msgs, partials, group_pks, indices)
        rand = self.make_rand(v, rng=rng)
        return self.recombine_packed(args, rand, v)

    # -- analyzer registration (ISSUE 11) ---------------------------------

    def kernel_families(self, prefix: str = "mesh"):
        """This plane's program variants as named kernel families for the
        static analyzer (charon_tpu/analysis/jaxpr_check.py): build
        closures pack canonical generator-point inputs on the bucket
        ladder and return (program, args) pairs that jax.make_jaxpr can
        trace WITHOUT executing. Returns {name: blsops.KernelFamily}."""
        import random as _random

        from charon_tpu.crypto.g1g2 import G1_GEN, G2_GEN, g2_to_bytes

        t = self.t
        n = self.bucket_lanes(4)
        mult = self.shard_count()
        idx_row = list(range(1, t + 1))
        rng = _random.Random(0)  # shape-only tracing — values never run

        def spec(fn, args):
            return blsops.TraceSpec(fn, args, self.ctx, n, mult)

        def _points():
            return (
                [[G1_GEN] * t] * n,
                [G2_GEN] * n,
                [[G2_GEN] * t] * n,
                [G1_GEN] * n,
                [idx_row] * n,
            )

        def _step():
            return spec(self._step, self.pack_inputs(*_points()))

        def _step_rlc():
            return spec(
                self._step_rlc,
                (*self.pack_inputs(*_points()), self.make_rand(n, rng=rng)),
            )

        def _verify():
            args = self.pack_verify_inputs(
                [G1_GEN] * n, [G2_GEN] * n, [G2_GEN] * n
            )
            return spec(self._verify, args)

        def _verify_rlc():
            args = self.pack_verify_inputs(
                [G1_GEN] * n, [G2_GEN] * n, [G2_GEN] * n
            )
            return spec(
                self._verify_rlc, (*args, self.make_lane_rand(n, rng=rng))
            )

        def _parsed():
            return DEC.parse_g2_lane(g2_to_bytes(G2_GEN))

        def _verify_dec():
            args = self.pack_verify_inputs_parsed(
                [G1_GEN] * n, [G2_GEN] * n, [_parsed()] * n
            )
            return spec(self._verify_dec, args)

        def _verify_rlc_dec():
            args = self.pack_verify_inputs_parsed(
                [G1_GEN] * n, [G2_GEN] * n, [_parsed()] * n
            )
            return spec(
                self._verify_rlc_dec,
                (*args, self.make_lane_rand(n, rng=rng)),
            )

        def _parsed_points():
            return (
                [[G1_GEN] * t] * n,
                [G2_GEN] * n,
                [[_parsed()] * t] * n,
                [G1_GEN] * n,
                [idx_row] * n,
            )

        def _step_dec():
            return spec(self._step_dec, self.pack_inputs_parsed(*_parsed_points()))

        def _step_rlc_dec():
            return spec(
                self._step_rlc_dec,
                (
                    *self.pack_inputs_parsed(*_parsed_points()),
                    self.make_rand(n, rng=rng),
                ),
            )

        def _h2c():
            lanes = [
                SSWU.hash_to_field_lane(b"jaxpr-check", SSWU.DST_POP)
            ] * n
            live = jnp.asarray(np.ones(n, bool))
            return spec(self._h2c, (*SSWU.pack_hashed(self.ctx, lanes), live))

        def _g1dec():
            from charon_tpu.crypto.g1g2 import g1_to_bytes

            parsed = [DEC.parse_g1_lane(g1_to_bytes(G1_GEN))] * n
            live = jnp.asarray(np.ones(n, bool))
            return spec(
                self._g1dec, (*DEC.pack_parsed_g1(self.ctx, parsed), live)
            )

        builders = {
            "step": (_step, False),
            "step_rlc": (_step_rlc, False),
            "verify": (_verify, False),
            "verify_rlc": (_verify_rlc, False),
            "verify_dec": (_verify_dec, False),
            "verify_rlc_dec": (_verify_rlc_dec, False),
            "step_dec": (_step_dec, False),
            "step_rlc_dec": (_step_rlc_dec, False),
            # the warm-up programs are lighter than the pairing bodies
            # but still SSWU/sqrt chains — h2c stays digest-covered,
            # g1dec is cheap enough to sentinel every run
            "h2c": (_h2c, False),
            "g1dec": (_g1dec, True),
        }
        return {
            f"{prefix}/{fname}": blsops.KernelFamily(
                f"{prefix}/{fname}", build, sentinel
            )
            for fname, (build, sentinel) in builders.items()
        }


    # canonical duty shapes: lane 1 catches the SMALLEST bucket (a lone
    # first-slot submission pads to the shard count, not to 16), the
    # rest cover the burst sizes; duplicates after bucket-padding are
    # compiled once (e.g. 1 and 16 share bucket 16 on a 16-shard mesh)
    PREWARM_VERIFY_LANES = (1, 16, 64, 256)
    PREWARM_RECOMBINE_LANES = (1, 16, 64)

    def prewarm(
        self,
        verify_lanes=None,
        recombine_lanes=None,
        decompress: bool = False,
    ) -> list[tuple[str, int, float]]:
        """Trace + compile the canonical duty shapes up front so the
        first live slot never eats a cold pairing compile on the duty
        path (XLA pairing programs compile in minutes cold).

        Each shape compiles BOTH tiers EXPLICITLY — the RLC fast path
        AND the per-lane attribution program (generator-point dummies
        are valid triples, so the RLC early-return would otherwise skip
        the attribution tier and the first forged lane mid-slot would
        still eat a cold compile). Shapes land on the same bucket
        ladder live flushes pad to, deduplicated per bucket. Returns
        [(kind, bucket_lanes, seconds)] per compiled shape.

        app/run.py sequences this AFTER core/autotune.resolve so the
        programs compile under the TUNED KernelConfig routing (and,
        warm, replay as persistent-cache loads — the AOT artifact
        story); the tuner's prewarm ladder (autotune.PREWARM_LANES)
        deliberately matches these shapes."""
        import time as _time

        from charon_tpu.crypto.g1g2 import G1_GEN, G2_GEN

        if verify_lanes is None:
            verify_lanes = self.PREWARM_VERIFY_LANES
        if recombine_lanes is None:
            recombine_lanes = self.PREWARM_RECOMBINE_LANES
        verify_lanes = _dedupe_buckets(verify_lanes, self.bucket_lanes)
        recombine_lanes = _dedupe_buckets(recombine_lanes, self.bucket_lanes)
        report: list[tuple[str, int, float]] = []
        for n in verify_lanes:
            t0 = _time.monotonic()
            pk, msg, sig, live = self.pack_verify_inputs(
                [G1_GEN] * n, [G2_GEN] * n, [G2_GEN] * n
            )
            rand = self.make_lane_rand(n)
            bool(self._verify_rlc(pk, msg, sig, live, rand))
            np.asarray(self._verify(pk, msg, sig, live))
            report.append(("verify", self.bucket_lanes(n),
                           _time.monotonic() - t0))
        t = self.t
        idx_row = list(range(1, t + 1))
        for v in recombine_lanes:
            t0 = _time.monotonic()
            args = self.pack_inputs(
                [[G1_GEN] * t] * v,
                [G2_GEN] * v,
                [[G2_GEN] * t] * v,
                [G1_GEN] * v,
                [idx_row] * v,
            )
            rand = self.make_rand(v)
            self.step_rlc(*args, rand)
            np.asarray(self.step(*args)[1])
            report.append(("recombine", self.bucket_lanes(v),
                           _time.monotonic() - t0))
        if decompress:
            # decode-fused programs (decode_mode device): same buckets,
            # generator-point encodings so decompression takes the live
            # (finite, subgroup-valid) path through the sqrt chain
            from charon_tpu.crypto.g1g2 import g2_to_bytes

            gen_parsed = DEC.parse_g2_lane(g2_to_bytes(G2_GEN))
            for n in verify_lanes:
                t0 = _time.monotonic()
                arrays = self.pack_verify_inputs_parsed(
                    [G1_GEN] * n, [G2_GEN] * n, [gen_parsed] * n
                )
                rand = self.make_lane_rand(n)
                pk, msg, sx0, sx1, sign, live = arrays
                bool(
                    self._verify_rlc_dec(pk, msg, sx0, sx1, sign, live, rand)[0]
                )
                np.asarray(self._verify_dec(pk, msg, sx0, sx1, sign, live))
                report.append(("verify-dec", self.bucket_lanes(n),
                               _time.monotonic() - t0))
            for v in recombine_lanes:
                t0 = _time.monotonic()
                args = self.pack_inputs_parsed(
                    [[G1_GEN] * t] * v,
                    [G2_GEN] * v,
                    [[gen_parsed] * t] * v,
                    [G1_GEN] * v,
                    [idx_row] * v,
                )
                rand = self.make_rand(v)
                self._step_rlc_dec(*args, rand)
                np.asarray(self._step_dec(*args)[1])
                report.append(("recombine-dec", self.bucket_lanes(v),
                               _time.monotonic() - t0))
        return report


_ANALYSIS_PLANE_T = 3  # canonical threshold for the analyzer's plane


def register_analysis_families(
    mesh: Mesh | None = None, t: int = _ANALYSIS_PLANE_T
) -> "SlotCryptoPlane":
    """Build the canonical analysis plane (single-device by default —
    the program structure is shard-count-invariant; shard_map only
    changes the split) and register its program variants into the
    blsops kernel-family registry. Idempotent. Called by
    analysis/jaxpr_check.py and core/cryptoplane.kernel_inventory()."""
    mesh = mesh or make_mesh(jax.devices()[:1])
    plane = SlotCryptoPlane(mesh, t)
    for name, fam in plane.kernel_families().items():
        if name not in blsops.kernel_families():
            blsops.register_kernel_family(name, fam.build, fam.sentinel)
    return plane
