"""Device-mesh sharding of the crypto batch plane.

The reference scales across validators by keeping duty sets cluster-level
(ref: docs/architecture.md:131-133 — one DutyDefinitionSet per slot for all
DVs) and across share indices with t-of-n recombination. Here those two
axes become array batch dimensions, and this package shards them over a
`jax.sharding.Mesh` with shard_map — batch-parallel over ICI within a
slice, DCN across hosts, with psum reductions for the cluster-wide
all-valid flags.
"""

from charon_tpu.parallel.mesh import (  # noqa: F401
    SlotCryptoPlane,
    make_mesh,
    make_mesh_2d,
)
