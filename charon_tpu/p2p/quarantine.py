"""Per-peer malformed-frame quarantine (ISSUE 8 satellite).

Dropping-and-counting a malformed frame keeps the authenticated
connection alive (p2p/transport per-frame fault isolation), but a peer
*streaming* garbage — a buggy build, a fuzzing adversary — still costs
a decode attempt and a log line per frame. This state machine mutes
such a peer temporarily: `strikes` CodecErrors inside `window` seconds
impose a mute of `base` seconds, doubling per repeat offense up to
`max_mute`; a clean frame after the mute expires forgives the backoff
level. Pure host bookkeeping with an injectable clock, deliberately
free of the transport's `cryptography` dependency so the fast tier
exercises it everywhere.
"""

from __future__ import annotations

import time

QUARANTINE_STRIKES = 5
QUARANTINE_WINDOW = 10.0
QUARANTINE_BASE = 5.0
QUARANTINE_MAX = 300.0


class PeerQuarantine:
    """Tracks strike windows and mute deadlines per peer id.

    observer(peer, mute_seconds) fires once per imposed mute (the
    transport chains logging + the wire_peer_quarantine_total metric
    through it).

    `exempt` peers (any hashable id — the crypto-service client keys by
    "host:port") still accumulate strike counts for observability but
    NEVER escalate into a mute: a client's own configured server
    address flapping mid-upgrade should trigger reconnect backoff, not
    a 300 s codec mute that silently extends the outage."""

    def __init__(
        self,
        strikes: int = QUARANTINE_STRIKES,
        window: float = QUARANTINE_WINDOW,
        base: float = QUARANTINE_BASE,
        max_mute: float = QUARANTINE_MAX,
        observer=None,
        clock=time.monotonic,
        exempt=(),
    ) -> None:
        self.strikes = strikes
        self.window = window
        self.base = base
        self.max_mute = max_mute
        self.observer = observer
        self.exempt = frozenset(exempt)
        self._clock = clock
        self._strikes: dict[int, list[float]] = {}
        self._until: dict[int, float] = {}
        self._level: dict[int, int] = {}
        self.quarantines = 0  # mutes imposed (wire_peer_quarantine_total)

    def muted(self, peer: int) -> bool:
        if peer in self.exempt:
            return False
        return self._clock() < self._until.get(peer, 0.0)

    def strike(self, peer: int) -> float | None:
        """One malformed frame from the peer. Returns the mute length
        when this strike imposes one, else None."""
        now = self._clock()
        strikes = self._strikes.setdefault(peer, [])
        strikes.append(now)
        while strikes and now - strikes[0] > self.window:
            strikes.pop(0)
        if peer in self.exempt:
            return None  # pinned address: backoff owns flap handling
        if len(strikes) < self.strikes:
            return None
        strikes.clear()
        level = self._level.get(peer, 0)
        mute = min(self.base * (2**level), self.max_mute)
        self._level[peer] = level + 1
        self._until[peer] = now + mute
        self.quarantines += 1
        if self.observer is not None:
            self.observer(peer, mute)
        return mute

    def forgive(self, peer: int) -> None:
        """A clean frame decoded after the mute expired: reset the
        exponential-backoff level (the peer recovered)."""
        self._level.pop(peer, None)
        self._until.pop(peer, None)

    @property
    def any_history(self) -> bool:
        """Cheap hot-path guard: False until a peer has ever offended."""
        return bool(self._level) or bool(self._strikes)
