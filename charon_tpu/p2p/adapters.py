"""TCP-backed transports for the workflow components.

These adapt the P2PNode mesh to the transport interfaces the in-memory
simnet fakes implement, so the same ParSigEx / QBFTConsensus components run
over real sockets (ref: the reference's parsigex protocol
/charon/parsigex/2.0.0 — p2p/parsigex.go:23 — and consensus transport
core/consensus/qbft/transport.go).
"""

from __future__ import annotations

from charon_tpu.p2p.transport import P2PNode

PARSIGEX_PROTOCOL = "parsigex/2.0.0"
QBFT_PROTOCOL = "qbft/2.0.0"


class TcpParSigTransport:
    """Drop-in for core.parsigex.MemTransport over the TCP mesh.

    Node indices are 0-based; share indices 1-based (idx = share-1)."""

    def __init__(self, node: P2PNode) -> None:
        self.node = node
        self.local = None
        node.register_handler(PARSIGEX_PROTOCOL, self._on_msg)

    def attach(self, parsigex) -> None:
        self.local = parsigex

    async def send(
        self, from_share_idx: int, duty, signed_set, tctx=None
    ) -> None:
        # trace context rides the frame so peer-node spans join the
        # sender's duty trace (ref: OTel ctx in the p2p envelopes)
        await self.node.broadcast(
            PARSIGEX_PROTOCOL, {"duty": duty, "set": signed_set, "tctx": tctx}
        )

    async def _on_msg(self, from_idx: int, msg):
        if self.local is not None:
            # channel identity: mesh node index -> 1-based share index,
            # so receive() can attribute spoofed/invalid sets to the
            # authenticated peer the frame arrived from
            await self.local.receive(
                msg["duty"],
                msg["set"],
                tctx=msg.get("tctx"),
                sender=from_idx + 1,
            )
        return None


class TcpQbftNet:
    """Drop-in for core.consensus_qbft.MemMsgNet over the TCP mesh."""

    def __init__(self, node: P2PNode) -> None:
        self.node = node
        self.local = None
        node.register_handler(QBFT_PROTOCOL, self._on_msg)

    def attach(self, consensus) -> int:
        self.local = consensus
        return self.node.index

    async def broadcast(
        self, from_idx: int, duty, msg, values, tctx=None
    ) -> None:
        await self.node.broadcast(
            QBFT_PROTOCOL,
            {"duty": duty, "msg": msg, "vals": values, "tctx": tctx},
        )

    async def _on_msg(self, from_idx: int, m):
        if self.local is not None:
            self.local.deliver(
                m["duty"],
                m["msg"],
                m["vals"],
                tctx=m.get("tctx"),
                sender=from_idx,
            )
        return None
