"""Typed wire codec for the framework's frozen-dataclass messages.

The reference frames delimited protobufs over libp2p streams
(ref: p2p/sender.go protobuf framing). This framework ships TWO codecs
behind one registry:

  * **JSON** (`encode`/`decode`) — the original self-describing encoding
    of registered dataclasses: bytes as hex, enums as ints, tuples as
    lists, nested dataclasses tagged with their registered type name.
    It remains the interop fallback: peers that never negotiated the
    binary wire format (older minors) speak it exclusively.
  * **Binary v1** (`encode_binary`/`decode_binary`) — a schema-compiled
    fixed-layout encoding for the hot frame types (ISSUE 7): at
    registration time each hot dataclass gets a stable numeric wire id
    and a compiled field-order encoder/decoder, so a ParSigEx set or a
    QBFT message serializes as length-prefixed raw bytes (no hex, no
    per-frame schema introspection) in a single pass over one buffer.
    Decode walks a memoryview without intermediate object graphs —
    payload bytes slice straight out of the transport frame. Cold /
    unregistered-for-binary types (the fork-versioned spec containers
    riding inside Proposal) fall back to an embedded JSON value, so
    nothing that the JSON codec could carry is lost.

Untrusted input is decoded only into *registered* types with field
filtering (never pickle). Every malformed-input failure — bad hex in
`__b`, unknown `__e` enum names, non-list `__l`/`__d` payloads,
truncated or over-long binary frames, unknown wire ids — raises the
typed `CodecError` (a ValueError subclass), which the transport read
loop maps to drop-and-count per frame instead of letting a bare
KeyError kill a connection task.

Binary wire-id tables (`_TYPE_WIRE_IDS`, `_ENUM_WIRE_IDS`) are
APPEND-ONLY: ids and the field ORDER of hot types are frozen once
released — a newer minor may append fields (with defaults) or new ids,
never renumber. Unknown trailing fields are decoded and dropped
(values are self-describing), which is what keeps the cross-minor
window of app/version intact on the binary path too.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import struct
from typing import Any, Type

_REGISTRY: dict[str, Type] = {}


class CodecError(ValueError):
    """Malformed wire input (either codec). Subclasses ValueError so
    pre-existing callers that caught ValueError keep working; the
    transport read loop catches THIS to drop-and-count per frame."""


# ---------------------------------------------------------------------------
# Binary wire ids — stable, append-only (see module docstring)
# ---------------------------------------------------------------------------

_TYPE_WIRE_IDS: dict[str, int] = {
    "Duty": 1,
    "SignedData": 2,
    "ParSignedData": 3,
    "Checkpoint": 4,
    "AttestationData": 5,
    "Attestation": 6,
    "BeaconBlockHeader": 7,
    "Proposal": 8,
    "AggregateAndProof": 9,
    "SyncCommitteeMessage": 10,
    "SyncCommitteeContribution": 11,
    "ContributionAndProof": 12,
    "ValidatorRegistration": 13,
    "VoluntaryExit": 14,
    "AttestationDuty": 15,
    "SyncSelectionData": 16,
    "SyncMessageDuty": 17,
    "Msg": 18,  # qbft.Msg
    "PriorityMsg": 19,
    "TopicResult": 20,
    # remote crypto-plane RPC frames (core/cryptosvc_wire) — appended,
    # never renumbered, like everything above
    "CryptoChallenge": 21,
    "CryptoHello": 22,
    "CryptoHelloAck": 23,
    "CryptoSubmit": 24,
    "CryptoResult": 25,
    "CryptoShed": 26,
    "CryptoHeartbeat": 27,
}

_ENUM_WIRE_IDS: dict[str, int] = {
    "DutyType": 1,
    "MsgType": 2,
}

# single-byte ids keep the encoder's header writes branch-free; 127
# hot types is plenty (cold types ride the JSON-fallback tag)
assert all(
    0 < i < 0x80
    for i in (*_TYPE_WIRE_IDS.values(), *_ENUM_WIRE_IDS.values())
)

# value tags (binary v1)
_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_ENUM = 0x09
_T_DATACLASS = 0x0A
_T_JSON = 0x0B  # cold-type fallback: embedded JSON value
_T_BOOLS = 0x0C  # homogeneous bool sequence as a packed bitmap
# (aggregation bitlists dominate attestation frames: 64 tagged values
# become 8 bytes and ONE decode dispatch)

# envelope markers (first byte of a transport frame body). JSON frames
# start with "{" (0x7B) — anything else must match a known version byte,
# which is how mixed-version interop stays sniffable per frame.
BINARY_V1 = 0x01

_PACK_F64 = struct.Struct(">d")
_VARINT_MAX = (1 << 1031) - 1  # decode loops stop at shift > 1024


@dataclasses.dataclass(frozen=True)
class _Schema:
    """Compiled binary layout of one registered dataclass."""

    cls: Type
    wire_id: int | None  # None = cold type (JSON fallback on the wire)
    field_names: tuple[str, ...]
    n_required: int  # leading fields without declared defaults
    getter: Any  # operator.attrgetter over field_names (C-speed reads)
    # trailing defaulted fields as (value, is_factory), aligned with
    # field_names[n_required:] — decode fills omitted tails from here
    defaults: tuple
    # True when construction may bypass __init__ (object.__new__ +
    # direct __dict__ fill): plain frozen dataclasses burn an
    # object.__setattr__ per field in __init__, which would otherwise
    # dominate hot-frame decode. Classes with __post_init__ or __slots__
    # take the normal constructor.
    fast_new: bool


_OBJ_NEW = object.__new__

_SCHEMAS: dict[str, _Schema] = {}
_WIRE_SCHEMAS: dict[int, _Schema] = {}
_WIRE_ENUMS: dict[int, Type] = {}
# encode dispatch: concrete type -> encoder fn, extended at register time
_ENC_DISPATCH: dict[type, Any] = {}


def _compile_schema(cls: Type) -> _Schema:
    import operator

    flds = dataclasses.fields(cls)
    names = tuple(f.name for f in flds)
    n_required = 0
    for f in flds:
        if (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            n_required += 1
        else:
            break
    getter = operator.attrgetter(*names) if len(names) > 1 else (
        operator.attrgetter(names[0]) if names else None
    )
    defaults = tuple(
        (f.default_factory, True)
        if f.default_factory is not dataclasses.MISSING
        else (f.default, False)
        for f in flds[n_required:]
    )
    schema = _Schema(
        cls=cls,
        wire_id=_TYPE_WIRE_IDS.get(cls.__name__),
        field_names=names,
        n_required=n_required,
        getter=getter,
        defaults=defaults,
        fast_new=(
            getattr(cls, "__post_init__", None) is None
            and "__slots__" not in cls.__dict__
        ),
    )
    return schema


def register(cls: Type) -> Type:
    """Register a dataclass for wire transport (decorator-friendly).
    Hot types (those with a stable wire id) get their binary layout
    compiled here, once, instead of introspected per frame."""
    _REGISTRY[cls.__name__] = cls
    schema = _compile_schema(cls)
    _SCHEMAS[cls.__name__] = schema
    if schema.wire_id is not None:
        _WIRE_SCHEMAS[schema.wire_id] = schema
        _ENC_DISPATCH[cls] = _make_dataclass_encoder(schema)
    else:
        _ENC_DISPATCH[cls] = _enc_dataclass
    return cls


_ENUMS: dict[str, Type] = {}


def register_enum(cls: Type) -> Type:
    _ENUMS[cls.__name__] = cls
    wire_id = _ENUM_WIRE_IDS.get(cls.__name__)
    if wire_id is not None:
        _WIRE_ENUMS[wire_id] = cls
        _ENC_DISPATCH[cls] = _make_enum_encoder(wire_id)
    else:
        _ENC_DISPATCH[cls] = _enc_enum
    return cls


def _make_enum_encoder(wire_id: int):
    """Compiled hot-enum encoder: header precomputed, int values
    (IntEnum — every hot enum) emitted inline."""
    header = bytes([_T_ENUM, wire_id])

    def enc(buf: bytearray, v) -> None:
        buf += header
        x = v.value
        if type(x) is int:
            buf.append(_T_INT)
            z = x << 1 if x >= 0 else ((-x) << 1) - 1
            if z < 0x80:
                buf.append(z)
            else:
                _enc_varint(buf, z)
        else:
            _enc_value(buf, x)

    return enc


# ---------------------------------------------------------------------------
# JSON codec (interop fallback + cold-type carrier)
# ---------------------------------------------------------------------------


def _to_jsonable(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        name = type(v).__name__
        if name not in _REGISTRY:
            raise TypeError(f"unregistered dataclass {name}")
        out = {"__t": name}
        for f in dataclasses.fields(v):
            out[f.name] = _to_jsonable(getattr(v, f.name))
        return out
    if isinstance(v, enum.Enum):
        return {"__e": type(v).__name__, "v": v.value}
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, (tuple, list)):
        return {"__l": [_to_jsonable(x) for x in v]}
    if isinstance(v, dict):
        return {"__d": [[_to_jsonable(k), _to_jsonable(x)] for k, x in v.items()]}
    if v is None or isinstance(v, (str, int, float, bool)):
        return v
    raise TypeError(f"cannot encode {type(v)}")


def _from_jsonable(v: Any) -> Any:
    if isinstance(v, dict):
        if "__t" in v:
            cls = _REGISTRY.get(v["__t"])
            if cls is None:
                raise CodecError(f"unknown wire type {v['__t']}")
            # protonil-equivalent guard (ref: app/protonil): REQUIRED
            # fields (those without declared defaults) must be present on
            # the wire — a peer cannot smuggle zero values by omission.
            # Fields with defaults are explicit opt-ins to omissibility,
            # which is what lets a newer minor add fields without
            # breaking the cross-minor window app/version promises.
            missing = [
                f.name
                for f in dataclasses.fields(cls)
                if f.name not in v
                and f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ]
            if missing:
                raise CodecError(
                    f"wire message {v['__t']} missing fields {missing}"
                )
            kwargs = {
                f.name: _from_jsonable(v[f.name])
                for f in dataclasses.fields(cls)
                if f.name in v
            }
            try:
                return cls(**kwargs)
            except (TypeError, ValueError) as e:
                raise CodecError(
                    f"cannot construct wire message {v['__t']}: {e}"
                ) from e
        if "__e" in v:
            cls = _ENUMS.get(v["__e"])
            if cls is None:
                raise CodecError(f"unknown enum {v['__e']}")
            try:
                return cls(v["v"])
            except (ValueError, KeyError, TypeError) as e:
                raise CodecError(f"bad enum value for {v['__e']}") from e
        if "__b" in v:
            try:
                return bytes.fromhex(v["__b"])
            except (ValueError, TypeError) as e:
                raise CodecError("malformed hex in __b payload") from e
        if "__l" in v:
            if not isinstance(v["__l"], list):
                raise CodecError("__l payload must be a list")
            return tuple(_from_jsonable(x) for x in v["__l"])
        if "__d" in v:
            if not isinstance(v["__d"], list):
                raise CodecError("__d payload must be a list of pairs")
            try:
                return {
                    _from_jsonable(k): _from_jsonable(x) for k, x in v["__d"]
                }
            except CodecError:
                raise
            except (ValueError, TypeError) as e:
                raise CodecError("malformed __d pair list") from e
    return v


def encode(msg: Any) -> bytes:
    return json.dumps(_to_jsonable(msg), separators=(",", ":")).encode()


def decode(data: bytes) -> Any:
    """Strict JSON decode: ANY malformed input raises CodecError."""
    try:
        obj = json.loads(bytes(data).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CodecError(f"malformed JSON frame: {e}") from e
    try:
        return _from_jsonable(obj)
    except CodecError:
        raise
    except (ValueError, KeyError, TypeError, RecursionError) as e:
        raise CodecError(f"malformed wire payload: {type(e).__name__}: {e}") from e


def decode_value(obj: Any) -> Any:
    """Strict decode of an already-parsed jsonable payload (the JSON
    envelope's `d` field) — same CodecError mapping as decode()."""
    try:
        return _from_jsonable(obj)
    except CodecError:
        raise
    except (ValueError, KeyError, TypeError, RecursionError) as e:
        raise CodecError(f"malformed wire payload: {type(e).__name__}: {e}") from e


# ---------------------------------------------------------------------------
# Binary codec v1 — encode
# ---------------------------------------------------------------------------


def _enc_varint(buf: bytearray, n: int) -> None:
    """Unsigned LEB128. Capped at the decoders' 1024-bit limit — an
    int no peer can decode must fail at ENCODE time (loud TypeError at
    the sender), not as a silent drop on every receiver."""
    if n > _VARINT_MAX:
        raise TypeError("int exceeds the 1024-bit wire limit")
    while n > 0x7F:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _enc_none(buf: bytearray, v) -> None:
    buf.append(_T_NONE)


def _enc_bool(buf: bytearray, v) -> None:
    buf.append(_T_TRUE if v else _T_FALSE)


def _enc_int(buf: bytearray, v) -> None:
    # zigzag so negatives stay short; arbitrary precision on purpose
    # (uint256 base fees ride spec containers through here)
    buf.append(_T_INT)
    _enc_varint(buf, v << 1 if v >= 0 else ((-v) << 1) - 1)


def _enc_float(buf: bytearray, v) -> None:
    buf.append(_T_FLOAT)
    buf += _PACK_F64.pack(v)


def _enc_str(buf: bytearray, v) -> None:
    raw = v.encode()
    buf.append(_T_STR)
    _enc_varint(buf, len(raw))
    buf += raw


def _enc_bytes(buf: bytearray, v) -> None:
    buf.append(_T_BYTES)
    _enc_varint(buf, len(v))
    buf += v


# byte <-> 8 bools (LSB first): _T_BOOLS packs/expands bitmaps via
# these tables so both directions run at C speed (dict/tuple lookups
# per 8 bits, never a Python loop per bit)
_BYTE_BITS = tuple(
    tuple(bool(b >> i & 1) for i in range(8)) for b in range(256)
)
_BITS_BYTE = {bits: byte for byte, bits in enumerate(_BYTE_BITS)}


def _enc_seq(buf: bytearray, v) -> None:
    n = len(v)
    if n >= 8 and set(map(type, v)) == {bool}:
        # bitlist fast path: LSB-first bitmap (SSZ-style, no sentinel)
        buf.append(_T_BOOLS)
        _enc_varint(buf, n)
        t = tuple(v)
        full = n & ~7
        buf += bytes(
            _BITS_BYTE[t[i : i + 8]] for i in range(0, full, 8)
        )
        if n > full:
            byte = 0
            for i in range(full, n):
                if t[i]:
                    byte |= 1 << (i & 7)
            buf.append(byte)
        return
    buf.append(_T_LIST)
    _enc_varint(buf, n)
    for x in v:
        _enc_value(buf, x)


def _enc_dict(buf: bytearray, v) -> None:
    buf.append(_T_DICT)
    _enc_varint(buf, len(v))
    for k, x in v.items():
        _enc_value(buf, k)
        _enc_value(buf, x)


def _enc_enum(buf: bytearray, v) -> None:
    wire_id = _ENUM_WIRE_IDS.get(type(v).__name__)
    if wire_id is None:
        _enc_json_fallback(buf, v)
        return
    buf.append(_T_ENUM)
    _enc_varint(buf, wire_id)
    _enc_value(buf, v.value)


def _enc_dataclass(buf: bytearray, v) -> None:
    schema = _SCHEMAS.get(type(v).__name__)
    if schema is None or schema.wire_id is None:
        # cold / unregistered-for-binary: embed the JSON encoding (raises
        # TypeError for genuinely unregistered types, same as encode())
        _enc_json_fallback(buf, v)
        return
    # wire ids and field counts are small: single-byte varints inline
    buf.append(_T_DATACLASS)
    buf.append(schema.wire_id)  # table ids are < 0x80 by construction
    names = schema.field_names
    n = len(names)
    if n >= 0x80:
        _enc_varint(buf, n)
    else:
        buf.append(n)
    if n == 1:
        _enc_value(buf, schema.getter(v))
        return
    for x in schema.getter(v):  # attrgetter: one C call for all fields
        _enc_value(buf, x)


def _enc_json_fallback(buf: bytearray, v) -> None:
    raw = json.dumps(_to_jsonable(v), separators=(",", ":")).encode()
    buf.append(_T_JSON)
    _enc_varint(buf, len(raw))
    buf += raw


def _make_dataclass_encoder(schema: _Schema):
    """Compile a hot type's encoder once at registration: header bytes
    precomputed, fields read in one attrgetter call, annotation-typed
    scalar fields emitted inline (type-checked per value — a field
    holding something else falls back to the generic tagged encoder,
    so the wire stays self-describing)."""
    names = schema.field_names
    if not names or len(names) >= 0x80:
        return _enc_dataclass
    header = bytes([_T_DATACLASS, schema.wire_id, len(names)])
    getter = schema.getter
    single = len(names) == 1
    wire_id = schema.wire_id

    def enc(buf: bytearray, v) -> None:
        buf += header
        prog = _PROGS.get(wire_id)
        if prog is None:
            prog = _build_prog(wire_id, schema)
        vals = (getter(v),) if single else getter(v)
        for (kind, _name), x in zip(prog, vals):
            if kind == K_INT and type(x) is int:
                buf.append(_T_INT)
                z = x << 1 if x >= 0 else ((-x) << 1) - 1
                if z < 0x80:
                    buf.append(z)
                else:
                    _enc_varint(buf, z)
            elif kind == K_BYTES and type(x) is bytes:
                buf.append(_T_BYTES)
                n = len(x)
                if n < 0x80:
                    buf.append(n)
                else:
                    _enc_varint(buf, n)
                buf += x
            elif kind == K_STR and type(x) is str:
                raw = x.encode()
                buf.append(_T_STR)
                n = len(raw)
                if n < 0x80:
                    buf.append(n)
                else:
                    _enc_varint(buf, n)
                buf += raw
            else:
                _enc_value(buf, x)

    return enc


_ENC_DISPATCH.update(
    {
        type(None): _enc_none,
        bool: _enc_bool,
        int: _enc_int,
        float: _enc_float,
        str: _enc_str,
        bytes: _enc_bytes,
        tuple: _enc_seq,
        list: _enc_seq,
        dict: _enc_dict,
    }
)


def _enc_value(buf: bytearray, v) -> None:
    # inline hot scalar paths (ints/bytes/strs dominate hot frames);
    # everything else goes through the per-type dispatch table
    t = v.__class__
    if t is int:
        buf.append(_T_INT)
        z = v << 1 if v >= 0 else ((-v) << 1) - 1
        if z < 0x80:
            buf.append(z)
        else:
            _enc_varint(buf, z)
        return
    if t is bytes:
        buf.append(_T_BYTES)
        n = len(v)
        if n < 0x80:
            buf.append(n)
        else:
            _enc_varint(buf, n)
        buf += v
        return
    if t is str:
        raw = v.encode()
        buf.append(_T_STR)
        n = len(raw)
        if n < 0x80:
            buf.append(n)
        else:
            _enc_varint(buf, n)
        buf += raw
        return
    fn = _ENC_DISPATCH.get(t)
    if fn is not None:
        fn(buf, v)
        return
    # subclass / first-seen-type slow path; the resolution is cached so
    # e.g. PubKey (a str NewType at runtime: plain str) or a memoryview
    # costs the isinstance chain exactly once per type
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        fn = _enc_dataclass
    elif isinstance(v, enum.Enum):
        fn = _enc_enum
    elif isinstance(v, bool):
        fn = _enc_bool
    elif isinstance(v, int):
        fn = _enc_int
    elif isinstance(v, float):
        fn = _enc_float
    elif isinstance(v, str):
        fn = _enc_str
    elif isinstance(v, (bytes, bytearray, memoryview)):
        fn = _enc_bytes
    elif isinstance(v, (tuple, list)):
        fn = _enc_seq
    elif isinstance(v, dict):
        fn = _enc_dict
    elif v is None:
        fn = _enc_none
    else:
        raise TypeError(f"cannot encode {type(v)}")
    _ENC_DISPATCH[v.__class__] = fn
    fn(buf, v)


def encode_binary(msg: Any) -> bytes:
    """Binary v1 encoding of a message (no envelope marker — the
    transport prepends its version byte)."""
    buf = bytearray()
    _enc_value(buf, msg)
    return bytes(buf)


# ---------------------------------------------------------------------------
# Binary codec v1 — decode (memoryview walk, bounds-checked throughout)
# ---------------------------------------------------------------------------


def _dec_varint(mv, pos: int, end: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = mv[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        # generous cap: zigzag uint256 values (spec containers) fit in
        # 257 bits; anything past 1024 bits is a malformed frame
        if shift > 1024:
            raise CodecError("oversized varint")


# -- compiled field programs (decode side) ----------------------------------
#
# At first decode of a wire id, the schema's field ANNOTATIONS compile
# to a per-field kind program: (K_INT, K_BYTES, K_STR, K_NESTED schema,
# K_ENUM cls, K_GENERIC). The hot decode loop then PREDICTS each
# field's tag instead of walking the generic tag chain — a mispredicted
# tag (schema evolution, union-typed fields) simply falls back to the
# generic decoder, so the wire format stays fully self-describing.

K_GENERIC, K_INT, K_BYTES, K_STR, K_NESTED, K_ENUM = range(6)

_PROGS: dict[int, tuple] = {}


def _build_prog(wire_id: int, schema: _Schema) -> tuple:
    """(kind, field_name) per field — the decode loop writes
    values straight into the instance __dict__ by name, so there is no
    args list, no zip, no per-field append."""
    flds = dataclasses.fields(schema.cls)
    prog = []
    for f in flds:
        t = f.type if isinstance(f.type, str) else getattr(
            f.type, "__name__", ""
        )
        if t == "int":
            kind = K_INT
        elif t == "bytes":
            kind = K_BYTES
        elif t == "str":
            kind = K_STR
        elif t in _TYPE_WIRE_IDS and t in _SCHEMAS:
            kind = K_NESTED
        elif t in _ENUM_WIRE_IDS and t in _ENUMS:
            kind = K_ENUM
        else:
            kind = K_GENERIC
        prog.append((kind, f.name))
    out = tuple(prog)
    _PROGS[wire_id] = out
    return out


def _dec_many(
    mv,
    pos: int,
    end: int,
    depth: int,
    count: int,
    # hot-loop locals: globals are dict lookups per access in CPython;
    # default-arg binding makes every tag compare an array load
    _int=_T_INT,
    _bytes_t=_T_BYTES,
    _str_t=_T_STR,
    _none=_T_NONE,
    _true=_T_TRUE,
    _false=_T_FALSE,
    _varint=None,
    _bytes=bytes,
) -> tuple:
    """Decode `count` consecutive values into a list. The scalar tags
    (ints, byte blobs, strings, the singletons) that carry nearly every
    value of a hot frame are handled INLINE in this one loop — a
    ParSigEx set decodes with one Python call per CONTAINER, not one
    per value, which is where the 5x over json.loads+_from_jsonable
    comes from on the decode side."""
    out: list = []
    append = out.append
    while count:
        count -= 1
        tag = mv[pos]
        pos += 1
        if tag == _int:
            z = 0
            shift = 0
            while True:
                if pos >= end:
                    raise CodecError("truncated varint")
                b = mv[pos]
                pos += 1
                z |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
                if shift > 1024:
                    raise CodecError("oversized varint")
            append((z >> 1) ^ -(z & 1))
        elif tag == _bytes_t:
            if pos < end and mv[pos] < 0x80:
                n = mv[pos]
                pos += 1
            else:
                n, pos = _dec_varint(mv, pos, end)
            if pos + n > end:
                raise CodecError("truncated bytes")
            # the ONE copy: frame buffer -> final object
            append(mv[pos : pos + n])
            pos += n
        elif tag == _str_t:
            if pos < end and mv[pos] < 0x80:
                n = mv[pos]
                pos += 1
            else:
                n, pos = _dec_varint(mv, pos, end)
            if pos + n > end:
                raise CodecError("truncated string")
            try:
                append(mv[pos : pos + n].decode())
            except UnicodeDecodeError as e:
                raise CodecError("malformed utf-8 string") from e
            pos += n
        elif tag == _none:
            append(None)
        elif tag == _true:
            append(True)
        elif tag == _false:
            append(False)
        else:
            v, pos = _dec_tagged(mv, pos, end, depth, tag)
            append(v)
    return out, pos


def _dec_value(
    mv,
    pos: int,
    end: int,
    depth: int = 0,
    _int=_T_INT,
    _bytes_t=_T_BYTES,
    _str_t=_T_STR,
    _none=_T_NONE,
    _true=_T_TRUE,
    _false=_T_FALSE,
    _bytes=bytes,
):
    """Decode ONE value: inline scalars (the same fast paths as
    _dec_many, duplicated on purpose — a wrapper call per scalar value
    is exactly the overhead this codec exists to remove), containers
    via _dec_tagged."""
    tag = mv[pos]
    pos += 1
    if tag == _int:
        z = 0
        shift = 0
        while True:
            b = mv[pos]
            pos += 1
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 1024:
                raise CodecError("oversized varint")
        return (z >> 1) ^ -(z & 1), pos
    if tag == _bytes_t:
        if pos < end and mv[pos] < 0x80:
            n = mv[pos]
            pos += 1
        else:
            n, pos = _dec_varint(mv, pos, end)
        if pos + n > end:
            raise CodecError("truncated bytes")
        return mv[pos : pos + n], pos + n
    if tag == _str_t:
        if pos < end and mv[pos] < 0x80:
            n = mv[pos]
            pos += 1
        else:
            n, pos = _dec_varint(mv, pos, end)
        if pos + n > end:
            raise CodecError("truncated string")
        try:
            return mv[pos : pos + n].decode(), pos + n
        except UnicodeDecodeError as e:
            raise CodecError("malformed utf-8 string") from e
    if tag == _none:
        return None, pos
    if tag == _true:
        return True, pos
    if tag == _false:
        return False, pos
    return _dec_tagged(mv, pos, end, depth, tag)


def _dec_tagged(
    mv,
    pos: int,
    end: int,
    depth: int,
    tag: int,
    _k_int=K_INT,
    _k_bytes=K_BYTES,
    _k_str=K_STR,
    _k_nested=K_NESTED,
    _k_enum=K_ENUM,
    _k_generic=K_GENERIC,
    _t_int=_T_INT,
    _t_bytes=_T_BYTES,
    _t_str=_T_STR,
    _t_list=_T_LIST,
    _t_dataclass=_T_DATACLASS,
    _t_enum_t=_T_ENUM,
    _bytes=bytes,
):
    """Container / rare tags (the scalar tags live in _dec_many)."""
    if depth > 32:
        raise CodecError("binary payload nests too deep")
    if tag == _t_dataclass:
        # header ints are single-byte in practice (ids < 0x80, small
        # field counts): inline the fast path, fall back for the rest
        if pos < end and mv[pos] < 0x80:
            wire_id = mv[pos]
            pos += 1
        else:
            wire_id, pos = _dec_varint(mv, pos, end)
        schema = _WIRE_SCHEMAS.get(wire_id)
        if schema is None:
            raise CodecError(f"unknown dataclass wire id {wire_id}")
        if pos < end and mv[pos] < 0x80:
            nfields = mv[pos]
            pos += 1
        else:
            nfields, pos = _dec_varint(mv, pos, end)
        if nfields > end - pos:
            raise CodecError("field count exceeds frame")
        names = schema.field_names
        if nfields < schema.n_required:
            raise CodecError(
                f"wire message {schema.cls.__name__} missing fields "
                f"{list(names[nfields:schema.n_required])}"
            )
        prog = _PROGS.get(wire_id)
        if prog is None:
            prog = _build_prog(wire_id, schema)
        n_prog = len(prog)
        d: dict = {}
        depth1 = depth + 1
        extra = 0
        if nfields == n_prog:
            kinds = prog  # exact schema match: no per-field bounds
        elif nfields < n_prog:
            kinds = prog[:nfields]
        else:
            kinds = prog
            extra = nfields - n_prog
        for kind, name in kinds:
            vtag = mv[pos]
            if kind == _k_int and vtag == _t_int:
                pos += 1
                z = 0
                shift = 0
                while True:
                    b = mv[pos]
                    pos += 1
                    z |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                    if shift > 1024:
                        raise CodecError("oversized varint")
                d[name] = (z >> 1) ^ -(z & 1)
            elif kind == _k_bytes and vtag == _t_bytes:
                pos += 1
                if pos < end and mv[pos] < 0x80:
                    n = mv[pos]
                    pos += 1
                else:
                    n, pos = _dec_varint(mv, pos, end)
                if pos + n > end:
                    raise CodecError("truncated bytes")
                d[name] = mv[pos : pos + n]
                pos += n
            elif kind == _k_str and vtag == _t_str:
                pos += 1
                if pos < end and mv[pos] < 0x80:
                    n = mv[pos]
                    pos += 1
                else:
                    n, pos = _dec_varint(mv, pos, end)
                if pos + n > end:
                    raise CodecError("truncated string")
                try:
                    d[name] = mv[pos : pos + n].decode()
                except UnicodeDecodeError as e:
                    raise CodecError("malformed utf-8 string") from e
                pos += n
            elif (kind == _k_nested and vtag == _t_dataclass) or (
                kind == _k_enum and vtag == _t_enum_t
            ) or vtag >= _t_list:
                # containers (predicted or not) skip the scalar chain
                d[name], pos = _dec_tagged(mv, pos + 1, end, depth1, vtag)
            else:
                # mispredicted / generic / evolved field: self-describing
                d[name], pos = _dec_value(mv, pos, end, depth1)
        for _ in range(extra):
            # trailing unknown fields (newer minor): decoded and dropped
            _v, pos = _dec_value(mv, pos, end, depth1)
        if schema.fast_new:
            if nfields < n_prog:
                for name, (dv, isf) in zip(
                    names[nfields:],
                    schema.defaults[nfields - schema.n_required :],
                ):
                    d[name] = dv() if isf else dv
            obj = _OBJ_NEW(schema.cls)
            # one C-level bulk fill (plain `__dict__ = d` would trip the
            # frozen dataclass __setattr__ guard)
            obj.__dict__.update(d)
            return obj, pos
        try:
            # omitted defaulted tails fill from the class defaults
            return schema.cls(**d), pos
        except (TypeError, ValueError) as e:
            raise CodecError(
                f"cannot construct wire message {schema.cls.__name__}: {e}"
            ) from e
    if tag == _T_BOOLS:
        if pos < end and mv[pos] < 0x80:
            n = mv[pos]
            pos += 1
        else:
            n, pos = _dec_varint(mv, pos, end)
        nbytes = (n + 7) // 8
        if pos + nbytes > end:
            raise CodecError("truncated bool bitmap")
        bits: list = []
        extend = bits.extend
        table = _BYTE_BITS
        for i in range(pos, pos + nbytes):
            extend(table[mv[i]])
        return tuple(bits[:n]), pos + nbytes
    if tag == _T_LIST:
        if pos < end and mv[pos] < 0x80:
            n = mv[pos]
            pos += 1
        else:
            n, pos = _dec_varint(mv, pos, end)
        if n > end - pos:
            raise CodecError("list count exceeds frame")
        out, pos = _dec_many(mv, pos, end, depth + 1, n)
        return tuple(out), pos
    if tag == _T_DICT:
        if pos < end and mv[pos] < 0x80:
            n = mv[pos]
            pos += 1
        else:
            n, pos = _dec_varint(mv, pos, end)
        if 2 * n > end - pos:
            raise CodecError("dict count exceeds frame")
        flat, pos = _dec_many(mv, pos, end, depth + 1, 2 * n)
        try:
            return dict(zip(flat[0::2], flat[1::2])), pos
        except TypeError as e:
            raise CodecError("unhashable dict key") from e
    if tag == _T_ENUM:
        wire_id, pos = _dec_varint(mv, pos, end)
        cls = _WIRE_ENUMS.get(wire_id)
        if cls is None:
            raise CodecError(f"unknown enum wire id {wire_id}")
        raw, pos = _dec_value(mv, pos, end, depth + 1)
        try:
            # direct member-map lookup: EnumMeta.__call__ costs ~15x
            # more and this runs per enum field of every hot frame
            return cls._value2member_map_[raw], pos
        except (KeyError, TypeError):
            pass
        try:
            return cls(raw), pos  # non-canonical values (aliases)
        except (ValueError, KeyError, TypeError) as e:
            raise CodecError(f"bad enum value for {cls.__name__}") from e
    if tag == _T_FLOAT:
        if pos + 8 > end:
            raise CodecError("truncated float")
        return _PACK_F64.unpack_from(mv, pos)[0], pos + 8
    if tag == _T_JSON:
        n, pos = _dec_varint(mv, pos, end)
        if pos + n > end:
            raise CodecError("truncated embedded JSON")
        try:
            obj = json.loads(mv[pos : pos + n])
        except (ValueError, UnicodeDecodeError) as e:
            raise CodecError("malformed embedded JSON") from e
        return decode_value(obj), pos + n
    raise CodecError(f"unknown binary tag 0x{tag:02x}")


def decode_binary(data) -> Any:
    """Binary v1 decode of one value. Accepts bytes or any buffer.
    Decodes IN PLACE over the frame buffer (offsets, no intermediate
    object graph; the one copy per bytes field is the slice into the
    final object). Raises CodecError on any malformation, including
    trailing garbage."""
    if not isinstance(data, bytes):
        data = bytes(data)
    try:
        v, pos = _dec_value(data, 0, len(data))
    except IndexError:
        # single-byte reads rely on the buffer's own bounds (slice
        # reads keep explicit guards — slices never raise)
        raise CodecError("truncated binary value") from None
    if pos != len(data):
        raise CodecError("trailing bytes after binary value")
    return v


# ---------------------------------------------------------------------------
# Transport envelope (both codecs behind one surface)
# ---------------------------------------------------------------------------
#
# JSON envelope (wire version 0):   {"p": .., "id": .., "k": "req"|"rsp",
#                                    "d": jsonable payload | null}
# Binary envelope (wire version 1): 0x01 | varint len + protocol utf8
#                                   | varint len + request id utf8
#                                   | kind byte (0 req, 1 rsp)
#                                   | binary value (payload; _T_NONE tag
#                                     for an empty payload)
#
# The first byte discriminates: JSON frames start with "{" (0x7B), a
# binary v1 frame with 0x01 — so a receiver never needs per-connection
# state to parse a frame, only to choose what it SENDS (negotiated in
# the p2p handshake; see transport._Conn.wire).


def encode_envelope(
    protocol: str, req_id: str, kind: str, msg: Any, binary: bool
) -> bytes:
    if not binary:
        return json.dumps(
            {
                "p": protocol,
                "id": req_id,
                "k": kind,
                "d": _to_jsonable(msg) if msg is not None else None,
            }
        ).encode()
    buf = bytearray([BINARY_V1])
    raw_p = protocol.encode()
    _enc_varint(buf, len(raw_p))
    buf += raw_p
    # a peer's envelope may carry no request id (fire-and-forget JSON
    # frames omit it) — the response encoder must not crash on None
    raw_id = req_id.encode() if isinstance(req_id, str) else b""
    _enc_varint(buf, len(raw_id))
    buf += raw_id
    buf.append(1 if kind == "rsp" else 0)
    _enc_value(buf, msg)
    return bytes(buf)


def decode_envelope(frame) -> dict:
    """One decrypted transport frame -> {"p", "id", "k", "d"} with the
    payload fully decoded. Sniffs the leading byte: JSON vs binary v1.
    Raises CodecError on any malformation."""
    if not isinstance(frame, bytes):
        frame = bytes(frame)
    if not frame:
        raise CodecError("empty frame")
    mv = frame
    lead = mv[0]
    if lead == BINARY_V1:
        end = len(mv)
        try:
            n, pos = _dec_varint(mv, 1, end)
            if pos + n > end:
                raise CodecError("truncated envelope protocol")
            try:
                protocol = mv[pos : pos + n].decode()
            except UnicodeDecodeError as e:
                raise CodecError("malformed envelope protocol") from e
            pos += n
            n, pos = _dec_varint(mv, pos, end)
            if pos + n > end:
                raise CodecError("truncated envelope request id")
            try:
                req_id = mv[pos : pos + n].decode()
            except UnicodeDecodeError as e:
                raise CodecError("malformed envelope request id") from e
            pos += n
            if pos >= end:
                raise CodecError("truncated envelope kind")
            kind = "rsp" if mv[pos] else "req"
            pos += 1
            payload, pos = _dec_value(mv, pos, end)
        except IndexError:
            raise CodecError("truncated binary envelope") from None
        if pos != end:
            raise CodecError("trailing bytes after envelope payload")
        return {"p": protocol, "id": req_id, "k": kind, "d": payload}
    if lead != 0x7B:  # "{"
        raise CodecError(f"unknown envelope version byte 0x{lead:02x}")
    try:
        env = json.loads(frame)
    except (ValueError, UnicodeDecodeError) as e:
        raise CodecError(f"malformed JSON envelope: {e}") from e
    if not isinstance(env, dict) or "p" not in env or "k" not in env:
        raise CodecError("JSON envelope missing required keys")
    return {
        "p": env["p"],
        "id": env.get("id"),
        "k": env["k"],
        "d": (
            decode_value(env["d"]) if env.get("d") is not None else None
        ),
    }


def _register_core_types() -> None:
    from charon_tpu.core import eth2data as d
    from charon_tpu.core import qbft
    from charon_tpu.core.types import Duty, DutyType
    from charon_tpu.eth2util import spec

    # fork-versioned spec containers ride inside Proposal values during
    # proposer consensus (ref: corepb carries the full VersionedProposal)
    for cls in (
        spec.Eth1Data,
        spec.SignedBeaconBlockHeader,
        spec.ProposerSlashing,
        spec.IndexedAttestation,
        spec.AttesterSlashing,
        spec.DepositData,
        spec.Deposit,
        spec.SignedVoluntaryExit,
        spec.SyncAggregate,
        spec.BLSToExecutionChange,
        spec.SignedBLSToExecutionChange,
        spec.Withdrawal,
        spec.ExecutionPayloadCapella,
        spec.ExecutionPayloadDeneb,
        spec.ExecutionPayloadHeaderCapella,
        spec.ExecutionPayloadHeaderDeneb,
        spec.BeaconBlockBodyCapella,
        spec.BlindedBeaconBlockBodyCapella,
        spec.BeaconBlockBodyDeneb,
        spec.BlindedBeaconBlockBodyDeneb,
        spec.BeaconBlockCapella,
        spec.BlindedBeaconBlockCapella,
        spec.BeaconBlockDeneb,
        spec.BlindedBeaconBlockDeneb,
    ):
        register(cls)

    for cls in (
        Duty,
        d.Checkpoint,
        d.AttestationData,
        d.Attestation,
        d.BeaconBlockHeader,
        d.Proposal,
        d.AggregateAndProof,
        d.SyncCommitteeMessage,
        d.SyncCommitteeContribution,
        d.ContributionAndProof,
        d.ValidatorRegistration,
        d.VoluntaryExit,
        d.AttestationDuty,
        d.SignedData,
        d.ParSignedData,
        d.SyncSelectionData,
        d.SyncMessageDuty,
        qbft.Msg,
    ):
        register(cls)
    register_enum(DutyType)
    register_enum(qbft.MsgType)

    # priority negotiation rides the p2p mesh and the consensus value
    # set (ref: core/corepb PriorityMsg / PriorityTopicResult)
    from charon_tpu.core import priority

    register(priority.PriorityMsg)
    register(priority.TopicResult)

    # remote crypto-plane RPC frames self-register on import (their
    # wire ids live in _TYPE_WIRE_IDS above; the schema golden check
    # snapshots them through this import)
    from charon_tpu.core import cryptosvc_wire  # noqa: F401


_register_core_types()
