"""Typed wire codec for the framework's frozen-dataclass messages.

The reference frames delimited protobufs over libp2p streams
(ref: p2p/sender.go protobuf framing); this framework's wire format is a
self-describing JSON encoding of its registered dataclasses — bytes as
hex, enums as ints, tuples as lists, nested dataclasses tagged with their
registered type name. Untrusted input is decoded only into *registered*
types with field filtering (never pickle).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Type

_REGISTRY: dict[str, Type] = {}


def register(cls: Type) -> Type:
    """Register a dataclass for wire transport (decorator-friendly)."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _to_jsonable(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        name = type(v).__name__
        if name not in _REGISTRY:
            raise TypeError(f"unregistered dataclass {name}")
        out = {"__t": name}
        for f in dataclasses.fields(v):
            out[f.name] = _to_jsonable(getattr(v, f.name))
        return out
    if isinstance(v, enum.Enum):
        return {"__e": type(v).__name__, "v": v.value}
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, (tuple, list)):
        return {"__l": [_to_jsonable(x) for x in v]}
    if isinstance(v, dict):
        return {"__d": [[_to_jsonable(k), _to_jsonable(x)] for k, x in v.items()]}
    if v is None or isinstance(v, (str, int, float, bool)):
        return v
    raise TypeError(f"cannot encode {type(v)}")


_ENUMS: dict[str, Type] = {}


def register_enum(cls: Type) -> Type:
    _ENUMS[cls.__name__] = cls
    return cls


def _from_jsonable(v: Any) -> Any:
    if isinstance(v, dict):
        if "__t" in v:
            cls = _REGISTRY.get(v["__t"])
            if cls is None:
                raise ValueError(f"unknown wire type {v['__t']}")
            # protonil-equivalent guard (ref: app/protonil): REQUIRED
            # fields (those without declared defaults) must be present on
            # the wire — a peer cannot smuggle zero values by omission.
            # Fields with defaults are explicit opt-ins to omissibility,
            # which is what lets a newer minor add fields without
            # breaking the cross-minor window app/version promises.
            missing = [
                f.name
                for f in dataclasses.fields(cls)
                if f.name not in v
                and f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ]
            if missing:
                raise ValueError(
                    f"wire message {v['__t']} missing fields {missing}"
                )
            kwargs = {
                f.name: _from_jsonable(v[f.name])
                for f in dataclasses.fields(cls)
                if f.name in v
            }
            return cls(**kwargs)
        if "__e" in v:
            cls = _ENUMS.get(v["__e"])
            if cls is None:
                raise ValueError(f"unknown enum {v['__e']}")
            return cls(v["v"])
        if "__b" in v:
            return bytes.fromhex(v["__b"])
        if "__l" in v:
            return tuple(_from_jsonable(x) for x in v["__l"])
        if "__d" in v:
            return {
                _from_jsonable(k): _from_jsonable(x) for k, x in v["__d"]
            }
    return v


def encode(msg: Any) -> bytes:
    return json.dumps(_to_jsonable(msg), separators=(",", ":")).encode()


def decode(data: bytes) -> Any:
    return _from_jsonable(json.loads(data.decode()))


def _register_core_types() -> None:
    from charon_tpu.core import eth2data as d
    from charon_tpu.core import qbft
    from charon_tpu.core.types import Duty, DutyType
    from charon_tpu.eth2util import spec

    # fork-versioned spec containers ride inside Proposal values during
    # proposer consensus (ref: corepb carries the full VersionedProposal)
    for cls in (
        spec.Eth1Data,
        spec.SignedBeaconBlockHeader,
        spec.ProposerSlashing,
        spec.IndexedAttestation,
        spec.AttesterSlashing,
        spec.DepositData,
        spec.Deposit,
        spec.SignedVoluntaryExit,
        spec.SyncAggregate,
        spec.BLSToExecutionChange,
        spec.SignedBLSToExecutionChange,
        spec.Withdrawal,
        spec.ExecutionPayloadCapella,
        spec.ExecutionPayloadDeneb,
        spec.ExecutionPayloadHeaderCapella,
        spec.ExecutionPayloadHeaderDeneb,
        spec.BeaconBlockBodyCapella,
        spec.BlindedBeaconBlockBodyCapella,
        spec.BeaconBlockBodyDeneb,
        spec.BlindedBeaconBlockBodyDeneb,
        spec.BeaconBlockCapella,
        spec.BlindedBeaconBlockCapella,
        spec.BeaconBlockDeneb,
        spec.BlindedBeaconBlockDeneb,
    ):
        register(cls)

    for cls in (
        Duty,
        d.Checkpoint,
        d.AttestationData,
        d.Attestation,
        d.BeaconBlockHeader,
        d.Proposal,
        d.AggregateAndProof,
        d.SyncCommitteeMessage,
        d.SyncCommitteeContribution,
        d.ContributionAndProof,
        d.ValidatorRegistration,
        d.VoluntaryExit,
        d.AttestationDuty,
        d.SignedData,
        d.ParSignedData,
        d.SyncSelectionData,
        d.SyncMessageDuty,
        qbft.Msg,
    ):
        register(cls)
    register_enum(DutyType)
    register_enum(qbft.MsgType)

    # priority negotiation rides the p2p mesh and the consensus value
    # set (ref: core/corepb PriorityMsg / PriorityTopicResult)
    from charon_tpu.core import priority

    register(priority.PriorityMsg)
    register(priority.TopicResult)


_register_core_types()
