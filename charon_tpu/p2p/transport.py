"""Asyncio TCP peer mesh: authenticated, gated, typed request/response.

Mirrors ref: p2p/ —
  * NewTCPNode (p2p/p2p.go:36): here one asyncio TCP server per node plus
    one outbound connection per peer, lazily dialed with backoff;
  * conn gater (p2p/gater.go:16): the handshake proves possession of the
    peer's registered secp256k1 key; unknown keys are dropped;
  * Sender.SendAsync/SendReceive (p2p/sender.go:90): protocol-tagged
    frames with request ids, send/receive timeouts, per-peer failure
    hysteresis to suppress log storms (sender.go:85-110);
  * RegisterHandler (p2p/receive.go:40): async handler per protocol id;
  * ping (p2p/ping.go): continuous keepalive feeding peer-health state.

Frame format (ISSUE 7): 4-byte big-endian length, then the sealed
envelope. After decryption the first byte discriminates the codec —
0x01 is a binary v1 envelope (length-prefixed protocol/id fields, raw
payload bytes, decoded by memoryview slices with no intermediate
object graph), "{" is the original JSON envelope {"p": protocol,
"id": reqid, "k": "req"|"rsp", "d": codec payload}. Which format a
node SENDS is negotiated in the handshake ("wire" field, min of both
sides, absent = 0 = JSON) so a binary-speaking node interops with a
JSON-speaking peer frame-for-frame; what it ACCEPTS is sniffed per
frame, so mixed-version clusters never wedge mid-rollout.

A malformed frame of either codec raises the typed codec.CodecError
and is dropped-and-counted per frame (codec_dropped) — decode
strictness must never kill the authenticated connection carrying live
consensus traffic. Max frame 128 MB and 5s/7s recv/send timeouts
follow the reference's envelope (p2p/sender.go:23-29).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from charon_tpu.app import k1util, log
from charon_tpu.app.errors import StructuredError
from charon_tpu.p2p import codec, quarantine

MAX_FRAME = 128 * 1024 * 1024  # ref: p2p/sender.go:26
SEND_TIMEOUT = 7.0  # ref: p2p/sender.go:28
RECV_TIMEOUT = 5.0  # ref: p2p/sender.go:27
HYSTERESIS_FAILS = 3  # suppress errors after this many consecutive fails
# Per-peer codec quarantine (ISSUE 8 satellite): dropping-and-counting
# malformed frames keeps the conn alive, but a peer STREAMING garbage
# (buggy build, fuzzing adversary) still costs a decode attempt + a log
# line per frame. After QUARANTINE_STRIKES CodecErrors inside
# QUARANTINE_WINDOW seconds the peer is temporarily muted — its frames
# drop before decode — for QUARANTINE_BASE seconds, doubling per repeat
# offense up to QUARANTINE_MAX; a clean frame after the mute expires
# forgives the backoff level. (State machine: p2p/quarantine.py —
# cryptography-free so the fast tier exercises it everywhere.)
QUARANTINE_STRIKES = quarantine.QUARANTINE_STRIKES
QUARANTINE_WINDOW = quarantine.QUARANTINE_WINDOW
QUARANTINE_BASE = quarantine.QUARANTINE_BASE
QUARANTINE_MAX = quarantine.QUARANTINE_MAX
# Highest binary wire format this build speaks (0 = JSON only). The
# handshake advertises it; each connection sends min(ours, theirs).
WIRE_VERSION = 1


@dataclass(frozen=True)
class PeerSpec:
    index: int
    pubkey: bytes  # 33-byte compressed secp256k1
    host: str
    port: int


class HandshakeError(StructuredError):
    """Mutual-auth failure; carries peer context fields
    (ref: app/errors structured errors at the p2p boundary)."""


class FrameError(ValueError):
    """Unsendable frame at the transport boundary (oversize payload).
    A ValueError subclass so broadcast()'s payload-bug logging keeps
    seeing it, typed so transport handlers can tell a local framing
    bug from the network errors the hysteresis counters absorb."""


@dataclass
class _Conn:
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    peer_idx: int
    # Per-connection AES-GCM key from static-static ECDH + handshake
    # nonces; every frame is sealed (confidentiality + integrity) with a
    # (direction, counter) nonce so a relay or on-path attacker can
    # neither read, inject, reorder, nor replay frames. Confidentiality
    # matters because DKG secret shares ride this channel (the reference
    # gets both properties from mutual libp2p-TLS, p2p/p2p.go).
    mac_key: bytes = b""
    send_dir: bytes = b"\x01"
    recv_dir: bytes = b"\x02"
    send_ctr: int = 0
    recv_ctr: int = 0
    # negotiated wire format this connection SENDS (min of both sides'
    # advertised versions; 0 = JSON). Inbound frames are sniffed per
    # frame regardless, so this only selects the outbound encoding.
    wire: int = 0
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    def _aead(self):
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        return AESGCM(self.mac_key)


def _nonce(direction: bytes, ctr: int) -> bytes:
    return direction * 4 + ctr.to_bytes(8, "big")  # 12 bytes


def _write_sframe(conn: _Conn, body: bytes) -> None:
    sealed = conn._aead().encrypt(
        _nonce(conn.send_dir, conn.send_ctr), body, None
    )
    # Write first, then advance the counter: an oversized-frame ValueError
    # must not desynchronize the nonce counters of a healthy connection.
    _write_frame(conn.writer, sealed)
    conn.send_ctr += 1


async def _read_sframe(conn: _Conn) -> bytes:
    frame = await _read_frame(conn.reader)
    try:
        body = conn._aead().decrypt(
            _nonce(conn.recv_dir, conn.recv_ctr), frame, None
        )
    except Exception as e:
        raise ConnectionError(f"frame decryption failed: {e}") from e
    conn.recv_ctr += 1
    return body


class P2PNode:
    def __init__(
        self,
        index: int,
        privkey,
        peers: list[PeerSpec],
        cluster_hash: bytes,
        relay=None,  # p2p.relay.RelayClient for NAT fallback
        wire_version: int = WIRE_VERSION,  # 0 forces the JSON codec
    ) -> None:
        self.index = index
        self.key = privkey
        self.peers = {p.index: p for p in peers if p.index != index}
        self.self_spec = next(p for p in peers if p.index == index)
        self.cluster_hash = cluster_hash
        self.relay = relay
        self.wire_version = wire_version
        self._server: asyncio.AbstractServer | None = None
        self._conns: dict[int, _Conn] = {}
        self._handlers: dict[str, Callable] = {}
        self._pending: dict[str, asyncio.Future] = {}
        self._fail_counts: dict[int, int] = {}
        self._ping_task: asyncio.Task | None = None
        self.ping_success: dict[int, bool] = {}
        self._recv_tasks: set[asyncio.Task] = set()
        # per-frame typed drops (codec.CodecError on a live connection)
        self.codec_dropped = 0
        # per-peer codec quarantine (see QUARANTINE_* above); module
        # constants are read at construction so tests can shrink them
        self._quarantine = quarantine.PeerQuarantine(
            strikes=QUARANTINE_STRIKES,
            window=QUARANTINE_WINDOW,
            base=QUARANTINE_BASE,
            max_mute=QUARANTINE_MAX,
            observer=self._on_quarantine,
        )
        self.quarantined_frames = 0  # frames dropped undecoded while muted
        # optional quarantine sink: called with (peer_idx, mute_seconds)
        self.quarantine_observer: Callable | None = None
        # optional wire metrics sink: called with (direction "tx"|"rx",
        # codec "binary"|"json", frame_bytes, codec_seconds). Must be
        # cheap and thread-safe (app/metrics.ClusterMetrics.wire_hook).
        self.wire_observer: Callable | None = None

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_inbound, self.self_spec.host, self.self_spec.port
        )
        self.register_handler("ping", self._handle_ping)
        if self.relay is not None:
            # inbound relayed streams get the normal responder handshake.
            # A dead relay degrades to direct-only dialing — a FALLBACK
            # must never make startup depend on it.
            self.relay.set_stream_acceptor(self._on_relay_stream)
            try:
                await self.relay.connect()
            except OSError as e:
                from charon_tpu.app import log

                log.warn(
                    "relay unreachable; direct-only p2p",
                    topic="p2p",
                    err=str(e),
                )
                self.relay = None

    async def _on_relay_stream(self, peer_idx: int, reader, writer) -> None:
        await self._on_inbound(reader, writer)

    async def stop(self) -> None:
        if self.relay is not None:
            await self.relay.close()
        if self._ping_task:
            self._ping_task.cancel()
        for task in list(self._recv_tasks):
            task.cancel()
        for conn in list(self._conns.values()):
            conn.writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    def register_handler(self, protocol: str, handler) -> None:
        """ref: p2p/receive.go:40 RegisterHandler."""
        self._handlers[protocol] = handler

    # -- handshake --------------------------------------------------------
    #
    # Mutual authentication (ADVICE round 1; ref gets this from libp2p-TLS
    # with pinned peer identities, p2p/p2p.go):
    #   1. responder sends nonce_s;
    #   2. dialer sends {idx, nonce_c, sig over transcript(dialer_idx,
    #      responder_idx, nonce_s, nonce_c)} — binding BOTH identities and
    #      BOTH nonces, so the challenge cannot be relayed to a third peer;
    #   3. responder verifies, replies {idx, sig over ack-transcript};
    #      dialer verifies against the pubkey of the peer it dialed.
    # Both sides then derive a per-connection MAC key from static-static
    # ECDH + the nonces; every subsequent frame is HMAC'd with a direction
    # byte and a monotonically increasing counter (no injection/replay).

    def _transcript(self, tag: bytes, dialer: int, responder: int,
                    nonce_s: bytes, nonce_c: bytes) -> bytes:
        return hashlib.sha256(
            tag
            + self.cluster_hash
            + dialer.to_bytes(4, "big")
            + responder.to_bytes(4, "big")
            + nonce_s
            + nonce_c
        ).digest()

    def _session_key(self, peer_pubkey: bytes, dialer: int, responder: int,
                     nonce_s: bytes, nonce_c: bytes) -> bytes:
        shared = k1util.ecdh(self.key, peer_pubkey)
        return hashlib.sha256(
            b"charon-tpu-key-v2"
            + self.cluster_hash
            + shared
            + dialer.to_bytes(4, "big")
            + responder.to_bytes(4, "big")
            + nonce_s
            + nonce_c
        ).digest()

    async def _on_inbound(self, reader, writer) -> None:
        try:
            nonce_s = os.urandom(16)
            writer.write(nonce_s)
            await writer.drain()
            hello = await asyncio.wait_for(_read_frame(reader), RECV_TIMEOUT)
            h = json.loads(hello)
            idx = h["idx"]
            peer = self.peers.get(idx)
            # conn gater: only registered cluster peers may connect
            # (ref: p2p/gater.go:16-77)
            if peer is None:
                raise HandshakeError("unknown peer index", peer=idx)
            nonce_c = bytes.fromhex(h["nonce"])
            sig = bytes.fromhex(h["sig"])
            digest = self._transcript(
                b"charon-tpu-hello-v2", idx, self.index, nonce_s, nonce_c
            )
            if not k1util.verify_bytes(peer.pubkey, digest, sig):
                raise HandshakeError("bad handshake signature", peer=idx)
            # wire negotiation: absent field = version 0 (JSON) — the
            # cross-minor interop floor. Not part of the signed
            # transcript on purpose: a downgrade costs bytes, not auth.
            wire = min(self.wire_version, int(h.get("wire", 0)))
            ack = self._transcript(
                b"charon-tpu-ack-v2", idx, self.index, nonce_s, nonce_c
            )
            _write_frame(
                writer,
                json.dumps(
                    {
                        "idx": self.index,
                        "sig": k1util.sign(self.key, ack).hex(),
                        "wire": self.wire_version,
                    }
                ).encode(),
            )
            await writer.drain()
            key = self._session_key(
                peer.pubkey, idx, self.index, nonce_s, nonce_c
            )
        except Exception:
            writer.close()
            return
        conn = _Conn(
            reader, writer, idx,
            mac_key=key, send_dir=b"\x02", recv_dir=b"\x01",
            wire=wire,
        )
        self._conns.setdefault(idx, conn)
        self._spawn_recv(conn)

    async def _dial(self, peer: PeerSpec) -> _Conn:
        """Direct TCP dial, with relay fallback: when the peer is
        unreachable and a relay is configured, run the SAME mutual
        handshake + MAC'd framing over a relay virtual stream — the
        relay is a blind forwarder, never a trusted party (ref:
        p2p/relay.go circuit-relay-v2; relayed conns stay libp2p-TLS
        end-to-end)."""
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(peer.host, peer.port), SEND_TIMEOUT
            )
        except (OSError, asyncio.TimeoutError):
            if self.relay is None:
                raise
            reader, writer = await self.relay.stream_to(peer.index)
        try:
            return await self._handshake_dialer(reader, writer, peer)
        except BaseException:
            # close on ANY failure (incl. timeout/cancel): a half-done
            # handshake must not leave a stale stream/socket behind
            writer.close()
            raise

    async def _handshake_dialer(self, reader, writer, peer: PeerSpec) -> _Conn:
        nonce_s = await asyncio.wait_for(reader.readexactly(16), RECV_TIMEOUT)
        nonce_c = os.urandom(16)
        digest = self._transcript(
            b"charon-tpu-hello-v2", self.index, peer.index, nonce_s, nonce_c
        )
        _write_frame(
            writer,
            json.dumps(
                {
                    "idx": self.index,
                    "nonce": nonce_c.hex(),
                    "sig": k1util.sign(self.key, digest).hex(),
                    "wire": self.wire_version,
                }
            ).encode(),
        )
        await writer.drain()
        ack_frame = await asyncio.wait_for(_read_frame(reader), RECV_TIMEOUT)
        a = json.loads(ack_frame)
        ack = self._transcript(
            b"charon-tpu-ack-v2", self.index, peer.index, nonce_s, nonce_c
        )
        if a.get("idx") != peer.index or not k1util.verify_bytes(
            peer.pubkey, ack, bytes.fromhex(a["sig"])
        ):
            writer.close()
            raise HandshakeError("responder failed mutual auth", peer=peer.index)
        key = self._session_key(
            peer.pubkey, self.index, peer.index, nonce_s, nonce_c
        )
        conn = _Conn(
            reader, writer, peer.index,
            mac_key=key, send_dir=b"\x01", recv_dir=b"\x02",
            wire=min(self.wire_version, int(a.get("wire", 0))),
        )
        self._spawn_recv(conn)
        return conn

    async def _get_conn(self, peer_idx: int) -> _Conn:
        conn = self._conns.get(peer_idx)
        if conn is not None and not conn.writer.is_closing():
            return conn
        peer = self.peers[peer_idx]
        conn = await self._dial(peer)
        self._conns[peer_idx] = conn
        return conn

    # -- send -------------------------------------------------------------

    def _encode_envelope(
        self, conn: _Conn, protocol: str, req_id: str, kind: str, msg
    ) -> bytes:
        """Envelope bytes in the connection's negotiated codec, feeding
        the wire observer (tx bytes + encode seconds) when wired."""
        binary = conn.wire >= 1
        if self.wire_observer is None:
            return codec.encode_envelope(protocol, req_id, kind, msg, binary)
        t0 = time.perf_counter()
        body = codec.encode_envelope(protocol, req_id, kind, msg, binary)
        self.wire_observer(
            "tx",
            "binary" if binary else "json",
            len(body),
            time.perf_counter() - t0,
        )
        return body

    async def send(self, peer_idx: int, protocol: str, msg, await_response: bool = False):
        """SendAsync / SendReceive (ref: p2p/sender.go:90-95)."""
        req_id = os.urandom(8).hex()
        fut = None
        if await_response:
            fut = asyncio.get_running_loop().create_future()
            self._pending[req_id] = fut
        try:
            conn = await self._get_conn(peer_idx)
            body = self._encode_envelope(conn, protocol, req_id, "req", msg)
            async with conn.lock:
                _write_sframe(conn, body)
                await asyncio.wait_for(conn.writer.drain(), SEND_TIMEOUT)
            self._fail_counts[peer_idx] = 0
            if fut is not None:
                return await asyncio.wait_for(fut, RECV_TIMEOUT)
            return None
        except Exception:
            # hysteresis: count failures, drop the dead connection
            self._fail_counts[peer_idx] = self._fail_counts.get(peer_idx, 0) + 1
            self._conns.pop(peer_idx, None)
            if fut is not None:
                self._pending.pop(req_id, None)
            raise

    def peer_failing(self, peer_idx: int) -> bool:
        return self._fail_counts.get(peer_idx, 0) >= HYSTERESIS_FAILS

    async def _broadcast_one(
        self, peer_idx: int, protocol: str, req_id: str, msg, cache: dict
    ) -> None:
        """One broadcast delivery: the envelope is encoded ONCE per
        negotiated codec and shared across peers (`cache`) — an n-node
        gossip burst pays one serialization, not n-1 (ISSUE 7). Safe
        because broadcast frames are fire-and-forget: the request id is
        never matched, so peers may share it."""
        try:
            conn = await self._get_conn(peer_idx)
            key = 1 if conn.wire >= 1 else 0
            body = cache.get(key)
            if body is None:
                body = cache[key] = self._encode_envelope(
                    conn, protocol, req_id, "req", msg
                )
            elif self.wire_observer is not None:
                # cache hit: count the wire bytes, no encode timing
                self.wire_observer(
                    "tx", "binary" if key else "json", len(body), None
                )
            async with conn.lock:
                _write_sframe(conn, body)
                await asyncio.wait_for(conn.writer.drain(), SEND_TIMEOUT)
            self._fail_counts[peer_idx] = 0
        except Exception:
            self._fail_counts[peer_idx] = (
                self._fail_counts.get(peer_idx, 0) + 1
            )
            self._conns.pop(peer_idx, None)
            raise

    async def broadcast(self, protocol: str, msg) -> None:
        """Fire-and-forget to every peer; failures are independent.
        Network errors surface via hysteresis state; programming errors
        (unserializable payloads) are logged loudly — silently dropping
        every frame would stall consensus with healthy-looking pings."""
        req_id = os.urandom(8).hex()
        cache: dict = {}
        results = await asyncio.gather(
            *(
                self._broadcast_one(idx, protocol, req_id, msg, cache)
                for idx in self.peers
            ),
            return_exceptions=True,
        )
        for res in results:
            if isinstance(res, (TypeError, ValueError)):
                from charon_tpu.app import log

                log.error(
                    "broadcast payload error",
                    topic="p2p",
                    protocol=protocol,
                    error=repr(res),
                )
                break

    # -- receive ----------------------------------------------------------

    def _spawn_recv(self, conn: _Conn) -> None:
        task = asyncio.create_task(self._recv_loop(conn))
        self._recv_tasks.add(task)
        task.add_done_callback(self._recv_tasks.discard)

    def _decode_envelope(self, frame: bytes) -> dict:
        """Sniff-and-decode one decrypted frame in place (offset walk
        over the frame bytes; payload bytes fields slice straight out
        of the buffer), feeding the wire observer (rx bytes + decode
        seconds)."""
        if self.wire_observer is None:
            return codec.decode_envelope(frame)
        t0 = time.perf_counter()
        env = codec.decode_envelope(frame)
        self.wire_observer(
            "rx",
            "binary" if frame[:1] != b"{" else "json",
            len(frame),
            time.perf_counter() - t0,
        )
        return env

    @property
    def peer_quarantines(self) -> int:
        """Mutes imposed so far (wire_peer_quarantine_total)."""
        return self._quarantine.quarantines

    def peer_quarantined(self, peer_idx: int) -> bool:
        return self._quarantine.muted(peer_idx)

    def _on_quarantine(self, peer_idx: int, mute: float) -> None:
        log.warn(
            "quarantining peer after repeated malformed frames",
            topic="p2p",
            peer=peer_idx,
            mute_seconds=mute,
            strikes=self._quarantine.strikes,
        )
        if self.quarantine_observer is not None:
            self.quarantine_observer(peer_idx, mute)

    async def _recv_loop(self, conn: _Conn) -> None:
        try:
            while True:
                frame = await _read_sframe(conn)
                if self._quarantine.any_history and self._quarantine.muted(
                    conn.peer_idx
                ):
                    # muted peer: drop before decode — a garbage stream
                    # costs a counter bump, not a decode attempt + log
                    # line per frame
                    self.quarantined_frames += 1
                    continue
                # Per-frame fault isolation: a malformed payload or a
                # handler bug drops THAT frame, not the authenticated
                # connection carrying live consensus traffic (frame
                # integrity itself is the MAC's job in _read_sframe).
                try:
                    env = self._decode_envelope(frame)
                    if self._quarantine.any_history:
                        # a clean frame after the mute expired forgives
                        # the peer's exponential-backoff level
                        self._quarantine.forgive(conn.peer_idx)
                    if env["k"] == "rsp":
                        fut = self._pending.pop(env["id"], None)
                        if fut is not None and not fut.done():
                            fut.set_result(env["d"])
                        continue
                    handler = self._handlers.get(env["p"])
                    if handler is None:
                        continue
                    # Source = the connection's authenticated peer index;
                    # a sender-claimed envelope field would allow
                    # impersonation (ADVICE round 1).
                    resp = await handler(conn.peer_idx, env["d"])
                except asyncio.CancelledError:
                    raise
                except codec.CodecError as e:
                    # typed malformed-frame drop (ISSUE 7 satellite):
                    # a sealed-but-malformed payload lands here,
                    # counted, and the transport task lives on. (Raw
                    # pre-AEAD garbage — chaos_p2p_node's corrupt knob
                    # — fails the MAC instead and tears down the conn
                    # by design; see _read_sframe.)
                    self.codec_dropped += 1
                    self._quarantine.strike(conn.peer_idx)
                    log.warn(
                        "dropping malformed frame",
                        topic="p2p",
                        peer=conn.peer_idx,
                        dropped=self.codec_dropped,
                        err=f"CodecError: {e}",
                    )
                    continue
                except Exception as e:
                    log.warn(
                        "dropping bad frame",
                        topic="p2p",
                        peer=conn.peer_idx,
                        err=f"{type(e).__name__}: {e}",
                    )
                    continue
                if resp is not None:
                    body = self._encode_envelope(
                        conn, env["p"], env["id"], "rsp", resp
                    )
                    async with conn.lock:
                        _write_sframe(conn, body)
                        await conn.writer.drain()
        # task-body terminus: cancellation (node stop) ENDS this loop —
        # there is no awaiting canceller to starve, and the conn cleanup
        # it exists for runs in the finally below either way
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):  # lint: allow(no-swallowed-cancellation)
            pass
        finally:
            self._conns.pop(conn.peer_idx, None)
            conn.writer.close()

    # -- ping (ref: p2p/ping.go:35) ---------------------------------------

    async def _handle_ping(self, from_idx: int, msg):
        return {"pong": self.index}

    def start_ping(self, interval: float = 1.0) -> None:
        async def loop():
            while True:
                for idx in self.peers:
                    try:
                        await self.send(idx, "ping", None, await_response=True)
                        self.ping_success[idx] = True
                    except Exception:
                        self.ping_success[idx] = False
                await asyncio.sleep(interval)

        self._ping_task = asyncio.create_task(loop())


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    if len(payload) > MAX_FRAME:
        raise FrameError("frame exceeds max size")
    # two writes, no header+payload concatenation: the transport never
    # copies a large frame just to prefix 4 bytes
    writer.write(len(payload).to_bytes(4, "big"))
    writer.write(payload)


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > MAX_FRAME:
        raise ConnectionError("oversized frame")
    return await reader.readexactly(length)
