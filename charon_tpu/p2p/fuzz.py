"""P2P chaos fuzzing: corrupt the wire to prove peers survive garbage.

Mirrors ref: p2p/fuzz.go:18-30 — a fuzzing reader/writer injected into the
sender for chaos testing (enabled by --p2p-fuzz, app/app.go:253-256). Here
a wrapper around P2PNode.send that randomly corrupts/drops/duplicates
frames, plus a raw-socket garbage blaster for the server side.
"""

from __future__ import annotations

import asyncio
import random


def fuzz_node(node, rate: float = 0.2, seed: int = 0) -> None:
    """Wrap node.send with probabilistic corruption (SetFuzzerDefaultsUnsafe
    analogue). Receivers must survive: bad frames are dropped by codec/
    handler error paths, never crash the process."""
    rng = random.Random(seed)
    orig_send = node.send

    async def fuzzed_send(peer_idx, protocol, msg, await_response=False):
        roll = rng.random()
        if roll < rate / 3:
            return None  # drop
        if roll < 2 * rate / 3:
            # corrupt: send garbage bytes on the raw connection
            try:
                conn = await node._get_conn(peer_idx)
                garbage = rng.randbytes(rng.randrange(1, 64))
                from charon_tpu.p2p.transport import _write_frame

                async with conn.lock:
                    _write_frame(conn.writer, garbage)
                    await conn.writer.drain()
            except Exception:
                pass
            if await_response:
                raise TimeoutError("fuzzed request")
            return None
        if roll < rate:
            await orig_send(peer_idx, protocol, msg)  # duplicate
        return await orig_send(peer_idx, protocol, msg, await_response)

    node.send = fuzzed_send


async def blast_garbage(host: str, port: int, n_frames: int = 50, seed: int = 0) -> None:
    """Open raw connections and write random bytes at the server —
    handshake and framing must reject them without taking the node down."""
    rng = random.Random(seed)
    for _ in range(n_frames):
        try:
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(rng.randbytes(rng.randrange(1, 256)))
            await writer.drain()
            writer.close()
        except ConnectionError:
            pass
