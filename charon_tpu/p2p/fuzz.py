"""P2P chaos fuzzing — compatibility shim over `testutil/chaos`.

Mirrors ref: p2p/fuzz.go:18-30 (a fuzzing reader/writer injected into
the sender, enabled by --p2p-fuzz). The implementation moved into the
seeded fault-injection plane (`charon_tpu/testutil/chaos.py`), which
adds partitions, crash/restart, delay/reorder and deterministic
substreams; this module keeps the original one-call surface for
existing callers.
"""

from __future__ import annotations

from charon_tpu.testutil.chaos import (  # noqa: F401 — re-exported API
    ChaosConfig,
    blast_garbage,
    chaos_p2p_node,
)


def fuzz_node(node, rate: float = 0.2, seed: int = 0) -> None:
    """Wrap node.send with probabilistic corruption
    (SetFuzzerDefaultsUnsafe analogue): the historical `rate` splits
    evenly into drop / corrupt / duplicate, as the old stub did."""
    chaos_p2p_node(
        node,
        ChaosConfig(
            seed=seed,
            drop=rate / 3,
            corrupt=rate / 3,
            duplicate=rate / 3,
        ),
    )
