"""Peer networking: asyncio TCP mesh with typed messages.

Mirrors ref: p2p/ (libp2p TCP host, typed request/response streams,
connection gating to cluster peers, continuous ping — p2p/p2p.go:36,
p2p/sender.go, p2p/gater.go, p2p/ping.go) re-designed on asyncio: one
length-prefixed TCP connection per peer pair, protocol-tagged frames
dispatched to registered handlers, secp256k1-authenticated handshake.
"""

from charon_tpu.p2p.codec import (  # noqa: F401
    CodecError,
    decode,
    decode_binary,
    encode,
    encode_binary,
    register,
)

try:
    from charon_tpu.p2p.transport import P2PNode, PeerSpec  # noqa: F401
except ModuleNotFoundError as e:  # pragma: no cover — the TCP stack needs
    # the `cryptography` package (k1 identity + AEAD framing); hosts
    # without it (codec-only tools, bench_wire.py, jax-less CI images)
    # still get the wire codec — the in-memory simnet never dials.
    # Only the known-optional dependency is masked: anything else
    # missing is a real packaging bug and must surface.
    if e.name != "cryptography":
        raise
    P2PNode = PeerSpec = None  # type: ignore[assignment]
