"""Peer networking: asyncio TCP mesh with typed messages.

Mirrors ref: p2p/ (libp2p TCP host, typed request/response streams,
connection gating to cluster peers, continuous ping — p2p/p2p.go:36,
p2p/sender.go, p2p/gater.go, p2p/ping.go) re-designed on asyncio: one
length-prefixed TCP connection per peer pair, protocol-tagged frames
dispatched to registered handlers, secp256k1-authenticated handshake.
"""

from charon_tpu.p2p.codec import decode, encode, register  # noqa: F401
from charon_tpu.p2p.transport import P2PNode, PeerSpec  # noqa: F401
