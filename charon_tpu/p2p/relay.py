"""Relay server + client: rendezvous and frame forwarding for NAT'd peers.

Mirrors ref: p2p/relay.go + cmd/relay — the reference uses libp2p
circuit-relay-v2 with reservations refreshed continuously and relay-HTTP
peer discovery (discv5 was removed). Here: an asyncio TCP relay that
registered peers keep a connection to; frames addressed to a peer index
are forwarded over its registered connection. Peers prefer direct dials
and fall back to the relay (ref: ForceDirectConnections upgrades relayed
connections, app/app.go:352-353).

Wire format between peer and relay:
  register:  {"op": "register", "cluster": hex, "idx": n}
  send:      {"op": "send", "to": n} + payload frame follows
  deliver:   {"op": "deliver", "from": n} + payload frame follows
"""

from __future__ import annotations

import asyncio
import json
from collections import defaultdict

from charon_tpu.p2p.transport import MAX_FRAME, _read_frame, _write_frame


class RelayServer:
    """`charon-tpu relay` (ref: cmd/relay/relay.go:46)."""

    def __init__(self) -> None:
        self._server: asyncio.AbstractServer | None = None
        # (cluster, idx) -> writer
        self._peers: dict[tuple[str, int], asyncio.StreamWriter] = {}
        self.port: int | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_conn, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        for w in self._peers.values():
            w.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _on_conn(self, reader, writer) -> None:
        key = None
        try:
            hello = json.loads(await _read_frame(reader))
            if hello.get("op") != "register":
                writer.close()
                return
            key = (hello["cluster"], int(hello["idx"]))
            self._peers[key] = writer
            while True:
                header = json.loads(await _read_frame(reader))
                payload = await _read_frame(reader)
                if header.get("op") != "send":
                    continue
                target = self._peers.get((key[0], int(header["to"])))
                if target is None or target.is_closing():
                    continue
                _write_frame(
                    target,
                    json.dumps({"op": "deliver", "from": key[1]}).encode(),
                )
                _write_frame(target, payload)
                await target.drain()
        except (asyncio.IncompleteReadError, ConnectionError, json.JSONDecodeError):
            pass
        finally:
            if key is not None and self._peers.get(key) is writer:
                del self._peers[key]
            writer.close()


class RelayClient:
    """Keeps a registered connection to the relay and exposes
    send/receive of raw frames (the P2PNode can route through this when a
    direct dial fails — relay fallback)."""

    def __init__(self, host: str, port: int, cluster_hash: bytes, index: int) -> None:
        self.host = host
        self.port = port
        self.cluster = cluster_hash.hex()
        self.index = index
        self._reader = None
        self._writer = None
        self._handlers = []
        self._recv_task: asyncio.Task | None = None

    def on_frame(self, handler) -> None:
        """handler(from_idx: int, payload: bytes)"""
        self._handlers.append(handler)

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        _write_frame(
            self._writer,
            json.dumps(
                {"op": "register", "cluster": self.cluster, "idx": self.index}
            ).encode(),
        )
        await self._writer.drain()
        self._recv_task = asyncio.create_task(self._recv_loop())

    async def _recv_loop(self) -> None:
        try:
            while True:
                header = json.loads(await _read_frame(self._reader))
                payload = await _read_frame(self._reader)
                if header.get("op") != "deliver":
                    continue
                for h in self._handlers:
                    res = h(int(header["from"]), payload)
                    if asyncio.iscoroutine(res):
                        await res
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    async def send(self, to_idx: int, payload: bytes) -> None:
        _write_frame(
            self._writer,
            json.dumps({"op": "send", "to": to_idx}).encode(),
        )
        _write_frame(self._writer, payload)
        await self._writer.drain()

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
        if self._writer:
            self._writer.close()
