"""Relay server + client: rendezvous and frame forwarding for NAT'd peers.

Mirrors ref: p2p/relay.go + cmd/relay — the reference uses libp2p
circuit-relay-v2 with reservations refreshed continuously and relay-HTTP
peer discovery (discv5 was removed). Here: an asyncio TCP relay that
registered peers keep a connection to; frames addressed to a peer index
are forwarded over its registered connection. Peers prefer direct dials
and fall back to the relay (ref: ForceDirectConnections upgrades relayed
connections, app/app.go:352-353).

Wire format between peer and relay:
  register:  {"op": "register", "cluster": hex, "idx": n}
  send:      {"op": "send", "to": n} + payload frame follows
  deliver:   {"op": "deliver", "from": n} + payload frame follows
"""

from __future__ import annotations

import asyncio
import json
from collections import defaultdict

from charon_tpu.p2p.transport import MAX_FRAME, _read_frame, _write_frame


class RelayServer:
    """`charon-tpu relay` (ref: cmd/relay/relay.go:46)."""

    def __init__(self) -> None:
        self._server: asyncio.AbstractServer | None = None
        # (cluster, idx) -> writer
        self._peers: dict[tuple[str, int], asyncio.StreamWriter] = {}
        self.port: int | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_conn, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        for w in self._peers.values():
            w.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _on_conn(self, reader, writer) -> None:
        key = None
        try:
            hello = json.loads(await _read_frame(reader))
            if hello.get("op") != "register":
                writer.close()
                return
            key = (hello["cluster"], int(hello["idx"]))
            self._peers[key] = writer
            while True:
                header = json.loads(await _read_frame(reader))
                payload = await _read_frame(reader)
                if header.get("op") != "send":
                    continue
                target = self._peers.get((key[0], int(header["to"])))
                if target is None or target.is_closing():
                    continue
                deliver = {"op": "deliver", "from": key[1]}
                if header.get("s"):
                    # virtual-stream frame: forwarded verbatim with the
                    # stream flag so the raw-frame layer never sees it
                    deliver["s"] = 1
                _write_frame(target, json.dumps(deliver).encode())
                _write_frame(target, payload)
                await target.drain()
        except (asyncio.IncompleteReadError, ConnectionError, json.JSONDecodeError):
            pass
        finally:
            if key is not None and self._peers.get(key) is writer:
                del self._peers[key]
            writer.close()


class _VirtualWriter:
    """StreamWriter-shaped façade over relayed frames: written bytes are
    flushed as `VS`-tagged payload frames addressed to one peer."""

    def __init__(self, client: "RelayClient", to_idx: int) -> None:
        self._client = client
        self._to = to_idx
        self._buf = bytearray()
        self._closing = False

    def write(self, data: bytes) -> None:
        self._buf += data

    async def drain(self) -> None:
        if self._closing:
            raise ConnectionError("virtual stream closed")
        if self._buf:
            data, self._buf = bytes(self._buf), bytearray()
            await self._client.send(self._to, b"VS" + data, stream=True)

    def _detach(self) -> bool:
        """Detach from the demux so the next stream_to/inbound VO starts
        FRESH — a stale half-dead pair must never be reused. Returns
        whether the VC close frame still needs sending."""
        if self._client._streams.get(self._to, (None, None))[1] is self:
            self._client._streams.pop(self._to, None)
            self._client._stream_origin.pop(self._to, None)
        if self._closing:
            return False
        self._closing = True
        return True

    def close(self) -> None:
        if self._detach():
            try:
                asyncio.get_running_loop().create_task(
                    self._client.send(self._to, b"VC", stream=True)
                )
            except RuntimeError:
                pass  # no running loop (teardown)

    async def aclose(self) -> None:
        """Inline (awaited) close: the VC frame is on the wire before the
        caller's next send, so a peer can never observe a newer open
        before this close."""
        if self._detach():
            await self._client.send(self._to, b"VC", stream=True)

    def is_closing(self) -> bool:
        return self._closing


class RelayClient:
    """Keeps a registered connection to the relay and exposes two layers:

    * raw frames (`on_frame` / `send`) — rendezvous-style messaging;
    * **virtual streams** (`stream_to` / `set_stream_acceptor`) — a
      StreamReader/Writer pair multiplexed over the relay, over which the
      P2PNode runs its NORMAL mutual handshake + per-frame MACs, so a
      relayed connection is end-to-end authenticated exactly like a
      direct one and the relay stays a blind forwarder (ref: libp2p
      circuit-relay-v2 conns are still libp2p-TLS end-to-end,
      p2p/relay.go).

    One virtual stream per peer pair. Simultaneous dial-via-relay from
    both ends can collide (both sides act as handshake dialer) — the
    handshake times out and the workflow retryer re-dials, mirroring TCP
    simultaneous-connect rarity."""

    def __init__(self, host: str, port: int, cluster_hash: bytes, index: int) -> None:
        self.host = host
        self.port = port
        self.cluster = cluster_hash.hex()
        self.index = index
        self._reader = None
        self._writer = None
        self._handlers = []
        self._recv_task: asyncio.Task | None = None
        self._streams: dict[int, tuple[asyncio.StreamReader, _VirtualWriter]] = {}
        self._stream_origin: dict[int, str] = {}  # "out" (stream_to) | "in"
        self._acceptor = None
        self._accept_tasks: set[asyncio.Task] = set()

    def on_frame(self, handler) -> None:
        """handler(from_idx: int, payload: bytes) — raw, non-stream frames."""
        self._handlers.append(handler)

    def set_stream_acceptor(self, acceptor) -> None:
        """acceptor(peer_idx, reader, writer): awaited when a peer opens
        a virtual stream toward this node (the P2PNode passes its
        responder-handshake entrypoint)."""
        self._acceptor = acceptor

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        _write_frame(
            self._writer,
            json.dumps(
                {"op": "register", "cluster": self.cluster, "idx": self.index}
            ).encode(),
        )
        await self._writer.drain()
        self._recv_task = asyncio.create_task(self._recv_loop())

    def _stream_pair(self, peer_idx: int, origin: str):
        pair = self._streams.get(peer_idx)
        if pair is None:
            pair = (asyncio.StreamReader(), _VirtualWriter(self, peer_idx))
            self._streams[peer_idx] = pair
            self._stream_origin[peer_idx] = origin
        return pair

    async def stream_to(self, peer_idx: int):
        """(reader, writer) virtual stream toward peer_idx (dialer side).
        Sends an explicit open marker: the responder speaks first in the
        node handshake (nonce), so it must learn of the stream before any
        dialer bytes flow. If an INBOUND stream from the same peer is
        already active (both sides fell back simultaneously), refuse —
        the caller's retry path will find the inbound-established
        connection instead of corrupting its handshake."""
        pair = self._streams.get(peer_idx)
        if pair is not None:
            if self._stream_origin.get(peer_idx) == "in":
                raise ConnectionError(
                    f"relay stream to {peer_idx} busy (inbound in progress)"
                )
            # stale dialer-side pair: drop it and start fresh. The close
            # frame is awaited so the peer can never observe the new VO
            # before the stale VC (close() defers its VC via create_task,
            # which could land after our VO and kill the fresh stream).
            await pair[1].aclose()
            # the await may have let a new pair appear — an inbound VO
            # from _recv_loop OR a concurrent stream_to that registered a
            # fresh dialer pair. Either way that stream has an owner;
            # joining it would interleave two handshakes, so refuse and
            # let the caller's retry find the established connection.
            if peer_idx in self._streams:
                raise ConnectionError(
                    f"relay stream to {peer_idx} busy (concurrent open)"
                )
        pair = self._stream_pair(peer_idx, "out")
        await self.send(peer_idx, b"VO", stream=True)
        return pair

    async def _recv_loop(self) -> None:
        try:
            while True:
                header = json.loads(await _read_frame(self._reader))
                payload = await _read_frame(self._reader)
                if header.get("op") != "deliver":
                    continue
                frm = int(header["from"])
                if header.get("s"):
                    # virtual-stream frames live in their own namespace
                    # (the relay forwards the flag) — raw on_frame
                    # payloads can never be hijacked by tag collisions
                    if payload[:2] in (b"VO", b"VS"):
                        existed = frm in self._streams
                        if payload[:2] == b"VS" and not existed:
                            # only VO opens a stream: a VS addressed to no
                            # registered stream is a stale flush from a
                            # torn-down pair — spawning a phantom inbound
                            # stream from it would block re-dials until
                            # its garbage handshake times out
                            continue
                        reader, _writer = self._stream_pair(frm, "in")
                        if payload[2:]:
                            reader.feed_data(payload[2:])
                        if not existed and self._acceptor is not None:
                            task = asyncio.create_task(
                                self._acceptor(frm, *self._streams[frm])
                            )
                            self._accept_tasks.add(task)
                            task.add_done_callback(self._accept_tasks.discard)
                    elif payload[:2] == b"VC":
                        pair = self._streams.pop(frm, None)
                        self._stream_origin.pop(frm, None)
                        if pair is not None:
                            pair[0].feed_eof()
                            pair[1]._closing = True
                    continue
                for h in self._handlers:
                    res = h(frm, payload)
                    if asyncio.iscoroutine(res):
                        await res
        except (asyncio.IncompleteReadError, ConnectionError):
            # relay link died: every virtual stream is dead — detach all
            # so later dials start fresh (after reconnect)
            streams, self._streams = self._streams, {}
            self._stream_origin.clear()
            for reader, vwriter in streams.values():
                reader.feed_eof()
                vwriter._closing = True

    async def send(self, to_idx: int, payload: bytes, stream: bool = False) -> None:
        if self._writer is None or self._writer.is_closing():
            raise ConnectionError("relay connection down")
        header = {"op": "send", "to": to_idx}
        if stream:
            header["s"] = 1
        _write_frame(self._writer, json.dumps(header).encode())
        _write_frame(self._writer, payload)
        await self._writer.drain()

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
        for task in list(self._accept_tasks):
            task.cancel()
        if self._writer:
            self._writer.close()
