"""CLI entry points (ref: cmd/ — cobra commands; argparse here)."""
