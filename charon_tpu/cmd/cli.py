"""charon-tpu command line interface.

Mirrors ref: cmd/cmd.go:72 — subcommands: run, dkg, create cluster,
enr, version (the reference's cobra tree; argparse here, flags also bound
to CHARON_TPU_* environment variables like the reference's viper
binding, ref: cmd/run.go:50).

    python -m charon_tpu.cmd.cli create-cluster --name test --nodes 4 \
        --threshold 3 --validators 2 --output-dir ./cluster
    python -m charon_tpu.cmd.cli run --data-dir ./cluster/node0 --simnet
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from pathlib import Path


def _env_default(name: str, default=None):
    return os.environ.get(f"CHARON_TPU_{name.upper().replace('-', '_')}", default)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="charon-tpu")
    sub = p.add_subparsers(dest="command", required=True)

    runp = sub.add_parser("run", help="run the distributed validator node")
    runp.add_argument("--data-dir", default=_env_default("data-dir", ".charon"))
    runp.add_argument("--node-index", type=int, default=int(_env_default("node-index", 0)))
    runp.add_argument("--simnet", action="store_true")
    runp.add_argument("--validator-api-port", type=int, default=int(_env_default("validator-api-port", 3600)))
    runp.add_argument("--monitoring-port", type=int, default=int(_env_default("monitoring-port", 3620)))
    runp.add_argument("--p2p-port", type=int, default=int(_env_default("p2p-port", 3610)))
    runp.add_argument("--slot-duration", type=float, default=float(_env_default("slot-duration", 12.0)))
    runp.add_argument(
        "--peers",
        default=_env_default("peers", ""),
        help="comma-separated host:port per operator (index order)",
    )
    runp.add_argument("--no-tpu", action="store_true", help="use the pure-python tbls backend")

    create = sub.add_parser(
        "create-cluster",
        help="generate a full cluster locally (keys, lock, node dirs)",
    )
    create.add_argument("--name", default="charon-tpu-cluster")
    create.add_argument("--nodes", type=int, default=4)
    create.add_argument("--threshold", type=int, default=3)
    create.add_argument("--validators", type=int, default=1)
    create.add_argument("--fork-version", default="0x00000000")
    create.add_argument("--output-dir", required=True)

    dkgp = sub.add_parser("dkg", help="run the distributed key generation ceremony")
    dkgp.add_argument("--definition-file", required=True)
    dkgp.add_argument("--data-dir", required=True)
    dkgp.add_argument(
        "--node-index",
        type=int,
        default=-1,
        help="operator index; default: derived from this node's key",
    )
    dkgp.add_argument(
        "--peers",
        required=True,
        help="comma-separated host:port per operator (index order)",
    )
    dkgp.add_argument("--timeout", type=float, default=120.0)
    dkgp.add_argument("--no-tpu", action="store_true")

    cenr = sub.add_parser(
        "create-enr",
        help="generate this node's p2p identity key and print its record",
    )
    cenr.add_argument("--data-dir", default=".charon")

    cdkg = sub.add_parser(
        "create-dkg",
        help="generate an unsigned cluster-definition.json for a ceremony",
    )
    cdkg.add_argument("--name", default="charon-tpu-cluster")
    cdkg.add_argument("--num-validators", type=int, default=1)
    cdkg.add_argument(
        "--threshold",
        type=int,
        default=0,
        help="0 = BFT default n - floor((n-1)/3)",
    )
    cdkg.add_argument("--fork-version", default="0x00000000")
    cdkg.add_argument(
        "--operator-enrs", required=True, help="comma-separated operator records"
    )
    cdkg.add_argument("--output", default="cluster-definition.json")

    sdef = sub.add_parser(
        "sign-definition",
        help="add this operator's signatures to a cluster definition",
    )
    sdef.add_argument("--definition-file", required=True)
    sdef.add_argument("--data-dir", default=".charon")

    enrp = sub.add_parser("enr", help="print this node's identity record")
    enrp.add_argument("--data-dir", default=".charon")

    sub.add_parser("version", help="print version")
    return p


def cmd_create_cluster(args) -> int:
    """ref: cmd/createcluster.go — an in-memory ceremony producing every
    node's directory (lock + keystores + p2p key)."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition, Operator
    from charon_tpu.dkg import frost
    from charon_tpu.dkg.ceremony import MemExchangeNet, run_dkg

    n, t, v = args.nodes, args.threshold, args.validators
    out = Path(args.output_dir)
    keys = [k1util.generate_private_key() for _ in range(n)]
    ops = tuple(
        Operator(
            address=f"operator-{i}",
            enr="enr:node-%d:%s"
            % (i, k1util.public_key_to_bytes(keys[i].public_key()).hex()),
        )
        for i in range(n)
    )
    defn = ClusterDefinition(
        name=args.name,
        num_validators=v,
        threshold=t,
        fork_version=args.fork_version,
        operators=ops,
    )
    for i in range(n):
        defn = defn.sign_operator(i, keys[i])

    async def ceremony():
        fnet = frost.MemFrostTransport(n)
        xnet = MemExchangeNet(n)
        return await asyncio.gather(
            *(
                run_dkg(
                    defn,
                    i,
                    keys[i],
                    fnet.participant(i + 1),
                    xnet.port(i),
                    data_dir=out / f"node{i}",
                )
                for i in range(n)
            )
        )

    results = asyncio.run(ceremony())
    for i in range(n):
        (out / f"node{i}" / "charon-enr-private-key").write_bytes(
            k1util.private_key_to_bytes(keys[i])
        )
    (out / "cluster-definition.json").write_text(
        json.dumps(defn.to_json(), indent=2)
    )
    print(f"created {n}-node cluster (threshold {t}, {v} validators) in {out}")
    print(f"lock hash: 0x{results[0].lock.lock_hash().hex()}")
    return 0


def cmd_run(args) -> int:
    from charon_tpu.app.run import Config, run

    peer_addrs = []
    if args.peers:
        for part in args.peers.split(","):
            host, port = part.rsplit(":", 1)
            peer_addrs.append((host, int(port)))
    config = Config(
        data_dir=args.data_dir,
        node_index=args.node_index,
        validator_api_port=args.validator_api_port,
        monitoring_port=args.monitoring_port,
        p2p_port=args.p2p_port,
        peer_addrs=peer_addrs,
        simnet=args.simnet,
        slot_duration=args.slot_duration,
        use_tpu_tbls=not args.no_tpu,
    )
    asyncio.run(run(config))
    return 0


def _load_node_key(data_dir):
    from charon_tpu.app import k1util

    key_path = Path(data_dir) / "charon-enr-private-key"
    return k1util.private_key_from_bytes(key_path.read_bytes())


def _operator_index_for_key(defn, key) -> int:
    """This key's 0-based operator index in the definition, or -1."""
    from charon_tpu.app import k1util

    my_pub = k1util.public_key_to_bytes(key.public_key()).hex()
    for i, op in enumerate(defn.operators):
        if op.enr.split(":")[-1] == my_pub:
            return i
    return -1


def cmd_dkg(args) -> int:
    """Networked ceremony over localhost/TCP (ref: dkg/dkg.go:82 Run):
    mesh up -> sync protocol -> FROST -> signed lock + keystores written
    to --data-dir."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition
    from charon_tpu.dkg.netdkg import run_networked_dkg

    defn = ClusterDefinition.from_json(
        json.loads(Path(args.definition_file).read_text())
    )
    key = _load_node_key(args.data_dir)
    node_idx = args.node_index
    if node_idx < 0:
        node_idx = _operator_index_for_key(defn, key)
        if node_idx < 0:
            print("this node's key matches no definition operator", file=sys.stderr)
            return 1

    peer_addrs = []
    for part in args.peers.split(","):
        host, port = part.rsplit(":", 1)
        peer_addrs.append((host, int(port)))
    if len(peer_addrs) != len(defn.operators):
        print(
            f"--peers must list all {len(defn.operators)} operators",
            file=sys.stderr,
        )
        return 1

    engine = None
    if not args.no_tpu:
        try:
            from charon_tpu.ops import blsops, limb

            engine = blsops.BlsEngine(
                limb.default_fp_ctx(), limb.default_fr_ctx()
            )
        except Exception:
            engine = None  # host fallback

    result = asyncio.run(
        run_networked_dkg(
            defn,
            node_idx,
            key,
            peer_addrs,
            data_dir=args.data_dir,
            engine=engine,
            timeout=args.timeout,
        )
    )
    print(f"dkg complete; lock hash: 0x{result.lock.lock_hash().hex()}")
    return 0


def cmd_create_enr(args) -> int:
    """ref: cmd/createenr.go — new key + printed record."""
    from charon_tpu.app import k1util

    data_dir = Path(args.data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    key_path = data_dir / "charon-enr-private-key"
    if key_path.exists():
        print(f"refusing to overwrite {key_path}", file=sys.stderr)
        return 1
    key = k1util.generate_private_key()
    key_path.write_bytes(k1util.private_key_to_bytes(key))
    print("enr:" + k1util.public_key_to_bytes(key.public_key()).hex())
    return 0


def cmd_create_dkg(args) -> int:
    """ref: cmd/createdkg.go — an unsigned definition the operators then
    sign (sign-definition) before running `dkg`."""
    from charon_tpu.cluster.definition import ClusterDefinition, Operator

    enrs = [e.strip() for e in args.operator_enrs.split(",") if e.strip()]
    n = len(enrs)
    if n < 3:
        print("need at least 3 operators", file=sys.stderr)
        return 1
    threshold = args.threshold or n - (n - 1) // 3
    defn = ClusterDefinition(
        name=args.name,
        num_validators=args.num_validators,
        threshold=threshold,
        fork_version=args.fork_version,
        operators=tuple(
            Operator(address=f"operator-{i}", enr=enr)
            for i, enr in enumerate(enrs)
        ),
    )
    Path(args.output).write_text(json.dumps(defn.to_json(), indent=2))
    print(f"wrote {args.output} ({n} operators, threshold {threshold})")
    return 0


def cmd_sign_definition(args) -> int:
    """Each operator signs the config hash + their record in turn
    (ref: the launchpad EIP-712 signing step, cluster/eip712sigs.go)."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition

    path = Path(args.definition_file)
    defn = ClusterDefinition.from_json(json.loads(path.read_text()))
    key = _load_node_key(args.data_dir)
    idx = _operator_index_for_key(defn, key)
    if idx < 0:
        print("this node's key matches no definition operator", file=sys.stderr)
        return 1
    defn = defn.sign_operator(idx, key)
    path.write_text(json.dumps(defn.to_json(), indent=2))
    print(f"signed as operator {idx}")
    return 0


def cmd_enr(args) -> int:
    from charon_tpu.app import k1util

    key_path = Path(args.data_dir) / "charon-enr-private-key"
    key = k1util.private_key_from_bytes(key_path.read_bytes())
    print("enr:" + k1util.public_key_to_bytes(key.public_key()).hex())
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        from charon_tpu import __version__

        print(f"charon-tpu {__version__}")
        return 0
    return {
        "run": cmd_run,
        "create-cluster": cmd_create_cluster,
        "dkg": cmd_dkg,
        "create-enr": cmd_create_enr,
        "create-dkg": cmd_create_dkg,
        "sign-definition": cmd_sign_definition,
        "enr": cmd_enr,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
