"""charon-tpu command line interface.

Mirrors ref: cmd/cmd.go:72 — subcommands: run, dkg, create cluster,
enr, version (the reference's cobra tree; argparse here, flags also bound
to CHARON_TPU_* environment variables like the reference's viper
binding, ref: cmd/run.go:50).

    python -m charon_tpu.cmd.cli create-cluster --name test --nodes 4 \
        --threshold 3 --validators 2 --output-dir ./cluster
    python -m charon_tpu.cmd.cli run --data-dir ./cluster/node0 --simnet
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from pathlib import Path


def _env_default(name: str, default=None):
    return os.environ.get(f"CHARON_TPU_{name.upper().replace('-', '_')}", default)


def run_coro(coro):
    """Run a command's async body to completion and return its result.

    The CLI is synchronous: each command builds exactly one coroutine
    and blocks on it — this is the single place that owns the event
    loop (VERDICT r3 weak #1: no nested asyncio.run in command bodies).
    When main() is itself invoked from code that already has a running
    loop in this thread (async test harnesses), asyncio.run would
    refuse; run the coroutine on a private loop in a worker thread so
    the caller's loop keeps running.
    """
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    import threading

    box: dict = {}

    def _target():
        try:
            box["result"] = asyncio.run(coro)
        except BaseException as e:  # noqa: BLE001 — reraised in caller
            box["error"] = e

    # daemon thread, joined without a context manager: a KeyboardInterrupt
    # while a long-lived command (run/relay) blocks here must propagate to
    # the caller immediately, not hang joining the worker
    t = threading.Thread(target=_target, name="cli-run-coro", daemon=True)
    t.start()
    t.join()
    if "error" in box:
        raise box["error"]
    return box["result"]


def _init_featureset(args) -> int:
    """Apply --feature-set{,-enable,-disable} to the global feature
    registry before the node builds (ref: app/app.go:136
    featureset.Init). Returns nonzero on an unknown status or feature
    name so a typo fails fast instead of silently running defaults."""
    from charon_tpu.app import featureset

    try:
        status = featureset.Status[args.feature_set.upper()]
    except KeyError:
        print(
            f"--feature-set {args.feature_set!r}: must be alpha, beta "
            "or stable",
            file=sys.stderr,
        )
        return 2

    def parse_features(raw: str, flag: str):
        out = []
        for name in filter(None, raw.split(",")):
            try:
                out.append(featureset.Feature(name.strip()))
            except ValueError:
                known = ", ".join(f.value for f in featureset.Feature)
                print(
                    f"{flag} {name.strip()!r}: unknown feature "
                    f"(known: {known})",
                    file=sys.stderr,
                )
                return None
        return out

    enable = parse_features(args.feature_set_enable, "--feature-set-enable")
    if enable is None:
        return 2
    disable = parse_features(args.feature_set_disable, "--feature-set-disable")
    if disable is None:
        return 2
    featureset.init(status, enable=enable, disable=disable)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="charon-tpu")
    sub = p.add_subparsers(dest="command", required=True)

    runp = sub.add_parser("run", help="run the distributed validator node")
    runp.add_argument("--data-dir", default=_env_default("data-dir", ".charon"))
    runp.add_argument("--node-index", type=int, default=int(_env_default("node-index", 0)))
    runp.add_argument("--simnet", action="store_true")
    runp.add_argument("--validator-api-port", type=int, default=int(_env_default("validator-api-port", 3600)))
    runp.add_argument("--monitoring-port", type=int, default=int(_env_default("monitoring-port", 3620)))
    runp.add_argument("--p2p-port", type=int, default=int(_env_default("p2p-port", 3610)))
    runp.add_argument("--slot-duration", type=float, default=float(_env_default("slot-duration", 12.0)))
    runp.add_argument(
        "--genesis-time",
        type=float,
        default=float(_env_default("genesis-time", 0.0)) or None,
        help="unix genesis timestamp (aligns simnet clocks across processes)",
    )
    runp.add_argument("--slots-per-epoch", type=int, default=int(_env_default("slots-per-epoch", 32)))
    runp.add_argument(
        "--peers",
        default=_env_default("peers", ""),
        help="comma-separated host:port per operator (index order)",
    )
    runp.add_argument("--no-tpu", action="store_true", help="use the pure-python tbls backend")
    # Empty env binding (unset compose templating) falls back to auto;
    # argparse validates `choices` only for command-line values, never
    # defaults, so a typo'd CHARON_TPU_CRYPTO_PLANE is caught in
    # cmd_run — at parser-build time it would abort EVERY subcommand.
    runp.add_argument(
        "--crypto-plane",
        choices=["auto", "on", "off"],
        default=_env_default("crypto-plane", "") or "auto",
        help="sharded multi-device crypto plane: auto installs it when "
        ">= 2 devices are visible (see core/cryptoplane.py)",
    )
    runp.add_argument(
        "--crypto-plane-window",
        type=float,
        default=float(_env_default("crypto-plane-window", 0.02)),
        help="base coalescing window in seconds; the plane grows it "
        "under load and duty deadlines shrink it (core/cryptoplane.py)",
    )
    runp.add_argument(
        "--crypto-plane-decode-workers",
        type=int,
        default=int(_env_default("crypto-plane-decode-workers", 4)),
        help="decode/pack thread-pool size for the pipelined host "
        "plane; 0 disables the pipeline (synchronous decode)",
    )
    runp.add_argument(
        "--crypto-plane-prewarm",
        choices=["auto", "on", "off"],
        default=_env_default("crypto-plane-prewarm", "") or "auto",
        help="compile the canonical duty shapes at startup: auto "
        "pre-warms on a TPU backend, or on any platform once a "
        "fresh tuned profile exists AND a prior prewarm completed "
        "under the same kernel sources (cache loads, not "
        "minutes-long compiles); the first off-TPU prewarm needs "
        "one explicit 'on' boot",
    )
    runp.add_argument(
        "--crypto-plane-decode",
        choices=["auto", "device", "python"],
        default=_env_default("crypto-plane-decode", "") or "auto",
        help="signature-decode rung: device batches point "
        "decompression into the flush programs (ops/decompress.py), "
        "python keeps the host bigint path, auto = device on TPU "
        "backends only (docs/operations.md 'Crypto-plane tuning')",
    )
    runp.add_argument(
        "--crypto-plane-warmup",
        choices=["auto", "on", "off"],
        default=_env_default("crypto-plane-warmup", "") or "auto",
        help="bulk point-cache warm-up at startup: decode the whole "
        "cluster key set through the batched device kernels so the "
        "first live slot starts warm; auto warms only on a TPU "
        "backend (docs/operations.md 'Cold start and rotation "
        "warm-up')",
    )
    runp.add_argument(
        "--crypto-autotune",
        choices=["auto", "on", "off", "force"],
        default=_env_default("crypto-autotune", "") or "auto",
        help="startup kernel auto-tune (core/autotune.py): auto loads "
        "the persisted per-platform profile or micro-benches + "
        "persists one, on refuses hosts without the device stack, "
        "force always re-benches, off applies KernelConfig defaults + "
        "the deprecated CHARON_* env pins (no profile IO, no bench) "
        "(docs/operations.md 'Kernel auto-tuning and cold start')",
    )
    runp.add_argument(
        "--crypto-autotune-profile",
        default=_env_default("crypto-autotune-profile", ""),
        help="kernel-profile path; default places it next to the "
        "persistent jit cache for the detected platform (jaxcache.py)",
    )
    runp.add_argument(
        "--crypto-tenant",
        default=_env_default("crypto-tenant", ""),
        help="tenant id this node registers with the multi-tenant "
        "crypto-plane service (core/cryptosvc.py); default = the "
        "cluster name (docs/operations.md 'Multi-tenant deployment')",
    )
    runp.add_argument(
        "--crypto-tenant-weight",
        type=float,
        default=float(_env_default("crypto-tenant-weight", 1.0)),
        help="this tenant's relative share of the per-round lane "
        "budget (weighted-fair scheduling across tenants)",
    )
    runp.add_argument(
        "--crypto-tenant-queue-lanes",
        type=int,
        default=int(_env_default("crypto-tenant-queue-lanes", 4096)),
        help="per-tenant admission bound: pending lanes beyond this "
        "shed with PlaneOverloadError onto the submitter's host rung",
    )
    runp.add_argument(
        "--crypto-tenant-queue-jobs",
        type=int,
        default=int(_env_default("crypto-tenant-queue-jobs", 256)),
        help="per-tenant admission bound on pending submissions "
        "(the jobs twin of --crypto-tenant-queue-lanes)",
    )
    runp.add_argument(
        "--crypto-plane-round-lanes",
        type=int,
        default=int(_env_default("crypto-plane-round-lanes", 4096)),
        help="total lanes the service admits per scheduling round "
        "across all tenants (split weight-proportionally)",
    )
    runp.add_argument(
        "--crypto-breaker-threshold",
        type=float,
        default=float(_env_default("crypto-breaker-threshold", 0.5)),
        help="failed-verification lane ratio that opens the tenant's "
        "circuit breaker (forged-flood quarantine to its own flushes)",
    )
    runp.add_argument(
        "--crypto-breaker-cooldown",
        type=float,
        default=float(_env_default("crypto-breaker-cooldown", 5.0)),
        help="seconds an open breaker waits before half-opening (one "
        "clean quarantined flush then closes it)",
    )
    # networked crypto plane (ISSUE 17). The auth token deliberately
    # has NO flag: tokens on the command line leak via ps/shell
    # history, so the env var is the only channel.
    runp.add_argument(
        "--crypto-remote",
        default=_env_default("crypto-remote", ""),
        help="host:port of a remote crypto-plane service to dial "
        "(core/cryptosvc_client); token via CHARON_TPU_CRYPTO_TOKEN "
        "env var only. Remote failures degrade to the local ladder.",
    )
    runp.add_argument(
        "--crypto-serve",
        type=int,
        default=int(_env_default("crypto-serve", -1)),
        help="TCP port to serve this node's crypto-plane service on "
        "(core/cryptosvc_server); 0 = ephemeral, -1/unset = off. "
        "Tenant tokens via CHARON_TPU_CRYPTO_SERVE_TOKENS "
        "('tenant=token,tenant2=token2') env var only.",
    )
    runp.add_argument(
        "--crypto-serve-host",
        default=_env_default("crypto-serve-host", "127.0.0.1"),
        help="bind address for --crypto-serve",
    )
    runp.add_argument(
        "--relay",
        default=_env_default("relay", ""),
        help="host:port of a charon-tpu relay for NAT fallback dials",
    )
    runp.add_argument(
        "--tracing-endpoint",
        default=_env_default("tracing-endpoint", ""),
        help="OTLP/HTTP collector base URL for workflow spans "
        "(e.g. http://jaeger:4318; ref charon --jaeger-address)",
    )
    runp.add_argument(
        "--tracing-jsonl",
        default=_env_default("tracing-jsonl", ""),
        help="per-node span JSONL export path; per-node files merge "
        "offline into one cross-node duty timeline (duty trace ids "
        "are deterministic across the cluster)",
    )
    runp.add_argument(
        "--beacon-urls",
        default=_env_default("beacon-urls", ""),
        help="comma-separated beacon-node HTTP endpoints (failover order)",
    )
    # feature rollout control (ref: app/featureset Init bound via flags
    # at app start, app/app.go:136)
    runp.add_argument(
        "--feature-set",
        default=_env_default("feature-set", "stable"),
        help="minimum feature rollout status to enable: alpha|beta|stable",
    )
    runp.add_argument(
        "--feature-set-enable",
        default=_env_default("feature-set-enable", ""),
        help="comma-separated feature names to force-enable",
    )
    runp.add_argument(
        "--feature-set-disable",
        default=_env_default("feature-set-disable", ""),
        help="comma-separated feature names to force-disable",
    )
    # seeded fault injection (ISSUE 2): inert unless a spec is given —
    # the env var CHARON_TPU_FAULT_INJECTION is the non-CLI equivalent
    runp.add_argument(
        "--fault-injection",
        default=_env_default("fault-injection", ""),
        help="seeded fault-injection spec, e.g. 'seed=42,drop=0.1,"
        "bn_error=0.2' (keys: testutil.chaos.ChaosConfig); empty = off",
    )

    create = sub.add_parser(
        "create-cluster",
        help="generate a full cluster locally (keys, lock, node dirs)",
    )
    create.add_argument("--name", default="charon-tpu-cluster")
    create.add_argument("--nodes", type=int, default=4)
    create.add_argument("--threshold", type=int, default=3)
    create.add_argument("--validators", type=int, default=1)
    create.add_argument("--fork-version", default="0x00000000")
    create.add_argument("--output-dir", required=True)

    dkgp = sub.add_parser("dkg", help="run the distributed key generation ceremony")
    dkgp.add_argument("--definition-file", required=True)
    dkgp.add_argument("--data-dir", required=True)
    dkgp.add_argument(
        "--node-index",
        type=int,
        default=-1,
        help="operator index; default: derived from this node's key",
    )
    dkgp.add_argument(
        "--peers",
        required=True,
        help="comma-separated host:port per operator (index order)",
    )
    dkgp.add_argument("--timeout", type=float, default=120.0)
    dkgp.add_argument("--no-tpu", action="store_true")
    dkgp.add_argument(
        "--keymanager-address",
        default="",
        help="push share keystores to this VC keymanager API after the "
        "ceremony (ref: dkg.go:118-128)",
    )
    dkgp.add_argument(
        "--publish-address",
        default="",
        help="publish the cluster lock to this Obol-API endpoint "
        "(ref: dkg.go obolapi publish)",
    )

    cenr = sub.add_parser(
        "create-enr",
        help="generate this node's p2p identity key and print its record",
    )
    cenr.add_argument("--data-dir", default=".charon")

    cdkg = sub.add_parser(
        "create-dkg",
        help="generate an unsigned cluster-definition.json for a ceremony",
    )
    cdkg.add_argument("--name", default="charon-tpu-cluster")
    cdkg.add_argument("--num-validators", type=int, default=1)
    cdkg.add_argument(
        "--threshold",
        type=int,
        default=0,
        help="0 = BFT default n - floor((n-1)/3)",
    )
    cdkg.add_argument("--fork-version", default="0x00000000")
    cdkg.add_argument(
        "--operator-enrs", required=True, help="comma-separated operator records"
    )
    cdkg.add_argument("--output", default="cluster-definition.json")

    sdef = sub.add_parser(
        "sign-definition",
        help="add this operator's signatures to a cluster definition",
    )
    sdef.add_argument("--definition-file", required=True)
    sdef.add_argument("--data-dir", default=".charon")

    enrp = sub.add_parser("enr", help="print this node's identity record")
    enrp.add_argument("--data-dir", default=".charon")

    comb = sub.add_parser(
        "combine",
        help="reconstruct group validator keys from >=threshold node dirs",
    )
    comb.add_argument(
        "--cluster-dir",
        required=True,
        help="directory containing node*/ data dirs from the same cluster",
    )
    comb.add_argument("--output-dir", required=True)
    comb.add_argument(
        "--force", action="store_true", help="overwrite existing output"
    )

    resh = sub.add_parser(
        "reshare",
        help="reshare validator key shares to a new operator set or "
        "threshold (group key unchanged, old shares retired)",
    )
    resh.add_argument(
        "--cluster-dir",
        required=True,
        help="directory containing node*/ data dirs from the same cluster",
    )
    resh.add_argument(
        "--new-nodes",
        type=int,
        default=0,
        help="new operator count (join/leave); 0 = unchanged",
    )
    resh.add_argument(
        "--threshold",
        type=int,
        default=0,
        help="new threshold; 0 = BFT default n - floor((n-1)/3) for "
        "the new operator count",
    )
    resh.add_argument(
        "--no-tpu",
        action="store_true",
        help="host-only ceremony verification (skip the device engine)",
    )

    exitp = sub.add_parser("exit", help="voluntary-exit operations")
    exitsub = exitp.add_subparsers(dest="exit_command", required=True)
    esign = exitsub.add_parser(
        "sign", help="sign this node's partial voluntary exit"
    )
    esign.add_argument("--data-dir", required=True)
    esign.add_argument("--validator-index", type=int, required=True)
    esign.add_argument(
        "--validator-pubkey", default="", help="0x group pubkey (default: by index order in lock)"
    )
    esign.add_argument("--epoch", type=int, required=True)
    esign.add_argument("--output", default="", help="partial-exit json path")
    ebcast = exitsub.add_parser(
        "broadcast",
        help="aggregate >=threshold partial exits and emit the signed exit",
    )
    ebcast.add_argument("--data-dir", required=True)
    ebcast.add_argument(
        "--partials", nargs="+", required=True, help="partial-exit json files"
    )
    ebcast.add_argument("--output", default="", help="signed-exit json path")
    elist = exitsub.add_parser(
        "list",
        help="list the cluster's validators eligible for exit "
        "(ref: cmd/exit_list.go)",
    )
    elist.add_argument("--data-dir", required=True)
    elist.add_argument(
        "--beacon-url",
        default="",
        help="also resolve on-chain index + status from this beacon node",
    )
    efetch = exitsub.add_parser(
        "fetch",
        help="fetch aggregated signed exits from the publish API "
        "(ref: cmd/exit_fetch.go)",
    )
    efetch.add_argument("--data-dir", required=True)
    efetch.add_argument(
        "--publish-address", required=True, help="obol publish API base URL"
    )
    efetch.add_argument(
        "--fetched-exit-path",
        default="",
        help="directory to store fetched signed exits (default: data dir)",
    )
    ebcast.add_argument(
        "--beacon-url", default="", help="POST the exit to this beacon node"
    )

    flightp = sub.add_parser(
        "flight", help="flight-recorder post-mortem tools"
    )
    flightsub = flightp.add_subparsers(dest="flight_command", required=True)
    fmerge = flightsub.add_parser(
        "merge",
        help="merge per-node flight dumps into one incident timeline",
    )
    fmerge.add_argument(
        "dumps", nargs="+", help="per-node flight-recorder JSONL dumps"
    )
    fmerge.add_argument(
        "--format",
        choices=("text", "jsonl"),
        default="text",
        help="text timeline (default) or merged JSONL",
    )
    fmerge.add_argument(
        "--category", default="", help="only events of this category"
    )
    fmerge.add_argument(
        "--tenant", default="", help="only events for this tenant"
    )
    fmerge.add_argument(
        "--output", default="", help="write here instead of stdout"
    )

    relayp = sub.add_parser("relay", help="run a rendezvous relay server")
    relayp.add_argument("--port", type=int, default=3640)
    relayp.add_argument("--host", default="0.0.0.0")

    alpha = sub.add_parser("alpha", help="experimental commands")
    alphasub = alpha.add_subparsers(dest="alpha_command", required=True)
    addv = alphasub.add_parser(
        "add-validators",
        help="solo: add validators to an existing cluster via the "
        "manifest mutation chain",
    )
    addv.add_argument(
        "--cluster-dir",
        required=True,
        help="directory with ALL node*/ data dirs (solo operator)",
    )
    addv.add_argument("--count", type=int, default=1)

    testp = sub.add_parser("test", help="operator diagnostics")
    testsub = testp.add_subparsers(dest="test_command", required=True)
    tpeers = testsub.add_parser("peers", help="measure peer connectivity")
    tpeers.add_argument(
        "--peers", required=True, help="comma-separated host:port list"
    )
    tpeers.add_argument("--count", type=int, default=5)
    tbeacon = testsub.add_parser("beacon", help="measure beacon-node latency")
    tbeacon.add_argument("--beacon-url", required=True)
    tbeacon.add_argument("--count", type=int, default=5)
    tvc = testsub.add_parser(
        "validator", help="measure validator-API latency (ref: cmd/testvalidator.go)"
    )
    tvc.add_argument("--validator-api-url", required=True)
    tvc.add_argument("--count", type=int, default=5)
    tmev = testsub.add_parser(
        "mev", help="measure MEV-boost relay latency (ref: cmd/testmev.go)"
    )
    tmev.add_argument("--mev-url", required=True)
    tmev.add_argument("--count", type=int, default=5)
    tperf = testsub.add_parser(
        "performance",
        help="local disk/hash/BLS throughput diagnostics "
        "(ref: cmd/testperformance.go)",
    )
    tperf.add_argument("--duration", type=float, default=1.0)

    sub.add_parser("version", help="print version")
    return p


def cmd_create_cluster(args) -> int:
    """ref: cmd/createcluster.go — an in-memory ceremony producing every
    node's directory (lock + keystores + p2p key)."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition, Operator
    from charon_tpu.dkg import frost
    from charon_tpu.dkg.ceremony import MemExchangeNet, run_dkg
    from charon_tpu.eth2util import enr as enrlib

    n, t, v = args.nodes, args.threshold, args.validators
    out = Path(args.output_dir)
    keys = [k1util.generate_private_key() for _ in range(n)]
    ops = tuple(
        Operator(
            address=f"operator-{i}",
            enr=enrlib.new(keys[i]).to_string(),
        )
        for i in range(n)
    )
    defn = ClusterDefinition(
        name=args.name,
        num_validators=v,
        threshold=t,
        fork_version=args.fork_version,
        operators=ops,
    )
    for i in range(n):
        defn = defn.sign_operator(i, keys[i])

    async def ceremony():
        fnet = frost.MemFrostTransport(n)
        xnet = MemExchangeNet(n)
        return await asyncio.gather(
            *(
                run_dkg(
                    defn,
                    i,
                    keys[i],
                    fnet.participant(i + 1),
                    xnet.port(i),
                    data_dir=out / f"node{i}",
                )
                for i in range(n)
            )
        )

    results = run_coro(ceremony())
    for i in range(n):
        key_path = out / f"node{i}" / "charon-enr-private-key"
        key_path.touch(mode=0o600)
        key_path.write_bytes(k1util.private_key_to_bytes(keys[i]))
    (out / "cluster-definition.json").write_text(
        json.dumps(defn.to_json(), indent=2)
    )
    print(f"created {n}-node cluster (threshold {t}, {v} validators) in {out}")
    print(f"lock hash: 0x{results[0].lock.lock_hash().hex()}")
    return 0


def cmd_run(args) -> int:
    # flag validation runs BEFORE the app.run import: bad flags must
    # fail fast with a clean diagnostic even on hosts where the node
    # stack's optional dependencies are absent
    if args.crypto_plane not in ("auto", "on", "off"):
        # env-var default bypassed argparse choices validation
        print(
            f"--crypto-plane {args.crypto_plane!r}: must be auto, on, or off",
            file=sys.stderr,
        )
        return 2
    if args.crypto_plane_prewarm not in ("auto", "on", "off"):
        print(
            f"--crypto-plane-prewarm {args.crypto_plane_prewarm!r}: "
            "must be auto, on, or off",
            file=sys.stderr,
        )
        return 2
    if args.crypto_plane_decode not in ("auto", "device", "python"):
        print(
            f"--crypto-plane-decode {args.crypto_plane_decode!r}: "
            "must be auto, device, or python",
            file=sys.stderr,
        )
        return 2
    if args.crypto_plane_warmup not in ("auto", "on", "off"):
        print(
            f"--crypto-plane-warmup {args.crypto_plane_warmup!r}: "
            "must be auto, on, or off",
            file=sys.stderr,
        )
        return 2
    if args.crypto_autotune not in ("auto", "on", "off", "force"):
        print(
            f"--crypto-autotune {args.crypto_autotune!r}: "
            "must be auto, on, off, or force",
            file=sys.stderr,
        )
        return 2

    rc = _init_featureset(args)
    if rc:
        return rc

    if args.fault_injection:
        # fail fast: a typo'd fault spec silently injecting nothing
        # would void the whole chaos run
        try:
            from charon_tpu.testutil.chaos import config_from_spec

            config_from_spec(args.fault_injection)
        except ValueError as e:
            print(f"--fault-injection: {e}", file=sys.stderr)
            return 2

    # networked crypto plane (ISSUE 17): validate the address shape
    # here (fail fast) and pull secrets from env only — never argv
    crypto_remote_token = ""
    if args.crypto_remote:
        host, sep, port = args.crypto_remote.rpartition(":")
        if not sep or not port.isdigit():
            print(
                f"--crypto-remote {args.crypto_remote!r}: "
                "must be host:port",
                file=sys.stderr,
            )
            return 2
        crypto_remote_token = os.environ.get(
            "CHARON_TPU_CRYPTO_TOKEN", ""
        )
        if not crypto_remote_token:
            print(
                "--crypto-remote requires the CHARON_TPU_CRYPTO_TOKEN "
                "environment variable (tokens never go on argv)",
                file=sys.stderr,
            )
            return 2
    crypto_serve_tokens = {}
    if args.crypto_serve >= 0:
        raw = os.environ.get("CHARON_TPU_CRYPTO_SERVE_TOKENS", "")
        for part in raw.split(","):
            if not part.strip():
                continue
            tenant, sep, token = part.partition("=")
            if not sep or not tenant.strip() or not token:
                print(
                    "CHARON_TPU_CRYPTO_SERVE_TOKENS: entries must be "
                    "'tenant=token', comma-separated",
                    file=sys.stderr,
                )
                return 2
            crypto_serve_tokens[tenant.strip()] = token
        if not crypto_serve_tokens:
            print(
                "--crypto-serve requires CHARON_TPU_CRYPTO_SERVE_TOKENS "
                "('tenant=token,...'); refusing to serve with no "
                "authenticated tenants",
                file=sys.stderr,
            )
            return 2

    peer_addrs = []
    if args.peers:
        for part in args.peers.split(","):
            host, port = part.rsplit(":", 1)
            peer_addrs.append((host, int(port)))

    from charon_tpu.app.run import Config, run

    config = Config(
        data_dir=args.data_dir,
        node_index=args.node_index,
        validator_api_port=args.validator_api_port,
        monitoring_port=args.monitoring_port,
        p2p_port=args.p2p_port,
        peer_addrs=peer_addrs,
        simnet=args.simnet,
        beacon_urls=[
            u.strip() for u in args.beacon_urls.split(",") if u.strip()
        ],
        slot_duration=args.slot_duration,
        slots_per_epoch=args.slots_per_epoch,
        genesis_time=args.genesis_time,
        use_tpu_tbls=not args.no_tpu,
        crypto_plane=args.crypto_plane,
        crypto_plane_window=args.crypto_plane_window,
        crypto_plane_decode_workers=args.crypto_plane_decode_workers,
        crypto_plane_prewarm=args.crypto_plane_prewarm,
        crypto_plane_decode=args.crypto_plane_decode,
        crypto_plane_warmup=args.crypto_plane_warmup,
        crypto_autotune=args.crypto_autotune,
        crypto_autotune_profile=args.crypto_autotune_profile,
        crypto_tenant=args.crypto_tenant,
        crypto_tenant_weight=args.crypto_tenant_weight,
        crypto_tenant_queue_lanes=args.crypto_tenant_queue_lanes,
        crypto_tenant_queue_jobs=args.crypto_tenant_queue_jobs,
        crypto_plane_round_lanes=args.crypto_plane_round_lanes,
        crypto_breaker_threshold=args.crypto_breaker_threshold,
        crypto_breaker_cooldown=args.crypto_breaker_cooldown,
        crypto_remote=args.crypto_remote,
        crypto_remote_token=crypto_remote_token,
        crypto_serve=args.crypto_serve if args.crypto_serve >= 0 else None,
        crypto_serve_host=args.crypto_serve_host,
        crypto_serve_tokens=crypto_serve_tokens,
        tracing_endpoint=args.tracing_endpoint,
        tracing_jsonl=args.tracing_jsonl,
        relay_addr=args.relay,
        fault_injection=args.fault_injection,
    )
    run_coro(run(config))
    return 0


def _load_node_key(data_dir):
    from charon_tpu.app import k1util

    key_path = Path(data_dir) / "charon-enr-private-key"
    return k1util.private_key_from_bytes(key_path.read_bytes())


def _operator_index_for_key(defn, key) -> int:
    """This key's 0-based operator index in the definition, or -1."""
    from charon_tpu.app import k1util

    from charon_tpu.eth2util import enr

    my_pub = k1util.public_key_to_bytes(key.public_key())
    for i, op in enumerate(defn.operators):
        try:
            if enr.pubkey_from_string(op.enr) == my_pub:
                return i
        except ValueError:
            continue
    return -1


def cmd_dkg(args) -> int:
    """Networked ceremony over localhost/TCP (ref: dkg/dkg.go:82 Run):
    mesh up -> sync protocol -> FROST -> signed lock + keystores written
    to --data-dir."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition
    from charon_tpu.dkg.netdkg import run_networked_dkg

    defn = ClusterDefinition.from_json(
        json.loads(Path(args.definition_file).read_text())
    )
    key = _load_node_key(args.data_dir)
    node_idx = args.node_index
    if node_idx < 0:
        node_idx = _operator_index_for_key(defn, key)
        if node_idx < 0:
            print("this node's key matches no definition operator", file=sys.stderr)
            return 1

    peer_addrs = []
    for part in args.peers.split(","):
        host, port = part.rsplit(":", 1)
        peer_addrs.append((host, int(port)))
    if len(peer_addrs) != len(defn.operators):
        print(
            f"--peers must list all {len(defn.operators)} operators",
            file=sys.stderr,
        )
        return 1

    engine = None
    if not args.no_tpu:
        try:
            from charon_tpu.ops import blsops, limb

            engine = blsops.BlsEngine(
                limb.default_fp_ctx(), limb.default_fr_ctx()
            )
        except Exception as e:
            print(
                f"warning: TPU engine unavailable ({type(e).__name__}: {e}); "
                "running ceremony on the host crypto path",
                file=sys.stderr,
            )
            engine = None

    result = run_coro(
        run_networked_dkg(
            defn,
            node_idx,
            key,
            peer_addrs,
            data_dir=args.data_dir,
            engine=engine,
            timeout=args.timeout,
        )
    )
    print(f"dkg complete; lock hash: 0x{result.lock.lock_hash().hex()}")

    if args.keymanager_address:
        # push share keystores into the operator's VC
        # (ref: dkg.go:118-128 keymanager import; eth2util/keymanager)
        from charon_tpu.eth2util.keymanager import KeymanagerClient

        keys_dir = Path(args.data_dir) / "validator_keys"
        keystores, passwords = [], []
        i = 0
        while (keys_dir / f"keystore-{i}.json").exists():
            keystores.append(
                json.loads((keys_dir / f"keystore-{i}.json").read_text())
            )
            passwords.append(
                (keys_dir / f"keystore-{i}.txt").read_text().strip()
            )
            i += 1
        client = KeymanagerClient(args.keymanager_address)
        run_coro(client.import_keystores(keystores, passwords))
        print(f"pushed {len(keystores)} keystores to keymanager")

    if args.publish_address:
        from charon_tpu.app.obolapi import ObolApiClient

        run_coro(ObolApiClient(args.publish_address).publish_lock(result.lock))
        print("lock published")
    return 0


def cmd_create_enr(args) -> int:
    """ref: cmd/createenr.go — new key + printed EIP-778 record."""
    from charon_tpu.app import k1util
    from charon_tpu.eth2util import enr as enrlib

    data_dir = Path(args.data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    key_path = data_dir / "charon-enr-private-key"
    if key_path.exists():
        print(f"refusing to overwrite {key_path}", file=sys.stderr)
        return 1
    key = k1util.generate_private_key()
    key_path.touch(mode=0o600)
    key_path.write_bytes(k1util.private_key_to_bytes(key))
    print(enrlib.new(key).to_string())
    return 0


def cmd_create_dkg(args) -> int:
    """ref: cmd/createdkg.go — an unsigned definition the operators then
    sign (sign-definition) before running `dkg`."""
    from charon_tpu.cluster.definition import ClusterDefinition, Operator

    enrs = [e.strip() for e in args.operator_enrs.split(",") if e.strip()]
    n = len(enrs)
    if n < 3:
        print("need at least 3 operators", file=sys.stderr)
        return 1
    threshold = args.threshold or n - (n - 1) // 3
    if not 1 < threshold <= n:
        print(f"threshold must be in (1, {n}], got {threshold}", file=sys.stderr)
        return 1
    defn = ClusterDefinition(
        name=args.name,
        num_validators=args.num_validators,
        threshold=threshold,
        fork_version=args.fork_version,
        operators=tuple(
            Operator(address=f"operator-{i}", enr=enr)
            for i, enr in enumerate(enrs)
        ),
    )
    Path(args.output).write_text(json.dumps(defn.to_json(), indent=2))
    print(f"wrote {args.output} ({n} operators, threshold {threshold})")
    return 0


def cmd_sign_definition(args) -> int:
    """Each operator signs the config hash + their record in turn
    (ref: the launchpad EIP-712 signing step, cluster/eip712sigs.go)."""
    from charon_tpu.app import k1util
    from charon_tpu.cluster.definition import ClusterDefinition

    path = Path(args.definition_file)
    defn = ClusterDefinition.from_json(json.loads(path.read_text()))
    key = _load_node_key(args.data_dir)
    idx = _operator_index_for_key(defn, key)
    if idx < 0:
        print("this node's key matches no definition operator", file=sys.stderr)
        return 1
    defn = defn.sign_operator(idx, key)
    path.write_text(json.dumps(defn.to_json(), indent=2))
    print(f"signed as operator {idx}")
    return 0


def cmd_enr(args) -> int:
    from charon_tpu.eth2util import enr as enrlib

    key = _load_node_key(args.data_dir)
    print(enrlib.new(key).to_string())
    return 0


def cmd_combine(args) -> int:
    """Reconstruct the group private keys from >= threshold node dirs
    (ref: cmd/combine — Lagrange-recover at x=0 from share keystores)."""
    from charon_tpu import tbls
    from charon_tpu.cluster.manifest import load_cluster_state
    from charon_tpu.eth2util import keystore

    cluster_dir = Path(args.cluster_dir)
    node_dirs = sorted(
        d
        for d in cluster_dir.iterdir()
        if d.is_dir() and (d / "cluster-lock.json").exists()
    )
    if not node_dirs:
        print(f"no node dirs with cluster-lock.json in {cluster_dir}", file=sys.stderr)
        return 1

    # manifest-materialised state: includes validators added after the
    # original ceremony (ref: app/app.go:166)
    lock = load_cluster_state(node_dirs[0])
    n = len(lock.definition.operators)
    t = lock.definition.threshold
    v = len(lock.validators)

    # map each node dir to its share index by matching pubshares
    shares_by_validator: list[dict[int, bytes]] = [dict() for _ in range(v)]
    for d in node_dirs:
        if load_cluster_state(d).lock_hash() != lock.lock_hash():
            print(f"{d} belongs to a different cluster", file=sys.stderr)
            return 1
        secrets = keystore.load_keys(d / "validator_keys")
        if len(secrets) != v:
            print(f"{d} has {len(secrets)} keystores, want {v}", file=sys.stderr)
            return 1
        impl = tbls.get_implementation()
        for vi, secret in enumerate(secrets):
            pub = impl.secret_to_public_key(secret)
            pubshares = [
                bytes.fromhex(s[2:])
                for s in lock.validators[vi].public_shares
            ]
            if pub not in pubshares:
                print(f"{d} keystore {vi} matches no pubshare", file=sys.stderr)
                return 1
            shares_by_validator[vi][pubshares.index(pub) + 1] = secret

    if any(len(s) < t for s in shares_by_validator):
        got = min(len(s) for s in shares_by_validator)
        print(f"need >= {t} share keystores per validator, got {got}", file=sys.stderr)
        return 1

    out = Path(args.output_dir)
    if out.exists() and any(out.iterdir()) and not args.force:
        print(f"{out} is not empty (use --force)", file=sys.stderr)
        return 1
    secrets, pubkeys = [], []
    for vi in range(v):
        secret = tbls.recover_secret(shares_by_validator[vi], n, t)
        want = lock.validators[vi].distributed_public_key
        have = "0x" + tbls.secret_to_public_key(secret).hex()
        if want != have:
            print(f"recovered key {vi} mismatches lock pubkey", file=sys.stderr)
            return 1
        secrets.append(secret)
        pubkeys.append(want)
    # `recover` EXISTS to write the combined keys back out as
    # encrypted EIP-2335 keystores  # lint: allow(secret-flow)
    keystore.store_keys(secrets, out, pubkeys=pubkeys)
    print(f"recovered {v} validator key(s) into {out}")
    return 0


def cmd_reshare(args) -> int:
    """Local resharing ceremony over a cluster directory (dkg/reshare):
    operator join/leave, threshold change, or proactive rotation — the
    group keys never change, every share does. Keystores swap in
    atomically per node dir (the pre-reshare set stays at
    validator_keys.pre-reshare until the operator retires it); the new
    pubshare map lands in reshare-pubshares.json for the lock/manifest
    update. See docs/operations.md "Key resharing at scale"."""
    from charon_tpu import tbls
    from charon_tpu.cluster.manifest import load_cluster_state
    from charon_tpu.crypto.g1g2 import g1_from_bytes, g1_to_bytes
    from charon_tpu.dkg import reshare
    from charon_tpu.eth2util import keystore

    cluster_dir = Path(args.cluster_dir)
    node_dirs = sorted(
        d
        for d in cluster_dir.iterdir()
        if d.is_dir() and (d / "cluster-lock.json").exists()
    )
    if not node_dirs:
        print(f"no node dirs with cluster-lock.json in {cluster_dir}", file=sys.stderr)
        return 1
    lock = load_cluster_state(node_dirs[0])
    n = len(lock.definition.operators)
    t = lock.definition.threshold
    v = len(lock.validators)
    pubshare_rows = [
        [bytes.fromhex(s[2:]) for s in val.public_shares]
        for val in lock.validators
    ]

    # map each dir to its share index by matching keystore pubshares
    # (cmd_combine idiom) — dealers are exactly the old nodes present
    impl = tbls.get_implementation()
    dirs_by_idx: dict[int, Path] = {}
    secrets_by_idx: dict[int, list[int]] = {}
    for d in node_dirs:
        if load_cluster_state(d).lock_hash() != lock.lock_hash():
            print(f"{d} belongs to a different cluster", file=sys.stderr)
            return 1
        secrets = keystore.load_keys(d / "validator_keys")
        if len(secrets) != v:
            print(f"{d} has {len(secrets)} keystores, want {v}", file=sys.stderr)
            return 1
        pub = impl.secret_to_public_key(secrets[0])
        if pub not in pubshare_rows[0]:
            print(f"{d} keystore matches no pubshare", file=sys.stderr)
            return 1
        idx = pubshare_rows[0].index(pub) + 1
        dirs_by_idx[idx] = d
        secrets_by_idx[idx] = [int.from_bytes(s, "big") for s in secrets]

    old_indices = tuple(sorted(dirs_by_idx))
    if len(old_indices) < t:
        print(
            f"need >= threshold ({t}) node dirs to reshare, got "
            f"{len(old_indices)}",
            file=sys.stderr,
        )
        return 1
    n_new = args.new_nodes or n
    t_new = args.threshold or (n_new - (n_new - 1) // 3)
    new_indices = tuple(range(1, n_new + 1))
    try:
        cfg = reshare.ReshareConfig(
            old_indices=old_indices,
            new_indices=new_indices,
            t_old=t,
            t_new=t_new,
            num_validators=v,
        )
    except reshare.ReshareError as e:
        print(f"bad reshare parameters: {e}", file=sys.stderr)
        return 1

    old_pubshares = [
        {j: g1_from_bytes(row[j - 1]) for j in range(1, n + 1)}
        for row in pubshare_rows
    ]
    group_pubkeys = [
        g1_from_bytes(bytes.fromhex(val.distributed_public_key[2:]))
        for val in lock.validators
    ]
    engine = None
    if not args.no_tpu:
        try:
            from charon_tpu.ops.blsops import BlsEngine

            engine = BlsEngine()
        except Exception as e:  # noqa: BLE001 — host-only fallback
            print(f"device engine unavailable ({e}); verifying on host")

    participants = sorted(set(old_indices) | set(new_indices))
    transport = reshare.MemReshareTransport(dealer_indices=old_indices)

    async def ceremony():
        return await asyncio.gather(
            *(
                reshare.run_reshare_parallel(
                    transport.participant(i),
                    i,
                    cfg,
                    old_pubshares,
                    group_pubkeys,
                    share_secrets=secrets_by_idx.get(i),
                    engine=engine,
                )
                for i in participants
            )
        )

    try:
        results = dict(zip(participants, run_coro(ceremony())))
    except reshare.ReshareError as e:
        print(f"reshare aborted: {e}", file=sys.stderr)
        return 1

    pubshare_map: dict[int, list[str]] = {}
    for j in new_indices:
        res = results[j]
        target = dirs_by_idx.get(j, cluster_dir / f"node{j - 1}")
        hexes = [
            "0x" + g1_to_bytes(r.pubshares[j]).hex() for r in res
        ]
        pubshare_map[j] = hexes
        reshare.write_reshare_outputs(target, res, pubshare_hexes=hexes)
    (cluster_dir / "reshare-pubshares.json").write_text(
        json.dumps(
            {
                "threshold": t_new,
                "num_operators": n_new,
                "public_shares": {str(j): pubshare_map[j] for j in new_indices},
            },
            indent=2,
        )
    )
    left = sorted(set(old_indices) - set(new_indices))
    print(
        f"reshared {v} validator(s): {len(old_indices)} dealers -> "
        f"{n_new} operators (threshold {t} -> {t_new})"
    )
    if left:
        print(
            f"operators {left} left the cluster — retire their "
            "validator_keys.pre-reshare directories"
        )
    print(
        "new pubshares in reshare-pubshares.json; update the cluster "
        "lock/manifest before restarting nodes"
    )
    return 0


def cmd_exit(args) -> int:
    from charon_tpu import tbls
    from charon_tpu.cluster.manifest import load_cluster_state
    from charon_tpu.core.eth2data import SignedData, VoluntaryExit
    from charon_tpu.eth2util import keystore

    data_dir = Path(args.data_dir)
    lock = load_cluster_state(data_dir)
    fork = lock.fork_info()

    if args.exit_command == "sign":
        # ref: cmd/exit_sign.go — one partial exit signed with this
        # node's share key
        vi = args.validator_index
        if not 0 <= vi < len(lock.validators):
            print("validator index out of range", file=sys.stderr)
            return 1
        dv = lock.validators[vi]
        if args.validator_pubkey and args.validator_pubkey.lower() != dv.distributed_public_key.lower():
            print("pubkey does not match lock validator at that index", file=sys.stderr)
            return 1
        secrets = keystore.load_keys(data_dir / "validator_keys")
        secret = secrets[vi]
        impl = tbls.get_implementation()
        my_pubshare = impl.secret_to_public_key(secret)
        share_idx = [
            bytes.fromhex(s[2:]) for s in dv.public_shares
        ].index(my_pubshare) + 1

        exit_msg = VoluntaryExit(epoch=args.epoch, validator_index=vi)
        root = SignedData("exit", exit_msg).signing_root(fork, args.epoch)
        sig = tbls.sign(secret, root)
        out = {
            "validator_pubkey": dv.distributed_public_key,
            "validator_index": vi,
            "epoch": args.epoch,
            "share_idx": share_idx,
            "partial_signature": sig.hex(),
        }
        path = args.output or str(
            data_dir / f"exit-partial-{vi}-{share_idx}.json"
        )
        Path(path).write_text(json.dumps(out, indent=2))
        print(f"wrote partial exit {path}")
        return 0

    if args.exit_command == "list":
        # ref: cmd/exit_list.go — the cluster's validators with (when a
        # BN is reachable) their on-chain index and status
        rows = []
        chain: dict[str, dict] = {}
        if args.beacon_url:
            import aiohttp

            async def fetch_statuses():
                async with aiohttp.ClientSession() as s:
                    ids = ",".join(
                        dv.distributed_public_key for dv in lock.validators
                    )
                    async with s.get(
                        args.beacon_url.rstrip("/")
                        + "/eth/v1/beacon/states/head/validators",
                        params={"id": ids},
                    ) as resp:
                        if resp.status != 200:
                            raise RuntimeError(
                                f"beacon validators query: HTTP {resp.status}"
                            )
                        for v in (await resp.json())["data"]:
                            chain[v["validator"]["pubkey"].lower()] = v

            run_coro(fetch_statuses())
        for i, dv in enumerate(lock.validators):
            onchain = chain.get(dv.distributed_public_key.lower(), {})
            rows.append(
                {
                    "cluster_index": i,
                    "validator_pubkey": dv.distributed_public_key,
                    "validator_index": onchain.get("index"),
                    "status": onchain.get("status"),
                }
            )
        print(json.dumps(rows, indent=2))
        return 0

    if args.exit_command == "fetch":
        # ref: cmd/exit_fetch.go — pull the aggregated signed exit for
        # each cluster validator from the publish API once threshold
        # partial shares were uploaded
        from charon_tpu.app.obolapi import ObolApiClient

        client = ObolApiClient(args.publish_address)
        out_dir = Path(args.fetched_exit_path or data_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        lock_hash = lock.lock_hash()

        async def fetch_all() -> int:
            fetched = 0
            for i, dv in enumerate(lock.validators):
                full = await client.fetch_full_exit(
                    lock_hash, dv.distributed_public_key
                )
                if full is None:
                    print(
                        f"validator {i}: exit not ready (needs threshold "
                        "partial shares)",
                    )
                    continue
                path = out_dir / f"exit-{dv.distributed_public_key}.json"
                path.write_text(json.dumps(full, indent=2))
                print(f"validator {i}: wrote {path}")
                fetched += 1
            return fetched

        run_coro(fetch_all())
        return 0

    # broadcast: aggregate >= t partials, verify, emit/submit
    # (ref: cmd/exit_broadcast.go)
    partials = [json.loads(Path(p).read_text()) for p in args.partials]
    vi = partials[0]["validator_index"]
    epoch = partials[0]["epoch"]
    if any(p["validator_index"] != vi or p["epoch"] != epoch for p in partials):
        print("partials disagree on validator/epoch", file=sys.stderr)
        return 1
    if not 0 <= vi < len(lock.validators):
        print(
            f"partials reference validator {vi}, cluster has "
            f"{len(lock.validators)}",
            file=sys.stderr,
        )
        return 1
    t = lock.definition.threshold
    # dedup by share index BEFORE the threshold count/slice so duplicate
    # files can't silently under-fill the quorum
    by_share = {
        p["share_idx"]: bytes.fromhex(p["partial_signature"])
        for p in partials
    }
    if len(by_share) < t:
        print(
            f"need >= {t} distinct share partials, got {len(by_share)}",
            file=sys.stderr,
        )
        return 1
    exit_msg = VoluntaryExit(epoch=epoch, validator_index=vi)
    root = SignedData("exit", exit_msg).signing_root(fork, epoch)
    subset = dict(sorted(by_share.items())[:t])
    sig = tbls.threshold_aggregate(subset)
    group_pk = bytes.fromhex(
        lock.validators[vi].distributed_public_key[2:]
    )
    try:
        tbls.verify(group_pk, root, sig)
    except Exception as e:
        print(f"aggregated exit signature invalid: {e}", file=sys.stderr)
        return 1
    signed = {
        "message": {"epoch": str(epoch), "validator_index": str(vi)},
        "signature": "0x" + sig.hex(),
    }
    path = args.output or str(data_dir / f"exit-{vi}.json")
    Path(path).write_text(json.dumps(signed, indent=2))
    print(f"wrote signed exit {path}")
    if args.beacon_url:
        import aiohttp

        async def submit():
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    args.beacon_url.rstrip("/")
                    + "/eth/v1/beacon/pool/voluntary_exits",
                    json=signed,
                ) as resp:
                    if resp.status != 200:
                        raise RuntimeError(
                            f"beacon rejected exit: HTTP {resp.status}"
                        )

        run_coro(submit())
        print("broadcast to beacon node")
    return 0


def cmd_alpha(args) -> int:
    """alpha add-validators: a solo operator (holding every node dir)
    extends the cluster with new distributed validators via the manifest
    mutation chain (ref: cmd/addvalidators.go add-validators-solo +
    cluster/manifest mutations)."""
    from charon_tpu.cluster.lock import DistributedValidator
    from charon_tpu.cluster.manifest import Manifest, load_cluster_state
    from charon_tpu.crypto.g1g2 import g1_to_bytes
    from charon_tpu.dkg import frost
    from charon_tpu.eth2util import keystore

    cluster_dir = Path(args.cluster_dir)
    node_dirs = sorted(
        d
        for d in cluster_dir.iterdir()
        if d.is_dir() and (d / "charon-enr-private-key").exists()
    )
    if not node_dirs:
        print(f"no node dirs in {cluster_dir}", file=sys.stderr)
        return 1
    lock = load_cluster_state(node_dirs[0])
    n = len(lock.definition.operators)
    t = lock.definition.threshold
    if len(node_dirs) != n:
        print(f"solo add-validators needs all {n} node dirs", file=sys.stderr)
        return 1
    # map each dir to its OPERATOR index via its key — directory sort
    # order is lexicographic (node10 < node2) and must not decide share
    # indices
    by_op: dict[int, object] = {}
    for d in node_dirs:
        key = _load_node_key(d)
        idx = _operator_index_for_key(lock.definition, key)
        if idx < 0:
            print(f"{d} key matches no operator", file=sys.stderr)
            return 1
        by_op[idx] = (d, key)
    if sorted(by_op) != list(range(n)):
        print("node dirs do not cover all operators", file=sys.stderr)
        return 1
    node_dirs = [by_op[i][0] for i in range(n)]
    keys = [by_op[i][1] for i in range(n)]

    # new FROST ceremony for the added validators only
    async def ceremony():
        net = frost.MemFrostTransport(n)
        return await asyncio.gather(
            *(
                frost.run_frost_parallel(
                    net.participant(i + 1),
                    i + 1,
                    n,
                    t,
                    args.count,
                    lock.lock_hash(),  # context binds to the cluster
                )
                for i in range(n)
            )
        )

    per_node_results = run_coro(ceremony())
    new_validators = [
        DistributedValidator(
            distributed_public_key="0x"
            + g1_to_bytes(r.group_pubkey).hex(),
            public_shares=tuple(
                "0x" + g1_to_bytes(r.pubshares[j]).hex()
                for j in range(1, n + 1)
            ),
        )
        for r in per_node_results[0]
    ]

    # manifest chain: genesis (if absent) -> add_validators -> approvals
    manifest_path = node_dirs[0] / "cluster-manifest.json"
    manifest = (
        Manifest.load(str(manifest_path))
        if manifest_path.exists()
        else Manifest.genesis(lock)
    )
    mutation = manifest.propose_add_validators(new_validators)
    manifest = manifest.append(mutation)
    for key in keys:  # every operator approves (solo holds all keys)
        manifest = manifest.append(manifest.approve(mutation.hash(), key))
    state = manifest.materialise()

    existing = len(lock.validators)
    for i, d in enumerate(node_dirs):
        manifest.save(str(d / "cluster-manifest.json"))
        share_secrets = [
            (r.secret_share % (1 << 256)).to_bytes(32, "big")
            for r in per_node_results[i]
        ]
        # add-validators writes the new shares as encrypted keystores
        # next to the existing set  # lint: allow(secret-flow)
        keystore.store_keys(
            share_secrets,
            d / "validator_keys",
            pubkeys=[dv.public_shares[i] for dv in new_validators],
            start_index=existing,
        )
    print(
        f"added {args.count} validator(s); cluster now has "
        f"{len(state.validators)} (manifest head 0x{manifest.head().hex()[:16]})"
    )
    return 0


def cmd_relay(args) -> int:
    """ref: cmd/relay — run the rendezvous/forwarding relay daemon."""
    from charon_tpu.p2p.relay import RelayServer

    async def serve():
        server = RelayServer()
        port = await server.start(args.host, args.port)
        print(f"relay listening on {args.host}:{port}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    try:
        run_coro(serve())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_test(args) -> int:
    """ref: cmd/test.go — operator diagnostics with latency stats."""
    import statistics
    import time

    def stats_line(name, samples_ms, errs):
        if samples_ms:
            line = (
                f"{name}: min={min(samples_ms):.1f}ms "
                f"median={statistics.median(samples_ms):.1f}ms "
                f"max={max(samples_ms):.1f}ms ok={len(samples_ms)}"
            )
        else:
            line = f"{name}: unreachable"
        if errs:
            line += f" errors={errs}"
        print(line)
        return bool(samples_ms)

    if args.test_command == "peers":
        async def probe_peer(host, port):
            samples, errs = [], 0
            for _ in range(args.count):
                t0 = time.perf_counter()
                try:
                    _, w = await asyncio.wait_for(
                        asyncio.open_connection(host, port), timeout=3
                    )
                    samples.append((time.perf_counter() - t0) * 1000)
                    w.close()
                except Exception:
                    errs += 1
            return samples, errs

        async def run_all():
            ok = True
            for part in args.peers.split(","):
                host, port = part.rsplit(":", 1)
                samples, errs = await probe_peer(host, int(port))
                ok &= stats_line(f"peer {part}", samples, errs)
            return 0 if ok else 1

        return run_coro(run_all())

    if args.test_command == "performance":
        # local machine diagnostics (ref: cmd/testperformance.go measures
        # disk and networking envelopes): sequential disk write MB/s,
        # SHA-256 MB/s, and host-backend BLS verify sigs/sec — the three
        # resources a charon-tpu node leans on.
        import hashlib
        import os
        import tempfile

        chunk = os.urandom(4 << 20)
        t0, written = time.perf_counter(), 0
        with tempfile.NamedTemporaryFile(dir=".") as f:
            while time.perf_counter() - t0 < args.duration:
                f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
                written += len(chunk)
        disk = written / (time.perf_counter() - t0) / 1e6
        print(f"disk_write: {disk:.0f} MB/s")

        t0, hashed = time.perf_counter(), 0
        while time.perf_counter() - t0 < args.duration:
            hashlib.sha256(chunk).digest()
            hashed += len(chunk)
        print(f"sha256: {hashed / (time.perf_counter() - t0) / 1e6:.0f} MB/s")

        try:
            from charon_tpu.tbls.native_impl import NativeImpl

            impl = NativeImpl()
            sk = (123).to_bytes(32, "big")
            pk = impl.secret_to_public_key(sk)
            sig = impl.sign(sk, b"perf-probe")
            t0, n = time.perf_counter(), 0
            while time.perf_counter() - t0 < args.duration:
                impl.verify(pk, b"perf-probe", sig)
                n += 1
            print(f"bls_verify_host: {n / (time.perf_counter() - t0):.0f} sigs/s")
        except Exception as e:  # native backend optional on exotic hosts
            print(f"bls_verify_host: unavailable ({e})")
        return 0

    # test beacon / validator / mev: HTTP latency probes against the
    # service's cheap status endpoint
    import aiohttp

    probes = {
        "beacon": ("beacon_url", "/eth/v1/node/version"),
        "validator": ("validator_api_url", "/eth/v1/node/version"),
        "mev": ("mev_url", "/eth/v1/builder/status"),
    }
    attr, path = probes[args.test_command]
    base = getattr(args, attr)

    async def probe_http():
        samples, errs = [], 0
        url = base.rstrip("/") + path
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=3)
        ) as s:
            for _ in range(args.count):
                t0 = time.perf_counter()
                try:
                    async with s.get(url) as resp:
                        await resp.read()
                        if resp.status == 200:
                            samples.append(
                                (time.perf_counter() - t0) * 1000
                            )
                        else:
                            errs += 1
                except Exception:
                    errs += 1
        return (
            0
            if stats_line(f"{args.test_command} {base}", samples, errs)
            else 1
        )

    return run_coro(probe_http())


def cmd_flight(args) -> int:
    """Post-mortem tooling over flight-recorder dumps (app/flightrec):
    merge per-node JSONL rings — dumped on SIGTERM/crash/stop or pulled
    from /debug/flight — into one wall-clock-ordered incident timeline,
    deduped by (node, seq)."""
    import json as _json

    from charon_tpu.app import flightrec

    events = flightrec.merge_jsonl(args.dumps)
    if args.category:
        events = [e for e in events if e.get("category") == args.category]
    if args.tenant:
        events = [e for e in events if e.get("tenant") == args.tenant]
    if args.format == "jsonl":
        out = "".join(_json.dumps(e) + "\n" for e in events)
    else:
        out = flightrec.render_timeline(events)
    if args.output:
        Path(args.output).write_text(out, encoding="utf-8")
        print(f"wrote {len(events)} events -> {args.output}")
    else:
        sys.stdout.write(out)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        from charon_tpu import __version__

        print(f"charon-tpu {__version__}")
        return 0
    return {
        "run": cmd_run,
        "create-cluster": cmd_create_cluster,
        "dkg": cmd_dkg,
        "create-enr": cmd_create_enr,
        "create-dkg": cmd_create_dkg,
        "sign-definition": cmd_sign_definition,
        "enr": cmd_enr,
        "combine": cmd_combine,
        "reshare": cmd_reshare,
        "exit": cmd_exit,
        "flight": cmd_flight,
        "relay": cmd_relay,
        "alpha": cmd_alpha,
        "test": cmd_test,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
