"""Persistent XLA compile-cache placement.

XLA:CPU serializes ahead-of-time executables that embed the *compile*
machine's CPU feature list; loading one on a host with a different
feature set fails ("machine features don't match ... could SIGILL",
cpu_aot_loader.cc) and forces a full recompile. That is how the round-4
multichip dryrun timed out: a 578 MB cache primed on the TPU-window
host was useless on the driver's host, so the dryrun drowned in loader
errors while recompiling everything inside its timeout
(MULTICHIP_r04.json tail).

Placement rule:

* CPU-platform runs key their cache dir by a host fingerprint (hash of
  the /proc/cpuinfo flags line) — entries compiled on another machine
  are simply *invisible* instead of noisily rejected, and same-host
  re-runs still hit warm.
* TPU-platform runs share one dir: the axon remote-compile service
  serializes device programs, not host AOT code, so those entries are
  host-portable and expensive to lose (~4-6 min remote compile per
  pairing program).

Shared by tests/conftest.py, bench_common.py and __graft_entry__.py so
every CPU-pinned harness on one host hits the same entries.
"""

from __future__ import annotations

import hashlib
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SHARED = os.path.join(_ROOT, ".jax_cache")


def host_fingerprint() -> str:
    """Stable id for this host's CPU feature set (what the XLA:CPU AOT
    loader actually checks)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    # sort: flag ORDER is not guaranteed stable across
                    # kernel versions, the feature SET is what matters
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(flags.encode()).hexdigest()[:12]
    except OSError:
        pass
    return "unknown-host"


def cache_dir(cpu: bool) -> str:
    """Cache dir for the given effective platform (see module doc)."""
    if cpu:
        return os.path.join(_SHARED, "cpu-" + host_fingerprint())
    return _SHARED


def configure(jax_mod, *, cpu: bool) -> str:
    """Point jax's persistent compilation cache at the right dir.

    Must run before any compilation; safe before backend init."""
    d = cache_dir(cpu)
    jax_mod.config.update("jax_compilation_cache_dir", d)
    jax_mod.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return d
