"""Persistent XLA compile-cache placement.

XLA:CPU serializes ahead-of-time executables that embed the *compile*
machine's CPU feature list; loading one on a host with a different
feature set fails ("machine features don't match ... could SIGILL",
cpu_aot_loader.cc) and forces a full recompile. That is how the round-4
multichip dryrun timed out: a 578 MB cache primed on the TPU-window
host was useless on the driver's host, so the dryrun drowned in loader
errors while recompiling everything inside its timeout
(MULTICHIP_r04.json tail).

Placement rule:

* CPU-platform runs key their cache dir by a host fingerprint (hash of
  the /proc/cpuinfo flags line) — entries compiled on another machine
  are simply *invisible* instead of noisily rejected, and same-host
  re-runs still hit warm.
* TPU-platform runs share one dir: the axon remote-compile service
  serializes device programs, not host AOT code, so those entries are
  host-portable and expensive to lose (~4-6 min remote compile per
  pairing program).

Shared by tests/conftest.py, bench_common.py and __graft_entry__.py so
every CPU-pinned harness on one host hits the same entries.
"""

from __future__ import annotations

import hashlib
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SHARED = os.path.join(_ROOT, ".jax_cache")


def host_fingerprint() -> str:
    """Stable id for this host's CPU feature set (what the XLA:CPU AOT
    loader actually checks)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    # sort: flag ORDER is not guaranteed stable across
                    # kernel versions, the feature SET is what matters
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(flags.encode()).hexdigest()[:12]
    except OSError:
        pass
    return "unknown-host"


def cache_dir(cpu: bool) -> str:
    """Cache dir for the given effective platform (see module doc)."""
    if cpu:
        return os.path.join(_SHARED, "cpu-" + host_fingerprint())
    return _SHARED


# Cache-effectiveness counters (ISSUE 18): jax emits a monitoring event
# per compilation that consulted the persistent cache and one per hit;
# misses = requests - hits. Registered once in configure(); the module
# stays importable without jax so app/metrics.py can scrape
# cache_stats() from any host process.
_EVENTS = {"hits": 0, "requests": 0}
_CONFIGURED_DIR: str | None = None


def _on_event(event: str, **_kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _EVENTS["hits"] += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _EVENTS["requests"] += 1


def configure(jax_mod, *, cpu: bool) -> str:
    """Point jax's persistent compilation cache at the right dir.

    Must run before any compilation; safe before backend init."""
    global _CONFIGURED_DIR
    d = cache_dir(cpu)
    jax_mod.config.update("jax_compilation_cache_dir", d)
    jax_mod.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    if _CONFIGURED_DIR is None:
        from jax import monitoring

        monitoring.register_event_listener(_on_event)
    _CONFIGURED_DIR = d
    return d


def cache_stats() -> dict | None:
    """Persistent-cache effectiveness for this process: entry count and
    bytes on disk plus hit/miss counts since configure(). None until
    configure() ran (host-only processes have no compile cache to
    report — app/metrics.observe_compile_cache skips the gauges then).
    """
    if _CONFIGURED_DIR is None:
        return None
    entries = 0
    nbytes = 0
    try:
        for root, _dirs, files in os.walk(_CONFIGURED_DIR):
            for name in files:
                if name.endswith(".json") or name.endswith(".tmp"):
                    continue  # the tuner profile, not an XLA artifact
                entries += 1
                try:
                    nbytes += os.stat(os.path.join(root, name)).st_size
                except OSError:
                    pass
    except OSError:
        pass
    return {
        "dir": _CONFIGURED_DIR,
        "entries": entries,
        "bytes": nbytes,
        "hits": _EVENTS["hits"],
        "misses": max(0, _EVENTS["requests"] - _EVENTS["hits"]),
    }
