"""Distributed key generation: FROST 2-round ceremonies.

Mirrors ref: dkg/ — ceremony orchestration (dkg/dkg.go:82), the FROST
round structure (dkg/frost.go:50-85 runs numValidators ceremonies in
lockstep sharing two transport rounds), pre-ceremony sync, and lock /
keystore outputs. The share-verification scalar-muls — the ceremony's
compute bulk — run batched on the device (BASELINE config 4).
"""
