"""Networked DKG: the ceremony over the TCP p2p mesh.

Mirrors ref: dkg/ —
  * sync protocol (dkg/sync/client.go:31-60): every node waits until all
    n peers are reachable and agree on (definition hash, version) before
    the ceremony starts;
  * FROST over p2p (dkg/frostp2p.go): round-1 commitment broadcasts are
    published to everyone; Shamir share vectors are addressed privately
    per recipient (served only to that peer; the transport's per-frame
    AES-GCM sealing protects them in transit);
  * signed exchange (dkg/bcast/impl.go:22-49): every published payload
    carries a k1 signature over (definition hash, tag, sender, payload),
    verified against the operator keys from the definition — the
    reliable-broadcast property that a peer cannot later equivocate about
    what it sent.

The transport is PULL-based: each node publishes its tagged payloads
locally and peers poll until they appear — robust to nodes starting at
different times (the reference's sync step exists for the same reason).
"""

from __future__ import annotations

import asyncio
import hashlib
import json

from charon_tpu.app import k1util
from charon_tpu.eth2util import enr
from charon_tpu.dkg.frost import Round1Broadcast, Round1Shares
from charon_tpu.p2p import codec
from charon_tpu.p2p.transport import P2PNode

DKG_PROTOCOL = "dkg/1.0.0"
DKG_VERSION = "ctpu-dkg/1"

codec.register(Round1Broadcast)
codec.register(Round1Shares)


class DkgError(Exception):
    pass


class TcpDkgTransport:
    """Signed tagged-payload exchange over an authenticated P2P mesh."""

    def __init__(
        self,
        node: P2PNode,
        defn,
        privkey,
        poll_interval: float = 0.25,
        timeout: float = 120.0,
    ) -> None:
        self.node = node
        self.defn = defn
        self.idx = node.index  # 0-based operator index
        self.n = len(defn.operators)
        self.def_hash = defn.definition_hash()
        self.privkey = privkey
        self.pubkeys = [
            enr.pubkey_from_string(op.enr) for op in defn.operators
        ]
        self.poll_interval = poll_interval
        self.timeout = timeout
        # tag -> (payload, sig_hex, private_to | None)
        self._local: dict[str, tuple] = {}
        node.register_handler(DKG_PROTOCOL, self._on_req)

    # -- signing -----------------------------------------------------------

    def _digest(self, tag: str, idx: int, payload) -> bytes:
        canon = json.dumps(
            codec._to_jsonable(payload), sort_keys=True, separators=(",", ":")
        ).encode()
        return hashlib.sha256(
            b"charon-tpu-dkg"
            + self.def_hash
            + tag.encode()
            + idx.to_bytes(4, "big")
            + canon
        ).digest()

    def publish(self, tag: str, payload, private_to: int | None = None) -> None:
        sig = k1util.sign(self.privkey, self._digest(tag, self.idx, payload))
        self._local[tag] = (payload, sig.hex(), private_to)

    async def _on_req(self, from_idx: int, msg):
        entry = self._local.get(msg.get("tag", ""))
        if entry is None:
            return {"ok": False}
        payload, sig_hex, private_to = entry
        # private payloads are served ONLY to their addressee (the channel
        # itself is AES-GCM sealed, so nothing leaks in transit either)
        if private_to is not None and from_idx != private_to:
            return {"ok": False}
        return {"ok": True, "payload": payload, "sig": sig_hex}

    # -- pulling -----------------------------------------------------------

    async def _pull(self, peer: int, tag: str, sender: int | None = None):
        """Poll `peer` for `tag` until it appears and its signature
        verifies against operator `sender` (default: the peer itself)."""
        sender = peer if sender is None else sender
        deadline = asyncio.get_running_loop().time() + self.timeout
        while True:
            try:
                resp = await self.node.send(
                    peer, DKG_PROTOCOL, {"tag": tag}, await_response=True
                )
                if resp and resp.get("ok"):
                    payload = resp["payload"]
                    if k1util.verify_bytes(
                        self.pubkeys[sender],
                        self._digest(tag, sender, payload),
                        bytes.fromhex(resp["sig"]),
                    ):
                        return payload
                    raise DkgError(
                        f"bad signature on {tag!r} from operator {sender}"
                    )
            except DkgError:
                raise
            except Exception:
                pass  # peer not up yet / payload not published yet
            if asyncio.get_running_loop().time() > deadline:
                raise DkgError(f"timeout pulling {tag!r} from peer {peer}")
            await asyncio.sleep(self.poll_interval)

    async def gather(self, tag: str, payload) -> dict[int, object]:
        """Publish ours, pull everyone else's. Returns {0-based idx: payload}."""
        self.publish(tag, payload)
        peers = sorted(self.node.peers)
        others = await asyncio.gather(
            *(self._pull(p, tag) for p in peers)
        )
        out = {self.idx: payload}
        out.update(dict(zip(peers, others)))
        return out

    # -- sync protocol (ref: dkg/sync/client.go:31-60) ---------------------

    async def sync(self) -> None:
        """Block until all n peers are reachable and agree on the
        definition hash + DKG version."""
        payload = {"version": DKG_VERSION, "def_hash": self.def_hash.hex()}
        got = await self.gather("sync", payload)
        for idx, p in got.items():
            if p.get("version") != DKG_VERSION:
                raise DkgError(
                    f"operator {idx} runs incompatible version {p.get('version')}"
                )
            if p.get("def_hash") != self.def_hash.hex():
                raise DkgError(f"operator {idx} has a different definition")


class TcpFrostPort:
    """frost.run_frost_parallel transport over TcpDkgTransport
    (ref: dkg/frostp2p.go fTransport)."""

    def __init__(self, tx: TcpDkgTransport) -> None:
        self.tx = tx

    async def round1(self, broadcasts, shares):
        tx = self.tx
        # publish per-recipient private share vectors first so peers'
        # pulls can succeed as soon as they reach us
        for share_idx_1b, sh in shares.items():
            to0 = share_idx_1b - 1
            if to0 != tx.idx:
                # THE sealed share channel: served only to its addressee
                # (_on_req private_to gate) over the per-frame AES-GCM
                # transport, mirroring the reference's private libp2p
                # share streams (frostp2p.go)
                # lint: allow(secret-flow)
                tx.publish(f"frost-r1-shares:{to0}", sh, private_to=to0)
        all_b = await tx.gather("frost-r1-bcast", list(broadcasts))
        my_shares = {tx.idx + 1: shares[tx.idx + 1]}
        pulled = await asyncio.gather(
            *(
                tx._pull(p, f"frost-r1-shares:{tx.idx}")
                for p in sorted(tx.node.peers)
            )
        )
        for p, sh in zip(sorted(tx.node.peers), pulled):
            my_shares[p + 1] = sh
        all_bcasts = {
            idx + 1: list(blist) for idx, blist in all_b.items()
        }
        return all_bcasts, my_shares


class TcpExchangePort:
    """ceremony.run_dkg exchange transport (ref: dkg/exchanger.go)."""

    def __init__(self, tx: TcpDkgTransport) -> None:
        self.tx = tx

    async def exchange(self, tag: str, payload) -> dict[int, object]:
        return await self.tx.gather(f"x:{tag}", payload)


async def run_networked_dkg(
    defn,
    node_idx: int,
    k1_privkey,
    peer_addrs: list[tuple[str, int]],
    data_dir=None,
    engine=None,
    timeout: float = 120.0,
):
    """Full networked ceremony: mesh up -> sync -> FROST -> lock
    (ref: dkg/dkg.go:82 Run). peer_addrs: (host, port) per operator in
    index order. Returns ceremony.DKGResult."""
    from charon_tpu.dkg.ceremony import run_dkg
    from charon_tpu.p2p.transport import PeerSpec

    pubkeys = [
        enr.pubkey_from_string(op.enr) for op in defn.operators
    ]
    # refuse to run a ceremony for a definition the operators didn't sign
    defn.verify_signatures(pubkeys)

    specs = [
        PeerSpec(index=i, pubkey=pubkeys[i], host=h, port=p)
        for i, (h, p) in enumerate(peer_addrs)
    ]
    node = P2PNode(node_idx, k1_privkey, specs, defn.definition_hash())
    await node.start()
    try:
        tx = TcpDkgTransport(node, defn, k1_privkey, timeout=timeout)
        await tx.sync()
        return await run_dkg(
            defn,
            node_idx,
            k1_privkey,
            TcpFrostPort(tx),
            TcpExchangePort(tx),
            engine=engine,
            data_dir=data_dir,
        )
    finally:
        await node.stop()
