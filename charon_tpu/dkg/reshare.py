"""Batched key resharing: operator join/leave, threshold change, and
proactive rotation over many validators at once (ISSUE 20).

Protocol (Desmedt–Jajodia resharing, the standard re-randomization of a
Shamir sharing without reconstructing the secret): each DEALER i from
the old operator set takes its live share s_i and, per validator, deals
a fresh degree-(t_new - 1) polynomial g_i with g_i(0) = s_i — Feldman
commitments D_ik = [g_ik] G broadcast, sub-shares g_i(j) sent privately
to each new node j. Each RECEIVER j then

  1. binds every dealer's commitment vector to the LIVE key:
     D_i0 must equal dealer i's existing pubshare (so a dealer cannot
     reshare a different secret), and sum_i lambda_i D_i0 must equal
     the group pubkey (the group key is provably unchanged);
  2. verifies its sub-shares against the commitments:
     [g_i(j)] G == sum_k D_ik j^k — the commitment_eval_batch /
     g1_gen_mul_batch device kernels, the same program family the FROST
     ceremony uses;
  3. re-interpolates: new share s'_j = sum_i lambda_i g_i(j) where
     lambda are the Lagrange coefficients AT ZERO over the dealer index
     set (host-side — sub-shares are secrets);
  4. derives every new node's pubshare without any secret:
     P'_m = sum_{i,k} (lambda_i m^k) D_ik — one segmented Pippenger MSM
     over all (validator, m) segments (blsops.g1_msm_batch), with the
     m = 0 segment doubling as the group-key consistency check.

Old shares keep satisfying the OLD polynomial — unusability of stale
shares is enforced at the cluster layer: the rotated pubshare registry
makes sigagg/Eth2Verifier reject partials signed with pre-reshare
shares (tests/test_reshare_scenarios.py proves this end to end).

Secret material (old shares, dealt polynomials, sub-shares, new shares)
never leaves the host; only commitments and derived public shares ride
the device. Abort semantics: ANY verification failure raises
ReshareError before any output is assembled — there is no partial
success, and disk output (write_reshare_outputs) stages into a temp
directory and renames, so a crash mid-ceremony leaves the old key
state untouched.
"""

from __future__ import annotations

import asyncio
import secrets as _secrets
from dataclasses import dataclass, field

from charon_tpu.crypto.fields import R
from charon_tpu.crypto.g1g2 import (
    G1_GEN,
    g1_add,
    g1_in_subgroup,
    g1_is_on_curve,
    g1_mul,
)
from charon_tpu.crypto.shamir import lagrange_coeffs_at_zero


class ReshareError(Exception):
    """Typed failure for any reshare abort (verification, transport,
    parameter). Carries NO secret material by construction — messages
    name peers/validators, never share values."""


@dataclass(frozen=True)
class ReshareConfig:
    """Public parameters of one resharing ceremony.

    old_indices/new_indices are 1-based Shamir x-coordinates; overlap is
    allowed and is the common case (join/leave/rotate keep most nodes).
    Dealers are the old nodes that participate; any subset of
    old_indices of size >= t_old re-shares the same key."""

    old_indices: tuple
    new_indices: tuple
    t_old: int
    t_new: int
    num_validators: int
    ctx: bytes = b""

    def __post_init__(self):
        old = tuple(self.old_indices)
        new = tuple(self.new_indices)
        if len(set(old)) != len(old) or len(set(new)) != len(new):
            raise ReshareError("duplicate share indices")
        if any(i < 1 for i in old + new):
            raise ReshareError("share indices are 1-based")
        if not 1 < self.t_old <= len(old):
            raise ReshareError("bad old threshold")
        if not 1 < self.t_new <= len(new):
            raise ReshareError("bad new threshold")
        if self.num_validators < 1:
            raise ReshareError("need at least one validator")


@dataclass(frozen=True)
class ReshareBroadcast:
    """Per (dealer, validator): Feldman commitments to the dealt
    polynomial — t_new G1 points, D_0 = [old share] G (the dealer's
    live pubshare)."""

    commitments: tuple


@dataclass(frozen=True)
class ReshareShares:
    """Secret sub-shares g_i(j) a dealer sends one recipient, one per
    validator ceremony. MUST travel an authenticated private channel.

    repr=False: the auto-repr would dump raw sub-share scalars into any
    log line or traceback that formats the object (secret-flow lint)."""

    shares: tuple = field(repr=False)  # num_validators scalars


@dataclass(frozen=True)
class ReshareResult:
    """One validator's post-reshare state for the local node."""

    group_pubkey: object  # G1 affine — UNCHANGED by the reshare
    # repr=False: a formatted result names the ceremony, never the
    # long-lived secret share (secret-flow lint)
    secret_share: int = field(repr=False)
    pubshares: dict  # new share idx -> G1 affine pubshare


class ReshareDealer:
    """Dealer side: re-shares this node's live shares to the new set."""

    def __init__(self, idx: int, cfg: ReshareConfig, share_secrets, rand=None):
        if idx not in cfg.old_indices:
            raise ReshareError(f"dealer index {idx} not in the old set")
        if len(share_secrets) != cfg.num_validators:
            raise ReshareError("one old share per validator required")
        self.idx = idx
        self.cfg = cfg
        randfn = rand or (lambda: _secrets.randbelow(R - 1) + 1)
        # per validator: fresh polynomial with g(0) = the live old share
        self._polys = [
            [int(s) % R] + [randfn() for _ in range(cfg.t_new - 1)]
            for s in share_secrets
        ]

    def round1(self):
        """-> (per-validator ReshareBroadcast, {new idx: ReshareShares})."""
        broadcasts = [
            ReshareBroadcast(
                commitments=tuple(g1_mul(G1_GEN, c) for c in poly)
            )
            for poly in self._polys
        ]
        shares = {
            j: ReshareShares(
                shares=tuple(_poly_eval(poly, j) for poly in self._polys)
            )
            for j in self.cfg.new_indices
        }
        return broadcasts, shares


def _poly_eval(poly, x: int) -> int:
    acc = 0
    for c in reversed(poly):
        acc = (acc * x + c) % R
    return acc


class ReshareReceiver:
    """Receiver side: verifies dealt material and derives the new share
    + the full new pubshare map for every validator."""

    def __init__(self, idx: int, cfg: ReshareConfig):
        if idx not in cfg.new_indices:
            raise ReshareError(f"receiver index {idx} not in the new set")
        self.idx = idx
        self.cfg = cfg

    # -- structural + binding checks (host, cheap) -----------------------

    def _check_structure(self, broadcasts, old_pubshares, group_pubkeys):
        cfg = self.cfg
        dealers = sorted(broadcasts)
        if len(dealers) < cfg.t_old:
            raise ReshareError(
                f"{len(dealers)} dealers < old threshold {cfg.t_old}"
            )
        if not set(dealers) <= set(cfg.old_indices):
            raise ReshareError("dealer outside the old operator set")
        for i in dealers:
            blist = broadcasts[i]
            if len(blist) != cfg.num_validators:
                raise ReshareError(
                    f"dealer {i}: {len(blist)} ceremonies, want "
                    f"{cfg.num_validators}"
                )
            for v, b in enumerate(blist):
                if len(b.commitments) != cfg.t_new:
                    raise ReshareError(
                        f"dealer {i} validator {v}: "
                        f"{len(b.commitments)} commitments, want "
                        f"t_new={cfg.t_new}"
                    )
                for pt in b.commitments:
                    if pt is None or not (
                        g1_is_on_curve(pt) and g1_in_subgroup(pt)
                    ):
                        raise ReshareError(
                            f"dealer {i} validator {v}: commitment "
                            "not in G1"
                        )
                # the binding that makes resharing ≠ a fresh DKG: the
                # constant term must be the dealer's LIVE pubshare
                if b.commitments[0] != old_pubshares[v].get(i):
                    raise ReshareError(
                        f"dealer {i} validator {v}: commitment does "
                        "not bind to the live pubshare"
                    )
        if len(group_pubkeys) != cfg.num_validators:
            raise ReshareError("one group pubkey per validator required")
        return dealers

    # -- round 2 ---------------------------------------------------------

    def round2(
        self,
        broadcasts: dict,
        my_shares: dict,
        old_pubshares,
        group_pubkeys,
        engine=None,
        metrics=None,
    ):
        """broadcasts: dealer idx -> per-validator ReshareBroadcast;
        my_shares: dealer idx -> ReshareShares addressed to us;
        old_pubshares: per validator {old idx: G1 affine};
        group_pubkeys: per validator G1 affine.
        Returns per-validator ReshareResult."""
        cfg = self.cfg
        dealers = self._check_structure(
            broadcasts, old_pubshares, group_pubkeys
        )
        if sorted(my_shares) != dealers:
            raise ReshareError("sub-share set does not match dealer set")
        for i in dealers:
            sh = my_shares[i].shares
            if len(sh) != cfg.num_validators or not all(
                isinstance(s, int) and 0 <= s < R for s in sh
            ):
                raise ReshareError(f"dealer {i}: malformed sub-shares")

        self._verify_subshares(broadcasts, my_shares, dealers, engine, metrics)

        # Lagrange at zero over the dealer set: public coefficients.
        lam = lagrange_coeffs_at_zero(dealers)

        pubshare_rows = self._derive_pubshares(
            broadcasts, dealers, lam, group_pubkeys, engine, metrics
        )

        results = []
        for v in range(cfg.num_validators):
            # host-side: sub-shares are secrets
            new_share = 0
            for i in dealers:
                new_share = (
                    new_share + lam[i] * my_shares[i].shares[v]
                ) % R
            results.append(
                ReshareResult(
                    group_pubkey=group_pubkeys[v],
                    secret_share=new_share,
                    pubshares=pubshare_rows[v],
                )
            )

        # self-consistency: our derived pubshare must be [new share] G
        if engine is not None:
            mine = engine.g1_gen_mul_batch(
                [r.secret_share for r in results]
            )
        else:
            mine = [g1_mul(G1_GEN, r.secret_share) for r in results]
        for v, (r, m) in enumerate(zip(results, mine)):
            if r.pubshares[self.idx] != m:
                raise ReshareError(
                    f"validator {v}: derived share does not match the "
                    "derived pubshare"
                )
        return results

    def _verify_subshares(self, broadcasts, my_shares, dealers, engine, metrics):
        """[g_i(j)] G == sum_k D_ik j^k per (dealer, validator)."""
        cfg = self.cfg
        tasks = []  # (i, v, sub-share)
        for i in dealers:
            for v in range(cfg.num_validators):
                tasks.append((i, v, my_shares[i].shares[v]))
        if engine is not None:
            lhs = engine.g1_gen_mul_batch([s for (_, _, s) in tasks])
            rhs = engine.commitment_eval_batch(
                [broadcasts[i][v].commitments for (i, v, _) in tasks],
                [self.idx] * len(tasks),
                cfg.t_new,
            )
            path = "device"
        else:
            lhs = [g1_mul(G1_GEN, s) for (_, _, s) in tasks]
            rhs = []
            for i, v, _ in tasks:
                acc = None
                xpow = 1
                for c in broadcasts[i][v].commitments:
                    acc = g1_add(acc, g1_mul(c, xpow))
                    xpow = xpow * self.idx % R
                rhs.append(acc)
            path = "host"
        if metrics is not None:
            metrics.observe_dkg_verify("reshare_share", path, len(tasks))
        for (i, v, _), l, r in zip(tasks, lhs, rhs):
            if l != r:
                raise ReshareError(
                    f"invalid sub-share from dealer {i} (validator {v})"
                )

    def _derive_pubshares(
        self, broadcasts, dealers, lam, group_pubkeys, engine, metrics
    ):
        """P'_m = sum_{i,k} (lambda_i m^k) D_ik for every new node m,
        plus the m = 0 segment == group pubkey consistency check.

        Device path: ONE segmented Pippenger MSM over all
        (validator, m) segments — q*t_new points each, full-width
        combined scalars lambda_i * m^k mod r (all public)."""
        cfg = self.cfg
        evals = [0] + list(cfg.new_indices)  # m = 0 first: group-key check
        if engine is not None:
            points, scalars, seg_ids = [], [], []
            seg = 0
            for v in range(cfg.num_validators):
                for m in evals:
                    for i in dealers:
                        xpow = 1
                        for c in broadcasts[i][v].commitments:
                            points.append(c)
                            scalars.append(lam[i] * xpow % R)
                            xpow = xpow * m % R
                    seg += 1
                    seg_ids.extend(
                        [seg - 1] * (len(dealers) * cfg.t_new)
                    )
            out = engine.g1_msm_batch(
                points, scalars, seg_ids, seg
            )
            if metrics is not None:
                metrics.observe_dkg_verify(
                    "reshare_pubshare", "device", len(points)
                )
            rows = []
            width = len(evals)
            for v in range(cfg.num_validators):
                lane = out[v * width : (v + 1) * width]
                if lane[0] != group_pubkeys[v]:
                    raise ReshareError(
                        f"validator {v}: resharing changed the group key"
                    )
                rows.append(dict(zip(cfg.new_indices, lane[1:])))
            return rows
        # host fallback: same math, sequential
        rows = []
        for v in range(cfg.num_validators):
            lane = {}
            for m in evals:
                acc = None
                for i in dealers:
                    xpow = 1
                    for c in broadcasts[i][v].commitments:
                        acc = g1_add(acc, g1_mul(c, lam[i] * xpow % R))
                        xpow = xpow * m % R
                lane[m] = acc
            if metrics is not None:
                metrics.observe_dkg_verify(
                    "reshare_pubshare",
                    "host",
                    len(evals) * len(dealers) * cfg.t_new,
                )
            if lane[0] != group_pubkeys[v]:
                raise ReshareError(
                    f"validator {v}: resharing changed the group key"
                )
            rows.append({m: lane[m] for m in cfg.new_indices})
        return rows


# ---------------------------------------------------------------------------
# Lockstep driver + in-memory transport (tests/simnet/CLI)
# ---------------------------------------------------------------------------


async def run_reshare_parallel(
    transport,
    idx: int,
    cfg: ReshareConfig,
    old_pubshares,
    group_pubkeys,
    share_secrets=None,
    engine=None,
    metrics=None,
):
    """One node's side of the resharing ceremony.

    A node acts as dealer (it holds old shares: `share_secrets` given),
    receiver (idx in cfg.new_indices), or both — the overlap case.
    transport duck-type: round1(broadcasts, shares_by_peer) ->
    (all_broadcasts, my_shares); a leaving node passes through round1
    and returns [] (it receives nothing).
    """
    dealer = (
        ReshareDealer(idx, cfg, share_secrets)
        if share_secrets is not None
        else None
    )
    broadcasts, shares = dealer.round1() if dealer else ([], {})
    all_bcasts, my_shares = await transport.round1(broadcasts, shares)
    if idx not in cfg.new_indices:
        return []  # leaving node: dealt and is done
    receiver = ReshareReceiver(idx, cfg)
    return receiver.round2(
        all_bcasts,
        my_shares,
        old_pubshares,
        group_pubkeys,
        engine=engine,
        metrics=metrics,
    )


class MemReshareTransport:
    """In-memory lockstep transport: `dealer_indices` publish, every
    new-set node collects. `timeout` bounds the barrier wait so a
    crashed peer aborts the ceremony cleanly (ReshareError) instead of
    hanging it; `crash` simulates a dealer dying before publishing."""

    def __init__(self, dealer_indices, timeout: float = 30.0, crash=()):
        self.dealers = tuple(sorted(dealer_indices))
        self.timeout = timeout
        self.crash = frozenset(crash)
        self._bcasts: dict[int, list] = {}
        self._shares: dict[int, dict] = {}
        self._done = asyncio.Event()

    def participant(self, idx: int) -> "_MemResharePort":
        return _MemResharePort(self, idx)


class _MemResharePort:
    def __init__(self, net: MemReshareTransport, idx: int):
        self.net = net
        self.idx = idx

    async def round1(self, broadcasts, shares):
        net = self.net
        if self.idx in net.crash:
            raise ReshareError(f"peer {self.idx} crashed mid-reshare")
        if broadcasts:
            net._bcasts[self.idx] = broadcasts
            net._shares[self.idx] = shares
        live = [d for d in net.dealers if d not in net.crash]
        if set(net._bcasts) >= set(live):
            net._done.set()
        try:
            await asyncio.wait_for(net._done.wait(), net.timeout)
        except asyncio.TimeoutError:
            missing = sorted(set(net.dealers) - set(net._bcasts))
            raise ReshareError(
                f"reshare round 1 timed out waiting for dealers {missing}"
            ) from None
        if set(net._bcasts) != set(net.dealers):
            missing = sorted(set(net.dealers) - set(net._bcasts))
            raise ReshareError(
                f"dealers {missing} never published — aborting"
            )
        my_shares = {
            i: net._shares[i][self.idx]
            for i in net._shares
            if self.idx in net._shares[i]
        }
        return dict(net._bcasts), my_shares


# ---------------------------------------------------------------------------
# Atomic disk handoff
# ---------------------------------------------------------------------------


def write_reshare_outputs(data_dir, results, pubshare_hexes=None):
    """Persist post-reshare keystores with NO torn intermediate state:
    everything stages into a sibling temp directory, then one rename
    swaps it in (the old validator_keys stays intact until the swap).
    Returns the path of the replaced (stale) key directory so callers
    can archive or shred it."""
    import os
    from pathlib import Path

    from charon_tpu.eth2util import keystore

    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    share_secrets = [
        (r.secret_share % (1 << 256)).to_bytes(32, "big") for r in results
    ]
    stage = data_dir / f".reshare-stage-{os.getpid()}"
    if stage.exists():
        import shutil

        shutil.rmtree(stage)
    # keystore I/O IS the reshare's output: shares leave only as
    # EIP-2335-encrypted keystores
    # lint: allow(secret-flow)
    keystore.store_keys(share_secrets, stage, pubkeys=pubshare_hexes)
    final = data_dir / "validator_keys"
    stale = data_dir / "validator_keys.pre-reshare"
    if stale.exists():
        import shutil

        shutil.rmtree(stale)
    if final.exists():
        os.replace(final, stale)
    os.replace(stage, final)
    return stale if stale.exists() else None
