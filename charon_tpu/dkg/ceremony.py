"""DKG ceremony orchestration: definition -> FROST -> signed lock + keys.

Mirrors ref: dkg/dkg.go:82-200 — load + verify the signed definition, run
the sync protocol, execute FROST, exchange partial signatures over the
lock hash (ref: dkg/exchanger.go, sigTypes dkg.go:190-194), aggregate +
verify, emit cluster-lock.json + EIP-2335 keystores + per-node k1
signatures (ref: dkg/nodesigs.go, outputs dkg/disk.go).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass, field
from pathlib import Path

from charon_tpu import tbls
from charon_tpu.app import k1util
from charon_tpu.cluster.definition import ClusterDefinition
from charon_tpu.cluster.lock import ClusterLock, DistributedValidator
from charon_tpu.crypto.g1g2 import g1_to_bytes
from charon_tpu.dkg import frost
from charon_tpu.eth2util import keystore


@dataclass
class DKGResult:
    lock: ClusterLock
    # repr=False: the auto-repr would dump every validator's share key
    # into any log/traceback formatting the result (secret-flow lint)
    share_secrets: list[bytes] = field(repr=False)  # per validator (32B)
    deposits: list = None  # eth2util.deposit.DepositData per validator


class MemExchangeNet:
    """Lockstep all-to-all exchange rounds keyed by tag (in-process DKG;
    the TCP ceremony uses the p2p mesh with the same interface)."""

    def __init__(self, n: int) -> None:
        self.n = n
        self._rounds: dict[str, dict[int, object]] = {}
        self._events: dict[str, asyncio.Event] = {}

    def port(self, idx: int) -> "_Port":
        return _Port(self, idx)


class _Port:
    def __init__(self, net: MemExchangeNet, idx: int) -> None:
        self.net = net
        self.idx = idx

    async def exchange(self, tag: str, payload) -> dict[int, object]:
        net = self.net
        rnd = net._rounds.setdefault(tag, {})
        ev = net._events.setdefault(tag, asyncio.Event())
        rnd[self.idx] = payload
        if len(rnd) == net.n:
            ev.set()
        await ev.wait()
        return dict(rnd)


async def run_dkg(
    defn: ClusterDefinition,
    node_idx: int,  # 0-based operator index
    k1_privkey,
    frost_port,
    exchange_port,
    engine=None,
    data_dir: str | Path | None = None,
) -> DKGResult:
    """One node's side of the ceremony."""
    n = len(defn.operators)
    t = defn.threshold
    v = defn.num_validators
    share_idx = node_idx + 1  # 1-based

    # 1. FROST: parallel ceremonies over two transport rounds
    # (ceremony context binds to the definition, ref: dkg.go def hash use).
    ctx = defn.definition_hash()
    results = await frost.run_frost_parallel(
        frost_port, share_idx, n, t, v, ctx, engine=engine
    )

    # 2. Build the (to-be-sealed) validator entries. Deposits and builder
    # registrations are signed and patched in BEFORE the lock hash is
    # computed — the lock hash covers them (ref: dkg.go runs the
    # exchanger's deposit/registration sig rounds first and the lock-hash
    # sig round last, dkg.go:190-194).
    validators = tuple(
        DistributedValidator(
            distributed_public_key="0x" + g1_to_bytes(r.group_pubkey).hex(),
            public_shares=tuple(
                "0x" + g1_to_bytes(r.pubshares[j]).hex()
                for j in range(1, n + 1)
            ),
        )
        for r in results
    )
    share_secrets = [
        (r.secret_share % (1 << 256)).to_bytes(32, "big") for r in results
    ]

    # 2b. Deposit data: threshold-sign each validator's deposit message
    # (ref: dkg/exchanger.go sigDepositData — partials exchanged and
    # aggregated exactly like the lock signature).
    from charon_tpu.eth2util import deposit as dep

    fork_version = bytes.fromhex(defn.fork_version[2:])
    deposit_msgs = [
        dep.DepositMessage(
            pubkey=bytes.fromhex(dv.distributed_public_key[2:]),
            withdrawal_credentials=dep.withdrawal_credentials_bls(
                bytes.fromhex(dv.distributed_public_key[2:])
            ),
            amount=dep.DEFAULT_AMOUNT_GWEI,
        )
        for dv in validators
    ]
    deposit_roots = [
        dep.signing_root(m, fork_version) for m in deposit_msgs
    ]
    my_dep_partials = [
        tbls.sign(share_secrets[i], deposit_roots[i]) for i in range(v)
    ]
    all_dep = await exchange_port.exchange(
        "deposit-sig", [s.hex() for s in my_dep_partials]
    )
    deposit_sigs = tbls.threshold_aggregate_batch(
        [
            {
                peer + 1: bytes.fromhex(all_dep[peer][i])
                for peer in sorted(all_dep)
            }
            for i in range(v)
        ]
    )
    deposits = []
    for msg, sig, root, dv in zip(
        deposit_msgs, deposit_sigs, deposit_roots, validators
    ):
        tbls.verify(
            bytes.fromhex(dv.distributed_public_key[2:]), root, sig
        )
        deposits.append(
            dep.DepositData(
                pubkey=msg.pubkey,
                withdrawal_credentials=msg.withdrawal_credentials,
                amount=msg.amount,
                signature=sig,
            )
        )

    import json as _json
    from dataclasses import replace as _replace

    validators = tuple(
        _replace(
            dv,
            deposit_data=_json.loads(
                dep.deposit_data_json([d], fork_version, defn.name)
            )[0],
        )
        for dv, d in zip(validators, deposits)
    )

    # 2c. Pre-generated builder registrations: threshold-sign a default
    # ValidatorRegistration per validator so the node can re-broadcast
    # them every epoch without a VC (ref: dkg.go:190-194 sigTypes include
    # registrations; core/bcast/recast.go consumes them from the lock).
    from charon_tpu.eth2util import network as networks
    from charon_tpu.eth2util import registration as regmod
    from charon_tpu.eth2util.signing import ForkInfo as _ForkInfo

    fee_recipient = bytes(20)
    if getattr(defn, "fee_recipient_address", ""):
        raw = defn.fee_recipient_address
        fee_recipient = bytes.fromhex(raw[2:] if raw.startswith("0x") else raw)
    reg_fork = _ForkInfo(
        genesis_validators_root=bytes(32),
        fork_version=fork_version,
        genesis_fork_version=fork_version,
    )
    reg_msgs = [
        regmod.ValidatorRegistration(
            fee_recipient=fee_recipient,
            gas_limit=regmod.DEFAULT_GAS_LIMIT,
            timestamp=networks.genesis_time(fork_version, default=0),
            pubkey=bytes.fromhex(dv.distributed_public_key[2:]),
        )
        for dv in validators
    ]
    reg_roots = [regmod.signing_root(m, reg_fork) for m in reg_msgs]
    my_reg_partials = [
        tbls.sign(share_secrets[i], reg_roots[i]) for i in range(v)
    ]
    all_reg = await exchange_port.exchange(
        "registration-sig", [s.hex() for s in my_reg_partials]
    )
    reg_sigs = tbls.threshold_aggregate_batch(
        [
            {
                peer + 1: bytes.fromhex(all_reg[peer][i])
                for peer in sorted(all_reg)
            }
            for i in range(v)
        ]
    )
    patched = []
    for dv, msg, sig, root in zip(validators, reg_msgs, reg_sigs, reg_roots):
        tbls.verify(
            bytes.fromhex(dv.distributed_public_key[2:]), root, sig
        )
        patched.append(
            _replace(
                dv, builder_registration=regmod.to_lock_json(msg, sig)
            )
        )
    validators = tuple(patched)

    # 3. The lock hash seals everything above. Exchange partial
    # signatures over it: every node signs with each validator's share
    # key (ref: dkg/exchanger.go sigLock — the LAST sig round).
    lock_hash = ClusterLock(definition=defn, validators=validators).lock_hash()
    my_partials = [
        tbls.sign(share_secrets[i], lock_hash) for i in range(v)
    ]
    all_partials = await exchange_port.exchange(
        "lock-sig", [s.hex() for s in my_partials]
    )

    # 4. Threshold-aggregate each validator's group signature, then
    # BLS-aggregate across validators (ref: lock signature_aggregate).
    group_sigs = tbls.threshold_aggregate_batch(
        [
            {
                peer + 1: bytes.fromhex(all_partials[peer][i])
                for peer in sorted(all_partials)
            }
            for i in range(v)
        ]
    )
    sig_agg = tbls.aggregate(group_sigs)
    tbls.verify_aggregate(
        [bytes.fromhex(dv.distributed_public_key[2:]) for dv in validators],
        lock_hash,
        sig_agg,
    )

    # 5. Per-node k1 signatures over the lock hash
    # (ref: dkg/nodesigs.go via the reliable-broadcast component).
    my_node_sig = k1util.sign(k1_privkey, lock_hash)
    all_node_sigs = await exchange_port.exchange(
        "node-sig", my_node_sig.hex()
    )
    lock = ClusterLock(
        definition=defn,
        validators=validators,
        signature_aggregate="0x" + sig_agg.hex(),
        node_signatures=tuple(
            all_node_sigs[i] for i in sorted(all_node_sigs)
        ),
    )

    # 6. Outputs (ref: dkg/disk.go — lock, keystores, passwords,
    # deposit-data.json).
    if data_dir is not None:
        data_dir = Path(data_dir)
        data_dir.mkdir(parents=True, exist_ok=True)
        lock.save(str(data_dir / "cluster-lock.json"))
        # keystore I/O IS the ceremony's output: shares leave only as
        # EIP-2335-encrypted keystores
        # lint: allow(secret-flow)
        keystore.store_keys(
            share_secrets,
            data_dir / "validator_keys",
            pubkeys=[
                dv.public_shares[node_idx] for dv in validators
            ],
        )
        (data_dir / "deposit-data.json").write_text(
            dep.deposit_data_json(deposits, fork_version, defn.name)
        )
    return DKGResult(
        lock=lock, share_secrets=share_secrets, deposits=deposits
    )
