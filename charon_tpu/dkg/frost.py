"""FROST distributed key generation (2-round Pedersen DKG with proofs of
knowledge, per Komlo–Goldberg 2020), batched across validators.

Mirrors ref: dkg/frost.go — `numValidators` ceremonies advance in lockstep
sharing two transport rounds (frost.go:50-85): round 1 broadcasts
polynomial commitments + a Schnorr proof of knowledge of the constant term
and sends Shamir shares peer-to-peer; round 2 verifies everything and
yields (group pubkey, secret share, public shares) per validator
(frost.go:115-246).

TPU-first redesign: the O(num_validators * n * t) commitment-evaluation
scalar-muls that dominate verification run as batched device kernels
(charon_tpu/ops/blsops.py g1_scalar_mul_batch) instead of the reference's
sequential kryptology calls. Secret material (polynomials, shares) never
leaves the host.

Groups follow eth2 BLS: secrets/shares in Fr, commitments in G1 (pubkeys).
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass, field
from typing import Sequence

from charon_tpu.crypto.fields import R
from charon_tpu.crypto.g1g2 import G1_GEN, g1_add, g1_mul, g1_to_bytes

# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Round1Broadcast:
    """Per (participant, validator): commitments + proof of knowledge."""

    commitments: tuple  # t G1 points (affine int tuples)
    pok_r: tuple  # G1 point (Schnorr commitment)
    pok_mu: int  # response scalar


@dataclass(frozen=True)
class Round1Shares:
    """Secret Shamir shares f_i(j) this participant sends to peer j,
    one per validator ceremony. MUST go over an authenticated private
    channel (the reference sends them via libp2p streams, frostp2p.go).

    repr=False: the auto-repr would dump raw share scalars into any log
    line, traceback, or asyncio "Task exception was never retrieved"
    report that formats the object (secret-flow lint finding)."""

    shares: tuple = field(repr=False)  # num_validators scalars


@dataclass(frozen=True)
class FrostResult:
    group_pubkey: object  # G1 affine
    # repr=False: a formatted FrostResult must show WHICH ceremony it
    # is, never the long-lived secret share (secret-flow lint finding)
    secret_share: int = field(repr=False)  # this node's share
    pubshares: dict  # share_idx -> G1 affine pubshare


def _pok_challenge(ctx: bytes, idx: int, a0_commit, pok_r) -> int:
    h = hashlib.sha256(
        b"charon-tpu-frost-pok"
        + ctx
        + idx.to_bytes(4, "big")
        + g1_to_bytes(a0_commit)
        + g1_to_bytes(pok_r)
    ).digest()
    return int.from_bytes(h, "big") % R


# ---------------------------------------------------------------------------
# Participant state machine
# ---------------------------------------------------------------------------


class FrostParticipant:
    """One node's side of `num_validators` parallel ceremonies.

    idx is 1-based (Shamir x-coordinate), matching the cluster convention
    (ref: tbls share IDs are 1-indexed)."""

    def __init__(
        self,
        idx: int,
        n: int,
        t: int,
        num_validators: int,
        ctx: bytes,
        rand=None,
    ) -> None:
        if not 1 <= idx <= n or not 1 < t <= n:
            raise ValueError("bad frost parameters")
        self.idx = idx
        self.n = n
        self.t = t
        self.v = num_validators
        self.ctx = ctx
        randfn = rand or (lambda: secrets.randbelow(R - 1) + 1)
        # per validator: secret polynomial coefficients
        self._polys = [
            [randfn() for _ in range(t)] for _ in range(num_validators)
        ]

    # -- round 1 ----------------------------------------------------------

    def round1(self) -> tuple[list[Round1Broadcast], dict[int, Round1Shares]]:
        """Returns (per-validator broadcast, per-peer secret shares)."""
        broadcasts = []
        for poly in self._polys:
            commits = tuple(g1_mul(G1_GEN, c) for c in poly)
            k = secrets.randbelow(R - 1) + 1
            pok_r = g1_mul(G1_GEN, k)
            c = _pok_challenge(self.ctx, self.idx, commits[0], pok_r)
            mu = (k + poly[0] * c) % R
            broadcasts.append(
                Round1Broadcast(
                    commitments=commits, pok_r=pok_r, pok_mu=mu
                )
            )
        shares = {}
        for j in range(1, self.n + 1):
            shares[j] = Round1Shares(
                shares=tuple(self._eval(poly, j) for poly in self._polys)
            )
        return broadcasts, shares

    @staticmethod
    def _eval(poly: Sequence[int], x: int) -> int:
        acc = 0
        for c in reversed(poly):
            acc = (acc * x + c) % R
        return acc

    # -- round 2 ----------------------------------------------------------

    def round2(
        self,
        broadcasts: dict[int, list[Round1Broadcast]],
        my_shares: dict[int, Round1Shares],
        engine=None,
    ) -> list[FrostResult]:
        """Verify peers' proofs + shares and derive the outputs.

        broadcasts: peer idx -> per-validator Round1Broadcast (including
        our own); my_shares: peer idx -> shares addressed to us.
        engine: optional blsops.BlsEngine for batched device verification.
        """
        if set(broadcasts) != set(range(1, self.n + 1)):
            raise ValueError("missing round-1 broadcasts")
        if set(my_shares) != set(range(1, self.n + 1)):
            raise ValueError("missing round-1 shares")

        # Structural validation before any verification math (ADVICE round
        # 1): a wrong-length commitment vector would misalign the batched
        # share verification below, and a degree >= t polynomial from a
        # malicious peer would break the t-of-n threshold property.
        from charon_tpu.crypto.g1g2 import g1_in_subgroup, g1_is_on_curve

        for i, blist in broadcasts.items():
            if len(blist) != self.v:
                raise ValueError(
                    f"peer {i}: {len(blist)} ceremonies, want {self.v}"
                )
            for v, b in enumerate(blist):
                if len(b.commitments) != self.t:
                    raise ValueError(
                        f"peer {i} validator {v}: {len(b.commitments)} "
                        f"commitments, want t={self.t}"
                    )
                for pt in (*b.commitments, b.pok_r):
                    if pt is None or not (
                        g1_is_on_curve(pt) and g1_in_subgroup(pt)
                    ):
                        raise ValueError(
                            f"peer {i} validator {v}: commitment not in G1"
                        )
        for i, sh in my_shares.items():
            if len(sh.shares) != self.v or not all(
                isinstance(s, int) and 0 <= s < R for s in sh.shares
            ):
                raise ValueError(f"peer {i}: malformed share vector")

        self._verify_poks(broadcasts, engine)
        self._verify_shares(broadcasts, my_shares, engine)

        pubshare_rows = self._derive_pubshares(broadcasts, engine)
        results = []
        for v in range(self.v):
            group_pk = None
            secret_share = 0
            for i in range(1, self.n + 1):
                group_pk = g1_add(group_pk, broadcasts[i][v].commitments[0])
                secret_share = (
                    secret_share + my_shares[i].shares[v]
                ) % R
            results.append(
                FrostResult(
                    group_pubkey=group_pk,
                    secret_share=secret_share,
                    pubshares=pubshare_rows[v],
                )
            )
        return results

    def _derive_pubshares(self, broadcasts, engine) -> list[dict]:
        """Per validator: {j: pubshare_j} for every node j.

        Device path: ONE commitment_eval_batch over all (validator, j)
        lanes — each lane evaluates the n concatenated commitment
        vectors at x=j and sums them in-graph (sum_i sum_k C_ik j^k).
        The host path is the original sequential loop."""
        if engine is None:
            return [
                {
                    j: self._eval_commitments(broadcasts, v, j)
                    for j in range(1, self.n + 1)
                }
                for v in range(self.v)
            ]
        rows, xs = [], []
        for v in range(self.v):
            for j in range(1, self.n + 1):
                row: list = []
                for i in range(1, self.n + 1):
                    row.extend(broadcasts[i][v].commitments)
                rows.append(row)
                xs.append(j)
        evals = engine.commitment_eval_batch(rows, xs, self.t)
        out = []
        for v in range(self.v):
            base = v * self.n
            out.append(
                {j: evals[base + j - 1] for j in range(1, self.n + 1)}
            )
        return out

    def _eval_commitments(self, broadcasts, v: int, j: int):
        """Pubshare of node j for validator v: sum_i sum_k C_ik * j^k."""
        acc = None
        for i in range(1, self.n + 1):
            xpow = 1
            for c in broadcasts[i][v].commitments:
                acc = g1_add(acc, g1_mul(c, xpow))
                xpow = xpow * j % R
        return acc

    def _verify_poks(self, broadcasts, engine) -> None:
        """g*mu == R + A0*c for every (peer, validator)."""
        bases, scalars, rhs = [], [], []
        for i in range(1, self.n + 1):
            for v in range(self.v):
                b = broadcasts[i][v]
                c = _pok_challenge(self.ctx, i, b.commitments[0], b.pok_r)
                bases.append(b.commitments[0])
                scalars.append(c)
                rhs.append((i, v, b))
        if engine is not None:
            # fixed-base table kernel for the G1_GEN side (no doublings)
            lhs = engine.g1_gen_mul_batch([b.pok_mu for (_, _, b) in rhs])
            a0c = engine.g1_scalar_mul_batch(bases, scalars)
        else:
            lhs = [g1_mul(G1_GEN, b.pok_mu) for (_, _, b) in rhs]
            a0c = [g1_mul(base, c) for base, c in zip(bases, scalars)]
        for (i, v, b), l, ac in zip(rhs, lhs, a0c):
            if l != g1_add(b.pok_r, ac):
                raise ValueError(
                    f"invalid proof of knowledge from peer {i} (validator {v})"
                )

    def _verify_shares(self, broadcasts, my_shares, engine) -> None:
        """g*f_i(me) == sum_k C_ik * me^k for every (peer, validator).

        The commitment evaluations are the ceremony's compute bulk — the
        device path runs them as ONE commitment_eval_batch program (a
        shared Straus doubling chain per (peer, validator) lane) plus a
        fixed-base table mul for the g*share side. Share scalars ride
        the device only as packed limbs (they never leave this
        process); everything that comes back is a public curve point."""
        tasks = []  # (i, v, share)
        for i in range(1, self.n + 1):
            for v in range(self.v):
                tasks.append((i, v, my_shares[i].shares[v]))
        if engine is not None:
            lhs = engine.g1_gen_mul_batch([s for (_, _, s) in tasks])
            rhs = engine.commitment_eval_batch(
                [broadcasts[i][v].commitments for (i, v, _) in tasks],
                [self.idx] * len(tasks),
                self.t,
            )
            for (i, v, _), l, r in zip(tasks, lhs, rhs):
                if l != r:
                    raise ValueError(
                        f"invalid share from peer {i} (validator {v})"
                    )
            return
        muls_b, muls_s = [], []
        for i, v, _ in tasks:
            xpow = 1
            for c in broadcasts[i][v].commitments:
                muls_b.append(c)
                muls_s.append(xpow)
                xpow = xpow * self.idx % R
        lhs = [g1_mul(G1_GEN, s) for (_, _, s) in tasks]
        terms = [g1_mul(b, s) for b, s in zip(muls_b, muls_s)]
        k = self.t
        for n_task, (i, v, _) in enumerate(tasks):
            acc = None
            for term in terms[n_task * k : (n_task + 1) * k]:
                acc = g1_add(acc, term)
            if lhs[n_task] != acc:
                raise ValueError(
                    f"invalid share from peer {i} (validator {v})"
                )


# ---------------------------------------------------------------------------
# Lockstep driver (ref: dkg/frost.go:50 runFrostParallel)
# ---------------------------------------------------------------------------


async def run_frost_parallel(
    transport,
    idx: int,
    n: int,
    t: int,
    num_validators: int,
    ctx: bytes,
    engine=None,
) -> list[FrostResult]:
    """Two transport rounds for all validators' ceremonies.

    transport duck-type:
      round1(broadcasts, shares_by_peer) -> (all_broadcasts, my_shares)
        where all_broadcasts: idx -> list[Round1Broadcast] and
        my_shares: idx -> Round1Shares addressed to us.
    """
    part = FrostParticipant(idx, n, t, num_validators, ctx)
    broadcasts, shares = part.round1()
    all_bcasts, my_shares = await transport.round1(broadcasts, shares)
    return part.round2(all_bcasts, my_shares, engine=engine)


class MemFrostTransport:
    """In-memory lockstep transport for n participants (tests/simnet)."""

    def __init__(self, n: int) -> None:
        import asyncio

        self.n = n
        self._bcasts: dict[int, list] = {}
        self._shares: dict[int, dict[int, Round1Shares]] = {}
        self._done = asyncio.Event()

    def participant(self, idx: int) -> "_MemFrostPort":
        return _MemFrostPort(self, idx)


class _MemFrostPort:
    def __init__(self, net: MemFrostTransport, idx: int) -> None:
        self.net = net
        self.idx = idx

    async def round1(self, broadcasts, shares):
        net = self.net
        net._bcasts[self.idx] = broadcasts
        net._shares[self.idx] = shares
        if len(net._bcasts) == net.n:
            net._done.set()
        await net._done.wait()
        my_shares = {
            i: net._shares[i][self.idx] for i in net._shares
        }
        return dict(net._bcasts), my_shares
