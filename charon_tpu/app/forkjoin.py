"""Bounded fork/join fan-out (ref: app/forkjoin/forkjoin.go:3-19 — the
reference's generic worker-pool util, 8 workers by default, used for
parallel beacon-node queries).

asyncio redesign: a semaphore-bounded gather that preserves input order
and separates successes from failures instead of the reference's
channel-of-results."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Sequence

DEFAULT_WORKERS = 8


@dataclass
class Result:
    """One input's outcome: exactly one of `output` / `error` is set."""

    input: Any
    output: Any = None
    error: BaseException | None = None

    @property
    def ok(self) -> bool:
        return self.error is None


async def forkjoin(
    inputs: Sequence[Any],
    fn: Callable[[Any], Awaitable[Any]],
    workers: int = DEFAULT_WORKERS,
) -> list[Result]:
    """Apply `fn` to every input with at most `workers` concurrent calls;
    results come back in input order, failures captured per-input."""
    sem = asyncio.Semaphore(workers)

    async def one(x):
        async with sem:
            try:
                return Result(input=x, output=await fn(x))
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 — captured per input
                return Result(input=x, error=e)

    return list(await asyncio.gather(*(one(x) for x in inputs)))


def flatten(results: list[Result]) -> list[Any]:
    """Outputs of successful results; raises the FIRST failure if any
    (ref: forkjoin.Join's flatten helper semantics)."""
    for r in results:
        if not r.ok:
            raise r.error
    return [r.output for r in results]
