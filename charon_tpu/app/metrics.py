"""Prometheus metrics with mandatory cluster labels.

Mirrors ref: app/promauto — a registry whose metrics all carry
cluster-identifying labels (app/app.go:227-241), plus the monitoring
HTTP endpoints (/metrics, /readyz, /livez — app/monitoringapi.go:47-122).
"""

from __future__ import annotations

import asyncio
import json as _json
from dataclasses import dataclass

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)


@dataclass
class ClusterMetrics:
    """Registry with cluster_hash/cluster_name/peer labels applied to every
    series (ref: promauto.NewRegistry cluster labels)."""

    cluster_hash: str
    cluster_name: str
    peer: str

    def __post_init__(self) -> None:
        self.registry = CollectorRegistry()
        labels = ["cluster_hash", "cluster_name", "peer"]
        self._label_values = [self.cluster_hash, self.cluster_name, self.peer]

        def counter(name, doc, extra=()):
            c = Counter(name, doc, labels + list(extra), registry=self.registry)
            return c

        self.duty_total = counter(
            "core_scheduler_duty_total", "Duties scheduled", ["duty"]
        )
        self.consensus_decided = counter(
            "core_consensus_decided_total", "Consensus decisions", ["duty"]
        )
        self.parsig_received = counter(
            "core_parsigex_received_total", "Partial signatures received", ["duty"]
        )
        self.sigagg_total = counter(
            "core_sigagg_aggregated_total", "Aggregated signatures", ["duty"]
        )
        self.bcast_total = counter(
            "core_bcast_broadcast_total", "Broadcast duties", ["duty"]
        )
        self.tracker_failed = counter(
            "core_tracker_failed_duties_total", "Failed duties", ["duty", "step"]
        )
        self.tracker_inconsistent = counter(
            "core_tracker_inconsistent_parsigs_total",
            "Duties with inconsistent partial signatures by duty type "
            "(ref: core/tracker/metrics.go:85)",
            ["duty"],
        )
        self.tracker_unexpected = counter(
            "core_tracker_unexpected_events_total",
            "Partial signatures from peers for unscheduled validators",
            ["peer_share"],
        )
        self.tracker_participation = counter(
            "core_tracker_participation_total",
            "Per-peer duty participation (dedup'd by validator)",
            ["duty", "peer_share"],
        )
        self.tracker_failed_validators = counter(
            "core_tracker_failed_validators_total",
            "Per-validator signing failures (expected pubkeys whose "
            "partials never reached threshold), by duty type and reason",
            ["duty", "reason"],
        )
        self.inclusion_checked = counter(
            "core_tracker_inclusion_total",
            "On-chain inclusion results for broadcast duties "
            "(ref: core/tracker/inclusion.go inclusion metrics)",
            ["duty", "result"],
        )
        self.inclusion_delay = Gauge(
            "core_tracker_inclusion_delay_slots",
            "Most recent on-chain inclusion delay in slots",
            labels,
            registry=self.registry,
        )
        self.consensus_decided_rounds = Gauge(
            "core_consensus_decided_rounds",
            "Round the most recent consensus instance decided in, by "
            "duty type and round-timer strategy (ref: consensus metrics "
            "SetDecidedRounds)",
            labels + ["duty", "timer"],
            registry=self.registry,
        )
        self.consensus_duration = Gauge(
            "core_consensus_duration_seconds",
            "Wall seconds the most recent consensus instance took, by "
            "duty type and round-timer strategy (ref: consensus metrics "
            "ObserveConsensusDuration)",
            labels + ["duty", "timer"],
            registry=self.registry,
        )
        self.peer_ping = Gauge(
            "p2p_ping_success",
            "Peer ping success",
            labels + ["peer_index"],
            registry=self.registry,
        )
        self.bcast_delay = Histogram(
            "core_bcast_delay_seconds",
            "Broadcast delay into the slot",
            labels,
            registry=self.registry,
        )
        self.eth2_latency = Histogram(
            "app_eth2_latency_seconds",
            "Beacon-node request latency per endpoint",
            labels + ["client", "endpoint"],
            registry=self.registry,
        )
        self.eth2_errors = Counter(
            "app_eth2_errors_total",
            "Beacon-node request errors per endpoint",
            labels + ["client", "endpoint"],
            registry=self.registry,
        )
        self.batch_size = Histogram(
            "tpu_batch_size",
            "Device batch sizes for crypto kernels",
            labels + ["kernel"],
            registry=self.registry,
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
        )
        self.plane_flushes = counter(
            "tpu_plane_flushes_total",
            "Crypto-plane coalescer flushes (device program launches)",
        )
        self.plane_coalesced = counter(
            "tpu_plane_coalesced_flushes_total",
            "Flushes that merged work from >= 2 concurrent submissions",
        )
        self.plane_lanes = counter(
            "tpu_plane_lanes_total",
            "Crypto lanes executed through the coalesced plane",
        )
        # pipelined host plane (ISSUE 3): per-flush latency/occupancy,
        # decode-pool queueing, bucket-padding waste, device-lane depth
        self.plane_flush_seconds = Histogram(
            "tpu_plane_flush_seconds",
            "Device-lane wall clock per coalescer flush (pack excluded)",
            labels,
            registry=self.registry,
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.5, 2.0, 10.0, 60.0),
        )
        self.plane_lanes_per_flush = Histogram(
            "tpu_plane_lanes_per_flush",
            "Crypto lanes merged into each coalescer flush (occupancy)",
            labels,
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 1024),
        )
        self.plane_decode_queue_seconds = Histogram(
            "tpu_plane_decode_queue_seconds",
            "Decode-pool queue delay per decode chunk (submit -> start)",
            labels,
            registry=self.registry,
            buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0),
        )
        self.plane_pad_waste = Gauge(
            "tpu_plane_pad_waste_ratio",
            "Bucket-padding lanes / padded lanes of the most recent "
            "flush (shape-bucket overhead)",
            labels,
            registry=self.registry,
        )
        self.plane_inflight = Gauge(
            "tpu_plane_inflight_depth",
            "Device-lane depth when the most recent flush was submitted "
            "(>= 2 means flushes are double-buffering)",
            labels,
            registry=self.registry,
        )
        self.plane_overlapped = counter(
            "tpu_plane_overlapped_flushes_total",
            "Flushes whose host stages overlapped a device program "
            "still in flight (double-buffered windows)",
        )
        # decode-source breakdown (ISSUE 5): where each flush's point
        # decodes were served — LRU point-cache lookups (pubkeys,
        # messages, pubshares) vs signature lanes decompressed on
        # device (decode-fused flush programs) vs on host (python
        # bigint rung)
        self.plane_decode_lanes = counter(
            "tpu_plane_decode_lanes_total",
            "Point decodes per flush by source: cache = LRU point "
            "lookups, device = signature lanes decompressed inside the "
            "flush program, python = host bigint decompression",
            ["source"],
        )
        self.plane_decode_mode = Gauge(
            "tpu_plane_decode_mode",
            "Decode rung that served the most recent flush "
            "(1 = device decompression kernels, 0 = python host decode)",
            labels,
            registry=self.registry,
        )
        # tpu_impl point-cache efficiency, polled from the process-wide
        # lru_cache counters at scrape time (monotonic, but exported as
        # gauges because cache_info() owns the counter state)
        self.point_cache_hits = Gauge(
            "tpu_point_cache_hits",
            "Cumulative lru_cache hits of the tpu_impl point caches, "
            "by cache (pubkey decompression / message hash-to-curve)",
            labels + ["cache"],
            registry=self.registry,
        )
        self.point_cache_misses = Gauge(
            "tpu_point_cache_misses",
            "Cumulative lru_cache misses (cold decodes paid on host)",
            labels + ["cache"],
            registry=self.registry,
        )
        self.point_cache_size = Gauge(
            "tpu_point_cache_entries",
            "Current entries held by the tpu_impl point caches",
            labels + ["cache"],
            registry=self.registry,
        )
        # cold-start observability (ISSUE 6): the bulk point-cache
        # warm-up path — lanes decoded per warm pass by cache and
        # source (device = sharded bulk kernels, python = host bigint
        # rung, cached = already warm, invalid = rejected lanes), plus
        # wall seconds per warm pass
        self.point_cache_warmup_lanes = counter(
            "tpu_point_cache_warmup_lanes_total",
            "Point-cache warm-up lanes by cache (pubkey decompression / "
            "message hash-to-curve) and source (device bulk kernels, "
            "python host decode, cached = skipped, invalid = rejected)",
            ["cache", "source"],
        )
        self.point_cache_warmup_seconds = Histogram(
            "tpu_point_cache_warmup_seconds",
            "Wall seconds per bulk warm-up pass (startup or "
            "validator-set rotation)",
            labels,
            registry=self.registry,
            buckets=(0.05, 0.2, 1.0, 5.0, 20.0, 60.0, 300.0),
        )
        # wire codec observability (ISSUE 7): per-frame encode/decode
        # host CPU and byte volume, attributed to the codec that
        # carried the frame (binary vs json fallback) — the rollout
        # dashboard for the binary wire format
        self.wire_encode_seconds = Histogram(
            "wire_encode_seconds",
            "Envelope encode host seconds per transport frame, by codec",
            labels + ["codec"],
            registry=self.registry,
            buckets=(1e-5, 5e-5, 2e-4, 1e-3, 5e-3, 0.02, 0.1),
        )
        self.wire_decode_seconds = Histogram(
            "wire_decode_seconds",
            "Envelope decode host seconds per transport frame, by codec",
            labels + ["codec"],
            registry=self.registry,
            buckets=(1e-5, 5e-5, 2e-4, 1e-3, 5e-3, 0.02, 0.1),
        )
        self.wire_bytes = Counter(
            "wire_bytes_total",
            "Transport frame bytes by direction and codec (binary "
            "broadcast frames are encoded once and written per peer; "
            "every write counts here)",
            labels + ["dir", "codec"],
            registry=self.registry,
        )
        self.wire_frames = Counter(
            "wire_frames_total",
            "Transport frames by direction and codec",
            labels + ["dir", "codec"],
            registry=self.registry,
        )
        self.wire_peer_quarantine = Counter(
            "wire_peer_quarantine_total",
            "Temporary peer mutes imposed after repeated malformed "
            "frames (p2p codec quarantine, exponential backoff)",
            labels + ["peer_index"],
            registry=self.registry,
        )
        # Byzantine evidence (ISSUE 16): every attributed detection made
        # by the protocol components — qbft equivocation/forged
        # justifications/replay/floods, conflicting or spoofed partial
        # signatures. Attribution is authenticated before recording, so
        # the counter names ONLY the adversary (the PR 8 acceptance
        # style); it feeds the per-peer quarantine primitive.
        self.byzantine_evidence = Counter(
            "byzantine_evidence_total",
            "Attributable Byzantine-behaviour detections by offending "
            "peer share index and evidence kind "
            "(core/evidence.py kind catalogue)",
            labels + ["peer", "kind"],
            registry=self.registry,
        )
        # multi-tenant crypto-plane service (ISSUE 8): per-tenant flush
        # attribution, admission-shed counts, queue occupancy, breaker
        # state machine and quarantined flushes — the isolation
        # dashboard that answers "who is hurting whom" on a shared mesh
        self.plane_tenant_lanes = Counter(
            "tpu_plane_tenant_lanes_total",
            "Crypto lanes flushed through the shared plane, by tenant "
            "(FlushStats.tenant_lanes attribution)",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.plane_tenant_shed = Counter(
            "tpu_plane_tenant_shed_total",
            "Submissions shed at admission with PlaneOverloadError, by "
            "tenant and bound hit (jobs = queue depth, lanes = lane "
            "depth); shed work serves from the submitter's host rung",
            labels + ["tenant", "reason"],
            registry=self.registry,
        )
        self.plane_tenant_queue = Gauge(
            "tpu_plane_tenant_queue_lanes",
            "Pending (queued + in-flight) lanes in the tenant's "
            "submission queue at the most recent admission",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.plane_tenant_breaker = Gauge(
            "tpu_plane_tenant_breaker_state",
            "Per-tenant circuit breaker state "
            "(0 = closed, 1 = half-open, 2 = open/quarantined)",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.plane_tenant_breaker_transitions = Counter(
            "tpu_plane_tenant_breaker_transitions_total",
            "Breaker state transitions by tenant and entered state",
            labels + ["tenant", "state"],
            registry=self.registry,
        )
        self.plane_tenant_quarantined = Counter(
            "tpu_plane_tenant_quarantined_flushes_total",
            "Dispatches served by the tenant's own quarantine flushes "
            "(breaker open/half-open) instead of the shared RLC batch",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.plane_tenant_submit_seconds = Histogram(
            "tpu_plane_tenant_submit_seconds",
            "Admission-to-result wall seconds per tenant submission "
            "through the crypto-plane service",
            labels + ["tenant"],
            registry=self.registry,
            buckets=(0.005, 0.02, 0.05, 0.1, 0.5, 2.0, 10.0, 60.0),
        )
        # remote crypto plane (ISSUE 17): the client-side view of the
        # networked service rung — every failover to the local ladder,
        # window/remote sheds, connection churn and rung state, all
        # attributed to the dialing tenant
        self.plane_remote_failovers = Counter(
            "tpu_plane_remote_failovers_total",
            "Jobs degraded from the remote crypto plane to the local "
            "ladder, by tenant and failure reason (down, probing, io, "
            "codec, timeout, heartbeat, shed, remote_error)",
            labels + ["tenant", "reason"],
            registry=self.registry,
        )
        self.plane_remote_failover_lanes = Counter(
            "tpu_plane_remote_failover_lanes_total",
            "Crypto lanes served by the local ladder after a remote "
            "failure, by tenant and failure reason",
            labels + ["tenant", "reason"],
            registry=self.registry,
        )
        self.plane_remote_shed = Counter(
            "tpu_plane_remote_shed_total",
            "Typed sheds on the remote rung by tenant and reason: the "
            "client's bounded in-flight window (jobs, lanes) and "
            "server admission sheds relayed as CryptoShed frames "
            "(remote_jobs, remote_lanes, remote_closed)",
            labels + ["tenant", "reason"],
            registry=self.registry,
        )
        self.plane_remote_connects = Counter(
            "tpu_plane_remote_connects_total",
            "Authenticated connections established to the remote "
            "crypto-plane service, by tenant (first dial + reconnects)",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.plane_remote_disconnects = Counter(
            "tpu_plane_remote_disconnects_total",
            "Remote crypto-plane connections torn down, by tenant and "
            "reason (io, codec, heartbeat, closed)",
            labels + ["tenant", "reason"],
            registry=self.registry,
        )
        self.plane_remote_state = Gauge(
            "tpu_plane_remote_state",
            "Remote crypto-plane rung state per tenant "
            "(0 = down/local-only, 1 = probing half-open, 2 = up)",
            labels + ["tenant"],
            registry=self.registry,
        )
        # duty-rooted tracing (ISSUE 4): per-step latency from span
        # ends plus the slow-duty detector's wall-time/budget verdicts
        self.step_latency = Histogram(
            "core_step_latency_seconds",
            "Workflow step latency derived from span ends (wire edges, "
            "parsigex/qbft receive paths, crypto-plane stages)",
            labels + ["step"],
            registry=self.registry,
            buckets=(0.001, 0.005, 0.02, 0.05, 0.2, 0.5, 2.0, 10.0),
        )
        self.duty_wall_seconds = Histogram(
            "core_duty_wall_seconds",
            "Duty wall time: first span start to last span end of the "
            "duty trace, observed at duty expiry",
            labels + ["duty"],
            registry=self.registry,
            buckets=(0.05, 0.2, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 60.0),
        )
        self.duty_slow = counter(
            "core_duty_slow_total",
            "Duties whose traced wall time exceeded the deadline budget "
            "(slow-duty detector over span ends)",
            ["duty"],
        )
        # kernel auto-tuner + AOT compile-artifact cache (ISSUE 18):
        # profile lifecycle, per-axis decisions and micro-bench
        # timings from core/autotune.resolve, plus persistent
        # compile-cache effectiveness from jaxcache.cache_stats —
        # cold-start regressions show up here instead of in a
        # 6-minute boot
        self.autotune_profile_events = counter(
            "tpu_autotune_profile_events_total",
            "Kernel-profile lifecycle events from the startup tuner "
            "(hit, miss, stale, corrupt, rebuilt, off, skipped)",
            ["event"],
        )
        self.autotune_decisions = counter(
            "tpu_autotune_decisions_total",
            "Kernel-routing decisions applied at startup, per tunable "
            "axis, with the choice and where it came from (profile, "
            "tuned, env, default, inapplicable)",
            ["axis", "choice", "source"],
        )
        self.autotune_bench_seconds = Histogram(
            "tpu_autotune_bench_seconds",
            "Per-candidate micro-bench dispatch time measured by the "
            "startup tuner (best of its reps)",
            labels + ["axis", "choice"],
            registry=self.registry,
            buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0),
        )
        self.autotune_prewarm_seconds = Histogram(
            "tpu_autotune_prewarm_seconds",
            "Ahead-of-time compile/load time per prewarm shape for the "
            "chosen kernel variants (cold = real XLA compile, warm = "
            "persistent-cache load)",
            labels + ["axis"],
            registry=self.registry,
            buckets=(0.01, 0.05, 0.2, 1.0, 5.0, 30.0, 120.0, 600.0),
        )
        self.compile_cache_hits = Gauge(
            "tpu_compile_cache_hits",
            "Persistent XLA compile-cache hits since process start "
            "(jaxcache monitoring listener; polled at scrape)",
            labels,
            registry=self.registry,
        )
        self.compile_cache_misses = Gauge(
            "tpu_compile_cache_misses",
            "Persistent XLA compile-cache misses (cache-consulting "
            "compile requests minus hits) since process start",
            labels,
            registry=self.registry,
        )
        self.compile_cache_entries = Gauge(
            "tpu_compile_cache_entries",
            "Artifact files in this process's persistent compile-cache "
            "dir (tuner profile excluded)",
            labels,
            registry=self.registry,
        )
        self.compile_cache_bytes = Gauge(
            "tpu_compile_cache_bytes",
            "Bytes on disk in this process's persistent compile-cache "
            "dir (tuner profile excluded)",
            labels,
            registry=self.registry,
        )
        # flight recorder + plane profiler + duty SLO engine (ISSUE 19):
        # the post-mortem spine's own telemetry — ring intake/eviction,
        # dump triggers, per-kernel-family device time, device duty
        # cycle, per-tenant device attribution, and the rolling
        # error-budget burn state
        self.flightrec_events = counter(
            "flightrec_events_total",
            "Events recorded into the flight-recorder ring, by category",
            ["category"],
        )
        self.flightrec_dropped = Gauge(
            "flightrec_dropped_events",
            "Events evicted from a full flight-recorder category ring "
            "(cumulative; the recorder owns the counter state)",
            labels + ["category"],
            registry=self.registry,
        )
        self.flightrec_dumps = Gauge(
            "flightrec_dumps",
            "Flight-recorder JSONL dumps written, by trigger (demand, "
            "sigterm, crash, stop; cumulative — recorder-owned state)",
            labels + ["trigger"],
            registry=self.registry,
        )
        self.plane_kernel_seconds = counter(
            "tpu_plane_kernel_seconds_total",
            "Device-dispatch wall seconds by mesh kernel family "
            "(mesh/verify_rlc, mesh/step, ... per kernel_inventory; "
            "'device' = plane without program hooks), sampled by the "
            "plane profiler from SlotCryptoPlane.on_program",
            ["family"],
        )
        self.plane_device_utilization = Gauge(
            "tpu_plane_device_utilization",
            "Device duty cycle: flush device_span seconds over the "
            "profiler's rolling window, 0..1",
            labels,
            registry=self.registry,
        )
        self.plane_tenant_device_seconds = Counter(
            "tpu_plane_tenant_device_seconds_total",
            "Flush device_span seconds attributed to each tenant by "
            "its live-lane share (FlushStats.tenant_lanes)",
            labels + ["tenant"],
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "core_slo_burn_rate",
            "Error-budget burn rate by objective (duty_miss, "
            "step_latency), tenant, and alert window (fast, slow); "
            "1.0 spends the budget exactly at the allowed pace",
            labels + ["slo", "tenant", "window"],
            registry=self.registry,
        )
        self.slo_budget_remaining = Gauge(
            "core_slo_budget_remaining",
            "Fraction of the slow-window error budget still unspent, "
            "by objective and tenant (0..1)",
            labels + ["slo", "tenant"],
            registry=self.registry,
        )
        self.slo_alerts = Counter(
            "core_slo_alerts_total",
            "Burn-rate alert rising edges by objective, tenant, and "
            "severity (critical gates /readyz via the health checker)",
            labels + ["slo", "tenant", "severity"],
            registry=self.registry,
        )
        self.stack_colocated = Gauge(
            "stack_colocated_processes",
            "Co-located validator-stack processes found on this host "
            "by the stacksnipe /proc scan, by binary name",
            labels + ["binary"],
            registry=self.registry,
        )
        # device-accelerated ceremonies (ISSUE 20): verification lanes
        # by ceremony stage and execution path, plus the resharing
        # lifecycle (operator join/leave, threshold change, proactive
        # rotation) as a live, benchmarked workload
        self.dkg_verify_lanes = counter(
            "dkg_verify_lanes_total",
            "Ceremony verification lanes by stage (pok / share / "
            "pubshare_eval / reshare_share / reshare_pubshare) and "
            "execution path (device batched kernels vs host bigint)",
            ["stage", "path"],
        )
        self.dkg_reshare_total = counter(
            "dkg_reshare_total",
            "Key resharing ceremonies by kind (join / leave / "
            "threshold / rotate) and result (ok / error)",
            ["kind", "result"],
        )
        self.dkg_reshare_seconds = Histogram(
            "dkg_reshare_seconds",
            "Wall seconds per resharing ceremony (rounds + share "
            "derivation, excluding transport wait on remote dealers)",
            labels,
            registry=self.registry,
            buckets=(0.05, 0.2, 1.0, 5.0, 20.0, 60.0, 300.0),
        )
        self.dkg_reshare_validators = counter(
            "dkg_reshare_validators_total",
            "Validators whose shares were rotated by completed "
            "resharing ceremonies",
        )

    def labels(self, metric, *extra):
        return metric.labels(*self._label_values, *extra)

    def observe_point_caches(self) -> None:
        """Refresh the point-cache gauges from the tpu_impl lru_cache
        counters. Only when tpu_impl is already imported — a scrape
        must never pull the jax stack into a host-only process."""
        import sys

        impl = sys.modules.get("charon_tpu.tbls.tpu_impl")
        if impl is None:
            return
        for name, cache in (
            ("pubkey", impl._cached_pubkey_point),
            ("message", impl._cached_msg_point),
        ):
            info = cache.cache_info()
            self.labels(self.point_cache_hits, name).set(info.hits)
            self.labels(self.point_cache_misses, name).set(info.misses)
            self.labels(self.point_cache_size, name).set(info.currsize)

    def observe_dkg_verify(self, stage: str, path: str, lanes: int) -> None:
        """Record one ceremony verification wave: `lanes` checks of
        `stage` served by `path` ("device" batched kernels or "host"
        python bigint fallback)."""
        if lanes:
            self.labels(self.dkg_verify_lanes, stage, path).inc(lanes)

    def observe_reshare(
        self,
        kind: str,
        result: str,
        seconds: float | None = None,
        validators: int = 0,
    ) -> None:
        """Record one resharing ceremony outcome. `kind` is the
        operator-facing mode (join / leave / threshold / rotate),
        `validators` the rotated share count on success."""
        self.labels(self.dkg_reshare_total, kind, result).inc()
        if seconds is not None:
            self.labels(self.dkg_reshare_seconds).observe(
                max(0.0, float(seconds))
            )
        if validators:
            self.labels(self.dkg_reshare_validators).inc(validators)

    def observe_warmup(self, stats: dict) -> None:
        """Record one bulk warm-up pass (the stats dict returned by
        tpu_impl.warm_point_caches / SlotCoalescer.warm_caches).
        Thread-safe — warm-up runs on its own worker thread."""
        for cache in ("pubkey", "message"):
            for source, count in stats.get(cache, {}).items():
                if count:
                    self.labels(
                        self.point_cache_warmup_lanes, cache, source
                    ).inc(count)
        self.labels(self.point_cache_warmup_seconds).observe(
            max(0.0, float(stats.get("seconds", 0.0)))
        )

    def wire_hook(self):
        """P2PNode.wire_observer sink: called per frame with
        (direction "tx"|"rx", codec "binary"|"json", frame_bytes,
        codec_seconds | None). seconds is None for broadcast cache
        hits — the frame hit the wire but paid no encode (ISSUE 7
        single-encode broadcast), so only bytes/frames count. Runs on
        the event loop; prometheus objects are thread-safe anyway."""

        def hook(direction, codec_name, nbytes, seconds) -> None:
            self.labels(self.wire_bytes, direction, codec_name).inc(nbytes)
            self.labels(self.wire_frames, direction, codec_name).inc()
            if seconds is None:
                return
            hist = (
                self.wire_encode_seconds
                if direction == "tx"
                else self.wire_decode_seconds
            )
            self.labels(hist, codec_name).observe(max(0.0, seconds))

        return hook

    def tenant_hook(self):
        """CryptoPlaneService.observer sink: typed service events ->
        the tenant-labeled metric families. Runs on the event loop;
        prometheus client objects are thread-safe anyway."""
        state_value = {"closed": 0, "half_open": 1, "open": 2}

        def hook(kind: str, tenant: str, **f) -> None:
            if kind == "shed":
                self.labels(self.plane_tenant_shed, tenant, f["reason"]).inc()
            elif kind == "queue":
                self.labels(self.plane_tenant_queue, tenant).set(f["lanes"])
            elif kind == "breaker":
                self.labels(self.plane_tenant_breaker, tenant).set(
                    state_value.get(f["state"], 0)
                )
                self.labels(
                    self.plane_tenant_breaker_transitions, tenant, f["state"]
                ).inc()
            elif kind == "complete":
                self.labels(self.plane_tenant_submit_seconds, tenant).observe(
                    max(0.0, f["seconds"])
                )
                if f.get("quarantined"):
                    self.labels(self.plane_tenant_quarantined, tenant).inc()

        return hook

    def remote_hook(self, tenant: str):
        """core/cryptosvc_client.RemotePlane observer sink: typed
        client events -> the tenant-labeled remote-plane families.
        Tenant identity is bound once here — the client never passes
        labels (and MUST never pass secrets) into metrics."""
        state_value = {"down": 0, "probing": 1, "up": 2}

        def hook(kind: str, **f) -> None:
            if kind == "failover":
                reason = f.get("reason", "unknown")
                self.labels(
                    self.plane_remote_failovers, tenant, reason
                ).inc()
                self.labels(
                    self.plane_remote_failover_lanes, tenant, reason
                ).inc(f.get("lanes", 0))
            elif kind == "shed":
                self.labels(
                    self.plane_remote_shed, tenant, f["reason"]
                ).inc()
            elif kind == "remote_shed":
                self.labels(
                    self.plane_remote_shed,
                    tenant,
                    f"remote_{f['reason']}",
                ).inc()
            elif kind == "connect":
                self.labels(self.plane_remote_connects, tenant).inc()
            elif kind == "disconnect":
                self.labels(
                    self.plane_remote_disconnects, tenant, f["reason"]
                ).inc()
            elif kind == "state":
                self.labels(self.plane_remote_state, tenant).set(
                    state_value.get(f["state"], 0)
                )

        return hook

    def autotune_hook(self):
        """core/autotune.resolve observer sink: typed tuner events ->
        the autotune metric families. Runs on the tuner's worker
        thread; prometheus client objects are thread-safe."""

        def hook(kind: str, **f) -> None:
            if kind == "profile":
                self.labels(self.autotune_profile_events, f["event"]).inc()
            elif kind == "decision":
                self.labels(
                    self.autotune_decisions,
                    f["axis"],
                    f["choice"],
                    f["source"],
                ).inc()
            elif kind == "bench":
                self.labels(
                    self.autotune_bench_seconds, f["axis"], f["choice"]
                ).observe(max(0.0, f["seconds"]))
            elif kind == "prewarm":
                self.labels(
                    self.autotune_prewarm_seconds, f["axis"]
                ).observe(max(0.0, f["seconds"]))

        return hook

    def observe_compile_cache(self) -> None:
        """Refresh the persistent compile-cache gauges from
        jaxcache.cache_stats (jax stays out of the scrape path —
        jaxcache imports only stdlib; stats are None until
        jaxcache.configure ran in this process)."""
        from charon_tpu import jaxcache

        stats = jaxcache.cache_stats()
        if stats is None:
            return
        self.labels(self.compile_cache_hits).set(stats["hits"])
        self.labels(self.compile_cache_misses).set(stats["misses"])
        self.labels(self.compile_cache_entries).set(stats["entries"])
        self.labels(self.compile_cache_bytes).set(stats["bytes"])

    def byzantine_hook(self):
        """core/evidence.EvidenceRegistry hook: one increment per
        attributed Byzantine detection, labelled by the offending peer
        (share index) and evidence kind."""

        def hook(peer, kind: str) -> None:
            self.labels(self.byzantine_evidence, str(peer), kind).inc()

        return hook

    def peer_quarantine_hook(self):
        """P2PNode.quarantine_observer sink: count imposed peer mutes
        by peer index."""

        def hook(peer_idx: int, mute_seconds: float) -> None:
            self.labels(self.wire_peer_quarantine, str(peer_idx)).inc()

        return hook

    def flightrec_hook(self):
        """app/flightrec.FlightRecorder observer: one increment per
        recorded event, by category. Runs on whatever thread recorded
        the event; prometheus client objects are thread-safe."""

        def hook(category: str, kind: str) -> None:
            self.labels(self.flightrec_events, category).inc()

        return hook

    def observe_flightrec(self, rec) -> None:
        """Refresh the recorder-owned cumulative state (eviction and
        dump counts) into the flightrec gauges — same polled-gauge
        pattern as the point caches."""
        for category, n in rec.dropped_total.items():
            if n:
                self.labels(self.flightrec_dropped, category).set(n)
        for trigger, n in rec.dumps_total.items():
            self.labels(self.flightrec_dumps, trigger).set(n)

    def profiler_hooks(self):
        """app/planeprof.PlaneProfiler callbacks -> the kernel-family /
        tenant-attribution / duty-cycle families. All run on the device
        worker thread; prometheus client objects are thread-safe."""

        def on_sample(family: str, seconds: float) -> None:
            self.labels(self.plane_kernel_seconds, family).inc(
                max(0.0, seconds)
            )

        def on_tenant(tenant: str, seconds: float) -> None:
            self.labels(self.plane_tenant_device_seconds, tenant).inc(
                max(0.0, seconds)
            )

        def on_utilization(fraction: float) -> None:
            self.labels(self.plane_device_utilization).set(fraction)

        return on_sample, on_tenant, on_utilization

    def observe_slo(self, rows) -> None:
        """Export one SLOEngine.evaluate() pass into the core_slo_*
        gauges (run.py's health sample loop cadence)."""
        for r in rows:
            self.labels(
                self.slo_burn_rate, r["slo"], r["tenant"], "fast"
            ).set(r["fast_burn"])
            self.labels(
                self.slo_burn_rate, r["slo"], r["tenant"], "slow"
            ).set(r["slow_burn"])
            self.labels(
                self.slo_budget_remaining, r["slo"], r["tenant"]
            ).set(r["budget_remaining"])

    def slo_alert_hook(self):
        """SLOEngine.on_alert sink: count burn-rate alert rising edges."""

        def hook(slo: str, tenant: str, severity: str) -> None:
            self.labels(self.slo_alerts, slo, tenant, severity).inc()

        return hook

    def stacksnipe_hook(self):
        """app/stacksnipe.StackSniper.on_report sink: publish the scan
        as per-binary gauges, zeroing binaries that disappeared since
        the previous scan."""
        seen: set[str] = set()

        def hook(report: dict) -> None:
            for binary in seen - set(report):
                self.labels(self.stack_colocated, binary).set(0)
            for binary, pids in report.items():
                self.labels(self.stack_colocated, binary).set(len(pids))
            seen.clear()
            seen.update(report)

        return hook

    def render(self) -> bytes:
        self.observe_point_caches()
        self.observe_compile_cache()
        return generate_latest(self.registry)


# wire() edges -> the counter each one increments when it fires
# (ref: the reference instruments components directly; one wire option
# keeps the components metric-free here)
_EDGE_COUNTERS = {
    "fetcher.fetch": "duty_total",
    "dutydb.store": "consensus_decided",
    "parsigdb.store_external": "parsig_received",
    "sigagg.aggregate": "sigagg_total",
    "broadcaster.broadcast": "bcast_total",
}


def instrument(metrics: "ClusterMetrics"):
    """wire() option: count workflow-edge completions per duty type."""

    def option(name: str, fn):
        attr = _EDGE_COUNTERS.get(name)
        if attr is None:
            return fn
        counter = getattr(metrics, attr)

        async def wrapped(duty, *args, **kwargs):
            result = await fn(duty, *args, **kwargs)
            metrics.labels(counter, str(duty.type.name.lower())).inc()
            return result

        return wrapped

    return option


def span_metrics(metrics: "ClusterMetrics"):
    """Tracer hook (app/tracer.Tracer.hooks): observe every finished
    span's duration into the per-step latency histogram. Runs on
    whatever thread records the span — prometheus client objects are
    thread-safe."""

    def hook(span) -> None:
        # bridged crypto-plane stages are recorded once per duty trace
        # that rode the flush; copies carry shared=True so one physical
        # flush observes each stage latency exactly once
        if span.attrs.get("shared"):
            return
        metrics.labels(metrics.step_latency, span.name).observe(
            max(0.0, span.end - span.start)
        )

    return hook


class SlowDutyDetector:
    """Duty wall-time vs deadline budget, derived from span ends
    (ISSUE 4: 'was the duty late?' answered from the trace, not logs).

    Feed every finished span via `observe` (a tracer hook); at duty
    expiry call `finalize(duty, budget)` — it computes the traced wall
    time (first span start to last span end across the duty's
    deterministic trace) and flags the duty slow when it exceeded the
    budget. State is per-trace and popped at finalize, so memory is
    bounded by in-flight duties."""

    def __init__(self, metrics: "ClusterMetrics | None" = None) -> None:
        import threading

        self.metrics = metrics
        self._window: dict[str, tuple[float, float]] = {}
        # observe() runs as a tracer hook on whatever thread records the
        # span — device worker threads for bridged plane spans, the
        # event loop for wire edges. Serialize the read-modify-write
        # (and the eviction scan) or concurrent observes lose window
        # updates / crash mid-iteration.
        self._lock = threading.Lock()
        self.slow_total = 0
        self.last: dict | None = None  # most recent finalize verdict

    def observe(self, span) -> None:
        with self._lock:
            cur = self._window.get(span.trace_id)
            if cur is None:
                self._window[span.trace_id] = (span.start, span.end)
            else:
                self._window[span.trace_id] = (
                    min(cur[0], span.start),
                    max(cur[1], span.end),
                )
            # bounded: a trace that never finalizes (non-duty spans)
            # must not leak; duty traces are finalized long before 4096
            # others
            if len(self._window) > 4096:
                for k in list(self._window)[:2048]:
                    self._window.pop(k, None)

    def finalize(self, duty, budget: float) -> float | None:
        """Wall seconds of the duty's trace, or None when no spans were
        recorded. `budget` is the duty's allotted seconds (deadline
        minus slot start)."""
        from charon_tpu.app.tracer import duty_trace_id

        with self._lock:
            window = self._window.pop(duty_trace_id(duty), None)
        if window is None:
            return None
        wall = max(0.0, window[1] - window[0])
        slow = budget > 0 and wall > budget
        self.last = {
            "duty": str(duty),
            "wall_seconds": wall,
            "budget_seconds": budget,
            "slow": slow,
        }
        d = str(duty.type.name).lower()
        if self.metrics is not None:
            self.metrics.labels(self.metrics.duty_wall_seconds, d).observe(
                wall
            )
        if slow:
            self.slow_total += 1
            if self.metrics is not None:
                self.metrics.labels(self.metrics.duty_slow, d).inc()
            from charon_tpu.app import log

            log.warn(
                "slow duty: traced wall time exceeded deadline budget",
                topic="tracer",
                duty=str(duty),
                wall_seconds=round(wall, 3),
                budget_seconds=round(budget, 3),
            )
        return wall


# cProfile is interpreter-global state: exactly one /debug/pprof/profile
# may hold it at a time (a concurrent enable() raises on CPython 3.12)
_PROFILE_ACTIVE = asyncio.Lock()


async def serve_monitoring(
    host: str,
    port: int,
    metrics: ClusterMetrics,
    health_checker=None,
    ready_fn=None,
    consensus_dump=None,
    tracer=None,
    flightrec=None,
    profiler=None,
) -> asyncio.AbstractServer:
    """Minimal HTTP endpoint: /metrics, /livez, /readyz, /debug/traces,
    /debug/duty/<slot>, /debug/consensus (ref: app/monitoringapi.go:47;
    docs/consensus.md:74 for the consensus debugger), /debug/flight
    (ISSUE 19: the flight-recorder ring, filterable by category/tenant/
    slot, ?format=text for the incident timeline, ?view=profile for the
    plane profiler snapshot). `tracer` overrides the process-global span
    store for the debug trace endpoints."""

    async def handle(reader, writer):
        try:
            request = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b""):
                pass
            path = request.split()[1].decode() if request.split() else "/"
            if path.startswith("/metrics"):
                body = metrics.render()
                ctype = b"text/plain; version=0.0.4"
                status = b"200 OK"
            elif path.startswith("/debug/traces"):
                # recorded workflow spans (ref: app/monitoringapi.go debug
                # endpoints + /debug/consensus, docs/consensus.md:74)
                from charon_tpu.app import tracer as _tracer

                from urllib.parse import parse_qs, urlsplit

                query = parse_qs(urlsplit(path).query)
                trace_id = (query.get("trace_id") or [None])[0]
                body = _json.dumps(
                    (tracer or _tracer.global_tracer()).dump(trace_id)
                ).encode()
                ctype = b"application/json"
                status = b"200 OK"
            elif path.startswith("/debug/duty/"):
                # assembled per-duty timeline for one slot: every trace
                # with spans at that slot, depth-annotated (JSON), or a
                # plain-text waterfall with ?format=text (ISSUE 4)
                from charon_tpu.app import tracer as _tracer

                from urllib.parse import parse_qs, urlsplit

                parts = urlsplit(path)
                raw_slot = parts.path.split("/debug/duty/", 1)[1].strip("/")
                fmt = (parse_qs(parts.query).get("format") or ["json"])[0]
                try:
                    slot = int(raw_slot)
                except ValueError:
                    slot = None
                timelines = (
                    _tracer.duty_timeline(slot, tracer=tracer)
                    if slot is not None
                    else []
                )
                if not timelines:
                    body = b"no spans recorded for that slot"
                    ctype = b"text/plain"
                    status = b"404 Not Found"
                elif fmt == "text":
                    body = _tracer.render_waterfall(timelines).encode()
                    ctype = b"text/plain"
                    status = b"200 OK"
                else:
                    body = _json.dumps(timelines).encode()
                    ctype = b"application/json"
                    status = b"200 OK"
            elif path.startswith("/debug/pprof/profile"):
                # CPU profile of the event-loop thread for ?seconds=N
                # (ref: monitoringapi.go net/http/pprof profile endpoint)
                import cProfile
                import io
                import math
                import pstats
                from urllib.parse import parse_qs, urlsplit

                q = parse_qs(urlsplit(path).query)
                try:
                    secs = float((q.get("seconds") or ["5"])[0])
                except ValueError:
                    secs = float("nan")
                if not math.isfinite(secs) or secs < 0:
                    body = b"bad seconds parameter"
                    ctype = b"text/plain"
                    status = b"400 Bad Request"
                elif _PROFILE_ACTIVE.locked():
                    # cProfile is interpreter-global: a second enable()
                    # raises; serialize instead of crashing the handler
                    body = b"another profile is already running"
                    ctype = b"text/plain"
                    status = b"503 Service Unavailable"
                else:
                    async with _PROFILE_ACTIVE:
                        prof = cProfile.Profile()
                        prof.enable()
                        try:
                            await asyncio.sleep(min(secs, 60.0))
                        finally:
                            prof.disable()
                    buf = io.StringIO()
                    pstats.Stats(prof, stream=buf).sort_stats(
                        pstats.SortKey.CUMULATIVE
                    ).print_stats(60)
                    body = buf.getvalue().encode()
                    ctype = b"text/plain"
                    status = b"200 OK"
            elif path.startswith("/debug/pprof/threads"):
                # all-thread stack dump — the goroutine-dump analogue
                import sys as _sys
                import threading as _threading
                import traceback as _traceback

                names = {
                    t.ident: t.name for t in _threading.enumerate()
                }
                parts = []
                for tid, frame in _sys._current_frames().items():
                    parts.append(
                        f"--- thread {tid} ({names.get(tid, '?')}) ---\n"
                        + "".join(_traceback.format_stack(frame))
                    )
                body = "\n".join(parts).encode()
                ctype = b"text/plain"
                status = b"200 OK"
            elif path.startswith("/debug/pprof/heap"):
                # allocation snapshots via tracemalloc. Tracing costs
                # ~2x on every allocation, so it NEVER arms implicitly:
                # ?start=1 arms, ?stop=1 disarms, bare GET reports (or
                # explains how to arm) — unlike Go's free heap profile,
                # the analogue here is an explicit toggle
                import tracemalloc
                from urllib.parse import parse_qs, urlsplit

                q = parse_qs(urlsplit(path).query)
                if q.get("start"):
                    if not tracemalloc.is_tracing():
                        tracemalloc.start(10)
                    body = b"tracemalloc armed; GET without params for a snapshot, ?stop=1 to disarm"
                elif q.get("stop"):
                    if tracemalloc.is_tracing():
                        tracemalloc.stop()
                    body = b"tracemalloc stopped"
                elif not tracemalloc.is_tracing():
                    body = (
                        b"tracemalloc not armed; GET ?start=1 to begin "
                        b"tracing (allocation overhead until ?stop=1)"
                    )
                else:
                    snap = tracemalloc.take_snapshot()
                    lines = [
                        str(stat)
                        for stat in snap.statistics("lineno")[:40]
                    ]
                    body = "\n".join(lines).encode()
                ctype = b"text/plain"
                status = b"200 OK"
            elif path.startswith("/debug/flight"):
                # the flight-recorder ring (ISSUE 19): newest-first-
                # bounded JSON by default, ?format=text for the merged
                # incident timeline, filters category/tenant/slot/limit,
                # ?view=profile for the plane profiler's kernel-family
                # decomposition. 404 when no recorder is wired (the
                # endpoint must say so, not fake an empty incident).
                from urllib.parse import parse_qs, urlsplit

                q = parse_qs(urlsplit(path).query)

                def one(name, conv=str):
                    raw = (q.get(name) or [None])[0]
                    if raw is None:
                        return None
                    try:
                        return conv(raw)
                    except ValueError:
                        return None

                if flightrec is None:
                    body = b"flight recorder not enabled"
                    ctype = b"text/plain"
                    status = b"404 Not Found"
                elif one("view") == "profile":
                    if profiler is None:
                        body = b"plane profiler not enabled"
                        ctype = b"text/plain"
                        status = b"404 Not Found"
                    else:
                        body = _json.dumps(profiler.snapshot()).encode()
                        ctype = b"application/json"
                        status = b"200 OK"
                else:
                    from charon_tpu.app import flightrec as _flightrec

                    events = flightrec.events(
                        category=one("category"),
                        tenant=one("tenant"),
                        slot=one("slot", int),
                        limit=one("limit", int),
                    )
                    if one("format") == "text":
                        body = _flightrec.render_timeline(events).encode()
                        ctype = b"text/plain"
                    else:
                        body = _json.dumps(
                            {
                                "schema": _flightrec.SCHEMA_VERSION,
                                "node": flightrec.node,
                                "events": [
                                    e.to_dict(node=flightrec.node)
                                    for e in events
                                ],
                            }
                        ).encode()
                        ctype = b"application/json"
                    status = b"200 OK"
            elif path.startswith("/debug/consensus"):
                body = _json.dumps(
                    consensus_dump() if consensus_dump else []
                ).encode()
                ctype = b"application/json"
                status = b"200 OK"
            elif path.startswith("/livez"):
                body = b"ok"
                ctype = b"text/plain"
                status = b"200 OK"
            elif path.startswith("/readyz"):
                ready = ready_fn() if ready_fn else True
                healthy = health_checker.healthy() if health_checker else True
                ok = ready and healthy
                if ok:
                    body = b"ok"
                else:
                    # name every failing check with its severity so the
                    # operator sees WHY (ref: monitoringapi readyz errors)
                    lines = ["not ready"]
                    if health_checker is not None:
                        lines += [
                            f"{c.severity}: {c.name} - {c.description}"
                            for c in health_checker.failing()
                        ]
                    body = "\n".join(lines).encode()
                ctype = b"text/plain"
                status = b"200 OK" if ok else b"503 Service Unavailable"
            else:
                body = b"not found"
                ctype = b"text/plain"
                status = b"404 Not Found"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\nContent-Type: " + ctype
                + b"\r\nContent-Length: " + str(len(body)).encode()
                + b"\r\nConnection: close\r\n\r\n" + body
            )
            await writer.drain()
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)
