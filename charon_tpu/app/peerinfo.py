"""Peer metadata exchange: version, start time, clock offset.

Mirrors ref: app/peerinfo — periodic exchange of node metadata over the
p2p mesh (version + git hash + start time + builder-api flag + clock
offset, ref app/app.go:299-304; metrics docs/metrics.md app_peerinfo_*).
Clock offset feeds the monitoring readiness checks.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from charon_tpu.app import log
from charon_tpu.app import version as version_mod

PROTOCOL = "peerinfo/1.0.0"


@dataclass
class PeerInfo:
    version: str
    start_time: float
    clock_offset: float = 0.0  # peer_time - our_time at receipt
    last_seen: float = 0.0
    compatible: bool = True  # version window check (ref: app/version)


class PeerInfoService:
    def __init__(self, node, version: str) -> None:
        self.node = node
        self.version = version
        self.start_time = time.time()
        self.peers: dict[int, PeerInfo] = {}
        self._task: asyncio.Task | None = None
        node.register_handler(PROTOCOL, self._handle)

    def _record(self, idx: int, msg, now: float) -> None:
        peer_version = msg.get("version", "?")
        compatible = version_mod.check_compatible(peer_version)
        prev = self.peers.get(idx)
        if not compatible and (prev is None or prev.compatible):
            # surface the mismatch once per transition
            # (ref: version.Supported gating in peerinfo)
            log.warn(
                "peer runs an unsupported version",
                topic="peerinfo",
                peer=idx,
                peer_version=peer_version,
                ours=self.version,
            )
        self.peers[idx] = PeerInfo(
            version=peer_version,
            start_time=msg.get("start_time", 0.0),
            clock_offset=msg.get("now", now) - now,
            last_seen=now,
            compatible=compatible,
        )

    def incompatible_peers(self) -> list[int]:
        return [i for i, p in self.peers.items() if not p.compatible]

    async def _handle(self, from_idx: int, msg):
        now = time.time()
        if msg is not None:
            self._record(from_idx, msg, now)
        return {
            "version": self.version,
            "start_time": self.start_time,
            "now": time.time(),
        }

    async def poll_once(self) -> None:
        for idx in self.node.peers:
            try:
                resp = await self.node.send(
                    idx,
                    PROTOCOL,
                    {
                        "version": self.version,
                        "start_time": self.start_time,
                        "now": time.time(),
                    },
                    await_response=True,
                )
                self._record(idx, resp, time.time())
            except Exception:
                pass

    def start(self, interval: float = 10.0) -> None:
        async def loop():
            while True:
                await self.poll_once()
                await asyncio.sleep(interval)

        self._task = asyncio.create_task(loop())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
