"""Staleness-based private-key file lock.

Mirrors ref: app/privkeylock — prevents two nodes from running with the
same key share material (a double-signing hazard): a lock file holding pid
+ timestamp, refreshed periodically; a second process refuses to start
while the lock is fresh (ref wiring: app/app.go:145-153).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from pathlib import Path

STALENESS_SECS = 5.0
REFRESH_SECS = 1.0


class PrivKeyLockError(Exception):
    pass


class PrivKeyLock:
    def __init__(self, path: str | Path, command: str = "run") -> None:
        self.path = Path(path)
        self.command = command
        self._task: asyncio.Task | None = None

    def acquire(self) -> None:
        if self.path.exists():
            try:
                data = json.loads(self.path.read_text())
                age = time.time() - data.get("timestamp", 0)
                if age < STALENESS_SECS:
                    raise PrivKeyLockError(
                        f"private key locked by pid {data.get('pid')} "
                        f"(command {data.get('command')!r}, {age:.1f}s ago); "
                        "another node is using these keys"
                    )
            except (json.JSONDecodeError, OSError):
                pass  # stale/corrupt lock: take it over
        self._write()

    def _write(self) -> None:
        self.path.write_text(
            json.dumps(
                {
                    "pid": os.getpid(),
                    "command": self.command,
                    "timestamp": time.time(),
                }
            )
        )

    def start_refresh(self) -> None:
        async def loop():
            while True:
                await asyncio.sleep(REFRESH_SECS)
                self._write()

        self._task = asyncio.create_task(loop())

    async def release(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        try:
            self.path.unlink()
        except OSError:
            pass
