"""Health checks over a rolling in-memory metric window.

Mirrors ref: app/health — a 10-minute rolling store of samples from the
node's OWN metrics, evaluated by a declarative check catalogue
(health/checker.go; catalogue health/checks.go:41-151): error/warning
log rates scaled by validator count, beacon-node sync state, connected
peer quorum, proposal failures, registration-recast failures — plus
clock skew from the peerinfo exchange (the reference surfaces it through
monitoring readiness, app/monitoringapi.go).

Severity semantics (ref: checks.go severityCritical/Warning/Info):
critical failures gate /readyz; warnings and infos are reported in the
readyz body and metrics but do not flip readiness.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Callable

WINDOW_SECS = 600.0  # ref: app/health 10-minute window

SEVERITY_CRITICAL = "critical"
SEVERITY_WARNING = "warning"
SEVERITY_INFO = "info"


class MetricStore:
    def __init__(self, now=time.time) -> None:
        self._now = now
        self._series: dict[str, deque] = defaultdict(deque)

    def sample(self, name: str, value: float) -> None:
        q = self._series[name]
        t = self._now()
        q.append((t, value))
        while q and q[0][0] < t - WINDOW_SECS:
            q.popleft()

    def latest(self, name: str, default: float = 0.0) -> float:
        q = self._series.get(name)
        return q[-1][1] if q else default

    def max(self, name: str, default: float = 0.0) -> float:
        """Max over the window (ref: checker.go gaugeMax)."""
        q = self._series.get(name)
        return max((v for _, v in q), default=default) if q else default

    def increase(self, name: str) -> float:
        """Increase of a counter over the window (ref: checker.go
        increase)."""
        q = self._series.get(name)
        if not q or len(q) < 2:
            return 0.0
        return max(0.0, q[-1][1] - q[0][1])


@dataclass
class Metadata:
    """Cluster facts the checks scale by (ref: health.Metadata)."""

    num_validators: int = 1
    quorum: int = 2


@dataclass
class Check:
    name: str
    description: str
    failing: Callable[[MetricStore, Metadata], bool]
    severity: str = SEVERITY_WARNING


def default_checks() -> list[Check]:
    """The reference catalogue (ref: health/checks.go:41-151) evaluated
    over this node's own sampled metrics."""
    return [
        Check(
            "high_error_log_rate",
            "high rate of error logs (allow 2 per validator per window)",
            lambda m, md: m.increase("app_log_errors")
            > 2 * md.num_validators,
            SEVERITY_WARNING,
        ),
        Check(
            "high_warning_log_rate",
            "high rate of warning logs (allow 2 per validator per window)",
            lambda m, md: m.increase("app_log_warnings")
            > 2 * md.num_validators,
            SEVERITY_WARNING,
        ),
        Check(
            "beacon_node_syncing",
            "beacon node is in syncing state",
            lambda m, md: m.max("app_beacon_syncing") > 0,
            SEVERITY_CRITICAL,
        ),
        Check(
            "insufficient_connected_peers",
            "not connected to at least quorum-1 peers",
            lambda m, md: m.max("p2p_peers_connected") < md.quorum - 1,
            SEVERITY_CRITICAL,
        ),
        Check(
            "proposal_failures",
            "proposal duties failed in the window",
            lambda m, md: m.increase("core_tracker_failed_proposals") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "failed_duties",
            "duties failed in the window",
            lambda m, md: m.increase("core_tracker_failed_duties") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "high_registration_failures_rate",
            "validator-registration recasts failed in the window",
            lambda m, md: m.increase("core_bcast_recast_errors") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "high_clock_skew",
            "peer clock offset above 2s (peerinfo exchange)",
            lambda m, md: m.max("app_peerinfo_clock_offset_abs") > 2.0,
            SEVERITY_WARNING,
        ),
        Check(
            "pending_validators",
            "validators pending activation",
            lambda m, md: m.max("core_scheduler_validators_pending") > 0,
            SEVERITY_INFO,
        ),
    ]


class HealthChecker:
    def __init__(
        self,
        store: MetricStore,
        checks: list[Check] | None = None,
        metadata: Metadata | None = None,
    ) -> None:
        self.store = store
        self.checks = checks if checks is not None else default_checks()
        self.metadata = metadata or Metadata()

    def evaluate(self) -> dict[str, bool]:
        """check name -> failing?"""
        return {
            c.name: c.failing(self.store, self.metadata)
            for c in self.checks
        }

    def failing(self) -> list[Check]:
        return [c for c in self.checks if c.failing(self.store, self.metadata)]

    def healthy(self) -> bool:
        """Readiness gate: only CRITICAL checks flip readiness
        (ref: severity semantics, checks.go)."""
        return not any(
            c.severity == SEVERITY_CRITICAL for c in self.failing()
        )
