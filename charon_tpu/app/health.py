"""Health checks over a rolling in-memory metric window.

Mirrors ref: app/health — a 10-minute rolling store of samples from the
node's own metrics, evaluated by declarative checks
(health/checker.go, checks health/checks.go:41-151): beacon node syncing,
insufficient connected peers, high error rates, pending duties.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Callable

WINDOW_SECS = 600.0  # ref: app/health 10-minute window


class MetricStore:
    def __init__(self, now=time.time) -> None:
        self._now = now
        self._series: dict[str, deque] = defaultdict(deque)

    def sample(self, name: str, value: float) -> None:
        q = self._series[name]
        t = self._now()
        q.append((t, value))
        while q and q[0][0] < t - WINDOW_SECS:
            q.popleft()

    def latest(self, name: str, default: float = 0.0) -> float:
        q = self._series.get(name)
        return q[-1][1] if q else default

    def increase(self, name: str) -> float:
        """Increase of a counter over the window."""
        q = self._series.get(name)
        if not q or len(q) < 2:
            return 0.0
        return max(0.0, q[-1][1] - q[0][1])


@dataclass
class Check:
    name: str
    description: str
    failing: Callable[[MetricStore], bool]


def default_checks(quorum: int) -> list[Check]:
    """ref: health/checks.go:41-151 (beacon sync, peer connectivity,
    error spikes, duty failures)."""
    return [
        Check(
            "beacon_node_syncing",
            "beacon node is syncing",
            lambda m: m.latest("app_beacon_syncing") > 0,
        ),
        Check(
            "insufficient_peers",
            "fewer than quorum-1 peers connected",
            lambda m: m.latest("p2p_peers_connected") < quorum - 1,
        ),
        Check(
            "high_error_rate",
            "log error rate spiked in the window",
            lambda m: m.increase("app_log_errors") > 10,
        ),
        Check(
            "failed_duties",
            "duties failed in the window",
            lambda m: m.increase("core_tracker_failed_duties") > 0,
        ),
    ]


class HealthChecker:
    def __init__(self, store: MetricStore, checks: list[Check]) -> None:
        self.store = store
        self.checks = checks

    def evaluate(self) -> dict[str, bool]:
        """check name -> failing?"""
        return {c.name: c.failing(self.store) for c in self.checks}

    def healthy(self) -> bool:
        return not any(self.evaluate().values())
