"""Health checks over a rolling in-memory metric window.

Mirrors ref: app/health — a 10-minute rolling store of samples from the
node's OWN metrics, evaluated by a declarative check catalogue
(health/checker.go; catalogue health/checks.go:41-151): error/warning
log rates scaled by validator count, beacon-node sync state, connected
peer quorum, proposal failures, registration-recast failures — plus
clock skew from the peerinfo exchange (the reference surfaces it through
monitoring readiness, app/monitoringapi.go).

Severity semantics (ref: checks.go severityCritical/Warning/Info):
critical failures gate /readyz; warnings and infos are reported in the
readyz body and metrics but do not flip readiness.

ISSUE 19 extends this module two ways:

  * `plane_checks()` — the post-PR-8/17/18 catalogue: tenant breaker
    open, remote plane down/probing, peer quarantine active, autotune
    fell back to defaults. Sampled from the live subsystems by
    run.py's health sample loop under the series names each check
    documents.
  * `SLOEngine` — rolling per-tenant duty-miss and step-latency error
    budgets with multi-window burn-rate alerting (the SRE
    fast+slow-window construction: a page needs BOTH the fast window —
    still burning now — and the slow window — burned enough to matter —
    above threshold). Exported as `core_slo_*` metrics by run.py and
    gating /readyz through `SLOEngine.checks()`.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Callable

WINDOW_SECS = 600.0  # ref: app/health 10-minute window

SEVERITY_CRITICAL = "critical"
SEVERITY_WARNING = "warning"
SEVERITY_INFO = "info"


class MetricStore:
    def __init__(self, now=time.time) -> None:
        self._now = now
        self._series: dict[str, deque] = defaultdict(deque)

    def sample(self, name: str, value: float) -> None:
        q = self._series[name]
        t = self._now()
        q.append((t, value))
        while q and q[0][0] < t - WINDOW_SECS:
            q.popleft()

    def latest(self, name: str, default: float = 0.0) -> float:
        q = self._series.get(name)
        return q[-1][1] if q else default

    def max(self, name: str, default: float = 0.0) -> float:
        """Max over the window (ref: checker.go gaugeMax)."""
        q = self._series.get(name)
        return max((v for _, v in q), default=default) if q else default

    def increase(self, name: str) -> float:
        """Increase of a counter over the window (ref: checker.go
        increase)."""
        q = self._series.get(name)
        if not q or len(q) < 2:
            return 0.0
        return max(0.0, q[-1][1] - q[0][1])


@dataclass
class Metadata:
    """Cluster facts the checks scale by (ref: health.Metadata)."""

    num_validators: int = 1
    quorum: int = 2
    # a remote crypto plane is configured (ISSUE 19): the remote-state
    # checks only mean anything when there is a remote to be down
    remote_plane: bool = False


@dataclass
class Check:
    name: str
    description: str
    failing: Callable[[MetricStore, Metadata], bool]
    severity: str = SEVERITY_WARNING


def default_checks() -> list[Check]:
    """The reference catalogue (ref: health/checks.go:41-151) evaluated
    over this node's own sampled metrics."""
    return [
        Check(
            "high_error_log_rate",
            "high rate of error logs (allow 2 per validator per window)",
            lambda m, md: m.increase("app_log_errors")
            > 2 * md.num_validators,
            SEVERITY_WARNING,
        ),
        Check(
            "high_warning_log_rate",
            "high rate of warning logs (allow 2 per validator per window)",
            lambda m, md: m.increase("app_log_warnings")
            > 2 * md.num_validators,
            SEVERITY_WARNING,
        ),
        Check(
            "beacon_node_syncing",
            "beacon node is in syncing state",
            lambda m, md: m.max("app_beacon_syncing") > 0,
            SEVERITY_CRITICAL,
        ),
        Check(
            "insufficient_connected_peers",
            "not connected to at least quorum-1 peers",
            lambda m, md: m.max("p2p_peers_connected") < md.quorum - 1,
            SEVERITY_CRITICAL,
        ),
        Check(
            "proposal_failures",
            "proposal duties failed in the window",
            lambda m, md: m.increase("core_tracker_failed_proposals") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "failed_duties",
            "duties failed in the window",
            lambda m, md: m.increase("core_tracker_failed_duties") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "high_registration_failures_rate",
            "validator-registration recasts failed in the window",
            lambda m, md: m.increase("core_bcast_recast_errors") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "high_clock_skew",
            "peer clock offset above 2s (peerinfo exchange)",
            lambda m, md: m.max("app_peerinfo_clock_offset_abs") > 2.0,
            SEVERITY_WARNING,
        ),
        Check(
            "pending_validators",
            "validators pending activation",
            lambda m, md: m.max("core_scheduler_validators_pending") > 0,
            SEVERITY_INFO,
        ),
    ]


def plane_checks() -> list[Check]:
    """The distributed-plane catalogue (ISSUE 19 satellite): checks
    over the PR 8/17/18 subsystems, evaluated against series run.py's
    health sample loop records each tick:

      tpu_plane_tenant_breaker_state .. max breaker state across tenants
                                        (0 closed, 1 half-open, 2 open)
      tpu_plane_remote_state .......... min remote rung state across
                                        tenants (0 down, 1 probing, 2 up)
      wire_peer_quarantine_total ...... cumulative imposed peer mutes
      tpu_autotune_fallback ........... 1 when the startup tuner failed
                                        and kernel routing fell back to
                                        defaults, else 0
    """
    return [
        Check(
            "tenant_breaker_open",
            "a tenant circuit breaker is open (forged-lane flood "
            "quarantined to its own flushes)",
            lambda m, md: m.max("tpu_plane_tenant_breaker_state") >= 2,
            SEVERITY_CRITICAL,
        ),
        Check(
            "remote_plane_down",
            "remote crypto plane unreachable; duties served from the "
            "local ladder",
            lambda m, md: md.remote_plane
            and m.latest("tpu_plane_remote_state", 2.0) == 0,
            SEVERITY_WARNING,
        ),
        Check(
            "remote_plane_probing",
            "remote crypto plane half-open (reconnect probe in flight)",
            lambda m, md: md.remote_plane
            and m.latest("tpu_plane_remote_state", 2.0) == 1,
            SEVERITY_WARNING,
        ),
        Check(
            "peer_quarantine_active",
            "peer codec mutes imposed in the window (a peer is "
            "streaming malformed frames)",
            lambda m, md: m.increase("wire_peer_quarantine_total") > 0,
            SEVERITY_WARNING,
        ),
        Check(
            "autotune_defaults",
            "startup kernel tuner failed; routing fell back to "
            "untuned defaults",
            lambda m, md: m.max("tpu_autotune_fallback") > 0,
            SEVERITY_WARNING,
        ),
    ]


class HealthChecker:
    def __init__(
        self,
        store: MetricStore,
        checks: list[Check] | None = None,
        metadata: Metadata | None = None,
    ) -> None:
        self.store = store
        self.checks = checks if checks is not None else default_checks()
        self.metadata = metadata or Metadata()

    def evaluate(self) -> dict[str, bool]:
        """check name -> failing?"""
        return {
            c.name: c.failing(self.store, self.metadata)
            for c in self.checks
        }

    def failing(self) -> list[Check]:
        return [c for c in self.checks if c.failing(self.store, self.metadata)]

    def healthy(self) -> bool:
        """Readiness gate: only CRITICAL checks flip readiness
        (ref: severity semantics, checks.go)."""
        return not any(
            c.severity == SEVERITY_CRITICAL for c in self.failing()
        )


# -- duty SLO engine (ISSUE 19) -------------------------------------------


@dataclass(frozen=True)
class SLOConfig:
    """One rolling error-budget objective.

    `budget` is the allowed bad-event fraction (0.01 = 99% objective).
    Burn rate over a window = (bad fraction in window) / budget; a burn
    of 1.0 spends the budget exactly at the allowed pace. The classic
    multi-window rule pages when BOTH windows exceed `page_burn`
    (fast window: it is burning NOW; slow window: enough budget is gone
    to matter) and warns at `warn_burn`."""

    name: str
    budget: float
    fast_window: float = 300.0
    slow_window: float = 3600.0
    page_burn: float = 14.4  # SRE workbook: 5m/1h pair spending ~2%/h
    warn_burn: float = 6.0
    min_events: int = 10  # below this, a window stays silent (no data)


# per-(slo, tenant) event history cap — at one duty every 12 s a slot,
# 4096 events cover > 13 h, far past the slow window
_MAX_SLO_EVENTS = 4096

SLO_DUTY_MISS = "duty_miss"
SLO_STEP_LATENCY = "step_latency"


class SLOEngine:
    """Rolling per-tenant duty-miss and step-latency budgets with
    multi-window burn-rate alerts (module docstring).

    Feed it duty outcomes (`observe_duty`, from tracker reports) and
    step latencies (`observe_step`, from the tracer's span hook); call
    `evaluate()` periodically (run.py's health sample loop). Alert
    rising edges fire `on_alert(slo, tenant, severity)` — run.py chains
    the `core_slo_alerts_total` counter and a flight-recorder event
    through it. `checks()` returns Check objects for the HealthChecker
    so a paging duty-miss burn gates /readyz."""

    def __init__(
        self,
        duty_budget: float = 0.01,
        step_budget: float = 0.05,
        step_latency_target: float = 1.0,
        fast_window: float = 300.0,
        slow_window: float = 3600.0,
        page_burn: float = 14.4,
        warn_burn: float = 6.0,
        min_events: int = 10,
        on_alert=None,
        clock=time.monotonic,
    ) -> None:
        common = dict(
            fast_window=fast_window,
            slow_window=slow_window,
            page_burn=page_burn,
            warn_burn=warn_burn,
            min_events=min_events,
        )
        self.slos: dict[str, SLOConfig] = {
            SLO_DUTY_MISS: SLOConfig(SLO_DUTY_MISS, duty_budget, **common),
            SLO_STEP_LATENCY: SLOConfig(
                SLO_STEP_LATENCY, step_budget, **common
            ),
        }
        self.step_latency_target = step_latency_target
        self.on_alert = on_alert
        self._clock = clock
        # (slo, tenant) -> deque[(t_mono, bad)]
        self._events: dict[tuple[str, str], deque] = defaultdict(
            lambda: deque(maxlen=_MAX_SLO_EVENTS)
        )
        # (slo, tenant) -> currently-firing severity ("" when quiet)
        self._firing: dict[tuple[str, str], str] = {}
        self.alerts_total: dict[tuple[str, str, str], int] = {}

    # -- intake ------------------------------------------------------------

    def observe_duty(self, success: bool, tenant: str = "local") -> None:
        self._observe(SLO_DUTY_MISS, tenant, bad=not success)

    def observe_step(self, seconds: float, tenant: str = "local") -> None:
        self._observe(
            SLO_STEP_LATENCY, tenant, bad=seconds > self.step_latency_target
        )

    def _observe(self, slo: str, tenant: str, bad: bool) -> None:
        self._events[(slo, tenant)].append((self._clock(), bool(bad)))

    # -- burn math ---------------------------------------------------------

    def burn_rate(self, slo: str, tenant: str, window: float) -> float:
        """(bad fraction over the trailing window) / budget; 0.0 when
        the window holds fewer than min_events events (no data is not
        an incident)."""
        cfg = self.slos[slo]
        cutoff = self._clock() - window
        events = self._events.get((slo, tenant))
        if not events:
            return 0.0
        total = bad = 0
        for t, is_bad in events:
            if t < cutoff:
                continue
            total += 1
            bad += is_bad
        if total < cfg.min_events:
            return 0.0
        return (bad / total) / cfg.budget

    def budget_remaining(self, slo: str, tenant: str) -> float:
        """Fraction of the slow-window error budget still unspent,
        clamped to [0, 1]. 1.0 with no data."""
        cfg = self.slos[slo]
        burn = self.burn_rate(slo, tenant, cfg.slow_window)
        return max(0.0, min(1.0, 1.0 - burn))

    def tenants(self) -> list[str]:
        return sorted({t for _, t in self._events})

    # -- alerting ----------------------------------------------------------

    def evaluate(self) -> list[dict]:
        """One row per (slo, tenant) with both window burns and the
        firing severity; updates rising-edge alert state (on_alert +
        alerts_total fire here, so call this on a steady cadence)."""
        rows: list[dict] = []
        for slo, cfg in self.slos.items():
            for tenant in self.tenants():
                if (slo, tenant) not in self._events:
                    continue
                fast = self.burn_rate(slo, tenant, cfg.fast_window)
                slow = self.burn_rate(slo, tenant, cfg.slow_window)
                both = min(fast, slow)
                if both >= cfg.page_burn:
                    severity = SEVERITY_CRITICAL
                elif both >= cfg.warn_burn:
                    severity = SEVERITY_WARNING
                else:
                    severity = ""
                prev = self._firing.get((slo, tenant), "")
                self._firing[(slo, tenant)] = severity
                if severity and severity != prev:
                    key = (slo, tenant, severity)
                    self.alerts_total[key] = self.alerts_total.get(key, 0) + 1
                    if self.on_alert is not None:
                        self.on_alert(slo, tenant, severity)
                rows.append(
                    {
                        "slo": slo,
                        "tenant": tenant,
                        "fast_burn": fast,
                        "slow_burn": slow,
                        "budget_remaining": self.budget_remaining(
                            slo, tenant
                        ),
                        "severity": severity,
                    }
                )
        return rows

    def firing(self, slo: str, severity: str = SEVERITY_CRITICAL) -> bool:
        """Any tenant currently firing >= severity for the slo (state
        from the most recent evaluate())."""
        order = {SEVERITY_WARNING: 1, SEVERITY_CRITICAL: 2}
        want = order[severity]
        return any(
            s == slo and order.get(sev, 0) >= want
            for (s, _t), sev in self._firing.items()
        )

    def checks(self) -> list[Check]:
        """HealthChecker integration: a paging duty-miss burn is
        CRITICAL (gates /readyz — the node is actively failing its
        duty objective); step-latency burn warns."""
        return [
            Check(
                "slo_duty_miss_burn",
                "duty-miss error budget burning at paging rate on both "
                "alert windows",
                lambda m, md: self.firing(SLO_DUTY_MISS, SEVERITY_CRITICAL),
                SEVERITY_CRITICAL,
            ),
            Check(
                "slo_step_latency_burn",
                "step-latency error budget burning above warning rate",
                lambda m, md: self.firing(
                    SLO_STEP_LATENCY, SEVERITY_WARNING
                ),
                SEVERITY_WARNING,
            ),
        ]
