"""secp256k1 node identity: sign/verify/serialize.

Mirrors ref: app/k1util — the reference signs QBFT and priority messages
with the node's secp256k1 p2p key. Backed by the `cryptography` library
(ECDSA over SECP256K1, DER signatures normalized to raw 64-byte r||s).
"""

from __future__ import annotations

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)

_CURVE = ec.SECP256K1()
# secp256k1 group order (for low-s normalization).
_ORDER = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def generate_private_key() -> ec.EllipticCurvePrivateKey:
    return ec.generate_private_key(_CURVE)


def private_key_to_bytes(key: ec.EllipticCurvePrivateKey) -> bytes:
    return key.private_numbers().private_value.to_bytes(32, "big")


def private_key_from_bytes(data: bytes) -> ec.EllipticCurvePrivateKey:
    return ec.derive_private_key(int.from_bytes(data, "big"), _CURVE)


def public_key_to_bytes(pub: ec.EllipticCurvePublicKey) -> bytes:
    """33-byte compressed SEC1 encoding (the reference's wire format)."""
    return pub.public_bytes(
        serialization.Encoding.X962,
        serialization.PublicFormat.CompressedPoint,
    )


def public_key_from_bytes(data: bytes) -> ec.EllipticCurvePublicKey:
    return ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, data)


def sign(key: ec.EllipticCurvePrivateKey, digest: bytes) -> bytes:
    """Sign a 32-byte digest; returns raw 64-byte r||s with low s."""
    if len(digest) != 32:
        raise ValueError("sign expects a 32-byte digest")
    der = key.sign(digest, ec.ECDSA(Prehashed(hashes.SHA256())))
    r, s = decode_dss_signature(der)
    if s > _ORDER // 2:
        s = _ORDER - s
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub: ec.EllipticCurvePublicKey, digest: bytes, sig: bytes) -> bool:
    if len(sig) != 64 or len(digest) != 32:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    try:
        der = encode_dss_signature(r, s)
        pub.verify(der, digest, ec.ECDSA(Prehashed(hashes.SHA256())))
        return True
    except Exception:
        return False


def verify_bytes(pubkey_bytes: bytes, digest: bytes, sig: bytes) -> bool:
    try:
        return verify(public_key_from_bytes(pubkey_bytes), digest, sig)
    except Exception:
        return False


def ecdh(key: ec.EllipticCurvePrivateKey, peer_pubkey_bytes: bytes) -> bytes:
    """Static-static ECDH shared secret with a peer's compressed pubkey.

    Used to derive per-connection MAC keys in the p2p handshake (the
    reference gets the same property from libp2p-TLS with pinned peer
    identities, ref: p2p/p2p.go security transport)."""
    peer = public_key_from_bytes(peer_pubkey_bytes)
    return key.exchange(ec.ECDH(), peer)
