"""HTTP beacon-node client: the production upstream connection.

Mirrors ref: the go-eth2-client HTTP service the reference wraps in
app/eth2wrap (eth2wrap.go NewMultiHTTP). Speaks the standard beacon REST
API and maps it onto the framework's duck-typed beacon interface (the
same one BeaconMock implements), so MultiClient/ValidatorCache/fetcher
run unchanged against real infrastructure.

Lazy connections (ref: app/eth2wrap/lazy.go:28): one aiohttp session is
created on first use and re-created after connection errors.
"""

from __future__ import annotations

import asyncio
from typing import Any

import aiohttp

from charon_tpu.core.eth2data import (
    AttestationData,
    Checkpoint,
    Proposal,
    proposal_from_data_json,
    signed_proposal_json,
)
from charon_tpu.eth2util import spec as spec_mod


class HttpError(RuntimeError):
    def __init__(self, status: int, msg: str) -> None:
        super().__init__(msg)
        self.status = status


class NotSyncedError(RuntimeError):
    """Beacon node is still syncing (single-shot probe semantics: the
    scheduler retries — an internal wait loop would starve MultiClient's
    per-call timeout)."""


class Eth2HttpClient:
    def __init__(self, base_url: str, timeout: float = 5.0) -> None:
        self.base_url = base_url.strip().rstrip("/")
        self.timeout = timeout
        self._session: aiohttp.ClientSession | None = None

    # -- lazy session (ref: lazy.go) --------------------------------------

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    async def _get(self, path: str, **params) -> Any:
        try:
            async with self._sess().get(
                self.base_url + path, params=params or None
            ) as resp:
                if resp.status != 200:
                    raise HttpError(
                        resp.status,
                        f"GET {path}: HTTP {resp.status} {await resp.text()}",
                    )
                return await resp.json()
        except (aiohttp.ClientConnectionError, asyncio.TimeoutError):
            await self.close()  # force a fresh connection next call
            raise

    async def _post(self, path: str, body: Any, headers=None) -> Any:
        try:
            async with self._sess().post(
                self.base_url + path, json=body, headers=headers
            ) as resp:
                if resp.status not in (200, 202):
                    raise HttpError(
                        resp.status,
                        f"POST {path}: HTTP {resp.status} {await resp.text()}",
                    )
                if resp.content_type == "application/json":
                    return await resp.json()
                return None
        except (aiohttp.ClientConnectionError, asyncio.TimeoutError):
            await self.close()
            raise

    # -- chain state ------------------------------------------------------

    async def await_synced(self) -> None:
        """Single-shot probe: raises NotSyncedError while syncing — the
        scheduler's startup loop retries (BeaconMock returns instantly)."""
        data = (await self._get("/eth/v1/node/syncing"))["data"]
        if data.get("is_syncing", False):
            raise NotSyncedError(self.base_url)

    async def spec(self) -> dict:
        return (await self._get("/eth/v1/config/spec"))["data"]

    async def genesis(self) -> dict:
        return (await self._get("/eth/v1/beacon/genesis"))["data"]

    # -- duties -----------------------------------------------------------

    async def attester_duties(self, epoch: int, validators: dict) -> list:
        idx_to_pubkey = {v: k for k, v in validators.items()}
        data = (
            await self._post(
                f"/eth/v1/validator/duties/attester/{epoch}",
                [str(i) for i in sorted(idx_to_pubkey)],
            )
        )["data"]
        return [
            dict(
                slot=int(d["slot"]),
                pubkey=idx_to_pubkey[int(d["validator_index"])],
                validator_index=int(d["validator_index"]),
                committee_index=int(d["committee_index"]),
                committee_length=int(d["committee_length"]),
                committees_at_slot=int(d["committees_at_slot"]),
                validator_committee_index=int(
                    d["validator_committee_index"]
                ),
            )
            for d in data
        ]

    async def proposer_duties(self, epoch: int, validators: dict) -> list:
        idx_to_pubkey = {v: k for k, v in validators.items()}
        data = (
            await self._get(f"/eth/v1/validator/duties/proposer/{epoch}")
        )["data"]
        return [
            dict(
                slot=int(d["slot"]),
                pubkey=idx_to_pubkey[int(d["validator_index"])],
                validator_index=int(d["validator_index"]),
            )
            for d in data
            if int(d["validator_index"]) in idx_to_pubkey
        ]

    async def sync_duties(self, epoch: int, validators: dict) -> list:
        idx_to_pubkey = {v: k for k, v in validators.items()}
        data = (
            await self._post(
                f"/eth/v1/validator/duties/sync/{epoch}",
                [str(i) for i in sorted(idx_to_pubkey)],
            )
        )["data"]
        return [
            dict(
                pubkey=idx_to_pubkey[int(d["validator_index"])],
                validator_index=int(d["validator_index"]),
                # real committee positions; the scheduler derives the
                # subcommittee (pos // 128) and in-subcommittee bit
                sync_committee_indices=[
                    int(p)
                    for p in d.get("validator_sync_committee_indices", [0])
                ],
            )
            for d in data
        ]

    # -- duty data --------------------------------------------------------

    async def attestation_data(
        self, slot: int, committee_index: int
    ) -> AttestationData:
        d = (
            await self._get(
                "/eth/v1/validator/attestation_data",
                slot=str(slot),
                committee_index=str(committee_index),
            )
        )["data"]
        return AttestationData(
            slot=int(d["slot"]),
            index=int(d["index"]),
            beacon_block_root=_hx(d["beacon_block_root"]),
            source=Checkpoint(
                int(d["source"]["epoch"]), _hx(d["source"]["root"])
            ),
            target=Checkpoint(
                int(d["target"]["epoch"]), _hx(d["target"]["root"])
            ),
        )

    async def block_proposal(
        self, slot: int, proposer_index: int, randao: bytes
    ) -> Proposal:
        """produceBlockV3: parse the full fork-versioned block container
        (or its blinded variant) from the response; the block root the
        cluster signs is computed with spec SSZ from the complete body
        (eth2util/spec.py), exactly as any consensus client would
        (ref: core/fetcher/fetcher.go fetchProposerData +
        eth2wrap Proposal)."""
        j = await self._get(
            f"/eth/v3/validator/blocks/{slot}",
            randao_reveal="0x" + randao.hex(),
        )
        version = j.get("version", spec_mod.latest_fork())
        blinded = str(j.get("execution_payload_blinded", False)).lower() in (
            "true",
            "1",
        )
        return proposal_from_data_json(version, blinded, j["data"])

    # -- aggregation / sync-committee surfaces ----------------------------

    async def aggregate_attestation(self, slot: int, att_data_root: bytes):
        d = (
            await self._get(
                "/eth/v1/validator/aggregate_attestation",
                slot=str(slot),
                attestation_data_root="0x" + att_data_root.hex(),
            )
        )["data"]
        from charon_tpu.core.eth2data import Attestation

        data = d["data"]
        return Attestation(
            aggregation_bits=_bits(d["aggregation_bits"]),
            data=AttestationData(
                slot=int(data["slot"]),
                index=int(data["index"]),
                beacon_block_root=_hx(data["beacon_block_root"]),
                source=Checkpoint(
                    int(data["source"]["epoch"]),
                    _hx(data["source"]["root"]),
                ),
                target=Checkpoint(
                    int(data["target"]["epoch"]),
                    _hx(data["target"]["root"]),
                ),
            ),
            signature=_hx(d["signature"]),
        )

    async def sync_committee_block_root(self, slot: int) -> bytes:
        d = (await self._get("/eth/v1/beacon/blocks/head/root"))["data"]
        return _hx(d["root"])

    async def sync_contribution(
        self, slot: int, subcommittee_index: int, block_root: bytes
    ):
        d = (
            await self._get(
                "/eth/v1/validator/sync_committee_contribution",
                slot=str(slot),
                subcommittee_index=str(subcommittee_index),
                beacon_block_root="0x" + block_root.hex(),
            )
        )["data"]
        from charon_tpu.core.eth2data import SyncCommitteeContribution

        return SyncCommitteeContribution(
            slot=int(d["slot"]),
            beacon_block_root=_hx(d["beacon_block_root"]),
            subcommittee_index=int(d["subcommittee_index"]),
            aggregation_bits=_bits(d["aggregation_bits"]) or tuple([False] * 128),
        )

    # -- inclusion surface (ref: core/tracker/inclusion.go data needs) ----

    async def block_attestations(self, slot: int):
        try:
            data = (
                await self._get(f"/eth/v1/beacon/blocks/{slot}/attestations")
            )["data"]
        except HttpError as e:
            if e.status == 404:
                return None  # genuinely no block at this slot
            raise  # 5xx etc: a transient failure is NOT "not included"
        from charon_tpu.core.eth2data import Attestation

        out = []
        for a in data:
            d = a["data"]
            out.append(
                Attestation(
                    aggregation_bits=_bits(a["aggregation_bits"]),
                    data=AttestationData(
                        slot=int(d["slot"]),
                        index=int(d["index"]),
                        beacon_block_root=_hx(d["beacon_block_root"]),
                        source=Checkpoint(
                            int(d["source"]["epoch"]),
                            _hx(d["source"]["root"]),
                        ),
                        target=Checkpoint(
                            int(d["target"]["epoch"]),
                            _hx(d["target"]["root"]),
                        ),
                    ),
                    signature=_hx(a["signature"]),
                )
            )
        return out

    async def block_root(self, slot: int):
        try:
            d = (await self._get(f"/eth/v1/beacon/blocks/{slot}/root"))[
                "data"
            ]
            return _hx(d["root"])
        except HttpError as e:
            if e.status == 404:
                return None
            raise

    # -- submissions ------------------------------------------------------

    async def submit_attestation(self, att) -> None:
        await self._post(
            "/eth/v1/beacon/pool/attestations", [_att_json(att)]
        )

    async def submit_proposal(self, proposal, signature: bytes) -> None:
        """publishBlock / publishBlindedBlock (v2, with the
        Eth-Consensus-Version header): the exact SignedBeaconBlock (or
        deneb signed block contents) wire shape a production node
        requires."""
        path = (
            "/eth/v2/beacon/blinded_blocks"
            if proposal.blinded
            else "/eth/v2/beacon/blocks"
        )
        await self._post(
            path,
            signed_proposal_json(proposal, signature),
            headers={"Eth-Consensus-Version": proposal.version},
        )

    async def submit_aggregate(self, agg_and_proof, signature: bytes) -> None:
        agg = agg_and_proof.aggregate
        await self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [
                {
                    "message": {
                        "aggregator_index": str(
                            agg_and_proof.aggregator_index
                        ),
                        "aggregate": _att_json(agg),
                        "selection_proof": "0x"
                        + agg_and_proof.selection_proof.hex(),
                    },
                    "signature": "0x" + signature.hex(),
                }
            ],
        )

    async def submit_sync_message(self, msg) -> None:
        await self._post(
            "/eth/v1/beacon/pool/sync_committees",
            [
                {
                    "slot": str(msg.slot),
                    "beacon_block_root": "0x"
                    + msg.beacon_block_root.hex(),
                    "validator_index": str(msg.validator_index),
                    "signature": "0x" + msg.signature.hex(),
                }
            ],
        )

    async def submit_contribution(
        self, contrib_and_proof, signature: bytes
    ) -> None:
        c = contrib_and_proof.contribution
        await self._post(
            "/eth/v1/validator/contribution_and_proofs",
            [
                {
                    "message": {
                        "aggregator_index": str(
                            contrib_and_proof.aggregator_index
                        ),
                        "contribution": {
                            "slot": str(c.slot),
                            "beacon_block_root": "0x"
                            + c.beacon_block_root.hex(),
                            "subcommittee_index": str(
                                c.subcommittee_index
                            ),
                            "aggregation_bits": _bits_hex_vector(
                                c.aggregation_bits
                            ),
                            "signature": "0x"
                            + getattr(c, "signature", b"").hex(),
                        },
                        "selection_proof": "0x"
                        + contrib_and_proof.selection_proof.hex(),
                    },
                    "signature": "0x" + signature.hex(),
                }
            ],
        )

    async def submit_exit(self, exit_msg, signature: bytes) -> None:
        await self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            {
                "message": {
                    "epoch": str(exit_msg.epoch),
                    "validator_index": str(exit_msg.validator_index),
                },
                "signature": "0x" + signature.hex(),
            },
        )

    async def submit_registration(self, reg, signature: bytes) -> None:
        await self._post(
            "/eth/v1/validator/register_validator",
            [
                {
                    "message": {
                        "fee_recipient": "0x"
                        + getattr(reg, "fee_recipient", b"").hex(),
                        "gas_limit": str(getattr(reg, "gas_limit", 0)),
                        "timestamp": str(getattr(reg, "timestamp", 0)),
                        "pubkey": "0x" + getattr(reg, "pubkey", b"").hex(),
                    },
                    "signature": "0x" + signature.hex(),
                }
            ],
        )


def _hx(s: str) -> bytes:
    return bytes.fromhex(s.removeprefix("0x"))


def _bits(hex_bitlist: str) -> tuple[bool, ...]:
    raw = _hx(hex_bitlist)
    bits = []
    for byte in raw:
        for i in range(8):
            bits.append(bool(byte & (1 << i)))
    # strip the SSZ length marker (highest set bit)
    while bits and not bits[-1]:
        bits.pop()
    if bits:
        bits.pop()  # the marker itself
    return tuple(bits)


def _att_json(att) -> dict:
    d = att.data
    return {
        "aggregation_bits": _bits_hex(att.aggregation_bits),
        "data": {
            "slot": str(d.slot),
            "index": str(d.index),
            "beacon_block_root": "0x" + d.beacon_block_root.hex(),
            "source": {
                "epoch": str(d.source.epoch),
                "root": "0x" + d.source.root.hex(),
            },
            "target": {
                "epoch": str(d.target.epoch),
                "root": "0x" + d.target.root.hex(),
            },
        },
        "signature": "0x" + att.signature.hex(),
    }


def _bits_hex(bits) -> str:
    marked = list(bits) + [True]  # SSZ bitlist length marker
    raw = bytearray((len(marked) + 7) // 8)
    for i, bit in enumerate(marked):
        if bit:
            raw[i // 8] |= 1 << (i % 8)
    return "0x" + bytes(raw).hex()


def _bits_hex_vector(bits) -> str:
    """Fixed-size bitvector encoding (no length marker) — sync-committee
    contribution aggregation bits."""
    raw = bytearray((len(bits) + 7) // 8)
    for i, bit in enumerate(bits):
        if bit:
            raw[i // 8] |= 1 << (i % 8)
    return "0x" + bytes(raw).hex()


