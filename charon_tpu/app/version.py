"""Version identity + peer compatibility negotiation.

Mirrors ref: app/version — the reference advertises its semantic version
through peerinfo and refuses protocol interaction with peers outside the
supported minor-version window (version.Supported()). Peerinfo wires
check_compatible() and surfaces incompatible peers to the operator.
"""

from __future__ import annotations

VERSION = "0.2.0"

# Minor versions this build interoperates with (ref: version.Supported
# returns the current and previous minors).
SUPPORTED_MINORS = ("0.2", "0.1")


def minor(version: str) -> str:
    parts = str(version).split(".")
    return ".".join(parts[:2]) if len(parts) >= 2 else str(version)


def check_compatible(peer_version) -> bool:
    """True when the peer's minor version is in our supported window.
    Tolerates untrusted/untyped wire input (coerced to str)."""
    return minor(peer_version) in SUPPORTED_MINORS
