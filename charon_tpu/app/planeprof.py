"""Crypto-plane profiler: decompose flush `device_span` into per-kernel-
family time (ISSUE 19 tentpole, consumer (a) of the flight recorder's
hook spine).

`SlotCryptoPlane.on_program` (parallel/mesh.py) times every compiled-
program dispatch — family names match `kernel_families()` /
`core.cryptoplane.kernel_inventory()` ("mesh/verify_rlc", "mesh/step",
...) and each sample includes the result sync, so samples between two
FlushStats deliveries account for (approximately) that flush's
`device_span`. This module correlates the two streams:

  * the program hook buffers (family, seconds, lanes) samples — called
    on the coalescer's serialized device worker thread;
  * the stats hook (chained into the existing stats_hook pipeline)
    drains the buffer at each FlushStats and attributes the samples to
    that flush, exporting:
      - `tpu_plane_kernel_seconds_total{family}` (on_sample callback),
      - `tpu_plane_device_utilization` — device busy fraction over a
        rolling window (on_utilization callback),
      - `tpu_plane_tenant_device_seconds_total{tenant}` — device_span
        split by `FlushStats.tenant_lanes` share (on_tenant callback).

Planes without the packed on_program hook (SimHostPlane, host tbls
rungs) still profile: a flush arriving with no buffered samples
attributes its whole device_span to the synthetic family "device", so
the per-family sum equals device_span exactly on jax-free paths and
utilization stays truthful everywhere.

Pure stdlib, jax-free (app-layer rule); overhead per flush is one lock
round-trip and a few dict updates — bench_hostplane.py --profiler holds
this within the 5% gate.
"""

from __future__ import annotations

import threading
import time
from collections import deque

# synthetic family for flushes served by planes without program hooks
FALLBACK_FAMILY = "device"

DEFAULT_WINDOW = 60.0


class PlaneProfiler:
    """Correlates mesh program samples with FlushStats deliveries.

    Callbacks (all optional, all fired on the device worker thread —
    prometheus client objects are thread-safe):
      on_sample(family, seconds)       one per drained program sample
      on_tenant(tenant, seconds)       per-flush tenant device share
      on_utilization(fraction)         rolling busy/window after a flush
    """

    def __init__(
        self,
        window: float = DEFAULT_WINDOW,
        on_sample=None,
        on_tenant=None,
        on_utilization=None,
        clock=time.monotonic,
    ) -> None:
        if window <= 0:
            raise ValueError(f"profiler window must be > 0, got {window}")
        self.window = window
        self.on_sample = on_sample
        self.on_tenant = on_tenant
        self.on_utilization = on_utilization
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: list[tuple[str, float, int]] = []
        self._busy: deque[tuple[float, float]] = deque()
        # cumulative totals (scenario tests + /debug introspection)
        self.kernel_seconds: dict[str, float] = {}
        self.kernel_calls: dict[str, int] = {}
        self.tenant_seconds: dict[str, float] = {}
        self.flushes = 0
        self.utilization = 0.0

    # -- producers ---------------------------------------------------------

    def program_hook(self):
        """The `SlotCryptoPlane.on_program` callable: buffer one timed
        program dispatch until the flush's FlushStats arrives."""

        def hook(family: str, seconds: float, lanes: int) -> None:
            with self._lock:
                self._pending.append((family, float(seconds), int(lanes)))

        return hook

    def stats_hook(self, inner=None):
        """Chain into the coalescer's stats_hook pipeline: profile the
        flush, then pass FlushStats on unchanged."""

        def hook(stats) -> None:
            try:
                self.observe_flush(stats)
            except Exception:  # noqa: BLE001 — profiling must never fail a flush
                pass
            if inner is not None:
                inner(stats)

        return hook

    # -- core --------------------------------------------------------------

    def observe_flush(self, stats) -> None:
        """Attribute everything sampled since the previous flush to this
        FlushStats. Runs on the serialized device worker thread, so the
        drained samples are exactly this flush's program dispatches."""
        span = getattr(stats, "device_span", None)
        device_s = max(0.0, span[1] - span[0]) if span else 0.0
        with self._lock:
            samples, self._pending = self._pending, []
        if not samples and device_s > 0.0:
            # hook-less plane (SimHostPlane, host rungs): the whole span
            # is one opaque device dispatch
            samples = [(FALLBACK_FAMILY, device_s, getattr(stats, "lanes", 0))]
        for family, seconds, _lanes in samples:
            self.kernel_seconds[family] = (
                self.kernel_seconds.get(family, 0.0) + seconds
            )
            self.kernel_calls[family] = self.kernel_calls.get(family, 0) + 1
            if self.on_sample is not None:
                self.on_sample(family, seconds)
        self.flushes += 1
        # tenant attribution: split device_span by live-lane share
        tenant_lanes = tuple(getattr(stats, "tenant_lanes", ()) or ())
        total = sum(lanes for _, lanes in tenant_lanes)
        if device_s > 0.0 and total > 0:
            for tenant, lanes in tenant_lanes:
                share = device_s * lanes / total
                self.tenant_seconds[tenant] = (
                    self.tenant_seconds.get(tenant, 0.0) + share
                )
                if self.on_tenant is not None:
                    self.on_tenant(tenant, share)
        # rolling duty cycle: busy seconds over the trailing window
        now = self._clock()
        busy = self._busy
        busy.append((now, device_s))
        while busy and busy[0][0] < now - self.window:
            busy.popleft()
        self.utilization = min(
            1.0, sum(s for _, s in busy) / self.window
        )
        if self.on_utilization is not None:
            self.on_utilization(self.utilization)

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """Cumulative per-family/per-tenant totals + current duty cycle
        (served under /debug/flight?view=profile)."""
        with self._lock:
            pending = len(self._pending)
        return {
            "kernel_seconds": {
                k: round(v, 6) for k, v in sorted(self.kernel_seconds.items())
            },
            "kernel_calls": dict(sorted(self.kernel_calls.items())),
            "tenant_seconds": {
                k: round(v, 6) for k, v in sorted(self.tenant_seconds.items())
            },
            "flushes": self.flushes,
            "utilization": round(self.utilization, 4),
            "pending_samples": pending,
        }
