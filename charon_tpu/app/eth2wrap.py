"""Multi beacon-node client: failover, instrumentation, validator cache.

Mirrors ref: app/eth2wrap — the multi-client races/falls back across
beacon nodes (eth2wrap/multi.go:21-100), instruments latency and errors
(eth2wrap_gen.go), lazily reconnects (lazy.go:28), and caches the active
validator set per epoch (valcache.go). Duck-typed over any object exposing
the beacon interface (testutil.BeaconMock or an HTTP client).
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Any, Sequence

from charon_tpu.app.errors import StructuredError


class AllClientsFailedError(StructuredError):
    """Every configured beacon client failed the call; fields carry the
    endpoint and per-client errors (ref: app/errors at the BN boundary)."""


_METHODS = (
    "await_synced",
    "attester_duties",
    "proposer_duties",
    "sync_duties",
    "attestation_data",
    "aggregate_attestation",
    "block_proposal",
    "sync_committee_block_root",
    "sync_contribution",
    "block_attestations",
    "block_root",
    "submit_attestation",
    "submit_aggregate",
    "submit_sync_message",
    "submit_contribution",
    "submit_proposal",
    "submit_registration",
    "submit_exit",
)


class MultiClient:
    """Try each client in best-first order; first success wins.

    Best-client selection (ref: multi.go picks the best client
    adaptively): clients are ordered by recent ERROR count first, then
    by rolling median LATENCY — a healthy-but-slow fallback BN stops
    being primary as soon as the fast one recovers, and duty-critical
    calls (attestation data at ⅓ slot) ride the fastest healthy node."""

    LATENCY_WINDOW = 64
    # hedge dispatch: when the best client has not answered within
    # HEDGE_FACTOR x its rolling-median latency (floored at HEDGE_MIN,
    # so a cold cache cannot hedge instantly), the runner-up is raced
    # and the first success wins — a stalled primary BN then costs one
    # median-latency wait, not a full `timeout`
    HEDGE_FACTOR = 2.0
    HEDGE_MIN = 0.05

    def __init__(
        self,
        clients: Sequence[Any],
        timeout: float = 5.0,
        hedge: bool = True,
    ) -> None:
        from collections import deque

        if not clients:
            raise ValueError("need at least one beacon client")
        self.clients = list(clients)
        self.timeout = timeout
        self.hedge = hedge and len(self.clients) >= 2
        self.hedged_total = 0  # hedges dispatched
        self.hedge_wins = 0  # hedges that answered first
        self.latencies: dict[str, list[float]] = defaultdict(list)
        self.errors: dict[int, int] = defaultdict(int)
        # rolling per-client latency window for the selection heuristic
        self.client_latency: dict[int, Any] = {
            i: deque(maxlen=self.LATENCY_WINDOW)
            for i in range(len(self.clients))
        }

    def _median_latency(self, i: int) -> float:
        import statistics

        window = self.client_latency[i]
        # untried clients get a chance at the front
        return statistics.median_high(window) if window else 0.0

    def best_order(self) -> list[int]:
        return sorted(
            range(len(self.clients)),
            key=lambda i: (self.errors[i], self._median_latency(i)),
        )

    @property
    def best_idx(self) -> int:
        return self.best_order()[0]

    async def _call_one(self, i: int, name: str, args, kwargs):
        client = self.clients[i]
        t0 = time.monotonic()
        result = await asyncio.wait_for(
            getattr(client, name)(*args, **kwargs), self.timeout
        )
        elapsed = time.monotonic() - t0
        self.latencies[name].append(elapsed)
        self.client_latency[i].append(elapsed)
        self.errors[i] = max(0, self.errors[i] - 1)
        return result

    def _hedge_delay(self, i: int) -> float | None:
        """Seconds to wait before racing the runner-up, or None when the
        primary has no latency history yet (an untried client gets one
        un-hedged sample first — hedging on zero data would double every
        call's load)."""
        window = self.client_latency[i]
        if not self.hedge or not window:
            return None
        return max(self._median_latency(i) * self.HEDGE_FACTOR, self.HEDGE_MIN)

    async def _hedged_pair(self, first: int, second: int, name: str, args, kwargs):
        """Race primary vs runner-up: runner-up starts only after the
        hedge delay elapses with the primary still pending (ref:
        multi.go's best-client race, plus the classic tail-latency hedge).
        Returns (ok, result, errs, failed) — ok is the explicit success
        flag because most beacon methods legitimately return None, and
        `failed` are the indices that ran and failed."""
        errs: list[str] = []
        failed: set[int] = set()
        race: set = set()
        primary = asyncio.ensure_future(
            self._call_one(first, name, args, kwargs)
        )
        race.add(primary)
        try:
            done, _ = await asyncio.wait(
                {primary}, timeout=self._hedge_delay(first)
            )
            if done:
                try:
                    return True, primary.result(), errs, failed
                except Exception as e:  # noqa: BLE001 — fails over
                    self.errors[first] += 1
                    errs.append(f"client{first}: {e!r}")
                    return False, None, errs, {first}
            self.hedged_total += 1
            hedge = asyncio.ensure_future(
                self._call_one(second, name, args, kwargs)
            )
            race.add(hedge)
            pending = set(race)
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    race.discard(task)
                    exc = task.exception()
                    if exc is None:
                        if task is hedge:
                            self.hedge_wins += 1
                        return True, task.result(), errs, failed
                    idx = first if task is primary else second
                    self.errors[idx] += 1
                    failed.add(idx)
                    errs.append(f"client{idx}: {exc!r}")
            return False, None, errs, failed
        finally:
            # cancel losers AND in-flight calls on external cancellation
            # (a duty-deadline cancel mid-hedge must not leave a submit
            # landing at the BN after the tracker reported the miss)
            for task in race:
                if not task.done():
                    task.cancel()

    def __getattr__(self, name: str):
        if name not in _METHODS:
            raise AttributeError(name)

        async def call(*args, **kwargs):
            errs: list[str] = []
            tried: set[int] = set()
            order = self.best_order()
            # best two ride the hedge; the race resolves stalls, the
            # sequential tail below resolves hard failures
            if len(order) >= 2 and self._hedge_delay(order[0]) is not None:
                ok, result, errs, tried = await self._hedged_pair(
                    order[0], order[1], name, args, kwargs
                )
                if ok:
                    return result
            for i in order:
                if i in tried:
                    continue
                try:
                    return await self._call_one(i, name, args, kwargs)
                except Exception as e:  # noqa: BLE001 — any failure fails over
                    self.errors[i] += 1
                    errs.append(f"client{i}: {e!r}")
            raise AllClientsFailedError(
                "all beacon clients failed",
                endpoint=name,
                errors="; ".join(errs),
            )

        return call


class InstrumentedClient:
    """Per-endpoint latency/error instrumentation around a beacon client
    (ref: app/eth2wrap/eth2wrap_gen.go wraps every generated method with
    latency() + incError(); metrics app_eth2_latency_seconds /
    app_eth2_errors_total in docs/metrics.md).

    `metrics` is a ClusterMetrics (app/metrics.py); falls back to local
    in-memory tallies when None so tests can instrument without a
    registry."""

    def __init__(self, client: Any, metrics=None, name: str = "beacon") -> None:
        from collections import deque

        self._client = client
        self._metrics = metrics
        self._name = name
        # bounded: full history lives in the Prometheus histogram; this
        # window only serves in-process diagnostics
        self.latency: dict[str, deque] = defaultdict(
            lambda: deque(maxlen=1024)
        )
        self.error_count: dict[str, int] = defaultdict(int)

    def __getattr__(self, name: str):
        inner = getattr(self._client, name)
        if not callable(inner) or name.startswith("_"):
            return inner

        async def call(*args, **kwargs):
            t0 = time.monotonic()
            try:
                result = await inner(*args, **kwargs)
            except BaseException:
                # BaseException: asyncio.CancelledError (e.g. the enclosing
                # MultiClient's wait_for timing this BN out) must count as
                # an error too, or a hung BN shows perfectly healthy metrics
                self.error_count[name] += 1
                if self._metrics is not None:
                    self._metrics.labels(
                        self._metrics.eth2_errors, self._name, name
                    ).inc()
                raise
            elapsed = time.monotonic() - t0
            self.latency[name].append(elapsed)
            if self._metrics is not None:
                self._metrics.labels(
                    self._metrics.eth2_latency, self._name, name
                ).observe(elapsed)
            return result

        return call


class LazyClient:
    """Connect-on-first-use beacon client with reconnect-on-failure
    (ref: app/eth2wrap/lazy.go:28 — the lazy client defers dialing the BN
    until the first call and rebuilds the underlying client when a call
    fails, so charon starts cleanly while its BN is still syncing/down).

    `factory` is an async callable returning a connected client. After a
    call fails the cached client is dropped; the next call redials with
    exponential backoff bounded by `max_backoff`."""

    def __init__(self, factory, max_backoff: float = 30.0) -> None:
        self._factory = factory
        self._client: Any = None
        self._lock = asyncio.Lock()
        self._backoff = ExpBackoff(max_delay=max_backoff)

    async def _get(self):
        async with self._lock:
            if self._client is None:
                await self._backoff.wait()
                self._client = await self._factory()
                self._backoff.reset()
            return self._client

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def call(*args, **kwargs):
            client = await self._get()
            try:
                return await getattr(client, name)(*args, **kwargs)
            except Exception:
                async with self._lock:
                    if self._client is client:  # drop the broken client
                        self._client = None
                raise

        return call


# Canonical home is the dedicated util module (ref:
# app/expbackoff/expbackoff.go); re-exported here for existing importers.
from charon_tpu.app.expbackoff import ExpBackoff  # noqa: E402


SYNTH_GRAFFITI = b"charon-tpu-synthetic"


class SyntheticProposerClient:
    """Synthetic block-proposal duties for idle validators
    (ref: app/eth2wrap/synthproposer.go — WithSyntheticDuties fabricates
    deterministic proposer duties for validators that have none in an
    epoch, serves marker-graffiti blocks for them, and swallows their
    submission so the whole proposer pipeline is exercised in testing
    without hitting the chain).

    Deterministic assignment: validator v proposes at slot
    epoch_start + (stable_hash(pubkey, epoch) % SLOTS_PER_EPOCH)."""

    def __init__(self, client: Any, slots_per_epoch: int = 32) -> None:
        self._client = client
        self.slots_per_epoch = slots_per_epoch
        self.synthetic_submitted = 0
        # epoch -> slots WE fabricated duties for; trimmed so a
        # long-lived node doesn't accumulate past epochs forever
        self._synth_by_epoch: dict[int, set[int]] = {}

    def _synth_slot(self, epoch: int, pubkey: bytes) -> int:
        import hashlib

        h = hashlib.sha256(b"synth-proposer" + pubkey + epoch.to_bytes(8, "big"))
        return epoch * self.slots_per_epoch + (
            int.from_bytes(h.digest()[:4], "big") % self.slots_per_epoch
        )

    async def proposer_duties(self, epoch: int, validators):
        real = list(await self._client.proposer_duties(epoch, validators))
        have = {d.get("pubkey") if isinstance(d, dict) else d[0] for d in real}
        # validators: mapping pubkey -> validator index (the shape the
        # scheduler passes), or a plain pubkey sequence in tests
        items = (
            validators.items()
            if isinstance(validators, dict)
            else [(v, i) for i, v in enumerate(validators)]
        )
        slots = self._synth_by_epoch.setdefault(epoch, set())
        # keep a small window of epochs (proposals only query duties
        # around the current epoch)
        for old in [e for e in self._synth_by_epoch if e < epoch - 2]:
            del self._synth_by_epoch[old]
        for pk, vidx in items:
            if pk in have:
                continue
            raw = pk if isinstance(pk, bytes) else str(pk).encode()
            slot = self._synth_slot(epoch, raw)
            slots.add(slot)
            real.append(
                {
                    "pubkey": pk,
                    "slot": slot,
                    "validator_index": vidx,
                    "synthetic": True,
                }
            )
        return real

    async def block_proposal(self, slot: int, *args, randao_reveal=None, graffiti=None, **kw):
        if any(slot in s for s in self._synth_by_epoch.values()):
            # ONLY slots we fabricated duties for get synthetic blocks; a
            # transient BN failure on a real duty must propagate so the
            # retryer can re-fetch it (ref: synthproposer.go consults its
            # own duty cache before synthesizing)
            return {
                "slot": slot,
                "graffiti": SYNTH_GRAFFITI.hex(),
                "synthetic": True,
                "body": {"randao_reveal": randao_reveal},
            }
        return await self._client.block_proposal(
            slot, *args, randao_reveal=randao_reveal, graffiti=graffiti, **kw
        )

    async def submit_proposal(self, signed_block, *a, **kw):
        block = getattr(signed_block, "message", signed_block)
        if isinstance(block, dict) and (
            block.get("synthetic")
            or block.get("graffiti") == SYNTH_GRAFFITI.hex()
        ):
            self.synthetic_submitted += 1  # swallowed, never broadcast
            return None
        return await self._client.submit_proposal(signed_block, *a, **kw)

    def __getattr__(self, name):
        return getattr(self._client, name)


class ValidatorCache:
    """Per-epoch cache of duty queries (ref: eth2wrap/valcache.go)."""

    def __init__(self, beacon) -> None:
        self.beacon = beacon
        self._cache: dict[tuple, object] = {}

    async def attester_duties(self, epoch: int, validators):
        key = ("att", epoch, tuple(sorted(validators)))
        if key not in self._cache:
            self._cache[key] = await self.beacon.attester_duties(
                epoch, validators
            )
        return self._cache[key]

    async def proposer_duties(self, epoch: int, validators):
        key = ("prop", epoch, tuple(sorted(validators)))
        if key not in self._cache:
            self._cache[key] = await self.beacon.proposer_duties(
                epoch, validators
            )
        return self._cache[key]

    def trim(self, before_epoch: int) -> None:
        self._cache = {
            k: v for k, v in self._cache.items() if k[1] >= before_epoch
        }

    def __getattr__(self, name):
        return getattr(self.beacon, name)
