"""Multi beacon-node client: failover, instrumentation, validator cache.

Mirrors ref: app/eth2wrap — the multi-client races/falls back across
beacon nodes (eth2wrap/multi.go:21-100), instruments latency and errors
(eth2wrap_gen.go), lazily reconnects (lazy.go:28), and caches the active
validator set per epoch (valcache.go). Duck-typed over any object exposing
the beacon interface (testutil.BeaconMock or an HTTP client).
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Any, Sequence


class AllClientsFailedError(Exception):
    pass


_METHODS = (
    "await_synced",
    "attester_duties",
    "proposer_duties",
    "sync_duties",
    "attestation_data",
    "aggregate_attestation",
    "block_proposal",
    "sync_committee_block_root",
    "sync_contribution",
    "block_attestations",
    "block_root",
    "submit_attestation",
    "submit_aggregate",
    "submit_sync_message",
    "submit_contribution",
    "submit_proposal",
    "submit_registration",
    "submit_exit",
)


class MultiClient:
    """Try each client in order; first success wins. The best (lowest
    error count) client is promoted to primary (ref: multi.go picks the
    best client adaptively)."""

    def __init__(self, clients: Sequence[Any], timeout: float = 5.0) -> None:
        if not clients:
            raise ValueError("need at least one beacon client")
        self.clients = list(clients)
        self.timeout = timeout
        self.latencies: dict[str, list[float]] = defaultdict(list)
        self.errors: dict[int, int] = defaultdict(int)

    def __getattr__(self, name: str):
        if name not in _METHODS:
            raise AttributeError(name)

        async def call(*args, **kwargs):
            errs = []
            # order clients by recent error count (stable for ties)
            order = sorted(
                range(len(self.clients)), key=lambda i: self.errors[i]
            )
            for i in order:
                client = self.clients[i]
                t0 = time.monotonic()
                try:
                    result = await asyncio.wait_for(
                        getattr(client, name)(*args, **kwargs), self.timeout
                    )
                    self.latencies[name].append(time.monotonic() - t0)
                    self.errors[i] = max(0, self.errors[i] - 1)
                    return result
                except Exception as e:  # noqa: BLE001 — any failure fails over
                    self.errors[i] += 1
                    errs.append(f"client{i}: {e!r}")
            raise AllClientsFailedError("; ".join(errs))

        return call


class ValidatorCache:
    """Per-epoch cache of duty queries (ref: eth2wrap/valcache.go)."""

    def __init__(self, beacon) -> None:
        self.beacon = beacon
        self._cache: dict[tuple, object] = {}

    async def attester_duties(self, epoch: int, validators):
        key = ("att", epoch, tuple(sorted(validators)))
        if key not in self._cache:
            self._cache[key] = await self.beacon.attester_duties(
                epoch, validators
            )
        return self._cache[key]

    async def proposer_duties(self, epoch: int, validators):
        key = ("prop", epoch, tuple(sorted(validators)))
        if key not in self._cache:
            self._cache[key] = await self.beacon.proposer_duties(
                epoch, validators
            )
        return self._cache[key]

    def trim(self, before_epoch: int) -> None:
        self._cache = {
            k: v for k, v in self._cache.items() if k[1] >= before_epoch
        }

    def __getattr__(self, name):
        return getattr(self.beacon, name)
