"""Env/flag-gated fault-injection registry for the production wiring.

The chaos injectors live in `testutil/chaos.py`; this registry is the
ONLY way production code reaches them. The contract is strict
inertness: unless the `CHARON_TPU_FAULT_INJECTION` env var or the
`--fault-injection` run flag carries a spec, `active()` is False, every
`wrap_*` returns its argument unchanged, and no wrapper object (nor the
chaos module itself) is ever constructed/imported — the un-instrumented
duty path pays zero overhead.

Spec syntax (also accepted by the CLI flag):

    CHARON_TPU_FAULT_INJECTION="seed=42,drop=0.1,bn_error=0.2"

Keys are `testutil.chaos.ChaosConfig` fields; a bare "1"/"on" installs
the wrappers with all-zero rates (useful to measure wrapper overhead).
The same seed replays the same fault schedule (see ChaosConfig.stream).
"""

from __future__ import annotations

import os

ENV_VAR = "CHARON_TPU_FAULT_INJECTION"

_plane = None  # FaultPlane | None — module-global, like featureset


class FaultPlane:
    """Bound chaos config + lazily-built injectors for one process."""

    def __init__(self, config) -> None:
        self.config = config
        # built on first use so an inert-but-installed plane still
        # constructs nothing it does not need
        self._partitioner = None

    @property
    def partitioner(self):
        if self._partitioner is None:
            from charon_tpu.testutil.chaos import Partitioner

            self._partitioner = Partitioner()
        return self._partitioner

    def wrap_beacon(self, beacon):
        from charon_tpu.testutil.chaos import ChaosBeacon

        return ChaosBeacon(beacon, self.config)

    def wrap_tbls(self, impl):
        from charon_tpu.testutil.chaos import FlakyBackend

        if (
            not self.config.crypto_fail_rate
            and self.config.crypto_fail_after is None
        ):
            return impl
        return FlakyBackend(impl, self.config)

    def wrap_p2p_node(self, node):
        from charon_tpu.testutil.chaos import chaos_p2p_node

        chaos_p2p_node(node, self.config)
        return node


def active() -> bool:
    return _plane is not None


def plane() -> FaultPlane | None:
    return _plane


def install(config) -> FaultPlane:
    """Install a plane for this process (config: ChaosConfig or spec
    string). Tests and cmd_run call this; everything else only reads."""
    global _plane
    if isinstance(config, str):
        from charon_tpu.testutil.chaos import config_from_spec

        config = config_from_spec(config)
    _plane = FaultPlane(config)
    return _plane


def uninstall() -> None:
    global _plane
    _plane = None


def init_from_env(environ=None) -> bool:
    """Install from CHARON_TPU_FAULT_INJECTION when set. Returns whether
    a plane is now active. Called once from app startup; the spec parse
    fails fast on typos (a chaos run that silently injects nothing is
    worse than a crash)."""
    spec = (environ if environ is not None else os.environ).get(ENV_VAR, "")
    if not spec:
        return False
    install(spec)
    return True


# Convenience pass-throughs: call sites stay one-liners and, when the
# plane is inert, these are attribute-check cheap with no allocation.


def maybe_wrap_beacon(beacon):
    return _plane.wrap_beacon(beacon) if _plane is not None else beacon


def maybe_wrap_tbls(impl):
    return _plane.wrap_tbls(impl) if _plane is not None else impl


def maybe_wrap_p2p_node(node):
    return _plane.wrap_p2p_node(node) if _plane is not None else node
