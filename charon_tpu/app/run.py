"""Application wiring: config -> running node.

Mirrors ref: app/app.go:131 Run — load the cluster lock, derive key maps,
start p2p, monitoring, the core workflow (wire()), and the lifecycle
manager. Every component is the production one; test configs swap the
beacon client for a mock and transports for in-memory fakes
(ref: app/app.go TestConfig pattern).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass, field
from pathlib import Path

from charon_tpu import tbls
from charon_tpu.app import featureset, k1util, log, tracer
from charon_tpu.app.eth2wrap import (
    InstrumentedClient,
    MultiClient,
    SyntheticProposerClient,
    ValidatorCache,
)
from charon_tpu.app.lifecycle import LifecycleManager, Order
from charon_tpu.app.metrics import ClusterMetrics, instrument, serve_monitoring
from charon_tpu.cluster.lock import ClusterLock
from charon_tpu.core.aggsigdb import new_agg_sigdb
from charon_tpu.core.bcast import Broadcaster
from charon_tpu.core.consensus import ConsensusController
from charon_tpu.core.consensus_qbft import QBFTConsensus
from charon_tpu.core.deadline import Deadliner, SlotClock
from charon_tpu.core.dutydb import DutyDB
from charon_tpu.core.fetcher import Fetcher
from charon_tpu.core.inclusion import InclusionChecker, InclusionReport
from charon_tpu.core.parsigdb import ParSigDB
from charon_tpu.core.parsigex import DutyGater, Eth2Verifier, ParSigEx
from charon_tpu.core.scheduler import Scheduler
from charon_tpu.core.sigagg import SigAgg
from charon_tpu.core.tracker import Tracker, tracking
from charon_tpu.core.types import DutyType, PubKey, pubkey_from_bytes
from charon_tpu.core.validatorapi import ValidatorAPI
from charon_tpu.core.vapi_http import VapiRouter
from charon_tpu.core.wire import tracing, wire
from charon_tpu.eth2util import enr, keystore
from charon_tpu.eth2util.signing import ForkInfo
from charon_tpu.p2p.adapters import TcpParSigTransport, TcpQbftNet
from charon_tpu.p2p.transport import P2PNode, PeerSpec


@dataclass
class Config:
    """ref: app/app.go:70-99 Config."""

    data_dir: str
    node_index: int  # 0-based operator index
    p2p_host: str = "127.0.0.1"
    p2p_port: int = 0
    relay_addr: str = ""  # host:port of a charon-tpu relay (NAT fallback)
    validator_api_port: int = 0
    monitoring_port: int = 0
    peer_addrs: list[tuple[str, int]] = field(default_factory=list)
    beacon_nodes: list = field(default_factory=list)  # client objects
    beacon_urls: list[str] = field(default_factory=list)  # HTTP endpoints
    simnet: bool = False
    simnet_vmock: bool = True  # in-process VC in simnet (ref: app/vmock.go)
    slot_duration: float = 12.0
    slots_per_epoch: int = 32
    genesis_time: float | None = None
    use_tpu_tbls: bool = True
    # sharded crypto plane over the visible device mesh: "auto" installs
    # it when >= 2 devices are visible (single-chip keeps the cheaper
    # single-device TPUImpl path), "on" forces it, "off" disables
    crypto_plane: str = "auto"
    crypto_plane_window: float = 0.02  # base coalescing window, seconds
    # adaptive window bounds: grows toward max under sustained load,
    # duty deadlines shrink it down to min (core/cryptoplane)
    crypto_plane_window_min: float = 0.002
    crypto_plane_window_max: float = 0.08
    # decode/pack pool size; 0 disables the pipelined host plane (decode
    # runs synchronously on the event loop — the pre-pipeline path)
    crypto_plane_decode_workers: int = 4
    # startup compile of the canonical duty shapes: "auto" pre-warms
    # on a real accelerator backend OR when the kernel auto-tuner left
    # a warm artifact story behind (valid tuned profile + a prewarm
    # that COMPLETED once under the same kernel sources, recorded by
    # autotune.mark_prewarmed — prewarm then costs cache loads, not
    # minutes-long compiles); "on" forces, "off" disables
    crypto_plane_prewarm: str = "auto"
    # startup kernel auto-tune (core/autotune, ISSUE 18): "auto" loads
    # the persisted per-platform profile (or micro-benches + persists
    # one on first boot) and degrades to KernelConfig defaults on any
    # failure; "on" is auto but refuses hosts without the device
    # stack; "force" always re-benches; "off" applies defaults + the
    # deprecated CHARON_* env overrides only
    crypto_autotune: str = "auto"
    # persisted kernel-profile path; "" = next to the jit cache
    # (jaxcache.py placement rules: host-fingerprinted CPU dirs, one
    # shared TPU dir)
    crypto_autotune_profile: str = ""
    # bulk point-cache warm-up at startup (ISSUE 6): decode every
    # cluster pubshare/group key through the batched device kernels so
    # the first live slot starts at a warm cache instead of paying a
    # python-bigint burst; "auto" warms on real accelerator backends
    # only, "on" forces (python rung on CPU), "off" disables. The same
    # path re-runs at validator-set rotation (Node.rewarm_point_caches).
    crypto_plane_warmup: str = "auto"
    # signature-decode rung (ISSUE 5): "device" batches compressed-point
    # decompression into the flush programs (ops/decompress.py),
    # "python" keeps the host bigint decode, "auto" resolves to device
    # on TPU backends only — python remains the degradation rung below
    crypto_plane_decode: str = "auto"
    # OTLP/HTTP collector for workflow spans (ref: --jaeger-address,
    # app/app.go:1014-1027 wireTracing); "" disables export
    tracing_endpoint: str = ""
    # per-node span JSONL export path; per-node files from a cluster
    # merge offline into one cross-node timeline (tracer.merge_jsonl —
    # the deterministic duty trace ids make the merge trivial)
    tracing_jsonl: str = ""
    # seeded fault-injection spec ("seed=42,drop=0.1,bn_error=0.2"; see
    # app/faultinject + testutil/chaos). "" keeps the plane inert: no
    # wrapper objects are constructed on the un-instrumented path.
    fault_injection: str = ""
    # multi-tenant crypto-plane service (ISSUE 8, core/cryptosvc): the
    # node registers its cluster as a tenant of the (possibly shared)
    # device plane; quotas bound the damage any one tenant can do to
    # the others. "" = tenant id defaults to the cluster name.
    crypto_tenant: str = ""
    crypto_tenant_weight: float = 1.0  # share of the per-round budget
    crypto_tenant_queue_jobs: int = 256  # admission bound (submissions)
    crypto_tenant_queue_lanes: int = 4096  # admission bound (lanes)
    crypto_plane_round_lanes: int = 4096  # total admission per round
    crypto_breaker_threshold: float = 0.5  # failed ratio that opens
    crypto_breaker_cooldown: float = 5.0  # seconds open -> half-open
    # networked crypto plane (ISSUE 17, core/cryptosvc_client): dial a
    # remote CryptoServiceServer at "host:port". The remote service is
    # a rung ABOVE the local plane: any remote failure (refused
    # connect, heartbeat miss, mid-flush socket death, malformed frame,
    # shed) degrades the affected jobs down the local tbls ladder —
    # never a single point of failure. "" keeps everything in-process.
    crypto_remote: str = ""
    # tenant auth token for the remote service handshake. repr=False:
    # the token must never reach logs, reprs or metrics labels
    # (analysis/rule_secret_flow enforces this).
    crypto_remote_token: str = field(default="", repr=False)
    # serve THIS node's CryptoPlaneService over TCP so other clusters
    # can share the device mesh (core/cryptosvc_server). None = off;
    # 0 = ephemeral port (resolved at start, Node.crypto_server.port).
    crypto_serve: int | None = None
    crypto_serve_host: str = "127.0.0.1"
    # tenant_id -> auth token for dialing clusters (repr=False: secret)
    crypto_serve_tokens: dict = field(default_factory=dict, repr=False)
    # flight recorder (ISSUE 19): always-on bounded per-category ring;
    # dumps land in flight_dump_dir on SIGTERM / unhandled crash / clean
    # stop for post-mortem merge (`charon-tpu flight merge`). 0 disables
    # (harnesses that build many throwaway nodes).
    flight_capacity: int = 512
    flight_dump_dir: str = ""  # "" = <data_dir>/flightrec
    # stack-sniping scan cadence (app/stacksnipe); 0 disables
    stacksnipe_interval: float = 600.0


@dataclass
class Node:
    """A fully wired node (returned by build_node for tests/CLI)."""

    config: Config
    lock: ClusterLock
    life: LifecycleManager
    scheduler: Scheduler
    vapi: ValidatorAPI
    vapi_router: VapiRouter
    p2p: P2PNode | None
    bcast: Broadcaster
    tracker: Tracker
    metrics: ClusterMetrics
    beacon: object
    sigagg: SigAgg | None = None
    crypto_plane: object | None = None  # core.cryptoplane.SlotCoalescer
    crypto_svc: object | None = None  # core.cryptosvc.CryptoPlaneService
    crypto_remote_plane: object | None = None  # cryptosvc_client.RemotePlane
    crypto_server: object | None = None  # cryptosvc_server.CryptoServiceServer
    inclusion: InclusionChecker | None = None
    flightrec: object | None = None  # app/flightrec.FlightRecorder
    profiler: object | None = None  # app/planeprof.PlaneProfiler
    slo: object | None = None  # app/health.SLOEngine
    # the live pubshare registry (shared with Eth2Verifier/ValidatorAPI
    # by reference) — apply_reshare rotates it in place
    pubshares_by_idx: dict | None = None

    async def apply_reshare(
        self, new_pubshares_by_idx: dict, kind: str = "rotate"
    ) -> dict:
        """Rotate the live pubshare registry after a completed resharing
        ceremony (dkg/reshare) and re-warm the point caches for the
        delta only. The registry dicts are shared by reference with
        Eth2Verifier and ValidatorAPI, so the in-place update takes
        effect on the next partial-signature verification — partials
        signed with pre-reshare shares stop verifying from that moment
        (the stale-share unusability property). Returns the warm-up
        stats dict; already-cached pubshares cost zero lanes."""
        if self.pubshares_by_idx is None:
            raise RuntimeError("node was built without a pubshare registry")
        delta: list[bytes] = []
        for idx, shares in new_pubshares_by_idx.items():
            reg = self.pubshares_by_idx.setdefault(idx, {})
            for gpk, pub in shares.items():
                if reg.get(gpk) != pub:
                    delta.append(pub)
                reg[gpk] = pub
        stats = await self.rewarm_point_caches(pubkeys=delta)
        self.metrics.observe_reshare(
            kind,
            "ok",
            validators=max(
                (len(s) for s in new_pubshares_by_idx.values()), default=0
            ),
        )
        return stats

    async def rewarm_point_caches(
        self, pubkeys=(), messages=()
    ) -> dict:
        """Validator-set rotation hook (ISSUE 6): bulk-warm the point
        caches for a new key/message set BEFORE the next slot's flush,
        through the coalescer's sharded warm programs when a crypto
        plane is installed (single-chip nodes fall back to the
        BlsEngine bulk path, off the event loop). Idempotent: already-
        cached keys are skipped, so calling this on every rotation
        costs only the delta. Device failures mid-pass step the warm
        down to the host rung (python lanes in the stats), never
        exceptions."""
        return await _warm_point_caches(
            self.crypto_plane, self.metrics, pubkeys, messages
        )


async def _warm_point_caches(
    crypto_plane, metrics: ClusterMetrics, pubkeys=(), messages=()
) -> dict:
    """The ONE warm dispatch both the startup lifecycle hook and
    Node.rewarm_point_caches ride: coalescer warm programs when a plane
    is installed (it fires its own warmup_hook), else the BlsEngine
    bulk path off the event loop with metrics recorded here."""
    if crypto_plane is not None and hasattr(crypto_plane, "warm_caches"):
        return await crypto_plane.warm_caches(
            pubkeys=pubkeys, messages=messages
        )
    import asyncio as _asyncio

    from charon_tpu.tbls import tpu_impl

    stats = await _asyncio.get_running_loop().run_in_executor(
        None,
        lambda: tpu_impl.warm_point_caches(
            pubkeys=list(pubkeys), messages=list(messages)
        ),
    )
    metrics.observe_warmup(stats)
    return stats


def _resilient_ladder(primary):
    """Wrap the chosen tbls backend in the degradation ladder: primary
    -> native C++ (when available and not already primary) -> pure-
    python spec. A backend ERROR (wedged device, native crash) then
    costs latency on the lower rung instead of the duty; verdicts
    (TblsError) pass through untouched. The fault-injection plane's
    crypto faults wrap the primary so chaos runs exercise the ladder."""
    from charon_tpu.app import faultinject
    from charon_tpu.tbls.python_impl import PythonImpl
    from charon_tpu.tbls.resilient import ResilientImpl

    rungs = [faultinject.maybe_wrap_tbls(primary)]
    if type(primary).__name__ != "NativeImpl":
        try:
            from charon_tpu.tbls.native_impl import NativeImpl

            rungs.append(NativeImpl())
        except Exception:  # noqa: BLE001 — native rung is optional
            pass
    rungs.append(PythonImpl())
    return ResilientImpl(rungs)


async def build_node(config: Config) -> Node:
    data_dir = Path(config.data_dir)
    # manifest mutation-DAG takes precedence over the plain lock
    # (ref: app/app.go:166 loadClusterManifest)
    from charon_tpu.cluster.manifest import load_cluster_state

    lock = load_cluster_state(data_dir)
    n = len(lock.definition.operators)
    t = lock.definition.threshold
    share_idx = config.node_index + 1

    # fault-injection plane (inert unless the flag/env carries a spec):
    # installed FIRST so every boundary below can be wrapped
    from charon_tpu.app import faultinject

    if config.fault_injection:
        faultinject.install(config.fault_injection)
        log.warn(
            "fault injection ACTIVE",
            topic="app",
            spec=config.fault_injection,
        )
    else:
        faultinject.init_from_env()

    # plane profiler (ISSUE 19): constructed before the crypto plane so
    # the plane factory can install its per-program timing hook; the
    # metric callbacks attach once the catalogue exists below
    from charon_tpu.app.planeprof import PlaneProfiler

    profiler = PlaneProfiler()

    crypto_plane = None
    crypto_svc = None
    tenant_plane = None  # the handle components hold (core/cryptosvc)
    remote_plane = None  # cryptosvc_client.RemotePlane when configured
    crypto_server = None  # cryptosvc_server.CryptoServiceServer
    if config.use_tpu_tbls:
        from charon_tpu.tbls.tpu_impl import TPUImpl

        tbls.set_implementation(
            _resilient_ladder(TPUImpl(decode_mode=config.crypto_plane_decode))
        )
        # persistent compile-cache placement for the node process (the
        # AOT artifact story — core/autotune + jaxcache): must be set
        # before the first compilation; idempotent under test harnesses
        # that already configured it (tests/conftest.py)
        import jax as _jax_mod

        from charon_tpu import jaxcache as _jaxcache

        _jaxcache.configure(
            _jax_mod, cpu=_jax_mod.default_backend() == "cpu"
        )
        if config.crypto_plane != "off":
            import jax

            n_devices = len(jax.devices())
            if config.crypto_plane == "on" or n_devices >= 2:
                # route the core workflow's batch crypto through the
                # sharded slot plane: one coalesced device program per
                # window across ALL concurrent duties (SURVEY §7 step 4)
                from charon_tpu.core.cryptoplane import SlotCoalescer
                from charon_tpu.parallel import SlotCryptoPlane, make_mesh

                def plane_factory():
                    p = SlotCryptoPlane(make_mesh(jax.devices()), t=t)
                    # per-program timing feeds the kernel-family
                    # decomposition of every flush's device_span
                    p.on_program = profiler.program_hook()
                    return p

                crypto_plane = SlotCoalescer(
                    plane_factory(),
                    window=config.crypto_plane_window,
                    plane_factory=plane_factory,
                    window_min=config.crypto_plane_window_min,
                    window_max=config.crypto_plane_window_max,
                    decode_workers=config.crypto_plane_decode_workers,
                    decode_mode=config.crypto_plane_decode,
                )
                log.info(
                    "crypto plane installed",
                    topic="app",
                    devices=n_devices,
                    window=config.crypto_plane_window,
                    decode_workers=config.crypto_plane_decode_workers,
                    decode_mode=config.crypto_plane_decode,
                )
    else:
        # host path: prefer the native C++ backend — pure-Python pairing
        # (~0.3 s/verify) stalls the event loop for whole slots
        try:
            from charon_tpu.tbls.native_impl import NativeImpl

            tbls.set_implementation(_resilient_ladder(NativeImpl()))
        except Exception as e:
            log.warn(
                "native tbls backend unavailable; pure-python crypto",
                topic="app",
                err=str(e),
            )

    # -- key material -----------------------------------------------------
    share_secrets = keystore.load_keys(data_dir / "validator_keys")
    group_pubkeys = [
        pubkey_from_bytes(bytes.fromhex(v.distributed_public_key[2:]))
        for v in lock.validators
    ]
    share_keys = dict(zip(group_pubkeys, share_secrets))
    pubshares_by_idx: dict[int, dict[PubKey, bytes]] = {
        j: {} for j in range(1, n + 1)
    }
    for v, gpk in zip(lock.validators, group_pubkeys):
        for j in range(1, n + 1):
            pubshares_by_idx[j][gpk] = bytes.fromhex(v.public_shares[j - 1][2:])
    validators = {gpk: i for i, gpk in enumerate(group_pubkeys)}

    k1_key = k1util.private_key_from_bytes(
        (data_dir / "charon-enr-private-key").read_bytes()
    )

    fork = lock.fork_info()

    # -- metrics ----------------------------------------------------------
    metrics = ClusterMetrics(
        cluster_hash="0x" + lock.lock_hash().hex()[:16],
        cluster_name=lock.definition.name,
        peer=f"node{config.node_index}",
    )

    # -- flight recorder + SLO engine (ISSUE 19) --------------------------
    # The recorder is the post-mortem spine: every observer chain below
    # records into it FIRST, then forwards to the existing metrics hook.
    from charon_tpu.app import flightrec as flightrec_mod
    from charon_tpu.app.health import SLOEngine

    flight = None
    flight_dump_dir = (
        Path(config.flight_dump_dir)
        if config.flight_dump_dir
        else data_dir / "flightrec"
    )
    if config.flight_capacity > 0:
        flight = flightrec_mod.FlightRecorder(
            capacity=config.flight_capacity,
            node=f"node{config.node_index}",
            observer=metrics.flightrec_hook(),
        )
        flight.record("lifecycle", "start", node_index=config.node_index)
    (
        profiler.on_sample,
        profiler.on_tenant,
        profiler.on_utilization,
    ) = metrics.profiler_hooks()
    # duty-miss + step-latency error budgets with multi-window burn-rate
    # alerting; tenant identity matches the crypto-plane tenant so the
    # SLO series line up with the plane attribution families
    slo_tenant = config.crypto_tenant or lock.definition.name
    slo = SLOEngine(on_alert=metrics.slo_alert_hook())
    # sampled by the health loop into the plane health-check series
    plane_health = {"quarantines": 0, "autotune_fallback": 0}

    # -- tracing ----------------------------------------------------------
    # installed BEFORE the workflow wires so every span — including those
    # recorded during component construction — lands in this node's
    # tracer (ref: app/app.go:162 wireTracing runs first)
    otlp = None
    if config.tracing_endpoint:
        otlp = tracer.OTLPExporter(
            config.tracing_endpoint,
            service_name=f"charon-tpu-node{config.node_index}",
        )
    if otlp is not None or config.tracing_jsonl:
        tracer.set_global_tracer(
            tracer.Tracer(
                jsonl_path=config.tracing_jsonl or None, exporter=otlp
            )
        )
    node_tracer = tracer.global_tracer()
    # span ends feed the per-step latency histograms and the slow-duty
    # detector (finalized at duty expiry, below)
    from charon_tpu.app.metrics import SlowDutyDetector, span_metrics

    slow_detector = SlowDutyDetector(metrics)

    def _slo_span(span) -> None:
        # every finished workflow-step span feeds the step-latency SLO
        # (same series the step-latency histogram observes; shared
        # plane-bridge copies are skipped for the same reason)
        if span.attrs.get("shared"):
            return
        slo.observe_step(max(0.0, span.end - span.start), tenant=slo_tenant)

    # keep handles so shutdown can unhook: node_tracer may be the
    # process-global tracer (default build), and a later build_node in
    # the same process must not feed spans into THIS node's registry
    _node_hooks = [span_metrics(metrics), slow_detector.observe, _slo_span]
    node_tracer.hooks.extend(_node_hooks)
    if crypto_plane is not None:
        # one rich per-flush stats hook (runs on the device worker
        # thread — prometheus client objects are thread-safe)
        def _plane_stats(s) -> None:  # chained behind the span bridge
            metrics.labels(metrics.plane_flushes).inc()
            if s.jobs >= 2:
                metrics.labels(metrics.plane_coalesced).inc()
            metrics.labels(metrics.plane_lanes).inc(s.lanes)
            metrics.labels(metrics.plane_flush_seconds).observe(
                s.flush_seconds
            )
            metrics.labels(metrics.plane_lanes_per_flush).observe(s.lanes)
            for q in s.decode_queue_seconds:
                metrics.labels(metrics.plane_decode_queue_seconds).observe(q)
            if s.padded_lanes:
                metrics.labels(metrics.plane_pad_waste).set(
                    s.pad_lanes / s.padded_lanes
                )
            metrics.labels(metrics.plane_inflight).set(s.inflight)
            if s.inflight >= 2:
                metrics.labels(metrics.plane_overlapped).inc()
            # decode-source breakdown (ISSUE 5): cache lookups vs
            # device-decompressed vs host-decoded signature lanes
            for source, count in (
                ("cache", s.decode_cache_lanes),
                ("device", s.decode_device_lanes),
                ("python", s.decode_python_lanes),
            ):
                if count:
                    metrics.labels(
                        metrics.plane_decode_lanes, source
                    ).inc(count)
            metrics.labels(metrics.plane_decode_mode).set(
                1 if s.decode_mode == "device" else 0
            )
            # per-tenant flush attribution (ISSUE 8)
            for tenant, lanes in s.tenant_lanes:
                if lanes:
                    metrics.labels(
                        metrics.plane_tenant_lanes, tenant
                    ).inc(lanes)

        # bridge each flush's decode/pack/device stages into tracer
        # spans joined to the duty traces that rode the flush (ISSUE 4
        # replaces cryptoplane's old trace=True tuples with this);
        # the profiler attributes the buffered per-program samples to
        # this flush, and the flight recorder logs the flush summary —
        # all on the serialized device worker thread
        _stats_chain = profiler.stats_hook(
            inner=tracer.plane_span_bridge(node_tracer, inner_hook=_plane_stats)
        )
        if flight is not None:
            _stats_chain = flightrec_mod.stats_hook(flight, inner=_stats_chain)
        crypto_plane.stats_hook = _stats_chain
        # bulk warm-up passes (startup + rotation) land in the
        # cold-start metric families (ISSUE 6)
        crypto_plane.warmup_hook = metrics.observe_warmup

        # multi-tenant service boundary (ISSUE 8): components below
        # hold a TenantPlane handle, never the raw coalescer — the
        # service adds admission control, deadline-aware fair
        # scheduling and the per-tenant forged-flood breaker in front
        # of the shared coalescing window
        from charon_tpu.core.cryptosvc import (
            CryptoPlaneService,
            TenantQuota,
        )

        tenant_id = config.crypto_tenant or lock.definition.name
        tenant_obs = metrics.tenant_hook()
        if flight is not None:
            tenant_obs = flightrec_mod.tenant_hook(flight, inner=tenant_obs)
        crypto_svc = CryptoPlaneService(
            crypto_plane,
            round_lanes=config.crypto_plane_round_lanes,
            observer=tenant_obs,
        )
        tenant_plane = crypto_svc.register(
            tenant_id,
            TenantQuota(
                weight=config.crypto_tenant_weight,
                max_queue_jobs=config.crypto_tenant_queue_jobs,
                max_queue_lanes=config.crypto_tenant_queue_lanes,
                breaker_threshold=config.crypto_breaker_threshold,
                breaker_cooldown=config.crypto_breaker_cooldown,
            ),
        )
        log.info(
            "crypto plane tenant registered",
            topic="app",
            tenant=tenant_id,
            queue_lanes=config.crypto_tenant_queue_lanes,
            round_lanes=config.crypto_plane_round_lanes,
        )

        # networked crypto plane (ISSUE 17): dial a shared remote
        # service; the just-registered local tenant plane becomes the
        # always-available rung below. The same span bridge that feeds
        # local FlushStats into duty traces receives the remote
        # attribution briefs (rebased onto this host's clock), so
        # operators see one consistent trace either way.
        if config.crypto_remote:
            from charon_tpu.core.cryptosvc_client import RemotePlane

            r_host, _, r_port = config.crypto_remote.rpartition(":")
            remote_obs = metrics.remote_hook(tenant_id)
            if flight is not None:
                # addr names the dialed server in the ring: a merged
                # post-mortem attributes a failover to the exact
                # aborted endpoint
                remote_obs = flightrec_mod.remote_hook(
                    flight,
                    tenant_id,
                    addr=f"{r_host or '127.0.0.1'}:{int(r_port)}",
                    inner=remote_obs,
                )
            remote_plane = RemotePlane(
                r_host or "127.0.0.1",
                int(r_port),
                tenant_id,
                config.crypto_remote_token,
                local=tenant_plane,
                observer=remote_obs,
                stats_hook=crypto_plane.stats_hook,
            )
            tenant_plane = remote_plane
            log.info(
                "remote crypto plane configured",
                topic="app",
                addr=remote_plane.addr,
                tenant=tenant_id,
            )

        # expose this node's service to other clusters (the serving
        # side of the same topology; tenants register with default
        # quotas on start unless pre-registered above)
        if config.crypto_serve is not None:
            from charon_tpu.core.cryptosvc_server import (
                CryptoServiceServer,
            )

            crypto_server = CryptoServiceServer(
                crypto_svc,
                config.crypto_serve_tokens,
                host=config.crypto_serve_host,
                port=config.crypto_serve,
                register_tenants=True,
                observer=(
                    flightrec_mod.server_hook(flight)
                    if flight is not None
                    else None
                ),
            )

    # -- beacon client ----------------------------------------------------
    import time as _time

    http_clients = []
    if config.beacon_urls and not config.beacon_nodes:
        from charon_tpu.app.eth2http import Eth2HttpClient

        http_clients = [Eth2HttpClient(url) for url in config.beacon_urls]
        config.beacon_nodes = list(http_clients)
        # derive chain timing from the node itself unless configured
        # (ref: app/app.go:754 uses Spec()/genesis from the BN)
        for client in http_clients:
            try:
                if config.genesis_time is None:
                    genesis = await client.genesis()
                    config.genesis_time = float(genesis["genesis_time"])
                spec = await client.spec()
                config.slot_duration = float(
                    spec.get("SECONDS_PER_SLOT", config.slot_duration)
                )
                config.slots_per_epoch = int(
                    spec.get("SLOTS_PER_EPOCH", config.slots_per_epoch)
                )
                break
            except Exception as e:
                log.warn(
                    "failed to fetch chain spec from beacon node",
                    topic="app",
                    url=client.base_url,
                    err=str(e),
                )
        if config.genesis_time is None:
            raise RuntimeError(
                "could not determine genesis time from any beacon node; "
                "pass --genesis-time"
            )
    if config.simnet or not config.beacon_nodes:
        from charon_tpu.testutil.beaconmock import BeaconMock

        beacon = BeaconMock(
            validators=validators,
            genesis_time=(
                config.genesis_time
                if config.genesis_time is not None
                else _time.time()
            ),
            slot_duration=config.slot_duration,
            slots_per_epoch=config.slots_per_epoch,
        )
        clock = beacon.clock()
    else:
        # each BN gets latency/error instrumentation before the failover
        # multi-client (ref: app/eth2wrap Instrument + NewMultiHTTP)
        instrumented = [
            InstrumentedClient(c, metrics, name=f"bn{i}")
            for i, c in enumerate(config.beacon_nodes)
        ]
        beacon = ValidatorCache(MultiClient(instrumented))
        clock = SlotClock(config.genesis_time or 0.0, config.slot_duration)
    if featureset.enabled(featureset.Feature.SYNTHETIC_DUTIES):
        # fabricate proposer duties for idle validators so the proposal
        # pipeline is exercised (ref: eth2wrap.WithSyntheticDuties)
        beacon = SyntheticProposerClient(
            beacon, slots_per_epoch=config.slots_per_epoch
        )
    # outermost so every component sees the injected faults (inert
    # no-op returning `beacon` unchanged unless the plane is active)
    beacon = faultinject.maybe_wrap_beacon(beacon)

    # -- lifecycle ---------------------------------------------------------
    life = LifecycleManager()
    if http_clients:

        async def close_clients():
            for client in http_clients:
                await client.close()

        life.register_stop(Order.P2P, "beacon-http", close_clients)

    # -- p2p --------------------------------------------------------------
    p2p_node = None
    qbft_net = None
    parsig_transport = None
    if config.peer_addrs:
        specs = []
        for i, (host, port) in enumerate(config.peer_addrs):
            # operator ENR field carries the k1 pubkey hex in this format
            pub = enr.pubkey_from_string(lock.definition.operators[i].enr)
            specs.append(PeerSpec(index=i, pubkey=pub, host=host, port=port))
        relay_client = None
        if config.relay_addr:
            # NAT fallback: unreachable peers are dialed through the
            # relay with the same end-to-end handshake (ref:
            # app/app.go:307-356 wires relays into the libp2p host)
            from charon_tpu.p2p.relay import RelayClient

            rhost, rport = config.relay_addr.rsplit(":", 1)
            relay_client = RelayClient(
                rhost, int(rport), lock.lock_hash(), config.node_index
            )
        p2p_node = P2PNode(
            config.node_index, k1_key, specs, lock.lock_hash(),
            relay=relay_client,
        )
        # wire codec observability (ISSUE 7): per-frame encode/decode
        # seconds + byte volume by codec (binary vs json fallback)
        p2p_node.wire_observer = metrics.wire_hook()
        # per-peer codec quarantine mutes (ISSUE 8 satellite); counted
        # for the peer_quarantine_active health check and recorded in
        # the flight ring
        _q_metrics = metrics.peer_quarantine_hook()

        def _q_obs(peer_idx, mute_seconds):
            plane_health["quarantines"] += 1
            _q_metrics(peer_idx, mute_seconds)

        p2p_node.quarantine_observer = (
            flightrec_mod.quarantine_hook(flight, inner=_q_obs)
            if flight is not None
            else _q_obs
        )
        await p2p_node.start()
        # frame-level faults on the live mesh (inert no-op by default)
        faultinject.maybe_wrap_p2p_node(p2p_node)
        qbft_net = TcpQbftNet(p2p_node)
        parsig_transport = TcpParSigTransport(p2p_node)
        life.register_stop(Order.P2P, "p2p", p2p_node.stop)

        # peer metadata + version-compat monitoring (ref: app/app.go:299)
        from charon_tpu.app import version as version_mod
        from charon_tpu.app.peerinfo import PeerInfoService

        peerinfo = PeerInfoService(p2p_node, version_mod.VERSION)
        peerinfo.start()

        async def stop_peerinfo():
            peerinfo.stop()

        life.register_stop(Order.P2P, "peerinfo", stop_peerinfo)
    else:
        # single-node / in-memory configurations (tests wire their own)
        from charon_tpu.core.consensus_qbft import MemMsgNet
        from charon_tpu.core.parsigex import MemTransport

        qbft_net = MemMsgNet()
        parsig_transport = MemTransport()

    # -- core workflow ----------------------------------------------------
    # Byzantine-evidence ledger (ISSUE 16): every attributed detection
    # across qbft / parsigex / parsigdb increments
    # byzantine_evidence_total{peer,kind}, and equivocation-class
    # evidence excludes the peer's lanes from sigagg recombination.
    from charon_tpu.core.evidence import EvidenceRegistry

    byz_hook = metrics.byzantine_hook()
    if flight is not None:
        # the flightrec adapter takes the 3-arg form: the registry
        # passes the free-text detail through to the ring
        byz_hook = flightrec_mod.byzantine_hook(flight, inner=byz_hook)
    evidence = EvidenceRegistry(hook=byz_hook)
    dutydb = DutyDB()
    parsigdb = ParSigDB(threshold=t, evidence=evidence)
    sigagg = SigAgg(
        threshold=t,
        fork=fork,
        slots_per_epoch=config.slots_per_epoch,
        plane=tenant_plane,
        pubshares_by_idx=pubshares_by_idx if tenant_plane else None,
        clock=clock if tenant_plane else None,
        evidence=evidence,
    )
    # impl selected by the AGG_SIG_DB_V2 feature flag (ref: app wiring
    # gates memory_v2 behind the alpha flag)
    aggsigdb = new_agg_sigdb()
    bcast = Broadcaster(beacon=beacon, clock=clock)
    # lock-file registrations re-broadcast every epoch by the recaster
    # (ref: app/app.go:676-743 wireRecaster pre-generate path)
    bcast.load_pregen_registrations(lock.validators)
    fetcher = Fetcher(beacon)
    # Per-message k1 auth: every consensus message (and each piggybacked
    # justification) is signed/verified against the operators' keys
    # (ref: core/consensus/qbft/transport.go:25-50, qbft.go:561).
    op_pubkeys = [
        enr.pubkey_from_string(op.enr)
        for op in lock.definition.operators
    ]
    duty_gater = DutyGater(clock, slots_per_epoch=config.slots_per_epoch)
    qbft_consensus = QBFTConsensus(
        qbft_net,
        n,
        privkey=k1_key,
        pubkeys=op_pubkeys,
        gater=duty_gater,
        evidence=evidence,
    )
    consensus = ConsensusController(qbft_consensus)

    def _consensus_stats(s):
        d = str(s["duty"].type.name).lower()
        metrics.labels(
            metrics.consensus_decided_rounds, d, s["timer"]
        ).set(s["round"])
        metrics.labels(
            metrics.consensus_duration, d, s["timer"]
        ).set(s["duration"])

    qbft_consensus.on_decided_stats = _consensus_stats
    if flight is not None:
        # round changes are the consensus-stall signature a post-mortem
        # looks for first
        qbft_consensus.on_round_change = flightrec_mod.consensus_hook(flight)
    vapi = ValidatorAPI(
        share_idx=share_idx,
        pubshares=pubshares_by_idx[share_idx],
        fork=fork,
        slots_per_epoch=config.slots_per_epoch,
        plane=tenant_plane,
    )
    verifier = Eth2Verifier(
        fork,
        pubshares_by_idx,
        config.slots_per_epoch,
        plane=tenant_plane,
        clock=clock if tenant_plane else None,
    )
    parsigex = ParSigEx(
        share_idx,
        parsig_transport,
        verifier,
        gater=duty_gater,
        evidence=evidence,
    )
    scheduler = Scheduler(
        beacon,
        clock,
        validators,
        slots_per_epoch=config.slots_per_epoch,
    )
    tracker = Tracker(
        peer_share_indices=list(range(1, n + 1)), threshold=t
    )

    wire(
        scheduler=scheduler,
        fetcher=fetcher,
        consensus=consensus,
        dutydb=dutydb,
        validatorapi=vapi,
        parsigdb=parsigdb,
        parsigex=parsigex,
        sigagg=sigagg,
        aggsigdb=aggsigdb,
        broadcaster=bcast,
        options=[tracking(tracker), tracing(node_tracer), instrument(metrics)],
    )

    # tracker reports -> metrics: failures, participation counts,
    # inconsistent partials, unexpected peers (ref: core/tracker
    # newFailedDutyReporter / newParticipationReporter / reportParSigs)
    def _report_metrics(report):
        d = str(report.duty.type.name).lower()
        if not report.success and report.failed_step is not None:
            metrics.labels(
                metrics.tracker_failed, d, str(report.failed_step)
            ).inc()
        if report.inconsistent_pubkeys:
            metrics.labels(metrics.tracker_inconsistent, d).inc()
        for share, cnt in report.participation_counts.items():
            metrics.labels(
                metrics.tracker_participation, d, str(share)
            ).inc(cnt)
        for share, cnt in report.unexpected_shares.items():
            metrics.labels(metrics.tracker_unexpected, str(share)).inc(cnt)
            log.warn(
                "unexpected peer participation",
                topic="tracker",
                duty=str(report.duty),
                peer_share=share,
                count=cnt,
            )
        for pk, why in report.failed_pubkeys.items():
            metrics.labels(
                metrics.tracker_failed_validators, d, why.value
            ).inc()
            log.warn(
                "validator failed to assemble threshold partials",
                topic="tracker",
                duty=str(report.duty),
                pubkey=str(pk)[:18],
                reason=why.value,
            )

    tracker.subscribe(_report_metrics)
    if flight is not None:
        tracker.subscribe(flightrec_mod.duty_hook(flight))

    def _slo_duty(report):
        slo.observe_duty(report.success, tenant=slo_tenant)

    tracker.subscribe(_slo_duty)

    # deadliner trims stores + triggers tracker analysis; the slow-duty
    # detector settles each duty's traced wall time against its budget
    # (deadline minus slot start) at the same expiry point
    deadliner = Deadliner(
        clock,
        _make_expiry(
            dutydb,
            parsigdb,
            aggsigdb,
            tracker,
            qbft_consensus,
            slow_detector=slow_detector,
            clock=clock,
        ),
    )
    scheduler.subscribe_duties(_register_deadline(deadliner))
    # recaster: re-broadcast VC + lock-file registrations once per epoch
    # (ref: app/app.go:676-743 wireRecaster subscribes to slots)
    scheduler.subscribe_slots(bcast.recast)

    # priority/infosync: negotiate the cluster-wide protocol preference
    # at each epoch edge over the p2p mesh and switch the consensus
    # implementation to the cluster's top choice (ref: core/priority +
    # core/infosync, wiring app/app.go:610-668)
    if p2p_node is not None:
        from charon_tpu.core.priority import (
            InfoSync,
            P2PPriorityExchange,
            Prioritiser,
            protocol_switcher,
        )

        from charon_tpu.app import version as version_mod

        from charon_tpu.core.priority import order_protocol_prefs

        prio_exchange = P2PPriorityExchange(p2p_node)

        def _protocol_prefs() -> list[str]:
            # v1.1+ definitions carry an operator-signed cluster-level
            # protocol preference that outranks the node default
            return order_protocol_prefs(
                [p.protocol_id for p in consensus.registered()],
                getattr(lock.definition, "consensus_protocol", ""),
            )

        prioritiser = Prioritiser(
            # the scheduler never emits INFO_SYNC, so the Prioritiser
            # itself registers its duty for expiry — consensus instance,
            # tracker events, and stores all trim on the normal path
            on_duty_done=deadliner.add,
            node_idx=share_idx,
            quorum=t,
            exchange=prio_exchange.exchange,
            consensus=consensus,
            topics_fn=lambda: {
                InfoSync.TOPIC_PROTOCOL: _protocol_prefs(),
                InfoSync.TOPIC_VERSION: [version_mod.VERSION],
            },
        )
        prioritiser.subscribe(protocol_switcher(consensus))
        infosync = InfoSync(prioritiser)
        scheduler.subscribe_slots(infosync.on_slot)

    # inclusion checker: broadcast duties must land on-chain within 32
    # slots (ref: core/tracker/inclusion.go, wiring app/app.go:746-780)
    inclusion = None
    if hasattr(beacon, "block_attestations"):
        inclusion = InclusionChecker(
            beacon, on_report=_log_inclusion, clock=clock
        )
        bcast.subscribe(inclusion.submitted)
        scheduler.subscribe_slots(inclusion.on_slot)
        # feed results back into the tracker's chain-inclusion step
        # counters and the metrics catalogue
        # (ref: app/app.go:562 wires track.InclusionChecked)
        def _on_inclusion(r):
            tracker.inclusion_checked(r.duty, r.pubkey, r.included)
            metrics.labels(
                metrics.inclusion_checked,
                str(r.duty.type.name).lower(),
                "included" if r.included else "missed",
            ).inc()
            if r.included:
                metrics.labels(metrics.inclusion_delay).set(r.delay_slots)

        inclusion.subscribe(_on_inclusion)

    # in-process validator client for simnet runs (ref: app/vmock.go —
    # the reference wires validatormock when --simnet-validator-mock)
    if config.simnet and config.simnet_vmock:
        from charon_tpu.core.types import DutyType
        from charon_tpu.testutil.validatormock import ValidatorMock

        vmock = ValidatorMock(
            vapi=vapi,
            share_keys=share_keys,
            fork=fork,
            slots_per_epoch=config.slots_per_epoch,
        )

        # keep strong refs to fire-and-forget proposer tasks and surface
        # their failures (asyncio holds tasks weakly)
        vmock_tasks: set[asyncio.Task] = set()

        def _spawn(coro, what: str) -> None:
            task = asyncio.create_task(coro)
            vmock_tasks.add(task)

            def done(t: asyncio.Task) -> None:
                vmock_tasks.discard(t)
                if not t.cancelled() and t.exception() is not None:
                    log.error(
                        "vmock duty failed",
                        topic="vmock",
                        exc=t.exception(),
                        duty=what,
                    )

            task.add_done_callback(done)

        async def on_duty(duty, defs):
            if duty.type == DutyType.ATTESTER:
                await vmock.attest(duty.slot, defs)
            elif duty.type == DutyType.PROPOSER:
                for pubkey in defs:
                    _spawn(vmock.propose(duty.slot, pubkey), str(duty))

        scheduler.subscribe_duties(on_duty)

    vapi_router = VapiRouter(
        vapi,
        beacon=beacon,
        validators=validators,
        genesis_time=config.genesis_time or 0.0,
        slots_per_epoch=config.slots_per_epoch,
        slot_duration=config.slot_duration,
        clock=clock,
    )
    if config.beacon_urls:
        # unmatched VC requests forward to the first beacon endpoint
        # (ref: router.go proxyHandler)
        vapi_router.proxy_url = config.beacon_urls[0]

    # -- lifecycle hooks --------------------------------------------------
    async def start_vapi():
        port = await vapi_router.start("127.0.0.1", config.validator_api_port)
        log.info("validator api listening", topic="vapi", port=port)

    life.register_start(Order.VALIDATOR_API, "vapi", start_vapi, background=False)
    life.register_stop(Order.VALIDATOR_API, "vapi", vapi_router.stop)
    life.register_start(
        Order.DEADLINER,
        "deadliner",
        _async_noop(deadliner.start),
        background=False,
    )
    life.register_stop(Order.DEADLINER, "deadliner", deadliner.stop)
    life.register_start(Order.SCHEDULER, "scheduler", scheduler.run)

    async def stop_sched():
        scheduler.stop()

    life.register_stop(Order.SCHEDULER, "scheduler", stop_sched)

    # -- kernel auto-tune (core/autotune, ISSUE 18) -----------------------
    # resolve the KernelConfig for this boot BEFORE the prewarm/warm-up
    # hooks compile anything, so the duty programs compile under the
    # TUNED routing (tune -> prewarm -> warm-up). Background task off
    # the event loop; any failure degrades to defaults + env overrides
    # and never blocks boot. Mode "off" flows through the SAME
    # resolve() call: the ops/ hot paths no longer read the
    # environment, so the deprecated CHARON_MSM/CHARON_MXU_MONT deploy
    # pins only take effect if something applies them — "off" means
    # defaults + env overrides, never silently-dropped pins.
    tune_done = asyncio.Event()
    if config.use_tpu_tbls:

        async def autotune_start():
            import time as _t

            from charon_tpu.core import autotune as _autotune

            t0 = _t.monotonic()
            loop = asyncio.get_running_loop()
            autotune_obs = metrics.autotune_hook()
            if flight is not None:
                autotune_obs = flightrec_mod.autotune_hook(
                    flight, inner=autotune_obs
                )
            try:
                result = await loop.run_in_executor(
                    None,
                    lambda: _autotune.resolve(
                        config.crypto_autotune,
                        config.crypto_autotune_profile or None,
                        observer=autotune_obs,
                    ),
                )
                # "skipped" = the tuner refused/degraded to defaults —
                # the autotune_defaults health check watches this
                plane_health["autotune_fallback"] = (
                    1 if result.outcome == "skipped" else 0
                )
                log.info(
                    "kernel auto-tune resolved",
                    topic="autotune",
                    outcome=result.outcome,
                    config=result.config.as_dict(),
                    sources=result.sources,
                    bench_runs=result.bench_runs,
                    seconds=round(_t.monotonic() - t0, 1),
                )
            except Exception as e:  # noqa: BLE001 — background task:
                # lifecycle gathers background exceptions silently, so
                # a tuner failure must log here AND degrade to the
                # proven defaults — kernel selection is a perf choice,
                # never worth a failed boot
                log.warn(
                    "kernel auto-tune failed; running KernelConfig "
                    "defaults",
                    topic="autotune",
                    err=f"{type(e).__name__}: {str(e)[:160]}",
                    seconds=round(_t.monotonic() - t0, 1),
                )
                plane_health["autotune_fallback"] = 1
                _autotune.apply_env()
            finally:
                tune_done.set()

        life.register_start(
            Order.MONITORING, "crypto-autotune", autotune_start
        )
    else:
        tune_done.set()

    if crypto_plane is not None:
        # queue live flushes behind the boot-time tuner: micro_bench's
        # trial.apply() flips the global dispatch flags and drops the
        # jitted-kernel caches, so a duty flush racing the tuning
        # window would compile under a transient trial config and
        # immediately lose its executable (recompile churn + latency
        # spikes exactly at boot). tune_done is set in the tuner
        # hook's finally (or immediately when tbls is off), so the
        # gate never wedges the plane.
        crypto_plane.dispatch_gate = tune_done
        prewarm = config.crypto_plane_prewarm
        if prewarm == "auto":
            # pairing compiles take minutes on XLA:CPU — a real
            # accelerator backend amortizes the warmup, and so does a
            # warm artifact story (fresh tuned profile + a prewarm
            # that COMPLETED once under the same fingerprint): prewarm
            # then replays the duty pairing compiles as cache loads
            # (core/autotune.warm_boot_ready)
            if jax.default_backend() == "tpu":
                prewarm = "on"
            else:
                from charon_tpu.core import autotune as _at

                prewarm = (
                    "on"
                    if config.crypto_autotune != "off"
                    and _at.warm_boot_ready(
                        config.crypto_autotune_profile or None
                    )
                    else "off"
                )
        if prewarm == "on":
            # background: duties arriving mid-warmup queue behind the
            # compile on the serialized device lane instead of racing it
            async def prewarm_plane():
                import time as _t

                # compile under the TUNED kernel routing, not whatever
                # defaults the tuner is about to replace
                await tune_done.wait()
                t0 = _t.monotonic()
                try:
                    shapes = await crypto_plane.prewarm()
                except Exception as e:  # noqa: BLE001 — background task:
                    # lifecycle gathers it silently at shutdown, so a
                    # failed warmup (wedged claim, compile error) must
                    # log here or the operator believes the shapes are
                    # warm while the first live slot eats a cold compile
                    log.warn(
                        "crypto plane pre-warm failed; first live "
                        "flushes will compile cold",
                        topic="app",
                        err=f"{type(e).__name__}: {str(e)[:160]}",
                        seconds=round(_t.monotonic() - t0, 1),
                    )
                    return
                log.info(
                    "crypto plane pre-warmed",
                    topic="app",
                    shapes=[(k, n) for k, n, _ in shapes],
                    seconds=round(_t.monotonic() - t0, 1),
                )
                # the duty pairing programs are now in the persistent
                # compile cache: record it so the NEXT boot's
                # `--crypto-plane-prewarm auto` gate knows prewarm
                # costs cache loads (autotune.warm_boot_ready)
                try:
                    from charon_tpu.core import autotune as _at2

                    _at2.mark_prewarmed(
                        config.crypto_autotune_profile or None
                    )
                except Exception as e:  # noqa: BLE001 — marker is an
                    # optimization signal; losing it only means the
                    # next auto boot stays conservative
                    log.warn(
                        "could not record prewarm completion marker",
                        topic="app",
                        err=f"{type(e).__name__}: {str(e)[:160]}",
                    )

            life.register_start(
                Order.MONITORING, "crypto-prewarm", prewarm_plane
            )

        async def stop_plane():
            if crypto_svc is not None:
                # service first: fail queued waiters fast and close the
                # per-tenant quarantine coalescers before the shared one
                crypto_svc.close()
            crypto_plane.close()

        life.register_stop(Order.SCHEDULER, "crypto-plane", stop_plane)

    if remote_plane is not None:
        # connection supervision starts with the node; jobs submitted
        # while the remote is down simply run on the local rung
        life.register_start(
            Order.MONITORING, "crypto-remote", remote_plane.start
        )
        life.register_stop(
            Order.SCHEDULER, "crypto-remote", remote_plane.close
        )

    if crypto_server is not None:

        async def start_crypto_server():
            await crypto_server.start()
            # tenant IDS only — the token VALUES never leave the dict
            log.info(  # lint: allow(secret-flow)
                "crypto plane service listening",
                topic="app",
                host=crypto_server.host,
                port=crypto_server.port,
                tenants=sorted(config.crypto_serve_tokens),
            )

        life.register_start(
            Order.MONITORING, "crypto-serve", start_crypto_server
        )
        life.register_stop(
            Order.SCHEDULER, "crypto-serve", crypto_server.close
        )

    if config.use_tpu_tbls:
        # bulk point-cache warm-up (ISSUE 6): decode the whole cluster
        # key set through the batched device kernels at startup so the
        # first live slot never pays the python-bigint cold burst
        warmup = config.crypto_plane_warmup
        if warmup == "auto":
            # the canonical backend probe (not default_backend() ==
            # "tpu"): plugin/tunneled TPUs report other platform names,
            # and the decode rung + warm_point_caches auto both resolve
            # through the same helper — the gates must agree
            from charon_tpu.ops import limb as _limb

            warmup = "on" if _limb._is_tpu_backend() else "off"
        if warmup == "on":
            warm_keyset = sorted(
                {
                    bytes.fromhex(v.distributed_public_key[2:])
                    for v in lock.validators
                }
                | {
                    bytes.fromhex(ps[2:])
                    for v in lock.validators
                    for ps in v.public_shares
                }
            )

            async def warm_point_caches_start():
                import time as _t

                # the decode kernels route through the tuned mont_mul
                # dispatch — warm AFTER the tuner settled the flags
                await tune_done.wait()
                t0 = _t.monotonic()
                try:
                    stats = await _warm_point_caches(
                        crypto_plane, metrics, pubkeys=warm_keyset
                    )
                except Exception as e:  # noqa: BLE001 — background task:
                    # a failed warm-up must log (the operator otherwise
                    # believes the caches are warm) but never block boot;
                    # cold keys decode on demand exactly as before
                    log.warn(
                        "point-cache warm-up failed; first live slot "
                        "decodes cold",
                        topic="app",
                        err=f"{type(e).__name__}: {str(e)[:160]}",
                        seconds=round(_t.monotonic() - t0, 1),
                    )
                    return
                log.info(
                    "point caches warmed",
                    topic="app",
                    pubkeys=stats.get("pubkey"),
                    seconds=round(_t.monotonic() - t0, 1),
                )

            life.register_start(
                Order.MONITORING, "crypto-cache-warmup", warm_point_caches_start
            )

    # health: the reference catalogue evaluated over this node's own
    # sampled metrics, gating /readyz (ref: app/health + monitoringapi)
    from charon_tpu.app import log as app_log
    from charon_tpu.app.health import (
        HealthChecker,
        Metadata,
        MetricStore,
        default_checks,
        plane_checks,
    )

    health_store = MetricStore()
    health = HealthChecker(
        health_store,
        # reference catalogue + distributed-plane catalogue + the SLO
        # engine's burn-rate gates (ISSUE 19)
        checks=default_checks() + plane_checks() + slo.checks(),
        metadata=Metadata(
            num_validators=len(lock.validators),
            quorum=t,
            remote_plane=remote_plane is not None,
        ),
    )

    async def _sample_health_loop(interval: float = 30.0):
        import asyncio as _asyncio

        while True:
            try:
                health_store.sample(
                    "app_log_errors", sum(app_log.error_counts.values())
                )
                health_store.sample(
                    "app_log_warnings", sum(app_log.warn_counts.values())
                )
                if p2p_node is not None:
                    health_store.sample(
                        "p2p_peers_connected",
                        sum(
                            1
                            for ok in p2p_node.ping_success.values()
                            if ok
                        ),
                    )
                else:  # in-process simnet: peers are always reachable
                    health_store.sample("p2p_peers_connected", n - 1)
                health_store.sample(
                    "core_tracker_failed_duties",
                    sum(tracker.failed_total.values()),
                )
                health_store.sample(
                    "core_tracker_failed_proposals",
                    sum(
                        cnt
                        for (dtype, _), cnt in tracker.failed_total.items()
                        if dtype == DutyType.PROPOSER
                    ),
                )
                health_store.sample(
                    "core_bcast_recast_errors", bcast.recast_errors
                )
                if p2p_node is not None and peerinfo.peers:
                    health_store.sample(
                        "app_peerinfo_clock_offset_abs",
                        max(
                            abs(p.clock_offset)
                            for p in peerinfo.peers.values()
                        ),
                    )
                try:
                    await beacon.await_synced()
                    health_store.sample("app_beacon_syncing", 0)
                except Exception:  # noqa: BLE001 — syncing or unreachable
                    health_store.sample("app_beacon_syncing", 1)
                # distributed-plane catalogue series (ISSUE 19): the
                # plane_checks() docstring documents each name
                if crypto_svc is not None:
                    _bstate = {"closed": 0, "half_open": 1, "open": 2}
                    health_store.sample(
                        "tpu_plane_tenant_breaker_state",
                        max(
                            (
                                _bstate.get(ten.breaker.state, 0)
                                for ten in crypto_svc._tenants.values()
                            ),
                            default=0,
                        ),
                    )
                if remote_plane is not None:
                    health_store.sample(
                        "tpu_plane_remote_state",
                        {"down": 0, "probing": 1, "up": 2}.get(
                            remote_plane.state, 0
                        ),
                    )
                health_store.sample(
                    "wire_peer_quarantine_total",
                    plane_health["quarantines"],
                )
                health_store.sample(
                    "tpu_autotune_fallback",
                    plane_health["autotune_fallback"],
                )
                # SLO burn gauges + recorder eviction/dump gauges ride
                # the same cadence
                metrics.observe_slo(slo.evaluate())
                if flight is not None:
                    metrics.observe_flightrec(flight)
            except Exception as e:  # noqa: BLE001 — sampling must not die
                log.warn("health sampling failed", topic="app", err=str(e))
            await _asyncio.sleep(interval)

    life.register_start(Order.MONITORING, "health-sampler", _sample_health_loop)

    # stack sniping (ISSUE 19 satellite): periodic /proc scan for
    # co-located validator-stack processes -> stack_colocated_processes
    # gauges + a lifecycle event in the flight ring
    if config.stacksnipe_interval > 0:
        from charon_tpu.app.stacksnipe import StackSniper

        _snipe_metrics = metrics.stacksnipe_hook()

        def _snipe_report(report):
            _snipe_metrics(report)
            if flight is not None and report:
                flight.record(
                    "lifecycle",
                    "colocated",
                    binaries=sorted(report),
                    processes=sum(len(p) for p in report.values()),
                )

        sniper = StackSniper(
            interval=config.stacksnipe_interval, on_report=_snipe_report
        )
        life.register_start(Order.MONITORING, "stacksnipe", sniper.run)

    # flight-recorder egress (ISSUE 19): crash/SIGTERM handlers dump the
    # ring; the stop hook dumps on clean shutdown and restores the
    # previous handlers. TRACKER order (lowest) = the dump runs LAST, so
    # events recorded during other components' teardown are captured.
    if flight is not None:
        flight_dump_dir.mkdir(parents=True, exist_ok=True)
        _uninstall_crash = flightrec_mod.install_crash_handlers(
            flight,
            str(flight_dump_dir / f"node{config.node_index}.crash.jsonl"),
        )

        async def stop_flight():
            flight.record("lifecycle", "stop")
            try:
                flight.dump_jsonl(
                    str(
                        flight_dump_dir
                        / f"node{config.node_index}.stop.jsonl"
                    ),
                    trigger="stop",
                )
            except OSError as e:
                log.warn(
                    "flight-recorder stop dump failed",
                    topic="app",
                    err=str(e),
                )
            _uninstall_crash()

        life.register_stop(Order.TRACKER, "flightrec", stop_flight)

    # exporter/JSONL built at the top of build_node (spans flow for the
    # node's whole life); flushed + closed at shutdown. Registered
    # unconditionally: the metric/slow-duty hooks must come OFF the
    # tracer even in default builds where it is the process-global one,
    # or a rebuild in the same process would keep feeding spans into
    # this node's dead registry.
    _own_tracer = otlp is not None or bool(config.tracing_jsonl)

    async def stop_tracing():
        for h in _node_hooks:
            try:
                node_tracer.hooks.remove(h)
            except ValueError:
                pass
        if _own_tracer:
            # close() joins the export thread (final POST can take
            # seconds against a dead collector) — keep the loop free so
            # later stop hooks' grace timeouts still fire
            await asyncio.get_running_loop().run_in_executor(
                None, node_tracer.close
            )

    # TRACKER order (lowest): stop hooks run highest-first, so the
    # exporter flushes AFTER p2p/beacon teardown — spans recorded
    # during other components' shutdown still reach the collector
    life.register_stop(Order.TRACKER, "tracing", stop_tracing)

    if config.monitoring_port:
        consensus_dump = getattr(qbft_consensus, "debug_dump", None)

        async def start_mon():
            await serve_monitoring(
                "127.0.0.1",
                config.monitoring_port,
                metrics,
                health_checker=health,
                consensus_dump=consensus_dump,
                tracer=node_tracer,
                flightrec=flight,
                profiler=profiler,
            )

        life.register_start(Order.MONITORING, "monitoring", start_mon, background=False)

    return Node(
        config=config,
        lock=lock,
        life=life,
        scheduler=scheduler,
        vapi=vapi,
        vapi_router=vapi_router,
        p2p=p2p_node,
        bcast=bcast,
        tracker=tracker,
        metrics=metrics,
        beacon=beacon,
        sigagg=sigagg,
        crypto_plane=crypto_plane,
        crypto_svc=crypto_svc,
        crypto_remote_plane=remote_plane,
        crypto_server=crypto_server,
        inclusion=inclusion,
        flightrec=flight,
        profiler=profiler,
        slo=slo,
        pubshares_by_idx=pubshares_by_idx,
    )


def _log_inclusion(report: InclusionReport) -> None:
    if report.included:
        log.debug(
            "duty included on-chain",
            topic="inclusion",
            duty=str(report.duty),
            delay_slots=report.delay_slots,
        )
    else:
        log.warn(
            "duty missed on-chain inclusion",
            topic="inclusion",
            duty=str(report.duty),
        )


def _make_expiry(
    dutydb,
    parsigdb,
    aggsigdb,
    tracker,
    consensus=None,
    slow_detector=None,
    clock=None,
):
    async def on_expired(duty):
        dutydb.trim(duty)
        parsigdb.trim(duty)
        aggsigdb.trim(duty)
        if consensus is not None:
            consensus.trim(duty)
        if slow_detector is not None and clock is not None:
            budget = clock.duty_deadline(duty) - clock.slot_start(duty.slot)
            slow_detector.finalize(duty, budget)
        await tracker.duty_expired(duty)

    return on_expired


def _register_deadline(deadliner):
    async def on_duty(duty, defs):
        deadliner.add(duty)

    return on_duty


def _async_noop(fn):
    async def run():
        fn()

    return run


async def run(config: Config, stop: asyncio.Event | None = None) -> None:
    """ref: app.Run (app/app.go:131) — build then run the lifecycle."""
    node = await build_node(config)
    await node.life.run(stop)
