"""Exponential backoff with jitter — the one shared implementation.

Mirrors ref: app/expbackoff/expbackoff.go (grpc-style schedule: delay =
base * multiplier^retries, jittered, capped at max): `Config` presets
(default + fast), the pure `backoff_delay` schedule for callers that own
their sleeps, and the stateful awaitable `ExpBackoff` used by the Lazy
eth2 client, the relay reserver and the DKG sync clients.

This is the dedicated util the inline backoffs grew out of
(VERDICT r4 missing #4); `app.eth2wrap.ExpBackoff` re-exports it for
existing importers.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass


@dataclass(frozen=True)
class Config:
    base_delay: float = 1.0  # seconds before the first retry
    multiplier: float = 1.6  # growth factor per retry
    jitter: float = 0.2  # ± fraction randomization per delay
    max_delay: float = 120.0  # upper bound on the unjittered delay


# ref: expbackoff.go:33 DefaultConfig / :41 FastConfig
DEFAULT_CONFIG = Config()
FAST_CONFIG = Config(base_delay=0.1, multiplier=1.6, jitter=0.2, max_delay=5.0)


def backoff_delay(config: Config, retries: int, rng=None) -> float:
    """Delay in seconds before retry number `retries` (0-based), matching
    ref: expbackoff.go:145 Backoff — exponential growth capped at
    max_delay, then jittered by ±jitter."""
    delay = config.base_delay
    for _ in range(max(0, retries)):
        delay *= config.multiplier
        if delay >= config.max_delay:
            break
    delay = min(delay, config.max_delay)
    r = (rng or random).random()
    return max(0.0, delay * (1 + config.jitter * (2 * r - 1)))


class ExpBackoff:
    """Stateful awaitable backoff with full jitter and reset
    (ref: expbackoff.go:115 NewWithReset). The first `wait()` returns
    immediately; each later call sleeps one schedule step further."""

    def __init__(
        self,
        base: float = 0.25,
        factor: float = 2.0,
        max_delay: float = 30.0,
        jitter: bool = True,
    ) -> None:
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self._attempt = 0
        self._waited = False

    def next_delay(self) -> float:
        delay = min(self.max_delay, self.base * self.factor**self._attempt)
        self._attempt += 1
        return random.uniform(0, delay) if self.jitter else delay

    async def wait(self) -> None:
        # first call returns immediately WITHOUT consuming an attempt, so
        # the first real sleep is the base delay (not base*factor)
        if self._waited:
            await asyncio.sleep(self.next_delay())
        else:
            self._waited = True

    def reset(self) -> None:
        self._attempt = 0
        self._waited = False
