"""Structured logging with context-carried fields and topics.

Mirrors ref: app/log + app/z — loggers carry a topic, contexts carry
fields that every log line in that call tree inherits
(log/log.go:32-43 WithCtx/WithTopic), and error/warn counters feed the
health checks (app/health). contextvars replace Go's context values.
"""

from __future__ import annotations

import contextvars
import logging
import sys
from collections import defaultdict
from typing import Any

_ctx_fields: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "log_fields", default={}
)

# error/warn counters by topic — consumed by app.health
# (ref: health/checks.go reads log counters).
error_counts: dict[str, int] = defaultdict(int)
warn_counts: dict[str, int] = defaultdict(int)

_root = logging.getLogger("charon_tpu")
if not _root.handlers:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).4s %(message)s")
    )
    _root.addHandler(handler)
    _root.setLevel(logging.INFO)


def init(level: str = "info") -> None:
    _root.setLevel(getattr(logging, level.upper(), logging.INFO))


def with_ctx(**fields) -> contextvars.Token:
    """Attach fields to the current context (ref: log.WithCtx)."""
    merged = {**_ctx_fields.get(), **fields}
    return _ctx_fields.set(merged)


def reset_ctx(token: contextvars.Token) -> None:
    _ctx_fields.reset(token)


def _fmt(msg: str, topic: str, fields: dict) -> str:
    all_fields = {**_ctx_fields.get(), **fields}
    # logs and traces cross-reference: a record emitted inside an active
    # span carries its trace id (ref: the reference stamps trace IDs
    # into zap fields via the log/trace bridge). Explicit fields win.
    if "trace_id" not in all_fields:
        from charon_tpu.app.tracer import current_ctx

        ctx = current_ctx()
        if ctx is not None:
            all_fields["trace_id"] = ctx[0]
    parts = [f"[{topic}]", msg]
    parts.extend(f"{k}={v}" for k, v in all_fields.items())
    return " ".join(parts)


def debug(msg: str, topic: str = "app", **fields) -> None:
    _root.debug(_fmt(msg, topic, fields))


def info(msg: str, topic: str = "app", **fields) -> None:
    _root.info(_fmt(msg, topic, fields))


def warn(msg: str, topic: str = "app", **fields) -> None:
    warn_counts[topic] += 1
    _root.warning(_fmt(msg, topic, fields))


def error(msg: str, topic: str = "app", exc: BaseException | None = None, **fields) -> None:
    error_counts[topic] += 1
    if exc is not None:
        # structured-error chains contribute their merged context fields
        # (explicit call-site fields win — ref: app/errors field logging)
        from charon_tpu.app.errors import fields_of

        for k, v in fields_of(exc).items():
            fields.setdefault(k, v)
        fields["err"] = repr(exc)
    _root.error(_fmt(msg, topic, fields))
