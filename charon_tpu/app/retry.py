"""Deadline-bounded async retry of workflow steps.

Mirrors ref: app/retry/retry.go:28-120 — each duty step is retried with a
constant 1s backoff until the duty's deadline, with error classification
(network-ish errors retried, programming errors surfaced immediately).
Wired into the workflow as a wire() option (ref: core.WithAsyncRetry,
app/app.go:571).
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

BACKOFF_SECS = 1.0  # ref: retry/retry.go constant backoff


def retryable_errors() -> tuple:
    # lazy: avoid a hard import edge at module load; AllClientsFailedError
    # (every configured BN failed) is the framework's own transient
    # network failure and MUST be retried (ref: retry.go classifies
    # net/url errors as temporary)
    from charon_tpu.app.eth2wrap import AllClientsFailedError

    return (
        ConnectionError,
        TimeoutError,
        asyncio.TimeoutError,
        OSError,
        AllClientsFailedError,
    )


_retryable = retryable_errors  # historical internal name

RETRYABLE = (ConnectionError, TimeoutError, asyncio.TimeoutError, OSError)


class Retryer:
    """deadline_of: maps a duty to its absolute wall-clock deadline
    (SlotClock). `now` (default: live `time.time`) is the wall clock
    the deadlines live on; `mono` is the clock the retry loop actually
    runs against. With the defaults the wall deadline is anchored to
    `time.monotonic()` ONCE per retry() call, so a host clock step
    mid-retry (NTP correction, chaos SkewedClock) can neither abort
    the remaining window nor stretch it past the duty deadline — the
    `_arm` bug class. Tests that inject a fake `now` drive a single
    steppable timeline and get `mono = now` automatically (one clock
    has no skew to misconvert)."""

    def __init__(
        self,
        deadline_of,
        now=None,
        backoff: float = BACKOFF_SECS,
        mono=None,
    ) -> None:
        self.deadline_of = deadline_of
        self.now = now
        self.backoff = backoff
        self.mono = mono
        self._tasks: set[asyncio.Task] = set()

    def _clocks(self):
        """(wall, mono) pair the loop runs on. Live `time.time` is read
        through the module attribute so clock-skew injection sees it."""
        if self.now is None:
            return (lambda: time.time()), (  # lint: allow(monotonic-clock) — wall INPUT timeline; loop runs on mono
                self.mono if self.mono is not None else time.monotonic
            )
        # injected wall clock IS the test's single timeline
        return self.now, (self.mono if self.mono is not None else self.now)

    async def retry(self, name: str, duty, fn, *args) -> None:
        """Deadline-bounded, not attempt-bounded: each attempt runs
        under wait_for(remaining) so a HUNG call cannot overshoot the
        duty deadline either — the timeout classifies as transient and
        the loop then stops at the deadline check. Cancellation (duty
        torn down / process stopping) propagates immediately: it is a
        BaseException and never swallowed as a retry."""
        now, mono = self._clocks()
        # wall deadline -> monotonic base, snapshotted once (PR 8 _arm)
        deadline = self.deadline_of(duty) - now() + mono()
        attempt = 0
        while True:
            attempt += 1
            remaining = deadline - mono()
            if remaining <= 0:
                return  # deadline exceeded; tracker reports the miss
            try:
                await asyncio.wait_for(fn(duty, *args), timeout=remaining)
                return
            except retryable_errors():
                if mono() + self.backoff >= deadline:
                    return  # deadline exceeded; tracker reports the miss
                await asyncio.sleep(self.backoff)
            except Exception:
                raise  # non-retryable: surface immediately

    def spawn(self, name: str, duty, fn, *args) -> None:
        """DoAsync (ref: retry.go:93): fire-and-forget with retries."""
        task = asyncio.create_task(self.retry(name, duty, fn, *args))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)


def with_async_retry(retryer: Retryer, edges: set[str] | None = None):
    """wire() option: wrap edges in deadline-bounded async retries."""
    edges = edges or {"fetcher.fetch"}

    def option(name: str, fn):
        if name not in edges:
            return fn

        async def wrapped(duty, *args, **kwargs):
            retryer.spawn(name, duty, fn, *args)

        return wrapped

    return option
