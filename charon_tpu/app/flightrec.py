"""Flight recorder: always-on, bounded-overhead event history for
post-mortem debugging of the distributed plane (ISSUE 19 tentpole).

Every operational hook in the node — FlushStats lifecycle, tenant
sheds/breaker transitions (core/cryptosvc), remote connect/failover/
shed (core/cryptosvc_client/_server), Byzantine evidence
(core/evidence), peer/codec quarantine (p2p/quarantine), autotune
decisions (core/autotune), QBFT round changes, duty tracker outcomes —
feeds one process-wide ring so an incident leaves a typed, ordered,
attributable record even when nobody was scraping /metrics.

Design constraints, in order:

1. **Bounded memory, storm-proof.** One fixed-capacity ring PER
   CATEGORY (``collections.deque(maxlen=...)``): a flush storm evicts
   old flush events, never the three byzantine detections that explain
   it. Eviction counts are kept per category so a dump says what was
   lost.
2. **Lock-light.** One tiny per-category lock held only for the
   append + counter bump — FlushStats arrives on the coalescer's
   device worker thread and server stats on socket threads, so the
   recorder must be safe from any thread without ever becoming a
   contention point on the duty path.
3. **Unrecordable secrets.** ``record()`` accepts only primitive field
   values (str/int/float/bool/None, short lists thereof); anything
   structured is replaced by its type name. Key material therefore
   cannot ride an event even by accident, and the secret-flow taint
   lint (analysis/rule_secret_flow.py) flags any tainted value reaching
   a ``record()`` sink at review time.
4. **Schema-versioned egress.** Dumps are JSONL with a header line
   carrying ``schema``/``node``; the event-field catalogue is an
   append-only golden (tests/testdata/flightrec_schema.json, checked by
   analysis/flightrec_check.py) so downstream incident tooling never
   silently breaks.

Cross-node reconstruction mirrors app/tracer.merge_jsonl: per-node
dumps merge on wall-clock order (dedup by (node, seq)) into one
incident timeline; ``render_timeline`` is the text view /debug/flight
serves with ``format=text``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

# The closed category set. Per-category sub-rings are the storm
# isolation mechanism, so this is deliberately an enum-like tuple —
# adding one is a schema change (bless it into the golden).
CATEGORIES = (
    "flush",       # coalescer FlushStats lifecycle
    "tenant",      # cryptosvc sheds / breaker transitions / queue
    "remote",      # remote-plane connect / failover / shed (client+server)
    "byzantine",   # attributed evidence (core/evidence kinds)
    "quarantine",  # peer/codec mutes (p2p/quarantine)
    "autotune",    # startup kernel-tuner decisions + profile lifecycle
    "consensus",   # QBFT round changes
    "duty",        # tracker duty outcomes
    "lifecycle",   # process events: dumps, crash handlers, colocation
)

DEFAULT_CAPACITY = 512  # events kept per category

# The event vocabulary the shipped hook adapters emit, per category —
# the downstream contract incident tooling parses against. Checked
# APPEND-ONLY against tests/testdata/flightrec_schema.json by
# analysis/flightrec_check.py: kinds may be added (re-bless with
# --update after review), never removed or recategorized.
EVENT_KINDS: dict[str, tuple[str, ...]] = {
    "flush": ("flush", "flush_unparsed"),
    "tenant": ("shed", "breaker"),
    "remote": (
        # client side (core/cryptosvc_client.RemotePlane observer)
        "failover",
        "shed",
        "remote_shed",
        "connect",
        "connect_fail",
        "disconnect",
        "state",
        "heartbeat_miss",
        # server side (core/cryptosvc_server observer, server_ prefix)
        "server_auth_fail",
        "server_connect",
        "server_disconnect",
        "server_shed",
        "server_quarantine",
    ),
    "byzantine": (
        "qbft_equivocation",
        "qbft_flood",
        "qbft_replay",
        "qbft_malformed",
        "qbft_forged_justification",
        "parsig_conflict",
        "parsig_flood",
        "parsig_invalid",
        "parsig_spoof",
    ),
    "quarantine": ("peer_muted",),
    "autotune": ("profile", "decision", "bench", "prewarm"),
    "consensus": ("round_change",),
    "duty": ("duty_ok", "duty_failed"),
    "lifecycle": ("start", "stop", "crash_dump", "dump", "colocated"),
}

# Envelope keys every dumped event line may carry (append-only too).
ENVELOPE_FIELDS = (
    "seq",
    "t_mono",
    "t_wall",
    "category",
    "kind",
    "node",
    "tenant",
    "slot",
    "fields",
)

# Field-value sanitation bounds: everything recorded must stay cheap to
# hold and safe to dump.
_MAX_STR = 200
_MAX_SEQ_ITEMS = 16


@dataclass(frozen=True)
class Event:
    """One recorded event. ``t_mono`` orders events within a node;
    ``t_wall`` is the cross-node merge key (wall clock is the only
    clock two machines share)."""

    seq: int
    t_mono: float
    t_wall: float
    category: str
    kind: str
    tenant: str | None = None
    slot: int | None = None
    fields: dict = field(default_factory=dict)

    def to_dict(self, node: str | None = None) -> dict:
        d = {
            "seq": self.seq,
            "t_mono": round(self.t_mono, 6),
            "t_wall": round(self.t_wall, 6),
            "category": self.category,
            "kind": self.kind,
        }
        if node is not None:
            d["node"] = node
        if self.tenant is not None:
            d["tenant"] = self.tenant
        if self.slot is not None:
            d["slot"] = self.slot
        if self.fields:
            d["fields"] = self.fields
        return d


def _sanitize_value(v):
    """Primitives pass (strings truncated); short sequences of
    primitives pass as lists; everything else is reduced to its type
    name — structured objects (and therefore key material wrapped in
    them) are unrecordable by construction."""
    if v is None or isinstance(v, (bool, int, float)):
        return v
    if isinstance(v, str):
        return v if len(v) <= _MAX_STR else v[:_MAX_STR] + "..."
    if isinstance(v, (list, tuple)):
        out = []
        for item in list(v)[:_MAX_SEQ_ITEMS]:
            if item is None or isinstance(item, (bool, int, float)):
                out.append(item)
            elif isinstance(item, str):
                out.append(
                    item if len(item) <= _MAX_STR else item[:_MAX_STR] + "..."
                )
            elif isinstance(item, (list, tuple)) and len(item) == 2:
                # one level of (name, count) pairs — the tenant_lanes shape
                out.append([_sanitize_value(item[0]), _sanitize_value(item[1])])
            else:
                out.append(f"<{type(item).__name__}>")
        return out
    return f"<{type(v).__name__}>"


class FlightRecorder:
    """Typed per-category ring buffer; every method is thread-safe.

    `observer` (optional, ``callable(category, kind)``) fires after
    each append — app/metrics wires the flightrec_* counter families
    through it. Exceptions from it are swallowed: recording must never
    take down the path that emitted the event.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        node: str = "",
        observer=None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"flight recorder capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.node = node
        self.observer = observer
        self._rings: dict[str, deque[Event]] = {
            cat: deque(maxlen=capacity) for cat in CATEGORIES
        }
        self._locks: dict[str, threading.Lock] = {
            cat: threading.Lock() for cat in CATEGORIES
        }
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.recorded_total: dict[str, int] = {cat: 0 for cat in CATEGORIES}
        self.dropped_total: dict[str, int] = {cat: 0 for cat in CATEGORIES}
        self.dumps_total: dict[str, int] = {}

    # -- intake ------------------------------------------------------------

    def record(
        self,
        category: str,
        kind: str,
        tenant: str | None = None,
        slot: int | None = None,
        **fields,
    ) -> None:
        """Append one event. Unknown categories are coerced into
        'lifecycle' rather than raised — a recorder bug must never
        crash an observer chain."""
        if category not in self._rings:
            fields = {"miscategorized": category, **fields}
            category = "lifecycle"
        ev = Event(
            seq=0,  # assigned under the seq lock below
            t_mono=time.monotonic(),
            # wall stamp is the cross-node merge key (logging edge,
            # never used for intra-node math)
            t_wall=time.time(),  # lint: allow(monotonic-clock)
            category=category,
            kind=str(kind)[:_MAX_STR],
            tenant=None if tenant is None else str(tenant)[:_MAX_STR],
            slot=None if slot is None else int(slot),
            fields={str(k)[:64]: _sanitize_value(v) for k, v in fields.items()},
        )
        with self._seq_lock:
            self._seq += 1
            object.__setattr__(ev, "seq", self._seq)
        ring = self._rings[category]
        with self._locks[category]:
            dropped = len(ring) == ring.maxlen
            ring.append(ev)
            self.recorded_total[category] += 1
            if dropped:
                self.dropped_total[category] += 1
        if self.observer is not None:
            try:
                self.observer(category, kind)
            except Exception:  # noqa: BLE001 — observers must not break intake
                pass

    # -- read side ---------------------------------------------------------

    def events(
        self,
        category: str | None = None,
        tenant: str | None = None,
        slot: int | None = None,
        limit: int | None = None,
    ) -> list[Event]:
        """Snapshot, merged across category rings, ordered by seq.
        Filters compose; `limit` keeps the NEWEST events."""
        cats = [category] if category in self._rings else list(CATEGORIES)
        out: list[Event] = []
        for cat in cats:
            with self._locks[cat]:
                out.extend(self._rings[cat])
        if tenant is not None:
            out = [e for e in out if e.tenant == tenant]
        if slot is not None:
            out = [e for e in out if e.slot == slot]
        out.sort(key=lambda e: e.seq)
        if limit is not None and limit >= 0:
            out = out[len(out) - min(limit, len(out)):]
        return out

    def __len__(self) -> int:
        return sum(len(r) for r in self._rings.values())

    # -- egress ------------------------------------------------------------

    def dump_jsonl(self, path: str, trigger: str = "demand") -> int:
        """Write the whole ring as schema-versioned JSONL (header line +
        one event per line), atomically (tmp + rename — a crash mid-dump
        never leaves a truncated file where tooling expects a dump).
        Returns the number of events written."""
        events = self.events()
        self.dumps_total[trigger] = self.dumps_total.get(trigger, 0) + 1
        header = {
            "schema": SCHEMA_VERSION,
            "node": self.node,
            "trigger": trigger,
            # dump stamp: operator-facing wall time for incident logs
            "written_at": round(time.time(), 3),  # lint: allow(monotonic-clock)
            "dropped": {k: v for k, v in self.dropped_total.items() if v},
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev.to_dict(node=self.node)) + "\n")
        os.replace(tmp, path)
        return len(events)


# -- crash/terminate dump handlers ----------------------------------------


def install_crash_handlers(rec: FlightRecorder, path: str):
    """Dump the ring on SIGTERM and on any unhandled exception (main
    thread AND worker threads), chaining whatever handlers were already
    installed. Returns an ``uninstall()`` callable that restores the
    previous handlers (tests and clean shutdowns).

    SIGTERM installation is best-effort: only the main thread may set
    signal handlers, and the dump-on-stop lifecycle hook covers clean
    exits anyway.
    """
    prev_excepthook = sys.excepthook
    prev_threading_hook = threading.excepthook

    def _dump(trigger: str) -> None:
        try:
            rec.record("lifecycle", "crash_dump", trigger=trigger)
            rec.dump_jsonl(path, trigger=trigger)
        except Exception:  # noqa: BLE001 — a failing dump must not mask the crash
            pass

    def excepthook(exc_type, exc, tb):
        _dump("crash")
        prev_excepthook(exc_type, exc, tb)

    def threading_hook(args):
        _dump("thread-crash")
        prev_threading_hook(args)

    sys.excepthook = excepthook
    threading.excepthook = threading_hook

    prev_sigterm = None
    installed_signal = False
    try:
        def on_sigterm(signum, frame):
            _dump("sigterm")
            if callable(prev_sigterm):
                prev_sigterm(signum, frame)
            elif prev_sigterm == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        prev_sigterm = signal.signal(signal.SIGTERM, on_sigterm)
        installed_signal = True
    except ValueError:
        # not the main thread — excepthooks still installed
        pass

    def uninstall() -> None:
        sys.excepthook = prev_excepthook
        threading.excepthook = prev_threading_hook
        if installed_signal:
            try:
                signal.signal(signal.SIGTERM, prev_sigterm or signal.SIG_DFL)
            except ValueError:
                pass

    return uninstall


# -- cross-node merge + text timeline -------------------------------------


def merge_jsonl(paths) -> list[dict]:
    """Merge per-node flight dumps into one incident ordering: dedup by
    (node, seq), sort by wall stamp (ties broken by node then seq —
    deterministic across re-runs). Unreadable lines are skipped, not
    fatal: a post-mortem works with whatever survived."""
    seen: set[tuple[str, int]] = set()
    out: list[dict] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        node = ""
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if i == 0 and "schema" in obj and "seq" not in obj:
                node = str(obj.get("node", ""))
                continue
            if "seq" not in obj or "category" not in obj:
                continue
            obj.setdefault("node", node)
            key = (str(obj["node"]), int(obj["seq"]))
            if key in seen:
                continue
            seen.add(key)
            out.append(obj)
    out.sort(key=lambda e: (e.get("t_wall", 0.0), str(e.get("node", "")), e["seq"]))
    return out


def render_timeline(events, limit: int | None = None) -> str:
    """Plain-text incident timeline (the format=text view of
    /debug/flight and the `flight merge` CLI): one line per event,
    offset-stamped from the first event, same spirit as the tracer's
    duty waterfall."""
    rows = [e.to_dict(node=None) if isinstance(e, Event) else dict(e) for e in events]
    if limit is not None:
        rows = rows[len(rows) - min(limit, len(rows)):]
    if not rows:
        return "(no flight-recorder events)\n"
    t0 = rows[0].get("t_wall", 0.0)
    lines = []
    for r in rows:
        off = r.get("t_wall", 0.0) - t0
        node = f" {r['node']}" if r.get("node") else ""
        tenant = f" tenant={r['tenant']}" if r.get("tenant") else ""
        slot = f" slot={r['slot']}" if r.get("slot") is not None else ""
        extras = " ".join(
            f"{k}={v}" for k, v in sorted((r.get("fields") or {}).items())
        )
        lines.append(
            f"+{off:9.3f}s{node} [{r['category']:<10}] "
            f"{r['kind']}{tenant}{slot}"
            + (f" {extras}" if extras else "")
        )
    return "\n".join(lines) + "\n"


# -- hook adapters ---------------------------------------------------------
# Each adapter chains an existing observer callback shape through the
# recorder: construct with the previously-wired hook as `inner` and
# install the adapter in its place. Recording happens FIRST so a
# throwing inner hook cannot suppress the record.


_TENANT_INCIDENT_KINDS = frozenset({"shed", "breaker"})


def tenant_hook(rec: FlightRecorder, inner=None):
    """core/cryptosvc observer: (kind, tenant, **fields). Only the
    incident-relevant kinds enter the ring — queue/dispatch/complete
    are per-job telemetry (the metrics inner hook still sees them)."""

    def hook(kind, tenant, **fields):
        if kind in _TENANT_INCIDENT_KINDS:
            rec.record("tenant", kind, tenant=tenant, **fields)
        if inner is not None:
            inner(kind, tenant, **fields)

    return hook


def remote_hook(rec: FlightRecorder, tenant: str, addr: str = "", inner=None):
    """core/cryptosvc_client observer: (kind, **fields). `addr` names
    the dialed server so a merged post-mortem can attribute a failover
    to the exact aborted endpoint."""

    def hook(kind, **fields):
        rec.record("remote", kind, tenant=tenant, addr=addr, **fields)
        if inner is not None:
            inner(kind, **fields)

    return hook


def server_hook(rec: FlightRecorder, inner=None):
    """core/cryptosvc_server observer: (kind, tenant, **fields) —
    recorded with a server_ prefix so client and server views of the
    same incident stay distinguishable after a merge."""

    def hook(kind, tenant, **fields):
        rec.record("remote", f"server_{kind}", tenant=tenant, **fields)
        if inner is not None:
            inner(kind, tenant, **fields)

    return hook


def byzantine_hook(rec: FlightRecorder, inner=None):
    """core/evidence hook: (peer, kind[, detail])."""

    def hook(peer, kind, detail=""):
        rec.record("byzantine", kind, peer=peer, detail=detail)
        if inner is not None:
            inner(peer, kind)

    return hook


def quarantine_hook(rec: FlightRecorder, inner=None):
    """p2p/quarantine observer: (peer, mute_seconds)."""

    def hook(peer, mute_seconds):
        rec.record("quarantine", "peer_muted", peer=peer, mute_seconds=mute_seconds)
        if inner is not None:
            inner(peer, mute_seconds)

    return hook


def autotune_hook(rec: FlightRecorder, inner=None):
    """core/autotune observer: (kind, **fields)."""

    def hook(kind, **fields):
        rec.record("autotune", kind, **fields)
        if inner is not None:
            inner(kind, **fields)

    return hook


def consensus_hook(rec: FlightRecorder, inner=None):
    """QBFT round-change observer: (duty, round, source, direction)
    (core/consensus_qbft.QBFTConsensus.on_round_change)."""

    def hook(duty, rnd, source, direction):
        rec.record(
            "consensus",
            "round_change",
            slot=getattr(duty, "slot", None),
            duty=str(duty),
            round=rnd,
            source=source,
            direction=direction,
        )
        if inner is not None:
            inner(duty, rnd, source, direction)

    return hook


def stats_hook(rec: FlightRecorder, inner=None):
    """SlotCoalescer stats_hook: (FlushStats) — called from the device
    worker thread. Records the flush summary (never the payloads)."""

    def hook(stats):
        try:
            dev = stats.device_span
            dev_s = (dev[1] - dev[0]) if dev else 0.0
            rec.record(
                "flush",
                "flush",
                jobs=stats.jobs,
                lanes=stats.lanes,
                flush_seconds=round(stats.flush_seconds, 6),
                device_seconds=round(dev_s, 6),
                window=round(stats.window, 6),
                fallback=stats.fallback,
                decode_mode=stats.decode_mode,
                tenants=[t for t, _ in (stats.tenant_lanes or ())],
            )
        except Exception:  # noqa: BLE001 — a stats-shape change must not kill the device lane
            rec.record("flush", "flush_unparsed")
        if inner is not None:
            inner(stats)

    return hook


def duty_hook(rec: FlightRecorder):
    """core/tracker report subscriber: records every duty outcome
    (success AND attributed failure) — the SLO engine's raw history,
    replayable from a dump."""

    def sub(report):
        rec.record(
            "duty",
            "duty_ok" if report.success else "duty_failed",
            slot=report.duty.slot,
            duty=str(report.duty),
            failed_step=str(report.failed_step) if report.failed_step else None,
            reason=report.reason.value if report.reason else None,
            trace_id=report.trace_id,
        )

    return sub
