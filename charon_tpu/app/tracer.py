"""Workflow tracing: duty-rooted spans across every wire edge.

Mirrors ref: app/tracer/trace.go (OpenTelemetry -> Jaeger) and
core/tracing.go (span-wrapped workflow steps, duty-rooted trace IDs via
StartDutyTrace). Redesign: a dependency-free span recorder — spans carry
OTel-compatible ids (128-bit trace, 64-bit span), nest via contextvars
(async-safe), and export to a ring buffer served at /debug/traces plus an
optional JSONL file. Duty traces use a DETERMINISTIC trace id derived
from the duty, so spans recorded on different nodes of the cluster can be
merged into one cross-node trace offline — same property the reference
gets from propagating trace context in its p2p envelopes.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import secrets
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class Span:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str  # 16 hex chars or ""
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"  # ok | error

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_us": int(self.start * 1e6),
            "duration_us": int((self.end - self.start) * 1e6),
            "attrs": self.attrs,
            "status": self.status,
        }


_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "charon_tpu_span", default=None
)


def _otlp_value(v) -> dict:
    """Map a Python attribute value to an OTLP JSON AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(span: "Span") -> dict:
    """One span in OTLP/JSON encoding (opentelemetry-proto trace.v1.Span)."""
    return {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "parentSpanId": span.parent_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(span.start * 1e9)),
        "endTimeUnixNano": str(int(span.end * 1e9)),
        "attributes": [
            {"key": k, "value": _otlp_value(v)} for k, v in span.attrs.items()
        ],
        "status": {"code": 2 if span.status == "error" else 1},
    }


class OTLPExporter:
    """Pushes spans to an OTLP/HTTP collector (`/v1/traces`, JSON
    encoding) — the standard Jaeger ≥1.35 / otel-collector ingest.
    Mirrors ref: app/tracer/trace.go:40-124 which exports via OTLP
    to Jaeger. Dependency-free: urllib POST from a background thread;
    spans batch until `batch_size` or `flush_interval`, and a dead
    collector drops batches (bounded queue) rather than stalling the
    node — tracing must never backpressure duty processing."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "charon-tpu",
        batch_size: int = 256,
        flush_interval: float = 5.0,
        max_queue: int = 8192,
    ):
        import queue
        import threading

        if not endpoint.rstrip("/").endswith("/v1/traces"):
            endpoint = endpoint.rstrip("/") + "/v1/traces"
        self.endpoint = endpoint
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.dropped = 0  # spans lost to a full queue / dead collector
        self.exported = 0
        self._q: "queue.Queue[Span | None]" = queue.Queue(maxsize=max_queue)
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    def offer(self, span: "Span") -> None:
        try:
            self._q.put_nowait(span)
        except Exception:
            self.dropped += 1

    def _post(self, batch: list["Span"]) -> None:
        import urllib.request

        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {
                                    "key": "service.name",
                                    "value": {"stringValue": self.service_name},
                                }
                            ]
                        },
                        "scopeSpans": [
                            {
                                "scope": {"name": "charon_tpu.app.tracer"},
                                "spans": [span_to_otlp(s) for s in batch],
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5.0):
                self.exported += len(batch)
        except Exception:
            self.dropped += len(batch)

    def _run(self) -> None:
        import queue

        batch: list[Span] = []
        deadline = time.monotonic() + self.flush_interval
        while True:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                item = ()  # timer tick
            if item is None:  # shutdown sentinel
                if batch:
                    self._post(batch)
                return
            if item != ():
                batch.append(item)
            if len(batch) >= self.batch_size or (
                batch and time.monotonic() >= deadline
            ):
                self._post(batch)
                batch = []
            if time.monotonic() >= deadline:
                deadline = time.monotonic() + self.flush_interval

    def shutdown(self, timeout: float = 10.0) -> None:
        """Flush pending spans and stop the export thread. A full queue
        still gets its sentinel (blocking put with a bound) so the
        flush-on-shutdown contract holds after a long collector outage."""
        import queue

        try:
            self._q.put(None, timeout=timeout / 2)
        except queue.Full:
            return  # exporter thread is wedged; give up without joining
        self._thread.join(timeout=timeout)


class Tracer:
    """Ring-buffered span store with optional JSONL export and optional
    OTLP/HTTP push (ref: app/tracer Init wiring, app/app.go:1014-1027)."""

    def __init__(
        self,
        capacity: int = 4096,
        jsonl_path: str | None = None,
        exporter: OTLPExporter | None = None,
    ):
        self.spans: deque[Span] = deque(maxlen=capacity)
        self.jsonl_path = jsonl_path
        self.exporter = exporter
        self._file = None

    def record(self, span: Span) -> None:
        self.spans.append(span)
        if self.jsonl_path:
            if self._file is None:
                os.makedirs(
                    os.path.dirname(self.jsonl_path) or ".", exist_ok=True
                )
                self._file = open(self.jsonl_path, "a")
            self._file.write(json.dumps(span.to_json()) + "\n")
            self._file.flush()
        if self.exporter is not None:
            self.exporter.offer(span)

    def dump(self, trace_id: str | None = None) -> list[dict]:
        return [
            s.to_json()
            for s in self.spans
            if trace_id is None or s.trace_id == trace_id
        ]

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None
        if self.exporter is not None:
            self.exporter.shutdown()


_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL


def set_global_tracer(tracer: Tracer) -> None:
    global _GLOBAL
    _GLOBAL = tracer


def duty_trace_id(duty) -> str:
    """Deterministic trace id for a duty — identical on every node
    (ref: core/tracing.go StartDutyTrace derives the id from the duty)."""
    return hashlib.sha256(
        b"charon-tpu-trace" + str(duty).encode()
    ).hexdigest()[:32]


@contextlib.contextmanager
def span(name: str, duty=None, tracer: Tracer | None = None, **attrs):
    """Start a span; nests under the context's current span. If `duty` is
    given and there is no active trace, the span roots a duty trace."""
    tracer = tracer or _GLOBAL
    parent = _current.get()
    if parent is not None:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    elif duty is not None:
        trace_id = duty_trace_id(duty)
        parent_id = ""
    else:
        trace_id = secrets.token_hex(16)
        parent_id = ""
    if duty is not None:
        attrs.setdefault("duty", str(duty))
    s = Span(
        trace_id=trace_id,
        span_id=secrets.token_hex(8),
        parent_id=parent_id,
        name=name,
        start=time.time(),
        attrs=attrs,
    )
    token = _current.set(s)
    try:
        yield s
    except BaseException as e:
        s.status = "error"
        s.attrs["error"] = repr(e)
        raise
    finally:
        s.end = time.time()
        _current.reset(token)
        tracer.record(s)


def tracing(tracer: Tracer | None = None):
    """wire() option wrapping every subscription edge in a span
    (ref: core/tracing.go + core.WithTracing, app/app.go:569)."""

    def option(name: str, fn):
        async def wrapped(duty, *args, **kwargs):
            with span(name, duty=duty, tracer=tracer):
                return await fn(duty, *args, **kwargs)

        return wrapped

    return option
