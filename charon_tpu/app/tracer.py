"""Workflow tracing: duty-rooted spans across every wire edge.

Mirrors ref: app/tracer/trace.go (OpenTelemetry -> Jaeger) and
core/tracing.go (span-wrapped workflow steps, duty-rooted trace IDs via
StartDutyTrace). Redesign: a dependency-free span recorder — spans carry
OTel-compatible ids (128-bit trace, 64-bit span), nest via contextvars
(async-safe), and export to a ring buffer served at /debug/traces plus an
optional JSONL file. Duty traces use a DETERMINISTIC trace id derived
from the duty, so spans recorded on different nodes of the cluster can be
merged into one cross-node trace offline — same property the reference
gets from propagating trace context in its p2p envelopes.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import secrets
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class Span:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str  # 16 hex chars or ""
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"  # ok | error

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_us": int(self.start * 1e6),
            "duration_us": int((self.end - self.start) * 1e6),
            "attrs": self.attrs,
            "status": self.status,
        }


_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "charon_tpu_span", default=None
)


def current_ctx() -> tuple[str, str] | None:
    """(trace_id, span_id) of the context's active span, or None."""
    s = _current.get()
    if s is None:
        return None
    return (s.trace_id, s.span_id)


def encode_ctx() -> str | None:
    """Wire encoding of the active span context for transport frames
    (ref: the reference propagates OTel trace context in its p2p
    envelopes). Format: '<32-hex-trace-id>-<16-hex-span-id>'."""
    ctx = current_ctx()
    if ctx is None:
        return None
    return f"{ctx[0]}-{ctx[1]}"


@contextlib.contextmanager
def detached():
    """Run with NO active span. In-process transports (simnet memory
    fabrics, chaos fabrics) cross a simulated network boundary where a
    real deployment would lose the ambient context — without this, the
    sender's contextvars leak into the receiver and trace context would
    appear to propagate even with broken frame encoding."""
    token = _current.set(None)
    try:
        yield
    finally:
        _current.reset(token)


def parse_ctx(raw) -> tuple[str, str] | None:
    """Defensive decode of a propagated trace context. ANY malformation
    (wrong type, wrong lengths, non-hex) returns None — the receiver
    then falls back to a fresh duty-rooted span instead of crashing on
    a corrupted or adversarial frame."""
    if not isinstance(raw, str):
        return None
    trace_id, sep, span_id = raw.partition("-")
    if not sep or len(trace_id) != 32 or len(span_id) != 16:
        return None
    # strict per-char check: int(x, 16) would accept '0x' prefixes,
    # whitespace and signs — exactly the garbage a corrupted frame sends
    hexdigits = set("0123456789abcdefABCDEF")
    if not all(c in hexdigits for c in trace_id + span_id):
        return None
    return (trace_id, span_id)


def _otlp_value(v) -> dict:
    """Map a Python attribute value to an OTLP JSON AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(span: "Span") -> dict:
    """One span in OTLP/JSON encoding (opentelemetry-proto trace.v1.Span)."""
    return {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "parentSpanId": span.parent_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(span.start * 1e9)),
        "endTimeUnixNano": str(int(span.end * 1e9)),
        "attributes": [
            {"key": k, "value": _otlp_value(v)} for k, v in span.attrs.items()
        ],
        "status": {"code": 2 if span.status == "error" else 1},
    }


class OTLPExporter:
    """Pushes spans to an OTLP/HTTP collector (`/v1/traces`, JSON
    encoding) — the standard Jaeger ≥1.35 / otel-collector ingest.
    Mirrors ref: app/tracer/trace.go:40-124 which exports via OTLP
    to Jaeger. Dependency-free: urllib POST from a background thread;
    spans batch until `batch_size` or `flush_interval`, and a dead
    collector drops batches (bounded queue) rather than stalling the
    node — tracing must never backpressure duty processing."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "charon-tpu",
        batch_size: int = 256,
        flush_interval: float = 5.0,
        max_queue: int = 8192,
    ):
        import queue
        import threading

        if not endpoint.rstrip("/").endswith("/v1/traces"):
            endpoint = endpoint.rstrip("/") + "/v1/traces"
        self.endpoint = endpoint
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.dropped = 0  # spans lost to a full queue / dead collector
        self.exported = 0
        self._q: "queue.Queue[Span | None]" = queue.Queue(maxsize=max_queue)
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    def offer(self, span: "Span") -> None:
        try:
            self._q.put_nowait(span)
        except Exception:
            self.dropped += 1

    def _post(self, batch: list["Span"]) -> None:
        import urllib.request

        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {
                                    "key": "service.name",
                                    "value": {"stringValue": self.service_name},
                                }
                            ]
                        },
                        "scopeSpans": [
                            {
                                "scope": {"name": "charon_tpu.app.tracer"},
                                "spans": [span_to_otlp(s) for s in batch],
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5.0):
                self.exported += len(batch)
        except Exception:
            self.dropped += len(batch)

    def _run(self) -> None:
        import queue

        batch: list[Span] = []
        deadline = time.monotonic() + self.flush_interval
        while True:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                item = ()  # timer tick
            if item is None:  # shutdown sentinel
                if batch:
                    self._post(batch)
                return
            if item != ():
                batch.append(item)
            if len(batch) >= self.batch_size or (
                batch and time.monotonic() >= deadline
            ):
                self._post(batch)
                batch = []
            if time.monotonic() >= deadline:
                deadline = time.monotonic() + self.flush_interval

    def shutdown(self, timeout: float = 10.0) -> None:
        """Flush pending spans and stop the export thread. A full queue
        still gets its sentinel (blocking put with a bound) so the
        flush-on-shutdown contract holds after a long collector outage."""
        import queue

        try:
            self._q.put(None, timeout=timeout / 2)
        except queue.Full:
            return  # exporter thread is wedged; give up without joining
        self._thread.join(timeout=timeout)


class Tracer:
    """Ring-buffered span store with optional JSONL export and optional
    OTLP/HTTP push (ref: app/tracer Init wiring, app/app.go:1014-1027)."""

    def __init__(
        self,
        capacity: int = 4096,
        jsonl_path: str | None = None,
        exporter: OTLPExporter | None = None,
    ):
        import threading

        self.spans: deque[Span] = deque(maxlen=capacity)
        self.jsonl_path = jsonl_path
        self.exporter = exporter
        self._file = None
        # record() runs from the event loop AND worker threads (plane
        # span bridge); serialize the lazy open and the line writes so
        # neither a double-open leaks a descriptor nor lines interleave
        self._file_lock = threading.Lock()
        # called with each finished Span (same thread that records it —
        # may be a worker thread, so hooks must be thread-safe). Feeds
        # app/metrics.span_metrics and the slow-duty detector.
        self.hooks: list = []

    def record(self, span: Span) -> None:
        self.spans.append(span)
        for hook in self.hooks:
            try:
                hook(span)
            except Exception:  # noqa: BLE001 — observers never break tracing
                pass
        if self.jsonl_path:
            with self._file_lock:
                if self._file is None:
                    os.makedirs(
                        os.path.dirname(self.jsonl_path) or ".",
                        exist_ok=True,
                    )
                    self._file = open(self.jsonl_path, "a")
                self._file.write(json.dumps(span.to_json()) + "\n")
                self._file.flush()
        if self.exporter is not None:
            self.exporter.offer(span)

    def dump(self, trace_id: str | None = None) -> list[dict]:
        # snapshot first: record() appends from worker threads (plane
        # span bridge), and a Python-level comprehension over the live
        # deque would raise 'deque mutated during iteration' mid-scrape;
        # list(deque) copies atomically under the GIL
        spans = list(self.spans)
        return [
            s.to_json()
            for s in spans
            if trace_id is None or s.trace_id == trace_id
        ]

    def close(self) -> None:
        with self._file_lock:
            if self._file:
                self._file.close()
                self._file = None
        if self.exporter is not None:
            self.exporter.shutdown()


_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL


def set_global_tracer(tracer: Tracer) -> None:
    global _GLOBAL
    _GLOBAL = tracer


def duty_trace_id(duty) -> str:
    """Deterministic trace id for a duty — identical on every node
    (ref: core/tracing.go StartDutyTrace derives the id from the duty)."""
    return hashlib.sha256(
        b"charon-tpu-trace" + str(duty).encode()
    ).hexdigest()[:32]


@contextlib.contextmanager
def span(
    name: str,
    duty=None,
    tracer: Tracer | None = None,
    remote: tuple[str, str] | None = None,
    **attrs,
):
    """Start a span; nests under the context's current span. If `duty` is
    given and there is no active trace, the span roots a duty trace.
    `remote` is a (trace_id, span_id) pair propagated from a peer node's
    transport frame (parse_ctx output): with no local parent the span
    joins the remote trace under that parent, so cross-node timelines
    carry true parentage instead of four disconnected roots."""
    tracer = tracer or _GLOBAL
    parent = _current.get()
    if parent is not None:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    elif remote is not None:
        trace_id, parent_id = remote
    elif duty is not None:
        trace_id = duty_trace_id(duty)
        parent_id = ""
    else:
        trace_id = secrets.token_hex(16)
        parent_id = ""
    if duty is not None:
        attrs.setdefault("duty", str(duty))
        slot = getattr(duty, "slot", None)
        if slot is not None:
            attrs.setdefault("slot", slot)
    s = Span(
        trace_id=trace_id,
        span_id=secrets.token_hex(8),
        parent_id=parent_id,
        name=name,
        start=time.time(),
        attrs=attrs,
    )
    token = _current.set(s)
    try:
        yield s
    except BaseException as e:
        s.status = "error"
        s.attrs["error"] = repr(e)
        raise
    finally:
        s.end = time.time()
        _current.reset(token)
        tracer.record(s)


def tracing(tracer: Tracer | None = None):
    """wire() option wrapping every subscription edge in a span.
    Canonical implementation lives in core/wire.py (sibling of
    instrument/tracking); kept here as an alias for existing callers."""
    from charon_tpu.core.wire import tracing as _wire_tracing

    return _wire_tracing(tracer)


def record_span(
    name: str,
    trace_id: str,
    parent_id: str,
    start: float,
    end: float,
    tracer: Tracer | None = None,
    status: str = "ok",
    **attrs,
) -> Span:
    """Record an already-measured span (explicit wall-clock window) —
    the bridge path for stages timed outside a context manager, e.g.
    the crypto plane's decode/pack/device stages delivered via
    FlushStats from worker threads."""
    s = Span(
        trace_id=trace_id,
        span_id=secrets.token_hex(8),
        parent_id=parent_id,
        name=name,
        start=start,
        end=end,
        attrs=attrs,
        status=status,
    )
    (tracer or _GLOBAL).record(s)
    return s


def plane_span_bridge(tracer: Tracer | None = None, inner_hook=None):
    """SlotCoalescer.stats_hook adapter: bridge each flush's pipeline
    stages (decode, pack, device) into real tracer spans, replacing the
    old ad-hoc `trace=True` (start, end) tuples.

    A flush coalesces submissions from several duties; `stats.parents`
    carries each submission's captured span context, so the stage spans
    are recorded into EVERY participating duty trace — each duty's
    timeline shows the shared device window it rode. Submissions with
    no active trace context get one standalone flush trace. Runs on the
    device worker thread (Tracer.record is thread-safe); `inner_hook`
    chains the plain metrics hook."""

    def hook(stats) -> None:
        t = tracer or _GLOBAL
        parents = []
        seen: set[str] = set()
        for trace_id, span_id in getattr(stats, "parents", ()) or ():
            if trace_id not in seen:
                seen.add(trace_id)
                parents.append((trace_id, span_id))
        if not parents:
            parents = [(secrets.token_hex(16), "")]
        stages = []
        if stats.decode_spans:
            stages.append(
                (
                    "cryptoplane.decode",
                    min(s for s, _ in stats.decode_spans),
                    max(e for _, e in stats.decode_spans),
                    {"chunks": len(stats.decode_spans)},
                )
            )
        if stats.pack_span is not None:
            stages.append(
                ("cryptoplane.pack", *stats.pack_span, {})
            )
        if stats.device_span is not None:
            stages.append(
                (
                    "cryptoplane.device",
                    *stats.device_span,
                    {"fallback": stats.fallback},
                )
            )
        start = min((s for _, s, _, _ in stages), default=0.0)
        end = max((e for _, _, e, _ in stages), default=0.0)
        flush_attrs = {
            "jobs": stats.jobs,
            "lanes": stats.lanes,
            "window": stats.window,
            "inflight": stats.inflight,
            "fallback": stats.fallback,
        }
        if stats.padded_lanes:
            flush_attrs["bucket"] = stats.padded_lanes
            flush_attrs["pad_lanes"] = stats.pad_lanes
        tenant_lanes = getattr(stats, "tenant_lanes", ()) or ()
        if tenant_lanes:
            # multi-tenant service (core/cryptosvc): name every tenant
            # whose lanes rode this flush, so a duty timeline shows WHO
            # shared the device window with it
            flush_attrs["tenants"] = ",".join(t for t, _ in tenant_lanes)
        for i, (trace_id, parent_id) in enumerate(parents):
            # one flush -> one record per participating duty trace: mark
            # the copies beyond the first so metric hooks (span_metrics)
            # count each physical flush stage once, not once per duty
            dup = {"shared": True} if i else {}
            flush = record_span(
                "cryptoplane.flush",
                trace_id,
                parent_id,
                start,
                end,
                tracer=t,
                **flush_attrs,
                **dup,
            )
            for name, s, e, attrs in stages:
                record_span(
                    name,
                    trace_id,
                    flush.span_id,
                    s,
                    e,
                    tracer=t,
                    **attrs,
                    **dup,
                )
        if inner_hook is not None:
            inner_hook(stats)

    return hook


# -- per-duty timeline assembly (served at /debug/duty/<slot>) ---------------


def merge_jsonl(paths) -> list[dict]:
    """Merge per-node span JSONL exports into one span list (dedup by
    span_id, sorted by start) — the offline cross-node merge the
    deterministic duty trace ids exist for."""
    seen: set[str] = set()
    spans: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                s = json.loads(line)
                if s["span_id"] in seen:
                    continue
                seen.add(s["span_id"])
                spans.append(s)
    spans.sort(key=lambda s: s["start_us"])
    return spans


def duty_timeline(
    slot: int, tracer: Tracer | None = None, spans: list[dict] | None = None
) -> list[dict]:
    """Assemble the per-duty timelines for one slot: every trace that
    carries a span with this slot attribute, as a depth-annotated span
    forest ordered by start time. `spans` overrides the tracer's live
    ring (e.g. a merged cross-node JSONL export)."""
    if spans is None:
        spans = (tracer or _GLOBAL).dump()
    # one pass: bucket by trace_id, then keep the traces at this slot
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    trace_ids = {
        s["trace_id"] for s in spans if s["attrs"].get("slot") == slot
    }
    timelines = []
    for trace_id in sorted(trace_ids):
        group = by_trace[trace_id]
        by_id = {s["span_id"]: s for s in group}
        children: dict[str, list] = {}
        roots = []
        for s in group:
            if s["parent_id"] and s["parent_id"] in by_id:
                children.setdefault(s["parent_id"], []).append(s)
            else:
                roots.append(s)
        t0 = min(s["start_us"] for s in group)
        t1 = max(s["start_us"] + s["duration_us"] for s in group)
        flat: list[dict] = []

        def walk(s: dict, depth: int) -> None:
            flat.append(
                {
                    "name": s["name"],
                    "depth": depth,
                    "offset_us": s["start_us"] - t0,
                    "duration_us": s["duration_us"],
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                    "attrs": s["attrs"],
                    "status": s["status"],
                }
            )
            for c in sorted(
                children.get(s["span_id"], ()), key=lambda c: c["start_us"]
            ):
                walk(c, depth + 1)

        for root in sorted(roots, key=lambda s: s["start_us"]):
            walk(root, 0)
        duty = next(
            (s["attrs"]["duty"] for s in group if "duty" in s["attrs"]), ""
        )
        timelines.append(
            {
                "trace_id": trace_id,
                "duty": duty,
                "slot": slot,
                "start_us": t0,
                "wall_us": t1 - t0,
                "spans": flat,
            }
        )
    return timelines


def render_waterfall(timelines: list[dict], width: int = 40) -> str:
    """Plain-text waterfall of duty_timeline() output — offsets,
    durations and a scaled bar per span, nested by parentage."""
    out: list[str] = []
    for tl in timelines:
        out.append(
            f"duty {tl['duty'] or '?'}  trace {tl['trace_id']}  "
            f"wall {tl['wall_us'] / 1000:.1f}ms"
        )
        scale = max(tl["wall_us"], 1)
        for s in tl["spans"]:
            left = int(s["offset_us"] * width / scale)
            bar_len = max(1, int(s["duration_us"] * width / scale))
            bar = " " * left + "#" * min(bar_len, width - left)
            mark = " !" if s["status"] == "error" else ""
            out.append(
                f"  {s['offset_us'] / 1000:8.1f}ms "
                f"{s['duration_us'] / 1000:8.1f}ms "
                f"|{bar:<{width}}| "
                + "  " * s["depth"]
                + s["name"]
                + mark
            )
        out.append("")
    return "\n".join(out)
