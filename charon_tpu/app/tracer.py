"""Workflow tracing: duty-rooted spans across every wire edge.

Mirrors ref: app/tracer/trace.go (OpenTelemetry -> Jaeger) and
core/tracing.go (span-wrapped workflow steps, duty-rooted trace IDs via
StartDutyTrace). Redesign: a dependency-free span recorder — spans carry
OTel-compatible ids (128-bit trace, 64-bit span), nest via contextvars
(async-safe), and export to a ring buffer served at /debug/traces plus an
optional JSONL file. Duty traces use a DETERMINISTIC trace id derived
from the duty, so spans recorded on different nodes of the cluster can be
merged into one cross-node trace offline — same property the reference
gets from propagating trace context in its p2p envelopes.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import secrets
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class Span:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str  # 16 hex chars or ""
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"  # ok | error

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_us": int(self.start * 1e6),
            "duration_us": int((self.end - self.start) * 1e6),
            "attrs": self.attrs,
            "status": self.status,
        }


_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "charon_tpu_span", default=None
)


class Tracer:
    """Ring-buffered span store with optional JSONL export
    (ref: app/tracer Init wiring, app/app.go:1014-1027)."""

    def __init__(self, capacity: int = 4096, jsonl_path: str | None = None):
        self.spans: deque[Span] = deque(maxlen=capacity)
        self.jsonl_path = jsonl_path
        self._file = None

    def record(self, span: Span) -> None:
        self.spans.append(span)
        if self.jsonl_path:
            if self._file is None:
                os.makedirs(
                    os.path.dirname(self.jsonl_path) or ".", exist_ok=True
                )
                self._file = open(self.jsonl_path, "a")
            self._file.write(json.dumps(span.to_json()) + "\n")
            self._file.flush()

    def dump(self, trace_id: str | None = None) -> list[dict]:
        return [
            s.to_json()
            for s in self.spans
            if trace_id is None or s.trace_id == trace_id
        ]

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None


_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL


def set_global_tracer(tracer: Tracer) -> None:
    global _GLOBAL
    _GLOBAL = tracer


def duty_trace_id(duty) -> str:
    """Deterministic trace id for a duty — identical on every node
    (ref: core/tracing.go StartDutyTrace derives the id from the duty)."""
    return hashlib.sha256(
        b"charon-tpu-trace" + str(duty).encode()
    ).hexdigest()[:32]


@contextlib.contextmanager
def span(name: str, duty=None, tracer: Tracer | None = None, **attrs):
    """Start a span; nests under the context's current span. If `duty` is
    given and there is no active trace, the span roots a duty trace."""
    tracer = tracer or _GLOBAL
    parent = _current.get()
    if parent is not None:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    elif duty is not None:
        trace_id = duty_trace_id(duty)
        parent_id = ""
    else:
        trace_id = secrets.token_hex(16)
        parent_id = ""
    if duty is not None:
        attrs.setdefault("duty", str(duty))
    s = Span(
        trace_id=trace_id,
        span_id=secrets.token_hex(8),
        parent_id=parent_id,
        name=name,
        start=time.time(),
        attrs=attrs,
    )
    token = _current.set(s)
    try:
        yield s
    except BaseException as e:
        s.status = "error"
        s.attrs["error"] = repr(e)
        raise
    finally:
        s.end = time.time()
        _current.reset(token)
        tracer.record(s)


def tracing(tracer: Tracer | None = None):
    """wire() option wrapping every subscription edge in a span
    (ref: core/tracing.go + core.WithTracing, app/app.go:569)."""

    def option(name: str, fn):
        async def wrapped(duty, *args, **kwargs):
            with span(name, duty=duty, tracer=tracer):
                return await fn(duty, *args, **kwargs)

        return wrapped

    return option
