"""Feature flags with rollout statuses.

Mirrors ref: app/featureset/featureset.go:12-40 — features register with a
minimum rollout status (alpha/beta/stable); the configured status enables
every feature at or above it, with explicit enable/disable overrides.
"""

from __future__ import annotations

import enum


class Status(enum.IntEnum):
    ALPHA = 0
    BETA = 1
    STABLE = 2


class Feature(str, enum.Enum):
    # Current framework features (the reference's set evolves per release;
    # these are ours).
    AGG_SIG_DB_V2 = "agg_sigdb_v2"
    # Eager-double-linear consensus round timer (ref:
    # app/featureset/featureset.go:32 EagerDoubleLinear; timer semantics
    # in core/qbft.py DoubleEagerLinearRoundTimer).
    EAGER_DOUBLE_LINEAR = "eager_double_linear"
    QBFT_CONSENSUS = "qbft_consensus"
    TPU_BATCH_VERIFY = "tpu_batch_verify"
    JSON_REQUESTS = "json_requests"
    SYNTHETIC_DUTIES = "synthetic_duties"


_STATUSES: dict[Feature, Status] = {
    Feature.AGG_SIG_DB_V2: Status.ALPHA,
    # stable = cluster default, matching ref featureset.go:53
    Feature.EAGER_DOUBLE_LINEAR: Status.STABLE,
    Feature.QBFT_CONSENSUS: Status.STABLE,
    Feature.TPU_BATCH_VERIFY: Status.STABLE,
    Feature.JSON_REQUESTS: Status.BETA,
    # ref: app/eth2wrap/synthproposer.go is test-path-only; alpha here
    Feature.SYNTHETIC_DUTIES: Status.ALPHA,
}

_min_status = Status.STABLE
_enabled: set[Feature] = set()
_disabled: set[Feature] = set()


def init(min_status: Status = Status.STABLE, enable: list[Feature] = (), disable: list[Feature] = ()) -> None:
    """ref: featureset.Init (app/app.go:136)."""
    global _min_status, _enabled, _disabled
    _min_status = min_status
    _enabled = set(enable)
    _disabled = set(disable)


def enabled(feature: Feature) -> bool:
    if feature in _disabled:
        return False
    if feature in _enabled:
        return True
    return _STATUSES.get(feature, Status.ALPHA) >= _min_status
