"""Stack sniping: detect co-located validator-stack processes.

Mirrors ref: app/stacksnipe/stacksnipe.go (wired app/app.go:155-156) —
periodically scans /proc for known Ethereum stack binaries running on the
same host and reports them as telemetry, giving operators visibility
into what shares the machine with the DV middleware.
"""

from __future__ import annotations

import asyncio
from pathlib import Path

# ref: stacksnipe.go binary allowlist (same stack components)
KNOWN_BINARIES = (
    "lighthouse",
    "prysm",
    "beacon-chain",
    "validator",
    "teku",
    "nimbus_beacon_node",
    "lodestar",
    "grandine",
    "geth",
    "nethermind",
    "besu",
    "erigon",
    "reth",
    "mev-boost",
    "charon",
)


def snipe(proc_root: str | Path = "/proc") -> dict[str, list[int]]:
    """One scan: binary name -> pids (ref: stacksnipe.go snipe)."""
    found: dict[str, list[int]] = {}
    root = Path(proc_root)
    try:
        entries = list(root.iterdir())
    except OSError:
        return found
    for entry in entries:
        if not entry.name.isdigit():
            continue
        try:
            cmdline = (entry / "cmdline").read_bytes()
        except OSError:
            continue
        if not cmdline:
            continue
        argv0 = cmdline.split(b"\x00", 1)[0].decode(errors="replace")
        base = argv0.rsplit("/", 1)[-1]
        for known in KNOWN_BINARIES:
            if base == known or base.startswith(known + "-"):
                found.setdefault(known, []).append(int(entry.name))
    return found


class StackSniper:
    """Periodic scanner feeding a metrics callback
    (ref: app/app.go wires stacksnipe with the promauto registry)."""

    def __init__(
        self,
        interval: float = 600.0,
        on_report=None,
        proc_root: str | Path = "/proc",
    ) -> None:
        self.interval = interval
        self.on_report = on_report
        self.proc_root = proc_root
        self.last: dict[str, list[int]] = {}
        self._task: asyncio.Task | None = None

    async def run(self) -> None:
        while True:
            self.last = snipe(self.proc_root)
            if self.on_report:
                self.on_report(self.last)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
