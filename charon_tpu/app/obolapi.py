"""Obol-API-style remote registry client.

Mirrors ref: app/obolapi/api.go — the reference can publish the cluster
lock after a DKG and upload partial exit shares to a remote coordination
API. The HTTP surface here is a minimal JSON REST client with the same
two capabilities; the testutil.obolapimock server implements the
matching endpoints for tests (ref: testutil/obolapimock).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import aiohttp


@dataclass
class ObolApiClient:
    base_url: str
    timeout: float = 10.0

    async def _post(self, path: str, body: dict) -> dict:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        ) as session:
            async with session.post(
                self.base_url.rstrip("/") + path, json=body
            ) as resp:
                if resp.status not in (200, 201):
                    raise RuntimeError(
                        f"obolapi {path} failed: HTTP {resp.status} "
                        f"{await resp.text()}"
                    )
                if resp.content_type == "application/json":
                    return await resp.json()
                return {}

    async def publish_lock(self, lock) -> dict:
        """Publish a cluster lock after the ceremony
        (ref: api.go PublishLock, wired dkg/dkg.go:118-128)."""
        return await self._post("/lock", lock.to_json())

    async def submit_partial_exit(
        self,
        lock_hash: bytes,
        share_idx: int,
        validator_pubkey: str,
        epoch: int,
        partial_signature: bytes,
    ) -> dict:
        """Upload one node's partial exit share
        (ref: api.go PostPartialExit, cmd/exit_sign.go)."""
        return await self._post(
            f"/exp/partial_exits/{lock_hash.hex()}",
            {
                "share_idx": share_idx,
                "validator_pubkey": validator_pubkey,
                "epoch": epoch,
                "partial_signature": partial_signature.hex(),
            },
        )

    async def fetch_full_exit(
        self, lock_hash: bytes, validator_pubkey: str
    ) -> dict | None:
        """Fetch the aggregated exit once threshold shares are uploaded
        (ref: api.go GetFullExit, cmd/exit_fetch.go)."""
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        ) as session:
            async with session.get(
                self.base_url.rstrip("/")
                + f"/exp/exit/{lock_hash.hex()}/{validator_pubkey}"
            ) as resp:
                if resp.status == 404:
                    return None
                if resp.status != 200:
                    raise RuntimeError(
                        f"obolapi exit fetch failed: HTTP {resp.status}"
                    )
                return await resp.json()
