"""App infrastructure: wiring, lifecycle, logging, retries, health.

Mirrors the reference's app layer (ref: app/ — lifecycle manager, log/z,
errors, retry, featureset, health, promauto, monitoring API) in asyncio
Python. The run() entry point (app/run.py) wires every component the way
ref app/app.go:131 does.
"""
