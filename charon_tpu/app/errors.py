"""Structured errors: fields + captured stack traces on exceptions.

Mirrors ref: app/errors + app/z — the reference replaces stdlib errors
with a structured type carrying zap fields and a creation stack trace,
wrapped as it crosses layers so logs show WHERE and WITH WHAT context a
failure happened. Python exceptions already chain (__cause__) and carry
tracebacks once RAISED; what they lack is (a) key-value context fields
and (b) a stack for errors that are constructed and logged without ever
being raised. This module adds both, the Python way:

    raise StructuredError("peer handshake failed", peer=idx, addr=addr)

    try:
        await dial()
    except OSError as e:
        raise wrap(e, "relay dial failed", relay=addr) from e

    log.error("duty failed", exc=e, **fields_of(e))  # merged chain fields

`fields_of` aggregates fields along the full __cause__/__context__ chain
(outermost wins on key conflicts), so a log site sees every layer's
context without manual threading — the analogue of the reference's
fields accumulating through errors.Wrap (ref: errors.go Wrap).
"""

from __future__ import annotations

import traceback


class StructuredError(Exception):
    """An error with key-value context fields and a creation stack.

    The creation stack matters for the construct-log-don't-raise pattern
    (ref: errors.go zap.StackSkip): `err.stack()` works whether or not
    the exception was ever raised.
    """

    def __init__(self, msg: str, **fields):
        super().__init__(msg)
        self.fields = fields
        # captured at construction, excluding this frame
        self._stack = traceback.extract_stack()[:-1]

    def stack(self) -> str:
        tb = self.__traceback__
        if tb is not None:  # raised: the real traceback is better
            return "".join(traceback.format_tb(tb))
        return "".join(traceback.format_list(self._stack))

    def __str__(self) -> str:
        base = super().__str__()
        if not self.fields:
            return base
        kv = " ".join(f"{k}={v!r}" for k, v in self.fields.items())
        return f"{base} [{kv}]"


def new(msg: str, **fields) -> StructuredError:
    """ref: errors.New — construct without raising."""
    return StructuredError(msg, **fields)


def sentinel(msg: str) -> StructuredError:
    """ref: errors.NewSentinel — module-level marker errors whose
    creation stack is noise; wrap() them at first return."""
    err = StructuredError(msg)
    err._stack = []
    return err


def wrap(err: BaseException, msg: str, **fields) -> StructuredError:
    """ref: errors.Wrap — layer a message + fields over a cause.
    Raise the result `from err` (or not — the cause is linked either
    way for fields_of / is_any)."""
    out = StructuredError(msg, **fields)
    out.__cause__ = err
    return out


def fields_of(err: BaseException | None) -> dict:
    """Merged fields along the cause chain, outermost layer winning
    (ref: the z.Field accumulation through wrapped errors)."""
    merged: dict = {}
    seen: set[int] = set()
    while err is not None and id(err) not in seen:
        seen.add(id(err))
        if isinstance(err, StructuredError):
            for k, v in err.fields.items():
                merged.setdefault(k, v)
        err = err.__cause__ or (
            None if err.__suppress_context__ else err.__context__
        )
    return merged


def is_any(err: BaseException | None, *sentinels: BaseException) -> bool:
    """ref: errors.Is over the chain — identity match against sentinel
    errors anywhere in the cause chain."""
    targets = {id(s) for s in sentinels}
    seen: set[int] = set()
    while err is not None and id(err) not in seen:
        seen.add(id(err))
        if id(err) in targets:
            return True
        err = err.__cause__ or (
            None if err.__suppress_context__ else err.__context__
        )
    return False
