"""Lifecycle manager: ordered async start/stop hooks.

Mirrors ref: app/lifecycle — hooks registered with explicit order labels,
started in order, stopped in reverse; app-context vs background tasks;
graceful then hard shutdown (lifecycle/manager.go:3-14, order.go).
"""

from __future__ import annotations

import asyncio
import enum
from dataclasses import dataclass, field
from typing import Awaitable, Callable


class Order(enum.IntEnum):
    """Start order (ref: app/lifecycle/order.go)."""

    TRACKER = 10
    P2P = 20
    MONITORING = 30
    VALIDATOR_API = 40
    DEADLINER = 50
    SCHEDULER = 60  # starts last: duties flow only once everything is up


@dataclass
class _Hook:
    order: int
    name: str
    fn: Callable
    background: bool  # background hooks run as tasks; sync hooks awaited


class LifecycleManager:
    def __init__(self) -> None:
        self._start_hooks: list[_Hook] = []
        self._stop_hooks: list[_Hook] = []
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()

    def register_start(self, order: int, name: str, fn, background: bool = True) -> None:
        self._start_hooks.append(_Hook(order, name, fn, background))

    def register_stop(self, order: int, name: str, fn) -> None:
        self._stop_hooks.append(_Hook(order, name, fn, False))

    async def run(self, stop_signal: asyncio.Event | None = None) -> None:
        """Start hooks in order; block until stop; stop in reverse order
        (ref: lifecycle/manager.go:65-85)."""
        for hook in sorted(self._start_hooks, key=lambda h: h.order):
            if hook.background:
                task = asyncio.create_task(hook.fn(), name=hook.name)
                self._tasks.append(task)
            else:
                await hook.fn()
        stop = stop_signal or self._stopped
        await stop.wait()
        await self.shutdown()

    def stop(self) -> None:
        self._stopped.set()

    async def shutdown(self, grace: float = 5.0) -> None:
        for hook in sorted(self._stop_hooks, key=lambda h: -h.order):
            try:
                await asyncio.wait_for(hook.fn(), grace)
            except Exception:
                pass
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
