"""TPU tbls backend: batched JAX kernels behind the Implementation API.

Where the reference binds herumi's C++ one-call-per-signature backend
(ref: tbls/herumi.go), this backend routes every operation through the
batched device engine (charon_tpu/ops/blsops.py). Single-item calls are
batches of one; the core workflow uses the *_batch entry points to push
whole duty-sets through one compiled XLA program per slot.

Host/device split (SURVEY.md §7 design stance):
  * secret material (keygen, Shamir split/recover, signing) stays on the
    host — the device only ever sees public points;
  * hash-to-curve (SHA-256 expand + SSWU) runs on the host, cached;
  * pairings, Lagrange recombination, point sums, and subgroup checks run
    batched on the device.

Caching: decompressed pubkeys are cached by compressed bytes (cluster
pubshares are a small static set — ref: core/validatorapi pubshare maps),
as are hashed messages.
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Mapping, NamedTuple, Sequence

from charon_tpu.crypto import g1g2, h2c
from charon_tpu.crypto.fields import R
from charon_tpu.ops import blsops
from charon_tpu.ops import curve as C
from charon_tpu.ops import limb
from charon_tpu.tbls import Implementation, TblsError
from charon_tpu.tbls.python_impl import PythonImpl, sig_to_point


def _decode_pubkey_point(pubkey: bytes):
    """Decompress + subgroup-check a pubkey (uncached decode body)."""
    try:
        pt = g1g2.g1_from_bytes(pubkey, subgroup_check=True)
    except ValueError as e:
        raise TblsError(str(e)) from e
    if pt is None:
        raise TblsError("infinite public key")
    return pt


def _decode_msg_point(data: bytes):
    return h2c.hash_to_g2(data)


class _CacheInfo(NamedTuple):
    hits: int
    misses: int
    maxsize: int
    currsize: int


class PointCache:
    """Thread-safe LRU point cache with BULK insertion (ISSUE 6).

    functools.lru_cache almost fits, but it cannot be pre-populated —
    and the whole point of the warm-up path is to decode a restart's
    key/message set through ONE device program and insert the results,
    so the first live slot starts at a ~100% hit rate instead of
    paying a python-bigint burst. Mirrors the lru_cache surface the
    metrics/test plumbing reads (cache_info / cache_clear) plus put()
    and __contains__ for the bulk path. Decode runs OUTSIDE the lock:
    the caches are hammered from the coalescer's decode pool, so
    concurrent misses of the same key may decode twice (same contract
    as lru_cache) but never block each other for milliseconds."""

    def __init__(self, decode, maxsize: int):
        self._decode = decode
        self._maxsize = maxsize
        self._data: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    def __call__(self, key):
        with self._lock:
            try:
                val = self._data[key]
            except KeyError:
                self._misses += 1
            else:
                self._data.move_to_end(key)
                self._hits += 1
                return val
        val = self._decode(key)  # bigint work — never under the lock
        self.put(key, val)
        return val

    def put(self, key, value) -> None:
        """Insert without decoding — the bulk warm-up entry point."""
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self._maxsize:
                self._data.popitem(last=False)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._data

    def cache_info(self) -> _CacheInfo:
        with self._lock:
            return _CacheInfo(
                self._hits, self._misses, self._maxsize, len(self._data)
            )

    def cache_clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._hits = 0
            self._misses = 0


def make_point_cache(decode, maxsize: int) -> PointCache:
    """LRU-wrap a point decoder. The module-level caches below use the
    production capacities; tests build small-capacity instances of the
    SAME wrapper to pin hit/eviction/concurrency/bulk-put behavior
    (the caches are hammered from the coalescer's decode pool, so
    PointCache's thread-safety is load-bearing)."""
    return PointCache(decode, maxsize)


# Decompressed pubkeys cached by compressed bytes (cluster pubshares are
# a small static set — ref: core/validatorapi pubshare maps), as are
# hashed messages. Shared by this impl AND core/cryptoplane's decode
# pool, and bulk-fed by the warm-up path below.
_cached_pubkey_point = make_point_cache(_decode_pubkey_point, 65536)
_cached_msg_point = make_point_cache(_decode_msg_point, 16384)

# Warm-up lanes per device program — THE default for every warm path
# (SlotCoalescer.warm_caches inherits it; docs/operations.md documents
# it): big enough to amortize dispatch, small enough that a warm chunk
# never monopolizes the device for whole seconds.
WARMUP_CHUNK = 512


def warm_point_caches(
    pubkeys: Sequence[bytes] = (),
    messages: Sequence[bytes] = (),
    engine: "blsops.BlsEngine | None" = None,
    device: bool | None = None,
    chunk: int = WARMUP_CHUNK,
) -> dict:
    """Bulk-populate the module point caches (ISSUE 6 cold path).

    Pubkeys decode through `decompress_g1_batch` (GLV subgroup check)
    and messages through `hash_to_g2_batch` (device SSWU + isogeny +
    psi cofactor clearing) in `chunk`-sized device programs; the
    python rung (`device=False`, or auto on a non-TPU backend) decodes
    per point on host — still a valid warm-up, just the old cost.
    Lanes the device marks invalid are NOT inserted: the on-demand
    decode re-raises the precise error when (if ever) the key is used.

    A device failure mid-pass (dead tunnel, XLA runtime error) steps
    the REST of the pass down to the python rung instead of raising —
    the PR 2 ladder discipline; warm-up can degrade but never aborts a
    rotation, and the step-down is visible as python lanes in the
    stats.

    Returns per-cache stats: lanes by source (device/python/cached/
    invalid) plus wall seconds — the shape app/metrics.observe_warmup
    records."""
    import time as _time

    t0 = _time.monotonic()
    if device is None:
        device = limb._is_tpu_backend()
    eng = None
    if device:
        try:
            eng = engine or blsops.default_engine()
        except Exception:  # jax-less / broken backend: host rung
            device = False
    rung = {"device": device}
    stats = {
        "pubkey": {"device": 0, "python": 0, "cached": 0, "invalid": 0},
        "message": {"device": 0, "python": 0, "cached": 0, "invalid": 0},
    }

    def work(keys, cache, bulk, single, name):
        st = stats[name]
        # lanes are UNIQUE keys: duplicates in the input collapse before
        # accounting, so a cold start with a repeated key never reports
        # source="cached" lanes it did not actually skip
        uniq = list(dict.fromkeys(keys))
        todo = [k for k in uniq if k not in cache]
        st["cached"] += len(uniq) - len(todo)
        cap = cache.cache_info().maxsize
        if len(todo) > cap:
            # decoding past capacity would only evict its own results:
            # warm the LAST cap keys (insertion order keeps them alive)
            # and report the rest as overflow — never burn device work
            # on lanes that cannot survive, never report them "warmed"
            st["overflow"] = st.get("overflow", 0) + len(todo) - cap
            todo = todo[-cap:]
        for i in range(0, len(todo), chunk):
            batch = todo[i : i + chunk]
            if rung["device"]:
                try:
                    pts, valid = bulk(batch)
                except Exception:  # noqa: BLE001 — device rung failure
                    # (dead tunnel / XLA error): step the rest of the
                    # pass down to host decode, never raise out of a
                    # warm-up
                    rung["device"] = False
                else:
                    for k, pt, ok in zip(batch, pts, valid):
                        if ok and pt is not None:
                            cache.put(k, pt)
                            st["device"] += 1
                        else:
                            st["invalid"] += 1
                    continue
            for k in batch:
                try:
                    cache.put(k, single(k))
                    st["python"] += 1
                except (TblsError, ValueError):
                    st["invalid"] += 1

    work(
        pubkeys,
        _cached_pubkey_point,
        lambda b: eng.decompress_g1_batch(b, subgroup_check=True),
        _decode_pubkey_point,
        "pubkey",
    )
    work(
        messages,
        _cached_msg_point,
        lambda b: eng.hash_to_g2_batch(b),
        _decode_msg_point,
        "message",
    )
    stats["seconds"] = _time.monotonic() - t0
    return stats


class TPUImpl(Implementation):
    """Batched device implementation.

    verify_inputs: when True (default), signature points are
    subgroup-checked on device before use. The core workflow's aggregation
    path sets False because every partial signature it aggregates was
    already individually verified on arrival (ref: core/parsigex
    verification before store).
    """

    def __init__(
        self,
        engine: blsops.BlsEngine | None = None,
        verify_inputs: bool = True,
        decode_mode: str = "auto",
    ):
        self.engine = engine or blsops.default_engine()
        self.verify_inputs = verify_inputs
        # signature decompression routing (ISSUE 5): "device" batches the
        # Fp2 sqrt + sign + psi subgroup check into one kernel (folding
        # the separate subgroup_check_g2_batch dispatch), "python" keeps
        # the host bigint path, "auto" = device on TPU backends only —
        # the python rung stays the degradation floor below it.
        if decode_mode not in ("auto", "device", "python"):
            raise ValueError(f"bad decode_mode {decode_mode!r}")
        self.decode_mode = decode_mode
        self._host = PythonImpl()
        # degradation ladder for device failures in the RLC batch path
        # (mirrors bench.py): Pippenger MSM off first (the newest kernel
        # family), then fused-fp2 off, then RLC off entirely
        self._degrade_rungs = ["msm-off", "fp2-fusion-off"]

    # -- host-side secret ops (delegate to the Python backend) ------------

    def generate_secret_key(self) -> bytes:
        return self._host.generate_secret_key()

    def secret_to_public_key(self, secret: bytes) -> bytes:
        return self._host.secret_to_public_key(secret)

    def threshold_split(self, secret: bytes, total: int, threshold: int):
        return self._host.threshold_split(secret, total, threshold)

    def recover_secret(self, shares, total: int, threshold: int) -> bytes:
        return self._host.recover_secret(shares, total, threshold)

    def sign(self, secret: bytes, data: bytes) -> bytes:
        return self._host.sign(secret, data)

    # -- decompression helpers -------------------------------------------

    def _device_decode(self) -> bool:
        if self.decode_mode != "auto":
            return self.decode_mode == "device"
        return limb._is_tpu_backend()

    def _sig_points(self, sigs: Sequence[bytes], what: str) -> list:
        """Decompress signatures — the bulk path runs the whole decode
        (sqrt + sign + on-curve + subgroup) as ONE device program
        (ops/decompress.py); the python rung decompresses on host and
        pays a separate subgroup dispatch when verify_inputs is set."""
        if self._device_decode():
            pts, valid = self.engine.decompress_g2_batch(
                sigs, subgroup_check=self.verify_inputs
            )
            for pt, ok in zip(pts, valid):
                if not ok:
                    raise TblsError(
                        f"{what} failed decompression or subgroup check"
                    )
                if pt is None:
                    raise TblsError(f"infinite {what}")
            return pts
        pts = []
        for sig in sigs:
            pt = sig_to_point(sig, subgroup_check=False)
            if pt is None:
                raise TblsError(f"infinite {what}")
            pts.append(pt)
        if self.verify_inputs:
            ok = self.engine.subgroup_check_g2_batch(pts)
            if not all(ok):
                raise TblsError(f"{what} not in G2 subgroup")
        return pts

    # -- verification -----------------------------------------------------

    def verify(self, pubkey: bytes, data: bytes, sig: bytes) -> None:
        if not self.verify_batch([(pubkey, data, sig)])[0]:
            raise TblsError("signature verification failed")

    # Below this size the per-lane kernel is used directly: RLC's shared
    # tail amortizes only over larger batches, and small shapes would
    # compile a second kernel family for no win.
    RLC_MIN_BATCH = 16

    def verify_batch(self, items) -> list[bool]:
        if not items:
            return []
        n = len(items)
        pks: list = [None] * n
        msgs: list = [None] * n
        sigs: list = [None] * n
        ok = [True] * n
        device_decode = self._device_decode()
        if device_decode:
            # one device program decompresses AND subgroup-checks every
            # signature lane — the separate subgroup_check_g2_batch
            # dispatch below is folded away (ISSUE 5). Malformed lanes
            # stay per-lane False (None points contribute neutrally).
            sigs, sig_ok = self.engine.decompress_g2_batch(
                [sig for _, _, sig in items],
                subgroup_check=self.verify_inputs,
            )
            for i in range(n):
                if not sig_ok[i] or sigs[i] is None:
                    ok[i] = False
                    sigs[i] = None
        for i, (pk, data, sig) in enumerate(items):
            try:
                pks[i] = _cached_pubkey_point(pk)
                msgs[i] = _cached_msg_point(data)
                if not device_decode:
                    sigs[i] = sig_to_point(sig, subgroup_check=False)
                if sigs[i] is None:
                    raise TblsError("infinite signature")
            except TblsError:
                ok[i] = False
                pks[i] = msgs[i] = sigs[i] = None
        accepted = (
            self._rlc_guarded(items, pks, msgs, sigs)
            if n >= self.RLC_MIN_BATCH
            else False
        )
        if accepted:
            # the whole batch verified in one shared-final-exp program;
            # decode failures (ok[i] False) pass None lanes which
            # contribute neutrally and stay False below
            verified = [True] * n
        else:
            verified = self.engine.verify_batch(pks, msgs, sigs)
        in_subgroup = [True] * n
        if self.verify_inputs and not device_decode:
            # ship only lanes that decoded: known-False lanes (None)
            # would pad the batch for a check whose answer is unused
            live = [i for i in range(n) if sigs[i] is not None]
            if live:
                checked = self.engine.subgroup_check_g2_batch(
                    [sigs[i] for i in live]
                )
                for i, s in zip(live, checked):
                    in_subgroup[i] = s
        return [o and v and s for o, v, s in zip(ok, verified, in_subgroup)]

    def _rlc_guarded(self, items, pks, msgs, sigs) -> bool:
        """_rlc_accepts with device-failure containment: a COMPILE or
        runtime error on the accelerator is not a crypto verdict — step
        down the same degradation ladder as bench.py (fused-fp2 off with
        the jit caches cleared so the flag actually re-traces, then RLC
        off for this impl) and keep serving verifies on the per-lane
        engine rather than breaking the duty pipeline."""
        while True:
            try:
                return self._rlc_accepts(items, pks, msgs, sigs)
            except TblsError:
                raise
            except Exception as e:  # noqa: BLE001 — device/compile failure
                from charon_tpu.app import log
                from charon_tpu.ops import fptower

                from charon_tpu.ops import msm as MSM

                rung = self._degrade_rungs.pop(0) if self._degrade_rungs else None
                if rung == "msm-off" and not MSM.msm_active():
                    # another impl already burned this rung process-wide
                    rung = (
                        self._degrade_rungs.pop(0)
                        if self._degrade_rungs
                        else None
                    )
                if rung == "fp2-fusion-off" and not fptower._FP2_FUSION:
                    # another impl already burned this rung process-wide;
                    # retrying the identical path would fail identically
                    rung = None
                log.warn(
                    "RLC batch path failed on device; degrading",
                    topic="tbls",
                    err=f"{type(e).__name__}: {str(e)[:160]}",
                    rung=rung or "rlc-disabled",
                )
                if rung in ("msm-off", "fp2-fusion-off"):
                    from charon_tpu.ops import blsops

                    if rung == "msm-off":
                        MSM.set_msm(False)
                    else:
                        fptower.set_fp2_fusion(False)
                    # the flags are read at TRACE time: without dropping
                    # the cached jit wrappers the retry re-runs the
                    # identical compiled executable
                    blsops.clear_kernel_caches()
                    continue
                self.RLC_MIN_BATCH = 1 << 62  # disables RLC for this impl
                return False

    # At most this many distinct messages take the grouped kernel (one
    # Miller pair per message); beyond it, the ungrouped RLC kernel.
    RLC_MAX_GROUPS = 8

    def _rlc_accepts(self, items, pks, msgs, sigs) -> bool:
        """Whole-batch RLC check, grouped by message when few distinct
        messages exist (a DV cluster's common case: every validator in a
        committee signs the same attestation data, so a slot's partial
        sigs collapse to a handful of Miller pairs)."""
        distinct: dict[bytes, list[int]] = {}
        for i, (_, data, _) in enumerate(items):
            distinct.setdefault(data, []).append(i)
        if len(distinct) <= self.RLC_MAX_GROUPS:
            groups = []
            for data, lane_ids in distinct.items():
                lanes = [
                    (pks[i], sigs[i])
                    for i in lane_ids
                    if pks[i] is not None
                ]
                if lanes:
                    groups.append((_cached_msg_point(data), lanes))
            if not groups:
                return True  # nothing decodable; per-lane flags carry it
            return self.engine.verify_batch_grouped_rlc(groups)
        return self.engine.verify_batch_rlc(pks, msgs, sigs)

    def verify_aggregate(self, pubkeys: Sequence[bytes], data: bytes, sig: bytes) -> None:
        if not pubkeys:
            raise TblsError("no public keys")
        pts = [_cached_pubkey_point(pk) for pk in pubkeys]
        [agg_pk] = self.engine.aggregate_pks_batch([pts])
        if agg_pk is None:
            raise TblsError("aggregate public key is infinite")
        [sig_pt] = self._sig_points([sig], "signature")
        [ok] = self.engine.verify_batch(
            [agg_pk], [_cached_msg_point(data)], [sig_pt]
        )
        if not ok:
            raise TblsError("aggregate signature verification failed")

    # -- aggregation ------------------------------------------------------

    def threshold_aggregate(self, partials: Mapping[int, bytes]) -> bytes:
        return self.threshold_aggregate_batch([partials])[0]

    def threshold_aggregate_batch(self, batch) -> list[bytes]:
        if not batch:
            return []
        point_batch = []
        for partials in batch:
            if not partials:
                raise TblsError("no partial signatures")
            if any(i <= 0 for i in partials):
                raise TblsError("share indices are 1-based")
            flat = list(partials.items())
            pts = self._sig_points([s for _, s in flat], "partial signature")
            point_batch.append({i: pt for (i, _), pt in zip(flat, pts)})
        t = len(point_batch[0])
        if any(len(p) != t for p in point_batch):
            raise TblsError("inconsistent thresholds in batch")
        out = self.engine.threshold_aggregate_batch(point_batch)
        return [g1g2.g2_to_bytes(pt) for pt in out]

    def aggregate(self, sigs: Sequence[bytes]) -> bytes:
        return self.aggregate_batch([sigs])[0]

    def aggregate_batch(self, groups) -> list[bytes]:
        if not groups:
            return []
        point_groups = []
        for sigs in groups:
            if not sigs:
                raise TblsError("no signatures")
            point_groups.append(self._sig_points(sigs, "signature"))
        out = self.engine.aggregate_sigs_batch(point_groups)
        return [g1g2.g2_to_bytes(pt) for pt in out]
