"""Threshold-BLS facade with swappable backends.

Mirrors the reference's plugin boundary (ref: tbls/tbls.go:28-76): a single
`Implementation` interface behind package-level functions, switched with
`set_implementation`. The reference swaps between herumi (C++/asm) and a
kryptology backend; this framework swaps between:

  * PythonImpl  — pure-Python bigint reference backend (charon_tpu/crypto),
  * TPUImpl     — the batched JAX engine (charon_tpu/ops), which also
                  exposes the batch APIs the core workflow feeds whole
                  duty-sets through.

Wire types follow eth2 exactly (ref: tbls/tbls.go:16-25): PrivateKey is 32
bytes, PublicKey 48 bytes (compressed G1), Signature 96 bytes (compressed
G2). All byte values are ZCash-format compressed points.

Batch extensions (not in the reference — the point of this framework):
`verify_batch`, `threshold_aggregate_batch`, `aggregate_batch` accept whole
slot-level workloads and execute them as single device programs.
"""

from __future__ import annotations

import abc
from typing import Mapping, Sequence

PRIVATE_KEY_LEN = 32
PUBLIC_KEY_LEN = 48
SIGNATURE_LEN = 96

PrivateKey = bytes
PublicKey = bytes
Signature = bytes


class TblsError(Exception):
    """Raised on malformed inputs or failed verification."""


class Implementation(abc.ABC):
    """The 11-op backend contract (ref: tbls/tbls.go:28-69) plus batch ops."""

    # -- key management ---------------------------------------------------

    @abc.abstractmethod
    def generate_secret_key(self) -> PrivateKey: ...

    @abc.abstractmethod
    def secret_to_public_key(self, secret: PrivateKey) -> PublicKey: ...

    @abc.abstractmethod
    def threshold_split(
        self, secret: PrivateKey, total: int, threshold: int
    ) -> dict[int, PrivateKey]: ...

    @abc.abstractmethod
    def recover_secret(
        self, shares: Mapping[int, PrivateKey], total: int, threshold: int
    ) -> PrivateKey: ...

    # -- signing / verification ------------------------------------------

    @abc.abstractmethod
    def sign(self, secret: PrivateKey, data: bytes) -> Signature: ...

    @abc.abstractmethod
    def verify(self, pubkey: PublicKey, data: bytes, sig: Signature) -> None:
        """Raises TblsError unless `sig` is a valid signature of `data`."""

    @abc.abstractmethod
    def verify_aggregate(
        self, pubkeys: Sequence[PublicKey], data: bytes, sig: Signature
    ) -> None:
        """FastAggregateVerify (ref: tbls/herumi.go:318)."""

    # -- aggregation ------------------------------------------------------

    @abc.abstractmethod
    def threshold_aggregate(
        self, partials: Mapping[int, Signature]
    ) -> Signature: ...

    @abc.abstractmethod
    def aggregate(self, sigs: Sequence[Signature]) -> Signature: ...

    # -- batch extensions (defaults loop; TPUImpl overrides) --------------

    def verify_batch(
        self, items: Sequence[tuple[PublicKey, bytes, Signature]]
    ) -> list[bool]:
        out = []
        for pk, data, sig in items:
            try:
                self.verify(pk, data, sig)
                out.append(True)
            except TblsError:
                out.append(False)
        return out

    def threshold_aggregate_batch(
        self, batch: Sequence[Mapping[int, Signature]]
    ) -> list[Signature]:
        return [self.threshold_aggregate(p) for p in batch]

    def aggregate_batch(
        self, groups: Sequence[Sequence[Signature]]
    ) -> list[Signature]:
        return [self.aggregate(g) for g in groups]


_current: Implementation | None = None


def set_implementation(impl: Implementation) -> None:
    """Swap the global backend (ref: tbls/tbls.go:72 SetImplementation)."""
    global _current
    _current = impl


def get_implementation() -> Implementation:
    global _current
    if _current is None:
        from charon_tpu.tbls.python_impl import PythonImpl

        _current = PythonImpl()
    return _current


# Package-level functions, mirroring ref tbls/tbls.go's package API.


def generate_secret_key() -> PrivateKey:
    return get_implementation().generate_secret_key()


def secret_to_public_key(secret: PrivateKey) -> PublicKey:
    return get_implementation().secret_to_public_key(secret)


def threshold_split(secret: PrivateKey, total: int, threshold: int) -> dict[int, PrivateKey]:
    return get_implementation().threshold_split(secret, total, threshold)


def recover_secret(shares: Mapping[int, PrivateKey], total: int, threshold: int) -> PrivateKey:
    return get_implementation().recover_secret(shares, total, threshold)


def sign(secret: PrivateKey, data: bytes) -> Signature:
    return get_implementation().sign(secret, data)


def verify(pubkey: PublicKey, data: bytes, sig: Signature) -> None:
    get_implementation().verify(pubkey, data, sig)


def verify_aggregate(pubkeys: Sequence[PublicKey], data: bytes, sig: Signature) -> None:
    get_implementation().verify_aggregate(pubkeys, data, sig)


def threshold_aggregate(partials: Mapping[int, Signature]) -> Signature:
    return get_implementation().threshold_aggregate(partials)


def aggregate(sigs: Sequence[Signature]) -> Signature:
    return get_implementation().aggregate(sigs)


def verify_batch(items: Sequence[tuple[PublicKey, bytes, Signature]]) -> list[bool]:
    return get_implementation().verify_batch(items)


def threshold_aggregate_batch(batch: Sequence[Mapping[int, Signature]]) -> list[Signature]:
    return get_implementation().threshold_aggregate_batch(batch)


def aggregate_batch(groups: Sequence[Sequence[Signature]]) -> list[Signature]:
    return get_implementation().aggregate_batch(groups)
