"""ResilientImpl: an ordered degradation ladder of tbls backends.

The duty hot path must stay live when a crypto backend misbehaves
(a wedged TPU runtime, a native library crash, a driver OOM): a backend
*error* is infrastructure, not a crypto verdict, so the call is retried
one rung down — TPU -> native C++ -> pure-python spec — and after
`demote_after` consecutive primary failures the broken rung is demoted
permanently (its jitted/compiled state is assumed wedged; re-probing a
dead accelerator on every signature would add its failure latency to
every duty).

TblsError is NEVER caught here: failed verification or malformed inputs
mean the same thing on every backend (they are bit-compatible — see
tests/test_tbls.py cross-impl suite), so falling through on a verdict
would only hide real signature failures.

Used by app/run.py when more than one backend is available, and by the
chaos suite (testutil/chaos.FlakyBackend forces the errors).
"""

from __future__ import annotations

import threading
from typing import Mapping, Sequence

from charon_tpu.tbls import Implementation, TblsError


class ResilientImpl(Implementation):
    """impls: backends in preference order (fastest first). All calls go
    to the active rung; a non-TblsError failure retries the same call on
    the next rung, and `demote_after` consecutive active-rung failures
    demote the active rung for good.

    Thread-safe: the ladder is hammered concurrently — the coalescer's
    decode pool, the serialized device lane, AND the overload-shed
    `run_in_executor` hops in parsigex/sigagg/validatorapi all call it.
    The streak/demote bookkeeping runs under one lock so a burst of
    concurrent failures demotes the broken rung exactly ONCE (two
    racing threads used to each append a demotion and double-step the
    ladder past a healthy rung)."""

    def __init__(
        self, impls: Sequence[Implementation], demote_after: int = 2
    ) -> None:
        if not impls:
            raise ValueError("need at least one tbls backend")
        self.impls = list(impls)
        self.demote_after = demote_after
        self.active = 0
        self.fallback_calls = 0  # calls served below the active rung
        self.demotions: list[int] = []  # rung indices demoted, in order
        self._fail_streak = 0
        self._mu = threading.Lock()  # guards streak/active/counters

    def _call(self, name: str, *args, **kwargs):
        i = self.active
        while True:
            impl = self.impls[i]
            try:
                result = getattr(impl, name)(*args, **kwargs)
            except TblsError:
                raise  # crypto verdict: identical on every rung
            except Exception as e:  # noqa: BLE001 — backend fault
                if i + 1 >= len(self.impls):
                    raise  # ladder exhausted: surface the fault
                demoted = None
                with self._mu:
                    if i == self.active:
                        self._fail_streak += 1
                        if self._fail_streak >= self.demote_after:
                            self.demotions.append(i)
                            self.active = i + 1
                            self._fail_streak = 0
                            demoted = type(impl).__name__
                    self.fallback_calls += 1
                if demoted is not None:
                    from charon_tpu.app import log

                    log.warn(
                        "tbls backend demoted",
                        topic="tbls",
                        rung=i,
                        backend=demoted,
                        err=f"{type(e).__name__}: {str(e)[:120]}",
                    )
                i += 1
                continue
            if i == self.active:
                with self._mu:
                    if i == self.active:
                        self._fail_streak = 0
            return result

    # -- the 11-op contract + batch extensions, all via the ladder --------

    def generate_secret_key(self):
        return self._call("generate_secret_key")

    def secret_to_public_key(self, secret):
        return self._call("secret_to_public_key", secret)

    def threshold_split(self, secret, total: int, threshold: int):
        return self._call("threshold_split", secret, total, threshold)

    def recover_secret(self, shares: Mapping[int, bytes], total: int, threshold: int):
        return self._call("recover_secret", shares, total, threshold)

    def sign(self, secret, data: bytes):
        return self._call("sign", secret, data)

    def verify(self, pubkey, data: bytes, sig) -> None:
        return self._call("verify", pubkey, data, sig)

    def verify_aggregate(self, pubkeys, data: bytes, sig) -> None:
        return self._call("verify_aggregate", pubkeys, data, sig)

    def threshold_aggregate(self, partials: Mapping[int, bytes]):
        return self._call("threshold_aggregate", partials)

    def aggregate(self, sigs):
        return self._call("aggregate", sigs)

    def verify_batch(self, items):
        return self._call("verify_batch", items)

    def threshold_aggregate_batch(self, batch):
        return self._call("threshold_aggregate_batch", batch)

    def aggregate_batch(self, groups):
        return self._call("aggregate_batch", groups)
