"""Pure-Python tbls backend over charon_tpu/crypto (the host reference).

Plays the role the herumi backend plays in the reference
(ref: tbls/herumi.go) — the trusted, simple implementation every other
backend is validated against (ref: tbls/tbls_test.go:209 randomized
cross-impl suite; ours is tests/test_tbls.py).
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

from charon_tpu.crypto import bls, g1g2, h2c, shamir
from charon_tpu.crypto.fields import R
from charon_tpu.tbls import (
    PRIVATE_KEY_LEN,
    PUBLIC_KEY_LEN,
    SIGNATURE_LEN,
    Implementation,
    TblsError,
)


def _check_len(data: bytes, want: int, what: str) -> None:
    if len(data) != want:
        raise TblsError(f"{what} must be {want} bytes, got {len(data)}")


def sk_to_int(secret: bytes) -> int:
    _check_len(secret, PRIVATE_KEY_LEN, "private key")
    sk = int.from_bytes(secret, "big")
    if not 0 < sk < R:
        raise TblsError("private key scalar out of range")
    return sk


def int_to_sk(sk: int) -> bytes:
    return (sk % R).to_bytes(PRIVATE_KEY_LEN, "big")


def pubkey_to_point(pubkey: bytes, subgroup_check: bool = True):
    _check_len(pubkey, PUBLIC_KEY_LEN, "public key")
    try:
        pt = g1g2.g1_from_bytes(pubkey, subgroup_check=subgroup_check)
    except ValueError as e:
        raise TblsError(str(e)) from e
    if pt is None:
        raise TblsError("infinite public key")
    return pt


def sig_to_point(sig: bytes, subgroup_check: bool = True):
    _check_len(sig, SIGNATURE_LEN, "signature")
    try:
        return g1g2.g2_from_bytes(sig, subgroup_check=subgroup_check)
    except ValueError as e:
        raise TblsError(str(e)) from e


class PythonImpl(Implementation):
    def generate_secret_key(self) -> bytes:
        return int_to_sk(bls.keygen(os.urandom(32)))

    def secret_to_public_key(self, secret: bytes) -> bytes:
        return g1g2.g1_to_bytes(bls.sk_to_pk(sk_to_int(secret)))

    def threshold_split(self, secret: bytes, total: int, threshold: int) -> dict[int, bytes]:
        if not 0 < threshold <= total:
            raise TblsError("invalid threshold/total")
        shares = shamir.split(sk_to_int(secret), total, threshold)
        return {i: int_to_sk(v) for i, v in shares.items()}

    def recover_secret(self, shares: Mapping[int, bytes], total: int, threshold: int) -> bytes:
        if len(shares) < threshold:
            raise TblsError("insufficient shares")
        ints = {i: sk_to_int(s) for i, s in shares.items()}
        return int_to_sk(shamir.recover_secret(ints))

    def sign(self, secret: bytes, data: bytes) -> bytes:
        return g1g2.g2_to_bytes(bls.sign(sk_to_int(secret), data))

    def verify(self, pubkey: bytes, data: bytes, sig: bytes) -> None:
        pk = pubkey_to_point(pubkey)
        s = sig_to_point(sig)
        if s is None:
            raise TblsError("infinite signature")
        if not bls.verify(pk, data, s):
            raise TblsError("signature verification failed")

    def verify_aggregate(self, pubkeys: Sequence[bytes], data: bytes, sig: bytes) -> None:
        if not pubkeys:
            raise TblsError("no public keys")
        pts = [pubkey_to_point(pk) for pk in pubkeys]
        s = sig_to_point(sig)
        if s is None:
            raise TblsError("infinite signature")
        if not bls.fast_aggregate_verify(pts, data, s):
            raise TblsError("aggregate signature verification failed")

    def threshold_aggregate(self, partials: Mapping[int, bytes]) -> bytes:
        if not partials:
            raise TblsError("no partial signatures")
        pts = {}
        for idx, sig in partials.items():
            if idx <= 0:
                raise TblsError("share indices are 1-based")
            pt = sig_to_point(sig)
            if pt is None:
                raise TblsError("infinite partial signature")
            pts[idx] = pt
        return g1g2.g2_to_bytes(shamir.threshold_aggregate_g2(pts))

    def aggregate(self, sigs: Sequence[bytes]) -> bytes:
        if not sigs:
            raise TblsError("no signatures")
        pts = []
        for sig in sigs:
            pt = sig_to_point(sig)
            if pt is None:
                raise TblsError("infinite signature")
            pts.append(pt)
        return g1g2.g2_to_bytes(bls.aggregate_sigs(pts))
