"""Native C++ tbls backend (ctypes over native/libcharon_native.so).

Plays the role of the herumi backend in the reference (ref: tbls/herumi.go
wrapping C++/asm via cgo): the fast host path. Secret-key management
(keygen, Shamir split/recover) stays in Python; signing/verification/
aggregation call into C++. Batch verification fans out with OpenMP.

Build: make -C native. If the library is missing this module raises
ImportError so callers can fall back to the Python backend.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Mapping, Sequence

from charon_tpu.tbls import (
    PRIVATE_KEY_LEN,
    PUBLIC_KEY_LEN,
    SIGNATURE_LEN,
    Implementation,
    TblsError,
)
from charon_tpu.tbls.python_impl import PythonImpl, _check_len

# CHARON_NATIVE_LIB overrides the shared object — the sanitized test
# harness points it at libcharon_native_san.so (ASan/UBSan build) inside
# an LD_PRELOAD=libasan subprocess (tests/test_native_sanitized.py).
_LIB_PATH = Path(
    os.environ.get(
        "CHARON_NATIVE_LIB",
        Path(__file__).resolve().parent.parent.parent
        / "native"
        / "libcharon_native.so",
    )
)


def _load():
    if not _LIB_PATH.exists():
        raise ImportError(
            f"native backend not built: {_LIB_PATH} (run `make -C native`)"
        )
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.ctpu_verify.restype = ctypes.c_int
    lib.ctpu_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.ctpu_sign.restype = ctypes.c_int
    lib.ctpu_sign.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.ctpu_sk_to_pk.restype = ctypes.c_int
    lib.ctpu_sk_to_pk.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.ctpu_aggregate.restype = ctypes.c_int
    lib.ctpu_aggregate.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.ctpu_aggregate_pks.restype = ctypes.c_int
    lib.ctpu_aggregate_pks.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.ctpu_threshold_aggregate.restype = ctypes.c_int
    lib.ctpu_threshold_aggregate.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.ctpu_verify_batch.restype = ctypes.c_int
    lib.ctpu_verify_batch.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.ctpu_hash_to_g2.restype = ctypes.c_int
    lib.ctpu_hash_to_g2.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    return lib


_lib = _load()


class NativeImpl(Implementation):
    def __init__(self) -> None:
        self._host = PythonImpl()

    # secret management stays in Python (host-only, infrequent)
    def generate_secret_key(self) -> bytes:
        return self._host.generate_secret_key()

    def threshold_split(self, secret, total, threshold):
        return self._host.threshold_split(secret, total, threshold)

    def recover_secret(self, shares, total, threshold):
        return self._host.recover_secret(shares, total, threshold)

    def secret_to_public_key(self, secret: bytes) -> bytes:
        _check_len(secret, PRIVATE_KEY_LEN, "private key")
        out = ctypes.create_string_buffer(PUBLIC_KEY_LEN)
        if not _lib.ctpu_sk_to_pk(secret, out):
            raise TblsError("sk_to_pk failed")
        return out.raw

    def sign(self, secret: bytes, data: bytes) -> bytes:
        _check_len(secret, PRIVATE_KEY_LEN, "private key")
        out = ctypes.create_string_buffer(SIGNATURE_LEN)
        if not _lib.ctpu_sign(secret, data, len(data), out):
            raise TblsError("sign failed")
        return out.raw

    def verify(self, pubkey: bytes, data: bytes, sig: bytes) -> None:
        _check_len(pubkey, PUBLIC_KEY_LEN, "public key")
        _check_len(sig, SIGNATURE_LEN, "signature")
        if not _lib.ctpu_verify(pubkey, data, len(data), sig):
            raise TblsError("signature verification failed")

    def verify_aggregate(self, pubkeys: Sequence[bytes], data: bytes, sig: bytes) -> None:
        if not pubkeys:
            raise TblsError("no public keys")
        for pk in pubkeys:
            _check_len(pk, PUBLIC_KEY_LEN, "public key")
        agg = ctypes.create_string_buffer(PUBLIC_KEY_LEN)
        if not _lib.ctpu_aggregate_pks(len(pubkeys), b"".join(pubkeys), agg):
            raise TblsError("pubkey aggregation failed")
        self.verify(agg.raw, data, sig)

    def threshold_aggregate(self, partials: Mapping[int, bytes]) -> bytes:
        if not partials:
            raise TblsError("no partial signatures")
        items = sorted(partials.items())
        for i, s in items:
            if i <= 0:
                raise TblsError("share indices are 1-based")
            _check_len(s, SIGNATURE_LEN, "signature")
        idx = (ctypes.c_uint64 * len(items))(*[i for i, _ in items])
        out = ctypes.create_string_buffer(SIGNATURE_LEN)
        if not _lib.ctpu_threshold_aggregate(
            len(items), idx, b"".join(s for _, s in items), out
        ):
            raise TblsError("threshold aggregation failed")
        return out.raw

    def aggregate(self, sigs: Sequence[bytes]) -> bytes:
        if not sigs:
            raise TblsError("no signatures")
        for s in sigs:
            _check_len(s, SIGNATURE_LEN, "signature")
        out = ctypes.create_string_buffer(SIGNATURE_LEN)
        if not _lib.ctpu_aggregate(len(sigs), b"".join(sigs), out):
            raise TblsError("aggregation failed")
        return out.raw

    def verify_batch(self, items) -> list[bool]:
        if not items:
            return []
        n = len(items)
        pks = []
        sigs = []
        msgs = b""
        offsets = [0]
        ok = [True] * n
        for i, (pk, data, sig) in enumerate(items):
            if len(pk) != PUBLIC_KEY_LEN or len(sig) != SIGNATURE_LEN:
                ok[i] = False
                pk, sig = bytes(PUBLIC_KEY_LEN), bytes(SIGNATURE_LEN)
                data = b""
            pks.append(pk)
            sigs.append(sig)
            msgs += data
            offsets.append(len(msgs))
        off = (ctypes.c_uint64 * (n + 1))(*offsets)
        results = ctypes.create_string_buffer(n)
        _lib.ctpu_verify_batch(
            n, b"".join(pks), msgs, off, b"".join(sigs), results
        )
        return [o and bool(results.raw[i]) for i, o in enumerate(ok)]

    def hash_to_g2_bytes(self, data: bytes) -> bytes:
        out = ctypes.create_string_buffer(SIGNATURE_LEN)
        _lib.ctpu_hash_to_g2(data, len(data), out)
        return out.raw
