"""Ethereum Node Records (EIP-778), "v4" identity scheme.

Real spec-conformant records (ref: eth2util/enr/enr.go): the record is an
RLP list [signature, seq, k1, v1, k2, v2, ...] with keys sorted; the
textual form is "enr:" + unpadded base64url of that RLP; the v4 identity
signs keccak256(rlp([seq, k1, v1, ...])) with the node's secp256k1 key
(64-byte r||s). Replaces the round-1 stand-in "enr:<hex-pubkey>" strings
(VERDICT round 1, Missing #7).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field

from charon_tpu.app import k1util
from charon_tpu.eth2util import rlp
from charon_tpu.eth2util.keccak import keccak_256

MAX_RECORD_SIZE = 300  # EIP-778 hard cap


@dataclass(frozen=True)
class Record:
    """A decoded node record. kvs holds the raw key/value byte pairs
    (sorted by key); seq is the sequence number."""

    signature: bytes
    seq: int
    kvs: tuple[tuple[bytes, bytes], ...]

    # -- accessors --------------------------------------------------------

    def get(self, key: str) -> bytes | None:
        for k, v in self.kvs:
            if k == key.encode():
                return v
        return None

    @property
    def pubkey(self) -> bytes:
        """33-byte compressed secp256k1 public key."""
        pk = self.get("secp256k1")
        if pk is None:
            raise ValueError("record has no secp256k1 key")
        return pk

    @property
    def ip(self) -> str | None:
        raw = self.get("ip")
        return ".".join(str(b) for b in raw) if raw else None

    @property
    def tcp(self) -> int | None:
        raw = self.get("tcp")
        return int.from_bytes(raw, "big") if raw else None

    # -- encoding ---------------------------------------------------------

    def _content(self) -> list:
        items: list = [self.seq]
        for k, v in self.kvs:
            items += [k, v]
        return items

    def encode(self) -> bytes:
        data = rlp.encode([self.signature] + self._content())
        if len(data) > MAX_RECORD_SIZE:
            raise ValueError("record exceeds 300 bytes")
        return data

    def to_string(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(self.encode()).rstrip(
            b"="
        ).decode()

    # -- verification -----------------------------------------------------

    def signing_digest(self) -> bytes:
        return keccak_256(rlp.encode(self._content()))

    def verify(self) -> bool:
        """v4 scheme: keccak256 content digest signed by the record's own
        secp256k1 key."""
        if self.get("id") != b"v4":
            return False
        try:
            return k1util.verify_bytes(
                self.pubkey, self.signing_digest(), self.signature
            )
        except Exception:
            return False


def new(
    privkey,
    seq: int = 1,
    ip: str | None = None,
    tcp: int | None = None,
    extra: dict[str, bytes] | None = None,
) -> Record:
    """Create and sign a v4 record for a secp256k1 private key."""
    kvs: dict[bytes, bytes] = {
        b"id": b"v4",
        b"secp256k1": k1util.public_key_to_bytes(privkey.public_key()),
    }
    if ip is not None:
        kvs[b"ip"] = bytes(int(p) for p in ip.split("."))
    if tcp is not None:
        kvs[b"tcp"] = tcp.to_bytes(2, "big")
    for k, v in (extra or {}).items():
        kvs[k.encode()] = v
    sorted_kvs = tuple(sorted(kvs.items()))

    unsigned = Record(signature=b"", seq=seq, kvs=sorted_kvs)
    sig = k1util.sign(privkey, unsigned.signing_digest())
    return Record(signature=sig, seq=seq, kvs=sorted_kvs)


def pubkey_from_string(text: str) -> bytes:
    """Operator record -> 33-byte compressed secp256k1 pubkey.

    Accepts real EIP-778 records and (for artifacts created before real
    ENRs landed) the legacy `enr:...:<hex-pubkey>` stand-in format. A
    structurally valid record that fails signature verification is an
    ERROR, not a fallback case — falling back would hide tampering."""
    parse_exc = None
    if text.startswith("enr:"):
        try:
            return parse(text).pubkey
        except ValueError as e:
            if "signature" in str(e):
                raise  # tampered record: never fall back
            parse_exc = e  # structurally not a record: try legacy
    try:
        pk = bytes.fromhex(text.split(":")[-1])
        if len(pk) == 33:
            return pk
    except ValueError:
        pass
    raise ValueError(
        f"cannot extract operator pubkey from {text!r}"
    ) from parse_exc


def parse(text: str) -> Record:
    """Parse + verify an enr:... string (ref: enr.go Parse)."""
    if not text.startswith("enr:"):
        raise ValueError("missing enr: prefix")
    raw = text[4:]
    data = base64.urlsafe_b64decode(raw + "=" * ((4 - len(raw) % 4) % 4))
    items = rlp.decode(data)
    if not isinstance(items, list) or len(items) < 2 or len(items) % 2 != 0:
        raise ValueError("malformed record structure")
    sig, seq_raw = items[0], items[1]
    kv_items = items[2:]
    kvs = tuple(
        (kv_items[i], kv_items[i + 1]) for i in range(0, len(kv_items), 2)
    )
    keys = [k for k, _ in kvs]
    if keys != sorted(keys):
        raise ValueError("record keys not sorted")
    rec = Record(
        signature=sig,
        seq=int.from_bytes(seq_raw, "big"),
        kvs=kvs,
    )
    if not rec.verify():
        raise ValueError("invalid record signature")
    return rec
