"""EIP-712 typed structured data hashing.

Mirrors ref: eth2util/eip712/eip712.go — the reference signs cluster
definition config hashes, operator ENRs and terms-and-conditions as
EIP-712 typed data so wallets can display what is being signed. This is
the spec-exact hashing: domain separator, type hashes, and the final
keccak256(0x1901 || domainSeparator || hashStruct(message)).

Supported field types match the reference's subset: string, uint256,
address, bytes32 (primitives the cluster payloads need).
"""

from __future__ import annotations

from dataclasses import dataclass

from charon_tpu.eth2util.keccak import keccak_256


@dataclass(frozen=True)
class Field:
    name: str
    type: str  # "string" | "uint256" | "address" | "bytes32"
    value: object


@dataclass(frozen=True)
class TypedData:
    """One primary type + its fields, hashed under a domain."""

    primary_type: str
    fields: tuple[Field, ...]


@dataclass(frozen=True)
class Domain:
    """EIP712Domain{name, version, chainId} (the reference's domain shape,
    ref: eip712.go eip712Domain)."""

    name: str
    version: str
    chain_id: int

    def separator(self) -> bytes:
        type_hash = keccak_256(
            b"EIP712Domain(string name,string version,uint256 chainId)"
        )
        return keccak_256(
            type_hash
            + keccak_256(self.name.encode())
            + keccak_256(self.version.encode())
            + self.chain_id.to_bytes(32, "big")
        )


def _encode_value(ftype: str, value) -> bytes:
    if ftype == "string":
        return keccak_256(
            value.encode() if isinstance(value, str) else bytes(value)
        )
    if ftype == "uint256":
        return int(value).to_bytes(32, "big")
    if ftype == "address":
        raw = (
            bytes.fromhex(value.removeprefix("0x"))
            if isinstance(value, str)
            else bytes(value)
        )
        return bytes(12) + raw
    if ftype == "bytes32":
        raw = (
            bytes.fromhex(value.removeprefix("0x"))
            if isinstance(value, str)
            else bytes(value)
        )
        if len(raw) != 32:
            raise ValueError("bytes32 value must be 32 bytes")
        return raw
    raise ValueError(f"unsupported EIP-712 field type {ftype}")


def hash_struct(data: TypedData) -> bytes:
    sig = (
        data.primary_type
        + "("
        + ",".join(f"{f.type} {f.name}" for f in data.fields)
        + ")"
    )
    encoded = keccak_256(sig.encode())
    for f in data.fields:
        encoded += _encode_value(f.type, f.value)
    return keccak_256(encoded)


def hash_typed_data(domain: Domain, data: TypedData) -> bytes:
    """The digest a wallet signs: keccak256(0x19 0x01 || domain || struct)
    (ref: eip712.go HashTypedData)."""
    return keccak_256(b"\x19\x01" + domain.separator() + hash_struct(data))
