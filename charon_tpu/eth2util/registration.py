"""Builder (validator) registrations: SSZ container, signing root, and
the pre-generated registrations carried in cluster locks.

Mirrors ref: eth2util/registration/registration.go — builds
ValidatorRegistration messages, computes their APPLICATION_BUILDER
signing root (genesis fork version + empty genesis validators root, per
the builder spec), and round-trips the lock-file JSON form that
core/bcast/recast.go re-broadcasts every epoch.
"""

from __future__ import annotations

from dataclasses import dataclass

from charon_tpu.eth2util import ssz
from charon_tpu.eth2util.signing import DomainName, ForkInfo

# Obol's conventional default for pre-generated registrations
# (ref: eth2util/registration DefaultGasLimit).
DEFAULT_GAS_LIMIT = 30_000_000


@dataclass(frozen=True)
class ValidatorRegistration:
    """The builder-spec ValidatorRegistrationV1 message."""

    fee_recipient: bytes  # 20 bytes
    gas_limit: int
    timestamp: int  # unix seconds; spec: the chain's genesis time
    pubkey: bytes  # 48-byte group BLS pubkey

    ssz_fields = (
        ssz.ByteVector(20),
        ssz.Uint64(),
        ssz.Uint64(),
        ssz.ByteVector(48),
    )

    def __post_init__(self) -> None:
        if len(self.fee_recipient) != 20:
            raise ValueError("fee recipient must be 20 bytes")
        if len(self.pubkey) != 48:
            raise ValueError("pubkey must be 48 bytes")

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


def signing_root(reg: ValidatorRegistration, fork: ForkInfo) -> bytes:
    """APPLICATION_BUILDER domain root (ref: the reference pins the
    genesis fork version with an empty genesis validators root for
    builder registrations)."""
    return fork.signing_root(
        DomainName.APPLICATION_BUILDER, reg.hash_tree_root()
    )


def to_lock_json(reg: ValidatorRegistration, signature: bytes) -> dict:
    """The cluster-lock `builder_registration` object
    (ref: cluster/lock.go DistributedValidator.BuilderRegistration)."""
    return {
        "message": {
            "fee_recipient": "0x" + reg.fee_recipient.hex(),
            "gas_limit": reg.gas_limit,
            "timestamp": reg.timestamp,
            "pubkey": "0x" + reg.pubkey.hex(),
        },
        "signature": "0x" + signature.hex(),
    }


def from_lock_json(obj: dict) -> tuple[ValidatorRegistration, bytes]:
    msg = obj["message"]

    def unhex(s: str) -> bytes:
        return bytes.fromhex(s[2:] if s.startswith("0x") else s)

    reg = ValidatorRegistration(
        fee_recipient=unhex(msg["fee_recipient"]),
        gas_limit=int(msg["gas_limit"]),
        timestamp=int(msg["timestamp"]),
        pubkey=unhex(msg["pubkey"]),
    )
    return reg, unhex(obj["signature"])
