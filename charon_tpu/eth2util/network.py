"""Ethereum network registry: fork versions, genesis times, names.

Mirrors ref: eth2util/network.go — a static registry of the public
networks charon supports plus custom/test networks registered at runtime.
The constants are public chain parameters (eth2 spec / client configs).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Network:
    name: str
    genesis_fork_version: bytes  # 4 bytes
    genesis_time: int  # unix seconds
    chain_id: int


_NETWORKS: dict[str, Network] = {}
_BY_FORK: dict[bytes, Network] = {}


def register(net: Network) -> None:
    _NETWORKS[net.name] = net
    _BY_FORK.setdefault(net.genesis_fork_version, net)


for _net in (
    Network("mainnet", bytes.fromhex("00000000"), 1_606_824_023, 1),
    Network("goerli", bytes.fromhex("00001020"), 1_616_508_000, 5),
    Network("sepolia", bytes.fromhex("90000069"), 1_655_733_600, 11155111),
    Network("holesky", bytes.fromhex("01017000"), 1_695_902_400, 17000),
    Network("gnosis", bytes.fromhex("00000064"), 1_638_993_340, 100),
    # reserved test fork version for in-process simnet clusters
    Network("simnet", bytes.fromhex("00000fff"), 0, 0),
):
    register(_net)


def by_name(name: str) -> Network:
    try:
        return _NETWORKS[name]
    except KeyError:
        raise ValueError(
            f"unknown network {name!r} (known: {sorted(_NETWORKS)})"
        ) from None


def by_fork_version(fork_version: bytes | str) -> Network | None:
    if isinstance(fork_version, str):
        fork_version = bytes.fromhex(
            fork_version[2:] if fork_version.startswith("0x") else fork_version
        )
    return _BY_FORK.get(fork_version)


def genesis_time(fork_version: bytes | str, default: int = 0) -> int:
    net = by_fork_version(fork_version)
    return net.genesis_time if net is not None else default
