"""Keccak-256 (the pre-NIST Ethereum flavour, NOT SHA3-256).

Ethereum's ENR identity scheme (EIP-778 "v4") and EIP-712 typed-data
hashing both use original Keccak with the 0x01 domain padding; Python's
hashlib only ships the NIST SHA-3 variant (0x06 padding), so this is a
small spec-exact keccak-f[1600] sponge. Pure Python is fine here: inputs
are tiny (record payloads, typed-data structs), never bulk data.

(ref: the reference gets this via go-ethereum's crypto.Keccak256 —
eth2util/enr/enr.go, eth2util/eip712/eip712.go)
"""

from __future__ import annotations

_ROUNDS = 24

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rotation offsets r[x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rol(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(a: list[list[int]]) -> None:
    for rnd in range(_ROUNDS):
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= _RC[rnd]


def keccak_256(data: bytes) -> bytes:
    rate = 136  # 1088-bit rate for 256-bit output
    # multi-rate padding with the 0x01 domain byte (keccak, not sha3's 0x06)
    pad_len = rate - (len(data) % rate)
    padded = data + b"\x01" + bytes(pad_len - 2) + b"\x80" if pad_len >= 2 else data + b"\x81"

    state = [[0] * 5 for _ in range(5)]
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            x, y = i % 5, i // 5
            state[x][y] ^= lane
        _keccak_f(state)

    out = b""
    for i in range(4):  # 32 bytes = 4 lanes
        x, y = i % 5, i // 5
        out += state[x][y].to_bytes(8, "little")
    return out
