"""Minimal RLP (recursive length prefix) encode/decode.

Exactly the subset Ethereum node records need: byte strings and
(possibly nested) lists of byte strings (ref: eth2util/rlp/rlp.go —
the reference implements the same subset for the same reason).
"""

from __future__ import annotations


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    blen = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(blen)]) + blen


def encode(item) -> bytes:
    """item: bytes | int | list of items. Ints encode minimally (no
    leading zeros; 0 is the empty string, per the spec)."""
    if isinstance(item, int):
        item = (
            b""
            if item == 0
            else item.to_bytes((item.bit_length() + 7) // 8, "big")
        )
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _encode_length(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise TypeError(f"cannot RLP-encode {type(item)}")


def decode(data: bytes):
    """Decode a single RLP item (bytes or nested list of bytes)."""
    item, rest = _decode_one(data)
    if rest:
        raise ValueError("trailing bytes after RLP item")
    return item


def _decode_one(data: bytes):
    if not data:
        raise ValueError("empty RLP input")
    b0 = data[0]
    if b0 < 0x80:
        return bytes([b0]), data[1:]
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        _check(data, 1 + n)
        s = data[1 : 1 + n]
        if n == 1 and s[0] < 0x80:
            raise ValueError("non-canonical single byte")
        return s, data[1 + n :]
    if b0 < 0xC0:  # long string
        ln = b0 - 0xB7
        _check(data, 1 + ln)
        n = int.from_bytes(data[1 : 1 + ln], "big")
        if n < 56 or data[1] == 0:
            raise ValueError("non-canonical length")
        _check(data, 1 + ln + n)
        return data[1 + ln : 1 + ln + n], data[1 + ln + n :]
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        _check(data, 1 + n)
        return _decode_list(data[1 : 1 + n]), data[1 + n :]
    ln = b0 - 0xF7  # long list
    _check(data, 1 + ln)
    n = int.from_bytes(data[1 : 1 + ln], "big")
    if n < 56 or data[1] == 0:
        raise ValueError("non-canonical length")
    _check(data, 1 + ln + n)
    return _decode_list(data[1 + ln : 1 + ln + n]), data[1 + ln + n :]


def _decode_list(payload: bytes) -> list:
    out = []
    while payload:
        item, payload = _decode_one(payload)
        out.append(item)
    return out


def _check(data: bytes, need: int) -> None:
    if len(data) < need:
        raise ValueError("truncated RLP input")
