"""Eth2 signing domains and signing-root computation.

Spec-exact implementation of compute_domain / compute_signing_root
(mirrors ref: eth2util/signing/signing.go:22-115, which maps duty types to
domain names and verifies against them).
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass


class DomainName(enum.Enum):
    """Domain types (4-byte little-endian tags per the eth2 spec); the set
    the reference registers in eth2util/signing/signing.go:22-35."""

    BEACON_PROPOSER = bytes.fromhex("00000000")
    BEACON_ATTESTER = bytes.fromhex("01000000")
    RANDAO = bytes.fromhex("02000000")
    DEPOSIT = bytes.fromhex("03000000")
    VOLUNTARY_EXIT = bytes.fromhex("04000000")
    SELECTION_PROOF = bytes.fromhex("05000000")
    AGGREGATE_AND_PROOF = bytes.fromhex("06000000")
    SYNC_COMMITTEE = bytes.fromhex("07000000")
    SYNC_COMMITTEE_SELECTION_PROOF = bytes.fromhex("08000000")
    CONTRIBUTION_AND_PROOF = bytes.fromhex("09000000")
    APPLICATION_BUILDER = bytes.fromhex("00000001")


def _sha(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def compute_fork_data_root(fork_version: bytes, genesis_validators_root: bytes) -> bytes:
    """hash_tree_root(ForkData{current_version, genesis_validators_root})."""
    if len(fork_version) != 4:
        raise ValueError("fork version must be 4 bytes")
    return _sha(fork_version + bytes(28), genesis_validators_root)


def compute_domain(
    domain: DomainName, fork_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return domain.value + compute_fork_data_root(fork_version, genesis_validators_root)[:28]


def compute_signing_root(object_root: bytes, domain: bytes) -> bytes:
    """hash_tree_root(SigningData{object_root, domain})."""
    if len(object_root) != 32 or len(domain) != 32:
        raise ValueError("object root and domain must be 32 bytes")
    return _sha(object_root, domain)


@dataclass(frozen=True)
class ForkInfo:
    """What a signer needs from the chain to derive domains.

    APPLICATION_BUILDER domains pin the genesis fork version with an empty
    genesis validators root, per the builder spec (mirrored from the
    reference's registration handling, ref: eth2util/registration)."""

    genesis_validators_root: bytes
    fork_version: bytes
    genesis_fork_version: bytes

    def signing_root(self, domain: DomainName, object_root: bytes) -> bytes:
        if domain is DomainName.APPLICATION_BUILDER:
            d = compute_domain(domain, self.genesis_fork_version, bytes(32))
        else:
            d = compute_domain(
                domain, self.fork_version, self.genesis_validators_root
            )
        return compute_signing_root(object_root, d)
