"""Minimal spec-exact SSZ hash-tree-root (simple serialize subset).

Implements the SSZ merkleization the duty workflow needs — uint64, byte
vectors, fixed containers, lists with limits, bitlists — exactly per the
eth2 simple-serialize spec, so signing roots computed here match any
compliant client. (The reference gets this via go-eth2-client types and a
codegen helper, ref: app/genssz; we implement the spec directly.)

Only hash_tree_root (+ its serialization helpers) is provided: the
framework's wire formats are protobuf/JSON, and SSZ is used for roots.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, fields
from typing import Any, Sequence

_ZERO_CHUNK = bytes(32)


def _sha(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def merkleize(chunks: Sequence[bytes], limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks, padded with zero chunks to the limit (or
    to the next power of two when no limit is given)."""
    count = len(chunks)
    size = _next_pow2(limit if limit is not None else max(count, 1))
    if limit is not None and count > limit:
        raise ValueError("chunk count exceeds limit")
    # Precompute zero-subtree hashes up the levels.
    layer = list(chunks) if chunks else [_ZERO_CHUNK]
    zero = _ZERO_CHUNK
    width = size
    while width > 1:
        if len(layer) % 2:
            layer.append(zero)
        layer = [_sha(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)]
        zero = _sha(zero, zero)
        width //= 2
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return _sha(root, length.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> list[bytes]:
    """Right-pad bytes into 32-byte chunks."""
    if not data:
        return []
    padded = data + bytes((-len(data)) % 32)
    return [padded[i : i + 32] for i in range(0, len(padded), 32)]


# -- type descriptors --------------------------------------------------------


class SSZType:
    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError


@dataclass(frozen=True)
class Uint64(SSZType):
    def hash_tree_root(self, value: int) -> bytes:
        return int(value).to_bytes(8, "little") + bytes(24)


@dataclass(frozen=True)
class Uint256(SSZType):
    """uint256 basic type (execution-layer base_fee_per_gas)."""

    def hash_tree_root(self, value: int) -> bytes:
        return int(value).to_bytes(32, "little")


@dataclass(frozen=True)
class Boolean(SSZType):
    def hash_tree_root(self, value: bool) -> bytes:
        return bytes([1 if value else 0]) + bytes(31)


@dataclass(frozen=True)
class ByteVector(SSZType):
    length: int

    def hash_tree_root(self, value: bytes) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"expected {self.length} bytes, got {len(value)}")
        return merkleize(pack_bytes(value))


@dataclass(frozen=True)
class ByteList(SSZType):
    limit: int

    def hash_tree_root(self, value: bytes) -> bytes:
        if len(value) > self.limit:
            raise ValueError("byte list exceeds limit")
        chunk_limit = (self.limit + 31) // 32
        return mix_in_length(
            merkleize(pack_bytes(value), chunk_limit), len(value)
        )


@dataclass(frozen=True)
class Vector(SSZType):
    elem: SSZType
    length: int

    def hash_tree_root(self, value: Sequence) -> bytes:
        if len(value) != self.length:
            raise ValueError("vector length mismatch")
        return merkleize([self.elem.hash_tree_root(v) for v in value])


@dataclass(frozen=True)
class List(SSZType):
    elem: SSZType
    limit: int

    def hash_tree_root(self, value: Sequence) -> bytes:
        if isinstance(self.elem, Uint64):
            # basic-type lists pack values into chunks
            data = b"".join(int(v).to_bytes(8, "little") for v in value)
            chunk_limit = (self.limit * 8 + 31) // 32
            root = merkleize(pack_bytes(data), chunk_limit)
        else:
            root = merkleize(
                [self.elem.hash_tree_root(v) for v in value], self.limit
            )
        return mix_in_length(root, len(value))


@dataclass(frozen=True)
class Bitlist(SSZType):
    limit: int

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError("bitlist exceeds limit")
        data = bytearray((len(value) + 7) // 8)
        for i, bit in enumerate(value):
            if bit:
                data[i // 8] |= 1 << (i % 8)
        chunk_limit = (self.limit + 255) // 256
        return mix_in_length(
            merkleize(pack_bytes(bytes(data)), chunk_limit), len(value)
        )


@dataclass(frozen=True)
class Bitvector(SSZType):
    length: int

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        if len(value) != self.length:
            raise ValueError("bitvector length mismatch")
        data = bytearray((self.length + 7) // 8)
        for i, bit in enumerate(value):
            if bit:
                data[i // 8] |= 1 << (i % 8)
        return merkleize(pack_bytes(bytes(data)))


@dataclass(frozen=True)
class Nested(SSZType):
    """Field whose value is itself an ssz_fields-bearing dataclass.
    `cls` (optional) names the concrete container class — required by the
    generic JSON codec (eth2util/spec.py) to decode; rooting alone never
    needs it."""

    cls: type | None = None

    def hash_tree_root(self, value) -> bytes:
        return hash_tree_root(value)


@dataclass(frozen=True)
class Container(SSZType):
    field_types: tuple[SSZType, ...]

    def hash_tree_root(self, value: Sequence) -> bytes:
        if len(value) != len(self.field_types):
            raise ValueError("container field count mismatch")
        return merkleize(
            [t.hash_tree_root(v) for t, v in zip(self.field_types, value)]
        )


def hash_tree_root(obj: Any) -> bytes:
    """Root of an object whose dataclass declares `ssz_fields`: a tuple of
    SSZType descriptors aligned with its dataclass fields."""
    types = obj.ssz_fields
    values = [getattr(obj, f.name) for f in fields(obj)][: len(types)]
    return Container(tuple(types)).hash_tree_root(values)


BYTES32 = ByteVector(32)
BYTES48 = ByteVector(48)
BYTES96 = ByteVector(96)
UINT64 = Uint64()
