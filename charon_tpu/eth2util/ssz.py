"""Minimal spec-exact SSZ hash-tree-root (simple serialize subset).

Implements the SSZ merkleization the duty workflow needs — uint64, byte
vectors, fixed containers, lists with limits, bitlists — exactly per the
eth2 simple-serialize spec, so signing roots computed here match any
compliant client. (The reference gets this via go-eth2-client types and a
codegen helper, ref: app/genssz; we implement the spec directly.)

Only hash_tree_root (+ its serialization helpers) is provided: the
framework's wire formats are protobuf/JSON, and SSZ is used for roots.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, fields
from typing import Any, Sequence

_ZERO_CHUNK = bytes(32)


def _sha(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def merkleize(chunks: Sequence[bytes], limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks, padded with zero chunks to the limit (or
    to the next power of two when no limit is given)."""
    count = len(chunks)
    size = _next_pow2(limit if limit is not None else max(count, 1))
    if limit is not None and count > limit:
        raise ValueError("chunk count exceeds limit")
    # Precompute zero-subtree hashes up the levels.
    layer = list(chunks) if chunks else [_ZERO_CHUNK]
    zero = _ZERO_CHUNK
    width = size
    while width > 1:
        if len(layer) % 2:
            layer.append(zero)
        layer = [_sha(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)]
        zero = _sha(zero, zero)
        width //= 2
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return _sha(root, length.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> list[bytes]:
    """Right-pad bytes into 32-byte chunks."""
    if not data:
        return []
    padded = data + bytes((-len(data)) % 32)
    return [padded[i : i + 32] for i in range(0, len(padded), 32)]


# -- type descriptors --------------------------------------------------------


class SSZType:
    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError


@dataclass(frozen=True)
class Uint64(SSZType):
    def hash_tree_root(self, value: int) -> bytes:
        return int(value).to_bytes(8, "little") + bytes(24)


@dataclass(frozen=True)
class Uint256(SSZType):
    """uint256 basic type (execution-layer base_fee_per_gas)."""

    def hash_tree_root(self, value: int) -> bytes:
        return int(value).to_bytes(32, "little")


@dataclass(frozen=True)
class Boolean(SSZType):
    def hash_tree_root(self, value: bool) -> bytes:
        return bytes([1 if value else 0]) + bytes(31)


@dataclass(frozen=True)
class ByteVector(SSZType):
    length: int

    def hash_tree_root(self, value: bytes) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"expected {self.length} bytes, got {len(value)}")
        return merkleize(pack_bytes(value))


@dataclass(frozen=True)
class ByteList(SSZType):
    limit: int

    def hash_tree_root(self, value: bytes) -> bytes:
        if len(value) > self.limit:
            raise ValueError("byte list exceeds limit")
        chunk_limit = (self.limit + 31) // 32
        return mix_in_length(
            merkleize(pack_bytes(value), chunk_limit), len(value)
        )


@dataclass(frozen=True)
class Vector(SSZType):
    elem: SSZType
    length: int

    def hash_tree_root(self, value: Sequence) -> bytes:
        if len(value) != self.length:
            raise ValueError("vector length mismatch")
        return merkleize([self.elem.hash_tree_root(v) for v in value])


@dataclass(frozen=True)
class List(SSZType):
    elem: SSZType
    limit: int

    def hash_tree_root(self, value: Sequence) -> bytes:
        if isinstance(self.elem, Uint64):
            # basic-type lists pack values into chunks
            data = b"".join(int(v).to_bytes(8, "little") for v in value)
            chunk_limit = (self.limit * 8 + 31) // 32
            root = merkleize(pack_bytes(data), chunk_limit)
        else:
            root = merkleize(
                [self.elem.hash_tree_root(v) for v in value], self.limit
            )
        return mix_in_length(root, len(value))


@dataclass(frozen=True)
class Bitlist(SSZType):
    limit: int

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError("bitlist exceeds limit")
        data = _bitbytes(value, sentinel=False)
        chunk_limit = (self.limit + 255) // 256
        return mix_in_length(
            merkleize(pack_bytes(data), chunk_limit), len(value)
        )


@dataclass(frozen=True)
class Bitvector(SSZType):
    length: int

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        if len(value) != self.length:
            raise ValueError("bitvector length mismatch")
        return merkleize(pack_bytes(_bitbytes(value, sentinel=False)))


@dataclass(frozen=True)
class Nested(SSZType):
    """Field whose value is itself an ssz_fields-bearing dataclass.
    `cls` (optional) names the concrete container class — required by the
    generic JSON codec (eth2util/spec.py) to decode; rooting alone never
    needs it."""

    cls: type | None = None

    def hash_tree_root(self, value) -> bytes:
        return hash_tree_root(value)


@dataclass(frozen=True)
class Container(SSZType):
    field_types: tuple[SSZType, ...]

    def hash_tree_root(self, value: Sequence) -> bytes:
        if len(value) != len(self.field_types):
            raise ValueError("container field count mismatch")
        return merkleize(
            [t.hash_tree_root(v) for t, v in zip(self.field_types, value)]
        )


def hash_tree_root(obj: Any) -> bytes:
    """Root of an object whose dataclass declares `ssz_fields`: a tuple of
    SSZType descriptors aligned with its dataclass fields."""
    types = obj.ssz_fields
    values = [getattr(obj, f.name) for f in fields(obj)][: len(types)]
    return Container(tuple(types)).hash_tree_root(values)


# ---------------------------------------------------------------------------
# Full SSZ serialization (simple-serialize wire encoding)
# ---------------------------------------------------------------------------
#
# The beacon API transports consensus objects as SSZ octet-stream when the
# client asks for it (Lighthouse publishes blocks as SSZ by default in
# some configs); roots alone are not enough for that path. Offsets per
# the spec: fixed parts concatenated with 4-byte little-endian offsets
# standing in for variable-size fields, then variable parts in order.

_OFFSET_SIZE = 4


def _is_variable(t: SSZType) -> bool:
    if isinstance(t, (ByteList, List, Bitlist)):
        return True
    if isinstance(t, Vector):
        return _is_variable(t.elem)
    if isinstance(t, Nested):
        if t.cls is None:
            raise TypeError("Nested descriptor lacks cls; cannot serialize")
        return any(_is_variable(ft) for ft in t.cls.ssz_fields)
    return False


def _bitbytes(value, sentinel: bool) -> bytes:
    n = len(value)
    data = bytearray(n // 8 + 1 if sentinel else (n + 7) // 8)
    for i, bit in enumerate(value):
        if bit:
            data[i // 8] |= 1 << (i % 8)
    if sentinel:
        data[n // 8] |= 1 << (n % 8)
    return bytes(data)


def _encode(t: SSZType, v: Any) -> bytes:
    if isinstance(t, Uint64):
        return int(v).to_bytes(8, "little")
    if isinstance(t, Uint256):
        return int(v).to_bytes(32, "little")
    if isinstance(t, Boolean):
        return bytes([1 if v else 0])
    if isinstance(t, ByteVector):
        if len(v) != t.length:
            raise ValueError(f"expected {t.length} bytes, got {len(v)}")
        return bytes(v)
    if isinstance(t, ByteList):
        if len(v) > t.limit:
            raise ValueError("byte list exceeds limit")
        return bytes(v)
    if isinstance(t, Bitvector):
        if len(v) != t.length:
            raise ValueError("bitvector length mismatch")
        return _bitbytes(v, sentinel=False)
    if isinstance(t, Bitlist):
        if len(v) > t.limit:
            raise ValueError("bitlist exceeds limit")
        return _bitbytes(v, sentinel=True)
    if isinstance(t, Nested):
        return serialize(v)
    if isinstance(t, Vector):
        return _encode_sequence([t.elem] * t.length, list(v))
    if isinstance(t, List):
        if len(v) > t.limit:
            raise ValueError("list exceeds limit")
        return _encode_sequence([t.elem] * len(v), list(v))
    raise TypeError(f"no SSZ encoding for {type(t).__name__}")


def _encode_sequence(types: Sequence[SSZType], values: Sequence[Any]) -> bytes:
    if len(types) != len(values):
        raise ValueError("sequence arity mismatch")
    parts = [_encode(t, v) for t, v in zip(types, values)]
    variable = [_is_variable(t) for t in types]
    fixed_len = sum(
        _OFFSET_SIZE if var else len(p) for p, var in zip(parts, variable)
    )
    out = bytearray()
    var_offset = fixed_len
    for p, var in zip(parts, variable):
        if var:
            out += var_offset.to_bytes(_OFFSET_SIZE, "little")
            var_offset += len(p)
        else:
            out += p
    for p, var in zip(parts, variable):
        if var:
            out += p
    return bytes(out)


def serialize(obj: Any) -> bytes:
    """SSZ wire encoding of an ssz_fields-bearing container."""
    types = obj.ssz_fields
    values = [getattr(obj, f.name) for f in fields(obj)][: len(types)]
    return _encode_sequence(tuple(types), values)


def _fixed_size(t: SSZType) -> int:
    """Byte size of a FIXED-size type."""
    if isinstance(t, Uint64):
        return 8
    if isinstance(t, Uint256):
        return 32
    if isinstance(t, Boolean):
        return 1
    if isinstance(t, ByteVector):
        return t.length
    if isinstance(t, Bitvector):
        return (t.length + 7) // 8
    if isinstance(t, Vector):
        return t.length * _fixed_size(t.elem)
    if isinstance(t, Nested):
        return sum(_fixed_size(ft) for ft in t.cls.ssz_fields)
    raise TypeError(f"{type(t).__name__} is not fixed-size")


def _decode(t: SSZType, data: bytes) -> Any:
    if isinstance(t, Uint64):
        if len(data) != 8:
            raise ValueError("uint64 needs 8 bytes")
        return int.from_bytes(data, "little")
    if isinstance(t, Uint256):
        if len(data) != 32:
            raise ValueError("uint256 needs 32 bytes")
        return int.from_bytes(data, "little")
    if isinstance(t, Boolean):
        if data not in (b"\x00", b"\x01"):
            raise ValueError("invalid boolean byte")
        return data == b"\x01"
    if isinstance(t, ByteVector):
        if len(data) != t.length:
            raise ValueError("byte vector length mismatch")
        return bytes(data)
    if isinstance(t, ByteList):
        if len(data) > t.limit:
            raise ValueError("byte list exceeds limit")
        return bytes(data)
    if isinstance(t, Bitvector):
        if len(data) != (t.length + 7) // 8:
            raise ValueError("bitvector length mismatch")
        # canonical encoding: padding bits above `length` must be zero
        # (two distinct byte strings must not decode to the same value)
        if t.length % 8 and data[-1] >> (t.length % 8):
            raise ValueError("bitvector has nonzero padding bits")
        return tuple(
            bool(data[i // 8] >> (i % 8) & 1) for i in range(t.length)
        )
    if isinstance(t, Bitlist):
        if not data or data[-1] == 0:
            raise ValueError("bitlist missing delimiter bit")
        total = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total > t.limit:
            raise ValueError("bitlist exceeds limit")
        return tuple(
            bool(data[i // 8] >> (i % 8) & 1) for i in range(total)
        )
    if isinstance(t, Nested):
        return deserialize(t.cls, data)
    if isinstance(t, Vector):
        return tuple(_decode_sequence([t.elem] * t.length, data))
    if isinstance(t, List):
        if not data:
            return ()
        if _is_variable(t.elem):
            first = int.from_bytes(data[:_OFFSET_SIZE], "little")
            # a zero first offset on non-empty data would decode
            # arbitrary garbage as an empty list — reject it
            if (
                first == 0
                or first % _OFFSET_SIZE
                or first > len(data)
            ):
                raise ValueError("malformed list offsets")
            count = first // _OFFSET_SIZE
        else:
            size = _fixed_size(t.elem)
            if len(data) % size:
                raise ValueError("list size not a multiple of element size")
            count = len(data) // size
        if count > t.limit:
            raise ValueError("list exceeds limit")
        return tuple(_decode_sequence([t.elem] * count, data))
    raise TypeError(f"no SSZ decoding for {type(t).__name__}")


def _decode_sequence(types: Sequence[SSZType], data: bytes) -> list:
    variable = [_is_variable(t) for t in types]
    fixed_sizes = [
        _OFFSET_SIZE if var else _fixed_size(t)
        for t, var in zip(types, variable)
    ]
    fixed_total = sum(fixed_sizes)
    if len(data) < fixed_total:
        raise ValueError("truncated SSZ sequence")
    if not any(variable) and len(data) != fixed_total:
        # no offsets: nothing else may follow the fixed parts
        raise ValueError("trailing bytes after fixed-size SSZ sequence")
    # first pass: slice fixed parts, collect offsets
    offsets: list[int] = []
    pos = 0
    fixed_parts: list[bytes | None] = []
    for size, var in zip(fixed_sizes, variable):
        chunk = data[pos : pos + size]
        pos += size
        if var:
            offsets.append(int.from_bytes(chunk, "little"))
            fixed_parts.append(None)
        else:
            fixed_parts.append(chunk)
    # offsets must be monotonically non-decreasing, start at the end of
    # the fixed part, and stay in bounds
    if offsets:
        if offsets[0] != fixed_total:
            raise ValueError("first offset must equal fixed-part size")
        bounds = offsets + [len(data)]
        for a, b in zip(bounds, bounds[1:]):
            if a > b or a > len(data):
                raise ValueError("malformed SSZ offsets")
    out = []
    var_idx = 0
    for t, var, part in zip(types, variable, fixed_parts):
        if var:
            start = offsets[var_idx]
            end = (
                offsets[var_idx + 1]
                if var_idx + 1 < len(offsets)
                else len(data)
            )
            var_idx += 1
            out.append(_decode(t, data[start:end]))
        else:
            out.append(_decode(t, part))
    return out


def deserialize(cls: type, data: bytes) -> Any:
    """Parse SSZ wire bytes into container `cls` (strict offsets)."""
    types = cls.ssz_fields
    flds = fields(cls)[: len(types)]
    values = _decode_sequence(tuple(types), data)
    return cls(**{f.name: v for f, v in zip(flds, values)})


BYTES32 = ByteVector(32)
BYTES48 = ByteVector(48)
BYTES96 = ByteVector(96)
UINT64 = Uint64()
