"""Fork-versioned eth2 spec containers + beacon-API JSON codec.

The canonical consensus-spec containers the duty workflow carries:
attestations, the FULL per-fork beacon-block family (capella, deneb) with
execution payloads, and their blinded (builder) variants. Roots are
spec-exact SSZ (eth2util/ssz.py); the JSON codec emits/parses the exact
beacon-API wire shapes (quoted uint64s, 0x-hex byte strings, SSZ-encoded
hex bitlists), so a stock validator client can round-trip blocks through
the validator API.

The reference gets these types from go-eth2-client's per-fork packages
and routes on the `version` discriminator (ref:
core/validatorapi/router.go:151-175 produceBlockV3 / submitProposal,
core/unsigneddata.go VersionedProposal, core/signeddata.go
VersionedSignedProposal). Here one descriptor-driven codec serves every
container: each dataclass declares `ssz_fields` aligned with its fields,
and `to_json`/`from_json` walk the descriptors — no per-type marshalling
code to drift.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, ClassVar

from charon_tpu.eth2util import ssz

# ---------------------------------------------------------------------------
# Common containers (phase0/altair — fork-independent)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Checkpoint:
    epoch: int
    root: bytes  # 32

    ssz_fields: ClassVar = (ssz.UINT64, ssz.BYTES32)


@dataclass(frozen=True)
class AttestationData:
    slot: int
    index: int
    beacon_block_root: bytes
    source: Checkpoint
    target: Checkpoint

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.UINT64,
        ssz.BYTES32,
        ssz.Nested(Checkpoint),
        ssz.Nested(Checkpoint),
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class Attestation:
    aggregation_bits: tuple[bool, ...]
    data: AttestationData
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.Bitlist(2048),
        ssz.Nested(AttestationData),
        ssz.BYTES96,
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class BeaconBlockHeader:
    slot: int
    proposer_index: int
    parent_root: bytes
    state_root: bytes
    body_root: bytes

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.UINT64,
        ssz.BYTES32,
        ssz.BYTES32,
        ssz.BYTES32,
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class SignedBeaconBlockHeader:
    message: BeaconBlockHeader
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.Nested(BeaconBlockHeader), ssz.BYTES96)


@dataclass(frozen=True)
class ProposerSlashing:
    signed_header_1: SignedBeaconBlockHeader
    signed_header_2: SignedBeaconBlockHeader

    ssz_fields: ClassVar = (
        ssz.Nested(SignedBeaconBlockHeader),
        ssz.Nested(SignedBeaconBlockHeader),
    )


@dataclass(frozen=True)
class IndexedAttestation:
    attesting_indices: tuple[int, ...]  # List[uint64, 2048]
    data: AttestationData
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.List(ssz.UINT64, 2048),
        ssz.Nested(AttestationData),
        ssz.BYTES96,
    )


@dataclass(frozen=True)
class AttesterSlashing:
    attestation_1: IndexedAttestation
    attestation_2: IndexedAttestation

    ssz_fields: ClassVar = (
        ssz.Nested(IndexedAttestation),
        ssz.Nested(IndexedAttestation),
    )


@dataclass(frozen=True)
class Eth1Data:
    deposit_root: bytes  # 32
    deposit_count: int
    block_hash: bytes  # 32

    ssz_fields: ClassVar = (ssz.BYTES32, ssz.UINT64, ssz.BYTES32)


@dataclass(frozen=True)
class DepositData:
    pubkey: bytes  # 48
    withdrawal_credentials: bytes  # 32
    amount: int
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.BYTES48,
        ssz.BYTES32,
        ssz.UINT64,
        ssz.BYTES96,
    )


@dataclass(frozen=True)
class Deposit:
    proof: tuple[bytes, ...]  # Vector[bytes32, 33]
    data: DepositData

    ssz_fields: ClassVar = (
        ssz.Vector(ssz.BYTES32, 33),
        ssz.Nested(DepositData),
    )


@dataclass(frozen=True)
class VoluntaryExit:
    epoch: int
    validator_index: int

    ssz_fields: ClassVar = (ssz.UINT64, ssz.UINT64)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class SignedVoluntaryExit:
    message: VoluntaryExit
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.Nested(VoluntaryExit), ssz.BYTES96)


@dataclass(frozen=True)
class SyncAggregate:
    sync_committee_bits: tuple[bool, ...]  # Bitvector[512]
    sync_committee_signature: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.Bitvector(512), ssz.BYTES96)


@dataclass(frozen=True)
class BLSToExecutionChange:
    validator_index: int
    from_bls_pubkey: bytes  # 48
    to_execution_address: bytes  # 20

    ssz_fields: ClassVar = (ssz.UINT64, ssz.BYTES48, ssz.ByteVector(20))


@dataclass(frozen=True)
class SignedBLSToExecutionChange:
    message: BLSToExecutionChange
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.Nested(BLSToExecutionChange), ssz.BYTES96)


@dataclass(frozen=True)
class Withdrawal:
    index: int
    validator_index: int
    address: bytes  # 20
    amount: int

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.UINT64,
        ssz.ByteVector(20),
        ssz.UINT64,
    )


# ---------------------------------------------------------------------------
# Execution payloads (capella, deneb)
# ---------------------------------------------------------------------------

# spec constants
MAX_BYTES_PER_TRANSACTION = 2**30
MAX_TRANSACTIONS_PER_PAYLOAD = 2**20
MAX_EXTRA_DATA_BYTES = 32
MAX_WITHDRAWALS_PER_PAYLOAD = 16
MAX_BLOB_COMMITMENTS_PER_BLOCK = 4096

_PAYLOAD_HEAD_FIELDS = (
    ssz.BYTES32,  # parent_hash
    ssz.ByteVector(20),  # fee_recipient
    ssz.BYTES32,  # state_root
    ssz.BYTES32,  # receipts_root
    ssz.ByteVector(256),  # logs_bloom
    ssz.BYTES32,  # prev_randao
    ssz.UINT64,  # block_number
    ssz.UINT64,  # gas_limit
    ssz.UINT64,  # gas_used
    ssz.UINT64,  # timestamp
    ssz.ByteList(MAX_EXTRA_DATA_BYTES),  # extra_data
    ssz.Uint256(),  # base_fee_per_gas
    ssz.BYTES32,  # block_hash
)


@dataclass(frozen=True)
class ExecutionPayloadCapella:
    parent_hash: bytes
    fee_recipient: bytes
    state_root: bytes
    receipts_root: bytes
    logs_bloom: bytes
    prev_randao: bytes
    block_number: int
    gas_limit: int
    gas_used: int
    timestamp: int
    extra_data: bytes
    base_fee_per_gas: int
    block_hash: bytes
    transactions: tuple[bytes, ...] = ()
    withdrawals: tuple[Withdrawal, ...] = ()

    ssz_fields: ClassVar = (
        *_PAYLOAD_HEAD_FIELDS,
        ssz.List(
            ssz.ByteList(MAX_BYTES_PER_TRANSACTION),
            MAX_TRANSACTIONS_PER_PAYLOAD,
        ),
        ssz.List(ssz.Nested(Withdrawal), MAX_WITHDRAWALS_PER_PAYLOAD),
    )


@dataclass(frozen=True)
class ExecutionPayloadDeneb:
    parent_hash: bytes
    fee_recipient: bytes
    state_root: bytes
    receipts_root: bytes
    logs_bloom: bytes
    prev_randao: bytes
    block_number: int
    gas_limit: int
    gas_used: int
    timestamp: int
    extra_data: bytes
    base_fee_per_gas: int
    block_hash: bytes
    transactions: tuple[bytes, ...] = ()
    withdrawals: tuple[Withdrawal, ...] = ()
    blob_gas_used: int = 0
    excess_blob_gas: int = 0

    ssz_fields: ClassVar = (
        *_PAYLOAD_HEAD_FIELDS,
        ssz.List(
            ssz.ByteList(MAX_BYTES_PER_TRANSACTION),
            MAX_TRANSACTIONS_PER_PAYLOAD,
        ),
        ssz.List(ssz.Nested(Withdrawal), MAX_WITHDRAWALS_PER_PAYLOAD),
        ssz.UINT64,
        ssz.UINT64,
    )


@dataclass(frozen=True)
class ExecutionPayloadHeaderCapella:
    parent_hash: bytes
    fee_recipient: bytes
    state_root: bytes
    receipts_root: bytes
    logs_bloom: bytes
    prev_randao: bytes
    block_number: int
    gas_limit: int
    gas_used: int
    timestamp: int
    extra_data: bytes
    base_fee_per_gas: int
    block_hash: bytes
    transactions_root: bytes = bytes(32)
    withdrawals_root: bytes = bytes(32)

    ssz_fields: ClassVar = (
        *_PAYLOAD_HEAD_FIELDS,
        ssz.BYTES32,
        ssz.BYTES32,
    )


@dataclass(frozen=True)
class ExecutionPayloadHeaderDeneb:
    parent_hash: bytes
    fee_recipient: bytes
    state_root: bytes
    receipts_root: bytes
    logs_bloom: bytes
    prev_randao: bytes
    block_number: int
    gas_limit: int
    gas_used: int
    timestamp: int
    extra_data: bytes
    base_fee_per_gas: int
    block_hash: bytes
    transactions_root: bytes = bytes(32)
    withdrawals_root: bytes = bytes(32)
    blob_gas_used: int = 0
    excess_blob_gas: int = 0

    ssz_fields: ClassVar = (
        *_PAYLOAD_HEAD_FIELDS,
        ssz.BYTES32,
        ssz.BYTES32,
        ssz.UINT64,
        ssz.UINT64,
    )


# ---------------------------------------------------------------------------
# Block bodies + blocks (per fork, full + blinded)
# ---------------------------------------------------------------------------

_BODY_HEAD_FIELDS = (
    ssz.BYTES96,  # randao_reveal
    ssz.Nested(Eth1Data),
    ssz.BYTES32,  # graffiti
    ssz.List(ssz.Nested(ProposerSlashing), 16),
    ssz.List(ssz.Nested(AttesterSlashing), 2),
    ssz.List(ssz.Nested(Attestation), 128),
    ssz.List(ssz.Nested(Deposit), 16),
    ssz.List(ssz.Nested(SignedVoluntaryExit), 16),
    ssz.Nested(SyncAggregate),
)

_EMPTY_ETH1 = Eth1Data(bytes(32), 0, bytes(32))
_EMPTY_SYNC_AGG = SyncAggregate(tuple([False] * 512))


def _body_cls(name: str, payload_field: str, payload_cls, *, blobs: bool):
    """Build a per-fork body dataclass: identical head fields, then the
    fork's execution payload (or header, blinded) and — deneb on — the
    bls-to-execution-change and blob-commitment tails."""
    fields = [
        ("randao_reveal", bytes, dataclasses.field(default=bytes(96))),
        ("eth1_data", Eth1Data, dataclasses.field(default=_EMPTY_ETH1)),
        ("graffiti", bytes, dataclasses.field(default=bytes(32))),
        ("proposer_slashings", tuple, dataclasses.field(default=())),
        ("attester_slashings", tuple, dataclasses.field(default=())),
        ("attestations", tuple, dataclasses.field(default=())),
        ("deposits", tuple, dataclasses.field(default=())),
        ("voluntary_exits", tuple, dataclasses.field(default=())),
        (
            "sync_aggregate",
            SyncAggregate,
            dataclasses.field(default=_EMPTY_SYNC_AGG),
        ),
        (payload_field, payload_cls, dataclasses.field(default=payload_cls(
            parent_hash=bytes(32),
            fee_recipient=bytes(20),
            state_root=bytes(32),
            receipts_root=bytes(32),
            logs_bloom=bytes(256),
            prev_randao=bytes(32),
            block_number=0,
            gas_limit=0,
            gas_used=0,
            timestamp=0,
            extra_data=b"",
            base_fee_per_gas=0,
            block_hash=bytes(32),
        ))),
        ("bls_to_execution_changes", tuple, dataclasses.field(default=())),
    ]
    types = [
        *_BODY_HEAD_FIELDS,
        ssz.Nested(payload_cls),
        ssz.List(ssz.Nested(SignedBLSToExecutionChange), 16),
    ]
    if blobs:
        fields.append(
            ("blob_kzg_commitments", tuple, dataclasses.field(default=()))
        )
        types.append(
            ssz.List(ssz.BYTES48, MAX_BLOB_COMMITMENTS_PER_BLOCK)
        )
    cls = dataclasses.make_dataclass(
        name,
        fields,
        frozen=True,
        namespace={
            "ssz_fields": tuple(types),
            "hash_tree_root": lambda self: ssz.hash_tree_root(self),
        },
    )
    cls.__module__ = __name__
    return cls


BeaconBlockBodyCapella = _body_cls(
    "BeaconBlockBodyCapella",
    "execution_payload",
    ExecutionPayloadCapella,
    blobs=False,
)
BlindedBeaconBlockBodyCapella = _body_cls(
    "BlindedBeaconBlockBodyCapella",
    "execution_payload_header",
    ExecutionPayloadHeaderCapella,
    blobs=False,
)
BeaconBlockBodyDeneb = _body_cls(
    "BeaconBlockBodyDeneb",
    "execution_payload",
    ExecutionPayloadDeneb,
    blobs=True,
)
BlindedBeaconBlockBodyDeneb = _body_cls(
    "BlindedBeaconBlockBodyDeneb",
    "execution_payload_header",
    ExecutionPayloadHeaderDeneb,
    blobs=True,
)


def _block_cls(name: str, body_cls):
    cls = dataclasses.make_dataclass(
        name,
        [
            ("slot", int),
            ("proposer_index", int),
            ("parent_root", bytes),
            ("state_root", bytes),
            ("body", body_cls),
        ],
        frozen=True,
        namespace={
            "ssz_fields": (
                ssz.UINT64,
                ssz.UINT64,
                ssz.BYTES32,
                ssz.BYTES32,
                ssz.Nested(body_cls),
            ),
            "hash_tree_root": lambda self: ssz.hash_tree_root(self),
            "header": lambda self: BeaconBlockHeader(
                slot=self.slot,
                proposer_index=self.proposer_index,
                parent_root=self.parent_root,
                state_root=self.state_root,
                body_root=ssz.hash_tree_root(self.body),
            ),
        },
    )
    cls.__module__ = __name__
    return cls


BeaconBlockCapella = _block_cls("BeaconBlockCapella", BeaconBlockBodyCapella)
BlindedBeaconBlockCapella = _block_cls(
    "BlindedBeaconBlockCapella", BlindedBeaconBlockBodyCapella
)
BeaconBlockDeneb = _block_cls("BeaconBlockDeneb", BeaconBlockBodyDeneb)
BlindedBeaconBlockDeneb = _block_cls(
    "BlindedBeaconBlockDeneb", BlindedBeaconBlockBodyDeneb
)

# version string -> (full block class, blinded block class); ordered
# oldest-first so `latest_fork()` is the last entry
FORK_BLOCKS: dict[str, tuple[type, type]] = {
    "capella": (BeaconBlockCapella, BlindedBeaconBlockCapella),
    "deneb": (BeaconBlockDeneb, BlindedBeaconBlockDeneb),
}


def _signed_cls(name: str, block_cls):
    cls = dataclasses.make_dataclass(
        name,
        [
            ("message", block_cls),
            ("signature", bytes, dataclasses.field(default=bytes(96))),
        ],
        frozen=True,
        namespace={
            "ssz_fields": (ssz.Nested(block_cls), ssz.BYTES96),
            "hash_tree_root": lambda self: ssz.hash_tree_root(self),
        },
    )
    cls.__module__ = __name__
    return cls


SignedBeaconBlockCapella = _signed_cls(
    "SignedBeaconBlockCapella", BeaconBlockCapella
)
SignedBlindedBeaconBlockCapella = _signed_cls(
    "SignedBlindedBeaconBlockCapella", BlindedBeaconBlockCapella
)
SignedBeaconBlockDeneb = _signed_cls(
    "SignedBeaconBlockDeneb", BeaconBlockDeneb
)
SignedBlindedBeaconBlockDeneb = _signed_cls(
    "SignedBlindedBeaconBlockDeneb", BlindedBeaconBlockDeneb
)

# deneb block contents (produce) / signed block contents (publish):
# block + blob sidecar material as one SSZ container
BYTES_PER_BLOB = 131072  # 4096 field elements x 32 bytes


@dataclass(frozen=True)
class BlockContentsDeneb:
    block: Any
    kzg_proofs: tuple[bytes, ...] = ()
    blobs: tuple[bytes, ...] = ()

    ssz_fields: ClassVar = (
        ssz.Nested(BeaconBlockDeneb),
        ssz.List(ssz.BYTES48, MAX_BLOB_COMMITMENTS_PER_BLOCK),
        ssz.List(
            ssz.ByteVector(BYTES_PER_BLOB), MAX_BLOB_COMMITMENTS_PER_BLOCK
        ),
    )


@dataclass(frozen=True)
class SignedBlockContentsDeneb:
    signed_block: Any
    kzg_proofs: tuple[bytes, ...] = ()
    blobs: tuple[bytes, ...] = ()

    ssz_fields: ClassVar = (
        ssz.Nested(SignedBeaconBlockDeneb),
        ssz.List(ssz.BYTES48, MAX_BLOB_COMMITMENTS_PER_BLOCK),
        ssz.List(
            ssz.ByteVector(BYTES_PER_BLOB), MAX_BLOB_COMMITMENTS_PER_BLOCK
        ),
    )


# version -> (signed full class, signed blinded class)
FORK_SIGNED_BLOCKS: dict[str, tuple[type, type]] = {
    "capella": (SignedBeaconBlockCapella, SignedBlindedBeaconBlockCapella),
    "deneb": (SignedBeaconBlockDeneb, SignedBlindedBeaconBlockDeneb),
}


def block_class(version: str, blinded: bool) -> type:
    try:
        full, blind = FORK_BLOCKS[version]
    except KeyError:
        raise ValueError(f"unsupported block version {version!r}") from None
    return blind if blinded else full


def latest_fork() -> str:
    return next(reversed(FORK_BLOCKS))


# ---------------------------------------------------------------------------
# beacon-API JSON codec (descriptor-driven)
# ---------------------------------------------------------------------------


def bits_to_bytes(bits, sentinel: bool) -> bytes:
    n = len(bits)
    data = bytearray(n // 8 + 1 if sentinel else (n + 7) // 8)
    for i, bit in enumerate(bits):
        if bit:
            data[i // 8] |= 1 << (i % 8)
    if sentinel:
        data[n // 8] |= 1 << (n % 8)
    return bytes(data)


def bits_from_bytes(data: bytes, sentinel: bool, length: int | None = None):
    if sentinel:
        if not data or data[-1] == 0:
            raise ValueError("bitlist missing delimiter bit")
        total = (len(data) - 1) * 8 + data[-1].bit_length() - 1
    else:
        assert length is not None
        # truncated/oversized hex must be a ValueError (-> HTTP 400 in the
        # vapi handlers), not an IndexError 500; padding bits above
        # `length` must be zero (same canonicality rule as ssz._decode)
        if len(data) != (length + 7) // 8:
            raise ValueError("bitvector byte length mismatch")
        if length % 8 and data[-1] >> (length % 8):
            raise ValueError("bitvector has nonzero padding bits")
        total = length
    return tuple(
        bool(data[i // 8] >> (i % 8) & 1) for i in range(total)
    )


def _enc(t: ssz.SSZType, v: Any) -> Any:
    if isinstance(t, (ssz.Uint64, ssz.Uint256)):
        return str(int(v))
    if isinstance(t, ssz.Boolean):
        return bool(v)
    if isinstance(t, (ssz.ByteVector, ssz.ByteList)):
        return "0x" + bytes(v).hex()
    if isinstance(t, ssz.Bitlist):
        return "0x" + bits_to_bytes(v, sentinel=True).hex()
    if isinstance(t, ssz.Bitvector):
        return "0x" + bits_to_bytes(v, sentinel=False).hex()
    if isinstance(t, ssz.Nested):
        return to_json(v)
    if isinstance(t, (ssz.List, ssz.Vector)):
        return [_enc(t.elem, x) for x in v]
    raise TypeError(f"no JSON encoding for {type(t).__name__}")


def unhex0x(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def hex0x(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _dec(t: ssz.SSZType, v: Any) -> Any:
    if isinstance(t, (ssz.Uint64, ssz.Uint256)):
        return int(v)
    if isinstance(t, ssz.Boolean):
        return bool(v)
    if isinstance(t, (ssz.ByteVector, ssz.ByteList)):
        return unhex0x(v)
    if isinstance(t, ssz.Bitlist):
        bits = bits_from_bytes(unhex0x(v), sentinel=True)
        if len(bits) > t.limit:
            raise ValueError("bitlist exceeds limit")
        return bits
    if isinstance(t, ssz.Bitvector):
        return bits_from_bytes(unhex0x(v), sentinel=False, length=t.length)
    if isinstance(t, ssz.Nested):
        if t.cls is None:
            raise TypeError("Nested descriptor lacks cls; cannot decode")
        return from_json(t.cls, v)
    if isinstance(t, (ssz.List, ssz.Vector)):
        return tuple(_dec(t.elem, x) for x in v)
    raise TypeError(f"no JSON decoding for {type(t).__name__}")


def to_json(obj: Any) -> dict:
    """Beacon-API JSON object for an ssz_fields-bearing container."""
    out = {}
    for f, t in zip(dataclasses.fields(obj), obj.ssz_fields):
        out[f.name] = _enc(t, getattr(obj, f.name))
    return out


def from_json(cls: type, j: dict) -> Any:
    """Parse a beacon-API JSON object into container `cls` (strict: every
    SSZ field must be present — consensus objects have no optionals)."""
    kwargs = {}
    for f, t in zip(dataclasses.fields(cls), cls.ssz_fields):
        if f.name not in j:
            raise ValueError(f"{cls.__name__}: missing field {f.name!r}")
        kwargs[f.name] = _dec(t, j[f.name])
    return cls(**kwargs)
