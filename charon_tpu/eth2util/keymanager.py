"""Keymanager API client: push validator key shares into a VC.

Mirrors ref: eth2util/keymanager/keymanager.go — POST
/eth/v1/keystores with EIP-2335 keystores + passwords, so a DKG can
deliver each node's share keys directly to its validator client
(wired from dkg, ref: dkg/dkg.go:118-128).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import aiohttp


@dataclass
class KeymanagerClient:
    base_url: str  # e.g. http://localhost:7500
    # bearer token (keymanager API standard auth); repr=False keeps it
    # out of tracebacks/log formatting of the client object
    auth_token: str = field(default="", repr=False)
    timeout: float = 10.0

    async def import_keystores(
        self, keystores: list[dict], passwords: list[str]
    ) -> list[dict]:
        """Import EIP-2335 keystores. Returns per-key statuses
        (ref: keymanager.go ImportKeystores)."""
        if len(keystores) != len(passwords):
            raise ValueError("keystore/password count mismatch")
        body = {
            "keystores": [json.dumps(k) for k in keystores],
            "passwords": list(passwords),
        }
        headers = {"Content-Type": "application/json"}
        if self.auth_token:
            # the Authorization header IS the token's destination
            headers["Authorization"] = f"Bearer {self.auth_token}"  # lint: allow(secret-flow)
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        ) as session:
            async with session.post(
                self.base_url.rstrip("/") + "/eth/v1/keystores",
                json=body,
                headers=headers,
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"keymanager import failed: HTTP {resp.status} "
                        f"{await resp.text()}"
                    )
                data = await resp.json()
        statuses = data.get("data", [])
        for st in statuses:
            if st.get("status") not in ("imported", "duplicate"):
                raise RuntimeError(f"keystore import rejected: {st}")
        return statuses
