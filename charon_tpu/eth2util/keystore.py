"""EIP-2335 BLS keystores: encrypt/decrypt share private keys.

Mirrors ref: eth2util/keystore/keystore.go:72-148 — keystore-N.json files
with adjacent password files, pbkdf2 KDF (spec-compliant EIP-2335 crypto
modules: pbkdf2-hmac-sha256 + AES-128-CTR + sha256 checksum).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import uuid as uuidlib
from pathlib import Path

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

_PBKDF2_C = 262144
_DKLEN = 32


def _kdf(password: str, salt: bytes, c: int = _PBKDF2_C) -> bytes:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, c, dklen=_DKLEN
    )


def _aes128ctr(key16: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key16), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def encrypt(secret: bytes, password: str, pubkey_hex: str = "", path: str = "") -> dict:
    """Encrypt a 32-byte BLS secret into an EIP-2335 keystore dict."""
    if len(secret) != 32:
        raise ValueError("secret must be 32 bytes")
    salt = secrets.token_bytes(32)
    iv = secrets.token_bytes(16)
    dk = _kdf(password, salt)
    ciphertext = _aes128ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
    return {
        "crypto": {
            "kdf": {
                "function": "pbkdf2",
                "params": {
                    "dklen": _DKLEN,
                    "c": _PBKDF2_C,
                    "prf": "hmac-sha256",
                    "salt": salt.hex(),
                },
                "message": "",
            },
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": checksum.hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": ciphertext.hex(),
            },
        },
        "description": "charon-tpu distributed validator key share",
        "pubkey": pubkey_hex.removeprefix("0x"),
        "path": path,
        "uuid": str(uuidlib.uuid4()),
        "version": 4,
    }


def decrypt(keystore: dict, password: str) -> bytes:
    crypto = keystore["crypto"]
    if crypto["kdf"]["function"] != "pbkdf2":
        raise ValueError("unsupported kdf")
    params = crypto["kdf"]["params"]
    dk = _kdf(password, bytes.fromhex(params["salt"]), params["c"])
    ciphertext = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise ValueError("keystore checksum mismatch (wrong password?)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, ciphertext)


# -- directory layout (ref: keystore.go StoreKeys / LoadKeys) ----------------


def store_keys(
    secrets_list: list[bytes],
    directory: str | Path,
    pubkeys: list[str] | None = None,
    start_index: int = 0,
) -> None:
    """Write keystore-N.json + keystore-N.txt password files starting at
    N = start_index (non-zero when appending validators to an existing
    dir, ref: cmd/addvalidators.go)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    for i, secret in enumerate(secrets_list, start=start_index):
        password = secrets.token_hex(16)
        ks = encrypt(
            secret,
            password,
            pubkey_hex=(pubkeys[i - start_index] if pubkeys else ""),
            path=f"m/12381/3600/{i}/0/0",
        )
        (directory / f"keystore-{i}.json").write_text(json.dumps(ks, indent=2))
        # the EIP-2335 sidecar password file is the keystore format's
        # own contract (ref: keystore.go)  # lint: allow(secret-flow)
        (directory / f"keystore-{i}.txt").write_text(password)


def load_keys(directory: str | Path) -> list[bytes]:
    directory = Path(directory)
    out = []
    i = 0
    while (directory / f"keystore-{i}.json").exists():
        ks = json.loads((directory / f"keystore-{i}.json").read_text())
        password = (directory / f"keystore-{i}.txt").read_text().strip()
        out.append(decrypt(ks, password))
        i += 1
    if not out:
        raise FileNotFoundError(f"no keystores in {directory}")
    return out
