"""Deposit data: the signed messages that activate validators on-chain.

Mirrors ref: eth2util/deposit/deposit.go — DepositMessage/DepositData
hash-tree-roots per the eth2 spec, the DOMAIN_DEPOSIT signing root
(computed against the genesis fork with an empty validators root), and
the launchpad-compatible deposit-data.json array.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass

from charon_tpu.eth2util import ssz
from charon_tpu.eth2util.signing import DomainName, compute_domain, compute_signing_root

# 32 ETH in gwei — the standard activation amount (ref: deposit.go).
DEFAULT_AMOUNT_GWEI = 32_000_000_000


def withdrawal_credentials_bls(withdrawal_pubkey: bytes) -> bytes:
    """0x00 BLS credentials: sha256(pubkey) with the first byte zeroed."""
    if len(withdrawal_pubkey) != 48:
        raise ValueError("withdrawal pubkey must be 48 bytes")
    h = hashlib.sha256(withdrawal_pubkey).digest()
    return b"\x00" + h[1:]


def withdrawal_credentials_eth1(address: bytes | str) -> bytes:
    """0x01 execution-address credentials (ref: deposit.go
    withdrawalCredsFromAddr)."""
    if isinstance(address, str):
        address = bytes.fromhex(address.removeprefix("0x"))
    if len(address) != 20:
        raise ValueError("execution address must be 20 bytes")
    return b"\x01" + bytes(11) + address


@dataclass(frozen=True)
class DepositMessage:
    pubkey: bytes  # 48
    withdrawal_credentials: bytes  # 32
    amount: int  # gwei

    ssz_fields = (ssz.BYTES48, ssz.BYTES32, ssz.UINT64)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class DepositData:
    pubkey: bytes  # 48
    withdrawal_credentials: bytes  # 32
    amount: int
    signature: bytes  # 96

    ssz_fields = (ssz.BYTES48, ssz.BYTES32, ssz.UINT64, ssz.BYTES96)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


def signing_root(msg: DepositMessage, genesis_fork_version: bytes) -> bytes:
    """DOMAIN_DEPOSIT is fork-agnostic: genesis fork version + zero
    validators root (ref: deposit.go GetMessageSigningRoot)."""
    domain = compute_domain(
        DomainName.DEPOSIT, genesis_fork_version, bytes(32)
    )
    return compute_signing_root(msg.hash_tree_root(), domain)


def deposit_data_json(
    deposits: list[DepositData],
    fork_version: bytes,
    network_name: str = "",
) -> str:
    """Launchpad-compatible deposit-data.json (ref: deposit.go
    MarshalDepositData)."""
    out = []
    for d in deposits:
        msg = DepositMessage(d.pubkey, d.withdrawal_credentials, d.amount)
        out.append(
            {
                "pubkey": d.pubkey.hex(),
                "withdrawal_credentials": d.withdrawal_credentials.hex(),
                "amount": str(d.amount),
                "signature": d.signature.hex(),
                "deposit_message_root": msg.hash_tree_root().hex(),
                "deposit_data_root": d.hash_tree_root().hex(),
                "fork_version": fork_version.hex(),
                "network_name": network_name,
            }
        )
    return json.dumps(out, indent=2)
