"""Eth2 utilities: SSZ hashing, signing domains, networks, keystores.

Mirrors the reference's eth2util layer (ref: eth2util/ — signing domains,
EIP-2335 keystores, deposit data, ENR helpers) in Python, built on a small
spec-exact SSZ merkleization core instead of the reference's codegen.
"""
