"""BLS12-381 reference implementation (pure Python).

This package is the framework's ground-truth for threshold-BLS: a complete,
dependency-free BLS12-381 stack — field towers, curve groups, optimal-ate
pairing, RFC-9380 hash-to-curve, eth2 (ZCash) point serialization, RFC-style
key generation, and Shamir/Lagrange threshold operations.

It plays the role herumi/bls-eth-go-binary plays in the reference
(ref: tbls/herumi.go, go.mod:14) — but as the *correctness oracle*: the JAX
TPU backend (charon_tpu/ops) and the C++ host backend are validated
byte-for-byte against this module, mirroring the reference's randomized
cross-implementation test strategy (ref: tbls/tbls_test.go:209-237).

Not constant-time: secret-key operations here are for reference/testing.
"""

from charon_tpu.crypto import bls, fields, g1g2, pairing, shamir  # noqa: F401
