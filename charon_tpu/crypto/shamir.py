"""Shamir secret sharing and Lagrange threshold recombination over Fr.

The threshold-BLS core of the framework (ref: tbls/herumi.go:137-223
ThresholdSplit/RecoverSecret, herumi.go:249-286 ThresholdAggregate):

  * split: sample a degree-(t-1) polynomial f with f(0) = secret; share_i =
    f(i) for share indices i in 1..n.
  * recover: Lagrange-interpolate f(0) from any t shares.
  * threshold_aggregate: recombine partial signatures sigma_i = sk_i * H(m)
    into the group signature via the same Lagrange coefficients applied in
    the exponent: sigma = sum_i lambda_i * sigma_i over G2.

Share indices are 1-based, matching the reference convention
(ref: tbls/herumi.go:158 "share IDs are 1-indexed").
"""

from __future__ import annotations

import secrets

from charon_tpu.crypto.fields import R, fr_inv, fr_mul
from charon_tpu.crypto.g1g2 import g1_add, g1_mul, g2_add, g2_mul


def split(secret: int, n: int, t: int, rand=None):
    """Split secret into n shares with threshold t.

    Returns {share_index: share_scalar} with 1-based indices.
    """
    if not 1 < t <= n:
        raise ValueError(f"invalid threshold {t} of {n}")
    if not 0 < secret < R:
        raise ValueError("secret out of range")
    randfn = rand if rand is not None else (lambda: secrets.randbelow(R - 1) + 1)
    coeffs = [secret] + [randfn() % R for _ in range(t - 1)]
    shares = {}
    for idx in range(1, n + 1):
        acc = 0
        for c in reversed(coeffs):  # Horner
            acc = (acc * idx + c) % R
        shares[idx] = acc
    return shares


def lagrange_coeffs_at_zero(indices):
    """lambda_i = prod_{j != i} j / (j - i) mod r, for 1-based share indices."""
    out = {}
    for i in indices:
        num, den = 1, 1
        for j in indices:
            if j == i:
                continue
            num = num * j % R
            den = den * (j - i) % R
        out[i] = fr_mul(num, fr_inv(den))
    return out


def recover_secret(shares: dict) -> int:
    """Recover f(0) from a {share_index: scalar} map of >= t shares."""
    coeffs = lagrange_coeffs_at_zero(list(shares))
    out = 0
    for idx, val in shares.items():
        out = (out + coeffs[idx] * val) % R
    return out


def threshold_aggregate_g2(partials: dict):
    """Recombine {share_index: G2 point} partial signatures into the group
    signature (Lagrange in the exponent)."""
    coeffs = lagrange_coeffs_at_zero(list(partials))
    out = None
    for idx, sig in partials.items():
        out = g2_add(out, g2_mul(sig, coeffs[idx]))
    return out


def threshold_aggregate_g1(partials: dict):
    """Same recombination for G1 points (pubkey recovery from pubshares)."""
    coeffs = lagrange_coeffs_at_zero(list(partials))
    out = None
    for idx, pt in partials.items():
        out = g1_add(out, g1_mul(pt, coeffs[idx]))
    return out
