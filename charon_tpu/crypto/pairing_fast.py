"""Production optimal-ate pairing for BLS12-381 (projective, sparse lines).

This is the *algorithmic specification* for the batched JAX engine
(charon_tpu/ops/pairing.py): identical control flow and formulas, scalar
Python ints here, limb arrays there. It is validated against the slow affine
oracle in charon_tpu/crypto/pairing.py.

Differences from the oracle (all standard production techniques):

  * G2 points are homogeneous projective (X, Y, Z) over Fp2 — no inversions
    inside the Miller loop.
  * Line functions are evaluated *unnormalized*: each line may be scaled by
    an arbitrary Fp2 constant, because the final exponentiation
    (p^12-1)/r kills every element of Fp2* (Fp2* has order p^2-1 which
    divides p^6-1 which divides the exponent).
  * Lines are sparse Fp12 elements with nonzero Fp2 coefficients only at
    (w^0 v^0), (w^1 v^1), (w^1 v^2) for the BLS12-381 M-twist with untwist
    x = x' * xi^-1 v^2,  y = y' * xi^-1 v w  (see pairing.py:untwist).
    Derivation of the doubling line at affine T=(x', y') evaluated at
    P=(xP, yP), scaled by 2 y' xi:
        l = 2 y' yP xi  +  (3 x'^3 - 2 y'^2) v w  -  3 x'^2 xP v^2 w
    and the chord line through T and affine Q=(x2, y2), scaled by
    (x_T - x2) xi ... with theta = y_T - y2, lam = x_T - x2:
        l = lam yP xi  +  (theta x2 - lam y2) v w  -  theta xP v^2 w
  * Final exponentiation hard part uses the BLS12 lattice identity
        3 * (p^4 - p^2 + 1)/r = (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3
    i.e. we compute f^(3h) instead of f^h. This is sound for every product-
    of-pairings == 1 check (GT has prime order r, gcd(3, r) = 1), and is
    what the tests assert: fast == oracle^3.

Plays the role of herumi's pairing (ref: tbls/herumi.go:288 Verify).
"""

from __future__ import annotations

from charon_tpu.crypto.fields import (
    FP2_ONE,
    FP2_ZERO,
    FP12_ONE,
    P,
    R,
    X_ABS,
    X_IS_NEG,
    fp2_add,
    fp2_is_zero,
    fp2_mul,
    fp2_neg,
    fp2_scalar,
    fp2_sqr,
    fp2_sub,
    fp12_conj,
    fp12_frobenius_n,
    fp12_inv,
    fp12_mul,
    fp12_sqr,
)

# Hard-part check constant: 3*(p^4-p^2+1)/r == (x-1)^2 (x+p) (x^2+p^2-1) + 3.
_X = -X_ABS if X_IS_NEG else X_ABS
assert (
    3 * ((P**4 - P**2 + 1) // R)
    == (_X - 1) ** 2 * (_X + P) * (_X * _X + P * P - 1) + 3
), "BLS12 final-exponentiation lattice identity"

# Bits of |x| below the leading one, MSB first: the Miller-loop schedule.
X_BITS = [int(b) for b in bin(X_ABS)[3:]]


def _mul_by_xi(a):
    # xi = 1 + u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


# ---------------------------------------------------------------------------
# Sparse Fp12 multiplication by a line l0 + l1 v w + l2 v^2 w
# ---------------------------------------------------------------------------


def fp12_mul_sparse_line(f, l0, l1, l2):
    """f * (l0 + l1*v*w + l2*v^2*w), with l0, l1, l2 in Fp2.

    18 fp2 muls vs 36 for a dense fp12 mul.
    """
    (a0, a1, a2), (b0, b1, b2) = f
    # A * L0 where L0 = (l0, 0, 0): scales each coefficient.
    t0 = (fp2_mul(a0, l0), fp2_mul(a1, l0), fp2_mul(a2, l0))
    # B * L1 where L1 = (0, l1, l2):
    #   c0 = xi*(b1*l2 + b2*l1); c1 = b0*l1 + xi*(b2*l2); c2 = b0*l2 + b1*l1
    t1 = (
        _mul_by_xi(fp2_add(fp2_mul(b1, l2), fp2_mul(b2, l1))),
        fp2_add(fp2_mul(b0, l1), _mul_by_xi(fp2_mul(b2, l2))),
        fp2_add(fp2_mul(b0, l2), fp2_mul(b1, l1)),
    )
    # c0 = t0 + v*t1
    c0 = (
        fp2_add(t0[0], _mul_by_xi(t1[2])),
        fp2_add(t0[1], t1[0]),
        fp2_add(t0[2], t1[1]),
    )
    # c1 = A*L1 + B*L0
    a_l1 = (
        _mul_by_xi(fp2_add(fp2_mul(a1, l2), fp2_mul(a2, l1))),
        fp2_add(fp2_mul(a0, l1), _mul_by_xi(fp2_mul(a2, l2))),
        fp2_add(fp2_mul(a0, l2), fp2_mul(a1, l1)),
    )
    b_l0 = (fp2_mul(b0, l0), fp2_mul(b1, l0), fp2_mul(b2, l0))
    c1 = (
        fp2_add(a_l1[0], b_l0[0]),
        fp2_add(a_l1[1], b_l0[1]),
        fp2_add(a_l1[2], b_l0[2]),
    )
    return (c0, c1)


# ---------------------------------------------------------------------------
# Projective Miller-loop steps (G2 in homogeneous projective over Fp2)
# ---------------------------------------------------------------------------


def _dbl_step(t, xp, yp):
    """Double T=(X,Y,Z) and return the tangent-line coefficients at P=(xp,yp).

    Line (scaled by 2 y_T Z^3 xi for the c00 term / by Z^3 for the rest —
    all Fp2-proportional, killed by the final exponentiation):
        l0 = 2 Y Z^2 yp * xi,  l1 = 3 X^3 - 2 Y^2 Z,  l2 = -(3 X^2 Z) xp
    Point:  W=3X^2, S=YZ, B=XYS, H=W^2-8B
            X' = 2HS,  Y' = W(4B - H) - 8 Y^2 S^2,  Z' = 8 S^3
    """
    x, y, z = t
    w = fp2_scalar(fp2_sqr(x), 3)
    s = fp2_mul(y, z)
    bb = fp2_mul(fp2_mul(x, y), s)
    h = fp2_sub(fp2_sqr(w), fp2_scalar(bb, 8))
    y2 = fp2_sqr(y)

    x3 = fp2_scalar(fp2_mul(h, s), 2)
    y3 = fp2_sub(
        fp2_mul(w, fp2_sub(fp2_scalar(bb, 4), h)),
        fp2_scalar(fp2_mul(y2, fp2_sqr(s)), 8),
    )
    z3 = fp2_scalar(fp2_mul(s, fp2_sqr(s)), 8)

    l0 = _mul_by_xi(fp2_scalar(fp2_mul(s, z), 2 * yp % P))
    l1 = fp2_sub(fp2_mul(w, x), fp2_scalar(fp2_mul(y2, z), 2))
    l2 = fp2_scalar(fp2_mul(w, z), (-xp) % P)
    return (x3, y3, z3), (l0, l1, l2)


def _add_step(t, q, xp, yp):
    """Mixed add T=(X,Y,Z) + affine Q=(x2,y2); chord line at P=(xp,yp).

    theta = Y - y2 Z, lam = X - x2 Z  (so the affine chord slope is
    theta/lam = (y_T - y2)/(x_T - x2)).
        l0 = lam yp * xi,  l1 = theta x2 - lam y2,  l2 = -theta xp
    Point:  W = theta^2 Z + lam^3 - 2 lam^2 X
            X' = lam W,  Y' = theta(lam^2 X - W) - lam^3 Y,  Z' = lam^3 Z
    """
    x, y, z = t
    x2, y2 = q
    theta = fp2_sub(y, fp2_mul(y2, z))
    lam = fp2_sub(x, fp2_mul(x2, z))
    lam2 = fp2_sqr(lam)
    lam3 = fp2_mul(lam2, lam)
    ww = fp2_add(
        fp2_sub(fp2_mul(fp2_sqr(theta), z), fp2_mul(lam2, fp2_scalar(x, 2))),
        lam3,
    )
    x3 = fp2_mul(lam, ww)
    y3 = fp2_sub(
        fp2_mul(theta, fp2_sub(fp2_mul(lam2, x), ww)),
        fp2_mul(lam3, y),
    )
    z3 = fp2_mul(lam3, z)

    l0 = _mul_by_xi(fp2_scalar(lam, yp))
    l1 = fp2_sub(fp2_mul(theta, x2), fp2_mul(lam, y2))
    l2 = fp2_mul(theta, (((-xp) % P), 0))
    return (x3, y3, z3), (l0, l1, l2)


def miller_loop_projective(pairs):
    """Product of Miller loops over (q, p) pairs; q in G2 affine (Fp2),
    p in G1 affine (Fp). Skips pairs with an identity member."""
    live = [
        ((q[0], q[1], FP2_ONE), q, p)
        for q, p in pairs
        if q is not None and p is not None
    ]
    f = FP12_ONE
    ts = [t for t, _, _ in live]
    for i, bit in enumerate(X_BITS):
        if i != 0:
            f = fp12_sqr(f)
        for k, (_, q, p) in enumerate(live):
            ts[k], line = _dbl_step(ts[k], p[0], p[1])
            f = fp12_mul_sparse_line(f, *line)
        if bit:
            for k, (_, q, p) in enumerate(live):
                ts[k], line = _add_step(ts[k], q, p[0], p[1])
                f = fp12_mul_sparse_line(f, *line)
    if X_IS_NEG:
        f = fp12_conj(f)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation: easy part + x-chain hard part (computes f^(3h))
# ---------------------------------------------------------------------------


def _cyc_pow_u(f):
    """f^|x| for f in the cyclotomic subgroup (square-and-multiply, MSB)."""
    out = f
    for bit in X_BITS:
        out = fp12_sqr(out)
        if bit:
            out = fp12_mul(out, f)
    return out


def _cyc_pow_x(f):
    """f^x with x negative: conj(f^|x|) (inverse == conjugate here)."""
    out = _cyc_pow_u(f)
    return fp12_conj(out) if X_IS_NEG else out


def final_exp_fast(f):
    """f^(3 * (p^12-1)/r): easy part then the lattice-identity hard part."""
    # Easy: f <- f^((p^6-1)(p^2+1)). Lands in the cyclotomic subgroup.
    f = fp12_mul(fp12_conj(f), fp12_inv(f))
    m = fp12_mul(fp12_frobenius_n(f, 2), f)
    # Hard: m^(3h) = m^((x-1)^2 (x+p) (x^2+p^2-1)) * m^3.
    # a = m^((x-1)^2) = (m^(u+1))^(u+1)  since x-1 = -(u+1).
    a = fp12_mul(_cyc_pow_u(m), m)
    a = fp12_mul(_cyc_pow_u(a), a)
    # b = a^(x+p) = a^x * frob(a)
    b = fp12_mul(_cyc_pow_x(a), fp12_frobenius_n(a, 1))
    # c = b^(x^2+p^2-1) = (b^x)^x * frob2(b) * b^-1
    c = fp12_mul(
        fp12_mul(_cyc_pow_x(_cyc_pow_x(b)), fp12_frobenius_n(b, 2)),
        fp12_conj(b),
    )
    # result = c * m^3
    return fp12_mul(c, fp12_mul(fp12_sqr(m), m))


def multi_pairing_fast(pairs):
    """Product of pairings raised to the 3rd power: prod e(p_i, q_i)^3.

    Equality/identity checks are unaffected by the cube (GT is prime order
    r, 3 invertible mod r)."""
    return final_exp_fast(miller_loop_projective(pairs))


def is_gt_one(f) -> bool:
    from charon_tpu.crypto.fields import fp12_is_one

    return fp12_is_one(f)
