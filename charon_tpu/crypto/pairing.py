"""Optimal ate pairing for BLS12-381.

Strategy (reference implementation — clarity over speed):
  * Untwist G2 points from E'(Fp2) into E(Fp12) using the sextic twist
    isomorphism, then run the Miller loop entirely in affine Fp12
    coordinates with slope-based line functions.
  * Final exponentiation: easy part via conjugation + Frobenius, hard part
    as a plain square-and-multiply by the integer (p^4 - p^2 + 1) / r.

With w^6 = xi the untwist map is (x, y) -> (x / w^2, y / w^3), i.e.
  x12 = x * xi^-1 * v^2          (an Fp6 coefficient at w^0)
  y12 = y * xi^-1 * v  * w       (an Fp6 coefficient at w^1)

The JAX engine implements the production pairing (projective, x-chain final
exp); this module is its correctness oracle.
"""

from __future__ import annotations

from charon_tpu.crypto.fields import (
    FP2_ZERO,
    FP6_ZERO,
    FP12_ONE,
    P,
    R,
    X_ABS,
    X_IS_NEG,
    XI,
    fp2_inv,
    fp2_mul,
    fp12_conj,
    fp12_frobenius_n,
    fp12_inv,
    fp12_mul,
    fp12_pow,
    fp12_sqr,
    fp12_sub,
    fp6_is_zero,
)

_XI_INV = fp2_inv(XI)

# Hard-part exponent of the final exponentiation.
_HARD_EXP = (P**4 - P**2 + 1) // R


def _fp12_from_fp(a: int):
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


_FP12_TWO = _fp12_from_fp(2)
_FP12_THREE = _fp12_from_fp(3)


def untwist(pt):
    """Map an affine E'(Fp2) point to affine E(Fp12)."""
    if pt is None:
        return None
    x, y = pt
    x12 = ((FP2_ZERO, FP2_ZERO, fp2_mul(x, _XI_INV)), FP6_ZERO)
    y12 = (FP6_ZERO, (FP2_ZERO, fp2_mul(y, _XI_INV), FP2_ZERO))
    return (x12, y12)


def _embed_g1(pt):
    """Embed an affine E(Fp) point into E(Fp12)."""
    return (_fp12_from_fp(pt[0]), _fp12_from_fp(pt[1]))


def _fp12_is_zero(a) -> bool:
    return fp6_is_zero(a[0]) and fp6_is_zero(a[1])


def _step(p1, p2, t):
    """One Miller-loop step on E(Fp12): add p1 + p2, evaluating the line
    through them at t. Returns (line_value, p1 + p2).

    Computes the slope once for both the line evaluation and the point
    arithmetic (affine chord-and-tangent).
    """
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    dx = fp12_sub(x2, x1)
    if not _fp12_is_zero(dx):
        m = fp12_mul(fp12_sub(y2, y1), fp12_inv(dx))
    elif y1 == y2:
        x1sq = fp12_mul(x1, x1)
        m = fp12_mul(
            fp12_mul(x1sq, _FP12_THREE),
            fp12_inv(fp12_mul(y1, _FP12_TWO)),
        )
    else:
        # Vertical line: p1 + p2 = infinity; line value is xt - x1.
        return fp12_sub(xt, x1), None
    line = fp12_sub(fp12_mul(m, fp12_sub(xt, x1)), fp12_sub(yt, y1))
    x3 = fp12_sub(fp12_sub(fp12_mul(m, m), x1), x2)
    y3 = fp12_sub(fp12_mul(m, fp12_sub(x1, x3)), y1)
    return line, (x3, y3)


def miller_loop(q, p):
    """Miller loop over |x| for untwisted q and embedded p (both E(Fp12))."""
    if q is None or p is None:
        return FP12_ONE
    f = FP12_ONE
    t = q
    for bit in bin(X_ABS)[3:]:  # skip the leading 1
        line, t = _step(t, t, p)
        f = fp12_mul(fp12_sqr(f), line)
        if bit == "1":
            line, t = _step(t, q, p)
            f = fp12_mul(f, line)
    if X_IS_NEG:
        # Conjugation inverts f in the cyclotomic subgroup.
        f = fp12_conj(f)
    return f


def final_exponentiation(f):
    # Easy part: f^((p^6 - 1)(p^2 + 1)).
    f = fp12_mul(fp12_conj(f), fp12_inv(f))
    f = fp12_mul(fp12_frobenius_n(f, 2), f)
    # Hard part: f^((p^4 - p^2 + 1)/r), plain square-and-multiply.
    return fp12_pow(f, _HARD_EXP)


def pairing(q, p):
    """e(P, Q) with P in G1(E/Fp), Q in G2(E'/Fp2). Returns an Fp12 element.

    Argument order note: callers pass (Q, P) — G2 first — matching the
    Miller-loop structure; the bilinear map computed is e: G1 x G2 -> GT.
    """
    if q is None or p is None:
        return FP12_ONE
    return final_exponentiation(miller_loop(untwist(q), _embed_g1(p)))


def multi_miller(pairs):
    """Product of Miller loops for (q, p) pairs, single final exponentiation.

    This is the production verification shape: verify checks
    e(-G1, sig) * e(pk, H(m)) == 1 with one final exponentiation.
    """
    f = FP12_ONE
    for q, p in pairs:
        if q is None or p is None:
            continue
        f = fp12_mul(f, miller_loop(untwist(q), _embed_g1(p)))
    return final_exponentiation(f)
