"""BLS12-381 field towers: Fp, Fp2, Fp6, Fp12, and the scalar field Fr.

Representation is deliberately primitive — Python ints and tuples, module-level
functions — so this file doubles as the executable specification for the
limb-based JAX engine (charon_tpu/ops/limb.py), which must agree with it
bit-for-bit.

Tower construction (standard 2-3-2 for BLS12-381):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = 1 + u
    Fp12 = Fp6[w] / (w^2 - v)

An Fp2 element is a tuple (c0, c1) of ints meaning c0 + c1*u.
An Fp6 element is a tuple of three Fp2 elements (coefficients of 1, v, v^2).
An Fp12 element is a tuple of two Fp6 elements (coefficients of 1, w).

Plays the role of herumi's field arithmetic in the reference
(ref: tbls/herumi.go:25-36 links the C++/asm backend).
"""

from __future__ import annotations

# Base field modulus p (381 bits).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Scalar field modulus r (255 bits) — the group order of G1/G2/GT.
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x; the curve is parameterised by x = -0xD201000000010000.
X_ABS = 0xD201000000010000
X_IS_NEG = True

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------


def fp_add(a: int, b: int) -> int:
    return (a + b) % P


def fp_sub(a: int, b: int) -> int:
    return (a - b) % P


def fp_mul(a: int, b: int) -> int:
    return (a * b) % P


def fp_neg(a: int) -> int:
    return (-a) % P


def fp_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("fp_inv(0)")
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> int | None:
    """Square root in Fp (p ≡ 3 mod 4), or None if a is not a square."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a % P else None


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1)
# ---------------------------------------------------------------------------

Fp2 = tuple  # (c0, c1)

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
# Non-residue xi = 1 + u used to build Fp6.
XI = (1, 1)


def fp2_add(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a: Fp2) -> Fp2:
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a: Fp2, b: Fp2) -> Fp2:
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def fp2_sqr(a: Fp2) -> Fp2:
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_scalar(a: Fp2, k: int) -> Fp2:
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a: Fp2) -> Fp2:
    """Frobenius on Fp2: (a0 + a1 u)^p = a0 - a1 u."""
    return (a[0], (-a[1]) % P)


def fp2_inv(a: Fp2) -> Fp2:
    a0, a1 = a
    norm = (a0 * a0 + a1 * a1) % P
    ninv = fp_inv(norm)
    return (a0 * ninv % P, -a1 * ninv % P)


def fp2_is_zero(a: Fp2) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fp2_pow(a: Fp2, e: int) -> Fp2:
    out = FP2_ONE
    base = a
    while e:
        if e & 1:
            out = fp2_mul(out, base)
        base = fp2_sqr(base)
        e >>= 1
    return out


def fp2_is_square(a: Fp2) -> bool:
    """a is a square in Fp2 iff norm(a)^((p-1)/2) == 1 (or a == 0)."""
    if fp2_is_zero(a):
        return True
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    return pow(norm, (P - 1) // 2, P) == 1


_SQRT_EXP = (P - 3) // 4


def fp2_sqrt(a: Fp2) -> Fp2 | None:
    """Square root in Fp2 for p ≡ 3 mod 4 (Adj–Rodríguez), or None.

    a1 = a^((p-3)/4); x0 = a1*a; alpha = a1*x0.
    If alpha == -1: sqrt = u * x0. Else sqrt = (1+alpha)^((p-1)/2) * x0.
    The candidate is verified by squaring, so wrong-path results return None.
    """
    if fp2_is_zero(a):
        return FP2_ZERO
    a1 = fp2_pow(a, _SQRT_EXP)
    x0 = fp2_mul(a1, a)
    alpha = fp2_mul(a1, x0)
    if alpha == (P - 1, 0):
        cand = ((-x0[1]) % P, x0[0])  # u * x0
    else:
        b = fp2_pow(fp2_add(FP2_ONE, alpha), (P - 1) // 2)
        cand = fp2_mul(b, x0)
    return cand if fp2_sqr(cand) == (a[0] % P, a[1] % P) else None


def fp2_sgn0(a: Fp2) -> int:
    """RFC 9380 sgn0 for Fp2 (m=2)."""
    sign_0 = a[0] % 2
    zero_0 = 1 if a[0] % P == 0 else 0
    sign_1 = a[1] % 2
    return sign_0 | (zero_0 & sign_1)


def fp2_is_lex_largest(a: Fp2) -> bool:
    """ZCash serialization sign: compare (c1, c0) lexicographically vs -a."""
    if a[1] % P != 0:
        return a[1] % P > (P - 1) // 2
    return a[0] % P > (P - 1) // 2


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi)
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def _mul_by_xi(a: Fp2) -> Fp2:
    """Multiply by xi = 1 + u: (a0 - a1) + (a0 + a1) u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t00 = fp2_mul(a0, b0)
    t11 = fp2_mul(a1, b1)
    t22 = fp2_mul(a2, b2)
    c0 = fp2_add(t00, _mul_by_xi(fp2_add(fp2_mul(a1, b2), fp2_mul(a2, b1))))
    c1 = fp2_add(fp2_add(fp2_mul(a0, b1), fp2_mul(a1, b0)), _mul_by_xi(t22))
    c2 = fp2_add(fp2_add(fp2_mul(a0, b2), fp2_mul(a2, b0)), t11)
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    """v * (a0 + a1 v + a2 v^2) = xi*a2 + a0 v + a1 v^2."""
    return (_mul_by_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    t0 = fp2_sub(fp2_sqr(a0), _mul_by_xi(fp2_mul(a1, a2)))
    t1 = fp2_sub(_mul_by_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    d = fp2_add(
        fp2_mul(a0, t0),
        _mul_by_xi(fp2_add(fp2_mul(a2, t1), fp2_mul(a1, t2))),
    )
    dinv = fp2_inv(d)
    return (fp2_mul(t0, dinv), fp2_mul(t1, dinv), fp2_mul(t2, dinv))


def fp6_is_zero(a) -> bool:
    return all(fp2_is_zero(c) for c in a)


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_neg(a):
    return (fp6_neg(a[0]), fp6_neg(a[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_add(fp6_mul(a0, b1), fp6_mul(a1, b0))
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """f^(p^6): conjugation, negates the w coefficient."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    d = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    dinv = fp6_inv(d)
    return (fp6_mul(a0, dinv), fp6_neg(fp6_mul(a1, dinv)))


def fp12_pow(a, e: int):
    out = FP12_ONE
    base = a
    while e:
        if e & 1:
            out = fp12_mul(out, base)
        base = fp12_sqr(base)
        e >>= 1
    return out


def fp12_is_one(a) -> bool:
    return a[0] == FP6_ONE and fp6_is_zero(a[1])


# Frobenius: gamma6 = xi^((p-1)/6); (w^k)^p = gamma6^k * w^k, and an Fp12
# element's (i, j) coefficient (of v^j w^i) sits at degree k = 2j + i of w.
_GAMMA6 = fp2_pow(XI, (P - 1) // 6)
_GAMMA_POWS = [FP2_ONE]
for _ in range(5):
    _GAMMA_POWS.append(fp2_mul(_GAMMA_POWS[-1], _GAMMA6))


def fp12_frobenius(a):
    """f^p on the tower representation."""
    out6 = []
    for i in range(2):  # w^i
        coeffs = []
        for j in range(3):  # v^j
            c = fp2_conj(a[i][j])
            coeffs.append(fp2_mul(c, _GAMMA_POWS[2 * j + i]))
        out6.append(tuple(coeffs))
    return tuple(out6)


def fp12_frobenius_n(a, n: int):
    for _ in range(n):
        a = fp12_frobenius(a)
    return a


# ---------------------------------------------------------------------------
# Fr (scalar field)
# ---------------------------------------------------------------------------


def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return (a * b) % R


def fr_neg(a: int) -> int:
    return (-a) % R


def fr_inv(a: int) -> int:
    if a % R == 0:
        raise ZeroDivisionError("fr_inv(0)")
    return pow(a, R - 2, R)
