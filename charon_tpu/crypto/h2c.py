"""RFC 9380 hash-to-curve for BLS12-381 G2 (BLS12381G2_XMD:SHA-256_SSWU_RO_).

Pipeline: expand_message_xmd(SHA-256) -> 2x hash_to_field(Fp2) ->
simplified SWU onto the 3-isogenous curve E'' -> 3-isogeny map onto E' ->
cofactor clearing by h_eff.

The eth2 ciphersuite DST (proof-of-possession scheme) is
BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_, the same one herumi is
configured with in the reference (ref: tbls/herumi.go:25-36 eth mode init).

Internal self-checks: every mapped point is verified on-curve and
in-subgroup by tests; the isogeny constants below are additionally
sanity-checked at import by mapping a fixed point and asserting the image
lands on E'.
"""

from __future__ import annotations

import hashlib

from charon_tpu.crypto.fields import (
    FP2_ONE,
    FP2_ZERO,
    P,
    fp2_add,
    fp2_inv,
    fp2_is_square,
    fp2_is_zero,
    fp2_mul,
    fp2_neg,
    fp2_sgn0,
    fp2_sqr,
    fp2_sqrt,
    fp2_sub,
)
from charon_tpu.crypto.g1g2 import (
    g2_add,
    g2_clear_cofactor_psi,
    g2_is_on_curve,
    g2_mul_raw,
)

DST_POP = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- SSWU curve E'': y^2 = x^3 + A'x + B' over Fp2 (3-isogenous to E') ---
A_PRIME = (0, 240)
B_PRIME = (1012, 1012)
Z_SSWU = ((-2) % P, (-1) % P)  # Z = -(2 + u)

# --- 3-isogeny map E'' -> E' coefficients (RFC 9380 appendix E.3) ---
_K = {
    "x_num": [
        (
            0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
            0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        ),
        (
            0,
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
        ),
        (
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
            0x08AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
        ),
        (
            0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
            0,
        ),
    ],
    "x_den": [
        (
            0,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
        ),
        (
            0xC,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
        ),
        (1, 0),
    ],
    "y_num": [
        (
            0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
            0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        ),
        (
            0,
            0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
        ),
        (
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
            0x08AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
        ),
        (
            0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
            0,
        ),
    ],
    "y_den": [
        (
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        ),
        (
            0,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
        ),
        (
            0x12,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
        ),
        (1, 0),
    ],
}

# Effective G2 cofactor h_eff (RFC 9380 §8.8.2): clear_cofactor(P) = h_eff * P.
# The live path clears by the psi-endomorphism split (g1g2.
# g2_clear_cofactor_psi — two 64-bit ladders instead of this 1253-bit
# one); H_EFF stays THE spec value, cross-checked at import below.
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    b_in_bytes = 32
    s_in_bytes = 64
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds exceeded")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = bytes(s_in_bytes)
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    msg_prime = z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    b0 = hashlib.sha256(msg_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        blocks.append(hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = DST_POP):
    """RFC 9380 §5.2 hash_to_field for Fp2 (m=2, L=64)."""
    L = 64
    pseudo = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            offset = L * (j + i * 2)
            coeffs.append(int.from_bytes(pseudo[offset : offset + L], "big") % P)
        out.append(tuple(coeffs))
    return out


def sswu_fp2(u):
    """Simplified SWU map (RFC 9380 §6.6.2) onto E'': returns affine (x, y)."""
    A, B, Z = A_PRIME, B_PRIME, Z_SSWU
    tv1 = fp2_mul(Z, fp2_sqr(u))  # Z u^2
    tv2 = fp2_sqr(tv1)
    x1_den = fp2_add(tv1, tv2)
    if fp2_is_zero(x1_den):
        # Exceptional case: x1 = B / (Z*A)
        x1 = fp2_mul(B, fp2_inv(fp2_mul(Z, A)))
    else:
        x1 = fp2_mul(
            fp2_mul(fp2_neg(B), fp2_inv(A)),
            fp2_add(FP2_ONE, fp2_inv(x1_den)),
        )
    gx1 = fp2_add(fp2_mul(fp2_add(fp2_sqr(x1), A), x1), B)
    if fp2_is_square(gx1):
        x, y = x1, fp2_sqrt(gx1)
    else:
        x2 = fp2_mul(tv1, x1)
        gx2 = fp2_mul(gx1, fp2_mul(tv1, tv2))  # gx2 = Z^3 u^6 gx1
        x, y = x2, fp2_sqrt(gx2)
    if y is None:  # pragma: no cover - mathematically impossible
        raise AssertionError("SSWU: no square root found")
    if fp2_sgn0(u) != fp2_sgn0(y):
        y = fp2_neg(y)
    return (x, y)


def iso_map_g2(pt):
    """3-isogeny from E'' to E' (RFC 9380 appendix E.3)."""
    x, y = pt

    def horner(coeffs):
        acc = coeffs[-1]
        for c in reversed(coeffs[:-1]):
            acc = fp2_add(fp2_mul(acc, x), c)
        return acc

    x_num = horner(_K["x_num"])
    x_den = horner(_K["x_den"])
    y_num = horner(_K["y_num"])
    y_den = horner(_K["y_den"])
    xo = fp2_mul(x_num, fp2_inv(x_den))
    yo = fp2_mul(y, fp2_mul(y_num, fp2_inv(y_den)))
    return (xo, yo)


def clear_cofactor_g2(pt):
    """[h_eff]P by the psi-endomorphism split — bit-identical to the
    g2_mul_raw(pt, H_EFF) ladder (asserted at import on a mapped point)
    at ~1/9 the point-op cost; this is what makes the PYTHON rung of a
    cold-cache hash-to-curve burst survivable."""
    return g2_clear_cofactor_psi(pt)


def map_to_curve_g2(u):
    return iso_map_g2(sswu_fp2(u))


def hash_to_g2(msg: bytes, dst: bytes = DST_POP):
    """Full hash_to_curve for G2: returns an affine E'(Fp2) point in the
    r-subgroup."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = map_to_curve_g2(u0)
    q1 = map_to_curve_g2(u1)
    return clear_cofactor_g2(g2_add(q0, q1))


def _selfcheck() -> None:
    """Verify the isogeny constants map E'' points onto E', and that the
    psi cofactor-clearing split equals the spec [H_EFF]P ladder on a
    mapped (pre-clearing, non-subgroup) point."""
    u = (5, 7)
    q = sswu_fp2(u)
    # On E''?
    lhs = fp2_sqr(q[1])
    rhs = fp2_add(fp2_add(fp2_mul(fp2_sqr(q[0]), q[0]), fp2_mul(A_PRIME, q[0])), B_PRIME)
    if lhs != rhs:
        raise AssertionError("SSWU output not on E''")
    mapped = iso_map_g2(q)
    if not g2_is_on_curve(mapped):
        raise AssertionError("isogeny image not on E' — bad constants")
    if g2_clear_cofactor_psi(mapped) != g2_mul_raw(mapped, H_EFF):
        raise AssertionError("psi cofactor clearing != [h_eff]P ladder")


_selfcheck()
