"""BLS signatures over BLS12-381 (eth2 flavour: pubkeys G1, signatures G2).

Implements the draft-irtf-cfrg-bls-signature operations the reference's tbls
facade exposes (ref: tbls/tbls.go:28-69): KeyGen, SkToPk, Sign, Verify,
Aggregate, FastAggregateVerify — in the proof-of-possession ciphersuite used
by eth2.
"""

from __future__ import annotations

import hashlib
import hmac
import os

from charon_tpu.crypto.fields import R
from charon_tpu.crypto.g1g2 import (
    G1_GEN,
    g1_add,
    g1_from_bytes,
    g1_mul,
    g1_neg,
    g1_to_bytes,
    g2_add,
    g2_from_bytes,
    g2_mul,
    g2_to_bytes,
)
from charon_tpu.crypto.h2c import DST_POP, hash_to_g2
from charon_tpu.crypto.pairing import multi_miller
from charon_tpu.crypto.fields import fp12_is_one

KEYGEN_SALT = b"BLS-SIG-KEYGEN-SALT-"


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    block = b""
    i = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + i.to_bytes(1, "big"), hashlib.sha256).digest()
        out += block
        i += 1
    return out[:length]


def keygen(ikm: bytes | None = None, key_info: bytes = b"") -> int:
    """RFC KeyGen: HKDF loop until a nonzero scalar mod r is derived."""
    if ikm is None:
        ikm = os.urandom(32)
    if len(ikm) < 32:
        raise ValueError("IKM must be >= 32 bytes")
    salt = KEYGEN_SALT
    sk = 0
    while sk == 0:
        prk = _hkdf_extract(hashlib.sha256(salt).digest(), ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % R
        salt = hashlib.sha256(salt).digest()
    return sk


def sk_to_pk(sk: int):
    return g1_mul(G1_GEN, sk)


def sign(sk: int, msg: bytes, dst: bytes = DST_POP):
    return g2_mul(hash_to_g2(msg, dst), sk)


def verify(pk, msg: bytes, sig, dst: bytes = DST_POP) -> bool:
    """e(-G1, sig) * e(pk, H(m)) == 1.

    Uses the production projective pairing with the x-chain final
    exponentiation (pairing_fast) — ~20x faster than the affine oracle and
    validated against it (tests/test_pairing_fast.py)."""
    if pk is None or sig is None:
        return False
    from charon_tpu.crypto.pairing_fast import is_gt_one, multi_pairing_fast

    h = hash_to_g2(msg, dst)
    return is_gt_one(multi_pairing_fast([(sig, g1_neg(G1_GEN)), (h, pk)]))


def aggregate_sigs(sigs):
    out = None
    for s in sigs:
        out = g2_add(out, s)
    return out


def aggregate_pks(pks):
    out = None
    for pk in pks:
        out = g1_add(out, pk)
    return out


def fast_aggregate_verify(pks, msg: bytes, sig, dst: bytes = DST_POP) -> bool:
    """All signers signed the same message (eth2 aggregate attestations)."""
    if not pks:
        return False
    return verify(aggregate_pks(pks), msg, sig, dst)


def aggregate_verify(pks, msgs, sig, dst: bytes = DST_POP) -> bool:
    """Distinct messages: e(-G1, sig) * prod e(pk_i, H(m_i)) == 1."""
    if not pks or len(pks) != len(msgs) or sig is None:
        return False
    from charon_tpu.crypto.pairing_fast import is_gt_one, multi_pairing_fast

    pairs = [(sig, g1_neg(G1_GEN))]
    for pk, msg in zip(pks, msgs):
        if pk is None:
            return False
        pairs.append((hash_to_g2(msg, dst), pk))
    return is_gt_one(multi_pairing_fast(pairs))


# --- byte-level convenience (the tbls wire types) ---


def sk_to_bytes(sk: int) -> bytes:
    return (sk % R).to_bytes(32, "big")


def sk_from_bytes(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("secret key must be 32 bytes")
    sk = int.from_bytes(data, "big")
    if not 0 < sk < R:
        raise ValueError("secret key out of range")
    return sk


pk_to_bytes = g1_to_bytes
pk_from_bytes = g1_from_bytes
sig_to_bytes = g2_to_bytes
sig_from_bytes = g2_from_bytes
